"""Farm split-frame encoding (ISSUE 14): band-shaped shards, the halo
relay, and cross-host bit-identity.

- wire/unit tiers: halo blob framing + digest rejection, relay
  generation fencing + ring eviction, band descriptor wire form (and
  the unchanged GOP form), unsupported-shape requeue with no attempt
  burn, the band-count clamp against the slowest worker's devices,
  claim affinity scoring, band-group lockstep restart, and the
  band-slice stitcher;
- `test_two_group_farm_bit_identical_to_local_mesh`: two in-process
  band slices (one device each) exchanging halo/probe/histogram
  through a real HaloRelay reproduce the local-mesh 2-band SFE stream
  byte for byte;
- `test_farm_sfe_end_to_end_two_workers`: the hermetic acceptance test
  — subprocess coordinator + 2 single-device worker daemons encode ONE
  stream as band shards over HTTP (halo via /work/halo), and the
  stitched MP4 is BYTE-identical to a local-mesh SFE encode; the job's
  trace carries both workers' band spans under one trace id.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from thinvids_tpu.cluster import Coordinator, WorkerRegistry
from thinvids_tpu.cluster.executor import LocalExecutor
from thinvids_tpu.cluster.halo import (HaloRelay, HaloSession,
                                       HaloStaleError, LocalHaloHub,
                                       pack_arrays, unpack_arrays)
from thinvids_tpu.cluster.remote import (RemoteExecutor, Shard,
                                         ShardBoard, stitch_band_shards)
from thinvids_tpu.core.config import DEFAULT_SETTINGS, Settings
from thinvids_tpu.core.status import ShardState, Status
from thinvids_tpu.core.types import (EncodedSegment, Frame, GopSpec,
                                     VideoMeta)
from thinvids_tpu.io.y4m import write_y4m

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_settings(**over):
    values = dict(DEFAULT_SETTINGS)
    values.update(over)
    return Settings(values=values)


def clip_frames(w=64, h=48, n=16):
    yy, xx = np.mgrid[0:h, 0:w]
    return [Frame(
        y=((xx * 2 + yy + 7 * i) % 256).astype(np.uint8),
        u=np.full((h // 2, w // 2), 108, np.uint8),
        v=np.full((h // 2, w // 2), 148, np.uint8),
    ) for i in range(n)]


def write_clip(path, w=64, h=48, n=16):
    meta = VideoMeta(width=w, height=h, fps_num=30, fps_den=1,
                     num_frames=n)
    write_y4m(str(path), meta, clip_frames(w, h, n))
    return meta


def make_board(workers=("w1", "w2"), devices=2, **over):
    """Board + coordinator with claim-capable workers. `devices` may
    be an int (every worker) or a {host: count} map — the claim's
    band-width gate reads the advertised worker_devices."""
    snap = make_settings(pipeline_worker_count=len(workers) + 1, **over)
    reg = WorkerRegistry()
    for hostname in workers:
        n = devices.get(hostname, 1) if isinstance(devices, dict)             else devices
        reg.heartbeat(hostname, metrics={"worker": True,
                                         "worker_devices": n})
    coord = Coordinator(registry=reg, settings_fn=lambda: snap)
    return ShardBoard(coord), coord


def band_shard(sid, lo, hi, total=2, job_id="j0", ngops=2,
               input_path="/in/a.y4m", gop0=0):
    gops = tuple(GopSpec(index=gop0 + i, start_frame=2 * (gop0 + i),
                         num_frames=2) for i in range(ngops))
    return Shard(id=sid, job_id=job_id, input_path=input_path,
                 meta=VideoMeta(width=64, height=48), gops=gops, qp=30,
                 gop_frames=2, timeout_s=60.0, shape="band",
                 band_start=lo, band_count=hi - lo, total_bands=total,
                 halo_rows=32, key=f"band{lo:03d}")


def gop_shard(sid="j0-0000", job_id="j0", gop0=0, ngops=2,
              input_path="/in/a.y4m"):
    gops = tuple(GopSpec(index=gop0 + i, start_frame=2 * (gop0 + i),
                         num_frames=2) for i in range(ngops))
    return Shard(id=sid, job_id=job_id, input_path=input_path,
                 meta=VideoMeta(width=64, height=48), gops=gops, qp=30,
                 gop_frames=2, timeout_s=60.0)


# ---------------------------------------------------------------------------
# halo framing + relay
# ---------------------------------------------------------------------------


class TestHaloFraming:
    def test_roundtrip(self):
        arrays = {"y": np.arange(64, dtype=np.int16).reshape(8, 8),
                  "n": np.asarray([7], np.int64)}
        out = unpack_arrays(pack_arrays(arrays))
        assert set(out) == {"y", "n"}
        np.testing.assert_array_equal(out["y"], arrays["y"])
        assert out["y"].dtype == np.int16
        assert int(out["n"][0]) == 7

    def test_flipped_bit_rejected(self):
        blob = bytearray(pack_arrays(
            {"y": np.arange(64, dtype=np.int16)}))
        blob[-3] ^= 0x10                # payload byte, not the header
        with pytest.raises(ValueError, match="sha256"):
            unpack_arrays(bytes(blob))

    def test_truncated_rejected(self):
        blob = pack_arrays({"y": np.arange(64, dtype=np.int16)})
        with pytest.raises(ValueError):
            unpack_arrays(blob[:-1])


class TestHaloRelay:
    def test_post_wait_roundtrip(self):
        relay = HaloRelay()
        relay.set_gen("j", 1)
        assert relay.post("j", 1, 0, 0, "top", b"abc")
        assert relay.wait("j", 1, 0, 0, "top", 0.1) == b"abc"

    def test_unknown_job_is_stale_not_resurrected(self):
        """Straggler traffic after clear_job (or against a bogus job
        id) must answer `stale`, never recreate an entry — a cleared
        job's blobs would otherwise leak on the coordinator forever."""
        relay = HaloRelay()
        assert not relay.post("ghost", 1, 0, 0, "top", b"x")
        with pytest.raises(HaloStaleError):
            relay.wait("ghost", 1, 0, 0, "top", 0.0)
        relay.set_gen("j", 1)
        relay.post("j", 1, 0, 0, "top", b"x")
        relay.clear_job("j")
        assert not relay.post("j", 1, 0, 1, "top", b"y")
        with pytest.raises(HaloStaleError):
            relay.wait("j", 1, 0, 0, "top", 0.0)
        assert relay.snapshot()["jobs"] == 0

    def test_wait_blocks_until_post(self):
        relay = HaloRelay()
        relay.set_gen("j", 1)

        def later():
            time.sleep(0.1)
            relay.post("j", 1, 5, 1, "bot", b"xyz")

        threading.Thread(target=later, daemon=True).start()
        assert relay.wait("j", 1, 5, 1, "bot", 5.0) == b"xyz"

    def test_stale_generation_fenced(self):
        relay = HaloRelay()
        relay.set_gen("j", 1)
        relay.post("j", 1, 0, 0, "top", b"old")
        relay.set_gen("j", 2)
        # stale post refused; stale wait raises; the old blob is gone
        assert not relay.post("j", 1, 0, 0, "top", b"old")
        with pytest.raises(HaloStaleError):
            relay.wait("j", 1, 0, 0, "top", 0.1)
        assert relay.wait("j", 2, 0, 0, "top", 0.05) is None

    def test_ring_evicts_old_frames_per_stream(self):
        relay = HaloRelay()
        relay.set_gen("j", 1)
        for seq in range(HaloRelay.RING + 4):
            relay.post("j", 1, seq, 0, "top", bytes([seq]))
        # the oldest frames fell off the ring; the newest survive
        assert relay.wait("j", 1, 0, 0, "top", 0.0) is None
        last = HaloRelay.RING + 3
        assert relay.wait("j", 1, last, 0, "top", 0.0) == bytes([last])
        # an unrelated stream is untouched
        relay.post("j", 1, 0, 1, "top", b"z")
        assert relay.wait("j", 1, 0, 1, "top", 0.0) == b"z"


# ---------------------------------------------------------------------------
# descriptor wire forms + board protocol
# ---------------------------------------------------------------------------


class TestBandDescriptor:
    def test_gop_shard_wire_form_unchanged(self):
        """Rolling-upgrade compat: a GOP-range shard's descriptor must
        not grow a shape tag (old workers key on the exact fields)."""
        desc = gop_shard().descriptor()
        assert "shape" not in desc
        assert "band" not in desc

    def test_band_shard_wire_form(self):
        desc = band_shard("j0-b0", 0, 1).descriptor()
        assert desc["shape"] == "band"
        assert desc["band"]["start"] == 0
        assert desc["band"]["count"] == 1
        assert desc["band"]["total"] == 2
        assert desc["band"]["halo_rows"] == 32

    def test_claim_fills_groups_and_generation(self):
        board, _ = make_board()
        board.add_job("j0", [band_shard("j0-b0", 0, 1),
                             band_shard("j0-b1", 1, 2)],
                      max_attempts=3, backoff_s=0.1, quarantine_after=3)
        desc = board.claim("w1")
        assert desc is not None and desc["shape"] == "band"
        assert desc["band"]["groups"] == [[0, 1], [1, 2]]
        assert desc["band"]["gen"] == 1

    def test_unknown_shape_rejected_by_worker(self):
        from thinvids_tpu.cluster.remote import (UnsupportedShardShape,
                                                 encode_shard)

        desc = gop_shard().descriptor()
        desc["shape"] = "hologram"
        with pytest.raises(UnsupportedShardShape):
            encode_shard(desc, [])


class TestUnsupportedRequeue:
    def test_requeue_burns_no_attempt_and_excludes_host(self):
        board, _ = make_board(workers=("w1", "w2"))
        shard = band_shard("j0-b0", 0, 2)
        board.add_job("j0", [shard], max_attempts=3, backoff_s=5.0,
                      quarantine_after=3)
        desc = board.claim("w1")
        assert desc["id"] == "j0-b0"
        board.report_unsupported("j0-b0", "w1", "unknown shape")
        assert shard.state is ShardState.PENDING
        assert shard.attempt == 0          # NO attempt burned
        assert shard.not_before == 0.0     # no backoff either
        assert "w1" in shard.no_hosts
        # w1 never gets it again; w2 does
        assert board.claim("w1") is None
        desc2 = board.claim("w2")
        assert desc2 is not None and desc2["id"] == "j0-b0"

    def test_unsupported_over_http_work_status(self, tmp_path):
        from thinvids_tpu.api.server import ApiServer

        board, coord = make_board()
        shard = band_shard("j0-b0", 0, 2)
        board.add_job("j0", [shard], max_attempts=3, backoff_s=5.0,
                      quarantine_after=3)
        api = ApiServer(coord, work=board).start()
        try:
            assert board.claim("w1")["id"] == "j0-b0"
            req = urllib.request.Request(
                api.url + "/work/status",
                data=json.dumps({"shard_id": "j0-b0", "host": "w1",
                                 "ok": False, "unsupported": True,
                                 "error": "unknown shape"}).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5) as resp:
                assert json.loads(resp.read())["ok"]
            assert shard.state is ShardState.PENDING
            assert shard.attempt == 0
            assert "w1" in shard.no_hosts
        finally:
            api.stop()


class TestBandGroupRestart:
    def test_sibling_requeue_no_attempt_burn_and_gen_bump(self):
        board, _ = make_board()
        s0, s1 = band_shard("j0-b0", 0, 1), band_shard("j0-b1", 1, 2)
        board.add_job("j0", [s0, s1], max_attempts=3, backoff_s=0.1,
                      quarantine_after=5)
        assert board.claim("w1")["id"] == "j0-b0"
        assert board.claim("w2")["id"] == "j0-b1"
        board.report_failure("j0-b0", "w1", "device fell over")
        # the failed shard burned ITS attempt; the stranded sibling
        # requeued for free (preemption semantics)
        assert s0.state is ShardState.PENDING and s0.attempt == 1
        assert s1.state is ShardState.PENDING and s1.attempt == 0
        # the halo epoch moved on: stale posts refuse
        assert not board.halo.post("j0", 1, 0, 0, "top", b"x")
        with pytest.raises(HaloStaleError):
            board.halo.wait("j0", 1, 0, 0, "top", 0.0)

    def test_done_sibling_requeued_with_part_retracted(self):
        """Code-review regression: a band shard that finished BEFORE a
        sibling failed must rejoin the restart — its worker is gone,
        so the re-encoding sibling would otherwise block on halo rows
        nobody will ever send, time out, and burn the job's budget.
        The DONE shard requeues with NO attempt burned and its spooled
        part retracted (the model-checked DONE→PENDING edge)."""
        board, _ = make_board()
        s0, s1 = band_shard("j0-b0", 0, 1), band_shard("j0-b1", 1, 2)
        board.add_job("j0", [s0, s1], max_attempts=3, backoff_s=0.1,
                      quarantine_after=5)
        assert board.claim("w1")["id"] == "j0-b0"
        assert board.claim("w2")["id"] == "j0-b1"
        segs = [EncodedSegment(
            gop=GopSpec(index=i, start_frame=2 * i, num_frames=2),
            payload=b"\0\0\1x", frame_sizes=(4,)) for i in range(2)]
        assert board.submit_part("j0-b0", "w1", segs)
        assert s0.state is ShardState.DONE and s0.part_path
        board.report_failure("j0-b1", "w2", "worker died")
        assert s1.state is ShardState.PENDING and s1.attempt == 1
        # the finished sibling rejoined the lockstep restart
        assert s0.state is ShardState.PENDING
        assert s0.attempt == 0             # retraction burns nothing
        assert s0.part_path == "" and not s0.segments
        # and both are claimable again (fresh generation; the failed
        # shard's backoff gate may still be ticking — only the
        # retracted sibling must be immediately claimable)
        assert board.claim("w1")["id"] == "j0-b0"

    def test_undersized_worker_never_claims_wide_band_shard(self):
        """Code-review regression: a worker with fewer devices than a
        band shard's band_count must never be offered it — the encode
        would fail, burn an attempt and restart the whole group."""
        board, coord = make_board(workers=("small", "big"),
                                  devices={"small": 1, "big": 4})
        wide = band_shard("j0-b0", 0, 2)   # band_count=2
        board.add_job("j0", [wide], max_attempts=3, backoff_s=0.1,
                      quarantine_after=5)
        assert board.claim("small") is None
        desc = board.claim("big")
        assert desc is not None and desc["id"] == "j0-b0"

    def test_gop_shards_unaffected(self):
        board, _ = make_board()
        s0 = gop_shard("j0-0000", gop0=0)
        s1 = gop_shard("j0-0002", gop0=2)
        board.add_job("j0", [s0, s1], max_attempts=3, backoff_s=0.1,
                      quarantine_after=5)
        assert board.claim("w1")
        assert board.claim("w2")
        board.report_failure(s0.id, "w1", "boom")
        assert s1.state is ShardState.ASSIGNED   # no group semantics


class TestClaimAffinity:
    def test_prefers_continuing_the_hosts_cached_input(self):
        board, _ = make_board(workers=("w1",))
        b0 = gop_shard("j-b0", job_id="j", gop0=0, input_path="/in/b.y4m")
        a0 = gop_shard("j-a0", job_id="j", gop0=0, input_path="/in/a.y4m")
        b1 = gop_shard("j-b1", job_id="j", gop0=2, input_path="/in/b.y4m")
        board.add_job("j", [b0, a0, b1], max_attempts=3, backoff_s=0.1,
                      quarantine_after=5)
        # first claim: FIFO (no affinity yet) → b0
        assert board.claim("w1")["id"] == "j-b0"
        # b1 CONTINUES b0's frame range on the same input: preferred
        # over the earlier-queued a0 (cold open)
        assert board.claim("w1")["id"] == "j-b1"
        assert board.claim("w1")["id"] == "j-a0"

    def test_affinity_never_overrides_priority(self):
        board, _ = make_board(workers=("w1",))
        batch = gop_shard("j-b0", job_id="j", gop0=0,
                          input_path="/in/b.y4m")
        board.add_job("j", [batch], max_attempts=3, backoff_s=0.1,
                      quarantine_after=5)
        assert board.claim("w1")["id"] == "j-b0"
        live = gop_shard("j2-l0", job_id="j2", gop0=0,
                         input_path="/in/live.y4m")
        live.priority = 0
        cont = gop_shard("j-b1", job_id="j", gop0=2,
                         input_path="/in/b.y4m")
        board.add_job("j2", [live], max_attempts=3, backoff_s=0.1,
                      quarantine_after=5)
        board.add_job("j3", [cont], max_attempts=3, backoff_s=0.1,
                      quarantine_after=5)
        # live-class work beats the affinity-perfect batch continuation
        assert board.claim("w1")["id"] == "j2-l0"


# ---------------------------------------------------------------------------
# planner + clamp + stitcher
# ---------------------------------------------------------------------------


class TestBandPlanning:
    def test_plan_band_groups_partition(self):
        from thinvids_tpu.parallel.planner import plan_band_groups

        assert plan_band_groups(4, 2) == ((0, 2), (2, 4))
        assert plan_band_groups(5, 2) == ((0, 3), (3, 5))
        assert plan_band_groups(2, 8) == ((0, 1), (1, 2))
        # pure function: same inputs, same partition
        assert plan_band_groups(7, 3) == plan_band_groups(7, 3)

    def test_plan_encode_band_record_roundtrip(self):
        from thinvids_tpu.parallel.planner import plan_encode

        snap = make_settings(gop_frames=4, sfe_bands=4)
        plan = plan_encode(32, snap, num_devices=2, shape="band",
                           total_bands=4, group_count=2, mb_height=8)
        assert plan.shape == "band"
        assert plan.total_bands == 4
        assert plan.band_groups == ((0, 2), (2, 4))
        rec = plan.record()
        assert rec["shape"] == "band" and rec["total_bands"] == 4

    def test_remote_clamps_bands_to_slowest_worker(self, tmp_path):
        """Satellite fix: band shards must never plan more bands per
        shard than the SLOWEST worker's device count — clamp + WARN up
        front, never a mid-job fallback."""
        snap = make_settings(sfe_bands=16, gop_frames=2,
                             heartbeat_throttle_s=0.0,
                             pipeline_worker_count=3)
        reg = WorkerRegistry()
        reg.heartbeat("w1", metrics={"worker": True,
                                     "worker_devices": 4})
        reg.heartbeat("w2", metrics={"worker": True,
                                     "worker_devices": 1})   # slowest
        coord = Coordinator(registry=reg, settings_fn=lambda: snap)
        execu = RemoteExecutor(coord, output_dir=str(tmp_path / "lib"),
                               sync=True)

        class FakeJob:
            id = "job0000000000"
            input_path = str(tmp_path / "x.y4m")
            job_type = "transcode"
            tenant = "default"

        meta = VideoMeta(width=64, height=256)   # 16 MB rows
        plan, shards = execu._build_band_shards(FakeJob(), meta, 16,
                                                snap, token="tok123")
        # 2 workers x min(4, 1) device = 2 bands, one slice each
        assert len(shards) == 2
        assert all(s.band_count == 1 for s in shards)
        assert shards[0].total_bands == 2
        assert any("clamped to 2" in e["message"]
                   for e in coord.activity.fetch(50))

    def test_checkpoint_record_restores_band_shape(self, tmp_path):
        """PR 13 crash-resume: the durable plan record covers the band
        shape, so a restarted coordinator re-plans the IDENTICAL band
        layout from the checkpoint — independent of the worker count
        live at recovery time."""
        snap = make_settings(sfe_bands=2, gop_frames=2,
                             heartbeat_throttle_s=0.0)
        reg = WorkerRegistry()
        for hostname in ("w1", "w2"):
            reg.heartbeat(hostname, metrics={"worker": True,
                                             "worker_devices": 1})
        coord = Coordinator(registry=reg, settings_fn=lambda: snap)
        execu = RemoteExecutor(coord, output_dir=str(tmp_path / "lib"),
                               sync=True)

        class FakeJob:
            id = "job0000000000"
            input_path = str(tmp_path / "x.y4m")
            job_type = "transcode"
            tenant = "default"

        meta = VideoMeta(width=64, height=96)
        plan, shards = execu._build_band_shards(FakeJob(), meta, 8,
                                                snap, token="aaaaaa")
        rec = execu._plan_record("sig0", plan, shards)
        restored_plan, restored = execu._shards_from_record(
            FakeJob(), meta, rec, snap, token="bbbbbb")
        assert [s.key for s in restored] == [s.key for s in shards]
        for a, b in zip(shards, restored):
            assert (b.shape, b.band_start, b.band_count,
                    b.total_bands, b.halo_rows) == \
                   (a.shape, a.band_start, a.band_count,
                    a.total_bands, a.halo_rows)
            assert b.gops == a.gops
            # fresh run token → fresh run-scoped ids, same stable keys
            assert b.id != a.id

    def test_stitch_band_shards_zips_frames(self):
        def seg(idx, frames):
            return EncodedSegment(
                gop=GopSpec(index=idx, start_frame=2 * idx,
                            num_frames=len(frames)),
                payload=b"".join(frames),
                frame_sizes=tuple(len(f) for f in frames))

        s0 = band_shard("b0", 0, 1)
        s0.segments = [seg(0, [b"A0", b"A1"]), seg(1, [b"A2", b"A3"])]
        s1 = band_shard("b1", 1, 2)
        s1.segments = [seg(0, [b"b0x", b"b1x"]), seg(1, [b"b2x", b"b3x"])]
        out = stitch_band_shards([s1, s0])    # order-insensitive input
        assert [s.gop.index for s in out] == [0, 1]
        assert out[0].payload == b"A0b0xA1b1x"
        assert out[0].frame_sizes == (5, 5)
        assert out[1].payload == b"A2b2xA3b3x"

    def test_stitch_rejects_frame_count_mismatch(self):
        s0 = band_shard("b0", 0, 1)
        s0.segments = [EncodedSegment(
            gop=GopSpec(index=0, start_frame=0, num_frames=2),
            payload=b"XY", frame_sizes=(1, 1))]
        s1 = band_shard("b1", 1, 2)
        s1.segments = [EncodedSegment(
            gop=GopSpec(index=0, start_frame=0, num_frames=2),
            payload=b"X", frame_sizes=(1,))]
        with pytest.raises(ValueError, match="frame count"):
            stitch_band_shards([s0, s1])


# ---------------------------------------------------------------------------
# cross-host bit-identity
# ---------------------------------------------------------------------------


class TestFarmBitIdentity:
    def test_two_group_farm_bit_identical_to_local_mesh(self):
        """Two band slices on SEPARATE single-device meshes, lockstep
        through a real HaloRelay (every exchange code path except the
        HTTP hop), emit slice streams whose per-frame zip equals the
        local-mesh 2-band SFE stream byte for byte."""
        import jax
        from jax.sharding import Mesh

        from thinvids_tpu.core.types import concat_segments
        from thinvids_tpu.parallel.dispatch import SfeShardEncoder
        from thinvids_tpu.parallel.sfefarm import FarmBandEncoder

        w, h, n, qp, gf = 192, 128, 6, 27, 3
        frames = clip_frames(w, h, n)
        meta = VideoMeta(width=w, height=h, fps_num=30, fps_den=1,
                         num_frames=n)
        ref = SfeShardEncoder(meta, qp=qp, gop_frames=gf, bands=2)
        want = concat_segments(ref.encode(frames))

        relay = HaloRelay()
        relay.set_gen("job", 1)
        groups = [(0, 1), (1, 2)]
        outs, errs = {}, []

        def run(lo, hi, dev):
            try:
                mesh = Mesh(np.array([jax.devices()[dev]]), ("band",))
                sess = HaloSession(
                    LocalHaloHub(relay, "job", 1, timeout_s=120.0),
                    band_lo=lo, band_hi=hi, groups=groups)
                enc = FarmBandEncoder(meta, qp=qp, mesh=mesh,
                                      gop_frames=gf, total_bands=2,
                                      band_range=(lo, hi), session=sess)
                outs[lo] = enc.encode(frames)
            except Exception as exc:    # noqa: BLE001 - surfaced below
                errs.append(exc)

        ts = [threading.Thread(target=run, args=(0, 1, 0)),
              threading.Thread(target=run, args=(1, 2, 1))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(300)
        assert not errs, errs
        s0 = band_shard("b0", 0, 1, job_id="job", ngops=0)
        s0.segments = outs[0]
        s1 = band_shard("b1", 1, 2, job_id="job", ngops=0)
        s1.segments = outs[1]
        got = concat_segments(stitch_band_shards([s0, s1]))
        assert got == want


# ---------------------------------------------------------------------------
# live: farm catch-up + banded edge
# ---------------------------------------------------------------------------


def _board_worker(board, host, stop):
    """Fake worker thread: claims straight off the board (no HTTP) and
    encodes with the real shard executor."""
    from thinvids_tpu.cluster.remote import encode_shard
    from thinvids_tpu.ingest.decode import read_video

    cache = {}

    def loop():
        while not stop.is_set():
            desc = board.claim(host)
            if desc is None:
                time.sleep(0.01)
                continue
            path = desc["input_path"]
            if path not in cache:
                cache[path] = read_video(path)[1]
            segs = encode_shard(desc, cache[path])
            board.submit_part(desc["id"], host, segs)

    t = threading.Thread(target=loop, daemon=True,
                         name=f"fake-worker-{host}")
    t.start()
    return t


def _tree_bytes(root):
    out = {}
    for dirpath, _dirs, files in os.walk(root):
        for f in files:
            p = os.path.join(dirpath, f)
            with open(p, "rb") as fp:
                out[os.path.relpath(p, root)] = fp.read()
    return out


class TestLiveFarm:
    def _live_clip(self, tmp_path, name, n=24, gop=3):
        d = tmp_path / name
        d.mkdir()
        path = d / "clip.live.y4m"
        meta = write_clip(path, w=64, h=48, n=n)
        # complete source + explicit end-of-stream marker: the tail
        # sees the whole backlog at once (the catch-up scenario) and
        # ends without the stall timeout
        (d / "clip.live.y4m.eos").write_text("")
        return str(path), meta

    def test_live_catchup_fans_across_farm_byte_identical(self,
                                                          tmp_path):
        """A live job under the remote backend farms its backlog GOPs
        across workers while the newest GOP encodes locally — and the
        served tree is byte-identical to the all-local live run."""
        path_l, meta = self._live_clip(tmp_path, "local")
        snap = make_settings(gop_frames=3, qp=30, ladder_rungs="24",
                             segment_s=0.2, dvr_window_s=0.0,
                             live_stall_s=5.0, heartbeat_throttle_s=0.0,
                             pipeline_worker_count=3)
        reg = WorkerRegistry()
        for i in range(8):
            reg.heartbeat(f"ref{i}")
        coord_l = Coordinator(registry=reg, settings_fn=lambda: snap)
        exec_l = LocalExecutor(coord_l,
                               output_dir=str(tmp_path / "lib_l"),
                               sync=True)
        coord_l._launcher = exec_l.launch
        job_l = coord_l.add_job(path_l, meta)
        job_l = coord_l.store.get(job_l.id)
        assert job_l.status is Status.DONE, job_l.failure_reason
        want = _tree_bytes(str(tmp_path / "lib_l" / "clip.live.hls"))

        path_r, meta = self._live_clip(tmp_path, "remote")
        reg_r = WorkerRegistry()
        for hostname in ("fw1", "fw2"):
            reg_r.heartbeat(hostname, metrics={"worker": True,
                                               "worker_devices": 1})
        coord_r = Coordinator(registry=reg_r, settings_fn=lambda: snap)
        exec_r = RemoteExecutor(coord_r,
                                output_dir=str(tmp_path / "lib_r"),
                                sync=False, poll_s=0.05)
        coord_r._launcher = exec_r.launch
        stop = threading.Event()
        try:
            for hostname in ("fw1", "fw2"):
                _board_worker(exec_r.board, hostname, stop)
            job_r = coord_r.add_job(path_r, meta)
            deadline = time.time() + 120
            while time.time() < deadline:
                st = coord_r.store.get(job_r.id)
                if st.status in (Status.DONE, Status.FAILED):
                    break
                time.sleep(0.1)
        finally:
            stop.set()
        st = coord_r.store.get(job_r.id)
        assert st.status is Status.DONE, st.failure_reason
        # the farm actually took catch-up shards
        events = [e["message"] for e in coord_r.activity.fetch(200)]
        assert any("live catch-up" in m for m in events), events
        got = _tree_bytes(str(tmp_path / "lib_r" / "clip.live.hls"))
        assert set(got) == set(want)
        diff = [k for k in want if got[k] != want[k]]
        assert not diff, f"live tree diverged at {diff}"

    def test_live_sfe_edge_single_rung(self, tmp_path):
        """`sfe_bands > 0` + a single-rung stream runs the live edge
        through the split-frame encoder (per-frame banded stepping) —
        the job completes and serves a playable tree."""
        path, meta = self._live_clip(tmp_path, "sfe", n=12)
        snap = make_settings(gop_frames=3, qp=30, ladder_rungs="48",
                             segment_s=0.2, dvr_window_s=0.0,
                             live_stall_s=5.0, sfe_bands=2,
                             heartbeat_throttle_s=0.0)
        reg = WorkerRegistry()
        for i in range(8):
            reg.heartbeat(f"w{i}")
        coord = Coordinator(registry=reg, settings_fn=lambda: snap)
        execu = LocalExecutor(coord, output_dir=str(tmp_path / "lib"),
                              sync=True)
        coord._launcher = execu.launch
        job = coord.add_job(path, meta)
        job = coord.store.get(job.id)
        assert job.status is Status.DONE, job.failure_reason
        tree = tmp_path / "lib" / "clip.live.hls"
        assert (tree / "master.m3u8").exists()
        # SFE frames flowed through the per-frame pipeline
        from thinvids_tpu.parallel.dispatch import stage_snapshot

        assert stage_snapshot().get("sfe_frames", 0) >= 12


# ---------------------------------------------------------------------------
# hermetic cross-host end-to-end (subprocess farm over HTTP)
# ---------------------------------------------------------------------------


def _call(base, path, method="GET", body=None, timeout=10):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(base + path, data=data, method=method)
    if data:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _wait(predicate, deadline_s, interval=0.25, what="condition"):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        out = predicate()
        if out:
            return out
        time.sleep(interval)
    raise TimeoutError(f"timed out waiting for {what}")


def _try_health(base):
    try:
        return _call(base, "/health", timeout=3)
    except (urllib.error.URLError, ConnectionError, OSError):
        return None


def _job_if_terminal(base, job_id):
    job = _call(base, f"/job_properties/{job_id}")["job"]
    return job if job["status"] in ("done", "failed", "stopped") \
        else None


def test_farm_sfe_end_to_end_two_workers(tmp_path):
    """Acceptance: coordinator + 2 single-device worker daemons encode
    ONE stream as frame-band shards — halo rows crossing hosts per
    frame over /work/halo — and the stitched MP4 is BYTE-identical to
    a local-mesh SFE encode with the same 2-band layout. The job's
    distributed trace carries both workers' band spans under one trace
    id."""
    import socket as socket_mod

    clip = tmp_path / "clip.y4m"
    meta = write_clip(clip, w=64, h=96, n=12)
    ref_settings = make_settings(gop_frames=3, qp=30, sfe_bands=2,
                                 heartbeat_throttle_s=0.0)
    reg = WorkerRegistry()
    for i in range(8):
        reg.heartbeat(f"ref{i}")
    ref_coord = Coordinator(registry=reg,
                            settings_fn=lambda: ref_settings)
    ref_exec = LocalExecutor(ref_coord,
                             output_dir=str(tmp_path / "lib_local"),
                             sync=True)
    ref_coord._launcher = ref_exec.launch
    ref_job = ref_coord.add_job(str(clip), meta)
    ref_job = ref_coord.store.get(ref_job.id)
    assert ref_job.status is Status.DONE, ref_job.failure_reason
    with open(ref_job.output_path, "rb") as fp:
        want = fp.read()

    with socket_mod.socket() as sk:
        sk.bind(("127.0.0.1", 0))
        port = sk.getsockname()[1]
    base = f"http://127.0.0.1:{port}"
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
        TVT_EXECUTION_BACKEND="remote", TVT_SFE_BANDS="2",
        TVT_MIN_IDLE_WORKERS="0", TVT_PIPELINE_WORKER_COUNT="3",
        TVT_METRICS_TTL_S="3", TVT_REMOTE_RETRY_BACKOFF_S="0.2",
        TVT_GOP_FRAMES="3", TVT_QP="30", TVT_SCHEDULER_POLL_S="0.5",
        TVT_HALO_TIMEOUT_S="120")
    coord = subprocess.Popen(
        [sys.executable, "-m", "thinvids_tpu.cli", "coordinator",
         "--host", "127.0.0.1", "--port", str(port),
         "--state-dir", str(tmp_path / "state"),
         "--output-dir", str(tmp_path / "library")],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    workers = []
    try:
        _wait(lambda: _try_health(base), 45, what="coordinator API")
        for i in range(2):
            workers.append(subprocess.Popen(
                [sys.executable, "-m", "thinvids_tpu.cli", "worker",
                 "--coordinator", base, "--node-name", f"farmsfe-w{i}",
                 "--interval", "0.3", "--poll", "0.2"],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT))
        _wait(lambda: len([n for n in _call(base, "/nodes_data")["nodes"]
                           if n["host"].startswith("farmsfe-w")]) == 2,
              30, what="both workers registered")
        job = _call(base, "/add_job", "POST",
                    {"input_path": str(clip)})
        done = _wait(lambda: _job_if_terminal(base, job["id"]), 300,
                     what="farm SFE job terminal")
        assert done["status"] == "done", done
        with open(done["output_path"], "rb") as fp:
            got = fp.read()
        assert got == want, (
            f"farm SFE output diverged from the local-mesh SFE "
            f"reference ({len(got)} vs {len(want)} bytes)")
        # one trace id spans both hosts' band work (PR 10 acceptance)
        trace = json.dumps(_call(base, f"/trace/{job['id']}"))
        assert "farmsfe-w0" in trace and "farmsfe-w1" in trace
        assert "worker_shard" in trace
    finally:
        for p in workers:
            p.kill()
        coord.kill()
        for p in workers + [coord]:
            p.wait(10)
