"""JAX compute path and native packer must match the numpy/Python reference
bit-exactly — these are the "same bits, different engine" guarantees that
let bench run the fast paths while conformance is proven on the slow ones."""

import numpy as np
import pytest

from thinvids_tpu import native
from thinvids_tpu.codecs.h264.encoder import encode_frame_arrays, pack_slice
from thinvids_tpu.codecs.h264.headers import PPS, SPS
from thinvids_tpu.codecs.h264.jaxcore import encode_intra_jax


def _planes(w, h, seed=7):
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:h, 0:w]
    y = np.clip(((xx * 2 + yy) % 256) + rng.integers(-8, 8, (h, w)),
                0, 255).astype(np.uint8)
    u = np.clip(128 + rng.integers(-20, 20, (h // 2, w // 2)), 0, 255).astype(np.uint8)
    v = np.clip(128 + rng.integers(-20, 20, (h // 2, w // 2)), 0, 255).astype(np.uint8)
    return y, u, v


class TestJaxCore:
    @pytest.mark.parametrize("size", [(64, 48), (96, 32), (16, 16)])
    @pytest.mark.parametrize("qp", [10, 27, 40])
    def test_bit_exact_vs_numpy(self, size, qp):
        w, h = size
        y, u, v = _planes(w, h)
        ref, _ = encode_frame_arrays(y, u, v, qp)
        jx = encode_intra_jax(y, u, v, qp)
        for name in ("luma_dc", "luma_ac", "chroma_dc", "chroma_ac",
                     "luma_mode", "chroma_mode"):
            assert np.array_equal(getattr(ref, name), getattr(jx, name)), name


@pytest.mark.skipif(not native.available(), reason="no C++ toolchain")
class TestNativePacker:
    @pytest.mark.parametrize("qp", [8, 20, 27, 40])
    def test_bit_identical_vs_python(self, qp):
        w, h = 96, 64
        y, u, v = _planes(w, h)
        sps, pps = SPS(width=w, height=h), PPS(init_qp=qp)
        levels, _ = encode_frame_arrays(y, u, v, qp)
        py = pack_slice(levels, w // 16, h // 16, sps, pps, qp, native=False)
        nat = pack_slice(levels, w // 16, h // 16, sps, pps, qp, native=True)
        assert py == nat

    def test_noise_worst_case(self):
        # pure noise maximizes coefficient density / table coverage
        rng = np.random.default_rng(0)
        w, h = 64, 32
        y = rng.integers(0, 256, (h, w), dtype=np.uint8)
        u = rng.integers(0, 256, (h // 2, w // 2), dtype=np.uint8)
        v = rng.integers(0, 256, (h // 2, w // 2), dtype=np.uint8)
        for qp in (4, 16, 30):
            sps, pps = SPS(width=w, height=h), PPS(init_qp=qp)
            levels, _ = encode_frame_arrays(y, u, v, qp)
            py = pack_slice(levels, w // 16, h // 16, sps, pps, qp, native=False)
            nat = pack_slice(levels, w // 16, h // 16, sps, pps, qp, native=True)
            assert py == nat
