"""Pallas ME kernel vs the XLA reference implementation.

`me_search_xla` is the executable spec (it backs the CPU conformance
tests against the libavcodec oracle); this file checks that the
PRODUCTION Pallas kernel — run in the Pallas interpreter on CPU —
computes the identical (mv, pred) on content engineered so neighboring
macroblocks pick DIFFERENT candidates. That non-uniformity matters: a
per-MB -> per-lane mask-expansion bug (pltpu.repeat is a tile repeat,
not an element repeat) was invisible on uniform-motion content because
every MB of a lane tile took the same candidate.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from thinvids_tpu.codecs.h264 import jaxme


def _mixed_motion_frames(w, h, seed=0):
    """(cur, ref_y, ref_u, ref_v) where different MBs have different
    true motion: the left half pans (+3, +3), the right half (-2, +1),
    with texture + noise so SADs are distinctive."""
    rng = np.random.default_rng(seed)
    pad = 8
    scene = rng.integers(0, 255, (h + 2 * pad, w + 2 * pad)).astype(np.uint8)
    ref = scene[pad:pad + h, pad:pad + w]
    cur = np.empty_like(ref)
    cur[:, :w // 2] = scene[pad + 3:pad + 3 + h, pad + 3:pad + 3 + w // 2]
    cur[:, w // 2:] = scene[pad - 2:pad - 2 + h,
                            pad + w // 2 + 1:pad + w + 1]
    ref_u = rng.integers(0, 255, (h // 2, w // 2)).astype(np.uint8)
    ref_v = rng.integers(0, 255, (h // 2, w // 2)).astype(np.uint8)
    return cur, ref, ref_u, ref_v


# (192, 128) pads to H4=128 → RG=2 grid bands: the multi-band row-block
# index maps (2*r+k) in _me_pallas and the band-relative row bases in
# the kernel only execute with >= 2 bands (ADVICE round 5: both original
# shapes collapsed to a single band, leaving a 1080p-sized blind spot).
@pytest.mark.parametrize("w,h", [(128, 64), (320, 32), (192, 128)])
def test_pallas_kernel_matches_xla_reference(w, h):
    cur, ref, ref_u, ref_v = _mixed_motion_frames(w, h)
    cy = jnp.asarray(cur, jnp.int16)
    ry = jnp.asarray(ref, jnp.int16)
    ru = jnp.asarray(ref_u, jnp.int16)
    rv = jnp.asarray(ref_v, jnp.int16)
    pmv = jnp.asarray([2, -3], jnp.int32)
    qp = jnp.asarray(27, jnp.int32)

    centers = jaxme.centers_from(cy, ry, pmv)
    lam = jnp.asarray(jaxme.LAMBDA_H)[27]

    out_k = jax.device_get(jaxme.me_search_pallas(
        cy, ry, ru, rv, centers, lam, interpret=True))
    out_x = jax.device_get(jaxme.me_search_xla(
        cy, ry, ru, rv, centers, lam))

    names = ["mv", "pred_y", "pred_u", "pred_v"]
    for name, a, b in zip(names, out_k, out_x):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"pallas kernel diverges from XLA reference: {name}")

    # sanity: the engineered content really did split MB decisions
    mv = np.asarray(out_x[0]).reshape(-1, 2)
    assert len({tuple(v) for v in mv}) > 1
