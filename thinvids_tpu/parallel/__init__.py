"""Sequence (GOP) parallelism over a TPU device mesh.

The reference shards the video timeline into ~10 MB file segments dispatched
to worker nodes over a task queue (/root/reference/worker/tasks.py:597-609,
977-1052); here the timeline is sharded at closed-GOP boundaries across the
devices of a `jax.sharding.Mesh` with `shard_map`, and encoded segments are
re-assembled in index order (the stitcher analog, tasks.py:2047-2069).
"""

from .planner import plan_segments
from .dispatch import GopShardEncoder, encode_clip_sharded

__all__ = ["plan_segments", "GopShardEncoder", "encode_clip_sharded"]
