"""Provider seam: how the capacity controller actually wakes and
suspends worker hosts.

The reference pair was WoL magic packets (manager side) + agent
self-suspend (node side); a TPU-VM farm substitutes a cloud API call;
tests and the autoscale bench substitute real ``cli.py worker``
subprocesses. The controller only ever sees two callables:

    wake(host) -> bool      bring the host's worker daemon up
    suspend(host) -> bool   take it down (after the controller drained it)

Both are best-effort booleans — a False/raise leaves the lifecycle
where it was so the controller retries on a later tick. Providers run
OUTSIDE the controller's lock (they may block on subprocess spawn or a
cloud API round-trip).

jax-free by contract.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
from typing import Callable, Mapping

from ..core.log import get_logging

_LOG = get_logging(__name__)


class CallableProvider:
    """Wrap two injected callables — the deployment seam (wire a cloud
    scale API, a WoL sender + agent-suspend POST, an IPMI call...)."""

    def __init__(self, wake: Callable[[str], bool] | None = None,
                 suspend: Callable[[str], bool] | None = None) -> None:
        self._wake = wake
        self._suspend = suspend

    def wake(self, host: str) -> bool:
        if self._wake is None:
            return False
        return bool(self._wake(host))

    def suspend(self, host: str) -> bool:
        if self._suspend is None:
            return False
        return bool(self._suspend(host))


class NullProvider(CallableProvider):
    """Default provider: logs the intent and reports failure, so the
    controller keeps lifecycle bookkeeping honest (a host it cannot
    actually suspend stays DRAINING→ACTIVE rather than lying
    SUSPENDED). Deployments replace this (deploy/README.md)."""

    def wake(self, host: str) -> bool:
        _LOG.info("no farm provider wired: cannot wake %s", host)
        return False

    def suspend(self, host: str) -> bool:
        _LOG.info("no farm provider wired: cannot suspend %s", host)
        return False


class SubprocessProvider:
    """Spawn/kill real ``python -m thinvids_tpu.cli worker`` daemons on
    this host — the hermetic analog of the reference's WoL wake +
    agent-suspend pair, used by tests and the autoscale bench
    (bench.py ``_run_autoscale``). ``suspend`` SIGTERMs the daemon
    (graceful: the controller already drained its leases); ``kill``
    SIGKILLs it without ceremony — the chaos harness's worker-crash
    primitive."""

    def __init__(self, coordinator_url: str,
                 env: Mapping[str, str] | None = None,
                 heartbeat_s: float = 0.3, poll_s: float = 0.2) -> None:
        self.coordinator_url = coordinator_url
        self.env = dict(env if env is not None else os.environ)
        self.heartbeat_s = heartbeat_s
        self.poll_s = poll_s
        self._lock = threading.Lock()
        self._procs: dict[str, subprocess.Popen] = {}

    def wake(self, host: str) -> bool:
        with self._lock:
            proc = self._procs.get(host)
            if proc is not None and proc.poll() is None:
                return True            # already up (re-wake is idempotent)
        # spawn OUTSIDE the lock (Popen blocks on fork/exec); the
        # re-check below resolves a racing double-wake in favor of
        # whoever registered first
        spawned = subprocess.Popen(
            [sys.executable, "-m", "thinvids_tpu.cli", "worker",
             "--coordinator", self.coordinator_url,
             "--node-name", host,
             "--interval", str(self.heartbeat_s),
             "--poll", str(self.poll_s)],
            env=self.env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT)
        duplicate = None
        with self._lock:
            proc = self._procs.get(host)
            if proc is not None and proc.poll() is None:
                duplicate = spawned    # lost the race: theirs wins
            else:
                self._procs[host] = spawned
        if duplicate is not None:
            duplicate.kill()
            duplicate.wait(timeout=10)
        return True

    def _stop(self, host: str, sig: int) -> bool:
        with self._lock:
            proc = self._procs.pop(host, None)
        if proc is None:
            return False
        if proc.poll() is None:
            try:
                proc.send_signal(sig)
            except OSError:
                pass
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
        return True

    def suspend(self, host: str) -> bool:
        return self._stop(host, signal.SIGTERM)

    def kill(self, host: str) -> bool:
        """SIGKILL, no goodbye — the chaos harness's crashed-worker
        primitive (the daemon's leases strand until the board's
        heartbeat-TTL sweep requeues them)."""
        return self._stop(host, signal.SIGKILL)

    def hosts(self) -> list[str]:
        """Hosts with a live daemon process right now."""
        with self._lock:
            return [h for h, p in self._procs.items() if p.poll() is None]

    def stop_all(self) -> None:
        with self._lock:
            hosts = list(self._procs)
        for host in hosts:
            self._stop(host, signal.SIGKILL)
