"""Farm split-frame encoding: one frame's band layout spread across
WORKER HOSTS (the cross-host form of PR 9's SfeShardEncoder).

Each band shard (cluster/remote.py, shape="band") owns a contiguous
slice [band_lo, band_hi) of the job's pinned GLOBAL band layout and
steps the SAME fixed GOP grid in lockstep with its peers. Within the
slice the device mesh still runs the PR 9 banded programs
(ppermute/psum over the local axis); ACROSS slices the three
collective flows move to the host and ride the coordinator-relayed
halo route (cluster/halo.py):

- neighbor reference rows: after each frame's step the slice's
  boundary recon rows ship to the adjacent groups and come back as
  injected halo inputs for the next frame's search;
- global-motion probe: a per-host partial-cost program
  (dispatch._sfe_probe_step) + cross-host int32 sum + host argmin —
  bit-identical to the full-mesh psum+argmin;
- temporal median: the per-host histogram partial leaves the device
  with the level streams, sums across hosts, and the host-side
  cumsum/argmax (jaxme.median_from_counts) feeds the next frame's
  search center.

Because every cross-host reduction is an integer sum and the injected
halo rows are exactly the bytes ppermute would have delivered, a farm
of N single-band hosts emits THE SAME band slices a local N-band mesh
would — the coordinator's per-frame zip of the groups' slices is
byte-identical to the local-mesh SFE stream (the hermetic 2-worker
test proves it end to end).

The GOP walk is synchronous here (a frame's step needs the previous
frame's exchange), so a "wave" = one GOP, fully encoded inside
dispatch_wave; escapes fall back to a host-LOCAL dense replay fed by
the cached per-frame injected inputs — peers never notice (recon,
halo and histogram flows are identical either way).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.types import EncodedSegment, VideoMeta
from .dispatch import (SfeShardEncoder, _sfe_intra_step_dense,
                       _sfe_p_step_farm, _sfe_p_step_farm_dense,
                       _sfe_probe_step)


class FarmBandEncoder(SfeShardEncoder):
    """SfeShardEncoder over a SLICE of a cross-host band layout."""

    def __init__(self, meta: VideoMeta, qp: int = 27,
                 mesh: Mesh | None = None, gop_frames: int = 32,
                 max_segments: int = 200, total_bands: int = 0,
                 band_range: tuple[int, int] | None = None,
                 halo_rows: int | None = None, session=None,
                 pack_workers: int | None = None):
        super().__init__(meta, qp=qp, mesh=mesh, gop_frames=gop_frames,
                         max_segments=max_segments, halo_rows=halo_rows,
                         pack_workers=pack_workers,
                         # synchronous GOP walk: the exchange serializes
                         # frames anyway, and window 1 bounds retained
                         # staged GOPs on worker hosts
                         pipeline_window=1,
                         total_bands=total_bands, band_range=band_range)
        #: cluster/halo.HaloSession (or None for a single-group layout
        #: covering the whole frame — no peers to talk to)
        self.session = session
        self.edge_top = self.band_lo == 0
        self.edge_bot = self.band_hi == self.global_band_plan.num_bands
        #: traced (2,) bool the farm steps take as an INPUT — a
        #: re-claim of a different slice position must reuse the same
        #: compiled programs, not recompile per edge-flag combination
        self._edges = jnp.asarray([self.edge_top, self.edge_bot],
                                  bool)
        if session is None and not (self.edge_top and self.edge_bot):
            raise ValueError(
                "a band SLICE (neighbors exist) needs a halo session")

    # -- host<->device glue for the injected halo ----------------------

    def _ext_device(self, top, bot, rows: int, width: int):
        """(top, bot) host arrays → the band-sharded injected-ext
        inputs of the farm steps: only the first band's block of `top`
        and the last band's block of `bot` are ever read."""
        B = self.band_plan.num_bands
        t = np.zeros((B * rows, width), np.int16)
        b = np.zeros((B * rows, width), np.int16)
        if top is not None:
            t[:rows] = top
        if bot is not None:
            b[(B - 1) * rows:] = bot
        if self._step_mesh() is None:
            return jnp.asarray(t), jnp.asarray(b)
        shard = NamedSharding(self.mesh, P("band"))
        return jax.device_put(t, shard), jax.device_put(b, shard)

    def _ext_triplet(self, top_in, bot_in):
        halo = self.halo_rows
        W = self.band_plan.mb_width * 16
        ty, by = self._ext_device(
            top_in["y"] if top_in else None,
            bot_in["y"] if bot_in else None, halo, W)
        tu, bu = self._ext_device(
            top_in["u"] if top_in else None,
            bot_in["u"] if bot_in else None, halo // 2, W // 2)
        tv, bv = self._ext_device(
            top_in["v"] if top_in else None,
            bot_in["v"] if bot_in else None, halo // 2, W // 2)
        return ty, by, tu, bu, tv, bv

    def _edge_rows(self, carry3):
        """This slice's boundary recon rows (frame just stepped): what
        the neighbor groups splice in as their halo. None at true
        frame edges (nobody consumes them)."""
        ry, ru, rv = carry3
        halo = self.halo_rows
        hc = halo // 2
        top = bot = None
        if not self.edge_top:
            with self.stages.stage("fetch"):
                top = {"y": np.asarray(jax.device_get(ry[:halo]),
                                       np.int16),
                       "u": np.asarray(jax.device_get(ru[:hc]), np.int16),
                       "v": np.asarray(jax.device_get(rv[:hc]),
                                       np.int16)}
        if not self.edge_bot:
            with self.stages.stage("fetch"):
                bot = {"y": np.asarray(jax.device_get(ry[-halo:]),
                                       np.int16),
                       "u": np.asarray(jax.device_get(ru[-hc:]),
                                       np.int16),
                       "v": np.asarray(jax.device_get(rv[-hc:]),
                                       np.int16)}
        return top, bot

    # -- cross-host reductions -----------------------------------------

    def _global_probe(self, seq: int, cur_y, ref_y, ty, by) -> np.ndarray:
        from ..codecs.h264 import jaxme

        bp = self.band_plan
        with self.stages.stage("dispatch"):
            cost = _sfe_probe_step(cur_y, ref_y, self._real_rows, ty,
                                   by, self._edges,
                                   mesh=self._step_mesh(),
                                   num_bands=bp.num_bands)
        with self.stages.stage("device_wait"):
            cost_h = np.asarray(jax.device_get(cost))[0]
        if self.session is not None:
            with self.stages.stage("halo"):
                cost_h = self.session.sum_probe(seq, cost_h)
        return jaxme.probe_center_from_cost(cost_h)

    def _global_median(self, seq: int, hist_local) -> np.ndarray:
        from ..codecs.h264 import jaxme

        cnt = np.asarray(hist_local[0], np.int32)
        n = int(hist_local[1])
        if self.session is not None:
            with self.stages.stage("halo"):
                peers = self.session.gather_hists(seq)
            for h in peers:
                cnt = (cnt + np.asarray(h["cnt"], np.int32)) \
                    .astype(np.int32)
                n += int(np.asarray(h["n"]).reshape(-1)[0])
        return jaxme.median_from_counts(cnt, n, 2 * jaxme.SEARCH_RANGE)

    # -- the lockstep GOP walk -----------------------------------------

    def dispatch_wave(self, staged: tuple) -> tuple:
        """Encode ONE GOP of this band slice, frame by frame in
        lockstep with the peer groups. Returns (global GopSpec,
        per-frame NAL bytes) — collect_wave only assembles the
        segment."""
        import dataclasses as _dc

        gop, ys, us, vs, qp = staged
        bp = self.band_plan
        mesh = self._step_mesh()
        sess = self.session
        qpj = jnp.asarray(qp, jnp.int32)
        gop_g = _dc.replace(gop, index=gop.index + self.gop_index_offset,
                            start_frame=(gop.start_frame
                                         + self.frame_offset))
        idr_pic_id = gop_g.index % 65536
        F = gop.num_frames
        nals: list[bytes] = []
        #: cached per-P-frame injected inputs — the dense replay's feed
        replay: list[tuple] = []
        dense_from: int | None = None
        hist_local = None
        carry3 = None
        pred = np.zeros(2, np.int32)
        for fi in range(F):
            seq = gop_g.start_frame + fi
            if fi == 0:
                with self.stages.stage("dispatch"):
                    r = self._intra_step(ys[0], us[0], vs[0], qpj)
                outs, carry3 = r[:6], r[6:9]
                hist_local = None
            else:
                with self.stages.stage("halo"):
                    top_in, bot_in = sess.gather_edges(seq - 1) \
                        if sess is not None else (None, None)
                pred = self._global_median(seq - 1, hist_local) \
                    if fi >= 2 else np.zeros(2, np.int32)
                ty, by, tu, bu, tv, bv = self._ext_triplet(top_in, bot_in)
                probe = self._global_probe(seq, ys[fi], carry3[0], ty, by)
                with self.stages.stage("dispatch"):
                    r = _sfe_p_step_farm(
                        ys[fi], us[fi], vs[fi], *carry3,
                        jnp.asarray(pred), jnp.asarray(probe),
                        ty, by, tu, bu, tv, bv, qpj, self._real_rows,
                        self._edges, mbw=bp.mb_width,
                        mbh_band=bp.band_mb_rows, mesh=mesh,
                        halo_rows=self.halo_rows,
                        num_bands=bp.num_bands, rd=self.rd)
                outs, carry3 = r[:6], r[8:11]
                with self.stages.stage("device_wait"):
                    cnt_h, n_h = jax.device_get([r[6], r[7]])
                hist_local = (np.asarray(cnt_h)[0].astype(np.int32),
                              int(np.asarray(n_h).reshape(-1)[0]))
                replay.append((pred, probe, top_in, bot_in))
            # unblock the peers FIRST: their next frame's search waits
            # on these rows, while our own pack work below is local
            if sess is not None and fi < F - 1:
                top_out, bot_out = self._edge_rows(carry3)
                hist_blob = None
                if hist_local is not None:
                    hist_blob = {
                        "cnt": hist_local[0],
                        "n": np.asarray([hist_local[1]], np.int64)}
                with self.stages.stage("halo"):
                    sess.publish_state(seq, top=top_out, bot=bot_out,
                                       hist=hist_blob)
            head, nblk, nval, n_esc, used, payload = outs
            with self.stages.stage("device_wait"):
                tiny = jax.device_get([nblk, nval, n_esc, used])
            self.stages.bump("d2h_bytes",
                             sum(int(a.nbytes) for a in tiny))
            nblk_h, nval_h, nesc_h, used_h = tiny
            if dense_from is None \
                    and int(np.asarray(nesc_h).max()) > 0:
                dense_from = fi     # escape: this slice replays dense
                                    # LOCALLY after the walk — the
                                    # exchange flows above continue
                                    # untouched (identical either way)
            if dense_from is not None:
                continue
            _, L = self._band_sizes(intra=(fi == 0))
            with self.stages.stage("fetch"):
                (head_h,) = self._fetch_bulk([head])
                rows = self._fetch_payload_rows(payload, used_h)
            with self.stages.stage("sfe"):
                nals.append(self._pack_band_frame(
                    fi, head_h, rows, nblk_h, nval_h, used_h, L, qp,
                    idr_pic_id))
            self._note_frame_done(seq)
        if dense_from is not None:
            nals = self._replay_dense(gop_g, staged, nals, dense_from,
                                      replay)
        return (gop_g, nals)

    def _pack_band_frame(self, fi: int, head_h, rows, nblk_h, nval_h,
                         used_h, L: int, qp: int,
                         idr_pic_id: int) -> bytes:
        bp = self.band_plan
        thunks = []
        for bi in range(bp.num_bands):
            rest = functools.partial(
                self._unpack_compact, rows[bi], int(nblk_h[bi]),
                int(nval_h[bi]), int(used_h[bi]), L)
            if fi == 0:
                thunks.append(functools.partial(
                    lambda r, b: self._pack_intra_band(
                        head_h[b], r(), b, qp, idr_pic_id), rest, bi))
            else:
                thunks.append(functools.partial(
                    lambda r, b, fn: self._pack_p_band(
                        head_h[b], r(), b, qp, fn), rest, bi, fi % 256))
        frame_nal = b"".join(self._gather_frame(thunks))
        if fi == 0 and self.emit_parameter_sets:
            frame_nal = self.sps.to_nal() + self.pps.to_nal() + frame_nal
        return frame_nal

    def _replay_dense(self, gop_g, staged: tuple, nals: list[bytes],
                      dense_from: int, replay: list[tuple]
                      ) -> list[bytes]:
        """Escape fallback, host-LOCAL: rerun this slice's GOP through
        the dense-transfer farm steps, feeding the CACHED per-frame
        injected inputs (pred, probe, neighbor rows) — no re-exchange,
        bit-identical levels (the wave path's fallback contract)."""
        prof = self.stages
        bp = self.band_plan
        _, ys, us, vs, qp = staged
        qpj = jnp.asarray(qp, jnp.int32)
        mesh = self._step_mesh()
        idr_pic_id = gop_g.index % 65536
        prof.bump("dense_fallback_waves")
        with prof.stage("dense_retry"):
            carry3 = None
            for fi in range(gop_g.num_frames):
                if fi == 0:
                    r = _sfe_intra_step_dense(
                        ys[0], us[0], vs[0], qpj, self._real_rows,
                        mbw=bp.mb_width, mbh_band=bp.band_mb_rows,
                        mesh=mesh, rd=self.rd,
                        total_mb_rows=self._total_mb_rows)
                    head, flat, carry3 = None, r[0], r[1:4]
                else:
                    pred, probe, top_in, bot_in = replay[fi - 1]
                    ty, by, tu, bu, tv, bv = self._ext_triplet(top_in,
                                                               bot_in)
                    r = _sfe_p_step_farm_dense(
                        ys[fi], us[fi], vs[fi], *carry3,
                        jnp.asarray(pred), jnp.asarray(probe),
                        ty, by, tu, bu, tv, bv, qpj, self._real_rows,
                        self._edges, mbw=bp.mb_width,
                        mbh_band=bp.band_mb_rows, mesh=mesh,
                        halo_rows=self.halo_rows,
                        num_bands=bp.num_bands, rd=self.rd)
                    head, flat, carry3 = r[0], r[1], r[2:5]
                if fi < dense_from:
                    continue        # already packed from sparse
                if head is None:
                    flat_h = self._fetch_bulk([flat])[0]
                    head_h = None
                else:
                    head_h, flat_h = self._fetch_bulk([head, flat])
                thunks = []
                for bi in range(bp.num_bands):
                    if fi == 0:
                        thunks.append(functools.partial(
                            lambda b, f: self._pack_intra_band_dense(
                                f[b], b, qp, idr_pic_id), bi, flat_h))
                    else:
                        thunks.append(functools.partial(
                            lambda b, m, f, fn: self._pack_p_band(
                                m[b], f[b], b, qp, fn),
                            bi, head_h, flat_h, fi % 256))
                frame_nal = b"".join(self._gather_frame(thunks))
                if fi == 0 and self.emit_parameter_sets:
                    frame_nal = self.sps.to_nal() + self.pps.to_nal() \
                        + frame_nal
                nals.append(frame_nal)
                self._note_frame_done(gop_g.start_frame + fi)
        return nals

    def collect_wave(self, pending: tuple) -> list[EncodedSegment]:
        gop_g, nals = pending
        with self.stages.stage("concat"):
            seg = EncodedSegment(gop=gop_g, payload=b"".join(nals),
                                 frame_sizes=tuple(len(n) for n in nals))
        self.stages.count_wave()
        return [seg]
