"""BENCH JSON schema guards.

The round driver parses bench.py's single JSON line; these tests pin the
schema — in particular the `stage_ms` host-stage breakdown (now
including the streaming-ingest `decode`/`stage` keys), the cold
end-to-end `fps_cold_1080p` figure, and the 4K quality key naming — on
a small CPU run (tiny resolution, no oracle decode) so a schema
regression fails fast instead of at round scoring.
"""

import bench


def test_run_pipeline_reports_stage_breakdown():
    from thinvids_tpu.parallel.dispatch import STAGE_COUNTERS, STAGE_NAMES

    r = bench._run_pipeline(64, 48, nframes=4, qp=27, gop_frames=2,
                            quality=False)
    assert r["fps"] > 0 and r["device_fps"] > 0 and r["bytes"] > 0
    for key in STAGE_NAMES:
        assert key in r["stage_ms"]
    # the boundary counters ride in the same snapshot: actual D2H
    # traffic (bench reports it per frame) + the dense-fallback and
    # per-shard-fetch tallies
    for key in STAGE_COUNTERS:
        assert key in r["stage_ms"]
    assert r["stage_ms"]["d2h_bytes"] > 0
    assert r["stage_ms"]["waves"] >= 1


def test_run_cold_reports_streaming_breakdown():
    """The cold figure runs the production streaming ingest; its stage
    breakdown must carry the decode/stage keys that path exercises."""
    r = bench._run_cold(64, 48, nframes=4, qp=27, gop_frames=2, runs=1)
    assert r["fps"] > 0 and r["bytes"] > 0
    assert "decode" in r["stage_ms"] and "stage" in r["stage_ms"]
    assert r["stage_ms"]["waves"] >= 1


def test_bench_result_schema_includes_stage_ms():
    from thinvids_tpu.parallel.dispatch import STAGE_NAMES

    r = {"fps": 33.3, "device_fps": 50.0, "bytes": 1200,
         "stage_ms": {k: 1.0 for k in STAGE_NAMES}
         | {"waves": 2, "d2h_bytes": 6400},
         "quality": {"psnr_y": 40.1, "ssim_y": 0.99}}
    r4k = {"fps": 2.8, "device_fps": 7.0, "bytes": 9000,
           "stage_ms": {}, "quality": {"psnr_y": 41.0, "ssim_y": 0.98}}
    cold = {"fps": 31.1, "bytes": 1200,
            "stage_ms": {k: 1.0 for k in STAGE_NAMES} | {"waves": 2}}
    result = bench.build_result(r, r4k, platform="cpu", qp=27, gop=8,
                                n_1080=64, cold=cold)
    assert result["value"] == 33.3
    assert result["fps_2160p"] == 2.8
    assert set(STAGE_NAMES) <= set(result["stage_ms"])
    # dense_retry is a first-class stage (not folded into fetch)
    assert "dense_retry" in result["stage_ms"]
    # the device→host boundary is a pinned, regression-checked metric:
    # e2e ÷ device fps per resolution + measured D2H bytes per frame
    assert result["host_gap_1080p"] == round(33.3 / 50.0, 3)
    assert result["host_gap_2160p"] == round(2.8 / 7.0, 3)
    assert result["d2h_bytes_per_frame"] == 100    # 6400 B / 64 frames
    # streaming-ingest stages are first-class schema keys
    assert "decode" in result["stage_ms"] and "stage" in result["stage_ms"]
    # cold end-to-end figure (decode -> encode -> concat, nothing
    # pre-staged) + its own breakdown
    assert result["fps_cold_1080p"] == 31.1
    assert "decode" in result["stage_ms_cold"]
    assert "stage" in result["stage_ms_cold"]
    # 4K quality rides with suffixed keys (VERDICT Weak #9)
    assert result["psnr_y_2160p"] == 41.0
    assert result["ssim_y_2160p"] == 0.98
    assert result["psnr_y"] == 40.1
