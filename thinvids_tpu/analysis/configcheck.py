"""Pass 4 — config discipline.

Dead config lies to operators (VERDICT Weak #3 took three rounds to
purge): this pass promotes the PR 6 dead-key test into the analyzer
and extends it to the whole env/settings surface.

TVT-C001  a DEFAULT_SETTINGS key with no reader outside core/config.py
          (attribute access, string reference, or TVT_ env mention —
          dashboards' .html files count as readers).
TVT-C002  an env knob that either doesn't live in the TVT_* namespace
          (foreign platform prefixes exempt) or is a TVT_* name that
          is neither a registered settings key (TVT_<KEY>) nor one of
          the manifest's declared process-level envs.
TVT-C003  raw subscript access on DEFAULT_SETTINGS or a Settings
          ``.values`` mapping outside core/config.py — every read goes
          through the snapshot attribute / .get path so the canonical
          coerce/clamp tier can't be bypassed.
"""

from __future__ import annotations

import ast
import os

from .astutil import (Finding, SourceTree, attribute_names, finding,
                      string_constants)
from .manifest import Manifest


def _default_settings() -> dict:
    from ..core.config import DEFAULT_SETTINGS

    return dict(DEFAULT_SETTINGS)


def _html_text(tree: SourceTree) -> str:
    chunks = []
    for dirpath, dirs, files in os.walk(tree.package_dir):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for name in files:
            if name.endswith(".html"):
                with open(os.path.join(dirpath, name),
                          encoding="utf-8") as fh:
                    chunks.append(fh.read())
    return "\n".join(chunks)


def check_dead_keys(tree: SourceTree, manifest: Manifest,
                    defaults: dict | None = None) -> list[Finding]:
    defaults = _default_settings() if defaults is None else defaults
    attrs: set[str] = set()
    consts: set[str] = set()
    for mod in tree.all_names():
        if mod == manifest.config_module:
            continue
        attrs |= attribute_names(tree.tree(mod))
        consts |= string_constants(tree.tree(mod))
    html = _html_text(tree)
    findings = []
    for key in sorted(defaults):
        env = "TVT_" + key.upper()
        if key in attrs or key in consts or env in consts:
            continue
        # substring matches keep the original grep-guard semantics:
        # `max_active_jobs` is read through the canonical
        # `effective_max_active_jobs()` helper, and f-strings mention
        # keys in fragments
        if any(key in a for a in attrs) or any(key in c for c in consts):
            continue
        if key in html or env in html:
            continue
        findings.append(finding(
            "TVT-C001", manifest.config_module, 0,
            f"settings key `{key}` has no reader outside "
            f"core/config.py — delete it or wire it up",
            key_detail=key))
    return findings


def _env_literals(tree: ast.Module):
    """(name, line) for every literal env read/write: os.environ.get,
    os.environ[...], os.getenv, os.environ.setdefault/pop."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = None
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    f.attr in ("get", "getenv", "setdefault", "pop"):
                root = f.value
                is_env = (isinstance(root, ast.Attribute)
                          and root.attr == "environ") or \
                    (isinstance(root, ast.Name) and root.id == "os"
                     and f.attr == "getenv")
                if is_env and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, str):
                    name = node.args[0].value
            if name:
                yield name, node.lineno
        elif isinstance(node, ast.Subscript):
            v = node.value
            if isinstance(v, ast.Attribute) and v.attr == "environ" and \
                    isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, str):
                yield node.slice.value, node.lineno


def check_env_knobs(tree: SourceTree, manifest: Manifest,
                    defaults: dict | None = None) -> list[Finding]:
    defaults = _default_settings() if defaults is None else defaults
    registered = {"TVT_" + k.upper() for k in defaults}
    registered |= set(manifest.process_env)
    findings = []
    for mod in tree.modules():
        if mod == manifest.config_module:
            continue        # constructs TVT_<key> names dynamically
        for name, line in _env_literals(tree.tree(mod)):
            if name.startswith("TVT_"):
                if name not in registered:
                    findings.append(finding(
                        "TVT-C002", mod, line,
                        f"unregistered env knob `{name}` — add the "
                        f"settings key or declare it in the "
                        f"manifest's process_env",
                        key_detail=name))
            elif not name.startswith(
                    tuple(manifest.foreign_env_prefixes)):
                findings.append(finding(
                    "TVT-C002", mod, line,
                    f"env knob `{name}` outside the TVT_* namespace",
                    key_detail=name))
    uniq: dict[str, Finding] = {}
    for f in findings:
        uniq.setdefault(f.key, f)
    return list(uniq.values())


def check_raw_access(tree: SourceTree, manifest: Manifest
                     ) -> list[Finding]:
    findings = []
    for mod in tree.modules():
        if mod == manifest.config_module:
            continue
        for node in ast.walk(tree.tree(mod)):
            if not isinstance(node, ast.Subscript):
                continue
            v = node.value
            if isinstance(v, ast.Name) and v.id == "DEFAULT_SETTINGS":
                findings.append(finding(
                    "TVT-C003", mod, node.lineno,
                    "raw DEFAULT_SETTINGS[...] access bypasses the "
                    "coerce/clamp tier — read a settings snapshot",
                    key_detail=f"{mod}:DEFAULT_SETTINGS"))
            elif isinstance(v, ast.Attribute) and v.attr == "values":
                base = v.value
                if isinstance(base, ast.Name) and (
                        "settings" in base.id or "snap" in base.id
                        or base.id in ("s", "cfg")):
                    findings.append(finding(
                        "TVT-C003", mod, node.lineno,
                        f"raw `{base.id}.values[...]` access bypasses "
                        f"the canonical attribute/.get read path",
                        key_detail=f"{mod}:{base.id}.values"))
    return findings


def run(tree: SourceTree, manifest: Manifest,
        defaults: dict | None = None) -> list[Finding]:
    return check_dead_keys(tree, manifest, defaults) \
        + check_env_knobs(tree, manifest, defaults) \
        + check_raw_access(tree, manifest)
