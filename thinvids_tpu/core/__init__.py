from .status import Status
from .types import (
    ChromaFormat,
    FrameType,
    VideoMeta,
    Frame,
    GopSpec,
    SegmentPlan,
    EncodedSegment,
)
from .config import Settings, get_settings, DEFAULT_SETTINGS
from .events import ActivityLog
from .log import get_logging

__all__ = [
    "Status",
    "ChromaFormat",
    "FrameType",
    "VideoMeta",
    "Frame",
    "GopSpec",
    "SegmentPlan",
    "EncodedSegment",
    "Settings",
    "get_settings",
    "DEFAULT_SETTINGS",
    "ActivityLog",
    "get_logging",
]
