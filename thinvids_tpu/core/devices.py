"""Virtual CPU device-mesh bootstrap.

This image's sitecustomize boots an `axon` (tunneled, single-chip TPU)
PJRT plugin and force-selects `jax_platforms=axon,cpu`; env vars alone
cannot override that, so multi-device paths (tests, the driver's
`dryrun_multichip`) must update the jax config directly BEFORE the first
backend initialization. One shared implementation so the recipe cannot
drift between callers.
"""

from __future__ import annotations

import os
import re

_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_devices(n: int) -> None:
    """Arrange for jax to expose >= `n` virtual CPU devices.

    Must run before any jax backend is initialized; raises RuntimeError
    (instead of failing later with a misleading device-count error) when
    backends already exist with fewer devices.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"{_FLAG}=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = f"{flags} {_FLAG}={n}".strip()
    elif int(m.group(1)) < n:
        os.environ["XLA_FLAGS"] = flags.replace(m.group(0), f"{_FLAG}={n}")

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError as exc:  # backends already initialized
        if len(jax.devices()) < n:
            raise RuntimeError(
                f"jax backends already initialized with "
                f"{len(jax.devices())} device(s); force_cpu_devices({n}) "
                f"must be called before the first jax backend use"
            ) from exc


def shard_map(f, *, mesh, in_specs, out_specs):
    """Version-portable `shard_map`: top-level `jax.shard_map` where it
    exists (>= 0.4.38), else the `jax.experimental` spelling this image's
    jax (0.4.37) still uses. One resolver so every SPMD call site keeps
    working across the rename."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
