"""Job QoS: priority classes and deadline-driven batch preemption.

The reference's capacity gate (SURVEY §2.3) admits every job as an
equal; a live origin cannot — a live stream that misses its part
deadline has VIEWERS buffering, while a batch backfill only gets done
later. Two mechanisms, both owned by the coordinator:

- **Priority classes** (live > ladder > batch): the dispatch pass picks
  the highest class first, live-class jobs bypass the politeness gates
  (shareability / idle headroom) that exist to protect batch throughput,
  and the remote ShardBoard hands out claims best-class-first.
- **Deadline preemption**: the live executor reports each part's
  encode+package latency against its budget (`live_part_budget_s`;
  0 = 2x the stream's segment duration). On a breach the controller
  closes the batch gate — ShardBoard requeues ASSIGNED batch shards
  (the PR 1 lease/requeue machinery makes that safe: a preempted
  worker's late part is still accepted, and the encode is
  deterministic so any completed attempt is THE answer) and local
  batch wave loops pause between waves — until `live_recover_parts`
  consecutive parts land back inside budget.
"""

from __future__ import annotations

import threading
from typing import Callable

from ..obs import metrics as obs_metrics

#: priority classes, best first; `auto` derives from the job type
PRIORITY_CLASSES = ("live", "ladder", "batch")
_RANK = {"live": 0, "ladder": 1, "batch": 2}
_TYPE_CLASS = {"live": "live", "ladder": "ladder", "transcode": "batch"}

#: the rank at or below which a job is preemptible batch work
BATCH_RANK = _RANK["batch"]


def job_class(job_type: str, override: str = "auto") -> str:
    """Resolve a job's priority class: the `job_priority` setting when
    it names a class explicitly, else the job type's natural class."""
    if override in _RANK:
        return override
    return _TYPE_CLASS.get(job_type, "batch")


def class_rank(cls: str) -> int:
    """Numeric rank (lower = more urgent); unknown classes are batch."""
    return _RANK.get(cls, BATCH_RANK)


def job_rank(job_type: str, override: str = "auto") -> int:
    return class_rank(job_class(job_type, override))


class QosController:
    """Tracks live-job deadline health and gates batch work.

    `note_live_part` is the executor's per-part report; a breach
    closes the batch gate and fires the registered preempt callbacks
    (the ShardBoard's requeue) ONCE per breach episode. Recovery —
    `recover_parts` consecutive within-budget parts, or the live job
    reaching a terminal state (`clear_live`) — reopens the gate.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._batch_ok = threading.Event()
        self._batch_ok.set()
        self._breached: set[str] = set()
        self._good_parts: dict[str, int] = {}
        self._preempt_cbs: list[Callable[[], int]] = []
        # counters for /metrics_snapshot + tests
        self._breaches = 0
        self._recoveries = 0
        self._preempted_shards = 0

    def on_preempt(self, cb: Callable[[], int]) -> None:
        """Register a preemption hook (returns how many work units it
        requeued). Fired outside the controller's lock."""
        with self._lock:
            self._preempt_cbs.append(cb)

    def note_live_part(self, job_id: str, latency_s: float,
                       budget_s: float, recover_parts: int = 2
                       ) -> str | None:
        """One live part's latency vs its budget. Returns "breach" on
        a new breach episode, "recovered" when the gate reopens, else
        None. budget_s <= 0 disables deadline tracking for the part."""
        if budget_s <= 0:
            return None
        fire = False
        event: str | None = None
        with self._lock:
            if latency_s > budget_s:
                self._good_parts[job_id] = 0
                if job_id not in self._breached:
                    self._breached.add(job_id)
                    self._breaches += 1
                    fire = True
                    event = "breach"
                self._batch_ok.clear()
            elif job_id in self._breached:
                n = self._good_parts.get(job_id, 0) + 1
                self._good_parts[job_id] = n
                if n >= max(1, int(recover_parts)):
                    self._breached.discard(job_id)
                    self._good_parts.pop(job_id, None)
                    self._recoveries += 1
                    event = "recovered"
                    if not self._breached:
                        self._batch_ok.set()
            # gauge published UNDER the lock (the metric child's own
            # leaf lock nests safely): racing events must not publish
            # a stale value last
            if event == "breach":
                obs_metrics.QOS_BREACHES.inc()
            elif event == "recovered":
                obs_metrics.QOS_RECOVERIES.inc()
            if event is not None:
                obs_metrics.QOS_PREEMPTING.set(
                    1 if self._breached else 0)
            cbs = list(self._preempt_cbs) if fire else []
        for cb in cbs:
            try:
                n = int(cb() or 0)
            except Exception:   # noqa: BLE001 - a broken hook must not
                continue        # take down the live encode loop
            if n:
                with self._lock:
                    self._preempted_shards += n
                obs_metrics.QOS_PREEMPTED_SHARDS.inc(n)
        return event

    def clear_live(self, job_id: str) -> None:
        """A live job reached a terminal state: drop its breach (a
        dead stream must not pin the batch gate shut forever)."""
        with self._lock:
            self._breached.discard(job_id)
            self._good_parts.pop(job_id, None)
            if not self._breached:
                self._batch_ok.set()
            # published under the lock — same rationale as
            # note_live_part's gauge write
            obs_metrics.QOS_PREEMPTING.set(1 if self._breached else 0)

    def batch_allowed(self) -> bool:
        return self._batch_ok.is_set()

    def wait_batch_allowed(self, timeout: float | None = None) -> bool:
        return self._batch_ok.wait(timeout)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "preempting": bool(self._breached),
                "breached_jobs": sorted(self._breached),
                "breaches": self._breaches,
                "recoveries": self._recoveries,
                "preempted_shards": self._preempted_shards,
            }
