"""Transfer-layout contract of the sharded GOP encode — host side.

jaxinter.encode_gop_planes emits ONE flat int16 vector per GOP (intra
blocked levels followed by P coefficient planes); this module owns the
per-MB sizes of that layout, the zero-copy host inverses (flat transfer
segments → per-slice views), and the COMPACT payload format the device
compaction stage (jaxcore._compact_stream) ships over the device→host
link.

Deliberately jax-free: the process-based pack sidecars
(parallel/packproc.py) import it in child processes that must never
initialize a backend, and the numpy implementations double as the
no-compiler parity references for the native entries.

Compact payload format (all offsets in bytes, NB = ceil(L / 16) sparse
blocks, nb8 = ceil(NB / 8)):

    [ bitmap      nb8 bytes   1 bit per 16-coeff block (big-endian
                              within bytes, np.unpackbits order)
    | bmask16     2 * nblk    per live block, a little-endian uint16
                              lane-occupancy mask (bit k = coeff k != 0)
    | vals        nval        the nonzero coeffs in (block, lane)
                              order, int8 ]

`used = nb8 + 2 * nblk + nval` bytes carry the whole stream; everything
after is transfer padding (the device buffer is budget-sized, the host
fetches a quantized slice). nblk/nval ride as separate tiny count
arrays, fetched with the device-wait barrier.
"""

from __future__ import annotations

import numpy as np

# Per-MB flat sizes. Intra: luma DC 16 + luma AC 240 + chroma DC 8 +
# chroma AC 120. P plane layout: luma coeff plane 256 + u/v hadamard DC
# 4+4 + u/v AC planes 64+64 (MVs ride separately as int8).
_P_FLAT_MB = 256 + 4 + 4 + 64 + 64        # = 392
_INTRA_FLAT_MB = 384

#: 16-coeff granularity of the block-sparse transfer tiers
SPARSE_BLOCK = 16


def rest_len(num_frames: int, mbw: int, mbh: int) -> int:
    """Coefficient count of the SPARSE remainder of one GOP's flat
    vector: the full layout minus the dense-shipped hadamard DC prefix
    (luma DC nmb*16 + chroma DC nmb*8 — see dispatch._per_gop_sparse)."""
    nmb = mbw * mbh
    return (nmb * (_INTRA_FLAT_MB - 24)
            + (num_frames - 1) * nmb * _P_FLAT_MB)


# ---- compact payload parsing ----------------------------------------------

def split_compact(payload: np.ndarray, nblk: int, nval: int, L: int):
    """Parse one compact payload (>= `used` uint8 bytes) into its
    (bitmap, bmask16, vals) streams. Views where alignment allows; the
    bmask16 lane masks are re-assembled from byte pairs (the payload
    gives them no alignment guarantee — nb8 may be odd)."""
    NB = -(-L // SPARSE_BLOCK)
    nb8 = (NB + 7) // 8
    if L <= 0 or nblk < 0 or nval < 0:
        # fuzz-found (tools/fuzz_native.py): negative slice counts
        # must reject like the native parser, not quietly shrink the
        # streams into a zero decode
        raise ValueError("compact stream counts out of range")
    need = nb8 + 2 * int(nblk) + int(nval)
    payload = np.asarray(payload, np.uint8).reshape(-1)
    if payload.shape[0] < need:
        raise ValueError(
            f"compact payload truncated: {payload.shape[0]} bytes < "
            f"{need} needed for nblk={nblk} nval={nval}")
    bitmap = payload[:nb8]
    mb = payload[nb8:nb8 + 2 * int(nblk)].astype(np.uint16)
    bmask16 = (mb[0::2] | (mb[1::2] << 8)).astype(np.uint16)
    vals = payload[nb8 + 2 * int(nblk):need].view(np.int8)
    return bitmap, bmask16, vals


def block_sparse_unpack2_host(nblk: int, nval: int, bitmap: np.ndarray,
                              bmask16: np.ndarray, vals: np.ndarray,
                              L: int) -> np.ndarray:
    """Numpy inverse of jaxcore._block_sparse_pack2 → flat int16 levels
    (the native scatter's parity reference; jaxcore re-exports it).
    Rejects count/stream disagreement like the native core: corrupt
    counts must fail loudly, not decode as silent zeros."""
    NB = -(-L // SPARSE_BLOCK)
    if L <= 0 or nblk < 0 or nval < 0:
        raise ValueError("sparse stream counts out of range")
    if nblk > np.asarray(bmask16).reshape(-1).shape[0] \
            or nval > np.asarray(vals).reshape(-1).shape[0]:
        raise ValueError("sparse stream counts exceed buffer sizes")
    nb8 = (NB + 7) // 8
    bitmap = np.asarray(bitmap, np.uint8).reshape(-1)
    if bitmap.shape[0] < nb8:
        # fuzz-found: a truncated bitmap must reject like the native
        # wrapper's size validation, not decode short
        raise ValueError("sparse bitmap truncated")
    bits = np.unpackbits(bitmap[:nb8])
    if bits[NB:].any():
        # pack never sets the byte-padding bits past NB; a set one is
        # a corrupt bitmap (the native core's tail scan rejects it too
        # — fuzz-found asymmetry, tools/fuzz_native.py)
        raise ValueError("sparse bitmap padding bits set")
    bm = bits[:NB].astype(bool)
    masks = np.asarray(bmask16)[:nblk].astype(np.uint32)
    lane_bits = ((masks[:, None] >> np.arange(SPARSE_BLOCK, dtype=np.uint32))
                 & 1).astype(bool)                      # (nblk, 16)
    # Explicit count agreement, like the native core's bi/vi checks:
    # numpy's size-1 broadcasting otherwise lets a corrupt nval=1
    # stream silently replicate one value across every live lane
    # (fuzz-found, tools/fuzz_native.py)
    if int(bm.sum()) != int(nblk):
        raise ValueError("sparse bitmap disagrees with nblk")
    if int(lane_bits.sum()) != int(nval):
        raise ValueError("sparse lane masks disagree with nval")
    stream = np.asarray(vals)[:nval].astype(np.int16)
    rows = np.zeros((nblk, SPARSE_BLOCK), np.int16)
    rows[lane_bits] = stream        # row-major = (block, lane) order
    out = np.zeros((NB, SPARSE_BLOCK), np.int16)
    out[bm] = rows
    return out.reshape(-1)[:L]


def unpack_compact_host(payload: np.ndarray, nblk: int, nval: int,
                        L: int) -> np.ndarray:
    """Compact payload → flat int16 levels (numpy fallback for the
    native cavlc_unpack_compact; identical output — tested)."""
    bitmap, bmask16, vals = split_compact(payload, nblk, nval, L)
    return block_sparse_unpack2_host(int(nblk), int(nval), bitmap,
                                     bmask16, vals, L)


def unpack_compact_auto(payload: np.ndarray, nblk: int, nval: int,
                        L: int) -> np.ndarray:
    """Two-tier compact unpack: the native single-pass parse+scatter
    when a compiler exists, :func:`unpack_compact_host` otherwise
    (identical output — tested). The ONE dispatcher shared by the
    in-process collect path (parallel/dispatch) and the pack sidecars
    (parallel/packproc)."""
    from ... import native as native_mod

    if native_mod.available():
        return native_mod.unpack_compact(nblk, nval, payload, L)
    return unpack_compact_host(payload, nblk, nval, L)


# ---- zero-copy unflatten (flat transfer segments → slice views) ------------

def unflatten_intra(seg: np.ndarray, nmb: int):
    """Flat intra segment (nmb * 384, layout il_dc|il_ac|ic_dc|ic_ac) →
    blocked VIEWS. The int16 views feed cavlc_pack_islice16 directly —
    an astype(int32) chain here would allocate ~4 copies of the intra
    levels per GOP on the critical path."""
    o = nmb * 16
    il_dc = seg[:o].reshape(nmb, 16)
    il_ac = seg[o:o + nmb * 240].reshape(nmb, 16, 15)
    o += nmb * 240
    ic_dc = seg[o:o + nmb * 8].reshape(nmb, 2, 4)
    o += nmb * 8
    ic_ac = seg[o:o + nmb * 120].reshape(nmb, 2, 4, 15)
    return il_dc, il_ac, ic_dc, ic_ac


def unflatten_p_planes(seg: np.ndarray, mv8: np.ndarray, num_frames: int,
                       mbw: int, mbh: int):
    """Flat P segment → plane VIEWS (the plane->blocked scan happens
    inside the native packer, cavlc_pack_pslice_plane, so no relayout
    pass runs on the host)."""
    nmb = mbw * mbh
    H, W = mbh * 16, mbw * 16
    hw2 = (H // 2) * (W // 2)
    F1 = num_frames - 1
    o = 0
    lp = seg[o:o + F1 * H * W].reshape(F1, H, W)
    o += F1 * H * W
    udc = seg[o:o + F1 * nmb * 4].reshape(F1, nmb, 4)
    o += F1 * nmb * 4
    vdc = seg[o:o + F1 * nmb * 4].reshape(F1, nmb, 4)
    o += F1 * nmb * 4
    uac = seg[o:o + F1 * hw2].reshape(F1, H // 2, W // 2)
    o += F1 * hw2
    vac = seg[o:o + F1 * hw2].reshape(F1, H // 2, W // 2)
    return (np.asarray(mv8), lp, udc, vdc, uac, vac)


def unflatten_gop(flat: np.ndarray, mv8: np.ndarray, num_frames: int,
                  mbw: int, mbh: int, ships_modes: bool = False):
    """Host inverse of jaxinter.encode_gop_planes: split the flat int16
    vector into (intra blocked arrays, P plane views). EVERY array is a
    zero-copy view into `flat`. With `ships_modes` the vector ends in
    the per-MB intra [mode16 | dqp16] side channel, appended to the
    returned intra tuple."""
    nmb = mbw * mbh
    flat = np.asarray(flat)
    o = nmb * _INTRA_FLAT_MB
    intra = unflatten_intra(flat[:o], nmb)
    p_end = flat.shape[0] - (2 * nmb if ships_modes else 0)
    planes = unflatten_p_planes(flat[o:p_end], mv8, num_frames, mbw, mbh)
    if ships_modes:
        intra = intra + (flat[p_end:p_end + nmb], flat[p_end + nmb:])
    return intra, planes


def unflatten_gop_parts(dense: np.ndarray, rest: np.ndarray,
                        mv8: np.ndarray, num_frames: int,
                        mbw: int, mbh: int, ships_modes: bool = False):
    """Sparse-path unflatten straight from the two transfer segments —
    dense = [il_dc | ic_dc] (the hadamard DC prefix, _per_gop_sparse;
    with `ships_modes` also the [mode16 | dqp16] tail, appended to the
    returned intra tuple), rest = [il_ac | ic_ac | P planes] — without
    first concatenating them back into the full flat layout (which
    copied ~25 MB per 1080p GOP). Views only."""
    nmb = mbw * mbh
    ndc, nlac = nmb * 16, nmb * 240
    dense = np.asarray(dense)
    rest = np.asarray(rest)
    il_dc = dense[:ndc].reshape(nmb, 16)
    ic_dc = dense[ndc:ndc + nmb * 8].reshape(nmb, 2, 4)
    il_ac = rest[:nlac].reshape(nmb, 16, 15)
    o = nlac + nmb * 120
    ic_ac = rest[nlac:o].reshape(nmb, 2, 4, 15)
    planes = unflatten_p_planes(rest[o:], mv8, num_frames, mbw, mbh)
    intra = (il_dc, il_ac, ic_dc, ic_ac)
    if ships_modes:
        t = ndc + nmb * 8
        intra = intra + (dense[t:t + nmb], dense[t + nmb:t + 2 * nmb])
    return intra, planes
