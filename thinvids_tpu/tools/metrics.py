"""Objective quality metrics: PSNR and SSIM (numpy, host-side).

The reference had no quality instrumentation at all — output quality
was judged by eye off the preview player (SURVEY.md §4); the driver
metric ("VMAF parity", BASELINE.md) demands numbers. VMAF itself needs
its trained model files (not in this image), so the harness reports
PSNR + SSIM — the standard proxies VMAF correlates with — computed
against the source on every bench run so quality regressions are
visible next to fps.
"""

from __future__ import annotations

import numpy as np


def psnr(ref: np.ndarray, dist: np.ndarray, peak: float = 255.0) -> float:
    """Peak signal-to-noise ratio in dB (inf for identical planes)."""
    ref = ref.astype(np.float64)
    dist = dist.astype(np.float64)
    mse = np.mean((ref - dist) ** 2)
    if mse == 0:
        return float("inf")
    return float(10.0 * np.log10(peak * peak / mse))


def _uniform_filter(x: np.ndarray, size: int) -> np.ndarray:
    """Separable box filter via cumulative sums ('same' shape for any
    window size, edge-padded) — keeps the module dependency-free on a
    1-core host."""
    pad_l = size // 2
    pad_r = size - 1 - pad_l
    out = x
    for axis in (0, 1):
        xs = np.swapaxes(out, 0, axis)
        padded = np.pad(xs, ((pad_l, pad_r), (0, 0)), mode="edge")
        c = np.cumsum(padded, axis=0, dtype=np.float64)
        c = np.vstack([np.zeros((1, c.shape[1])), c])
        xs = (c[size:] - c[:-size]) / size
        out = np.swapaxes(xs, 0, axis)
    return out


def ssim(ref: np.ndarray, dist: np.ndarray, peak: float = 255.0,
         window: int = 8) -> float:
    """Mean structural similarity (Wang et al. 2004, uniform window —
    the same simplification x264's ssim tuning uses)."""
    ref = ref.astype(np.float64)
    dist = dist.astype(np.float64)
    c1 = (0.01 * peak) ** 2
    c2 = (0.03 * peak) ** 2
    mu_x = _uniform_filter(ref, window)
    mu_y = _uniform_filter(dist, window)
    sxx = _uniform_filter(ref * ref, window) - mu_x * mu_x
    syy = _uniform_filter(dist * dist, window) - mu_y * mu_y
    sxy = _uniform_filter(ref * dist, window) - mu_x * mu_y
    num = (2 * mu_x * mu_y + c1) * (2 * sxy + c2)
    den = (mu_x ** 2 + mu_y ** 2 + c1) * (sxx + syy + c2)
    return float(np.mean(num / den))


def clip_quality(ref_frames, dist_y_planes) -> dict[str, float]:
    """Mean luma PSNR/SSIM of a decoded clip vs its source frames.

    ref_frames: list of core.types.Frame; dist_y_planes: decoded luma
    planes (same count/geometry — the caller crops any codec padding).
    """
    n = min(len(ref_frames), len(dist_y_planes))
    ps, ss = [], []
    for i in range(n):
        ry = ref_frames[i].y
        dy = dist_y_planes[i][:ry.shape[0], :ry.shape[1]]
        ps.append(psnr(ry, dy))
        ss.append(ssim(ry, dy))
    finite = [p for p in ps if np.isfinite(p)]
    return {
        "psnr_y": float(np.mean(finite)) if finite else float("inf"),
        "ssim_y": float(np.mean(ss)) if ss else 1.0,
        "frames_compared": n,
    }
