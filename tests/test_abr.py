"""ABR ladder subsystem tests (thinvids_tpu/abr/).

Layers: downscaler parity against an independent pure-numpy polyphase
reference (odd/even dims, 4:2:0 chroma), ladder planning (rung dims /
QP model), the decode+H2D-once invariant (`h2d_bytes` must not scale
with rung count) and top-rung byte identity with the single-rendition
path, HLS packaging + playlist conformance lint (positive and
tampered), the executor end-to-end ladder job (watch-folder naming →
DONE → servable master.m3u8 with decodable rungs), the remote-farm
rung×shard path, and the jax-free grep guard on ladder.py/hls.py.
"""

import os
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from thinvids_tpu.abr import hls
from thinvids_tpu.abr.ladder import (LadderShardEncoder, plan_ladder,
                                     rung_segments)
from thinvids_tpu.abr.scale import (LANCZOS_A, PlaneScaler,
                                    lanczos_kernel, resample_matrix)
from thinvids_tpu.cluster import Coordinator, WorkerRegistry
from thinvids_tpu.cluster.executor import LocalExecutor
from thinvids_tpu.core.config import DEFAULT_SETTINGS, Settings
from thinvids_tpu.core.status import Status
from thinvids_tpu.core.types import (Frame, VideoMeta, concat_segments)
from thinvids_tpu.io.y4m import write_y4m
from thinvids_tpu.parallel.dispatch import GopShardEncoder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_settings(**over):
    values = dict(DEFAULT_SETTINGS)
    values.update(over)
    return Settings(values=values)


def textured_frames(w, h, n, seed=0):
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:h, 0:w]
    base = (xx * 1.7 + yy * 0.9) % 256 + 20 * np.sin(xx * 0.2)
    frames = []
    for i in range(n):
        y = np.clip(base + 5 * i + rng.normal(0, 3, (h, w)), 0,
                    255).astype(np.uint8)
        u = np.clip(120 + 30 * np.sin(yy[::2, ::2] * 0.05 + i), 0,
                    255).astype(np.uint8)
        v = np.clip(130 + 30 * np.cos(xx[::2, ::2] * 0.04 + i), 0,
                    255).astype(np.uint8)
        frames.append(Frame(y=y, u=u, v=v))
    return frames


# ---------------------------------------------------------------------------
# downscaler
# ---------------------------------------------------------------------------


def reference_polyphase(plane: np.ndarray, src_valid: int, dst_valid: int,
                        axis: int) -> np.ndarray:
    """Independent pure-numpy polyphase Lanczos-3 along one axis
    (direct per-output-tap convolution — no shared code with
    abr/scale.py's matrix builder)."""
    moved = np.moveaxis(plane.astype(np.float64), axis, 0)
    ratio = src_valid / dst_valid
    support = LANCZOS_A * ratio
    out = np.zeros((dst_valid,) + moved.shape[1:], np.float64)
    for i in range(dst_valid):
        center = (i + 0.5) * ratio - 0.5
        acc = np.zeros(moved.shape[1:], np.float64)
        wsum = 0.0
        j = int(np.floor(center - support)) + 1
        while j < center + support:
            wj = float(lanczos_kernel(
                np.array([(j - center) / ratio]))[0])
            acc += wj * moved[min(max(j, 0), src_valid - 1)]
            wsum += wj
            j += 1
        out[i] = acc / wsum
    return np.moveaxis(out, 0, axis)


class TestScale:
    def test_matrix_rows_normalized_and_edge_clamped(self):
        m = resample_matrix(64, 32, src_valid=50, dst_valid=24)
        np.testing.assert_allclose(m.sum(axis=1), 1.0, atol=1e-5)
        # taps never sample the padding beyond the valid source range
        assert np.all(m[:, 50:] == 0.0)
        # padded output rows repeat the last valid row
        np.testing.assert_array_equal(m[24], m[23])
        np.testing.assert_array_equal(m[31], m[23])

    @pytest.mark.parametrize("src,dst", [
        ((64, 48), (32, 24)),        # clean power-of-two, mb-aligned
        ((62, 50), (36, 24)),        # even, not mb-aligned
        ((61, 37), (24, 16)),        # odd luma dims (odd chroma too)
    ])
    def test_device_scale_matches_numpy_polyphase_reference(self, src,
                                                            dst):
        w, h = src
        dw, dh = dst
        rng = np.random.default_rng(7)
        frame = Frame(
            y=rng.integers(0, 256, (h, w), np.uint8),
            u=rng.integers(0, 256, ((h + 1) // 2, (w + 1) // 2),
                           np.uint8),
            v=rng.integers(0, 256, ((h + 1) // 2, (w + 1) // 2),
                           np.uint8)).padded(16)
        sc = PlaneScaler(w, h, dw, dh)
        dy, du, dv = sc.scale_wave(jnp.asarray(frame.y[None]),
                                   jnp.asarray(frame.u[None]),
                                   jnp.asarray(frame.v[None]))
        # reference works on the VALID region with its own edge clamp
        ref_y = reference_polyphase(
            reference_polyphase(frame.y, h, dh, axis=0), w, dw, axis=1)
        ref_y = np.clip(np.floor(ref_y + 0.5), 0, 255).astype(np.uint8)
        got_y = np.asarray(dy[0])[:dh, :dw]
        diff = np.abs(got_y.astype(int) - ref_y.astype(int))
        # ≤1 LSB from float summation order; overwhelmingly exact
        assert diff.max() <= 1
        assert (diff == 0).mean() > 0.95
        for plane, dev in (("u", du), ("v", dv)):
            p = getattr(frame, plane)
            ch, cw = (h + 1) // 2, (w + 1) // 2
            ref = reference_polyphase(
                reference_polyphase(p, ch, dh // 2, axis=0),
                cw, dw // 2, axis=1)
            ref = np.clip(np.floor(ref + 0.5), 0, 255).astype(np.uint8)
            got = np.asarray(dev[0])[:dh // 2, :dw // 2]
            assert np.abs(got.astype(int) - ref.astype(int)).max() <= 1

    def test_psnr_floor_on_real_decoded_frame(self, tmp_path):
        """Scale a frame decoded from a REAL encoded stream and pin a
        PSNR floor against an independent resampler (cv2 INTER_AREA):
        the device scaler must produce the picture, not just match its
        own reference."""
        import cv2

        from thinvids_tpu.io.mp4 import write_mp4

        w, h, n = 128, 96, 4
        frames = textured_frames(w, h, n)
        meta = VideoMeta(width=w, height=h, fps_num=30, fps_den=1,
                         num_frames=n)
        enc = GopShardEncoder(meta, qp=24, gop_frames=n)
        stream = concat_segments(enc.encode(frames))
        path = str(tmp_path / "clip.mp4")
        write_mp4(path, stream, meta)
        cap = cv2.VideoCapture(path)
        ok, img = cap.read()
        cap.release()
        assert ok
        decoded_y = cv2.cvtColor(img, cv2.COLOR_BGR2YUV)[:, :, 0]

        dw, dh = 64, 48
        frame = Frame(y=decoded_y,
                      u=np.full((h // 2, w // 2), 128, np.uint8),
                      v=np.full((h // 2, w // 2), 128, np.uint8)
                      ).padded(16)
        sc = PlaneScaler(w, h, dw, dh)
        dy, _du, _dv = sc.scale_wave(jnp.asarray(frame.y[None]),
                                     jnp.asarray(frame.u[None]),
                                     jnp.asarray(frame.v[None]))
        got = np.asarray(dy[0])[:dh, :dw].astype(np.float64)
        want = cv2.resize(decoded_y, (dw, dh),
                          interpolation=cv2.INTER_AREA).astype(np.float64)
        mse = np.mean((got - want) ** 2)
        psnr = 10 * np.log10(255.0 ** 2 / max(mse, 1e-9))
        assert psnr >= 30.0, f"downscale PSNR {psnr:.1f} dB below floor"


# ---------------------------------------------------------------------------
# planner
# ---------------------------------------------------------------------------


class TestLadderPlan:
    def test_default_ladder_from_1080p(self):
        meta = VideoMeta(width=1920, height=1080)
        rungs = plan_ladder(meta, make_settings(qp=27))
        assert [(r.width, r.height) for r in rungs] == [
            (1920, 1080), (1280, 720), (854, 480), (640, 360)]
        assert rungs[0].top and not any(r.top for r in rungs[1:])
        # top rung keeps the base QP exactly (byte-identity anchor);
        # lower rungs encode finer under the octave ladder model
        assert rungs[0].qp == 27
        qps = [r.qp for r in rungs]
        assert qps == sorted(qps, reverse=True)
        assert all(r.qp <= 27 for r in rungs)
        assert all(r.width % 2 == 0 and r.height % 2 == 0 for r in rungs)

    def test_rungs_at_or_above_source_collapse(self):
        meta = VideoMeta(width=1280, height=720)
        rungs = plan_ladder(meta, make_settings(qp=30))
        assert [(r.width, r.height) for r in rungs] == [
            (1280, 720), (854, 480), (640, 360)]

    def test_junk_and_custom_spec(self):
        meta = VideoMeta(width=640, height=480)
        rungs = plan_ladder(
            meta, make_settings(qp=30, ladder_rungs="360p, nope, 240,"))
        assert [(r.height) for r in rungs] == [480, 360, 240]

    def test_filename_convention_is_stem_suffix_only(self):
        """`name.ladder.ext` opts in; derived names (stamped copies)
        must NOT inherit the ladder type."""
        snap = make_settings(auto_start_jobs=False)
        coord = Coordinator(registry=WorkerRegistry(),
                            settings_fn=lambda: snap)
        meta = VideoMeta(width=64, height=48, num_frames=4)
        assert coord.add_job("/w/a.ladder.y4m", meta).job_type \
            == "ladder"
        assert coord.add_job("/w/a.ladder.stamped.y4m", meta).job_type \
            == "transcode"
        assert coord.add_job("/w/plain.y4m", meta).job_type \
            == "transcode"
        assert coord.add_job("/w/plain2.y4m", meta,
                             job_type="ladder").job_type == "ladder"

    def test_live_setting_clamp_uses_canonical_parser(self):
        from thinvids_tpu.core.config import _validate_setting

        assert _validate_setting("ladder_rungs",
                                 "360p; junk, 720 ,720") == "720,360"
        assert _validate_setting("ladder_rungs", "nope") \
            == "1080,720,480,360"


# ---------------------------------------------------------------------------
# ladder encode: identity + upload invariant
# ---------------------------------------------------------------------------


class TestLadderEncode:
    W, H, N, GOP = 64, 48, 16, 4

    def _meta(self):
        return VideoMeta(width=self.W, height=self.H, fps_num=30,
                         fps_den=1, num_frames=self.N)

    def test_top_rung_byte_identical_and_h2d_once(self):
        frames = textured_frames(self.W, self.H, self.N)
        meta = self._meta()
        snap = make_settings(qp=30, ladder_rungs="32,24")
        rungs = plan_ladder(meta, snap)
        assert len(rungs) == 3

        ladder = LadderShardEncoder(meta, rungs, gop_frames=self.GOP)
        bundles = ladder.encode(frames)
        single = GopShardEncoder(meta, qp=30, gop_frames=self.GOP)
        ref = concat_segments(single.encode(frames))

        top = concat_segments(rung_segments(bundles, rungs[0].name))
        assert top == ref                      # byte-identical top rung

        snap_ladder = ladder.stages.snapshot()
        h2d_single = single.stages.snapshot()["h2d_bytes"]
        assert h2d_single > 0
        # decode + H2D once per wave: a 3-rung ladder uploads EXACTLY
        # what the single-rendition encode uploads
        assert snap_ladder["h2d_bytes"] == h2d_single
        # the aggregated profile carries the scaled rungs' host work
        # (pack/dispatch), not just the stager's, plus the scale stage
        assert snap_ladder["pack"] > 0 and snap_ladder["scale"] > 0

        # every rung shares the GOP plan (count + frame ranges)
        for rung in rungs[1:]:
            segs = rung_segments(bundles, rung.name)
            assert [(s.gop.index, s.gop.start_frame, s.gop.num_frames)
                    for s in segs] == \
                   [(s.gop.index, s.gop.start_frame, s.gop.num_frames)
                    for s in rung_segments(bundles, rungs[0].name)]

    def test_h2d_does_not_scale_with_rung_count(self):
        frames = textured_frames(self.W, self.H, 8)
        meta = VideoMeta(width=self.W, height=self.H, fps_num=30,
                         fps_den=1, num_frames=8)
        totals = []
        for spec in ("32", "32,24"):
            rungs = plan_ladder(meta, make_settings(qp=30,
                                                    ladder_rungs=spec))
            enc = LadderShardEncoder(meta, rungs, gop_frames=4)
            enc.encode(frames)
            totals.append(enc.stages.snapshot()["h2d_bytes"])
        assert totals[0] == totals[1] > 0

    def test_rung_streams_decode_at_rung_dims(self):
        """Every rung's bitstream decodes cleanly at its own dims
        (cv2/ffmpeg as the independent decoder)."""
        import cv2

        from thinvids_tpu.io.mp4 import write_mp4

        frames = textured_frames(self.W, self.H, 8)
        meta = VideoMeta(width=self.W, height=self.H, fps_num=30,
                         fps_den=1, num_frames=8)
        rungs = plan_ladder(meta, make_settings(qp=30,
                                                ladder_rungs="32,24"))
        bundles = LadderShardEncoder(meta, rungs,
                                     gop_frames=4).encode(frames)
        import tempfile

        for rung in rungs:
            stream = concat_segments(rung_segments(bundles, rung.name))
            rmeta = VideoMeta(width=rung.width, height=rung.height,
                              fps_num=30, fps_den=1, num_frames=8)
            with tempfile.NamedTemporaryFile(suffix=".mp4") as fp:
                write_mp4(fp.name, stream, rmeta)
                cap = cv2.VideoCapture(fp.name)
                count = 0
                while True:
                    ok, img = cap.read()
                    if not ok:
                        break
                    assert img.shape[:2] == (rung.height, rung.width)
                    count += 1
                cap.release()
            assert count == 8, f"rung {rung.name} decoded {count}/8"


# ---------------------------------------------------------------------------
# HLS packaging + conformance lint
# ---------------------------------------------------------------------------


def packaged_ladder(tmp_path, segment_s=0.25, n=16):
    w, h = 64, 48
    frames = textured_frames(w, h, n)
    meta = VideoMeta(width=w, height=h, fps_num=30, fps_den=1,
                     num_frames=n)
    rungs = plan_ladder(meta, make_settings(qp=30, ladder_rungs="32,24"))
    bundles = LadderShardEncoder(meta, rungs, gop_frames=4).encode(frames)
    out = str(tmp_path / "out.hls")
    streams = [hls.RungStream(r.name, r.width, r.height,
                              rung_segments(bundles, r.name))
               for r in rungs]
    master = hls.package_ladder(out, streams, 30, 1,
                                segment_s=segment_s)
    return out, master, rungs, n


class TestHlsPackaging:
    def test_lint_passes_and_boundaries_align(self, tmp_path):
        out, master, rungs, n = packaged_ladder(tmp_path)
        info = hls.lint_ladder(out, expected_duration_s=n / 30)
        assert info["rungs"] == len(rungs) == 3
        assert info["segments"] > 1            # actually segmented
        # EXTINF sums match the stream duration exactly (lint arg) and
        # BANDWIDTH is monotonic (lint raises otherwise)
        assert info["bandwidths"] == sorted(info["bandwidths"])

    def test_master_attributes(self, tmp_path):
        out, master, rungs, _n = packaged_ladder(tmp_path)
        text = open(master).read()
        for rung in rungs:
            assert f"RESOLUTION={rung.width}x{rung.height}" in text
            assert f"{rung.name}/media.m3u8" in text
        assert 'CODECS="avc1.42C0' in text
        assert "FRAME-RATE=30.000" in text

    def test_segments_open_on_idr_and_samples_read_back(self, tmp_path):
        out, _master, rungs, n = packaged_ladder(tmp_path)
        for rung in rungs:
            rung_dir = os.path.join(out, rung.name)
            init = open(os.path.join(rung_dir, hls.INIT_NAME),
                        "rb").read()
            entry = hls.init_video_entry(init)
            assert entry[4:8] == b"avc1"
            total = 0
            for name in sorted(os.listdir(rung_dir)):
                if not name.endswith(".m4s"):
                    continue
                seg = open(os.path.join(rung_dir, name), "rb").read()
                samples = hls.segment_track_samples(seg, track_id=1)
                assert samples, f"{rung.name}/{name} has no samples"
                # first sample of every segment is an IDR NAL
                nal_type = samples[0][4] & 0x1F
                assert nal_type == 5, f"segment opens on NAL {nal_type}"
                total += len(samples)
            assert total == n

    def test_lint_rejects_extinf_over_target_duration(self, tmp_path):
        out, _master, rungs, _n = packaged_ladder(tmp_path)
        mp = os.path.join(out, rungs[0].name, hls.MEDIA_PLAYLIST)
        text = open(mp).read().replace("#EXTINF:0.26667,",
                                       "#EXTINF:5.00000,", 1)
        open(mp, "w").write(text)
        with pytest.raises(ValueError, match="TARGETDURATION"):
            hls.lint_ladder(out)

    def test_lint_rejects_non_monotonic_bandwidth(self, tmp_path):
        out, master, _rungs, _n = packaged_ladder(tmp_path)
        lines = open(master).read().splitlines()
        # swap the first variant's BANDWIDTH to a huge value
        for i, line in enumerate(lines):
            if line.startswith("#EXT-X-STREAM-INF:"):
                lines[i] = line.replace("BANDWIDTH=",
                                        "BANDWIDTH=9999999990", 1)
                break
        open(master, "w").write("\n".join(lines) + "\n")
        with pytest.raises(ValueError, match="monotonic"):
            hls.lint_ladder(out)

    def test_lint_rejects_boundary_mismatch(self, tmp_path):
        out, _master, rungs, _n = packaged_ladder(tmp_path)
        mp = os.path.join(out, rungs[1].name, hls.MEDIA_PLAYLIST)
        text = open(mp).read().replace("#EXTINF:0.26667,",
                                      "#EXTINF:0.40000,", 1)
        open(mp, "w").write(text)
        with pytest.raises(ValueError, match="differ|sum"):
            hls.lint_ladder(out)

    def test_package_rejects_misaligned_rung_plans(self, tmp_path):
        out, _master, _rungs, _n = packaged_ladder(tmp_path)
        # reuse one rung's real segments, drop one from the other rung
        frames = textured_frames(64, 48, 8)
        meta = VideoMeta(width=64, height=48, fps_num=30, fps_den=1,
                         num_frames=8)
        rungs = plan_ladder(meta, make_settings(qp=30,
                                                ladder_rungs="24"))
        bundles = LadderShardEncoder(meta, rungs,
                                     gop_frames=4).encode(frames)
        top = rung_segments(bundles, rungs[0].name)
        low = rung_segments(bundles, rungs[1].name)[:-1]
        with pytest.raises(ValueError, match="align"):
            hls.package_ladder(
                str(tmp_path / "bad.hls"),
                [hls.RungStream("48p", 64, 48, top),
                 hls.RungStream("24p", 32, 24, low)], 30, 1)


# ---------------------------------------------------------------------------
# executor end-to-end (local + watch-folder naming + serving)
# ---------------------------------------------------------------------------


def make_rig(tmp_path, snap):
    reg = WorkerRegistry()
    for i in range(8):
        reg.heartbeat(f"w{i:02d}")
    coord = Coordinator(registry=reg, settings_fn=lambda: snap)
    execu = LocalExecutor(coord, output_dir=str(tmp_path / "library"),
                          sync=True)
    coord._launcher = execu.launch
    return coord, execu


class TestLadderJobEndToEnd:
    def test_watch_named_ladder_job_to_served_master(self, tmp_path):
        w, h, n = 64, 48, 16
        meta = VideoMeta(width=w, height=h, fps_num=30, fps_den=1,
                         num_frames=n)
        clip = tmp_path / "clip.ladder.y4m"     # watch-folder naming
        write_y4m(str(clip), meta, textured_frames(w, h, n))
        snap = make_settings(qp=30, gop_frames=4, segment_s=0.25,
                             ladder_rungs="32,24",
                             heartbeat_throttle_s=0.0)
        coord, _execu = make_rig(tmp_path, snap)
        job = coord.add_job(str(clip), meta)
        job = coord.store.get(job.id)
        assert job.job_type == "ladder"          # from the filename
        assert job.status is Status.DONE, job.failure_reason
        assert job.output_path.endswith("master.m3u8")
        assert os.path.exists(job.output_path)
        out_dir = os.path.dirname(job.output_path)
        info = hls.lint_ladder(out_dir, expected_duration_s=n / 30)
        assert info["rungs"] == 3
        assert job.parts_done == job.parts_total > 0
        assert job.output_bytes > 0

        # the API serves the tree at /hls/<job>/...
        from thinvids_tpu.api.server import ApiServer, _FileResponse

        api = ApiServer(coord)
        status, payload = api.route("GET", f"/hls/{job.id}/master.m3u8",
                                    {}, {})
        assert status == 200 and isinstance(payload, _FileResponse)
        assert payload.content_type == "application/vnd.apple.mpegurl"
        status, payload = api.route(
            "GET", f"/hls/{job.id}/32p/media.m3u8", {}, {})
        assert status == 200
        status, payload = api.route(
            "GET", f"/hls/{job.id}/32p/init.mp4", {}, {})
        assert status == 200 and payload.content_type == "video/mp4"
        # traversal + junk rejected
        from thinvids_tpu.api.server import ApiError

        with pytest.raises(ApiError):
            api.route("GET", f"/hls/{job.id}/../../etc/passwd", {}, {})
        with pytest.raises(ApiError):
            api.route("GET", f"/hls/{job.id}/32p/evil.sh", {}, {})
        # /preview must not hand a playlist out labelled video/mp4
        with pytest.raises(ApiError, match="master.m3u8"):
            api.route("GET", f"/preview/{job.id}", {}, {})

    def test_audio_passthrough_fragment_track(self, tmp_path):
        """A RungStream with audio carries it bit-exact as a second
        fragment track (second trak in init + second traf per segment,
        audio codec in that variant's CODECS); audio=None stays
        video-only. (The executor attaches audio to every rung — this
        pins the per-stream plumbing underneath.)"""
        from thinvids_tpu.io.mp4 import Mp4Track, _box

        w, h, n = 64, 48, 8
        frames = textured_frames(w, h, n)
        meta = VideoMeta(width=w, height=h, fps_num=30, fps_den=1,
                         num_frames=n)
        # fabricate a passthrough-able audio track (opaque sample entry)
        entry = _box(b"mp4a", b"\x00" * 28)
        audio = Mp4Track(handler="soun", stsd_entry=entry,
                         timescale=48000,
                         stts=[(4, 12000)],
                         samples=[bytes([i] * 8) for i in range(4)])
        rungs = plan_ladder(meta, make_settings(qp=30,
                                                ladder_rungs="24"))
        bundles = LadderShardEncoder(meta, rungs,
                                     gop_frames=4).encode(frames)
        out = str(tmp_path / "a.hls")
        streams = [hls.RungStream(r.name, r.width, r.height,
                                  rung_segments(bundles, r.name),
                                  audio=audio if r.top else None)
                   for r in rungs]
        master = hls.package_ladder(out, streams, 30, 1, segment_s=0.15)
        hls.lint_ladder(out)
        # the muxed variant must declare BOTH codecs (RFC 8216
        # §4.3.4.2) or players never bring up the audio decoder
        text = open(master).read()
        top_inf = [l for l in text.splitlines()
                   if l.startswith("#EXT-X-STREAM-INF") and
                   f"RESOLUTION={rungs[0].width}x{rungs[0].height}"
                   in l][0]
        assert "mp4a.40.2" in top_inf
        low_inf = [l for l in text.splitlines()
                   if l.startswith("#EXT-X-STREAM-INF") and
                   f"RESOLUTION={rungs[1].width}x{rungs[1].height}"
                   in l][0]
        assert "mp4a" not in low_inf
        top_dir = os.path.join(out, rungs[0].name)
        init = open(os.path.join(top_dir, hls.INIT_NAME), "rb").read()
        assert init.count(b"trak") >= 2 and b"mp4a" in init
        got_audio = []
        for name in sorted(os.listdir(top_dir)):
            if name.endswith(".m4s"):
                seg = open(os.path.join(top_dir, name), "rb").read()
                got_audio.extend(hls.segment_track_samples(seg,
                                                           track_id=2))
        assert got_audio == audio.samples       # bit-exact passthrough
        low_dir = os.path.join(out, rungs[1].name)
        low_init = open(os.path.join(low_dir, hls.INIT_NAME),
                        "rb").read()
        assert b"mp4a" not in low_init


# ---------------------------------------------------------------------------
# remote farm: rungs × shards
# ---------------------------------------------------------------------------


def board_worker(board, host, stop):
    """Fake worker thread claiming straight off the board with the real
    shard encoder (the test_remote harness pattern)."""
    from thinvids_tpu.cluster.remote import encode_shard
    from thinvids_tpu.ingest.decode import read_video

    cache = {}

    def loop():
        while not stop.is_set():
            desc = board.claim(host)
            if desc is None:
                time.sleep(0.01)
                continue
            path = desc["input_path"]
            if path not in cache:
                cache[path] = read_video(path)[1]
            segs = encode_shard(desc, cache[path])
            board.submit_part(desc["id"], host, segs)

    t = threading.Thread(target=loop, daemon=True,
                         name=f"fake-worker-{host}")
    t.start()
    return t


class TestRemoteLadder:
    def test_rung_shard_encodes_bit_identical_to_local_ladder(
            self, tmp_path):
        """A worker's scaled-rung shard (device downscale on ITS mesh)
        reproduces the coordinator-local ladder encode bit for bit."""
        from thinvids_tpu.cluster.remote import Shard, encode_shard

        w, h, n = 64, 48, 8
        frames = textured_frames(w, h, n)
        meta = VideoMeta(width=w, height=h, fps_num=30, fps_den=1,
                         num_frames=n)
        rungs = plan_ladder(meta, make_settings(qp=30,
                                                ladder_rungs="24"))
        ladder = LadderShardEncoder(meta, rungs, gop_frames=4)
        bundles = ladder.encode(frames)
        want = rung_segments(bundles, rungs[1].name)

        plan = ladder.plan(n)
        shard = Shard(
            id="j-24p-0000", job_id="j", input_path="x.y4m", meta=meta,
            gops=plan.gops, qp=rungs[1].qp, gop_frames=4,
            timeout_s=60.0, rung=rungs[1].name,
            rung_width=rungs[1].width, rung_height=rungs[1].height)
        got = encode_shard(shard.descriptor(), frames)
        assert [s.payload for s in got] == [s.payload for s in want]

    def test_remote_ladder_job_end_to_end(self, tmp_path):
        from thinvids_tpu.cluster.remote import RemoteExecutor

        w, h, n = 64, 48, 16
        meta = VideoMeta(width=w, height=h, fps_num=30, fps_den=1,
                         num_frames=n)
        clip = tmp_path / "farm.ladder.y4m"
        write_y4m(str(clip), meta, textured_frames(w, h, n))
        snap = make_settings(
            qp=30, gop_frames=2, segment_s=0.25, ladder_rungs="32,24",
            heartbeat_throttle_s=0.0, remote_plan_devices=8,
            remote_shard_gops=2, remote_no_worker_grace_s=10.0)
        reg = WorkerRegistry()
        for i in range(8):
            reg.heartbeat(f"w{i:02d}", metrics={"worker": True})
        coord = Coordinator(registry=reg, settings_fn=lambda: snap)
        execu = RemoteExecutor(coord, output_dir=str(tmp_path / "lib"),
                               sync=True, poll_s=0.02)
        coord._launcher = execu.launch
        stop = threading.Event()
        for i in range(2):
            board_worker(execu.board, f"w{i:02d}", stop)
        try:
            job = coord.add_job(str(clip), meta)
        finally:
            stop.set()
        job = coord.store.get(job.id)
        assert job.status is Status.DONE, job.failure_reason
        # rungs × GOPs parts accounting: 8 GOPs × 3 rungs
        assert job.parts_total == 24 and job.parts_done == 24
        assert job.output_path.endswith("master.m3u8")
        info = hls.lint_ladder(os.path.dirname(job.output_path),
                               expected_duration_s=n / 30)
        assert info["rungs"] == 3


# ---------------------------------------------------------------------------
# jax-free guard
# ---------------------------------------------------------------------------


def test_ladder_and_hls_are_manifested_jax_free(analysis_ctx):
    """Packaging and planning must run on jax-free worker/sidecar
    processes (same rule as parallel/packproc.py). Migrated from a
    subprocess import probe to the analyzer's import-graph proof: the
    manifest must keep declaring both modules jax-free, and the
    confinement pass (which walks the TRANSITIVE module-scope import
    closure, package __init__ chains included) must be clean on HEAD.
    Tree-wide enforcement rides `cli.py check` in tier-1."""
    from thinvids_tpu.analysis import imports
    from thinvids_tpu.analysis.astutil import matches_any

    m, tree = analysis_ctx
    for mod in ("thinvids_tpu.abr.ladder", "thinvids_tpu.abr.hls"):
        assert matches_any(mod, m.jax_free), (
            f"manifest no longer declares {mod} jax-free")
    open_ = [f for f in imports.check_jax_confinement(tree, m)
             if f.key not in m.waivers and f.module.startswith(
                 "thinvids_tpu.abr")]
    assert not open_, "\n".join(f.format() for f in open_)
