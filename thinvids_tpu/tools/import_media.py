"""Media import CLI: scan a source tree and queue transcodes.

Analog of the reference's acquisition tooling
(/root/reference/rips/dvd_rip_queue.py): that tool ripped a disc with
makemkvcon, auto-titled via TMDb, remuxed, normalized the name to
``Title (Year) <res>p h264.mkv`` and dropped the file into the watch
root (or POSTed /add_job). The disc-drive and TMDb halves are hardware/
network-bound and out of scope here; this tool keeps the pipeline-facing
half: discover source media, probe it natively, normalize names the
same way, and queue it — by watch-root drop (the watcher's ledger picks
it up) or directly against the coordinator API. `--dry-run` prints the
plan, as the reference's tooling did (dvd_rip_queue.py:1947).

Usage:
    python -m thinvids_tpu.tools.import_media SRC_DIR \
        --watch-root /srv/watch [--movies-subdir movies] [--dry-run]
    python -m thinvids_tpu.tools.import_media SRC_DIR \
        --api http://manager:5005 [--dry-run]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import urllib.request

from ..ingest.decode import supported_exts
from ..ingest.probe import ProbeError, probe_video


def normalized_name(path: str, height: int, codec: str) -> str:
    """``Title (Year) <res>p <codec>.<ext>`` when a year is present in
    the source name, else ``Title <res>p <codec>.<ext>`` — the
    reference's final-name scheme (dvd_rip_queue.py:1761-1814)."""
    base, ext = os.path.splitext(os.path.basename(path))
    year = None
    # a parenthesized/bracketed year wins; otherwise take the LAST bare
    # year-like token so titles containing a year keep it
    # ("Blade Runner 2049 (2017)" → year 2017, not 2049)
    m = re.search(r"[(\[](19\d{2}|20\d{2})[)\]]", base)
    if m is None:
        bare = list(re.finditer(r"[.\s](19\d{2}|20\d{2})(?=[.\s]|$)",
                                base))
        m = bare[-1] if bare else None
    if m:
        year = m.group(1)
        base = base[:m.start()]
    title = re.sub(r"[._]+", " ", base).strip(" -_.")
    title = re.sub(r"\s{2,}", " ", title) or "Untitled"
    title = " ".join(w if w.isupper() else w.capitalize()
                     for w in title.split())
    res = f"{height}p"
    tail = f"({year}) {res}" if year else res
    return f"{title} {tail} {codec}{ext.lower()}"


def discover(src_dir: str) -> list[str]:
    exts = supported_exts()
    found = []
    for root, _dirs, files in os.walk(src_dir):
        for name in sorted(files):
            if name.lower().endswith(exts) and not name.startswith("."):
                found.append(os.path.join(root, name))
    return found


def plan_imports(src_dir: str) -> list[dict]:
    """[{src, name, width, height, codec, duration_s} | {src, error}]"""
    plans = []
    for src in discover(src_dir):
        try:
            meta = probe_video(src)
        except ProbeError as exc:
            plans.append({"src": src, "error": str(exc)})
            continue
        plans.append({
            "src": src,
            "name": normalized_name(src, meta.height, meta.codec),
            "width": meta.width, "height": meta.height,
            "codec": meta.codec,
            "duration_s": round(meta.duration_s, 3),
        })
    return plans


def import_to_watch(plan: dict, watch_root: str, subdir: str = "") -> str:
    """Copy one planned file into the watch root under its normalized
    name (atomic: temp + rename, so the watcher's size-stabilization
    never sees a half-copied file as stable)."""
    dest_dir = os.path.join(watch_root, subdir) if subdir else watch_root
    os.makedirs(dest_dir, exist_ok=True)
    dest = os.path.join(dest_dir, plan["name"])
    tmp = dest + ".importing"
    shutil.copyfile(plan["src"], tmp)
    os.replace(tmp, dest)
    return dest


def submit_to_api(plan: dict, api_base: str, timeout_s: float = 30.0
                  ) -> dict:
    body = json.dumps({"input_path": plan["src"]}).encode()
    req = urllib.request.Request(
        api_base.rstrip("/") + "/add_job", data=body, method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read())


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="import_media", description=__doc__.splitlines()[0])
    p.add_argument("src_dir")
    dest = p.add_mutually_exclusive_group(required=True)
    dest.add_argument("--watch-root", help="copy into this watch folder")
    dest.add_argument("--api", help="submit paths to this coordinator API")
    p.add_argument("--movies-subdir", default="",
                   help="subdirectory under the watch root")
    p.add_argument("--dry-run", action="store_true")
    args = p.parse_args(argv)

    plans = plan_imports(args.src_dir)
    rc = 0
    for plan in plans:
        if "error" in plan:
            print(f"SKIP {plan['src']}: {plan['error']}")
            rc = 1
            continue
        probe = (f"[{plan['width']}x{plan['height']} {plan['codec']} "
                 f"{plan['duration_s']}s]")
        if args.dry_run:
            target = (f"-> {plan['name']}" if args.watch_root
                      else "(submitted as-is)")
            print(f"PLAN {plan['src']} {target} {probe}")
        elif args.watch_root:
            dest_path = import_to_watch(plan, args.watch_root,
                                        args.movies_subdir)
            print(f"COPIED {plan['src']} -> {dest_path} {probe}")
        else:
            # API mode submits the source path verbatim — the output
            # file is named from it; name normalization applies only to
            # watch-root drops
            job = submit_to_api(plan, args.api)
            print(f"QUEUED {plan['src']} {probe} as job {job.get('id')}")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
