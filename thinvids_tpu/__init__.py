"""thinvids_tpu — a TPU-native distributed video transcoding framework.

A ground-up rebuild of the capabilities of AwsGeek/thinvids (a Redis/Huey/
ffmpeg/VAAPI thin-client transcoding farm) designed TPU-first:

- the encode path is JAX/Pallas kernels (integer transforms, quantization,
  intra prediction, block motion estimation, deblocking) over HBM-resident
  YUV planes instead of external ffmpeg+VAAPI processes;
- segment/GOP parallelism uses ``jax.sharding.Mesh`` + ``shard_map`` with
  ICI collectives for rate-control stats instead of Huey task dispatch to
  worker nodes;
- the control plane (job store, scheduler, watchdog, heartbeats, activity
  log) is an in-process coordinator with an HTTP API mirroring the
  reference's Flask surface (reference: /root/reference/manager/app.py).

Layout (maps to SURVEY.md §7.1):
    core/      video types, layered config, status/events, logging
    codecs/    H.264 (and HEVC/AV1 scaffolding) kernels + entropy coding
    pipeline/  jitted per-GOP encode functions + rate control
    parallel/  segment planner, mesh helpers, shard_map dispatch
    cluster/   coordinator, job store, scheduler, watchdog, agent
    ingest/    watch-folder daemon, processed ledger, probing
    io/        y4m / Annex-B / IVF / MP4 container IO
    api/       HTTP API + dashboard UI
    tools/     stamp seam verification, quality metrics, benchmarks
    native/    C++ hot paths (entropy packing) loaded via ctypes
"""

__version__ = "0.1.0"
