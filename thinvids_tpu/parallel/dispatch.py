"""shard_map GOP dispatch: one GOP per mesh device per wave.

The reference's dispatch loop enqueued one encode task per segment onto a
Redis-backed queue consumed by worker nodes (/root/reference/worker/
tasks.py:1167-1281); here a wave of GOPs is one SPMD program over the mesh:
frames live HBM-resident per device, the jitted intra compute runs a
sequential `lax.map` over the GOP's frames (the carry will hold reference
frames once P-frames land), and the quantized levels return to host for
entropy packing. Encoded segments concat in index order — bit-identical to
a single-device encode (tested).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core.types import EncodedSegment, Frame, GopSpec, SegmentPlan, VideoMeta
from ..codecs.h264.encoder import FrameLevels, _mode_policy, pack_slice
from ..codecs.h264.headers import PPS, SPS
from ..codecs.h264 import jaxcore
from .planner import plan_segments


def default_mesh(devices=None) -> Mesh:
    devices = list(jax.devices()) if devices is None else list(devices)
    return Mesh(np.array(devices), ("gop",))


@functools.partial(jax.jit, static_argnames=("mbw", "mbh", "mesh"))
def _encode_wave(ys, us, vs, qp, *, mbw: int, mbh: int, mesh: Mesh):
    """ys: (G, F, H, W) uint8 sharded over `gop`; returns level arrays with
    leading (G, F) dims."""

    def per_gop(y_g, u_g, v_g):
        # y_g: (1, F, H, W) — this device's GOP(s)
        def per_frame(planes):
            y, u, v = planes
            return jaxcore._encode_intra(y, u, v, qp, mbw=mbw, mbh=mbh)

        def one(y_f, u_f, v_f):
            return jax.lax.map(per_frame, (y_f, u_f, v_f))

        return jax.vmap(one)(y_g, u_g, v_g)

    shard = jax.shard_map(
        per_gop, mesh=mesh,
        in_specs=(P("gop"), P("gop"), P("gop")),
        out_specs=(P("gop"), P("gop"), P("gop"), P("gop")),
    )
    return shard(ys, us, vs)


class GopShardEncoder:
    """Encode a clip as closed GOPs fanned across a device mesh."""

    def __init__(self, meta: VideoMeta, qp: int = 27, mesh: Mesh | None = None,
                 gop_frames: int = 32, max_segments: int = 200):
        self.meta = meta
        self.qp = qp
        self.mesh = mesh if mesh is not None else default_mesh()
        self.gop_frames = gop_frames
        self.max_segments = max_segments
        self.sps = SPS(width=meta.width, height=meta.height,
                       fps_num=meta.fps_num, fps_den=meta.fps_den)
        self.pps = PPS(init_qp=qp)

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size

    def plan(self, num_frames: int) -> SegmentPlan:
        return plan_segments(num_frames, self.gop_frames, self.num_devices,
                             self.max_segments)

    def encode(self, frames: list[Frame]) -> list[EncodedSegment]:
        plan = self.plan(len(frames))
        padded = [f.padded(16) for f in frames]
        ph, pw = padded[0].y.shape
        mbh, mbw = ph // 16, pw // 16
        luma_mode, chroma_mode = _mode_policy(mbw, mbh)
        qp = jnp.asarray(self.qp)

        segments: list[EncodedSegment] = []
        D = self.num_devices
        gops = list(plan.gops)
        for wave_start in range(0, len(gops), D):
            wave = gops[wave_start:wave_start + D]
            F = max(g.num_frames for g in wave)
            # Stack into (G, F, ...) with tail-repeat padding to static F,
            # and pad the wave itself to D gops (encoded then discarded).
            pad_gop = wave[-1]
            full = wave + [pad_gop] * (D - len(wave))
            ys = np.stack([self._gop_plane(padded, g, F, "y") for g in full])
            us = np.stack([self._gop_plane(padded, g, F, "u") for g in full])
            vs = np.stack([self._gop_plane(padded, g, F, "v") for g in full])
            out = _encode_wave(jnp.asarray(ys), jnp.asarray(us),
                               jnp.asarray(vs), qp,
                               mbw=mbw, mbh=mbh, mesh=self.mesh)
            luma_dc, luma_ac, chroma_dc, chroma_ac = (np.asarray(o) for o in out)
            for gi, gop in enumerate(wave):
                payload = []
                for fi in range(gop.num_frames):
                    levels = FrameLevels(
                        luma_mode=luma_mode, chroma_mode=chroma_mode,
                        luma_dc=luma_dc[gi, fi], luma_ac=luma_ac[gi, fi],
                        chroma_dc=chroma_dc[gi, fi], chroma_ac=chroma_ac[gi, fi],
                    )
                    nal = pack_slice(levels, mbw, mbh, self.sps, self.pps,
                                     self.qp, idr=True,
                                     idr_pic_id=(gop.start_frame + fi) % 65536)
                    if fi == 0:
                        nal = self.sps.to_nal() + self.pps.to_nal() + nal
                    payload.append(nal)
                segments.append(EncodedSegment(
                    gop=gop, payload=b"".join(payload),
                    frame_sizes=tuple(len(p) for p in payload)))
        return segments

    @staticmethod
    def _gop_plane(padded: list[Frame], gop: GopSpec, F: int, plane: str
                   ) -> np.ndarray:
        arrs = [getattr(padded[i], plane) for i in range(gop.start_frame,
                                                        gop.end_frame)]
        while len(arrs) < F:            # tail-repeat to the wave's static F
            arrs.append(arrs[-1])
        return np.stack(arrs)


def encode_clip_sharded(frames: list[Frame], meta: VideoMeta, qp: int = 27,
                        mesh: Mesh | None = None, gop_frames: int = 32
                        ) -> bytes:
    """Convenience: plan → shard encode → order-restoring concat."""
    from ..core.types import concat_segments

    enc = GopShardEncoder(meta, qp=qp, mesh=mesh, gop_frames=gop_frames)
    return concat_segments(enc.encode(frames))
