"""Media probing: header-only metadata extraction for ingest.

The reference probed sources by shelling out to ffprobe with a timeout
(/root/reference/worker/tasks.py:190-268, manager/app.py:2120-2220);
here probing is native: parse the container header and derive stream
facts without reading frame payloads.
"""

from __future__ import annotations

import os

from ..core.types import VideoMeta


class ProbeError(ValueError):
    """File is not probeable media (unknown container or bad header)."""


def _probe_y4m(path: str) -> VideoMeta:
    from ..io.y4m import Y4MReader

    size = os.path.getsize(path)
    with open(path, "rb") as fp:
        reader = Y4MReader(fp)
        header_len = fp.tell()
    meta = reader.meta
    # Frame payload size is constant for 8-bit y4m; each frame is a
    # "FRAME\n" marker + planes. Frame-header parameters would break
    # this arithmetic, but Y4MWriter never emits them and the reader
    # rejects interlaced input already.
    plane_bytes = sum(h * w for (h, w) in reader._plane_shapes())
    per_frame = len(b"FRAME\n") + plane_bytes
    num_frames = max(0, (size - header_len) // per_frame)
    fps = meta.fps if meta.fps else 30.0
    return VideoMeta(
        width=meta.width, height=meta.height,
        fps_num=meta.fps_num, fps_den=meta.fps_den,
        num_frames=int(num_frames), chroma=meta.chroma,
        codec="rawvideo", duration_s=num_frames / fps,
        size_bytes=size,
    )


def _probe_mp4(path: str) -> VideoMeta:
    from ..io.mp4 import probe_mp4_header

    info = probe_mp4_header(path)       # moov-only: never loads mdat
    return VideoMeta(
        width=info["width"], height=info["height"],
        fps_num=info["fps_num"], fps_den=info["fps_den"],
        num_frames=info["num_frames"], codec=info["codec"],
        duration_s=info["duration_s"],
        size_bytes=os.path.getsize(path))


_PROBERS = {
    ".y4m": _probe_y4m,
    ".mp4": _probe_mp4,
}


def probe_video(path: str | os.PathLike) -> VideoMeta:
    """Probe a media file's metadata from its header.

    Raises :class:`ProbeError` for unsupported or malformed files —
    the watcher treats those as non-media and skips them.
    """
    path = os.fspath(path)
    ext = os.path.splitext(path)[1].lower()
    prober = _PROBERS.get(ext)
    if prober is None:
        raise ProbeError(f"unsupported media extension {ext!r}: {path}")
    try:
        return prober(path)
    except (OSError, ValueError, EOFError) as exc:
        raise ProbeError(f"cannot probe {path}: {exc}") from exc
