"""Activity / event log.

Port of the reference's tracing substrate (/root/reference/common.py:276-425):
JSON events pushed to a capped global deque plus compact per-job lines, with a
stage→label classifier. The reference kept these in Redis lists
(``activity:log`` cap 2000, ``joblog:<id>`` cap 50000); here they are
in-process ring buffers owned by the coordinator and served over its API.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Iterable

_STAGE_LABELS = [
    ("error", "ERROR"),
    ("fail", "ERROR"),
    ("segment", "SEGMENT"),
    ("split", "SEGMENT"),
    ("encode", "ENCODE"),
    ("stitch", "STITCH"),
    ("concat", "STITCH"),
    ("finish", "FINISH"),
    ("done", "FINISH"),
    ("start", "START"),
    ("stamp", "STAMP"),
]


def activity_label(stage: str) -> str:
    s = (stage or "").lower()
    for needle, label in _STAGE_LABELS:
        if needle in s:
            return label
    return "INFO"


class ActivityLog:
    """Thread-safe capped event log with per-job sublogs."""

    def __init__(self, cap: int = 2000, job_cap: int = 50000) -> None:
        self._lock = threading.Lock()
        self._events: collections.deque[dict[str, Any]] = collections.deque(maxlen=cap)
        self._job_logs: dict[str, collections.deque[str]] = {}
        self._job_cap = job_cap

    def emit(
        self,
        stage: str,
        message: str,
        job_id: str | None = None,
        host: str | None = None,
        **fields: Any,
    ) -> dict[str, Any]:
        event = {
            "ts": time.time(),
            "stage": stage,
            "label": activity_label(stage),
            "message": message,
            "job_id": job_id,
            "host": host,
        }
        event.update(fields)
        with self._lock:
            self._events.appendleft(event)
            if job_id is not None:
                log = self._job_logs.setdefault(
                    job_id, collections.deque(maxlen=self._job_cap)
                )
                log.append(self._format_line(event))
        return event

    @staticmethod
    def _format_line(event: dict[str, Any]) -> str:
        ts = time.strftime("%H:%M:%S", time.localtime(event["ts"]))
        host = event.get("host") or "-"
        extra = ""
        if "part" in event:
            extra += f" part={event['part']}"
        if "elapsed_ms" in event:
            extra += f" {event['elapsed_ms']:.0f}ms"
        return f"{ts} {event['label']:<8} {host} {event['message']}{extra}"

    def fetch(self, limit: int = 100) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._events)[:limit]

    def fetch_job(self, job_id: str, limit: int = 500) -> list[str]:
        with self._lock:
            log = self._job_logs.get(job_id)
            if not log:
                return []
            return list(log)[-limit:]

    def drop_job(self, job_id: str) -> None:
        with self._lock:
            self._job_logs.pop(job_id, None)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._job_logs.clear()


def merge_events(logs: Iterable[ActivityLog], limit: int = 100) -> list[dict[str, Any]]:
    merged: list[dict[str, Any]] = []
    for log in logs:
        merged.extend(log.fetch(limit))
    merged.sort(key=lambda e: e["ts"], reverse=True)
    return merged[:limit]
