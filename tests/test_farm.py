"""Elastic multi-tenant farm tests (thinvids_tpu/farm/).

Four layers:

- `TestTenancy` / `TestFairShare`: tenant parsing and the weighted
  fair-share admission at BOTH points (ShardBoard.claim and the
  coordinator's dispatch pass).
- `TestController`: the CapacityController's lifecycle decisions on a
  fake clock with a recording provider — scale-up from zero, drain
  completes in-flight shards before suspend, drain-grace requeue (no
  attempt burned), wake timeout, crashed-worker absorption, the
  claim gate, and energy accounting.
- `TestChaos`: the loadgen chaos harness (diurnal curve, kills,
  /work partition) on injected clocks.
- `test_subprocess_provider_end_to_end`: the hermetic acceptance rig —
  a real coordinator + HTTP API with the controller spawning a REAL
  ``cli.py worker`` subprocess from scale-to-zero, the job reaching
  DONE, and the scale-down draining and killing the daemon
  (alongside tests/test_remote.py's 2-worker farm rig).
"""

import os
import threading
import time

import pytest

from thinvids_tpu.cluster import Coordinator, WorkerRegistry
from thinvids_tpu.cluster.remote import RemoteExecutor, Shard, ShardBoard
from thinvids_tpu.core.config import DEFAULT_SETTINGS, Settings
from thinvids_tpu.core.status import ShardState, Status
from thinvids_tpu.core.types import GopSpec, VideoMeta
from thinvids_tpu.farm import (
    CallableProvider,
    CapacityController,
    WorkerState,
    clean_tenant,
    parse_tenant_shares,
    render_tenant_shares,
    tenant_of,
)
from thinvids_tpu.tools import loadgen

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class RecordingProvider(CallableProvider):
    """Provider that records calls; wake/suspend outcomes injectable."""

    def __init__(self, wake_ok=True, suspend_ok=True):
        self.woken: list[str] = []
        self.suspended: list[str] = []
        self.wake_ok = wake_ok
        self.suspend_ok = suspend_ok

    def wake(self, host):
        self.woken.append(host)
        return self.wake_ok

    def suspend(self, host):
        self.suspended.append(host)
        return self.suspend_ok


def make_settings(**over):
    values = dict(DEFAULT_SETTINGS)
    values.update(over)
    return Settings(values=values)


def make_shard(sid="j0-0000", job_id="j0", gop0=0, ngops=1,
               timeout_s=60.0, tenant="default", priority=2):
    gops = tuple(GopSpec(index=gop0 + i, start_frame=2 * (gop0 + i),
                         num_frames=2) for i in range(ngops))
    return Shard(id=sid, job_id=job_id, input_path="/in/a.y4m",
                 meta=VideoMeta(width=64, height=48), gops=gops, qp=30,
                 gop_frames=2, timeout_s=timeout_s, tenant=tenant,
                 priority=priority)


def make_rig(clock=None, workers=("w1", "w2"), **over):
    """Coordinator + board + controller on one fake clock; every host
    in `workers` heartbeats as a claim-capable daemon."""
    clock = clock or FakeClock()
    over.setdefault("pipeline_worker_count", len(workers) or 1)
    snap = make_settings(min_idle_workers=0, **over)
    reg = WorkerRegistry(clock=clock)
    for hostname in workers:
        reg.heartbeat(hostname, metrics={"worker": True}, now=clock())
    coord = Coordinator(registry=reg, clock=clock,
                        settings_fn=lambda: snap)
    board = ShardBoard(coord, clock=clock)
    provider = RecordingProvider()
    farm = CapacityController(coord, provider=provider, board=board,
                              clock=clock)
    coord.farm = farm
    return coord, board, farm, provider, clock


# ---------------------------------------------------------------------------
# tenancy + fair share
# ---------------------------------------------------------------------------


class TestTenancy:
    def test_tenant_from_filename_prefix(self):
        assert tenant_of("/watch/acme__clip.y4m") == "acme"
        assert tenant_of("/watch/acme__clip.ladder.y4m") == "acme"
        assert tenant_of("/watch/clip.y4m") == "default"
        # single underscore is NOT a tenant separator
        assert tenant_of("/watch/my_clip.y4m") == "default"
        # a bare "__x" prefix has no tenant name
        assert tenant_of("/watch/__clip.y4m") == "default"

    def test_explicit_tenant_wins_and_sanitizes(self):
        assert tenant_of("/watch/acme__clip.y4m", "Bravo!") == "bravo"
        assert clean_tenant("  UPPER-case_9  ") == "upper-case_9"
        assert clean_tenant("%$#") == "default"

    def test_shares_parse_and_render(self):
        shares = parse_tenant_shares("acme:3, bravo:1, bad:x, :2")
        assert shares["acme"] == 3.0 and shares["bravo"] == 1.0
        assert "bad" not in shares
        assert render_tenant_shares("bravo:1,acme:3") == \
            "acme:3,bravo:1"
        # zero/negative weights floor at a tiny positive share
        assert parse_tenant_shares("acme:0")["acme"] > 0

    def test_job_registration_resolves_tenant(self, tmp_path):
        coord = Coordinator(settings_fn=lambda: make_settings(
            auto_start_jobs=False))
        meta = VideoMeta(width=64, height=48, num_frames=4)
        j1 = coord.add_job("/in/acme__a.y4m", meta)
        j2 = coord.add_job("/in/b.y4m", meta,
                           settings={"tenant": "bravo"})
        j3 = coord.add_job("/in/c.y4m", meta)
        assert j1.tenant == "acme"
        assert j2.tenant == "bravo"
        assert j3.tenant == "default"


class TestFairShare:
    def test_claim_interleaves_tenants(self):
        """An early flood from one tenant must not starve the other:
        with equal shares the claim alternates tenants even though
        every acme shard is older in FIFO order."""
        coord, board, farm, _p, _c = make_rig(workers=("w1", "w2"),
                                              pipeline_worker_count=1)
        shards = [make_shard(sid=f"a-{i}", job_id="ja", tenant="acme")
                  for i in range(4)]
        shards += [make_shard(sid=f"b-{i}", job_id="jb",
                              tenant="bravo") for i in range(2)]
        board.add_job("ja", shards[:4], max_attempts=3, backoff_s=0.0,
                      quarantine_after=9)
        board.add_job("jb", shards[4:], max_attempts=3, backoff_s=0.0,
                      quarantine_after=9)
        got = [board.claim("w2")["id"] for _ in range(4)]
        tenants = ["acme" if g.startswith("a-") else "bravo"
                   for g in got]
        # usage balances 1:1 — strict FIFO would have been
        # [acme, acme, acme, acme]
        assert tenants == ["acme", "bravo", "acme", "bravo"]

    def test_claim_honors_weighted_shares(self):
        coord, board, farm, _p, _c = make_rig(
            workers=("w1", "w2"), pipeline_worker_count=1,
            tenant_shares="acme:3,bravo:1")
        a = [make_shard(sid=f"a-{i}", job_id="ja", tenant="acme")
             for i in range(6)]
        b = [make_shard(sid=f"b-{i}", job_id="jb", tenant="bravo")
             for i in range(6)]
        board.add_job("ja", a, max_attempts=3, backoff_s=0.0,
                      quarantine_after=9)
        board.add_job("jb", b, max_attempts=3, backoff_s=0.0,
                      quarantine_after=9)
        got = [board.claim("w2")["id"] for _ in range(4)]
        acme = sum(1 for g in got if g.startswith("a-"))
        # 3:1 weighting → acme takes 3 of the first 4 leases
        assert acme == 3

    def test_priority_class_still_dominates_tenancy(self):
        """Fair share is WITHIN a class: a live-class shard from the
        most-overserved tenant still beats any batch shard."""
        coord, board, farm, _p, _c = make_rig(workers=("w1", "w2"),
                                              pipeline_worker_count=1)
        board.add_job("jb", [make_shard(sid="b-0", job_id="jb",
                                        tenant="bravo", priority=2)],
                      max_attempts=3, backoff_s=0.0, quarantine_after=9)
        board.add_job("ja", [
            make_shard(sid=f"a-{i}", job_id="ja", tenant="acme",
                       priority=0) for i in range(2)],
            max_attempts=3, backoff_s=0.0, quarantine_after=9)
        got = [board.claim("w2")["id"] for _ in range(2)]
        assert got == ["a-0", "a-1"]

    def test_dispatch_picks_underserved_tenant(self):
        """The coordinator's dispatch pass applies the same weighted
        key: with an acme job already active, bravo's older queue
        position wins the next slot."""
        launched = []
        snap = make_settings(auto_start_jobs=False, max_active_jobs=2,
                             pipeline_worker_count=8,
                             min_idle_workers=0)
        reg = WorkerRegistry()
        for i in range(8):
            reg.heartbeat(f"n{i}", metrics={"devices": 1})
        coord = Coordinator(registry=reg, settings_fn=lambda: snap,
                            launcher=lambda j: launched.append(j))
        meta = VideoMeta(width=64, height=48, num_frames=4)
        ja = coord.add_job("/in/acme__a.y4m", meta)
        jb = coord.add_job("/in/acme__b.y4m", meta)
        jc = coord.add_job("/in/bravo__c.y4m", meta)
        coord.queue_job(ja.id)
        coord.queue_job(jb.id)
        coord.queue_job(jc.id)
        first = coord.dispatch_next_waiting_job()
        assert first.id == ja.id          # empty usage: FIFO
        # make ja shareable (RUNNING, segmented, drained) so the
        # admission gate lets a neighbor in
        token = coord.store.get(ja.id).run_token
        coord.mark_running(ja.id, token)
        coord.update_progress(ja.id, token, segment_progress=100.0,
                              parts_total=1, parts_done=1)
        second = coord.dispatch_next_waiting_job()
        # acme already holds a slot → bravo's job jumps acme's older one
        assert second is not None and second.id == jc.id

    def test_board_tenant_accounting_surfaces(self):
        coord, board, farm, _p, _c = make_rig(workers=("w1", "w2"),
                                              pipeline_worker_count=1)
        board.add_job("ja", [make_shard(sid="a-0", job_id="ja",
                                        tenant="acme")],
                      max_attempts=3, backoff_s=0.0, quarantine_after=9)
        board.claim("w2")
        assert board.tenant_assigned() == {"acme": 1}
        snap = board.snapshot()
        assert snap["tenants"]["acme"]["assigned"] == 1


# ---------------------------------------------------------------------------
# capacity controller
# ---------------------------------------------------------------------------


class TestController:
    def test_discovers_live_workers_as_active(self):
        coord, board, farm, prov, clock = make_rig()
        out = farm.tick()
        assert out["counts"]["active"] == 2
        assert farm.lifecycle_of("w1") is WorkerState.ACTIVE

    def test_waiting_job_demand_wakes_from_zero(self):
        """Scale-to-zero wake path: no workers exist, a WAITING job
        appears → the controller provisions a fresh host through the
        provider and tracks it WAKING; its first heartbeat lands it
        ACTIVE."""
        coord, board, farm, prov, clock = make_rig(
            workers=(), autoscale_enabled=True, farm_max_workers=3)
        meta = VideoMeta(width=64, height=48, num_frames=4)
        job = coord.add_job("/in/a.y4m", meta, auto_start=False)
        coord.queue_job(job.id)
        out = farm.tick()
        assert out["want"] == 1 and prov.woken
        host = prov.woken[0]
        assert farm.lifecycle_of(host) is WorkerState.WAKING
        # first heartbeat AFTER the wake → ACTIVE
        clock.advance(1.0)
        coord.registry.heartbeat(host, metrics={"worker": True},
                                 now=clock())
        farm.tick()
        assert farm.lifecycle_of(host) is WorkerState.ACTIVE

    def test_pending_shards_scale_with_class_weight(self):
        coord, board, farm, prov, clock = make_rig(
            workers=(), autoscale_enabled=True, farm_max_workers=8)
        board.add_job("j0", [make_shard(sid=f"s{i}", gop0=i,
                                        priority=0) for i in range(2)],
                      max_attempts=3, backoff_s=0.0, quarantine_after=9)
        out = farm.tick()
        # 2 live-class shards x weight 4 / 2-per-worker = 4 workers
        assert out["demand"] == 4
        assert len(prov.woken) == 4

    def test_idle_farm_drains_then_suspends(self):
        coord, board, farm, prov, clock = make_rig(
            autoscale_enabled=True, farm_min_workers=0,
            drain_grace_s=30.0)
        farm.tick()                       # discover w1/w2 ACTIVE
        out = farm.tick()                 # no demand → drain both
        assert farm.lifecycle_of("w1") is WorkerState.SUSPENDED \
            or "w1" in out["suspended"]
        assert sorted(prov.suspended) == ["w1", "w2"]
        # claims now refused outright
        board.add_job("j0", [make_shard()], max_attempts=3,
                      backoff_s=0.0, quarantine_after=9)
        assert board.claim("w1") is None

    def test_min_workers_floor_holds(self):
        coord, board, farm, prov, clock = make_rig(
            autoscale_enabled=True, farm_min_workers=1)
        farm.tick()
        farm.tick()
        counts = farm.snapshot()["counts"]
        assert counts["active"] == 1 and counts["suspended"] == 1

    def test_drain_finishes_inflight_before_suspend(self):
        """The graceful-drain contract: a DRAINING worker keeps its
        lease, stops claiming, and suspend fires only once the lease
        set empties."""
        coord, board, farm, prov, clock = make_rig(
            workers=("w1",), pipeline_worker_count=1,
            autoscale_enabled=True, farm_min_workers=0,
            drain_grace_s=1000.0)
        shard = make_shard()
        board.add_job("j0", [shard], max_attempts=3, backoff_s=0.0,
                      quarantine_after=9)
        farm.tick()                         # w1 ACTIVE
        desc = board.claim("w1")
        assert desc is not None
        farm.tick()                         # demand 0 → drain w1
        assert farm.lifecycle_of("w1") is WorkerState.DRAINING
        assert board.claim("w1") is None    # stops claiming
        farm.tick()                         # lease still held
        assert prov.suspended == []
        assert farm.lifecycle_of("w1") is WorkerState.DRAINING
        from tests.test_remote import fake_segment

        board.submit_part(desc["id"], "w1", [fake_segment(0, 0, 2)])
        farm.tick()                         # lease set empty → suspend
        assert prov.suspended == ["w1"]
        assert farm.lifecycle_of("w1") is WorkerState.SUSPENDED

    def test_drain_grace_requeues_without_attempt_burn(self):
        coord, board, farm, prov, clock = make_rig(
            workers=("w1",), pipeline_worker_count=1,
            autoscale_enabled=True, farm_min_workers=0,
            drain_grace_s=10.0)
        board.add_job("j0", [make_shard(timeout_s=9999.0)],
                      max_attempts=3, backoff_s=0.0, quarantine_after=9)
        farm.tick()
        board.claim("w1")
        farm.tick()                         # drain
        clock.advance(11.0)
        coord.registry.heartbeat("w1", metrics={"worker": True},
                                 now=clock())   # host alive, just stuck
        farm.tick()                         # grace expired → requeue
        shard = board._find_locked("j0-0000")
        assert shard.state is ShardState.PENDING
        assert shard.attempt == 0           # NO attempt burned
        assert shard.not_before == 0.0      # no backoff either
        assert prov.suspended == ["w1"]

    def test_wake_timeout_falls_back_to_suspended(self):
        coord, board, farm, prov, clock = make_rig(
            workers=(), autoscale_enabled=True, farm_max_workers=1,
            drain_grace_s=10.0)
        meta = VideoMeta(width=64, height=48, num_frames=4)
        job = coord.add_job("/in/a.y4m", meta, auto_start=False)
        coord.queue_job(job.id)
        farm.tick()
        host = prov.woken[0]
        assert farm.lifecycle_of(host) is WorkerState.WAKING
        clock.advance(11.0)                 # wake never heartbeats
        # the timeout drops the host back to SUSPENDED and — demand
        # persisting — the SAME tick's plan fires a retry wake
        farm.tick()
        assert prov.woken.count(host) == 2
        assert farm.lifecycle_of(host) is WorkerState.WAKING
        # with the demand gone, the next timeout parks it SUSPENDED
        coord.stop_job(job.id)
        clock.advance(11.0)
        farm.tick()
        assert farm.lifecycle_of(host) is WorkerState.SUSPENDED

    def test_crashed_active_worker_is_absorbed(self):
        """SIGKILLed worker: heartbeat goes stale → drained; a dark
        host's drain completes WITHOUT provider confirmation, so the
        next tick's demand can wake a replacement."""
        coord, board, farm, prov, clock = make_rig(
            workers=("w1",), pipeline_worker_count=1,
            autoscale_enabled=True, farm_min_workers=0,
            metrics_ttl_s=15.0)
        prov.suspend_ok = False             # dead process: no handle
        # standing demand keeps w1 wanted (and re-wakes a replacement)
        board.add_job("j0", [make_shard(sid=f"s{i}", gop0=i)
                             for i in range(4)],
                      max_attempts=3, backoff_s=0.0, quarantine_after=9)
        farm.tick()
        assert farm.lifecycle_of("w1") is WorkerState.ACTIVE
        clock.advance(20.0)                 # TTL lapses (crash)
        farm.tick()                         # dark host drains; its
        # drain completes WITHOUT provider confirmation (not live),
        # and the standing demand provisions replacements in the same
        # pass — the chaos-kill absorption loop
        assert farm.lifecycle_of("w1") is WorkerState.SUSPENDED
        assert len(prov.woken) >= 1

    def test_claim_promotes_waking_worker(self):
        coord, board, farm, prov, clock = make_rig(
            workers=("w1",), pipeline_worker_count=1,
            autoscale_enabled=True, farm_min_workers=0)
        farm.tick()
        farm.tick()                         # idle → drain+suspend w1
        assert farm.lifecycle_of("w1") is WorkerState.SUSPENDED
        board.add_job("j0", [make_shard()], max_attempts=3,
                      backoff_s=0.0, quarantine_after=9)
        farm.tick()                         # demand → wake w1
        assert farm.lifecycle_of("w1") is WorkerState.WAKING
        # the worker's own claim is proof it is up: promoted + served
        coord.registry.heartbeat("w1", metrics={"worker": True},
                                 now=clock())
        assert board.claim("w1") is not None
        assert farm.lifecycle_of("w1") is WorkerState.ACTIVE

    def test_autoscale_disabled_keeps_hands_off(self):
        coord, board, farm, prov, clock = make_rig(
            autoscale_enabled=False)
        farm.tick()
        farm.tick()
        assert prov.suspended == [] and prov.woken == []
        assert farm.snapshot()["counts"]["active"] == 2

    def test_active_worker_seconds_accumulate_only_while_on(self):
        coord, board, farm, prov, clock = make_rig(
            workers=("w1",), pipeline_worker_count=1,
            autoscale_enabled=True, farm_min_workers=0)
        # standing demand keeps w1 ACTIVE through the accrual window
        board.add_job("j0", [make_shard()], max_attempts=3,
                      backoff_s=0.0, quarantine_after=9)
        farm.tick()
        clock.advance(10.0)
        coord.registry.heartbeat("w1", metrics={"worker": True},
                                 now=clock())
        farm.tick()                         # 10 s ACTIVE
        board.cancel_job("j0")              # demand gone
        clock.advance(5.0)
        coord.registry.heartbeat("w1", metrics={"worker": True},
                                 now=clock())
        farm.tick()                         # +5 s, then drain+suspend
        base = farm.active_worker_seconds()
        assert base == pytest.approx(15.0)
        assert farm.lifecycle_of("w1") is WorkerState.SUSPENDED
        clock.advance(100.0)
        farm.tick()                         # suspended: no accrual
        assert farm.active_worker_seconds() == pytest.approx(base)

    def test_flight_record_carries_tenant(self, tmp_path):
        """Satellite: a failed job's postmortem artifact attributes
        the incident to its tenant next to the settings snapshot."""
        import json

        from thinvids_tpu.obs import flight, trace

        trace.TRACE.start("jobt")
        trace.TRACE.record_error("jobt", "boom")
        path = flight.record("jobt", reason="test failure",
                             out_dir=str(tmp_path),
                             settings={"qp": 27}, tenant="acme")
        assert path is not None
        with open(path, encoding="utf-8") as fp:
            doc = json.load(fp)
        assert doc["otherData"]["tenant"] == "acme"
        assert doc["otherData"]["settings"]["qp"] == 27
        trace.TRACE.drop("jobt")

    def test_snapshot_and_metrics_surface(self):
        from thinvids_tpu.api.server import ApiServer

        coord, board, farm, prov, clock = make_rig()
        farm.tick()
        api = ApiServer(coord, work=board)
        _status, snap = api.route("GET", "/metrics_snapshot", {}, {})
        assert snap["farm"]["counts"]["active"] == 2
        _status, text = api.route("GET", "/metrics", {}, {})
        body = text.body.decode("utf-8")
        assert 'tvt_farm_workers{lifecycle="active"} 2' in body
        assert "tvt_tenant_active_shards" in body
        assert 'tvt_jobs{tenant="default",status="done"}' in body


# ---------------------------------------------------------------------------
# chaos harness
# ---------------------------------------------------------------------------


class TestChaos:
    def test_diurnal_rate_shape(self):
        assert loadgen.diurnal_rate(0.0, 60.0, 0.0, 2.0) == \
            pytest.approx(0.0)
        assert loadgen.diurnal_rate(30.0, 60.0, 0.0, 2.0) == \
            pytest.approx(2.0)
        assert loadgen.diurnal_rate(60.0, 60.0, 0.0, 2.0) == \
            pytest.approx(0.0, abs=1e-9)
        mid = loadgen.diurnal_rate(15.0, 60.0, 1.0, 3.0)
        assert 1.0 < mid < 3.0

    def test_run_chaos_load_fires_everything(self):
        clock = {"t": 0.0}

        def fake_clock():
            return clock["t"]

        def fake_sleep(_s):
            clock["t"] += 0.5

        submitted, kills = [], []
        out = loadgen.run_chaos_load(
            lambda i: submitted.append(i), 20.0, period_s=20.0,
            lo_rps=0.0, hi_rps=1.0,
            kill=lambda: kills.append(1) or True, kill_interval_s=8.0,
            partition=lambda s: kills.append(("part", s)),
            partition_s=2.0, clock=fake_clock, sleep=fake_sleep)
        assert out["submitted"] == len(submitted) > 0
        assert out["kills"] >= 1
        assert out["partitions"] == 1
        assert ("part", 2.0) in kills

    def test_api_partition_blackholes_work_routes(self):
        from thinvids_tpu.api.server import ApiError, ApiServer

        coord, board, farm, _p, _c = make_rig()
        api = ApiServer(coord, work=board)
        board.add_job("j0", [make_shard()], max_attempts=3,
                      backoff_s=0.0, quarantine_after=9)
        api.partition_work(30.0)
        with pytest.raises(ApiError) as ei:
            api.route("POST", "/work/claim", {}, {"host": "w2"})
        assert ei.value.status == 503
        api.partition_work(0.0)            # heal
        status, out = api.route("POST", "/work/claim", {},
                                {"host": "w2"})
        assert status == 200 and out["shard"] is not None


# ---------------------------------------------------------------------------
# hermetic subprocess-provider acceptance rig
# ---------------------------------------------------------------------------


def test_subprocess_provider_end_to_end(tmp_path):
    """Scale-to-zero → wake a REAL worker daemon → job DONE → drain →
    suspend kills the daemon. The farm analog of test_remote.py's
    2-worker rig, with the controller doing the spawning."""
    from tests.test_remote import write_clip

    from thinvids_tpu.api.server import ApiServer
    from thinvids_tpu.farm import SubprocessProvider

    clip = tmp_path / "clip.y4m"
    meta = write_clip(clip, n=8)
    snap = make_settings(
        gop_frames=2, qp=30, heartbeat_throttle_s=0.0,
        execution_backend="remote", autoscale_enabled=True,
        farm_min_workers=0, farm_max_workers=1, drain_grace_s=20.0,
        pipeline_worker_count=1, min_idle_workers=0,
        scheduler_poll_s=0.25, metrics_ttl_s=5.0,
        remote_plan_devices=4, remote_shard_gops=2,
        remote_no_worker_grace_s=120.0)
    coord = Coordinator(settings_fn=lambda: snap)
    execu = RemoteExecutor(coord, output_dir=str(tmp_path / "lib"),
                           sync=False, poll_s=0.1)
    coord._launcher = execu.launch
    api = ApiServer(coord, work=execu.board).start()
    provider = SubprocessProvider(
        api.url, env=dict(os.environ, JAX_PLATFORMS="cpu",
                          PYTHONPATH=REPO))
    farm = CapacityController(coord, provider=provider,
                              board=execu.board)
    coord.farm = farm
    farm.start(poll_s=0.3)
    coord.start_background()
    try:
        job = coord.add_job(str(clip), meta)

        seen_hosts: set[str] = set()

        def wait_for(pred, budget, what):
            deadline = time.time() + budget
            while time.time() < deadline:
                seen_hosts.update(provider.hosts())
                if pred():
                    return
                time.sleep(0.2)
            raise TimeoutError(what)

        # the farm wakes from zero and the job lands DONE
        wait_for(lambda: coord.store.get(job.id).status
                 in (Status.DONE, Status.FAILED), 180,
                 "job terminal")
        done = coord.store.get(job.id)
        assert done.status is Status.DONE, done.failure_reason
        assert seen_hosts, "no worker daemon was ever spawned"
        host = sorted(seen_hosts)[0]
        # demand is gone: the controller drains and SUSPENDS the
        # daemon (SIGTERM through the provider — process exits)
        wait_for(lambda: farm.lifecycle_of(host)
                 is WorkerState.SUSPENDED, 60, "scale-down")
        wait_for(lambda: not provider.hosts(), 30,
                 "daemon process exit")
        assert farm.active_worker_seconds() > 0
    finally:
        coord.stop_background()
        farm.stop()
        provider.stop_all()
        api.stop()
        execu.join(30)
