"""Rate-distortion operating point of the encoder core.

One frozen, hashable config rides as a STATIC argument through every
jitted encode program (jaxcore/jaxinter/parallel.dispatch) and through
the numpy reference paths, so a feature toggle is a compile-time
specialization, never a traced branch:

- ``mode_decision``: per-MB intra mode decision — SATD (4x4 Hadamard)
  cost over the candidate I16x16/chroma predictors instead of the
  fixed V/H/DC raster policy (encoder._mode_policy stays the
  feature-off layout AND the fallback).
- ``pskip``: P_Skip bias — inter MBs whose quantized residual is
  near-zero (sum |level| <= pskip_sum, max |level| <= 1) drop the
  residual entirely, so the entropy packer's §8.4.1.1 skip inference
  turns them into mb_skip_run entries and the recon stays closed-loop
  (pure prediction — exactly what a decoder reconstructs for a
  skipped MB).
- ``deblock``: §8.7 in-loop deblocking applied to the recon carried
  between frames (and signaled in the slice headers), as the
  shifted-plane approximation implemented in codecs/h264/deblock.py.
- ``aq_strength``: perceptual (variance/JND-style) per-MB QP
  modulation on INTRA frames: flat MBs (where quantization error is
  most visible) encode finer, busy MBs (where texture masks it)
  coarser, around the same average QP. P frames keep the slice QP
  (their mb_qp_delta would be unsignalable on skipped/uncoded MBs).

This module is deliberately jax-free: the pack sidecars and the host
packers import it without initializing a device backend.
"""

from __future__ import annotations

import dataclasses

import numpy as np

#: AQ quantization of the strength knob: configs are static jit args,
#: so the continuous setting is snapped to 1/AQ_QUANT steps to bound
#: the number of distinct compiled programs.
AQ_QUANT = 4
#: AQ per-MB offset clamp (QP steps either side of the frame QP).
AQ_MAX_DELTA = 6
#: P_Skip bias: an inter MB whose quantized levels sum to <= this (in
#: absolute value, all planes) with every |level| <= 1 drops its
#: residual. 2 keeps the bias to MBs whose coded cost would exceed the
#: distortion it buys back (measured on the bench clip: bits fall with
#: no PSNR loss at 2; 4+ starts to visibly smear grain).
PSKIP_SUM = 2


@dataclasses.dataclass(frozen=True)
class RdConfig:
    """Static RD feature set of one encode. Hashable (a jit static)."""

    mode_decision: bool = False
    pskip: bool = False
    deblock: bool = False
    #: aq strength in 1/AQ_QUANT QP units (0 = off); use from_settings
    #: or aq_from_strength to build from the float knob
    aq_q: int = 0

    @property
    def aq_strength(self) -> float:
        return self.aq_q / AQ_QUANT

    @property
    def aq(self) -> bool:
        return self.aq_q > 0

    @property
    def ships_modes(self) -> bool:
        """True when the transfer layouts carry a per-MB intra mode
        (+ qp-delta) side channel (see layout.extra_len)."""
        return self.mode_decision or self.aq_q > 0


#: the feature-off config: every existing path's behavior, bit for bit
RD_OFF = RdConfig()


def aq_from_strength(strength: float) -> int:
    """Quantize the float aq_strength knob to the static aq_q field."""
    return max(0, min(3 * AQ_QUANT,
                      int(round(float(strength) * AQ_QUANT))))


def rd_from_settings(settings) -> RdConfig:
    """Build the static RD config from a Settings snapshot (the four
    knobs registered in core/config.DEFAULT_SETTINGS)."""
    from ...core.config import as_bool, as_float

    return RdConfig(
        mode_decision=as_bool(settings.get("mode_decision", False), False),
        pskip=as_bool(settings.get("pskip", False), False),
        deblock=as_bool(settings.get("deblock", False), False),
        aq_q=aq_from_strength(as_float(settings.get("aq_strength", 0.0),
                                       0.0)),
    )


# ---------------------------------------------------------------------------
# SATD (4x4 Hadamard) — the intra mode-decision cost, numpy reference.
# jaxcore implements the same transform on device; both must agree
# exactly (integer math only).
# ---------------------------------------------------------------------------

_H4 = np.array([[1, 1, 1, 1], [1, 1, -1, -1],
                [1, -1, -1, 1], [1, -1, 1, -1]], np.int32)


def satd16_np(resid: np.ndarray) -> int:
    """Sum of |Hadamard4x4| over a (16, 16) int32 residual block,
    divided by 2 (the standard SATD normalization — integer exact
    because the Hadamard doubles parity)."""
    total = 0
    r = resid.astype(np.int64)
    for by in range(4):
        for bx in range(4):
            b = r[4 * by:4 * by + 4, 4 * bx:4 * bx + 4]
            t = _H4 @ b @ _H4
            total += int(np.abs(t).sum())
    return total // 2


def satd8_np(resid: np.ndarray) -> int:
    """SATD of an (8, 8) chroma residual (four 4x4 Hadamards)."""
    total = 0
    r = resid.astype(np.int64)
    for by in range(2):
        for bx in range(2):
            b = r[4 * by:4 * by + 4, 4 * bx:4 * bx + 4]
            t = _H4 @ b @ _H4
            total += int(np.abs(t).sum())
    return total // 2


# ---------------------------------------------------------------------------
# perceptual AQ map — per-MB intra QP offsets from luma activity.
# ---------------------------------------------------------------------------

#: activity ceiling: 256·Σx² − (Σx)² <= 256·255²·256 < 2^32 for a
#: 16x16 uint8 block — 32 power-of-two thresholds cover every ilog2
#: value, and the whole computation fits uint32 (the jax mirror runs
#: without x64).
AQ_ACT_BITS = 32


def mb_activity_np(y: np.ndarray, mbw: int, mbh: int) -> np.ndarray:
    """(nmb,) int32 integer activity per MB: floor(log2(1 + V)) where
    V = 256·Σx² − (Σx)² (= 256² · variance of the MB's luma). ALL
    integer math — the jax mirror (jaxcore._mb_activity) must agree
    bit for bit, which float32 log2/variance cannot guarantee at
    rounding boundaries. floor(log2(1+v)) = |{k in 1..32 : v >= 2^k-1}|
    (the 2^k−1 form keeps every threshold inside uint32)."""
    y64 = y[:16 * mbh, :16 * mbw].astype(np.int64)
    mb = y64.reshape(mbh, 16, mbw, 16).transpose(0, 2, 1, 3)
    mb = mb.reshape(mbh * mbw, 256)
    s = mb.sum(axis=1)
    s2 = (mb * mb).sum(axis=1)
    v = 256 * s2 - s * s                       # >= 0, < 2^32
    act = np.zeros(mbh * mbw, np.int64)
    for k in range(1, AQ_ACT_BITS + 1):
        act += v >= ((1 << k) - 1)
    return act.astype(np.int32)


def aq_offsets_from_activity(act: np.ndarray, aq_q: int) -> np.ndarray:
    """(nmb,) int32 per-MB QP offsets from the integer activity map:
    round(strength · (act − mean(act))) via pure integer arithmetic
    (floor-division rounding, identical in numpy and XLA), clamped to
    ±AQ_MAX_DELTA — the x264-style variance-AQ shape: busy MBs
    (texture masks quantization error) move UP in QP, flat MBs down,
    ~zero-mean over the frame so the frame QP stays the rate operating
    point."""
    act = np.asarray(act, np.int64)
    nmb = act.shape[0]
    if aq_q <= 0 or nmb == 0:
        return np.zeros(nmb, np.int32)
    total = act.sum()
    num = aq_q * (act * nmb - total)           # strength·diff · (Q·nmb)
    den = AQ_QUANT * nmb
    delta = (2 * num + den) // (2 * den)       # floor-based round
    return np.clip(delta, -AQ_MAX_DELTA, AQ_MAX_DELTA).astype(np.int32)


def aq_offsets_np(y: np.ndarray, aq_q: int, mbw: int, mbh: int
                  ) -> np.ndarray:
    """(nmb,) int32 per-MB QP offsets for one INTRA frame."""
    return aq_offsets_from_activity(mb_activity_np(y, mbw, mbh), aq_q)


def clamp_qp_map(base_qp, offsets) -> np.ndarray:
    """Per-MB QP = base + offset, clamped to the legal H.264 range."""
    return np.clip(np.asarray(base_qp) + np.asarray(offsets), 0, 51
                   ).astype(np.int32)
