"""CLI daemons: the real deployment entrypoints, driven as processes.

The coordinator process = API + executor + ingest + durable state; the
agent process heartbeats over HTTP. These are the units deploy/*.service
run (reference analog: ansible units, SURVEY §2.8).
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np

from thinvids_tpu.core.types import Frame, VideoMeta
from thinvids_tpu.io.y4m import write_y4m

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _call(base, path, method="GET", body=None, timeout=5):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(base + path, data=data, method=method)
    if data:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _wait_api(base, deadline_s=30):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        try:
            return _call(base, "/health")
        except (urllib.error.URLError, ConnectionError, OSError):
            time.sleep(0.3)
    raise TimeoutError(f"coordinator API never came up at {base}")


def _spawn_coordinator(tmp_path, port):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               TVT_MIN_IDLE_WORKERS="0", TVT_PIPELINE_WORKER_COUNT="2")
    return subprocess.Popen(
        [sys.executable, "-m", "thinvids_tpu.cli", "coordinator",
         "--host", "127.0.0.1", "--port", str(port),
         "--state-dir", str(tmp_path / "state"),
         "--watch-dir", str(tmp_path / "watch"),
         "--output-dir", str(tmp_path / "library"),
         "--scan-interval", "0.5"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def test_coordinator_process_end_to_end(tmp_path):
    os.makedirs(tmp_path / "watch")
    import socket
    with socket.socket() as sk:          # reserve a free port
        sk.bind(("127.0.0.1", 0))
        port = sk.getsockname()[1]
    base = f"http://127.0.0.1:{port}"
    proc = _spawn_coordinator(tmp_path, port)
    try:
        _wait_api(base)
        # dashboard serves
        with urllib.request.urlopen(base + "/", timeout=5) as resp:
            assert resp.headers["Content-Type"].startswith("text/html")

        # agent process heartbeats in
        agent = subprocess.Popen(
            [sys.executable, "-m", "thinvids_tpu.cli", "agent",
             "--coordinator", base, "--node-name", "w-test",
             "--interval", "0.3"],
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        try:
            deadline = time.time() + 20
            while time.time() < deadline:
                nodes = _call(base, "/nodes_data")["nodes"]
                if any(n["host"] == "w-test" for n in nodes):
                    break
                time.sleep(0.3)
            assert any(n["host"] == "w-test" for n in nodes)
        finally:
            agent.send_signal(signal.SIGINT)
            agent.wait(timeout=10)

        # watch-folder ingest → transcode → DONE
        n, w, h = 6, 48, 32
        frames = [Frame(np.full((h, w), 60 + 20 * i, np.uint8),
                        np.full((h // 2, w // 2), 110, np.uint8),
                        np.full((h // 2, w // 2), 140, np.uint8))
                  for i in range(n)]
        meta = VideoMeta(width=w, height=h, fps_num=30, fps_den=1,
                         num_frames=n)
        write_y4m(str(tmp_path / "watch" / "clip.y4m"), meta, frames)
        deadline = time.time() + 120
        job = None
        while time.time() < deadline:
            jobs = _call(base, "/jobs")["jobs"]
            if jobs and jobs[0]["status"] in ("done", "failed"):
                job = jobs[0]
                break
            time.sleep(0.5)
        assert job is not None and job["status"] == "done", job
        assert os.path.exists(job["output_path"])

        # regression (VERDICT Weak #7): the coordinator's local agent
        # reports ONE node carrying its device count in metrics — no
        # phantom `{host}-devN` pseudo-nodes gaming slot admission.
        # The job above dispatched, so the device-weighted gate works.
        nodes = _call(base, "/nodes_data")["nodes"]
        assert nodes, "coordinator agent never registered"
        assert not any("-dev" in n["host"] for n in nodes), nodes
        metrics = _call(base, "/metrics_snapshot")["metrics"]
        assert any(int(m.get("devices", 0) or 0) >= 1
                   for m in metrics.values()), metrics

        # hard-kill and restart over the same state dir: the DONE job
        # must be recovered from the journal
        proc.kill()
        proc.wait(timeout=10)
        proc = _spawn_coordinator(tmp_path, port)
        _wait_api(base)
        jobs = _call(base, "/jobs")["jobs"]
        assert len(jobs) == 1 and jobs[0]["status"] == "done"
        # the watcher ledger survived too: no double-submit
        time.sleep(1.5)
        assert len(_call(base, "/jobs")["jobs"]) == 1
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
