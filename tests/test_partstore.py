"""Durable shard checkpointing (cluster/partstore.py + the board/
executor integration in cluster/remote.py).

Four layers:

- `TestPartStore`: the spool + checkpoint journal in isolation —
  atomic spool/commit, digest verification against bit flips, plan
  signature semantics of begin_job, torn-tail journal replay, flock
  ownership, the spool-bytes accounting.
- `TestWireDigests`: the /work part framing's embedded sha256 — a
  flipped payload bit raises PartIntegrityError at unpack.
- `TestBoardSpool`: ShardBoard holds PartRefs instead of bytes (the
  RAM un-pinning the ISSUE names), take_segments reads parts back from
  the spool, integrity rejection requeues with NO attempt burned, and
  the pre-stitch gate refuses corrupt spooled bytes.
- `TestResume`: the executor-level crash-resume path — a second
  coordinator over the same spool re-plans deterministically from the
  checkpoint, rehydrates verified shards DONE under the fresh run
  token, re-encodes corrupt ones, and respects resume_enabled and
  signature drift.
"""

import json
import os
import threading

import pytest

from thinvids_tpu.cluster import Coordinator, WorkerRegistry
from thinvids_tpu.cluster.jobs import Job
from thinvids_tpu.cluster.partstore import (PartIntegrityError, PartRef,
                                            PartStore)
from thinvids_tpu.cluster.remote import (RemoteExecutor, Shard,
                                         ShardBoard, pack_parts,
                                         unpack_parts)
from thinvids_tpu.core.config import DEFAULT_SETTINGS, Settings
from thinvids_tpu.core.status import ShardState
from thinvids_tpu.core.types import EncodedSegment, GopSpec, VideoMeta
from thinvids_tpu.obs import metrics as obs_metrics


def make_settings(**over):
    values = dict(DEFAULT_SETTINGS)
    values.update(over)
    return Settings(values=values)


def seg(index, payload=b"\0\0\1abc", start_frame=None, num_frames=2):
    return EncodedSegment(
        gop=GopSpec(index=index,
                    start_frame=(2 * index if start_frame is None
                                 else start_frame),
                    num_frames=num_frames),
        payload=payload, frame_sizes=(len(payload),))


def make_shard(sid="j0-0000", key="0000", job_id="j0", gop0=0, ngops=2,
               timeout_s=60.0):
    gops = tuple(GopSpec(index=gop0 + i, start_frame=2 * (gop0 + i),
                         num_frames=2) for i in range(ngops))
    return Shard(id=sid, key=key, job_id=job_id, input_path="/in/a.y4m",
                 meta=VideoMeta(width=64, height=48), gops=gops, qp=30,
                 gop_frames=2, timeout_s=timeout_s)


# the production chaos helper IS the test's corruption tool — one
# implementation of the "flip past the framing header" knowledge
# (tools/loadgen.py), so a framing change cannot silently leave the
# tests flipping the wrong region
from thinvids_tpu.tools.loadgen import flip_part_bit as flip_payload_bit


class TestPartStore:
    def test_spool_commit_read_roundtrip(self, tmp_path):
        store = PartStore(str(tmp_path / "spool"))
        try:
            segs = [seg(0), seg(1, b"\0\0\1defgh")]
            ref, tmp = store.spool("jobA", "0000", segs)
            assert os.path.exists(tmp) and not os.path.exists(ref.path)
            store.commit(ref, tmp)
            assert os.path.exists(ref.path) and not os.path.exists(tmp)
            back = store.read_part(ref)
            assert [s.payload for s in back] == [s.payload for s in segs]
            assert [s.gop for s in back] == [s.gop for s in segs]
            assert store.spool_bytes() == ref.nbytes > 0
            # the gauge follows the store's accounting
            assert obs_metrics.PART_SPOOL_BYTES.get() == \
                store.spool_bytes()
        finally:
            store.close()

    def test_discard_drops_uncommitted_temp(self, tmp_path):
        store = PartStore(str(tmp_path / "spool"))
        try:
            ref, tmp = store.spool("jobA", "0000", [seg(0)])
            store.discard(tmp)
            assert not os.path.exists(tmp)
            assert store.spool_bytes() == 0
        finally:
            store.close()

    def test_bit_flip_fails_verification(self, tmp_path):
        store = PartStore(str(tmp_path / "spool"))
        try:
            ref, tmp = store.spool("jobA", "0000", [seg(0)])
            store.commit(ref, tmp)
            flip_payload_bit(ref.path)
            assert not store.verify_part(ref)
            with pytest.raises(PartIntegrityError):
                store.read_part(ref)
            # verification OFF reads the (corrupt) bytes — the escape
            # hatch the part_integrity knob documents
            assert store.read_part(ref, verify=False)
        finally:
            store.close()

    def _plan(self, sig, keys):
        return {"sig": sig, "gop_frames": 2, "num_devices": 1,
                "plan_gops": [[i, 2 * i, 2, True]
                              for i in range(len(keys))],
                "shards": [{"key": k, "qp": 30,
                            "gops": [[i, 2 * i, 2, True]],
                            "timeout_s": 60.0, "rung": "",
                            "rung_width": 0, "rung_height": 0}
                           for i, k in enumerate(keys)]}

    def test_begin_job_retains_on_matching_sig(self, tmp_path):
        store = PartStore(str(tmp_path / "spool"))
        try:
            plan = self._plan("sigA", ["0000", "0001"])
            assert store.begin_job("jobA", plan) == {}
            ref, tmp = store.spool("jobA", "0000", [seg(0)])
            store.commit(ref, tmp)
            # same signature (the crash-resume case): record retained
            kept = store.begin_job("jobA", plan)
            assert set(kept) == {"0000"}
            assert kept["0000"].digests == ref.digests
            assert os.path.exists(ref.path)
            # replay agrees
            ck = store.load_job("jobA")
            assert ck.plan["sig"] == "sigA" and set(ck.done) == {"0000"}
        finally:
            store.close()

    def test_begin_job_resets_on_sig_drift(self, tmp_path):
        store = PartStore(str(tmp_path / "spool"))
        try:
            store.begin_job("jobA", self._plan("sigA", ["0000"]))
            ref, tmp = store.spool("jobA", "0000", [seg(0)])
            store.commit(ref, tmp)
            # operator changed qp → new signature: stale parts must
            # never rehydrate, and their spool files drop
            kept = store.begin_job("jobA", self._plan("sigB", ["0000"]))
            assert kept == {}
            assert not os.path.exists(ref.path)
            assert store.spool_bytes() == 0
        finally:
            store.close()

    def test_begin_job_reaps_orphan_spool_files(self, tmp_path):
        """A crash between rename and journal append leaves a part
        file no record names — begin_job sweeps it."""
        store = PartStore(str(tmp_path / "spool"))
        try:
            plan = self._plan("sigA", ["0000"])
            store.begin_job("jobA", plan)
            ref, tmp = store.spool("jobA", "0000", [seg(0)])
            os.replace(tmp, ref.path)       # renamed, never journaled
            store.begin_job("jobA", plan)
            assert not os.path.exists(ref.path)
        finally:
            store.close()

    def test_drop_done_retracts_record(self, tmp_path):
        store = PartStore(str(tmp_path / "spool"))
        try:
            plan = self._plan("sigA", ["0000"])
            store.begin_job("jobA", plan)
            ref, tmp = store.spool("jobA", "0000", [seg(0)])
            store.commit(ref, tmp)
            store.drop_done("jobA", "0000", ref)
            assert not os.path.exists(ref.path)
            assert store.load_job("jobA").done == {}
            # the retraction survives a replay (journaled, not RAM)
            assert store.begin_job("jobA", plan) == {}
        finally:
            store.close()

    def test_torn_journal_tail_replays_prefix(self, tmp_path):
        store = PartStore(str(tmp_path / "spool"))
        store.begin_job("jobA", self._plan("sigA", ["0000", "0001"]))
        ref, tmp = store.spool("jobA", "0000", [seg(0)])
        store.commit(ref, tmp)
        store.close()
        jpath = str(tmp_path / "spool" / "jobA.board.jsonl")
        with open(jpath, "ab") as fh:       # torn mid-append record
            fh.write(b'{"op": "done", "key": "0001", "pa')
        store2 = PartStore(str(tmp_path / "spool"))
        try:
            ck = store2.load_job("jobA")
            assert ck is not None and set(ck.done) == {"0000"}
        finally:
            store2.close()

    def test_clear_job_removes_everything(self, tmp_path):
        store = PartStore(str(tmp_path / "spool"))
        try:
            store.begin_job("jobA", self._plan("sigA", ["0000"]))
            ref, tmp = store.spool("jobA", "0000", [seg(0)])
            store.commit(ref, tmp)
            store.clear_job("jobA")
            assert store.load_job("jobA") is None
            assert not os.path.exists(ref.path)
            assert store.spool_bytes() == 0
        finally:
            store.close()

    def test_flock_exclusive_ownership(self, tmp_path):
        root = str(tmp_path / "spool")
        store = PartStore(root)
        with pytest.raises(RuntimeError):
            PartStore(root)
        store.close()
        PartStore(root).close()             # released: reopens cleanly

    def test_restart_rescans_spool_bytes(self, tmp_path):
        root = str(tmp_path / "spool")
        store = PartStore(root)
        ref, tmp = store.spool("jobA", "0000", [seg(0)])
        store.commit(ref, tmp)
        nbytes = store.spool_bytes()
        store.close()
        store2 = PartStore(root)
        try:
            assert store2.spool_bytes() == nbytes > 0
        finally:
            store2.close()


class TestWireDigests:
    def test_roundtrip_carries_digests(self):
        data = pack_parts([seg(0), seg(1)])
        hlen = int.from_bytes(data[:4], "big")
        header = json.loads(data[4:4 + hlen])
        assert all(len(r["sha256"]) == 64 for r in header["segments"])
        assert len(unpack_parts(data)) == 2

    def test_flipped_payload_bit_rejected(self):
        data = bytearray(pack_parts([seg(0, b"\0\0\1" + b"x" * 64)]))
        data[-10] ^= 0x01
        with pytest.raises(PartIntegrityError):
            unpack_parts(bytes(data))
        # verification off: the documented escape hatch still parses
        assert len(unpack_parts(bytes(data), verify=False)) == 1

    def test_pre_digest_frame_still_parses(self):
        """Old-format frames (no sha256 field) verify trivially —
        rolling upgrades must not reject a pre-digest worker."""
        segs = [seg(0)]
        data = bytearray(pack_parts(segs))
        hlen = int.from_bytes(data[:4], "big")
        header = json.loads(data[4:4 + hlen])
        for rec in header["segments"]:
            del rec["sha256"]
        new_header = json.dumps(header, separators=(",", ":")).encode()
        data = (len(new_header).to_bytes(4, "big") + new_header
                + bytes(data[4 + hlen:]))
        assert len(unpack_parts(bytes(data))) == 1


def make_board(tmp_path, clock=None, workers=("w1", "w2", "w3"), **over):
    from tests.test_remote import FakeClock

    clock = clock or FakeClock()
    snap = make_settings(pipeline_worker_count=1, **over)
    reg = WorkerRegistry(clock=clock)
    for hostname in workers:
        reg.heartbeat(hostname, metrics={"worker": True}, now=clock())
    coord = Coordinator(registry=reg, clock=clock,
                        settings_fn=lambda: snap)
    board = ShardBoard(coord, clock=clock,
                       spool_dir=str(tmp_path / "spool"))
    return board, coord, clock


class TestBoardSpool:
    def test_done_shard_holds_ref_not_bytes(self, tmp_path):
        """The board-memory fix made observable (ISSUE 13 satellite):
        after submit, the DONE shard's payload is NOT resident — only
        the PartRef — and take_segments reads it back from the
        spool."""
        board, coord, _ = make_board(tmp_path)
        board.add_job("j0", [make_shard()], max_attempts=3,
                      backoff_s=0.0, quarantine_after=3)
        desc = board.claim("w2")
        segs = [seg(0), seg(1, b"\0\0\1defgh")]
        assert board.submit_part(desc["id"], "w2", segs)
        shard = board._find_locked(desc["id"])
        assert shard.state is ShardState.DONE
        assert shard.segments == []             # un-pinned from RAM
        assert os.path.exists(shard.part_path)
        assert len(shard.part_digests) == 2
        snap = board.snapshot()
        assert snap["spool_bytes"] > 0
        assert snap["integrity_rejects"] == 0
        got = board.take_segments("j0")
        assert [s.payload for s in got] == [s.payload for s in segs]

    def test_reject_part_requeues_without_attempt_burn(self, tmp_path):
        board, coord, _ = make_board(tmp_path)
        board.add_job("j0", [make_shard()], max_attempts=3,
                      backoff_s=5.0, quarantine_after=3)
        desc = board.claim("w2")
        board.reject_part(desc["id"], "w2", "digest mismatch")
        shard = board._find_locked(desc["id"])
        assert shard.state is ShardState.PENDING
        assert shard.attempt == 0               # NO attempt burned
        assert shard.not_before == 0.0          # and no backoff
        assert board.snapshot()["integrity_rejects"] == 1
        # the same (healthy) worker may re-claim immediately, and its
        # quarantine streak is untouched
        w2 = {w.host: w for w in coord.registry.all()}["w2"]
        assert w2.consecutive_failures == 0
        assert board.claim("w2") is not None

    def test_persistent_rejection_escalates_to_failure(self, tmp_path):
        """A deterministically corrupting link must not livelock the
        job in a claim/encode/reject hot loop: past the free-reject
        budget the rejection routes through the normal failure path
        (attempt burned) until the job FAILS with attribution."""
        board, coord, _ = make_board(tmp_path)
        board.add_job("j0", [make_shard()], max_attempts=1,
                      backoff_s=0.0, quarantine_after=99)
        for _ in range(board.INTEGRITY_FREE_REJECTS):
            desc = board.claim("w2")
            board.reject_part(desc["id"], "w2", "flipped in transit")
            shard = board._find_locked("j0-0000")
            assert shard.attempt == 0           # transient flips: free
            assert shard.state is ShardState.PENDING
        desc = board.claim("w2")
        board.reject_part(desc["id"], "w2", "flipped in transit")
        shard = board._find_locked("j0-0000")
        assert shard.attempt == 1               # escalated: burned
        desc = board.claim("w2")
        board.reject_part(desc["id"], "w2", "flipped in transit")
        *_rest, failed, _host = board.job_progress("j0")
        assert "persistent part corruption" in failed

    def test_stale_reject_does_not_touch_new_holder(self, tmp_path):
        board, coord, clock = make_board(tmp_path)
        board.add_job("j0", [make_shard(timeout_s=10.0)], max_attempts=5,
                      backoff_s=0.0, quarantine_after=99)
        board.claim("w2")
        clock.advance(11.0)
        coord.registry.heartbeat("w3", now=clock())
        board.requeue_expired()
        board.claim("w3")
        board.reject_part("j0-0000", "w2", "late corrupt upload")
        shard = board._find_locked("j0-0000")
        assert shard.state is ShardState.ASSIGNED
        assert shard.assigned_host == "w3"      # w3's lease intact

    def test_corrupt_spool_blocks_stitch(self, tmp_path):
        """The pre-stitch gate: a bit that flipped on the spool disk
        after accept fails the collect — corrupt bytes can never reach
        concat."""
        board, coord, _ = make_board(tmp_path)
        board.add_job("j0", [make_shard()], max_attempts=3,
                      backoff_s=0.0, quarantine_after=3)
        desc = board.claim("w2")
        board.submit_part(desc["id"], "w2", [seg(0), seg(1)])
        shard = board._find_locked(desc["id"])
        flip_payload_bit(shard.part_path)
        with pytest.raises(RuntimeError, match="digest"):
            board.take_shards("j0")

    def test_duplicate_after_done_discards_spool_temp(self, tmp_path):
        board, coord, _ = make_board(tmp_path)
        board.add_job("j0", [make_shard()], max_attempts=3,
                      backoff_s=0.0, quarantine_after=3)
        desc = board.claim("w2")
        segs = [seg(0), seg(1)]
        assert board.submit_part(desc["id"], "w2", segs)
        before = board.parts.spool_bytes()
        assert not board.submit_part(desc["id"], "w3", segs)
        assert board.parts.spool_bytes() == before
        # no stray temp files beside the committed part
        spool_dir = os.path.dirname(
            board._find_locked(desc["id"]).part_path)
        assert [f for f in os.listdir(spool_dir)
                if f.endswith(".tmp")] == []


# ---------------------------------------------------------------------------
# executor-level crash-resume (in-process)
# ---------------------------------------------------------------------------


def make_rig(tmp_path, snap, job_id="deadbeefcafe0000",
             spool="spool", workers=4):
    reg = WorkerRegistry()
    for i in range(workers):
        reg.heartbeat(f"w{i:02d}", metrics={"worker": True})
    coord = Coordinator(registry=reg, settings_fn=lambda: snap)
    execu = RemoteExecutor(coord, output_dir=str(tmp_path / "lib"),
                           sync=True, poll_s=0.02,
                           spool_dir=str(tmp_path / spool))
    return coord, execu


def resume_settings(**over):
    base = dict(gop_frames=2, qp=30, heartbeat_throttle_s=0.0,
                remote_plan_devices=4, remote_shard_gops=1,
                remote_no_worker_grace_s=5.0)
    base.update(over)
    return make_settings(**base)


@pytest.fixture
def crashed_run(tmp_path):
    """A job whose first run accepted 2 of 4 shards, then the
    coordinator 'crashed' (store closed without collect). Yields
    (tmp_path, job, meta, settings, completed plan keys)."""
    from tests.test_remote import write_clip

    clip = tmp_path / "clip.y4m"
    meta = write_clip(clip, n=8)        # 4 GOPs → 4 single-GOP shards
    snap = resume_settings()
    coord, execu = make_rig(tmp_path, snap)
    job = Job(id="deadbeefcafe0000", input_path=str(clip), meta=meta)
    plan, shards, reused = execu._plan_or_resume(
        job, "aaaa1111", snap, meta, 8)
    assert reused == 0 and len(shards) == 4
    board = execu.board
    board.add_job(job.id, shards, max_attempts=3, backoff_s=0.0,
                  quarantine_after=3, token="aaaa1111")
    done_keys = []
    for host in ("w01", "w02"):
        desc = board.claim(host)
        from thinvids_tpu.cluster.remote import encode_shard
        from thinvids_tpu.ingest.decode import read_video

        segs = encode_shard(desc, read_video(str(clip))[1])
        assert board.submit_part(desc["id"], host, segs)
        done_keys.append(desc["id"].split("-")[-1])
    execu.board.parts.close()           # the 'crash': flock released,
    yield tmp_path, job, meta, snap, done_keys   # nothing collected


class TestResume:
    def test_resume_rehydrates_verified_shards(self, crashed_run):
        tmp_path, job, meta, snap, done_keys = crashed_run
        coord2, execu2 = make_rig(tmp_path, snap)
        plan, shards, reused = execu2._plan_or_resume(
            job, "bbbb2222", snap, meta, 8)
        assert reused == 2
        by_key = {s.key: s for s in shards}
        for key in done_keys:
            s = by_key[key]
            assert s.state is ShardState.DONE and s.resumed
            assert s.segments == [] and os.path.exists(s.part_path)
            assert "bbbb22" in s.id     # fresh run token in the id
        open_keys = set(by_key) - set(done_keys)
        assert all(by_key[k].state is ShardState.PENDING
                   for k in open_keys)
        assert execu2.board.snapshot()["resumed"] == 2
        execu2.board.parts.close()

    def test_resume_drops_corrupt_spool(self, crashed_run):
        tmp_path, job, meta, snap, done_keys = crashed_run
        # chaos: one spooled part rots between crash and restart
        spool_dir = str(tmp_path / "spool" / job.id)
        victim = os.path.join(spool_dir, f"{done_keys[0]}.part")
        flip_payload_bit(victim)
        coord2, execu2 = make_rig(tmp_path, snap)
        plan, shards, reused = execu2._plan_or_resume(
            job, "bbbb2222", snap, meta, 8)
        assert reused == 1              # only the intact part
        by_key = {s.key: s for s in shards}
        assert by_key[done_keys[0]].state is ShardState.PENDING
        assert by_key[done_keys[0]].attempt == 0    # no attempt burn
        assert by_key[done_keys[1]].state is ShardState.DONE
        assert execu2.board.snapshot()["integrity_rejects"] == 1
        # the retraction is durable: a THIRD restart re-encodes too
        execu2.board.parts.close()
        coord3, execu3 = make_rig(tmp_path, snap)
        _p, shards3, reused3 = execu3._plan_or_resume(
            job, "cccc3333", snap, meta, 8)
        assert reused3 == 1
        execu3.board.parts.close()

    def test_resume_disabled_replans_fresh(self, crashed_run):
        tmp_path, job, meta, snap, _done = crashed_run
        snap2 = resume_settings(resume_enabled=False)
        coord2, execu2 = make_rig(tmp_path, snap2)
        _p, shards, reused = execu2._plan_or_resume(
            job, "bbbb2222", snap2, meta, 8)
        assert reused == 0
        assert all(s.state is ShardState.PENDING for s in shards)
        execu2.board.parts.close()

    def test_signature_drift_resets_checkpoint(self, crashed_run):
        tmp_path, job, meta, snap, _done = crashed_run
        snap2 = resume_settings(qp=35)  # different encoded bytes
        coord2, execu2 = make_rig(tmp_path, snap2)
        _p, shards, reused = execu2._plan_or_resume(
            job, "bbbb2222", snap2, meta, 8)
        assert reused == 0
        assert all(s.state is ShardState.PENDING for s in shards)
        # the stale parts dropped with the reset
        assert execu2.board.parts.spool_bytes() == 0
        execu2.board.parts.close()

    def test_resumed_plan_ignores_live_worker_count(self, crashed_run):
        """Deterministic re-plan: the resumed run re-creates the
        CHECKPOINTED plan even when the farm came back a different
        size (planning from the live count would shift shard
        boundaries and orphan every spooled part)."""
        tmp_path, job, meta, snap, done_keys = crashed_run
        coord2, execu2 = make_rig(tmp_path, snap, workers=1)
        plan, shards, reused = execu2._plan_or_resume(
            job, "bbbb2222", snap, meta, 8)
        assert len(shards) == 4 and reused == 2
        execu2.board.parts.close()
