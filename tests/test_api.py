"""HTTP API tests: the full job lifecycle driven over a live socket.

Mirrors the reference's route surface contracts
(/root/reference/manager/app.py:1919-2400, 2836-3051).
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from thinvids_tpu.api import ApiServer
from thinvids_tpu.cluster.coordinator import Coordinator
from thinvids_tpu.cluster.executor import LocalExecutor
from thinvids_tpu.core.config import reset_live_settings, update_live_settings
from thinvids_tpu.core.status import Status
from thinvids_tpu.core.types import Frame, VideoMeta
from thinvids_tpu.io.y4m import write_y4m


def make_clip(path, n=6, w=48, h=32):
    frames = [Frame(np.full((h, w), 50 + 10 * i, np.uint8),
                    np.full((h // 2, w // 2), 110, np.uint8),
                    np.full((h // 2, w // 2), 140, np.uint8))
              for i in range(n)]
    meta = VideoMeta(width=w, height=h, fps_num=30, fps_den=1, num_frames=n)
    write_y4m(path, meta, frames)


def call(url, method="GET", body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


@pytest.fixture
def api(tmp_path):
    reset_live_settings()
    co = Coordinator()
    # an always-available worker pool so dispatch gates pass
    for i in range(6):
        co.registry.heartbeat(f"w{i}")
    update_live_settings({"pipeline_worker_count": 6,
                          "min_idle_workers": 0})
    execu = LocalExecutor(co, str(tmp_path / "out"), sync=False)
    co._launcher = execu.launch
    server = ApiServer(co).start()
    yield server, co, execu, tmp_path
    server.stop()
    reset_live_settings()


class TestUi:
    def test_dashboard_served_at_root(self, api):
        server, co, execu, tmp_path = api
        req = urllib.request.Request(server.url + "/")
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/html")
            page = resp.read().decode()
        # the page drives the same JSON routes the tests do
        for route in ("/jobs", "/add_job", "/nodes_data",
                      "/metrics_snapshot", "/activity", "/settings"):
            assert route in page
        assert "thinvids" in page


class TestBrowsePreviewStamp:
    def test_browse_list_traversal_safe(self, api, tmp_path):
        server, co, execu, _ = api
        server.browse_roots["watch"] = str(tmp_path)
        (tmp_path / "sub").mkdir()
        (tmp_path / "a.y4m").write_bytes(b"x")
        code, out = call(f"{server.url}/browse/list?root=watch")
        assert code == 200
        names = {e["name"]: e for e in out["entries"]}
        assert names["sub"]["dir"] is True
        assert names["a.y4m"]["size"] == 1
        code, out = call(f"{server.url}/browse/list?root=watch&path=../..")
        assert code == 400
        code, out = call(f"{server.url}/browse/list?root=nope")
        assert code == 400

    def test_preview_streams_output(self, api):
        server, co, execu, tmp_path = api
        clip = tmp_path / "movie.y4m"
        make_clip(str(clip))
        code, job = call(f"{server.url}/add_job", "POST",
                         {"input_path": str(clip), "auto_start": False})
        jid = job["id"]
        code, _ = call(f"{server.url}/preview/{jid}")
        assert code == 404                       # no output yet
        call(f"{server.url}/start_job/{jid}", "POST")
        execu.join(timeout=120)
        req = urllib.request.Request(f"{server.url}/preview/{jid}")
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"] == "video/mp4"
            data = resp.read()
        assert data[4:8] == b"ftyp"

    def test_stamp_restore_does_not_resurrect_stopped_job(self, api):
        """An operator stop landing while the stamp thread runs must
        win: the finally-restore only rewrites a job that is STILL
        STAMPING (stop-wins, same property as the reserve guard)."""
        from thinvids_tpu.api.server import _restore_after_stamp

        server, co, execu, tmp_path = api
        clip = tmp_path / "movie.y4m"
        make_clip(str(clip))
        code, job = call(f"{server.url}/add_job", "POST",
                         {"input_path": str(clip), "auto_start": False})
        jid = job["id"]
        # the stamp thread set STAMPING...
        co.store.update(jid, lambda j: setattr(
            j, "status", Status.STAMPING))
        # ...the operator stops mid-stamp...
        co.stop_job(jid)
        assert co.store.get(jid).status is Status.STOPPED
        # ...and the stamp thread's restore must NOT resurrect it
        _restore_after_stamp(co, jid, Status.READY)
        assert co.store.get(jid).status is Status.STOPPED
        # while an undisturbed STAMPING job restores normally
        co.store.update(jid, lambda j: setattr(
            j, "status", Status.STAMPING))
        _restore_after_stamp(co, jid, Status.READY)
        assert co.store.get(jid).status is Status.READY

    def test_stamp_rejected_job_refused(self, api):
        """REJECTED absorbs (the declared job machine): the stamp flow
        must not put an admission-rejected job back to work."""
        server, co, execu, tmp_path = api
        clip = tmp_path / "movie.y4m"
        make_clip(str(clip))
        code, job = call(f"{server.url}/add_job", "POST",
                         {"input_path": str(clip), "auto_start": False})
        jid = job["id"]
        co.store.update(jid, lambda j: setattr(
            j, "status", Status.REJECTED))
        code, out = call(f"{server.url}/stamp_job/{jid}", "POST", {})
        assert code == 409
        assert co.store.get(jid).status is Status.REJECTED

    def test_stamp_entry_guard_is_atomic_with_the_write(self, api):
        """The STAMPING entry re-checks under the store lock: a job
        that turned active (scheduler reserve) after the handler's
        snapshot must 409 instead of taking the undeclared
        STARTING→STAMPING edge."""
        server, co, execu, tmp_path = api
        clip = tmp_path / "movie.y4m"
        make_clip(str(clip))
        code, job = call(f"{server.url}/add_job", "POST",
                         {"input_path": str(clip), "auto_start": False})
        jid = job["id"]
        co.store.update(jid, lambda j: setattr(
            j, "status", Status.STARTING))
        code, out = call(f"{server.url}/stamp_job/{jid}", "POST", {})
        assert code == 409
        assert co.store.get(jid).status is Status.STARTING

    def test_stamp_job_creates_stamped_copy(self, api):
        from thinvids_tpu.io.y4m import read_y4m
        from thinvids_tpu.tools.stamp import read_stamp, stamp_width_px

        server, co, execu, tmp_path = api
        clip = tmp_path / "movie.y4m"
        # wide enough for the 16-bit stamp
        make_clip(str(clip), n=4, w=stamp_width_px(), h=32)
        code, job = call(f"{server.url}/add_job", "POST",
                         {"input_path": str(clip), "auto_start": False})
        jid = job["id"]
        code, out = call(f"{server.url}/stamp_job/{jid}", "POST", {})
        assert code == 200 and out["status"] == "ready"
        stamped = tmp_path / "movie.stamped.y4m"
        assert stamped.exists()
        _meta, frames = read_y4m(str(stamped))
        assert [read_stamp(f.y) for f in frames] == [0, 1, 2, 3]
        # a NEW job for the stamped file was registered
        code, listing = call(f"{server.url}/jobs")
        paths = {j["input_path"] for j in listing["jobs"]}
        assert str(stamped) in paths

    def test_stamp_job_dedups_on_target_path(self, api):
        # Repeated POST /stamp_job refreshes the stamped file but must
        # not register a second job for the same .stamped.y4m target.
        from thinvids_tpu.tools.stamp import stamp_width_px

        server, co, execu, tmp_path = api
        clip = tmp_path / "movie.y4m"
        make_clip(str(clip), n=2, w=stamp_width_px(), h=32)
        code, job = call(f"{server.url}/add_job", "POST",
                         {"input_path": str(clip), "auto_start": False})
        jid = job["id"]
        for _ in range(3):
            code, _ = call(f"{server.url}/stamp_job/{jid}", "POST", {})
            assert code == 200
        stamped = str(tmp_path / "movie.stamped.y4m")
        dupes = [j for j in co.store.list() if j.input_path == stamped]
        assert len(dupes) == 1

    def test_metrics_snapshot_carries_stage_ms(self, api):
        server, *_ = api
        code, out = call(f"{server.url}/metrics_snapshot")
        assert code == 200
        # the live encode-stage breakdown rides the snapshot (empty
        # aggregate is fine when no encoder has run in this process)
        assert isinstance(out["stage_ms"], dict)


class TestLifecycle:
    def test_full_job_lifecycle_over_http(self, api):
        server, co, execu, tmp_path = api
        clip = tmp_path / "movie.y4m"
        make_clip(str(clip))

        code, job = call(f"{server.url}/add_job", "POST",
                         {"input_path": str(clip), "auto_start": False})
        assert code == 201
        jid = job["id"]
        assert job["status"] == "ready"
        assert job["meta"]["num_frames"] == 6

        code, listing = call(f"{server.url}/jobs")
        assert code == 200 and listing["total"] == 1

        code, started = call(f"{server.url}/start_job/{jid}", "POST")
        assert code == 200
        execu.join(timeout=120)

        code, props = call(f"{server.url}/job_properties/{jid}")
        assert code == 200
        assert props["job"]["status"] == "done"
        assert props["job"]["output_path"].endswith("movie.mp4")
        assert props["job"]["parts_done"] >= 1
        assert any("done" in line for line in props["activity"])

        code, feed = call(f"{server.url}/activity")
        assert code == 200 and feed["events"]

        code, _ = call(f"{server.url}/delete_job/{jid}", "DELETE")
        assert code == 200
        code, listing = call(f"{server.url}/jobs")
        assert listing["total"] == 0

    def test_stop_and_restart(self, api):
        server, co, execu, tmp_path = api
        clip = tmp_path / "movie.y4m"
        make_clip(str(clip))
        code, job = call(f"{server.url}/add_job", "POST",
                         {"input_path": str(clip), "auto_start": False})
        jid = job["id"]
        code, stopped = call(f"{server.url}/stop_job/{jid}", "POST")
        assert stopped["status"] == "stopped"
        code, restarted = call(f"{server.url}/restart_job/{jid}", "POST")
        assert restarted["status"] in ("waiting", "starting", "running",
                                       "done")
        execu.join(timeout=120)
        code, props = call(f"{server.url}/job_properties/{jid}")
        assert props["job"]["status"] == "done"


class TestRoutes:
    def test_add_job_validation(self, api):
        server, *_ = api
        code, err = call(f"{server.url}/add_job", "POST", {})
        assert code == 400 and "input_path" in err["error"]
        code, err = call(f"{server.url}/add_job", "POST",
                         {"input_path": "/nonexistent.y4m"})
        assert code == 422

    def test_unknown_routes_and_jobs(self, api):
        server, *_ = api
        code, err = call(f"{server.url}/nope")
        assert code == 404
        code, err = call(f"{server.url}/job_properties/deadbeef")
        assert code == 404

    def test_jobs_filter_sort_paginate(self, api):
        server, co, execu, tmp_path = api
        for i in range(3):
            clip = tmp_path / f"c{i}.y4m"
            make_clip(str(clip), n=2)
            call(f"{server.url}/add_job", "POST",
                 {"input_path": str(clip), "auto_start": False})
        code, out = call(
            f"{server.url}/jobs?status=ready&sort=input_path&order=asc"
            f"&page=1&page_size=2")
        assert code == 200
        assert out["total"] == 3 and len(out["jobs"]) == 2
        names = [j["input_path"] for j in out["jobs"]]
        assert names == sorted(names)
        code, out = call(f"{server.url}/jobs?sort=bogus")
        assert code == 400

    def test_job_settings_blocked_while_active(self, api):
        server, co, execu, tmp_path = api
        clip = tmp_path / "movie.y4m"
        make_clip(str(clip))
        code, job = call(f"{server.url}/add_job", "POST",
                         {"input_path": str(clip), "auto_start": False})
        jid = job["id"]
        code, out = call(f"{server.url}/job_settings/{jid}", "POST",
                         {"qp": 33})
        assert code == 200 and out["settings"] == {"qp": 33}
        co.store.update(jid, lambda j: setattr(j, "status", Status.RUNNING))
        code, err = call(f"{server.url}/job_settings/{jid}", "POST",
                         {"qp": 20})
        assert code == 409

    def test_job_settings_validated_at_write(self, api):
        server, co, execu, tmp_path = api
        clip = tmp_path / "movie.y4m"
        make_clip(str(clip))
        code, job = call(f"{server.url}/add_job", "POST",
                         {"input_path": str(clip), "auto_start": False})
        jid = job["id"]
        # malformed value -> clamped to the key's default at WRITE time
        # (the config tier is deliberately lenient, mirroring the
        # reference's POST /settings clamping) — what's stored is what
        # dispatch will use, never the raw garbage
        code, out = call(f"{server.url}/job_settings/{jid}", "POST",
                         {"gop_frames": "abc"})
        assert code == 200 and out["settings"] == {"gop_frames": 32}
        # unknown key -> 400 (overlay would silently drop it otherwise)
        code, err = call(f"{server.url}/job_settings/{jid}", "POST",
                         {"no_such_knob": 1})
        assert code == 400
        # valid values are coerced/clamped exactly like the live tier
        code, out = call(f"{server.url}/job_settings/{jid}", "POST",
                         {"gop_frames": "16"})
        assert code == 200 and out["settings"] == {"gop_frames": 16}

    def test_nodes_and_metrics(self, api):
        server, co, *_ = api
        co.registry.heartbeat("w0", metrics={"hbm_used": 0.5})
        code, out = call(f"{server.url}/nodes_data")
        assert code == 200
        hosts = {n["host"] for n in out["nodes"]}
        assert "w0" in hosts and len(hosts) == 6
        code, _ = call(f"{server.url}/nodes/disable/w0", "POST",
                       {"reason": "flaky"})
        code, out = call(f"{server.url}/nodes_data")
        w0 = next(n for n in out["nodes"] if n["host"] == "w0")
        assert w0["disabled"] and w0["quarantine_reason"] == "flaky"
        call(f"{server.url}/nodes/enable/w0", "POST")
        code, out = call(f"{server.url}/metrics_snapshot")
        assert out["metrics"]["w0"]["hbm_used"] == 0.5
        code, _ = call(f"{server.url}/nodes/delete/w5", "DELETE")
        assert code == 200
        code, _ = call(f"{server.url}/nodes/delete/w5", "DELETE")
        assert code == 404

    def test_settings_roundtrip_with_clamps(self, api):
        server, *_ = api
        code, out = call(f"{server.url}/settings")
        assert code == 200 and "qp" in out["settings"]
        code, out = call(f"{server.url}/settings", "POST", {"qp": 99})
        assert code == 200
        code, out = call(f"{server.url}/settings")
        assert 0 <= out["settings"]["qp"] <= 51    # clamped

    def test_health(self, api):
        server, *_ = api
        code, out = call(f"{server.url}/health")
        assert code == 200 and out["ok"]
