"""Tenant namespaces and weighted fair-share math.

A tenant is a job namespace: the ``tenant`` per-job setting when set,
else a ``<tenant>__<name>`` prefix on the input filename (the
watch-folder analog of the ``.ladder``/``.live`` stem-suffix
conventions — a drop named ``acme__clip.y4m`` belongs to tenant
``acme``), else the shared ``default`` namespace.

Fair share is weighted max-min over *current usage*: the
``tenant_shares`` setting (``"acme:3,bravo:1"``) assigns weights
(unlisted tenants weigh 1), and both admission points — the
coordinator's dispatch pass and the ShardBoard's claim — pick, within
a QoS priority class, the candidate whose tenant has the LOWEST
usage÷share ratio right now. One tenant flooding the queue therefore
cannot starve the farm: its backlog only competes for its own share,
and an idle tenant's first job always wins the next slot.

jax-free by contract (imported by cluster/ control-plane modules).
"""

from __future__ import annotations

import os
import re
from typing import Mapping

#: the shared namespace jobs land in when nothing names a tenant
DEFAULT_TENANT = "default"

#: filename convention: ``<tenant>__<rest>`` (double underscore so
#: ordinary single-underscore names never grow a surprise tenant)
_NAME_RE = re.compile(r"^(?P<tenant>[a-z0-9][a-z0-9_-]{0,31})__(?=.)")

_CLEAN_RE = re.compile(r"[^a-z0-9_-]+")


def clean_tenant(raw: object) -> str:
    """Sanitize a tenant label: lowercase, [a-z0-9_-], max 32 chars;
    empty/invalid input falls back to the default namespace. Shared by
    the config clamp and the name parser so every surface agrees."""
    text = _CLEAN_RE.sub("", str(raw or "").strip().lower())[:32]
    return text or DEFAULT_TENANT


def tenant_of(input_path: str, explicit: object = None) -> str:
    """Resolve a job's tenant: explicit (per-job ``tenant`` setting)
    wins, else the ``<tenant>__name`` filename prefix, else default."""
    if explicit:
        return clean_tenant(explicit)
    stem = os.path.splitext(os.path.basename(input_path or ""))[0].lower()
    m = _NAME_RE.match(stem)
    if m:
        return clean_tenant(m.group("tenant"))
    return DEFAULT_TENANT


def parse_tenant_shares(spec: object) -> dict[str, float]:
    """``"acme:3,bravo:1"`` → {"acme": 3.0, "bravo": 1.0}. Bad entries
    are dropped; non-positive weights are floored at a tiny positive
    value (a zero share would make the usage ratio infinite and
    starve the tenant outright, which is an operator error, not a
    scheduling mode)."""
    shares: dict[str, float] = {}
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, weight = part.partition(":")
        tenant = clean_tenant(name)
        try:
            w = float(weight) if weight else 1.0
        except ValueError:
            continue
        shares[tenant] = max(0.001, w)
    return shares


def render_tenant_shares(spec: object) -> str:
    """Canonical re-render for the config clamp (stable ordering, so
    the settings surface shows exactly what the scheduler parses)."""
    shares = parse_tenant_shares(spec)
    return ",".join(f"{t}:{shares[t]:g}" for t in sorted(shares))


def share_of(shares: Mapping[str, float], tenant: str) -> float:
    return float(shares.get(tenant, 1.0))


def fair_usage(shares: Mapping[str, float],
               usage: Mapping[str, float], tenant: str) -> float:
    """The scheduling key: current usage normalized by the tenant's
    weight. Lower = more underserved = next in line."""
    return float(usage.get(tenant, 0.0)) / share_of(shares, tenant)
