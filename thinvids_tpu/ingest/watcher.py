"""Watch-folder ingest: processed ledger, size stabilization, discovery.

Port of the reference watcher's semantics
(/root/reference/manager/watcher.py):

- `FileLedger` ≙ FileProcessedStore (watcher.py:73-266): a durable
  rel_path → "size:mtime_ns" map as JSON lines, flock-serialized
  appends + fsync, mtime-triggered external-change reload, legacy
  path-only lines adopted lazily.
- `WatchIngester` ≙ periodic_scanner + submit_job_if_stable
  (watcher.py:351-452, 586-673): a file is submitted only after its
  signature has been identical for `stable_checks` consecutive scans
  (the reference polled size 5x at 10 s; here stability is measured in
  scan ticks, which makes tests deterministic),
  deduped through the ledger (marked synchronously on accept).
- `bootstrap_if_first_run` ≙ bootstrap_processed_if_first_run
  (watcher.py:482-503): an empty ledger adopts every existing file
  without submitting, so a fresh deployment doesn't re-transcode the
  whole library.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Iterable

from .decode import supported_exts as decode_supported_exts
from .tail import is_live_name

try:
    import fcntl
except ImportError:                      # non-POSIX: best-effort locking
    fcntl = None


def file_signature(path: str) -> str:
    st = os.stat(path)
    return f"{st.st_size}:{st.st_mtime_ns}"


class FileLedger:
    """Durable processed-file ledger (rel_path → signature)."""

    LEGACY_SIG = ""

    def __init__(self, path: str) -> None:
        self.path = path
        self._entries: dict[str, str] = {}
        self._loaded_mtime_ns: int | None = None
        self._lock = threading.Lock()
        self.reload_if_changed()

    # -- reading -------------------------------------------------------

    def _load(self) -> None:
        entries: dict[str, str] = {}
        try:
            with open(self.path, "r", encoding="utf-8") as fp:
                for line in fp:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        # legacy path-only line (reference
                        # watcher.py:155-170): known, signature unknown
                        entries[line] = self.LEGACY_SIG
                        continue
                    if isinstance(rec, dict) and "path" in rec:
                        entries[str(rec["path"])] = str(rec.get("sig", ""))
                    else:
                        entries[line] = self.LEGACY_SIG
            self._loaded_mtime_ns = os.stat(self.path).st_mtime_ns
        except FileNotFoundError:
            self._loaded_mtime_ns = None
        self._entries = entries

    def reload_if_changed(self) -> bool:
        """Re-read the ledger if another writer changed it on disk."""
        with self._lock:
            try:
                mtime = os.stat(self.path).st_mtime_ns
            except FileNotFoundError:
                mtime = None
            if mtime != self._loaded_mtime_ns:
                self._load()
                return True
            return False

    def __len__(self) -> int:
        return len(self._entries)

    def entries(self) -> dict[str, str]:
        return dict(self._entries)

    def state(self, rel_path: str, sig: str) -> str:
        """'missing' | 'legacy' | 'matched' | 'changed' for a file."""
        have = self._entries.get(rel_path)
        if have is None:
            return "missing"
        if have == self.LEGACY_SIG:
            return "legacy"
        return "matched" if have == sig else "changed"

    # -- writing -------------------------------------------------------

    def mark(self, rel_path: str, sig: str) -> None:
        self.mark_many([(rel_path, sig)])

    def mark_many(self, items) -> None:
        """Append {path, sig} lines under ONE flock + fsync (reference
        watcher.py:113-124; manager/app.py:859-870 uses the same
        protocol for manual submissions). Batching matters for
        bootstrap over a large library — one fsync, not one per file."""
        items = list(items)
        if not items:
            return
        payload = "".join(
            json.dumps({"path": rel, "sig": sig},
                       separators=(",", ":")) + "\n"
            for rel, sig in items)
        with self._lock:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(self.path, "a", encoding="utf-8") as fp:
                if fcntl is not None:
                    fcntl.flock(fp.fileno(), fcntl.LOCK_EX)
                try:
                    fp.write(payload)
                    fp.flush()
                    os.fsync(fp.fileno())
                finally:
                    if fcntl is not None:
                        fcntl.flock(fp.fileno(), fcntl.LOCK_UN)
            for rel, sig in items:
                self._entries[rel] = sig
            try:
                self._loaded_mtime_ns = os.stat(self.path).st_mtime_ns
            except FileNotFoundError:
                pass


class WatchIngester:
    """Scans a watch root and submits stabilized, unprocessed files.

    `submit(abs_path, state) -> bool` is the injection point — in
    production :func:`coordinator_submitter`; in tests a recording
    stub. `state` is the ledger verdict for the file ('missing' or
    'changed'), so the submitter can distinguish a first sighting from
    a re-drop with new content. A True return marks the file processed
    in the ledger.
    """

    # Watch exactly what the decode stage can ingest — submitting a
    # file the probe/decoder rejects would never mark the ledger and
    # retry forever. Derived, not hand-synced: widening decode._READERS
    # widens the watch set automatically.
    DEFAULT_EXTS = decode_supported_exts()

    def __init__(self, watch_dir: str, ledger: FileLedger,
                 submit: Callable[[str], bool],
                 exts: Iterable[str] = DEFAULT_EXTS,
                 stable_checks: int = 3) -> None:
        self.watch_dir = os.path.abspath(watch_dir)
        self.ledger = ledger
        self.submit = submit
        self.exts = tuple(e.lower() for e in exts)
        self.stable_checks = max(1, int(stable_checks))
        #: rel_path → (last signature, consecutive identical scans)
        self._stability: dict[str, tuple[str, int]] = {}
        #: serializes whole scans: run() loops on a watcher thread
        #: while scan_once() is public API — two interleaved scans
        #: would double-submit a just-stabilized file between its
        #: submit and its ledger mark (`cli.py check` TVT-T001)
        self._scan_lock = threading.Lock()

    # -- discovery -----------------------------------------------------

    def _discover(self) -> dict[str, str]:
        """rel_path → signature for every candidate file on disk."""
        found: dict[str, str] = {}
        for root, _dirs, files in os.walk(self.watch_dir):
            for name in files:
                if not name.lower().endswith(self.exts):
                    continue
                if name.startswith("."):
                    continue
                abs_path = os.path.join(root, name)
                try:
                    sig = file_signature(abs_path)
                except OSError:
                    continue                     # vanished mid-scan
                found[os.path.relpath(abs_path, self.watch_dir)] = sig
        return found

    def bootstrap_if_first_run(self) -> int:
        """Empty ledger → adopt every existing file without submitting
        (reference watcher.py:482-503). Returns files adopted."""
        self.ledger.reload_if_changed()
        if len(self.ledger):
            return 0
        found = self._discover()
        self.ledger.mark_many(sorted(found.items()))
        return len(found)

    # -- scanning ------------------------------------------------------

    def scan_once(self) -> list[str]:
        """One discovery pass. Returns the rel paths submitted.
        Serialized: concurrent calls run one after the other."""
        with self._scan_lock:
            return self._scan_once_locked()

    def _scan_once_locked(self) -> list[str]:
        self.ledger.reload_if_changed()
        found = self._discover()
        submitted: list[str] = []

        # drop stability state for files that disappeared
        for rel in list(self._stability):
            if rel not in found:
                del self._stability[rel]

        for rel, sig in sorted(found.items()):
            state = self.ledger.state(rel, sig)
            if state == "matched":
                continue
            if state == "legacy":
                # adopt the current signature without re-transcoding
                # (lazy legacy adoption, reference watcher.py:155-170)
                self.ledger.mark(rel, sig)
                continue

            # live-named drops are INGEST STREAMS, not settled files: a
            # growing source never passes the stability gate (its size
            # changes every scan by design), so `.live.` names submit
            # on first sighting and the tail source follows the growth
            # (ingest/tail.py — the watch-folder-as-ingest model
            # generalized to a file that never settles)
            if not is_live_name(rel):
                prev_sig, streak = self._stability.get(rel, (None, 0))
                streak = streak + 1 if sig == prev_sig else 1
                self._stability[rel] = (sig, streak)
                if streak < self.stable_checks:
                    continue                     # still stabilizing

            abs_path = os.path.join(self.watch_dir, rel)
            try:
                accepted = self.submit(abs_path, state)
            except Exception:                    # noqa: BLE001 - keep scanning
                accepted = False
            if accepted:
                # Mark the signature that was OBSERVED stable: if the
                # file changed while the submit ran, the next scan sees
                # 'changed' and requeues the final content.
                self.ledger.mark(rel, sig)
                self._stability.pop(rel, None)   # live names never
                                                 # entered stabilization
                submitted.append(rel)
        return submitted

    def run(self, interval_s: float = 60.0,
            stop: threading.Event | None = None) -> None:
        """Blocking scan loop (the reference scanned every 60 s,
        watcher.py:586-614)."""
        stop = stop or threading.Event()
        while not stop.is_set():
            self.scan_once()
            stop.wait(interval_s)


def coordinator_submitter(coordinator, activity_host: str = "watcher"):
    """submit() implementation targeting an in-process Coordinator:
    probe → add_job (the reference POSTed to /add_job,
    watcher.py:415-428). Unprobeable files are recorded in the activity
    feed and MARKED processed (True) — the reference likewise ledgered
    files whose /add_job came back REJECTED; returning False would
    retry a corrupt file on every scan forever."""
    from .probe import ProbeError, probe_video

    def submit(abs_path: str, state: str = "missing") -> bool:
        # A growing live source keeps changing its ledger signature on
        # every scan; once its live job is registered and not terminal,
        # each new sighting is EXPECTED GROWTH, not a re-drop — ledger
        # the new signature (True) and leave the running tail alone.
        if is_live_name(abs_path) and any(
                j.input_path == abs_path and not j.status.is_terminal
                for j in coordinator.store):
            return True
        try:
            meta = probe_video(abs_path)
        except ProbeError as exc:
            if is_live_name(abs_path):
                # live drop whose header isn't on disk yet: retry on a
                # later scan rather than blacklisting the stream
                return False
            if isinstance(exc.__cause__, OSError):
                # transient I/O (NFS hiccup, EACCES-until-chmod): retry
                # on a later scan — ledgering now would blacklist the
                # file forever since its signature won't change
                return False
            coordinator.activity.emit(
                "reject", f"unprobeable, skipped: {exc}",
                host=activity_host)
            return True
        # A job already registered for this path (manual /add_job, stamp
        # copies written into the watch tree) must not re-queue:
        # returning True ledgers it, the analog of the reference manager
        # writing the watcher ledger for manual submissions
        # (_mark_watcher_processed, app.py:828-870). BUT a re-drop the
        # ledger flags as 'changed' is NEW CONTENT and always
        # re-registers — a path-only dedup swallowed it forever
        # (round-4 open finding), and even probe meta can't tell a
        # same-length re-edit apart; only the ledger's size+mtime
        # signature can. The meta check still guards the 'missing'
        # path: a job for the same path with different probe meta means
        # the ledger lost track of a change.
        if state != "changed" and any(
                j.input_path == abs_path and j.meta == meta
                for j in coordinator.store):
            return True
        # Re-registering: supersede ANY non-terminal job on this path
        # (whether the ledger said 'changed' or the meta mismatch on a
        # 'missing' re-probe revealed it) — a run already encoding this
        # path holds the OLD content in memory and would commit a stale
        # output file over the new cut's (both derive
        # library/<basename>.mp4); stopping it fences its run token.
        for j in coordinator.store:
            if j.input_path == abs_path and not j.status.is_terminal:
                coordinator.stop_job(j.id)
                coordinator.activity.emit(
                    "stop", "superseded by re-dropped file with "
                    "changed content", job_id=j.id, host=activity_host)
        job = coordinator.add_job(abs_path, meta)
        return job is not None

    return submit
