"""Inter-frame (P) coding tests: GOP encode vs the libavcodec oracle.

The conformance bar is bit-exactness: the oracle's decoded planes must
equal the encoder's closed-loop reconstruction for every frame, across
content that exercises motion search, skip runs, MV prediction edge
cases, and frame cropping.
"""

import numpy as np
import pytest

from thinvids_tpu.codecs.h264.encoder import encode_frames, encode_gop
from thinvids_tpu.codecs.h264.inter import (
    CBP_INTER_TO_CODE,
    _CODE_TO_CBP_INTER,
    predict_mvs,
)
from thinvids_tpu.core.types import Frame, VideoMeta
from thinvids_tpu.tools import oracle


def translating_clip(w, h, n, step=3, noise=2.0, seed=0):
    """Pattern moving `step` px/frame — exercises non-zero MVs."""
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:h, 0:w]
    frames = []
    for i in range(n):
        y = np.clip(((xx * 3 + yy * 2 + step * i) % 256)
                    + rng.normal(0, noise, (h, w)), 0, 255).astype(np.uint8)
        u = np.clip(128 + 20 * np.sin(xx[::2, ::2] * 0.1 + i * 0.5),
                    0, 255).astype(np.uint8)
        v = np.clip(128 + 20 * np.cos(yy[::2, ::2] * 0.1 + i * 0.5),
                    0, 255).astype(np.uint8)
        frames.append(Frame(y, u, v))
    return frames


def static_clip(w, h, n):
    """Identical frames — P frames should collapse to skip runs."""
    yy, xx = np.mgrid[0:h, 0:w]
    y = ((xx + yy) % 256).astype(np.uint8)
    u = np.full((h // 2, w // 2), 100, np.uint8)
    v = np.full((h // 2, w // 2), 150, np.uint8)
    return [Frame(y.copy(), u.copy(), v.copy()) for _ in range(n)]


def assert_bit_exact(frames, meta, qp, **kw):
    stream, recons = encode_gop(frames, meta, qp=qp, return_recon=True, **kw)
    decoded = oracle.decode_h264(stream)
    assert len(decoded) == len(frames)
    ry, ru, rv = recons
    for i, (oy, ou, ov) in enumerate(decoded):
        # The oracle returns display (cropped) planes; recon is padded.
        for name, got, want in (("y", oy, ry[i]), ("u", ou, ru[i]),
                                ("v", ov, rv[i])):
            want = np.asarray(want).astype(np.uint8)
            np.testing.assert_array_equal(
                got, want[:got.shape[0], :got.shape[1]],
                err_msg=f"frame {i} {name}")
    return stream


class TestCbpTable:
    def test_bijective(self):
        assert sorted(_CODE_TO_CBP_INTER) == list(range(48))
        for cbp in range(48):
            assert _CODE_TO_CBP_INTER[CBP_INTER_TO_CODE[cbp]] == cbp


class TestMvPrediction:
    def test_uniform_field_predicts_itself(self):
        mv = np.tile(np.array([2, -3], np.int32), (12, 1))
        mvp, skip = predict_mvs(mv, 4, 3)
        # Interior MBs: median of identical vectors is the vector.
        assert np.array_equal(mvp[5], [2, -3])
        # Top-left corner: nothing available -> zero.
        assert np.array_equal(mvp[0], [0, 0])
        # First row beyond MB0: A-only rule.
        assert np.array_equal(mvp[1], [2, -3])

    def test_skip_mv_zero_conditions(self):
        # Any zero-MV left/top neighbor forces the skip predictor to 0.
        mv = np.tile(np.array([2, 2], np.int32), (9, 1))
        mv[4] = 0                      # center MB of a 3x3 grid
        mvp, skip = predict_mvs(mv, 3, 3)
        assert np.array_equal(skip[5], [0, 0])   # left neighbor (4) is zero
        assert np.array_equal(skip[7], [0, 0])   # top neighbor (4) is zero
        assert np.array_equal(skip[0], [0, 0])   # edge: A/B unavailable


@pytest.mark.skipif(not oracle.oracle_available(), reason="libavcodec missing")
class TestGopConformance:
    @pytest.mark.parametrize("qp", [20, 27, 35])
    def test_translating_motion_bit_exact(self, qp):
        w, h, n = 64, 48, 6
        frames = translating_clip(w, h, n)
        meta = VideoMeta(width=w, height=h, num_frames=n)
        assert_bit_exact(frames, meta, qp)

    def test_static_clip_skips_and_is_tiny(self):
        w, h, n = 96, 64, 8
        frames = static_clip(w, h, n)
        meta = VideoMeta(width=w, height=h, num_frames=n)
        stream = assert_bit_exact(frames, meta, 27)
        intra_stream = encode_frames(frames, meta, qp=27)
        # 7 of 8 frames should be nearly all skip runs.
        assert len(stream) < len(intra_stream) / 4

    def test_cropped_dimensions(self):
        # Non-MB-multiple dims exercise padding + cropping with motion.
        w, h, n = 70, 50, 5
        frames = translating_clip(w, h, n, step=2)
        meta = VideoMeta(width=w, height=h, num_frames=n)
        assert_bit_exact(frames, meta, 27)

    def test_fast_motion_hits_search_range(self):
        # 12 px/frame translation requires |mv| up to the search range.
        w, h, n = 96, 64, 4
        frames = translating_clip(w, h, n, step=12, noise=0.0)
        meta = VideoMeta(width=w, height=h, num_frames=n)
        assert_bit_exact(frames, meta, 27)

    def test_noise_content_bit_exact(self):
        # Uncorrelated noise: ME finds junk vectors, residuals are dense —
        # stresses CAVLC inter paths and CBP corners.
        rng = np.random.default_rng(5)
        w, h, n = 48, 32, 4
        frames = [Frame(
            y=rng.integers(0, 256, (h, w), dtype=np.uint8),
            u=rng.integers(0, 256, (h // 2, w // 2), dtype=np.uint8),
            v=rng.integers(0, 256, (h // 2, w // 2), dtype=np.uint8),
        ) for _ in range(n)]
        meta = VideoMeta(width=w, height=h, num_frames=n)
        assert_bit_exact(frames, meta, 30)

    def test_gop_beats_all_intra_3x_on_low_motion(self):
        # The VERDICT acceptance bar: >=3x smaller than all-IDR at qp 27
        # on a low-motion clip.
        w, h, n = 128, 96, 10
        frames = translating_clip(w, h, n, step=1, noise=1.0)
        meta = VideoMeta(width=w, height=h, num_frames=n)
        stream = assert_bit_exact(frames, meta, 27)
        intra_stream = encode_frames(frames, meta, qp=27)
        assert len(stream) * 3 <= len(intra_stream)
