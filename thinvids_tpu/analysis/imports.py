"""Pass 1 — jax confinement over the transitive import graph.

Builds the package's module-scope import graph (what executes at
``import`` time: top-level statements, class bodies, module-level
``try``/``if`` arms — NOT function bodies or ``if TYPE_CHECKING``
blocks) and proves that every module the manifest declares jax-free
can never reach a forbidden external root (``jax``) through any chain
of module-scope imports.

Importing ``a.b.c`` executes ``a/__init__`` and ``a.b/__init__`` too,
so package-__init__ edges are part of every module's closure — the
lazy ``__getattr__`` pattern (parallel/__init__.py, codecs/h264/
__init__.py) is exactly what keeps those edges clean, and this pass is
what notices when someone "simplifies" one back into an eager import.

Also enforces the manifest's forbidden-symbol rules (TVT-J002): e.g.
the streaming executors must never reference ``read_video`` (the
blocking whole-clip decode prologue), formerly a grep guard in
tests/test_streaming.py.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .astutil import (Finding, SourceTree, finding, is_type_checking_if,
                      matches_any)
from .manifest import Manifest


def _module_scope_nodes(tree: ast.Module):
    """Statements that execute at import time: walk the module body,
    descending into If/Try/With/ClassDef but not into function
    bodies; TYPE_CHECKING arms are skipped."""
    stack = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if is_type_checking_if(node):
            stack.extend(node.orelse)
            continue
        yield node
        for child in ast.iter_child_nodes(node):
            stack.append(child)


def _resolve_from(mod: str, node: ast.ImportFrom, tree: SourceTree,
                  package: str) -> tuple[list[str], list[str]]:
    """ImportFrom → (in-package module edges, external roots)."""
    internal: list[str] = []
    external: list[str] = []
    if node.level:
        # relative: base = this module minus `level` trailing parts
        # (a package __init__ counts as the package itself)
        base_parts = mod.split(".")
        if not tree.path(mod).endswith("__init__.py"):
            base_parts = base_parts[:-1]
        base_parts = base_parts[:len(base_parts) - (node.level - 1)]
        base = ".".join(base_parts + ([node.module] if node.module else []))
    else:
        base = node.module or ""
        if not (base == package or base.startswith(package + ".")):
            if base:
                external.append(base.split(".")[0])
            return internal, external
    if tree.has_module(base):
        internal.append(base)
    for alias in node.names:
        sub = f"{base}.{alias.name}"
        # `from pkg import submodule` imports the submodule file
        if tree.has_module(sub):
            internal.append(sub)
    return internal, external


def build_import_graph(tree: SourceTree, package: str
                       ) -> dict[str, tuple[set[str], set[str]]]:
    """module → (in-package imports, external top-level roots), at
    module scope only. Every in-package edge also pulls the target's
    ancestor package __init__s (Python executes them on import)."""
    graph: dict[str, tuple[set[str], set[str]]] = {}
    for mod in tree.modules():
        internal: set[str] = set()
        external: set[str] = set()
        for node in _module_scope_nodes(tree.tree(mod)):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.name
                    if name == package or name.startswith(package + "."):
                        if tree.has_module(name):
                            internal.add(name)
                    else:
                        external.add(name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                ints, exts = _resolve_from(mod, node, tree, package)
                internal.update(ints)
                external.update(exts)
        # ancestor __init__ edges (importing a.b.c executes a and a.b)
        expanded = set(internal)
        for tgt in internal:
            parts = tgt.split(".")
            for i in range(1, len(parts)):
                anc = ".".join(parts[:i])
                if tree.has_module(anc):
                    expanded.add(anc)
        expanded.discard(mod)
        graph[mod] = (expanded, external)
    return graph


def import_closure(graph, roots) -> tuple[set[str], dict[str, str]]:
    """Transitive in-package closure of `roots` (a module name or an
    iterable of them) + a parent map for chain reconstruction. ONE
    traversal over all roots: each node gets its parent assigned
    exactly once when first discovered, so the map is a forest rooted
    at `roots` — merging per-root maps instead can stitch a cycle
    (A←B, B←A from different roots) and hang the chain walk."""
    roots = [roots] if isinstance(roots, str) else list(roots)
    seen: set[str] = set(roots)
    parent: dict[str, str] = {}
    frontier = list(roots)
    while frontier:
        cur = frontier.pop()
        for nxt in graph.get(cur, (set(), set()))[0]:
            if nxt not in seen:
                seen.add(nxt)
                parent[nxt] = cur
                frontier.append(nxt)
    return seen, parent


def _chain(parent: dict[str, str], roots: set[str], end: str) -> str:
    path = [end]
    # the parent map is a forest rooted at `roots` (see
    # import_closure); the bound is belt-and-braces so a future graph
    # bug degrades the message instead of hanging the checker
    for _ in range(len(parent) + 1):
        if path[-1] in roots or path[-1] not in parent:
            break
        path.append(parent[path[-1]])
    return " -> ".join(reversed(path))


def _own_ancestors(tree: SourceTree, mod: str) -> list[str]:
    """Importing `mod` executes its own ancestor __init__s first."""
    parts = mod.split(".")
    return [".".join(parts[:i]) for i in range(1, len(parts))
            if tree.has_module(".".join(parts[:i]))]


def check_jax_confinement(tree: SourceTree, manifest: Manifest
                          ) -> list[Finding]:
    graph = build_import_graph(tree, manifest.package)
    findings: list[Finding] = []
    declared = [m for m in tree.modules()
                if matches_any(m, manifest.jax_free)]
    for mod in declared:
        roots = list(_own_ancestors(tree, mod)) + [mod]
        seen, parent = import_closure(graph, roots)
        for reached in sorted(seen):
            _ints, exts = graph.get(reached, (set(), set()))
            bad = exts.intersection(manifest.jax_roots)
            if not bad:
                continue
            via = "" if reached == mod else \
                f" via {_chain(parent, set(roots), reached)}"
            findings.append(finding(
                "TVT-J001", mod, 1,
                f"declared jax-free but reaches {sorted(bad)} at module "
                f"scope{via}",
                key_detail=f"{mod}:{reached}"))
    return findings


def check_forbidden_symbols(tree: SourceTree, manifest: Manifest
                            ) -> list[Finding]:
    findings: list[Finding] = []
    for mod, rules in manifest.forbidden_symbols.items():
        if not tree.has_module(mod):
            continue
        for node in ast.walk(tree.tree(mod)):
            names: Iterable[tuple[str, int]] = ()
            if isinstance(node, ast.Name):
                names = [(node.id, node.lineno)]
            elif isinstance(node, ast.Attribute):
                names = [(node.attr, node.lineno)]
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                names = [(alias.name.split(".")[-1], node.lineno)
                         for alias in node.names]
            for name, line in names:
                for symbol, reason in rules:
                    if name == symbol:
                        findings.append(finding(
                            "TVT-J002", mod, line,
                            f"references forbidden symbol "
                            f"`{symbol}`: {reason}",
                            key_detail=f"{mod}:{symbol}"))
    # one finding per (module, symbol): dedup repeated references
    uniq: dict[str, Finding] = {}
    for f in findings:
        uniq.setdefault(f.key, f)
    return list(uniq.values())


def run(tree: SourceTree, manifest: Manifest) -> list[Finding]:
    return check_jax_confinement(tree, manifest) \
        + check_forbidden_symbols(tree, manifest)
