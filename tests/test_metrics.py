"""Quality metrics sanity: PSNR/SSIM behave like the standard
definitions and the encoder's output lands in the expected band."""

import numpy as np
import pytest

from thinvids_tpu.tools.metrics import clip_quality, psnr, ssim


class TestPsnr:
    def test_identical_is_inf(self):
        x = np.random.default_rng(0).integers(0, 256, (64, 64)).astype(np.uint8)
        assert psnr(x, x) == float("inf")

    def test_known_value(self):
        ref = np.zeros((16, 16), np.uint8)
        dist = np.full((16, 16), 10, np.uint8)   # mse=100
        assert abs(psnr(ref, dist) - 10 * np.log10(255**2 / 100)) < 1e-9

    def test_monotone_in_noise(self):
        rng = np.random.default_rng(1)
        x = rng.integers(0, 256, (64, 64)).astype(np.float64)
        a = psnr(x, np.clip(x + rng.normal(0, 2, x.shape), 0, 255))
        b = psnr(x, np.clip(x + rng.normal(0, 8, x.shape), 0, 255))
        assert a > b


class TestSsim:
    def test_identical_is_one(self):
        x = np.random.default_rng(0).integers(0, 256, (64, 64)).astype(np.uint8)
        assert abs(ssim(x, x) - 1.0) < 1e-12

    def test_noise_degrades(self):
        rng = np.random.default_rng(1)
        x = rng.integers(0, 256, (64, 64)).astype(np.float64)
        noisy = np.clip(x + rng.normal(0, 20, x.shape), 0, 255)
        s = ssim(x, noisy)
        assert 0.0 < s < 0.99

    def test_constant_shift_nearly_one(self):
        # SSIM is mean-shift tolerant (luminance term saturates)
        x = np.random.default_rng(2).integers(40, 200, (64, 64)).astype(float)
        assert ssim(x, x + 3) > 0.97


class TestEncoderQuality:
    def test_qp27_band_on_synthetic_content(self):
        from thinvids_tpu.core.types import Frame, VideoMeta
        from thinvids_tpu.parallel.dispatch import encode_clip_sharded
        from thinvids_tpu.tools import oracle

        if not oracle.oracle_available():
            pytest.skip("libavcodec missing")
        rng = np.random.default_rng(3)
        h, w, n = 48, 64, 8
        yy, xx = np.mgrid[0:h, 0:w]
        frames = [Frame(
            y=np.clip((xx * 2 + 3 * i) % 200 +
                      rng.integers(-10, 11, (h, w)), 0, 255).astype(np.uint8),
            u=np.full((h // 2, w // 2), 110, np.uint8),
            v=np.full((h // 2, w // 2), 140, np.uint8)) for i in range(n)]
        meta = VideoMeta(width=w, height=h, num_frames=n)
        stream = encode_clip_sharded(frames, meta, qp=27, gop_frames=4)
        decoded = oracle.decode_h264(stream)
        q = clip_quality(frames, [d[0] for d in decoded])
        assert q["frames_compared"] == n
        assert 28.0 < q["psnr_y"] < 60.0        # lossy but reasonable
        assert 0.75 < q["ssim_y"] <= 1.0
        # lower QP must not reduce quality
        stream_hi = encode_clip_sharded(frames, meta, qp=18, gop_frames=4)
        q_hi = clip_quality(frames,
                            [d[0] for d in oracle.decode_h264(stream_hi)])
        assert q_hi["psnr_y"] > q["psnr_y"]


class TestVmafProxy:
    def test_monotone_and_bounded(self):
        from thinvids_tpu.tools.metrics import vmaf_proxy

        lo = vmaf_proxy(30.0, 0.80)
        mid = vmaf_proxy(36.0, 0.90)
        hi = vmaf_proxy(44.0, 0.99)
        assert 0 <= lo < mid < hi <= 100
        assert vmaf_proxy(float("inf"), 1.0) == 100.0
        # monotone in each input separately
        assert vmaf_proxy(37.0, 0.9) > vmaf_proxy(36.0, 0.9)
        assert vmaf_proxy(36.0, 0.95) > vmaf_proxy(36.0, 0.9)

    def test_clip_quality_carries_proxy(self):
        import numpy as np

        from thinvids_tpu.core.types import Frame
        from thinvids_tpu.tools.metrics import clip_quality, vmaf_proxy

        rng = np.random.default_rng(0)
        y = rng.integers(0, 256, (32, 48), np.uint8)
        u = y[::2, ::2].copy()
        f = Frame(y, u, u)
        noisy = np.clip(y.astype(np.int16)
                        + rng.integers(-8, 9, y.shape), 0, 255
                        ).astype(np.uint8)
        q = clip_quality([f], [noisy])
        assert q["vmaf_proxy"] == vmaf_proxy(q["psnr_y"], q["ssim_y"])
        assert 0 <= q["vmaf_proxy"] <= 100
