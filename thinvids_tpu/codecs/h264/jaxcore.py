"""JAX/TPU implementation of the intra encode compute path.

Bit-exact port of encoder.encode_frame_arrays (tested against it): the
whole prediction→transform→quant→reconstruction loop runs as one jitted
XLA program. Structure chosen for the TPU execution model:

- macroblock ROW 0 has a left-neighbor dependency (DC/H modes) → a small
  `lax.scan` over its MBs;
- every other row uses VERTICAL prediction, which depends only on the
  reconstructed bottom edge of the row above → `lax.scan` over rows with
  all MBs of a row computed as one vectorized batch (VPU-friendly int32
  ops over (mbw, 16, 16) tiles, static shapes, no data-dependent control
  flow).

The sequential entropy pack stays on host (codecs/h264/encoder.pack_slice
or the C++ packer); this module only produces level arrays.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from .encoder import FrameLevels, _mode_policy
from .intra import LUMA_BLOCK_ORDER
from .transform import MF_TABLE, V_TABLE, ZIGZAG_4x4, CHROMA_QP_TABLE

_MF = jnp.asarray(MF_TABLE)          # (6, 4, 4)
_V = jnp.asarray(V_TABLE)            # (6, 4, 4)
_ZZ = jnp.asarray(ZIGZAG_4x4)        # (16,)
_QPC = jnp.asarray(CHROMA_QP_TABLE)  # (52,)
_CF = jnp.asarray([[1, 1, 1, 1], [2, 1, -1, -2], [1, -1, -1, 1], [1, -2, 2, -1]],
                  dtype=jnp.int32)
_H4 = jnp.asarray([[1, 1, 1, 1], [1, 1, -1, -1], [1, -1, -1, 1], [1, -1, 1, -1]],
                  dtype=jnp.int32)
_H2 = jnp.asarray([[1, 1], [1, -1]], dtype=jnp.int32)
# raster (by*4+bx) index for each z-scan position
_ZSCAN = jnp.asarray([by * 4 + bx for (bx, by) in LUMA_BLOCK_ORDER])


def _varying_zero(x):
    """A zero int32 scalar DERIVED from `x`, not a constant.

    Under `shard_map`, values built from plain constants are unvarying
    over the mesh axes while data-derived values are varying; a
    `lax.scan` whose init carry is unvarying but whose carry output is
    varying fails the carry-type check. Deriving the zero from the
    sharded input gives inits the same varying manual axes. Do NOT
    simplify `zeros + _varying_zero(x)` to `zeros`.
    """
    return (x.reshape(-1)[0] * 0).astype(jnp.int32)


def _fwd4(x):
    return jnp.einsum("ij,...jk,lk->...il", _CF, x, _CF)


def _inv4(d):
    d0, d1, d2, d3 = d[..., 0], d[..., 1], d[..., 2], d[..., 3]
    e0, e1 = d0 + d2, d0 - d2
    e2, e3 = (d1 >> 1) - d3, d1 + (d3 >> 1)
    f = jnp.stack([e0 + e3, e1 + e2, e1 - e2, e0 - e3], axis=-1)
    g0, g1, g2, g3 = f[..., 0, :], f[..., 1, :], f[..., 2, :], f[..., 3, :]
    h0, h1 = g0 + g2, g0 - g2
    h2, h3 = (g1 >> 1) - g3, g1 + (g3 >> 1)
    return jnp.stack([h0 + h3, h1 + h2, h1 - h2, h0 - h3], axis=-2)


def _quant(w, qp, skip_dc):
    qbits = 15 + qp // 6
    f = (1 << qbits) // 3
    mf = _MF[qp % 6]
    z = (jnp.abs(w) * mf + f) >> qbits
    z = jnp.where(w < 0, -z, z)
    if skip_dc:
        z = z.at[..., 0, 0].set(0)
    return z


def _dequant(z, qp):
    return (z * _V[qp % 6]) << (qp // 6)


def _zigzag(b):
    return b.reshape(*b.shape[:-2], 16)[..., _ZZ]


def _inv_zigzag(seq):
    out = jnp.zeros_like(seq)
    out = out.at[..., _ZZ].set(seq)
    return out.reshape(*seq.shape[:-1], 4, 4)


def _luma_dc_quant(wd, qp):
    qbits = 15 + qp // 6
    f = (1 << qbits) // 3
    mf00 = _MF[qp % 6, 0, 0]
    z = (jnp.abs(wd) * mf00 + 2 * f) >> (qbits + 1)
    return jnp.where(wd < 0, -z, z)


def _luma_dc_dequant(z, qp):
    f = jnp.einsum("ij,...jk,lk->...il", _H4, z, _H4)
    ls = _V[qp % 6, 0, 0] * 16
    hi = (f * ls) << jnp.maximum(qp // 6 - 6, 0)
    shift = jnp.maximum(6 - qp // 6, 1)
    lo = (f * ls + (1 << (shift - 1))) >> shift
    return jnp.where(qp >= 36, hi, lo)


def _chroma_dc_quant(wd, qp):
    qbits = 15 + qp // 6
    f = (1 << qbits) // 3
    mf00 = _MF[qp % 6, 0, 0]
    z = (jnp.abs(wd) * mf00 + 2 * f) >> (qbits + 1)
    return jnp.where(wd < 0, -z, z)


def _chroma_dc_dequant(z, qp):
    f = jnp.einsum("ij,...jk,lk->...il", _H2, z, _H2)
    ls = _V[qp % 6, 0, 0] * 16
    return ((f * ls) << (qp // 6)) >> 5


def _luma_mb_batch(src, pred, qp):
    """src/pred: (n, 16, 16) int32 → (dc_lev (n,16), ac_lev (n,16,15),
    recon (n,16,16))."""
    n = src.shape[0]
    resid = src - pred
    blocks = resid.reshape(n, 4, 4, 4, 4).transpose(0, 1, 3, 2, 4).reshape(n, 16, 4, 4)
    w = _fwd4(blocks)
    dc = w[..., 0, 0].reshape(n, 4, 4)                      # [by, bx]
    wd = jnp.einsum("ij,njk,lk->nil", _H4, dc, _H4) // 2
    dc_lev = _zigzag(_luma_dc_quant(wd, qp))
    z = _quant(w, qp, skip_dc=True)
    ac_lev = _zigzag(z)[:, _ZSCAN, 1:]
    # closed-loop recon from the signaled levels
    dcr = _luma_dc_dequant(_inv_zigzag(dc_lev), qp)         # (n, 4, 4)
    d = _dequant(z, qp)
    d = d.at[..., 0, 0].set(dcr.reshape(n, 16))
    r = (_inv4(d) + 32) >> 6
    predb = pred.reshape(n, 4, 4, 4, 4).transpose(0, 1, 3, 2, 4).reshape(n, 16, 4, 4)
    rec = jnp.clip(predb + r, 0, 255)
    rec = rec.reshape(n, 4, 4, 4, 4).transpose(0, 1, 3, 2, 4).reshape(n, 16, 16)
    return dc_lev, ac_lev, rec


def _chroma_mb_batch(src, pred, qpc):
    """src/pred: (n, 8, 8) int32 → (dc_lev (n,4), ac_lev (n,4,15), recon)."""
    n = src.shape[0]
    resid = src - pred
    blocks = resid.reshape(n, 2, 4, 2, 4).transpose(0, 1, 3, 2, 4).reshape(n, 4, 4, 4)
    w = _fwd4(blocks)
    dc = w[..., 0, 0].reshape(n, 2, 2)
    wd = jnp.einsum("ij,njk,lk->nil", _H2, dc, _H2)
    dc_lev = _chroma_dc_quant(wd, qpc).reshape(n, 4)
    z = _quant(w, qpc, skip_dc=True)
    ac_lev = _zigzag(z)[..., 1:]
    dcr = _chroma_dc_dequant(dc_lev.reshape(n, 2, 2), qpc)
    d = _dequant(z, qpc)
    d = d.at[..., 0, 0].set(dcr.reshape(n, 4))
    r = (_inv4(d) + 32) >> 6
    predb = pred.reshape(n, 2, 4, 2, 4).transpose(0, 1, 3, 2, 4).reshape(n, 4, 4, 4)
    rec = jnp.clip(predb + r, 0, 255)
    rec = rec.reshape(n, 2, 2, 4, 4).transpose(0, 1, 3, 2, 4).reshape(n, 8, 8)
    return dc_lev, ac_lev, rec


@functools.partial(jax.jit, static_argnames=("mbw", "mbh"))
def _encode_intra(y, u, v, qp, *, mbw: int, mbh: int):
    """Jitted intra compute: level arrays only (recon DCE'd away)."""
    return _intra_core(y, u, v, qp, mbw=mbw, mbh=mbh)[:4]


def _intra_core(y, u, v, qp, *, mbw: int, mbh: int):
    qp = qp.astype(jnp.int32)
    qpc = _QPC[jnp.clip(qp, 0, 51)]
    y = y.astype(jnp.int32)
    u = u.astype(jnp.int32)
    v = v.astype(jnp.int32)

    # --- row 0: sequential over MBs (DC for MB0, horizontal after) ---
    y_row0 = y[:16].reshape(16, mbw, 16).transpose(1, 0, 2)      # (mbw,16,16)
    u_row0 = u[:8].reshape(8, mbw, 8).transpose(1, 0, 2)
    v_row0 = v[:8].reshape(8, mbw, 8).transpose(1, 0, 2)

    def row0_step(carry, x):
        ly, lu, lv, idx = carry
        sy, su, sv = x
        pred_y = jnp.where(idx == 0, jnp.full((16, 16), 128, jnp.int32),
                           jnp.tile(ly[:, None], (1, 16)))
        pred_u = jnp.where(idx == 0, jnp.full((8, 8), 128, jnp.int32),
                           jnp.tile(lu[:, None], (1, 8)))
        pred_v = jnp.where(idx == 0, jnp.full((8, 8), 128, jnp.int32),
                           jnp.tile(lv[:, None], (1, 8)))
        ydc, yac, yrec = _luma_mb_batch(sy[None], pred_y[None], qp)
        udc, uac, urec = _chroma_mb_batch(su[None], pred_u[None], qpc)
        vdc, vac, vrec = _chroma_mb_batch(sv[None], pred_v[None], qpc)
        carry = (yrec[0, :, -1], urec[0, :, -1], vrec[0, :, -1], idx + 1)
        return carry, (ydc[0], yac[0], udc[0], uac[0], vdc[0], vac[0],
                       yrec[0], urec[0], vrec[0])

    zero = _varying_zero(y)        # see _varying_zero: shard_map carries
    init = (jnp.zeros(16, jnp.int32) + zero, jnp.zeros(8, jnp.int32) + zero,
            jnp.zeros(8, jnp.int32) + zero, zero)
    _, row0_out = jax.lax.scan(row0_step, init, (y_row0, u_row0, v_row0))
    (r0_ydc, r0_yac, r0_udc, r0_uac, r0_vdc, r0_vac,
     r0_yrec, r0_urec, r0_vrec) = row0_out
    bottom_y = r0_yrec[:, -1, :].reshape(-1)                     # (W,)
    bottom_u = r0_urec[:, -1, :].reshape(-1)
    bottom_v = r0_vrec[:, -1, :].reshape(-1)

    if mbh > 1:
        # --- rows 1..mbh-1: scan over rows, vectorized across MBs ---
        y_rows = y[16:].reshape(mbh - 1, 16, mbw, 16).transpose(0, 2, 1, 3)
        u_rows = u[8:].reshape(mbh - 1, 8, mbw, 8).transpose(0, 2, 1, 3)
        v_rows = v[8:].reshape(mbh - 1, 8, mbw, 8).transpose(0, 2, 1, 3)

        def row_step(carry, x):
            by, bu, bv = carry
            sy, su, sv = x                                       # (mbw,16,16)
            pred_y = jnp.broadcast_to(by.reshape(mbw, 1, 16), (mbw, 16, 16))
            pred_u = jnp.broadcast_to(bu.reshape(mbw, 1, 8), (mbw, 8, 8))
            pred_v = jnp.broadcast_to(bv.reshape(mbw, 1, 8), (mbw, 8, 8))
            ydc, yac, yrec = _luma_mb_batch(sy, pred_y, qp)
            udc, uac, urec = _chroma_mb_batch(su, pred_u, qpc)
            vdc, vac, vrec = _chroma_mb_batch(sv, pred_v, qpc)
            carry = (yrec[:, -1, :].reshape(-1), urec[:, -1, :].reshape(-1),
                     vrec[:, -1, :].reshape(-1))
            return carry, (ydc, yac, udc, uac, vdc, vac, yrec, urec, vrec)

        _, rows_out = jax.lax.scan(
            row_step, (bottom_y, bottom_u, bottom_v), (y_rows, u_rows, v_rows))
        (ydc_r, yac_r, udc_r, uac_r, vdc_r, vac_r,
         yrec_r, urec_r, vrec_r) = rows_out
        luma_dc = jnp.concatenate([r0_ydc[None], ydc_r]).reshape(-1, 16)
        luma_ac = jnp.concatenate([r0_yac[None], yac_r]).reshape(-1, 16, 15)
        u_dc = jnp.concatenate([r0_udc[None], udc_r]).reshape(-1, 4)
        u_ac = jnp.concatenate([r0_uac[None], uac_r]).reshape(-1, 4, 15)
        v_dc = jnp.concatenate([r0_vdc[None], vdc_r]).reshape(-1, 4)
        v_ac = jnp.concatenate([r0_vac[None], vac_r]).reshape(-1, 4, 15)
        yrec_all = jnp.concatenate([r0_yrec[None], yrec_r])  # (mbh,mbw,16,16)
        urec_all = jnp.concatenate([r0_urec[None], urec_r])
        vrec_all = jnp.concatenate([r0_vrec[None], vrec_r])
    else:
        luma_dc, luma_ac = r0_ydc, r0_yac
        u_dc, u_ac, v_dc, v_ac = r0_udc, r0_uac, r0_vdc, r0_vac
        yrec_all = r0_yrec[None]
        urec_all = r0_urec[None]
        vrec_all = r0_vrec[None]

    chroma_dc = jnp.stack([u_dc, v_dc], axis=1)                  # (nmb,2,4)
    chroma_ac = jnp.stack([u_ac, v_ac], axis=1)                  # (nmb,2,4,15)
    recon_y = yrec_all.transpose(0, 2, 1, 3).reshape(16 * mbh, 16 * mbw)
    recon_u = urec_all.transpose(0, 2, 1, 3).reshape(8 * mbh, 8 * mbw)
    recon_v = vrec_all.transpose(0, 2, 1, 3).reshape(8 * mbh, 8 * mbw)
    return (luma_dc, luma_ac, chroma_dc, chroma_ac,
            recon_y, recon_u, recon_v)


@functools.partial(jax.jit, static_argnames=("mbw", "mbh", "dtype"))
def _encode_intra_packed(y, u, v, qp, *, mbw: int, mbh: int, dtype):
    """Dense fallback: intra compute + device-side concat of all level
    arrays into ONE flat `dtype` buffer (int16 covers the full CAVLC
    level range at 2x fewer device→host bytes than raw int32). The
    common path is the sparse transfer (`_encode_intra_sparse`)."""
    luma_dc, luma_ac, chroma_dc, chroma_ac = _encode_intra(
        y, u, v, qp, mbw=mbw, mbh=mbh)
    flat = jnp.concatenate([
        luma_dc.reshape(-1), luma_ac.reshape(-1),
        chroma_dc.reshape(-1), chroma_ac.reshape(-1)])
    return flat.astype(dtype)


_I8_MAX = 127

# Sparse level-transfer budget: nonzero density above 1/div falls back
# to a dense fetch. Typical density at qp 27 is ~10-15 % for all-intra
# frames; the dense fallback keeps correctness for busy content. (The
# GOP path uses the block-granular budget _BLOCK_BUDGET_DIV below.)
_SPARSE_BUDGET_DIV = 4
# Escape side-channel size: levels with |v| > 127 are rare at practical
# QPs; they ride as (position, value) int32 pairs so vals stay int8.
_SPARSE_ESCAPES = 4096
_BIT_WEIGHTS = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], jnp.uint8)


def _sparse_pack(flat, budget_div: int = _SPARSE_BUDGET_DIV):
    """Compact a flat int32 level vector on device.

    Returns (nnz, n_esc, bitmap, vals, esc_pos, esc_val):
    - bitmap: 1 bit/coeff nonzero mask (big-endian within bytes, matching
      np.unpackbits), L/8 bytes;
    - vals: the nonzero levels in scan order, clipped to int8, in a fixed
      L//_SPARSE_BUDGET_DIV buffer;
    - esc_pos/esc_val: flat positions + true values of levels exceeding
      int8 (|v| > 127), in a fixed _SPARSE_ESCAPES buffer.
    ~10x fewer device→host bytes than raw int32 at typical densities.
    The caller must fall back to a dense fetch iff nnz > budget or
    n_esc > _SPARSE_ESCAPES.
    """
    L = flat.shape[0]
    budget = L // budget_div
    mask = flat != 0
    nnz = jnp.sum(mask.astype(jnp.int32))
    pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
    idx = jnp.where(mask, pos, budget)
    clipped = jnp.clip(flat, -_I8_MAX, _I8_MAX).astype(jnp.int8)
    vals = jnp.zeros(budget + 1, jnp.int8).at[idx].set(
        clipped, mode="drop")[:budget]
    bitmap = jnp.sum(
        _pad8(mask).reshape(-1, 8).astype(jnp.uint8) * _BIT_WEIGHTS, axis=-1
    ).astype(jnp.uint8)
    esc_mask = jnp.abs(flat) > _I8_MAX
    n_esc = jnp.sum(esc_mask.astype(jnp.int32))
    epos = jnp.cumsum(esc_mask.astype(jnp.int32)) - 1
    eidx = jnp.where(esc_mask, epos, _SPARSE_ESCAPES)
    esc_pos = jnp.zeros(_SPARSE_ESCAPES + 1, jnp.int32).at[eidx].set(
        jnp.arange(L, dtype=jnp.int32), mode="drop")[:_SPARSE_ESCAPES]
    esc_val = jnp.zeros(_SPARSE_ESCAPES + 1, jnp.int32).at[eidx].set(
        flat, mode="drop")[:_SPARSE_ESCAPES]
    return nnz, n_esc, bitmap, vals, esc_pos, esc_val


_BLOCK = 16
# Block-sparse budget: tolerated fraction of 16-coeff blocks with any
# nonzero coefficient is 1/_BLOCK_BUDGET_DIV; beyond that the caller
# falls back to the dense fetch. P-frame residual blocks are sparse
# (~10-15 % nonzero at qp 27) but the GOP's intra frame is NOT — most
# intra blocks carry at least a DC level — so the budget must absorb
# intra_blocks + sparse P blocks (measured ~300K of 1.57M for an
# 8-frame 1080p GOP).
_BLOCK_BUDGET_DIV = 4


def _block_sparse_pack(flat, budget_div: int = _BLOCK_BUDGET_DIV):
    """Compact a flat int16 level vector on device at BLOCK granularity.

    The element-granular `_sparse_pack` needs cumsums/scatters over the
    full coefficient vector — XLA lowers a 25M-element cumsum as
    O(n log n) passes, measured ~0.6 s per 1080p GOP on a v5e chip.
    At 16-coeff-block granularity the position computation shrinks 16x
    and the values move by GATHER (fast) instead of scatter:

    Returns (nblk, n_esc, bitmap, payload, esc_pos, esc_val):
    - bitmap: 1 bit per 16-coeff block (any-nonzero), L/128 bytes;
    - payload: the nonzero blocks' 16 coeffs each, int8-clipped, in
      block order, in a fixed (L/16//budget_div, 16) buffer (tail
      zeroed);
    - esc_pos/esc_val: payload-flat positions + true values of coeffs
      exceeding int8, in a fixed _SPARSE_ESCAPES buffer.
    Caller must fall back to a dense fetch iff nblk > budget or
    n_esc > _SPARSE_ESCAPES (see `block_sparse_fits`).
    """
    L = flat.shape[0]
    NB = -(-L // _BLOCK)
    pad = NB * _BLOCK - L
    if pad:        # odd-mb-count resolutions: L need not divide 16
        flat = jnp.concatenate([flat, jnp.zeros(pad, flat.dtype)])
    budget = NB // budget_div
    blocks = flat.reshape(NB, _BLOCK)
    bmask = jnp.any(blocks != 0, axis=1)
    nblk = jnp.sum(bmask.astype(jnp.int32))
    pos = jnp.cumsum(bmask.astype(jnp.int32)) - 1
    idx = jnp.where(bmask, pos, budget)
    blist = jnp.zeros(budget + 1, jnp.int32).at[idx].set(
        jnp.arange(NB, dtype=jnp.int32), mode="drop")[:budget]
    gathered = jnp.take(blocks, blist, axis=0)           # (budget, 16)
    live = (jnp.arange(budget, dtype=jnp.int32) < nblk)[:, None]
    gathered = jnp.where(live, gathered, 0)
    payload = jnp.clip(gathered, -_I8_MAX, _I8_MAX).astype(jnp.int8)
    bitmap = jnp.sum(
        _pad8(bmask).reshape(-1, 8).astype(jnp.uint8) * _BIT_WEIGHTS,
        axis=-1).astype(jnp.uint8)
    gflat = gathered.reshape(-1)
    esc_mask = jnp.abs(gflat) > _I8_MAX
    n_esc = jnp.sum(esc_mask.astype(jnp.int32))
    epos = jnp.cumsum(esc_mask.astype(jnp.int32)) - 1
    eidx = jnp.where(esc_mask, epos, _SPARSE_ESCAPES)
    esc_pos = jnp.zeros(_SPARSE_ESCAPES + 1, jnp.int32).at[eidx].set(
        jnp.arange(gflat.shape[0], dtype=jnp.int32), mode="drop"
    )[:_SPARSE_ESCAPES]
    esc_val = jnp.zeros(_SPARSE_ESCAPES + 1, jnp.int32).at[eidx].set(
        gflat.astype(jnp.int32), mode="drop")[:_SPARSE_ESCAPES]
    return nblk, n_esc, bitmap, payload, esc_pos, esc_val


def block_sparse_fits(nblk: int, n_esc: int, L: int,
                      budget_div: int = _BLOCK_BUDGET_DIV) -> bool:
    return (int(nblk) <= (-(-L // _BLOCK)) // budget_div
            and int(n_esc) <= _SPARSE_ESCAPES)


# Value-stream budget for the two-tier pack: elementwise nonzero density
# beyond 1/div falls back dense. Measured 1080p GOP at qp 27 on heavily
# grainy content: ~723K nonzero coeffs of 25.5M (~2.8%); 1/24 still
# leaves ~1.5x headroom, and every budget byte rides the ~8 MB/s
# device->host link once per GOP.
_VAL_BUDGET_DIV = 24


def _block_sparse_pack2(flat, budget_div: int = _BLOCK_BUDGET_DIV,
                        val_div: int = _VAL_BUDGET_DIV):
    """Two-tier device compaction: block-granular gather (tier 1, see
    _block_sparse_pack) + within-block value compaction (tier 2).

    The device→host link is the pipeline's scarce resource (~8 MB/s
    over the tunnel); tier 1 alone ships 16 int8 per nonzero block but
    only ~2.5 of those are nonzero at qp 27, so tier 2 ships a 16-bit
    occupancy mask per block + just the nonzero values: ~2.6 MB/GOP vs
    ~6.6 MB (1080p, F=8).

    Returns (nblk, nval, n_esc, bitmap, bmask16, vals):
    - bitmap: 1 bit per block (any-nonzero), ceil(L/16)/8 bytes;
    - bmask16: per gathered block, a uint16 lane-occupancy mask
      (bit k = coeff k nonzero), fixed (NB//budget_div,) buffer;
    - vals: the nonzero coeffs in (block, lane) order, int8-clipped,
      fixed (L//val_div,) buffer;
    - n_esc: COUNT of coeffs exceeding int8. There is no escape
      side-channel: levels beyond ±127 are rare at practical QPs, and
      the old (position, value) stream needed a full-size cumsum plus
      two more full-size scatters — measured ~90 ms of a 160 ms pack
      per 1080p GOP. Any escape (n_esc > 0) now falls back to the
      dense fetch for the whole wave.
    Caller falls back to a dense fetch iff nblk/nval/n_esc exceed their
    budgets (`block_sparse2_fits`).
    """
    L = flat.shape[0]
    NB = -(-L // _BLOCK)
    pad = NB * _BLOCK - L
    flat = flat.astype(jnp.int16)       # CAVLC levels fit int16
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, flat.dtype)])
    budget = NB // budget_div
    vbudget = L // val_div
    blocks = flat.reshape(NB, _BLOCK)
    bmask = jnp.any(blocks != 0, axis=1)
    nblk = jnp.sum(bmask.astype(jnp.int32))
    pos = jnp.cumsum(bmask.astype(jnp.int32)) - 1
    idx = jnp.where(bmask, pos, budget)
    blist = jnp.zeros(budget + 1, jnp.int32).at[idx].set(
        jnp.arange(NB, dtype=jnp.int32), mode="drop")[:budget]
    gathered = jnp.take(blocks, blist, axis=0)           # (budget, 16)
    live = (jnp.arange(budget, dtype=jnp.int32) < nblk)[:, None]
    gathered = jnp.where(live, gathered, 0)
    bitmap = jnp.sum(
        _pad8(bmask).reshape(-1, 8).astype(jnp.uint8) * _BIT_WEIGHTS,
        axis=-1).astype(jnp.uint8)

    emask = gathered != 0                                # (budget, 16)
    lanes = jnp.asarray([1 << k for k in range(_BLOCK)], jnp.int32)
    bmask16 = jnp.sum(emask.astype(jnp.int32) * lanes,
                      axis=1).astype(jnp.uint16)
    counts = jnp.sum(emask.astype(jnp.int32), axis=1)    # (budget,)
    offs = jnp.cumsum(counts) - counts
    within = jnp.cumsum(emask.astype(jnp.int32), axis=1) - 1
    nval = jnp.sum(counts)
    vpos = jnp.where(emask, offs[:, None] + within, vbudget)
    clipped = jnp.clip(gathered, -_I8_MAX, _I8_MAX).astype(jnp.int8)
    vals = jnp.zeros(vbudget + 1, jnp.int8).at[
        vpos.reshape(-1)].set(clipped.reshape(-1), mode="drop")[:vbudget]
    n_esc = jnp.sum((jnp.abs(gathered) > _I8_MAX).astype(jnp.int32))
    return (nblk, nval, n_esc, bitmap, bmask16, vals)


def block_sparse2_fits(nblk: int, nval: int, n_esc: int, L: int,
                       budget_div: int = _BLOCK_BUDGET_DIV,
                       val_div: int = _VAL_BUDGET_DIV) -> bool:
    return (int(nblk) <= (-(-L // _BLOCK)) // budget_div
            and int(nval) <= L // val_div
            and int(n_esc) == 0)


def _block_sparse_unpack2(nblk: int, nval: int, bitmap: np.ndarray,
                          bmask16: np.ndarray, vals: np.ndarray,
                          L: int) -> np.ndarray:
    """Host inverse of _block_sparse_pack2 → flat int16 levels (the
    single numpy implementation lives in the jax-free layout module so
    the process pack sidecars can share it)."""
    from .layout import block_sparse_unpack2_host

    return block_sparse_unpack2_host(nblk, nval, bitmap, bmask16, vals, L)


def _compact_stream(nblk, nval, bitmap, bmask16, vals):
    """Device-side stream compaction (tier 3 of the transfer pack):
    concatenate the two-tier sparse streams into ONE dense uint8
    payload per GOP, so the bulk fetch moves a single compact byte
    array instead of three budget-padded int arrays.

    Layout (layout.split_compact is the host parser):

        [ bitmap (nb8 bytes) | bmask16 as little-endian byte pairs,
          first nblk live entries | vals, first nval entries ]

    The vals section lands RIGHT AFTER the live bmask16 entries via a
    dynamic_update_slice at offset nb8 + 2*nblk, so the used prefix —
    ``used = nb8 + 2*nblk + nval`` bytes, returned alongside — is
    contiguous: the host fetches ``payload[:, :used_max]`` (quantized,
    parallel/dispatch) and the padding tail never crosses the link.
    There is no escape section: levels beyond ±127 have no side-channel
    in _block_sparse_pack2 (n_esc > 0 forces the wave-wide dense
    fallback before any payload is read).

    Returns (used int32, payload uint8[nb8 + 2*budget + vbudget]).
    """
    nb8 = bitmap.shape[0]
    budget = bmask16.shape[0]
    lo = (bmask16 & jnp.uint16(0xFF)).astype(jnp.uint8)
    hi = (bmask16 >> 8).astype(jnp.uint8)
    mb = jnp.stack([lo, hi], axis=1).reshape(-1)         # (2*budget,)
    vals_u8 = jax.lax.bitcast_convert_type(vals, jnp.uint8)
    payload = jnp.concatenate(
        [bitmap, mb, jnp.zeros(vals.shape[0], jnp.uint8)])
    # Live bmask16 entries occupy [nb8, nb8 + 2*nblk); the dead tail of
    # `mb` beyond that is all-zero (pack2 zeroes dead gathered rows), so
    # overwriting it with the vals stream loses nothing.
    payload = jax.lax.dynamic_update_slice(
        payload, vals_u8, ((nb8 + 2 * nblk).astype(jnp.int32),))
    used = (nb8 + 2 * nblk + nval).astype(jnp.int32)
    return used, payload


def _block_sparse_unpack(nblk: int, n_esc: int, bitmap: np.ndarray,
                         payload: np.ndarray, esc_pos: np.ndarray,
                         esc_val: np.ndarray, L: int) -> np.ndarray:
    """Host inverse of _block_sparse_pack → flat int16 levels (CAVLC
    levels fit int16 at every legal qp; int16 halves the memset +
    scatter traffic on the 1-core host)."""
    NB = -(-L // _BLOCK)
    bm = np.unpackbits(bitmap)[:NB].astype(bool)
    pay = payload[:nblk].astype(np.int16)
    if n_esc:
        ep = esc_pos[:n_esc]
        ok = ep < nblk * _BLOCK
        flatpay = pay.reshape(-1)
        flatpay[ep[ok]] = esc_val[:n_esc][ok].astype(np.int16)
        pay = flatpay.reshape(nblk, _BLOCK)
    out = np.zeros((NB, _BLOCK), np.int16)
    out[bm] = pay
    return out.reshape(-1)[:L]


def _pad8(mask):
    L = mask.shape[0]
    pad = (-L) % 8
    if pad:
        mask = jnp.concatenate([mask, jnp.zeros(pad, mask.dtype)])
    return mask


def sparse_fits(nnz: int, n_esc: int, L: int,
                budget_div: int = _SPARSE_BUDGET_DIV) -> bool:
    return (int(nnz) <= L // budget_div
            and int(n_esc) <= _SPARSE_ESCAPES)


def _sparse_unpack(nnz: int, n_esc: int, bitmap: np.ndarray,
                   vals: np.ndarray, esc_pos: np.ndarray,
                   esc_val: np.ndarray, L: int) -> np.ndarray:
    mask = np.unpackbits(bitmap)[:L].astype(bool)
    out = np.zeros(L, np.int32)
    out[mask] = vals[:nnz].astype(np.int32)
    if n_esc:
        out[esc_pos[:n_esc]] = esc_val[:n_esc]
    return out


@functools.partial(jax.jit, static_argnames=("mbw", "mbh"))
def _encode_intra_sparse(y, u, v, qp, *, mbw: int, mbh: int):
    luma_dc, luma_ac, chroma_dc, chroma_ac = _encode_intra(
        y, u, v, qp, mbw=mbw, mbh=mbh)
    flat = jnp.concatenate([
        luma_dc.reshape(-1), luma_ac.reshape(-1),
        chroma_dc.reshape(-1), chroma_ac.reshape(-1)])
    return _sparse_pack(flat)


def _unpack_levels(flat: np.ndarray, mbw: int, mbh: int) -> FrameLevels:
    nmb = mbw * mbh
    sizes = (nmb * 16, nmb * 16 * 15, nmb * 2 * 4, nmb * 2 * 4 * 15)
    offs = np.cumsum((0,) + sizes)
    # keep the transfer dtype: int16 feeds the zero-copy native entry
    # (cavlc_pack_islice16), int32 the original one — no widening here
    flat = np.asarray(flat)
    luma_mode, chroma_mode = _mode_policy(mbw, mbh)
    return FrameLevels(
        luma_mode=luma_mode,
        chroma_mode=chroma_mode,
        luma_dc=flat[offs[0]:offs[1]].reshape(nmb, 16),
        luma_ac=flat[offs[1]:offs[2]].reshape(nmb, 16, 15),
        chroma_dc=flat[offs[2]:offs[3]].reshape(nmb, 2, 4),
        chroma_ac=flat[offs[3]:offs[4]].reshape(nmb, 2, 4, 15),
    )


def encode_intra_jax(y: np.ndarray, u: np.ndarray, v: np.ndarray,
                     qp: int) -> FrameLevels:
    """Run the jitted intra compute and return host-side FrameLevels."""
    mbh, mbw = y.shape[0] // 16, y.shape[1] // 16
    yd, ud, vd = jnp.asarray(y), jnp.asarray(u), jnp.asarray(v)
    qpd = jnp.asarray(qp)
    L = mbw * mbh * 384
    nnz, n_esc, bitmap, vals, esc_pos, esc_val = jax.device_get(
        _encode_intra_sparse(yd, ud, vd, qpd, mbw=mbw, mbh=mbh))
    if sparse_fits(nnz, n_esc, L):
        return _unpack_levels(
            _sparse_unpack(int(nnz), int(n_esc), bitmap, vals,
                           esc_pos, esc_val, L), mbw, mbh)
    # Rare (very dense content): recompute (cheap) and fetch wide.
    flat16 = _encode_intra_packed(yd, ud, vd, qpd, mbw=mbw, mbh=mbh,
                                  dtype=jnp.int16)
    return _unpack_levels(np.asarray(flat16), mbw, mbh)


def build_intra_encoder(y_shape: tuple[int, int], qp: int):
    """Encoder-facing factory: returns fn(y, u, v) -> FrameLevels."""
    def fn(y, u, v):
        return encode_intra_jax(y, u, v, qp)
    return fn
