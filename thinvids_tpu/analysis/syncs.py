"""Pass 2 — host-sync confinement.

Blocking device→host synchronization (``jax.device_get``,
``.block_until_ready()``) serializes the wave pipeline: BENCH r04→r05
showed every device-side win dying at this boundary, and PR 4 spent a
whole change moving the last stray fetches behind
``GopShardEncoder._fetch_bulk``. This pass keeps it that way: any call
of a sync API outside the manifest's allowlist is a finding
(TVT-S001), generalizing the `device_get` grep that used to live in
tests/test_compact.py into a real AST check.

It also flags the IMPLICIT syncs a grep can't see (TVT-S002): inside a
single function, a value produced by a ``jax.*``/``jnp.*`` call that
is then fed to ``np.asarray`` / ``np.array`` / ``float`` / ``int``
forces the same blocking transfer without the word "device_get"
appearing anywhere. The taint tracking is deliberately local (names
assigned from jax-namespace calls within one function) — cheap, zero
false positives on host-only numpy code, and exactly the shape the
historical regressions took (`np.asarray(payload)` on a device array).
"""

from __future__ import annotations

import ast

from .astutil import (Finding, SourceTree, dotted_name, finding,
                      matches_any)
from .manifest import Manifest

#: numpy-side consumers that force a device sync when fed a jax value
_SYNC_SINKS = {"asarray", "array", "ascontiguousarray"}
_SCALAR_SINKS = {"float", "int"}


def _jax_aliases(tree: ast.Module) -> set[str]:
    """Local names bound to the jax / jax.numpy modules at module
    scope (`import jax`, `import jax.numpy as jnp`, ...)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                root = alias.name.split(".")[0]
                if root == "jax":
                    out.add(alias.asname or root)
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.module.split(".")[0] == "jax":
                for alias in node.names:
                    # `from jax import numpy as jnp` binds a module;
                    # `from jax.sharding import Mesh` binds a class —
                    # either way calls through it aren't device values
                    # unless they're jnp.*; keep module-ish names only
                    if alias.name == "numpy":
                        out.add(alias.asname or alias.name)
    return out


def _is_jax_call(node: ast.AST, aliases: set[str]) -> bool:
    """Call whose dotted root is a jax alias (jnp.zeros, jax.jit...)."""
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    return bool(name) and name.split(".")[0] in aliases


def _function_nodes(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def check_sync_calls(tree: SourceTree, manifest: Manifest
                     ) -> list[Finding]:
    """Flag ANY reference to a sync API name — attribute access, bare
    name, or `from jax import device_get as dg` alias — not just
    direct calls: storing/aliasing the function escapes a call-only
    check but reintroduces the same serialized fetch (the retired grep
    matched the substring anywhere; this keeps that strength with AST
    precision — docstrings and comments no longer count)."""
    findings: list[Finding] = []
    for mod in tree.modules():
        if matches_any(mod, manifest.sync_allowlist):
            continue
        for node in ast.walk(tree.tree(mod)):
            names: list[tuple[str, int]] = []
            if isinstance(node, ast.Attribute):
                names.append((node.attr, node.lineno))
            elif isinstance(node, ast.Name):
                names.append((node.id, node.lineno))
            elif isinstance(node, ast.ImportFrom):
                names.extend((alias.name, node.lineno)
                             for alias in node.names)
            for attr, line in names:
                if attr in manifest.sync_calls:
                    findings.append(finding(
                        "TVT-S001", mod, line,
                        f"blocking device sync `{attr}` referenced "
                        f"outside the allowlist — route transfers "
                        f"through GopShardEncoder._fetch_bulk",
                        key_detail=f"{mod}:{attr}"))
    uniq: dict[tuple[str, int], Finding] = {}
    for f in findings:
        uniq.setdefault((f.key, f.line), f)
    return list(uniq.values())


def check_implicit_syncs(tree: SourceTree, manifest: Manifest
                         ) -> list[Finding]:
    findings: list[Finding] = []
    for mod in tree.modules():
        if matches_any(mod, manifest.sync_allowlist):
            continue
        aliases = _jax_aliases(tree.tree(mod))
        if not aliases:
            continue                # module can't hold device values
        for fn in _function_nodes(tree.tree(mod)):
            tainted: set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and \
                        _is_jax_call(node.value, aliases):
                    for tgt in node.targets:
                        for el in (tgt.elts if isinstance(
                                tgt, (ast.Tuple, ast.List)) else [tgt]):
                            if isinstance(el, ast.Name):
                                tainted.add(el.id)
            if not tainted:
                continue
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call) and node.args):
                    continue
                func = node.func
                sink = None
                if isinstance(func, ast.Attribute) and \
                        func.attr in _SYNC_SINKS:
                    sink = func.attr
                elif isinstance(func, ast.Name) and \
                        func.id in _SCALAR_SINKS:
                    sink = func.id
                if sink is None:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Name) and arg.id in tainted:
                    findings.append(finding(
                        "TVT-S002", mod, node.lineno,
                        f"`{sink}({arg.id})` forces an implicit device "
                        f"sync on a jax value in `{fn.name}`",
                        key_detail=f"{mod}:{fn.name}"))
    return findings


def run(tree: SourceTree, manifest: Manifest) -> list[Finding]:
    return check_sync_calls(tree, manifest) \
        + check_implicit_syncs(tree, manifest)
