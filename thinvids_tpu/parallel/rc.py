"""Rate control: complexity-adaptive QP + two-pass VBR over the mesh.

The reference ran fixed-CQP hardware encodes per part
(/root/reference/worker/tasks.py:66-68) — rate control never crossed
segment boundaries. Here the GOP mesh makes global rate control a
collective: per-GOP complexity stats are exchanged with `jax.lax.psum`
over the ``gop`` mesh axis INSIDE the sharded program (BASELINE config
4's "ICI-allreduced rate-control stats"), so every device derives the
same global picture without a host round-trip, and the host then solves
per-GOP QPs against the bitrate target using the standard R ∝ 2^(-qp/6)
H.264 rate model.

Two-pass flow (`encode_vbr2pass`):
  pass 1: sharded encode at the base QP → exact per-GOP byte counts
          (the entropy pack is the true bit counter) + psum-normalized
          complexity shares from the device analysis program;
  solve:  global log2 shift from total bits vs target, per-GOP delta
          from its complexity share (busy GOPs get bits first);
  pass 2: sharded encode with the per-GOP QP vector
          (GopShardEncoder.gop_qp), slice headers carry the deltas.
"""

from __future__ import annotations

import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core.devices import shard_map
from ..core.types import EncodedSegment, Frame, VideoMeta
from .dispatch import GopShardEncoder

QP_MIN, QP_MAX = 10, 48
#: bits halve roughly every 6 QP steps (H.264 quantizer step doubles)
_QP_PER_OCTAVE = 6.0


@functools.partial(jax.jit, static_argnames=("mesh",))
def _complexity_stats(ys, *, mesh: Mesh | None):
    """(G, F, H, W) uint8 luma → ((G,) complexity, (G,) wave total).

    Complexity = mean |frame diff| over the GOP (zero-MV residual
    energy — the dominant bit driver for P frames) + intra gradient of
    the first frame (the IDR's bit driver). The wave total is exchanged
    with `jax.lax.psum` over the `gop` mesh axis when a mesh is given,
    so every device holds the GLOBAL sum without a host round-trip —
    the collective the reference's per-part CQP never had (BASELINE
    config 4).
    """
    def per_gop(y):
        y16 = y.astype(jnp.int16)
        temporal = jnp.abs(y16[1:] - y16[:-1]).astype(jnp.float32).mean() \
            if y.shape[0] > 1 else jnp.float32(0.0)
        g0 = y16[0]
        grad = (jnp.abs(g0[:, 1:] - g0[:, :-1]).astype(jnp.float32).mean()
                + jnp.abs(g0[1:] - g0[:-1]).astype(jnp.float32).mean())
        return temporal + 0.5 * grad

    def per_dev(y_g):
        local = jax.lax.map(per_gop, y_g)              # (k,)
        total = jax.lax.psum(jnp.sum(local), "gop")    # ICI allreduce
        return local, jnp.broadcast_to(total, local.shape)

    if mesh is None or mesh.devices.size == 1:
        local = jax.lax.map(per_gop, ys)
        return local, jnp.broadcast_to(jnp.sum(local), local.shape)
    shard = shard_map(per_dev, mesh=mesh, in_specs=(P("gop"),),
                          out_specs=(P("gop"), P("gop")))
    return shard(ys)


def analyze_complexity(enc: GopShardEncoder, frames: list[Frame]
                       ) -> np.ndarray:
    """Per-GOP complexity shares for a clip (sums to 1). Per-wave
    totals come from the psum'd device program; the host only sums the
    wave totals. Deterministic across mesh sizes: tested identical
    1-device vs 8-device CPU mesh."""
    comp: list[float] = []
    wave_totals: list[float] = []
    for wave, ysd in enc.stage_luma_waves(frames):
        mesh = enc.mesh if enc.num_devices > 1 else None
        local, total = _complexity_stats(ysd, mesh=mesh)
        local = np.asarray(local, np.float64)
        # pad GOPs at the wave tail repeat a real GOP: drop them, and
        # deduct them from the psum'd wave total
        pad_sum = float(local[len(wave):].sum())
        comp.extend(local[:len(wave)])
        wave_totals.append(float(np.asarray(total)[0]) - pad_sum)
    arr = np.asarray(comp, np.float64)
    return arr / max(sum(wave_totals), 1e-9)


def jnd_masked_shares(shares: np.ndarray, aq_strength: float
                      ) -> np.ndarray:
    """Perceptual (JND/masking) weighting of complexity shares for the
    octave-model solve: a busy GOP masks its own coding error (Weber —
    the same activity-masking premise as the per-MB variance AQ in
    codecs/h264/rdo), so its effective bit DEMAND grows sublinearly
    with measured complexity. shares^(1/(1+s/2)), renormalized; s = 0
    returns the input — the historical allocation — exactly."""
    s = np.asarray(shares, np.float64)
    if aq_strength <= 0 or s.size == 0:
        return s
    exponent = 1.0 / (1.0 + float(aq_strength) / 2.0)
    out = np.power(np.maximum(s, 1e-12), exponent)
    return out / out.sum()


def solve_gop_qps(base_qp: int, pass1_bytes: np.ndarray,
                  shares: np.ndarray, target_bits_total: float,
                  modulation: float = 2.0) -> np.ndarray:
    """Per-GOP QPs hitting `target_bits_total` under the octave model.

    Global shift: bits scale as 2^(-Δqp/6), so
    Δqp = 6·log2(actual/target). Per-GOP modulation nudges QP down for
    GOPs whose complexity share exceeds their bit share (they are
    under-served at flat QP) and up for over-served ones, bounded by
    ±`modulation` — the classic 2-pass allocation shape without a full
    lagrangian solve.
    """
    actual = float(pass1_bytes.sum()) * 8.0
    if actual <= 0 or target_bits_total <= 0:
        return np.full(len(pass1_bytes), base_qp, np.int32)
    shift = _QP_PER_OCTAVE * math.log2(actual / target_bits_total)
    bit_share = pass1_bytes / max(pass1_bytes.sum(), 1)
    ratio = np.clip(shares / np.maximum(bit_share, 1e-9), 0.25, 4.0)
    nudge = np.clip(_QP_PER_OCTAVE * np.log2(ratio) / 2.0,
                    -modulation, modulation)
    qps = np.rint(base_qp + shift - nudge).astype(np.int32)
    return np.clip(qps, QP_MIN, QP_MAX)


def ladder_rung_qps(base_qp: int, pixel_ratios, alpha: float = 0.75
                    ) -> np.ndarray:
    """Per-rung QPs for an ABR ladder under the octave model.

    At a fixed QP the model says R ∝ pixels · 2^(-qp/6); a good ladder
    spends MORE bits per pixel as resolution drops (the classic
    bitrate ladders follow R_rung ≈ R_top · ratio^alpha with
    alpha < 1), so the QP shift that hits that target is

        Δqp = 6 · (1 − alpha) · log2(pixel_ratio)     (ratio ≤ 1 → Δ ≤ 0)

    i.e. lower rungs encode slightly FINER than the top rung.
    `pixel_ratios` are rung_pixels / top_pixels (1.0 for the top rung,
    which therefore keeps `base_qp` exactly — the byte-identity
    invariant with the single-rendition path).
    """
    ratios = np.clip(np.asarray(pixel_ratios, np.float64), 1e-6, 1.0)
    shift = _QP_PER_OCTAVE * (1.0 - float(alpha)) * np.log2(ratios)
    qps = np.rint(base_qp + shift).astype(np.int32)
    qps[ratios >= 1.0] = base_qp        # top rung: no rounding drift
    return np.clip(qps, QP_MIN, QP_MAX)


def refine_gop_qps(prev_qps: np.ndarray, actual_bits: float,
                   target_bits: float) -> np.ndarray:
    """One fixed-point step: shift every GOP's QP by the octave-model
    correction for the measured total. Monotone in the shared shift, so
    iterating converges even when flat GOPs are QP-insensitive (the
    busy GOPs absorb the correction)."""
    shift = _QP_PER_OCTAVE * math.log2(max(actual_bits, 1.0)
                                       / max(target_bits, 1.0))
    return np.clip(np.rint(prev_qps + shift).astype(np.int32),
                   QP_MIN, QP_MAX)


def encode_vbr2pass(frames: list[Frame], meta: VideoMeta,
                    target_bitrate_kbps: float, base_qp: int = 27,
                    mesh: Mesh | None = None, gop_frames: int = 32,
                    gops_per_wave: int = 4, tolerance: float = 0.08,
                    max_refine: int = 3, enc: GopShardEncoder | None = None,
                    encode_fn=None, on_pass=None,
                    aq_strength: float = 0.0,
                    ) -> tuple[list[EncodedSegment], dict]:
    """Two-pass VBR encode (+ up to `max_refine` correction passes when
    the octave model misses — e.g. clips whose flat stretches are
    QP-insensitive). Returns (segments, stats): pass1_bits, pass2_bits,
    target_bits, gop_qps, passes.

    This is THE solve/refine loop — the executor reuses it by injecting
    its own `enc` (settings-built) and `encode_fn(enc) -> segments`
    (its retry/halt/progress wrapper); `on_pass(pass_no, gop_qps|None)`
    is a progress hook (heartbeat notes).
    """
    fps = meta.fps_num / max(1, meta.fps_den)
    duration_s = len(frames) / max(fps, 1e-9)
    target_bits = target_bitrate_kbps * 1000.0 * duration_s

    if enc is None:
        enc = GopShardEncoder(meta, qp=base_qp, mesh=mesh,
                              gop_frames=gop_frames,
                              gops_per_wave=gops_per_wave)
    if encode_fn is None:
        def encode_fn(e):
            return e.encode_waves(e.stage_waves(frames))

    if on_pass is not None:
        on_pass(1, None)
    # aq_strength > 0 also masks the GOP-level allocation: the octave
    # model serves perceptual demand, not raw residual energy
    shares = jnd_masked_shares(analyze_complexity(enc, frames),
                               aq_strength)
    pass1 = encode_fn(enc)
    pass1_bytes = np.asarray([len(s.payload) for s in pass1], np.float64)

    gop_qps = solve_gop_qps(base_qp, pass1_bytes, shares, target_bits)
    passes = 1
    while True:
        enc.gop_qp = {i: int(q) for i, q in enumerate(gop_qps)}
        if on_pass is not None:
            on_pass(passes + 1, gop_qps)
        segments = encode_fn(enc)
        passes += 1
        bits = float(sum(len(s.payload) for s in segments)) * 8.0
        err = abs(bits - target_bits) / max(target_bits, 1.0)
        at_floor = (bits > target_bits
                    and (gop_qps >= QP_MAX).all())       # can't go coarser
        at_ceil = (bits < target_bits
                   and (gop_qps <= QP_MIN).all())        # can't go finer
        if err <= tolerance or passes - 1 > max_refine or at_floor \
                or at_ceil:
            break
        gop_qps = refine_gop_qps(gop_qps, bits, target_bits)
    stats = {
        "pass1_bits": float(pass1_bytes.sum()) * 8.0,
        "pass2_bits": bits,
        "target_bits": target_bits,
        "gop_qps": gop_qps.tolist(),
        "complexity_shares": shares.tolist(),
        "passes": passes,
    }
    return segments, stats
