"""Node agent: metrics sampling, heartbeat sinks, idle-suspend gate.

Mirrors the reference agent's contracts (/root/reference/agent/agent.py:
355-496): 1 Hz metrics → registry (TTL = liveness), suspend only after
cpu + cluster-idle gates hold for suspend_idle_s, one suspend per idle
episode.
"""

import pytest

from thinvids_tpu.cluster.agent import (
    NodeAgent,
    coordinator_submitter,
    http_submitter,
    sample_device_metrics,
    sample_host_metrics,
)
from thinvids_tpu.cluster.coordinator import Coordinator
from thinvids_tpu.core.config import (
    get_settings,
    reset_live_settings,
    update_live_settings,
)


@pytest.fixture(autouse=True)
def _clean_settings():
    reset_live_settings()
    yield
    reset_live_settings()


class TestSampling:
    def test_host_metrics_fields(self):
        m = sample_host_metrics()
        assert 0.0 <= m["cpu"] <= 100.0
        assert 0.0 <= m["mem"] <= 100.0
        assert m["mem_total"] > 0
        assert "net_rx_bytes" in m and "disk" in m

    def test_device_metrics_graceful(self):
        m = sample_device_metrics()
        assert m["devices"] >= 1          # CPU backend still reports
        if "hbm_pct" in m:
            assert 0.0 <= m["hbm_pct"] <= 100.0


class TestHeartbeatSinks:
    def test_coordinator_submitter_feeds_registry(self):
        co = Coordinator()
        agent = NodeAgent(coordinator_submitter(co), host="n1",
                          clock=lambda: 1000.0)
        m = agent.tick()
        workers = {w.host: w for w in co.registry.all()}
        assert "n1" in workers
        assert workers["n1"].metrics["cpu"] == m["cpu"]
        assert workers["n1"].metrics["role"] == "encode"

    def test_http_submitter_roundtrip(self):
        from thinvids_tpu.api import ApiServer

        co = Coordinator()
        server = ApiServer(co).start()
        try:
            agent = NodeAgent(http_submitter(server.url), host="remote1")
            agent.tick()
            workers = {w.host for w in co.registry.all()}
            assert "remote1" in workers
        finally:
            server.stop()

    def test_submit_failure_does_not_crash_tick(self):
        def bad(host, metrics):
            raise OSError("network down")
        agent = NodeAgent(bad, host="n2")
        agent.tick()                      # must not raise


class TestIdleGate:
    def _agent(self, clock, idle, suspended):
        update_live_settings({"suspend_enabled": True,
                              "suspend_idle_s": 300.0,
                              "suspend_cpu_pct": 200.0})  # cpu gate open
        return NodeAgent(lambda h, m: None, host="n3",
                         settings_fn=get_settings,
                         idle_probe=lambda: idle["v"],
                         suspend_action=lambda: suspended.append(1),
                         clock=lambda: clock["t"])

    def test_suspend_after_idle_window_once(self):
        clock, idle, susp = {"t": 0.0}, {"v": True}, []
        agent = self._agent(clock, idle, susp)
        agent.tick()                      # idle episode starts
        clock["t"] = 299.0
        agent.tick()
        assert susp == []                 # window not yet elapsed
        clock["t"] = 301.0
        agent.tick()
        assert susp == [1]
        clock["t"] = 500.0
        agent.tick()
        assert susp == [1]                # once per episode

    def test_suspend_fires_once_under_concurrent_ticks(self):
        """Regression (cli.py check TVT-T001): tick() is public while
        _loop ticks on the agent thread — the idle-gate's
        check-and-set is now atomic under _gate_lock, so a tick storm
        fires suspend_action exactly once per episode."""
        import threading

        clock, idle, susp = {"t": 0.0}, {"v": True}, []
        agent = self._agent(clock, idle, susp)
        agent.tick()                      # arm the episode
        clock["t"] = 301.0
        barrier = threading.Barrier(8)

        def storm():
            barrier.wait()
            agent._idle_gate({"cpu": 0.0})

        workers = [threading.Thread(target=storm) for _ in range(8)]
        for t in workers:
            t.start()
        for t in workers:
            t.join(5)
        assert susp == [1]

    def test_activity_resets_idle_window(self):
        clock, idle, susp = {"t": 0.0}, {"v": True}, []
        agent = self._agent(clock, idle, susp)
        agent.tick()
        clock["t"] = 200.0
        idle["v"] = False                 # a job arrived
        agent.tick()
        idle["v"] = True
        clock["t"] = 450.0                # 250 s since re-idle: not yet
        agent.tick()
        clock["t"] = 460.0
        agent.tick()
        assert susp == []
        clock["t"] = 751.0
        agent.tick()
        assert susp == [1]

    def test_disabled_never_suspends(self):
        clock, idle, susp = {"t": 0.0}, {"v": True}, []
        agent = self._agent(clock, idle, susp)
        update_live_settings({"suspend_enabled": False})
        for t in (0.0, 400.0, 800.0):
            clock["t"] = t
            agent.tick()
        assert susp == []


class TestResume:
    """The suspend seam's inverse: a suspended episode can now end
    cleanly — resume_action fires once when work returns, when
    suspend_enabled is toggled off mid-episode, or when resume() is
    called explicitly (the capacity controller's wake path)."""

    def _agent(self, clock, idle, susp, res):
        update_live_settings({"suspend_enabled": True,
                              "suspend_idle_s": 300.0,
                              "suspend_cpu_pct": 200.0})
        return NodeAgent(lambda h, m: None, host="n4",
                         settings_fn=get_settings,
                         idle_probe=lambda: idle["v"],
                         suspend_action=lambda: susp.append(1),
                         resume_action=lambda: res.append(1),
                         clock=lambda: clock["t"])

    def _suspend(self, clock, agent):
        clock["t"] = 0.0
        agent.tick()
        clock["t"] = 301.0
        agent.tick()

    def test_resume_fires_when_work_returns(self):
        clock, idle, susp, res = {"t": 0.0}, {"v": True}, [], []
        agent = self._agent(clock, idle, susp, res)
        self._suspend(clock, agent)
        assert susp == [1] and res == []
        idle["v"] = False                 # work arrived
        clock["t"] = 400.0
        agent.tick()
        assert res == [1]
        agent.tick()                      # once per episode
        assert res == [1]

    def test_toggle_off_mid_episode_resumes_and_rearms(self):
        """Regression for the re-arm hole: disabling suspend_enabled
        while suspended must end the episode (resume fires) AND leave
        the gate armed for a fresh idle window when re-enabled."""
        clock, idle, susp, res = {"t": 0.0}, {"v": True}, [], []
        agent = self._agent(clock, idle, susp, res)
        self._suspend(clock, agent)
        update_live_settings({"suspend_enabled": False})
        clock["t"] = 350.0
        agent.tick()
        assert res == [1]                 # episode ended cleanly
        update_live_settings({"suspend_enabled": True})
        clock["t"] = 400.0
        agent.tick()                      # fresh window starts HERE
        clock["t"] = 699.0
        agent.tick()
        assert susp == [1]                # 299 s idle: not yet
        clock["t"] = 701.0
        agent.tick()
        assert susp == [1, 1]             # re-armed window elapsed

    def test_explicit_resume_and_episode_state(self):
        clock, idle, susp, res = {"t": 0.0}, {"v": True}, [], []
        agent = self._agent(clock, idle, susp, res)
        assert agent.episode_state() == {"suspended": False,
                                         "idle_since": None}
        assert agent.resume() is False    # nothing suspended: no-op
        self._suspend(clock, agent)
        assert agent.episode_state()["suspended"] is True
        assert agent.resume() is True
        assert res == [1]
        assert agent.episode_state()["suspended"] is False
        assert agent.resume() is False    # once per episode

    def test_resume_without_action_is_silent(self):
        clock, idle, susp = {"t": 0.0}, {"v": True}, []
        update_live_settings({"suspend_enabled": True,
                              "suspend_idle_s": 300.0,
                              "suspend_cpu_pct": 200.0})
        agent = NodeAgent(lambda h, m: None, host="n5",
                          settings_fn=get_settings,
                          idle_probe=lambda: idle["v"],
                          suspend_action=lambda: susp.append(1),
                          clock=lambda: clock["t"])
        clock["t"] = 0.0
        agent.tick()
        clock["t"] = 301.0
        agent.tick()
        assert susp == [1]
        idle["v"] = False
        clock["t"] = 400.0
        agent.tick()                      # no resume_action: no crash
        assert agent.episode_state()["suspended"] is False
