"""Job lifecycle states.

Capability port of the reference's string-backed ``Status`` enum
(/root/reference/common.py:72-97): READY, STARTING, WAITING, RUNNING,
STAMPING, STOPPED, FAILED, REJECTED, DONE, with a lenient ``parse`` that
accepts any case / surrounding whitespace and falls back to READY.
"""

from __future__ import annotations

import enum


class Status(str, enum.Enum):
    READY = "ready"        # registered, not queued
    WAITING = "waiting"    # queued for dispatch
    STARTING = "starting"  # reserved by scheduler, warmup in progress
    RUNNING = "running"    # encode pipeline active
    STAMPING = "stamping"  # verification (watermark) encode active
    STOPPED = "stopped"    # operator stop
    FAILED = "failed"      # watchdog / retry-budget failure
    REJECTED = "rejected"  # admission policy rejection
    DONE = "done"          # output committed to library

    @classmethod
    def parse(cls, value: object, default: "Status | None" = None) -> "Status":
        if isinstance(value, Status):
            return value
        if default is None:
            default = cls.READY
        if value is None:
            return default
        text = str(value).strip().lower()
        for member in cls:
            if member.value == text or member.name.lower() == text:
                return member
        return default

    @property
    def is_active(self) -> bool:
        """True while the job occupies pipeline capacity."""
        return self in (Status.STARTING, Status.RUNNING, Status.STAMPING)

    @property
    def is_terminal(self) -> bool:
        return self in (Status.STOPPED, Status.FAILED, Status.REJECTED, Status.DONE)
