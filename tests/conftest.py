"""Test configuration.

Tests run on CPU with 8 virtual devices so multi-chip sharding paths
(`shard_map` over a Mesh) are exercised without TPU hardware — the
JAX-native "fake cluster" (SURVEY.md §4). The bootstrap recipe lives in
thinvids_tpu.core.devices (shared with the driver's dryrun entry point).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from thinvids_tpu.core.devices import force_cpu_devices  # noqa: E402

force_cpu_devices(8)
