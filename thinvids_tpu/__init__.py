"""thinvids_tpu — a TPU-native distributed video transcoding framework.

A ground-up rebuild of the capabilities of AwsGeek/thinvids (a Redis/Huey/
ffmpeg/VAAPI thin-client transcoding farm) designed TPU-first:

- the encode path is jitted JAX compute (integer transforms, quantization,
  intra prediction, block motion estimation) over HBM-resident YUV planes
  plus a native C++ CAVLC entropy packer, instead of external ffmpeg+VAAPI
  processes;
- segment/GOP parallelism uses ``jax.sharding.Mesh`` + ``shard_map``
  (one closed GOP per device per wave) instead of Huey task dispatch to
  worker nodes;
- the control plane (job store, scheduler, watchdog, heartbeats, activity
  log, executor) is an in-process coordinator whose semantics port the
  reference's manager (reference: /root/reference/manager/app.py).

Layout:
    core/      video types, layered config, status/events, logging, devices
    codecs/    H.264 intra+inter encode (JAX compute, bit-exact vs
               libavcodec) + CAVLC entropy coding
    parallel/  segment planner, mesh helpers, shard_map GOP dispatch
    cluster/   coordinator, job store, admission policy, executor
    io/        y4m reader, bit writer, MP4 muxer
    tools/     libavcodec ctypes oracle (conformance decode)
    native/    C++ hot paths (CAVLC entropy packing) loaded via ctypes
"""

__version__ = "0.2.0"
