"""BENCH JSON schema guards.

The round driver parses bench.py's single JSON line; these tests pin the
schema — in particular the `stage_ms` host-stage breakdown (now
including the streaming-ingest `decode`/`stage` keys), the cold
end-to-end `fps_cold_1080p` figure, and the 4K quality key naming — on
a small CPU run (tiny resolution, no oracle decode) so a schema
regression fails fast instead of at round scoring.
"""

import bench
import pytest


def test_run_pipeline_reports_stage_breakdown():
    from thinvids_tpu.parallel.dispatch import STAGE_COUNTERS, STAGE_NAMES

    r = bench._run_pipeline(64, 48, nframes=4, qp=27, gop_frames=2,
                            quality=False)
    assert r["fps"] > 0 and r["device_fps"] > 0 and r["bytes"] > 0
    for key in STAGE_NAMES:
        assert key in r["stage_ms"]
    # the boundary counters ride in the same snapshot: actual D2H
    # traffic (bench reports it per frame) + the dense-fallback and
    # per-shard-fetch tallies
    for key in STAGE_COUNTERS:
        assert key in r["stage_ms"]
    assert r["stage_ms"]["d2h_bytes"] > 0
    assert r["stage_ms"]["waves"] >= 1


def test_run_cold_reports_streaming_breakdown():
    """The cold figure runs the production streaming ingest; its stage
    breakdown must carry the decode/stage keys that path exercises."""
    r = bench._run_cold(64, 48, nframes=4, qp=27, gop_frames=2, runs=1)
    assert r["fps"] > 0 and r["bytes"] > 0
    assert "decode" in r["stage_ms"] and "stage" in r["stage_ms"]
    assert r["stage_ms"]["waves"] >= 1


def test_bench_result_schema_includes_stage_ms():
    from thinvids_tpu.parallel.dispatch import STAGE_NAMES

    r = {"fps": 33.3, "device_fps": 50.0, "bytes": 1200,
         "stage_ms": {k: 1.0 for k in STAGE_NAMES}
         | {"waves": 2, "d2h_bytes": 6400},
         "quality": {"psnr_y": 40.1, "ssim_y": 0.99}}
    r4k = {"fps": 2.8, "device_fps": 7.0, "bytes": 9000,
           "stage_ms": {}, "quality": {"psnr_y": 41.0, "ssim_y": 0.98}}
    cold = {"fps": 31.1, "bytes": 1200,
            "stage_ms": {k: 1.0 for k in STAGE_NAMES} | {"waves": 2}}
    ladder = {"fps": 101.3, "rungs": 4,
              "rung_bits_per_frame": {"1080p": 9000, "720p": 5000,
                                      "480p": 2500, "360p": 1500},
              "h2d_bytes": 123456}
    live = {"latency_s": 0.41, "latency_p99_s": 0.62,
            "dvr_segments": 2, "segment_s": 1.0, "ingest_fps": 12.5,
            "gops": 6}
    origin = {"sessions": 500, "sessions_sustained": 498,
              "p50_segment_ms": 2.1, "p99_segment_ms": 14.7,
              "requests": 120000, "errors": 2,
              "live_latency_under_load_s": 0.9,
              "origin_hits": 90000, "origin_bytes": 1 << 30,
              "duration_s": 10.0}
    sfe = {"fps": 5.6, "latency_ms_p50": 178.0, "latency_ms_p99": 201.0,
           "bands": 8, "halo_rows": 32, "bytes": 3_000_000,
           "stage_ms": {}}
    sfe_farm = {"workers": {1: 1.4, 2: 2.5, 4: 4.1},
                "bands": {1: 1, 2: 2, 4: 4}, "halo_rows": 32}
    live_sfe = {"latency_s": 0.31, "latency_p99_s": 0.44,
                "dvr_segments": 2, "segment_s": 1.0,
                "ingest_fps": 11.0, "gops": 6}
    trace = {"fps_off": 33.5, "fps_on": 33.1, "overhead_pct": 1.2,
             "sampled": True}
    autoscale = {"p99_queue_s": 4.2, "active_worker_s": 41.0,
                 "alwayson_worker_s": 90.0, "jobs_done": 7,
                 "peak_workers": 3, "kills": 2, "partitions": 1,
                 "duration_s": 30.0}
    crash = {"reuse_pct": 58.3, "recovery_s": 6.4,
             "integrity_rejects": 2, "resumed_shards": 7,
             "total_shards": 12}
    result = bench.build_result(r, r4k, platform="cpu", qp=27, gop=8,
                                n_1080=64, cold=cold, ladder=ladder,
                                live=live, origin=origin, sfe=sfe,
                                sfe_farm=sfe_farm, live_sfe=live_sfe,
                                trace=trace, autoscale=autoscale,
                                crash=crash)
    assert result["value"] == 33.3
    assert set(STAGE_NAMES) <= set(result["stage_ms"])
    # sfe is a first-class stage key
    assert "sfe" in result["stage_ms"]
    # dense_retry is a first-class stage (not folded into fetch)
    assert "dense_retry" in result["stage_ms"]
    # the device→host boundary is a pinned, regression-checked metric:
    # e2e ÷ device fps per resolution + measured D2H bytes per frame
    assert result["host_gap_1080p"] == round(33.3 / 50.0, 3)
    assert result["host_gap_2160p"] == round(2.8 / 7.0, 3)
    assert result["d2h_bytes_per_frame"] == 100    # 6400 B / 64 frames
    # streaming-ingest stages are first-class schema keys
    assert "decode" in result["stage_ms"] and "stage" in result["stage_ms"]
    # cold end-to-end figure (decode -> encode -> concat, nothing
    # pre-staged) + its own breakdown
    assert result["fps_cold_1080p"] == 31.1
    assert "decode" in result["stage_ms_cold"]
    assert "stage" in result["stage_ms_cold"]
    # 4K quality rides with suffixed keys (VERDICT Weak #9)
    assert result["psnr_y_2160p"] == 41.0
    assert result["ssim_y_2160p"] == 0.98
    assert result["psnr_y"] == 40.1
    # ABR ladder figure: aggregate frames·rungs/s + per-rung bits/frame
    assert result["ladder_fps_1080p"] == 101.3
    assert result["ladder_rungs"] == 4
    assert result["ladder_bits_per_frame"]["360p"] == 1500
    # live LL-HLS: glass-to-playlist latency (median + p99), the DVR
    # window depth, and the paced ingest rate for context
    assert result["live_latency_s"] == 0.41
    assert result["live_latency_p99_s"] == 0.62
    assert result["live_dvr_segments"] == 2
    assert result["live_segment_s"] == 1.0
    assert result["live_ingest_fps"] == 12.5
    # split-frame encoding: per-frame glass-to-bitstream latency is a
    # MEASURED bench key, and the headline 4K fps takes the better
    # single-stream path (here SFE's 5.6 beats the GOP wave's 2.8)
    assert result["sfe_latency_ms_2160p"] == 178.0
    assert result["sfe_latency_p99_ms_2160p"] == 201.0
    assert result["sfe_fps_2160p"] == 5.6
    assert result["sfe_bands"] == 8
    assert result["sfe_halo_rows"] == 32
    assert result["fps_2160p"] == 5.6
    assert result["fps_2160p_path"] == "sfe"
    # origin-at-scale: sustained concurrent HLS sessions + MEASURED
    # segment-latency percentiles + live latency under viewer load
    assert result["origin_sessions_sustained"] == 498
    assert result["origin_p99_segment_ms"] == 14.7
    assert result["origin_p50_segment_ms"] == 2.1
    assert result["origin_requests"] == 120000
    assert result["live_latency_under_load_s"] == 0.9
    # farm SFE: the single-stream worker-count scaling curve is a
    # pinned key per worker count (the 2w >= 1.5 x 1w acceptance bar
    # reads these)
    assert result["sfe_fps_2160p_w1"] == 1.4
    assert result["sfe_fps_2160p_w2"] == 2.5
    assert result["sfe_fps_2160p_w4"] == 4.1
    # live with a banded (SFE) edge: glass-to-playlist latency line
    assert result["live_sfe_latency_s"] == 0.31
    assert result["live_sfe_latency_p99_s"] == 0.44
    # distributed-tracing cost on the e2e hot path is a pinned BENCH
    # key (acceptance gate: < 3% on the driver's run)
    assert result["trace_overhead_pct"] == 1.2
    # elastic farm under chaos: p99 queued→dispatched wait and
    # worker-seconds consumed vs always-on (the measurement raises
    # inside _run_autoscale unless active < always-on, so the pinned
    # pair is the breathing proof)
    assert result["autoscale_p99_queue_s"] == 4.2
    assert result["farm_active_worker_s"] == 41.0
    assert result["farm_alwayson_worker_s"] == 90.0
    assert result["autoscale_jobs_done"] == 7
    assert result["chaos_worker_kills"] == 2
    assert result["chaos_partitions"] == 1
    # durable shard checkpointing under coordinator SIGKILL + data
    # corruption (ISSUE 13): spool reuse on the crashed run, restart
    # recovery time, and the injected-corruption reject count
    assert result["crash_resume_shard_reuse_pct"] == 58.3
    assert result["coordinator_recovery_s"] == 6.4
    assert result["part_integrity_rejects"] == 2


def test_run_trace_overhead_measures_both_paths():
    """The tracing-overhead bench runs the SAME waves traced and
    untraced, asserts byte parity internally, and reports both fps
    figures plus the relative cost."""
    r = bench._run_trace_overhead(64, 48, nframes=4, qp=27,
                                  gop_frames=2, runs=1)
    assert r["fps_off"] > 0 and r["fps_on"] > 0
    assert r["sampled"] is True
    assert isinstance(r["overhead_pct"], float)


def test_run_sfe_reports_per_frame_latency():
    """The SFE bench drives the production split-frame path (per-frame
    band dispatch/collect) and reports measured per-frame latency
    percentiles + the band layout it actually ran with."""
    r = bench._run_sfe(64, 96, nframes=6, qp=27, gop_frames=3, bands=2,
                       runs=1)
    assert r["fps"] > 0 and r["bytes"] > 0
    assert r["bands"] == 2
    assert r["latency_ms_p99"] >= r["latency_ms_p50"] > 0
    assert r["stage_ms"]["sfe_frames"] == 6
    assert r["stage_ms"]["sfe"] > 0


def test_run_live_reports_glass_to_playlist_latency():
    """The live bench drives the PRODUCTION live pipeline (paced
    writer → tail → ladder → incremental packager → playlist poll) and
    reports per-part latency percentiles."""
    r = bench._run_live(64, 48, nframes=16, qp=27, gop_frames=4,
                        rungs_spec="24", segment_s=0.25,
                        dvr_window_s=0.0)
    assert r["latency_s"] > 0
    assert r["latency_p99_s"] >= r["latency_s"]
    assert r["dvr_segments"] >= 1
    assert r["gops"] >= 4
    assert r["ingest_fps"] > 0


@pytest.mark.slow
def test_run_origin_serves_mixed_load():
    """The origin bench drives the PRODUCTION serving stack (real
    coordinator + HTTP API + loadgen player sessions over a served VOD
    ladder while a live job encodes) and reports sustained sessions +
    measured latency. Small here — 24 sessions, tiny frames — so the
    harness itself is exercised; the driver's run uses the
    loadgen_sessions default (500)."""
    r = bench._run_origin(64, 48, nframes=16, qp=27, gop_frames=4,
                          sessions=24, duration_s=3.0,
                          rungs_spec="24")
    assert r["sessions"] == 24
    assert r["sessions_sustained"] >= 20
    assert r["p99_segment_ms"] >= r["p50_segment_ms"] > 0
    assert r["live_latency_under_load_s"] > 0
    assert r["requests"] > 0 and r["errors"] <= 2
    assert r["origin_hits"] > 0        # hot segments came from memory


@pytest.mark.slow
def test_run_sfe_farm_scaling_smoke():
    """The farm-SFE bench drives the PRODUCTION cross-host path: an
    in-process coordinator planning band shards + real single-device
    worker subprocesses exchanging halo per frame over /work/halo.
    Small here (1 and 2 workers, tiny frames — the harness is the
    measured quantity); the driver's run uses 2160p at 1/2/4."""
    r = bench._run_sfe_farm(64, 96, nframes=6, qp=27, gop_frames=3,
                            worker_counts=(1, 2))
    assert set(r["workers"]) == {1, 2}
    assert all(fps > 0 for fps in r["workers"].values())
    assert r["halo_rows"] >= 16


@pytest.mark.slow
def test_run_live_sfe_reports_latency_smoke():
    """_run_live with sfe_bands runs the banded live edge (single-rung
    stream through the per-frame SFE pipeline) and reports the same
    glass-to-playlist schema."""
    r = bench._run_live(64, 48, nframes=12, qp=27, gop_frames=3,
                        rungs_spec="48", segment_s=0.25,
                        dvr_window_s=0.0, sfe_bands=2)
    assert r["latency_s"] > 0
    assert r["latency_p99_s"] >= r["latency_s"]


@pytest.mark.slow
def test_run_autoscale_breathes_under_chaos():
    """The autoscale bench drives the PRODUCTION elastic farm: real
    worker subprocesses scaled from zero by the capacity controller
    against a diurnal submission curve, one SIGKILL and one /work
    partition. Small here (2 workers max, short window); the driver's
    run uses the full curve. The measurement itself raises unless
    every job reaches DONE byte-identical AND the farm's
    worker-seconds land below always-on."""
    r = bench._run_autoscale(64, 48, 8, qp=27, gop_frames=2,
                             duration_s=10.0, hi_rps=0.4, farm_max=2,
                             kill_interval_s=6.0, partition_s=2.0)
    assert r["jobs_done"] >= 1
    assert r["p99_queue_s"] >= 0.0
    assert 0 < r["active_worker_s"] < r["alwayson_worker_s"]
    assert r["kills"] >= 1
    assert r["partitions"] == 1


@pytest.mark.slow
def test_run_crash_resume_survives_sigkill_and_corruption():
    """The crash bench SIGKILLs a subprocess coordinator mid-farm-job
    with one in-flight upload and one spooled part bit-flipped, then
    restarts it. The measurement itself raises unless the resumed
    output is byte-identical to an uninterrupted run, >= 50% of
    shards rehydrate from the spool, and BOTH injected corruptions
    are rejected before stitch."""
    r = bench._run_crash_resume(64, 48, 24, qp=27, gop_frames=2,
                                workers=2)
    assert r["reuse_pct"] >= 50.0
    assert r["integrity_rejects"] == 2
    assert r["recovery_s"] > 0
    assert 1 <= r["resumed_shards"] <= r["total_shards"]
    assert r["total_shards"] >= 12      # 24 frames / gop 2, >= 12 GOPs


def test_run_ladder_reports_aggregate_and_shared_upload():
    """The ladder bench fans one staged wave stream across rungs:
    aggregate fps counts frames x rungs, per-rung bits ride along, and
    h2d_bytes proves upload didn't scale with the rung count."""
    r = bench._run_ladder(64, 48, nframes=4, qp=27, gop_frames=2,
                          rungs_spec="24", runs=1)
    assert r["rungs"] == 2                     # 48p (source) + 24p
    assert r["fps"] > 0
    assert set(r["rung_bits_per_frame"]) == {"48p", "24p"}
    assert all(v > 0 for v in r["rung_bits_per_frame"].values())
    # the single-rendition encoder uploads the same bytes for the same
    # clip — the ladder's extra rung derived on device, not re-uploaded
    from thinvids_tpu.core.types import VideoMeta
    from thinvids_tpu.parallel.dispatch import GopShardEncoder

    meta = VideoMeta(width=64, height=48, fps_num=30, fps_den=1,
                     num_frames=4)
    single = GopShardEncoder(meta, qp=27, gop_frames=2)
    single.prepare_waves(bench.make_frames(4, 64, 48))
    assert r["h2d_bytes"] == \
        single.stages.snapshot()["h2d_bytes"] > 0


def test_rd_figures_in_schema():
    """The r4-gate RD point: bits/frame + PSNR-Y + VMAF-proxy with the
    feature set on vs off ride the BENCH line as first-class keys."""
    from thinvids_tpu.parallel.dispatch import STAGE_NAMES

    r = {"fps": 30.0, "device_fps": 40.0, "bytes": 1000,
         "stage_ms": {k: 1.0 for k in STAGE_NAMES} | {"waves": 1},
         "quality": {}}
    r4k = {"fps": 2.0, "device_fps": 4.0, "bytes": 2000,
           "stage_ms": {}, "quality": {}}
    rd = {"qp": 25, "gop_frames": 32, "frames": 32,
          "on": {"bits_per_frame": 184369, "psnr_y": 37.54,
                 "ssim_y": 0.9146, "vmaf_proxy": 74.87},
          "off": {"bits_per_frame": 205303, "psnr_y": 37.77,
                  "ssim_y": 0.9202, "vmaf_proxy": 76.25}}
    out = bench.build_result(r, r4k, platform="cpu", qp=27, gop=8,
                             n_1080=64, rd=rd)
    assert out["rd_bits_per_frame"] == 184369
    assert out["rd_psnr_y"] == 37.54
    assert out["rd_bits_per_frame_off"] == 205303
    assert out["rd_psnr_y_off"] == 37.77
    assert out["vmaf_1080p"] == 74.87
    assert out["vmaf_1080p_off"] == 76.25
    assert out["rd_qp"] == 25 and out["rd_gop_frames"] == 32
    # the r4 gate the ON point must satisfy at 1080p
    assert out["rd_bits_per_frame"] <= 300_000
    assert out["rd_psnr_y"] >= 36.5


def test_run_rd_small():
    """_run_rd end-to-end on a tiny clip: both configs report the full
    metric set and the feature set changes the stream."""
    r = bench._run_rd(96, 80, nframes=2, qp=27, gop_frames=2)
    for cfg in ("on", "off"):
        for k in ("bits_per_frame", "psnr_y", "ssim_y", "vmaf_proxy"):
            assert k in r[cfg], (cfg, k)
        assert r[cfg]["bits_per_frame"] > 0
        assert 0 <= r[cfg]["vmaf_proxy"] <= 100
    assert r["on"]["bits_per_frame"] != r["off"]["bits_per_frame"]
