"""Compact device→host level-stream transfer (ISSUE 4).

Covers the three layers of the boundary rework: the device-side payload
compaction (jaxcore._compact_stream + the native/numpy unpack parity),
bit-identity of the compact transfer against the validated sparse2 path
(including the escape-heavy dense-fallback edge), the per-shard
concurrent fetch on the 8-device virtual mesh, the process pack
sidecars (pack_backend=process), the stage-honesty accounting
(dense_retry / dense_fallback_waves / d2h_bytes), and the sync
confinement that keeps blocking `jax.device_get` off the hot path for
good (now enforced tree-wide by `cli.py check`; the test here asserts
the analyzer manifest still encodes this file's contract).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from thinvids_tpu.codecs.h264 import jaxcore, layout
from thinvids_tpu.core.types import Frame, VideoMeta, concat_segments
from thinvids_tpu.parallel.dispatch import GopShardEncoder


def _smooth_frames(n, w=64, h=48):
    """Pan-style content that stays inside every sparse budget."""
    yy, xx = np.mgrid[0:h, 0:w]
    return [Frame(
        y=((xx + yy + 5 * i) % 256).astype(np.uint8),
        u=np.full((h // 2, w // 2), 100 + i, np.uint8),
        v=np.full((h // 2, w // 2), 140 - i, np.uint8),
    ) for i in range(n)]


def _noise_frames(n, w=64, h=48, seed=0):
    """iid noise: blows the block budget, forcing the dense fallback."""
    rng = np.random.default_rng(seed)
    return [Frame(
        y=rng.integers(0, 256, (h, w), dtype=np.uint8),
        u=rng.integers(0, 256, (h // 2, w // 2), dtype=np.uint8),
        v=rng.integers(0, 256, (h // 2, w // 2), dtype=np.uint8),
    ) for _ in range(n)]


def _pack_compact(flat):
    """flat int levels → (nblk, nval, n_esc, used, payload) as numpy."""
    nblk, nval, n_esc, bitmap, bmask16, vals = [
        np.asarray(x) for x in jaxcore._block_sparse_pack2(
            jnp.asarray(flat))]
    used, payload = [np.asarray(x) for x in jaxcore._compact_stream(
        *[jnp.asarray(v) for v in (nblk, nval, bitmap, bmask16, vals)])]
    return (int(nblk), int(nval), int(n_esc), int(used), payload,
            (bitmap, bmask16, vals))


class TestCompactStream:
    def test_roundtrip_across_sparsity_levels(self):
        # from near-empty to just under the value budget (L // 24),
        # clustered like residuals so the block budget holds
        rng = np.random.default_rng(11)
        L = 16 * 600 + 8                   # non-multiple-of-16 tail
        for hot_blocks, max_lanes in ((3, 2), (60, 3), (140, 3)):
            flat = np.zeros(L, np.int32)
            for b in rng.choice(300, hot_blocks, replace=False):
                lanes = rng.choice(16, rng.integers(1, max_lanes + 1),
                                   replace=False)
                flat[b * 16 + lanes] = rng.integers(-120, 121, len(lanes))
            nblk, nval, n_esc, used, payload, _ = _pack_compact(flat)
            assert jaxcore.block_sparse2_fits(nblk, nval, n_esc, L)
            NB = -(-L // 16)
            assert used == (NB + 7) // 8 + 2 * nblk + nval
            # the used prefix alone reconstructs the levels bit-exactly
            got = layout.unpack_compact_host(payload[:used], nblk,
                                             nval, L)
            np.testing.assert_array_equal(got, flat.astype(np.int16))

    def test_payload_used_prefix_is_contiguous(self):
        # bytes past `used` must be irrelevant: corrupting them cannot
        # change the decode (the host fetches only the prefix)
        rng = np.random.default_rng(3)
        L = 16 * 200
        flat = np.zeros(L, np.int32)
        for b in rng.choice(100, 40, replace=False):
            flat[b * 16 + rng.integers(0, 16)] = 7
        nblk, nval, _, used, payload, _ = _pack_compact(flat)
        trashed = payload.copy()
        trashed[used:] = 0xAB
        np.testing.assert_array_equal(
            layout.unpack_compact_host(trashed, nblk, nval, L),
            flat.astype(np.int16))

    def test_native_matches_numpy_and_rejects_corruption(self):
        from thinvids_tpu import native as native_mod

        if not native_mod.available():
            pytest.skip("no compiler")
        rng = np.random.default_rng(17)
        L = 16 * 777 + 8
        flat = np.zeros(L, np.int32)
        for b in rng.choice(150, 90, replace=False):
            lanes = rng.choice(16, rng.integers(1, 7), replace=False)
            flat[b * 16 + lanes] = rng.integers(-120, 121, len(lanes))
        nblk, nval, n_esc, used, payload, streams = _pack_compact(flat)
        want = jaxcore._block_sparse_unpack2(nblk, nval, *streams, L)
        got = native_mod.unpack_compact(nblk, nval, payload[:used], L)
        np.testing.assert_array_equal(got, want)
        assert got.dtype == np.int16
        # counts disagreeing with the streams must raise, not
        # mis-scatter (nval - 1: the payload is long enough, but the
        # lane masks demand one more value than the count admits) ...
        with pytest.raises(ValueError, match="inconsistent"):
            native_mod.unpack_compact(nblk, nval - 1, payload[:used], L)
        # ... and a payload shorter than its counts demand must too
        with pytest.raises(ValueError, match="truncated"):
            native_mod.unpack_compact(nblk, nval, payload[:used - 1], L)
        with pytest.raises(ValueError, match="truncated"):
            layout.unpack_compact_host(payload[:used - 1], nblk, nval, L)


class TestCompactTransferParity:
    def test_bit_identical_to_sparse2_and_moves_fewer_bytes(self):
        frames = _smooth_frames(12)
        meta = VideoMeta(width=64, height=48, num_frames=12)

        enc_new = GopShardEncoder(meta, qp=27, gop_frames=3,
                                  compact_transfer=True)
        got = concat_segments(enc_new.encode(frames))
        snap_new = enc_new.stages.snapshot()
        enc_old = GopShardEncoder(meta, qp=27, gop_frames=3,
                                  compact_transfer=False)
        want = concat_segments(enc_old.encode(frames))
        snap_old = enc_old.stages.snapshot()

        assert got == want
        # both stayed on the sparse path...
        assert snap_new["dense_fallback_waves"] == 0
        assert snap_old["dense_fallback_waves"] == 0
        # ...and the compact payload crossed the link in fewer bytes
        # than the three budget-padded arrays
        assert 0 < snap_new["d2h_bytes"] <= snap_old["d2h_bytes"]

    def test_escape_heavy_content_takes_dense_fallback_identically(self):
        # iid noise overflows the block budget: both transfer modes
        # must fall back to the dense wave and still agree bit-for-bit
        frames = _noise_frames(8, seed=23)
        meta = VideoMeta(width=64, height=48, num_frames=8)

        def run(compact):
            enc = GopShardEncoder(meta, qp=27, gop_frames=2,
                                  compact_transfer=compact)
            stream = concat_segments(enc.encode(frames))
            return stream, enc.stages.snapshot()

        got, snap_new = run(True)
        want, snap_old = run(False)
        assert got == want
        assert snap_new["dense_fallback_waves"] >= 1
        assert snap_old["dense_fallback_waves"] >= 1

    def test_dense_retry_is_its_own_stage(self, monkeypatch):
        # Stage honesty: the dense re-encode must land in dense_retry,
        # not pollute the fetch number (it used to re-encode the whole
        # wave inside prof.stage("fetch")).
        monkeypatch.setattr(jaxcore, "block_sparse2_fits",
                            lambda *a, **k: False)
        frames = _smooth_frames(8)
        meta = VideoMeta(width=64, height=48, num_frames=8)
        enc = GopShardEncoder(meta, qp=27, gop_frames=2)
        concat_segments(enc.encode(frames))
        snap = enc.stages.snapshot()
        assert snap["dense_fallback_waves"] >= 1
        assert snap["dense_retry"] > 0
        monkeypatch.undo()
        # parity with the sparse pass of the same clip
        enc2 = GopShardEncoder(meta, qp=27, gop_frames=2)
        base = concat_segments(enc2.encode(frames))
        enc3 = GopShardEncoder(meta, qp=27, gop_frames=2)
        monkeypatch.setattr(jaxcore, "block_sparse2_fits",
                            lambda *a, **k: False)
        assert concat_segments(enc3.encode(frames)) == base


class TestPerShardFetch:
    def test_concurrent_fetch_engages_and_stays_bit_identical(self):
        # 8-device mesh (conftest): the collect path must fetch with
        # one transfer per device shard AND still match the
        # single-device reference byte-for-byte.
        from thinvids_tpu.codecs.h264.encoder import encode_gop
        from thinvids_tpu.parallel.planner import plan_segments

        assert len(jax.devices()) == 8
        frames = _smooth_frames(16)
        meta = VideoMeta(width=64, height=48, num_frames=16)
        enc = GopShardEncoder(meta, qp=27, gop_frames=2)
        assert enc._fetch_pool is not None
        got = concat_segments(enc.encode(frames))
        snap = enc.stages.snapshot()
        assert snap["fetch_shards"] >= len(jax.devices())
        plan = plan_segments(16, 2, len(jax.devices()))
        want = b"".join(
            encode_gop(frames[g.start_frame:g.end_frame], meta, qp=27,
                       idr_pic_id=g.index)
            for g in plan.gops)
        assert got == want

    def test_single_device_path_has_no_fetch_pool(self):
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:1]), ("gop",))
        frames = _smooth_frames(4)
        meta = VideoMeta(width=64, height=48, num_frames=4)
        enc = GopShardEncoder(meta, qp=27, mesh=mesh, gop_frames=2)
        assert enc._fetch_pool is None
        segs = enc.encode(frames)
        assert len(segs) == 2
        assert enc.stages.snapshot()["fetch_shards"] == 0


class TestProcessPackBackend:
    def test_process_and_thread_backends_byte_identical(self):
        frames = _smooth_frames(12)
        meta = VideoMeta(width=64, height=48, num_frames=12)
        enc_t = GopShardEncoder(meta, qp=27, gop_frames=3,
                                pack_workers=2)
        base = concat_segments(enc_t.encode(frames))
        enc_p = GopShardEncoder(meta, qp=27, gop_frames=3,
                                pack_workers=2, pack_backend="process")
        if enc_p._proc_pool is None:
            pytest.skip("platform cannot spawn a process pool")
        got = concat_segments(enc_p.encode(frames))
        assert got == base
        # the sidecars actually took the GOPs (not a silent thread
        # fallback)
        assert enc_p.stages.snapshot()["proc_pack_gops"] >= 4

    def test_process_backend_dense_fallback_uses_threads(self):
        # GOPs that leave the compact path (dense wave) must still pack
        # correctly on the thread pool under pack_backend=process
        frames = _noise_frames(8, seed=5)
        meta = VideoMeta(width=64, height=48, num_frames=8)
        enc_t = GopShardEncoder(meta, qp=27, gop_frames=2)
        base = concat_segments(enc_t.encode(frames))
        enc_p = GopShardEncoder(meta, qp=27, gop_frames=2,
                                pack_backend="process")
        if enc_p._proc_pool is None:
            pytest.skip("platform cannot spawn a process pool")
        assert concat_segments(enc_p.encode(frames)) == base
        snap = enc_p.stages.snapshot()
        assert snap["dense_fallback_waves"] >= 1
        assert snap["proc_pack_gops"] == 0

    def test_broken_pool_degrades_to_inline_pack(self):
        # A sidecar pool that breaks mid-job must not fail the encode:
        # the spool bytes re-pack in-process, the pool is retired, and
        # the output stays bit-identical. No shared-memory blocks may
        # outlive the wave either way.
        from concurrent.futures import Future
        from concurrent.futures.process import BrokenProcessPool

        frames = _smooth_frames(12)
        meta = VideoMeta(width=64, height=48, num_frames=12)
        enc_t = GopShardEncoder(meta, qp=27, gop_frames=3)
        base = concat_segments(enc_t.encode(frames))

        class BrokenPool:
            def submit(self, fn, *args):
                fut = Future()
                fut.set_exception(BrokenProcessPool("child died"))
                return fut

        enc = GopShardEncoder(meta, qp=27, gop_frames=3,
                              pack_backend="process")
        enc._proc_pool = BrokenPool()
        assert concat_segments(enc.encode(frames)) == base
        assert enc._proc_pool is None       # retired after first break

    def test_pack_backend_knobs(self, monkeypatch):
        from thinvids_tpu.core.config import (get_settings,
                                              invalidate_settings_cache,
                                              update_live_settings)

        meta = VideoMeta(width=64, height=48, num_frames=4)
        monkeypatch.setenv("TVT_PACK_BACKEND", "process")
        monkeypatch.setenv("TVT_COMPACT_TRANSFER", "0")
        invalidate_settings_cache()
        try:
            enc = GopShardEncoder(meta, qp=27)
            assert enc.pack_backend == "process"
            assert enc.compact_transfer is False
            # constructor args beat the config tier
            enc2 = GopShardEncoder(meta, qp=27, pack_backend="thread",
                                   compact_transfer=True)
            assert enc2.pack_backend == "thread"
            assert enc2.compact_transfer is True
        finally:
            monkeypatch.delenv("TVT_PACK_BACKEND")
            monkeypatch.delenv("TVT_COMPACT_TRANSFER")
            invalidate_settings_cache()
        # the live tier clamps unknown backends back to "thread"
        update_live_settings({"pack_backend": "bogus"})
        try:
            assert get_settings(refresh=True).pack_backend == "thread"
        finally:
            from thinvids_tpu.core.config import reset_live_settings

            reset_live_settings()

    def test_packproc_imports_without_jax(self):
        # Pool children (spawn) import packproc fresh; dragging jax in
        # would initialize a device backend per pack worker. Run in a
        # clean interpreter so this process's imports don't mask it.
        code = ("import sys; import thinvids_tpu.parallel.packproc; "
                "assert 'jax' not in sys.modules, 'packproc pulled jax in'")
        env = dict(os.environ)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        subprocess.run([sys.executable, "-c", code], check=True, env=env,
                       timeout=120)


class TestSyncConfinement:
    """The device_get guard, migrated to the analyzer (tree-wide
    enforcement lives in `cli.py check` / tests/test_analysis.py; this
    asserts the manifest still encodes THIS subsystem's contract, so
    deleting the allowlist entry fails here, next to the code it
    protects)."""

    def test_manifest_owns_the_boundary(self):
        from thinvids_tpu.analysis import default_manifest
        from thinvids_tpu.analysis.astutil import matches_any

        m = default_manifest()
        # the wave dispatcher owns the boundary (tiny count barriers +
        # dense retry); tools/ is offline; the two codec entries are
        # single-frame/GOP reference paths off the wave hot path
        for mod in ("thinvids_tpu.parallel.dispatch",
                    "thinvids_tpu.codecs.h264.jaxcore",
                    "thinvids_tpu.codecs.h264.encoder",
                    "thinvids_tpu.tools.oracle"):
            assert matches_any(mod, m.sync_allowlist), mod
        assert "device_get" in m.sync_calls
        assert "block_until_ready" in m.sync_calls

    def test_sync_pass_clean_on_head(self, analysis_ctx):
        """A blocking `jax.device_get` outside the allowlist
        reintroduces a serialized fetch on the hot path — route
        transfers through GopShardEncoder._fetch_bulk instead."""
        from thinvids_tpu.analysis import syncs

        m, tree = analysis_ctx
        open_ = [f for f in syncs.run(tree, m)
                 if f.key not in m.waivers]
        assert not open_, "\n".join(f.format() for f in open_)


class TestProcPoolThreadSafety:
    def test_disable_proc_pool_single_shot_across_threads(self, caplog):
        """Regression (cli.py check TVT-T001): several collector
        threads can hit a broken sidecar pool in the same wave window;
        the swap-under-_proc_lock retires it exactly once (one warning,
        no double-disable, never an exception)."""
        import logging
        import threading

        enc = object.__new__(GopShardEncoder)
        enc._proc_lock = threading.Lock()
        enc._proc_pool = object()
        barrier = threading.Barrier(8)

        def hit():
            barrier.wait()
            enc._disable_proc_pool(RuntimeError("boom"))

        workers = [threading.Thread(target=hit) for _ in range(8)]
        with caplog.at_level(logging.WARNING,
                             logger="thinvids_tpu.parallel.dispatch"):
            for t in workers:
                t.start()
            for t in workers:
                t.join(5)
        assert enc._proc_pool is None
        retired = [r for r in caplog.records
                   if "pack sidecar pool broke" in r.getMessage()]
        assert len(retired) == 1
