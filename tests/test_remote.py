"""Remote worker execution backend tests (cluster/remote.py).

Three layers, increasingly integrated:

- `TestWireFormat` / `TestShardBoard`: deterministic unit tests of the
  part framing and the board's lease state machine on a fake clock —
  claim gating by role/quarantine, timeout + stale-worker requeue with
  backoff, attempt budgets, quarantine after consecutive failures.
- `TestRemoteExecutorInProcess`: a real RemoteExecutor with fake worker
  THREADS claiming straight off the board — byte-identity with
  LocalExecutor, worker death mid-shard, all-workers-dead failure,
  vbr2pass local fallback.
- `TestWorkApi` + `test_farm_end_to_end_with_worker_kill`: the HTTP
  layer, the latter the hermetic acceptance test — coordinator + 2
  worker daemon SUBPROCESSES on localhost, stitched bitstream
  byte-identical to a single-process LocalExecutor encode, and the job
  surviving a SIGKILL of one worker mid-encode.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from thinvids_tpu.cluster import Coordinator, WorkerRegistry
from thinvids_tpu.cluster.executor import LocalExecutor
from thinvids_tpu.cluster.remote import (
    RemoteExecutor,
    Shard,
    ShardBoard,
    WorkerClient,
    encode_shard,
    pack_parts,
    unpack_parts,
)
from thinvids_tpu.core.config import DEFAULT_SETTINGS, Settings
from thinvids_tpu.core.status import ShardState, Status
from thinvids_tpu.core.types import EncodedSegment, Frame, GopSpec, VideoMeta
from thinvids_tpu.io.y4m import write_y4m

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def make_settings(**over):
    values = dict(DEFAULT_SETTINGS)
    values.update(over)
    return Settings(values=values)


def clip_frames(w=64, h=48, n=16):
    yy, xx = np.mgrid[0:h, 0:w]
    return [Frame(
        y=((xx * 2 + yy + 7 * i) % 256).astype(np.uint8),
        u=np.full((h // 2, w // 2), 108, np.uint8),
        v=np.full((h // 2, w // 2), 148, np.uint8),
    ) for i in range(n)]


def write_clip(path, w=64, h=48, n=16):
    meta = VideoMeta(width=w, height=h, fps_num=30, fps_den=1, num_frames=n)
    write_y4m(str(path), meta, clip_frames(w, h, n))
    return meta


def fake_segment(index, start_frame=0, num_frames=2, payload=b"\0\0\1x"):
    return EncodedSegment(
        gop=GopSpec(index=index, start_frame=start_frame,
                    num_frames=num_frames),
        payload=payload, frame_sizes=(len(payload),))


def make_shard(sid="j0-0000", job_id="j0", gop0=0, ngops=2,
               timeout_s=60.0):
    gops = tuple(GopSpec(index=gop0 + i, start_frame=2 * (gop0 + i),
                         num_frames=2) for i in range(ngops))
    return Shard(id=sid, job_id=job_id, input_path="/in/a.y4m",
                 meta=VideoMeta(width=64, height=48), gops=gops, qp=30,
                 gop_frames=2, timeout_s=timeout_s)


class TestWireFormat:
    def test_roundtrip(self):
        segs = [fake_segment(3, 6, 2, b"\0\0\1abc"),
                fake_segment(4, 8, 1, b"\0\0\1d" * 5)]
        out = unpack_parts(pack_parts(segs))
        assert len(out) == 2
        for a, b in zip(segs, out):
            assert a.gop == b.gop
            assert a.payload == b.payload
            assert a.frame_sizes == b.frame_sizes

    def test_truncated_payload_raises(self):
        data = pack_parts([fake_segment(0)])
        with pytest.raises(ValueError):
            unpack_parts(data[:-1])

    def test_trailing_garbage_raises(self):
        data = pack_parts([fake_segment(0)])
        with pytest.raises(ValueError):
            unpack_parts(data + b"!")


def make_board(clock=None, workers=("w1", "w2", "w3"), pipeline_count=1,
               worker_metrics=True, **over):
    """Coordinator + board with `workers` heartbeated as claim-capable
    daemons; pipeline_count=1 puts the naturally-first host on the
    pipeline role and the rest on encode."""
    clock = clock or FakeClock()
    snap = make_settings(pipeline_worker_count=pipeline_count, **over)
    reg = WorkerRegistry(clock=clock)
    for hostname in workers:
        reg.heartbeat(hostname,
                      metrics={"worker": True} if worker_metrics else None,
                      now=clock())
    coord = Coordinator(registry=reg, clock=clock,
                        settings_fn=lambda: snap)
    return ShardBoard(coord, clock=clock), coord, clock


class TestShardBoard:
    def test_claim_respects_role_split(self):
        board, coord, _ = make_board()
        board.add_job("j0", [make_shard()], max_attempts=3, backoff_s=0.0,
                      quarantine_after=3)
        # w1 is the pipeline-role host; encode workers exist → denied
        assert board.claim("w1") is None
        desc = board.claim("w2")
        assert desc is not None and desc["id"] == "j0-0000"
        assert desc["gops"] == [[0, 0, 2], [1, 2, 2]]   # shard-local
        assert board.claim("w3") is None                # queue drained

    def test_pipeline_worker_claims_when_no_encode_workers(self):
        board, coord, _ = make_board(workers=("w1",), pipeline_count=8)
        board.add_job("j0", [make_shard()], max_attempts=3, backoff_s=0.0,
                      quarantine_after=3)
        assert board.claim("w1") is not None

    def test_pipeline_worker_takes_overflow(self):
        """Reserved pipeline-role workers absorb pending work the
        encode workers can't start on — the reserve must not idle a
        farm with a deep queue."""
        board, coord, _ = make_board()      # w1 pipeline, w2/w3 encode
        shards = [make_shard(sid=f"j0-{i:04d}", gop0=2 * i)
                  for i in range(5)]
        board.add_job("j0", shards, max_attempts=3, backoff_s=0.0,
                      quarantine_after=3)
        # 5 pending > 2 encode workers → overflow opens to w1
        assert board.claim("w1") is not None
        assert board.claim("w2") is not None
        assert board.claim("w3") is not None
        # 2 pending, 2 encode workers → reserve closes again
        assert board.claim("w1") is None

    def test_quarantined_worker_denied(self):
        board, coord, _ = make_board()
        coord.registry.set_disabled("w2", True, reason="flaky")
        board.add_job("j0", [make_shard()], max_attempts=3, backoff_s=0.0,
                      quarantine_after=3)
        assert board.claim("w2") is None
        assert board.claim("w3") is not None

    def test_submit_part_completes_job(self):
        board, coord, _ = make_board()
        board.add_job("j0", [make_shard()], max_attempts=3, backoff_s=0.0,
                      quarantine_after=3)
        desc = board.claim("w2")
        segs = [fake_segment(0, 0, 2), fake_segment(1, 2, 2)]
        assert board.submit_part(desc["id"], "w2", segs)
        done, total, retried, failed, _h = board.job_progress("j0")
        assert (done, total, retried, failed) == (2, 2, 0, "")
        got = board.take_segments("j0")
        assert [s.gop.index for s in got] == [0, 1]
        # lifetime counters feed /metrics_snapshot
        w2 = {w.host: w for w in coord.registry.all()}["w2"]
        assert w2.shards_done == 1

    def test_wrong_gop_coverage_rejected(self):
        board, coord, _ = make_board()
        board.add_job("j0", [make_shard()], max_attempts=3, backoff_s=0.0,
                      quarantine_after=3)
        desc = board.claim("w2")
        with pytest.raises(ValueError):
            board.submit_part(desc["id"], "w2", [fake_segment(0, 0, 2)])

    def test_lease_timeout_requeues_with_backoff(self):
        board, coord, clock = make_board()
        board.add_job("j0", [make_shard(timeout_s=60.0)], max_attempts=3,
                      backoff_s=2.0, quarantine_after=5)
        board.claim("w2")
        clock.advance(61.0)
        # keep w3 alive so the requeued shard has somewhere to go
        coord.registry.heartbeat("w3", now=clock())
        assert board.requeue_expired() == ["j0-0000"]
        _d, _t, retried, failed, _h = board.job_progress("j0")
        assert retried == 2 and failed == ""
        # backoff gates the re-claim...
        assert board.claim("w3") is None
        clock.advance(2.1)
        desc = board.claim("w3")
        assert desc is not None and desc["attempt"] == 1
        # ...and the failure counted against the lease holder
        w2 = {w.host: w for w in coord.registry.all()}["w2"]
        assert w2.shards_failed == 1 and w2.consecutive_failures == 1

    def test_stale_worker_requeues_before_deadline(self):
        """SIGKILLed worker: its heartbeat TTL expires long before the
        lease deadline; the sweep must not wait for the lease."""
        board, coord, clock = make_board()
        board.add_job("j0", [make_shard(timeout_s=3600.0)], max_attempts=3,
                      backoff_s=0.0, quarantine_after=5)
        board.claim("w2")
        clock.advance(20.0)                  # > metrics_ttl_s (15), << lease
        assert board.requeue_expired() == ["j0-0000"]
        coord.registry.heartbeat("w3", now=clock())
        assert board.claim("w3") is not None

    def test_attempt_budget_fails_job(self):
        board, coord, clock = make_board()
        board.add_job("j0", [make_shard()], max_attempts=1, backoff_s=0.0,
                      quarantine_after=99)
        for _ in range(2):
            desc = board.claim("w2")
            assert desc is not None
            board.report_failure(desc["id"], "w2", "encoder exploded")
        _d, _t, _r, failed, failed_host = board.job_progress("j0")
        assert "after 2 attempts" in failed
        assert "encoder exploded" in failed
        assert failed_host == "w2"

    def test_quarantine_after_consecutive_failures(self):
        board, coord, clock = make_board()
        shards = [make_shard(sid=f"j0-{i:04d}", gop0=2 * i)
                  for i in range(4)]
        board.add_job("j0", shards, max_attempts=5, backoff_s=0.0,
                      quarantine_after=3)
        for _ in range(3):
            desc = board.claim("w2")
            board.report_failure(desc["id"], "w2", "boom")
        w2 = {w.host: w for w in coord.registry.all()}["w2"]
        assert w2.disabled and "quarantined" in w2.quarantine_reason
        assert board.claim("w2") is None     # no more work for w2
        assert any(e["stage"] == "quarantine"
                   for e in coord.activity.fetch())

    def test_stale_failure_report_ignored_after_requeue(self):
        """An evicted worker's failure report lands after the shard was
        requeued and re-leased: it must not touch the current holder's
        lease or burn an attempt."""
        board, coord, clock = make_board()
        board.add_job("j0", [make_shard(timeout_s=10.0)], max_attempts=2,
                      backoff_s=0.0, quarantine_after=99)
        board.claim("w2")
        clock.advance(11.0)
        coord.registry.heartbeat("w3", now=clock())
        board.requeue_expired()                     # attempt 1, w2 blamed
        desc2 = board.claim("w3")
        assert desc2 is not None
        board.report_failure("j0-0000", "w2", "late crash report")
        shard = board._find_locked("j0-0000")
        assert shard.state is ShardState.ASSIGNED   # w3's lease intact
        assert shard.assigned_host == "w3"
        assert shard.attempt == 1                   # no extra attempt

    def test_late_part_from_expired_lease_accepted_once(self):
        """First result wins: the original worker's part lands after a
        requeue — the encode is deterministic, so accept it and let the
        second worker's duplicate drop."""
        board, coord, clock = make_board()
        board.add_job("j0", [make_shard(timeout_s=10.0)], max_attempts=5,
                      backoff_s=0.0, quarantine_after=99)
        board.claim("w2")
        clock.advance(11.0)
        coord.registry.heartbeat("w3", now=clock())
        board.requeue_expired()
        desc2 = board.claim("w3")
        segs = [fake_segment(0, 0, 2), fake_segment(1, 2, 2)]
        assert board.submit_part("j0-0000", "w2", segs)      # late winner
        assert not board.submit_part(desc2["id"], "w3", segs)  # duplicate
        done, total, _r, _f, _h = board.job_progress("j0")
        assert done == total == 2

    def test_restart_race_cancel_is_token_fenced(self):
        """A halted run waking after /restart_job must not cancel the
        new run's board entry; the new add_job also supersedes the old
        entry's queue slots."""
        board, coord, _ = make_board()
        board.add_job("j0", [make_shard()], max_attempts=3, backoff_s=0.0,
                      quarantine_after=3, token="run-old")
        # restart: new run installs its shards before the old run's
        # cleanup fires
        board.add_job("j0", [make_shard()], max_attempts=3, backoff_s=0.0,
                      quarantine_after=3, token="run-new")
        board.cancel_job("j0", token="run-old")     # stale: no-op
        desc = board.claim("w2")
        assert desc is not None                     # new entry intact
        assert desc["id"] == "j0-0000"
        board.cancel_job("j0", token="run-new")     # owner: removes
        _d, _t, _r, failed, _h = board.job_progress("j0")
        assert failed == "cancelled"

    def test_part_from_superseded_run_is_dropped(self):
        """Shard ids are RUN-SCOPED (the run token rides in the id), so
        a part still in flight from a superseded run resolves to NO
        shard in the restarted run's entry — the old run may have
        encoded under different job settings, and its bytes must not
        land in the new run's output. This is the TVT-M002 model's
        `cross-run-part` invariant (mutation `shared_ids` reproduces
        the pre-fix hole)."""
        board, coord, _ = make_board()
        old = make_shard(sid="j0-runAAA-0000")
        board.add_job("j0", [old], max_attempts=3, backoff_s=0.0,
                      quarantine_after=3, token="run-old")
        desc = board.claim("w2")
        assert desc["id"] == "j0-runAAA-0000"
        # restart: fresh plan under the new token → new run-scoped ids
        board.add_job("j0", [make_shard(sid="j0-runBBB-0000")],
                      max_attempts=3, backoff_s=0.0,
                      quarantine_after=3, token="run-new")
        accepted = board.submit_part(
            desc["id"], "w2", [fake_segment(0, 0, 2),
                               fake_segment(1, 2, 2)])
        assert not accepted
        done, total, *_rest = board.job_progress("j0")
        assert (done, total) == (0, 2)

    def test_shard_ids_embed_the_run_token(self, tmp_path):
        """RemoteExecutor._shards_for scopes every shard id to the run
        token that planned it (restart ⇒ disjoint id namespaces)."""
        from thinvids_tpu.cluster.jobs import Job

        settings = make_settings()
        coord, execu = make_remote_rig(tmp_path, settings)
        job = Job(id="deadbeefdeadbeef", input_path="/in/a.y4m")
        vm = VideoMeta(width=64, height=48, num_frames=16)
        plan = execu._plan_remote(16, settings)
        ids_a = [s.id for s in execu._shards_for(
            job, vm, plan, settings, qp=30, token="aaaa1111")]
        ids_b = [s.id for s in execu._shards_for(
            job, vm, plan, settings, qp=30, token="bbbb2222")]
        assert all("aaaa11" in sid for sid in ids_a)
        assert all("bbbb22" in sid for sid in ids_b)
        assert not set(ids_a) & set(ids_b)

    def test_stale_worker_claim_denied_until_real_heartbeat(self):
        """Regression (ISSUE 12 satellite): a worker whose heartbeat
        TTL lapsed used to revive itself through claim()'s
        unconditional pre-check heartbeat and win a shard — racing
        requeue_expired's pre-lock active-set snapshot, which then
        swept the fresh lease and burned an attempt. Liveness is now
        re-checked under the lock from the registry's current state,
        and only a GRANTED claim refreshes it: a stale worker's poll
        returns None until its agent actually heartbeats again."""
        board, coord, clock = make_board()
        shards = [make_shard(sid=f"j0-{i:04d}", gop0=2 * i)
                  for i in range(2)]
        board.add_job("j0", shards, max_attempts=3, backoff_s=0.0,
                      quarantine_after=9)
        assert board.claim("w2") is not None      # fresh: wins
        clock.advance(20.0)                       # > metrics_ttl_s 15
        # stale worker asks for more work: denied, NOT revived
        assert board.claim("w2") is None
        workers = {w.host: w for w in coord.registry.all()}
        assert clock() - workers["w2"].last_seen > 15.0
        # the sweep judges the stale lease without interference
        assert board.requeue_expired() == ["j0-0000"]
        # a real agent heartbeat restores eligibility
        coord.registry.heartbeat("w2", now=clock())
        assert board.claim("w2") is not None

    def test_snapshot_carries_timings(self):
        board, coord, clock = make_board()
        board.add_job("j0", [make_shard()], max_attempts=3, backoff_s=0.0,
                      quarantine_after=3)
        desc = board.claim("w2")
        clock.advance(1.5)
        board.submit_part(desc["id"], "w2",
                          [fake_segment(0, 0, 2), fake_segment(1, 2, 2)])
        snap = board.snapshot()
        assert snap["shards"]["done"] == 1
        assert snap["workers"]["w2"]["shards_done"] == 1
        assert snap["workers"]["w2"]["last_shard_s"] == 1.5
        assert snap["recent"][-1]["host"] == "w2"


# ---------------------------------------------------------------------------
# in-process executor tests (fake worker threads on the real board)
# ---------------------------------------------------------------------------


def make_remote_rig(tmp_path, settings, workers=8):
    reg = WorkerRegistry()
    for i in range(workers):
        reg.heartbeat(f"w{i:02d}", metrics={"worker": True})
    coord = Coordinator(registry=reg, settings_fn=lambda: settings)
    execu = RemoteExecutor(coord, output_dir=str(tmp_path / "lib_remote"),
                           sync=True, poll_s=0.02)
    coord._launcher = execu.launch
    return coord, execu


def board_worker(board, host, stop, die_holding=False):
    """Fake worker thread: claims straight off the board (no HTTP) and
    encodes with the real shard encoder. `die_holding=True` makes it
    vanish with its first claimed lease unfinished (SIGKILL analog)."""
    from thinvids_tpu.ingest.decode import read_video

    cache = {}

    def loop():
        while not stop.is_set():
            desc = board.claim(host)
            if desc is None:
                time.sleep(0.01)
                continue
            if die_holding:
                return                       # lease dies with us
            path = desc["input_path"]
            if path not in cache:
                cache[path] = read_video(path)[1]
            segs = encode_shard(desc, cache[path])
            board.submit_part(desc["id"], host, segs)

    t = threading.Thread(target=loop, daemon=True,
                         name=f"fake-worker-{host}")
    t.start()
    return t


def local_reference_bytes(tmp_path, clip, meta, settings):
    reg = WorkerRegistry()
    for i in range(8):
        reg.heartbeat(f"w{i:02d}")
    coord = Coordinator(registry=reg, settings_fn=lambda: settings)
    execu = LocalExecutor(coord, output_dir=str(tmp_path / "lib_local"),
                          sync=True)
    coord._launcher = execu.launch
    job = coord.add_job(str(clip), meta)
    job = coord.store.get(job.id)
    assert job.status is Status.DONE, job.failure_reason
    with open(job.output_path, "rb") as fp:
        return fp.read()


class TestRemoteExecutorInProcess:
    def test_remote_matches_local_bit_identical(self, tmp_path):
        clip = tmp_path / "clip.y4m"
        meta = write_clip(clip, n=16)
        # plan width pinned to the local mesh's 8 devices so both
        # backends derive the identical GOP plan
        snap = make_settings(gop_frames=2, qp=30, heartbeat_throttle_s=0.0,
                             remote_plan_devices=8, remote_shard_gops=2,
                             remote_no_worker_grace_s=10.0)
        want = local_reference_bytes(tmp_path, clip, meta, snap)

        coord, execu = make_remote_rig(tmp_path, snap)
        stop = threading.Event()
        for i in range(2):
            board_worker(execu.board, f"w{i:02d}", stop)
        try:
            job = coord.add_job(str(clip), meta)
        finally:
            stop.set()
        job = coord.store.get(job.id)
        assert job.status is Status.DONE, job.failure_reason
        assert job.parts_done == job.parts_total == 8
        assert job.encode_progress == 100.0
        with open(job.output_path, "rb") as fp:
            assert fp.read() == want

    def test_worker_death_mid_shard_requeues_and_completes(self, tmp_path):
        clip = tmp_path / "clip.y4m"
        meta = write_clip(clip, n=24)
        # short liveness TTL: the dead worker's lease is swept as soon
        # as its heartbeat goes stale, long before the 1h lease
        snap = make_settings(gop_frames=2, qp=30, heartbeat_throttle_s=0.0,
                             remote_plan_devices=8, remote_shard_gops=1,
                             metrics_ttl_s=0.5, remote_shard_timeout_s=3600.0,
                             remote_retry_backoff_s=0.0,
                             remote_no_worker_grace_s=30.0,
                             min_idle_workers=0)
        want = local_reference_bytes(
            tmp_path, clip, meta,
            make_settings(gop_frames=2, qp=30, heartbeat_throttle_s=0.0))

        coord, execu = make_remote_rig(tmp_path, snap, workers=2)
        stop = threading.Event()
        board_worker(execu.board, "w00", stop, die_holding=True)
        live = {"started": False}

        def start_survivor():
            # let the dying worker grab its lease first
            time.sleep(0.2)
            board_worker(execu.board, "w01", stop)
            live["started"] = True

        threading.Thread(target=start_survivor, daemon=True).start()
        # keep the survivor's heartbeat fresh under the tiny TTL
        beat = threading.Event()

        def heartbeat_survivor():
            while not beat.is_set():
                if live["started"]:
                    coord.registry.heartbeat("w01",
                                             metrics={"worker": True})
                time.sleep(0.1)

        threading.Thread(target=heartbeat_survivor, daemon=True).start()
        try:
            job = coord.add_job(str(clip), meta)
        finally:
            stop.set()
            beat.set()
        job = coord.store.get(job.id)
        assert job.status is Status.DONE, job.failure_reason
        assert job.parts_retried >= 1          # the orphaned shard
        assert any("w00" in (e.get("host") or "") and "failed" in e["message"]
                   for e in coord.activity.fetch(200))
        with open(job.output_path, "rb") as fp:
            assert fp.read() == want

    def test_all_workers_dead_fails_with_attribution(self, tmp_path):
        clip = tmp_path / "clip.y4m"
        meta = write_clip(clip, n=8)
        snap = make_settings(gop_frames=2, qp=30, heartbeat_throttle_s=0.0,
                             metrics_ttl_s=0.3, min_idle_workers=0,
                             remote_no_worker_grace_s=0.3)
        coord, execu = make_remote_rig(tmp_path, snap, workers=2)
        # the coordinator's own agent keeps heartbeating (no worker
        # flag): it must NOT suppress the all-dead detection
        beat = threading.Event()

        def coordinator_agent():
            while not beat.is_set():
                coord.registry.heartbeat("coord-host")
                time.sleep(0.05)

        threading.Thread(target=coordinator_agent, daemon=True).start()
        deadline = time.time() + 30
        try:
            job = coord.add_job(str(clip), meta)   # sync: returns failed
        finally:
            beat.set()
        job = coord.store.get(job.id)
        assert time.time() < deadline, "all-dead detection hung"
        assert job.status is Status.FAILED
        assert "no live encode workers" in job.failure_reason
        assert job.failure_stage == "encode"
        events = coord.activity.fetch(200)
        assert any(e["label"] == "ERROR"
                   and "no live encode workers" in e["message"]
                   for e in events)

    def test_vbr2pass_falls_back_to_local_mesh(self, tmp_path):
        clip = tmp_path / "clip.y4m"
        meta = write_clip(clip, n=16)
        snap = make_settings(gop_frames=4, qp=30, heartbeat_throttle_s=0.0,
                             rc_mode="vbr2pass", target_bitrate_kbps=300.0)
        coord, execu = make_remote_rig(tmp_path, snap)
        job = coord.add_job(str(clip), meta)   # no workers needed
        job = coord.store.get(job.id)
        assert job.status is Status.DONE, job.failure_reason
        assert any("coordinator mesh" in e["message"]
                   for e in coord.activity.fetch(200))

    def test_direct_mode_job_encodes_on_coordinator_mesh(self, tmp_path):
        """The admission policy's processing_mode finally has teeth:
        a direct-mode job (here: oversize under
        large_file_behavior="direct") encodes whole on the coordinator
        mesh — it completes with NO worker ever claiming."""
        import os

        clip = tmp_path / "big.y4m"
        write_clip(clip, n=8)
        meta = VideoMeta(width=64, height=48, fps_num=30, fps_den=1,
                         num_frames=8,
                         size_bytes=os.path.getsize(str(clip)))
        snap = make_settings(gop_frames=2, qp=30, heartbeat_throttle_s=0.0,
                             large_file_gb=1e-9,
                             large_file_behavior="direct",
                             min_idle_workers=0)
        coord, execu = make_remote_rig(tmp_path, snap)   # nobody claims
        job = coord.add_job(str(clip), meta)
        job = coord.store.get(job.id)
        assert job.processing_mode == "direct"
        assert job.status is Status.DONE, job.failure_reason
        assert any("direct mode" in e["message"]
                   for e in coord.activity.fetch(200))
        # nothing ever hit the farm board
        assert execu.board.snapshot()["shards"]["done"] == 0

    def test_recovered_job_defers_planning_until_workers_heartbeat(
            self, tmp_path):
        """The coordinator-restart scenario (ROADMAP open item): the
        job launches while only non-claim-capable agents are registered
        (the coordinator's own device pseudo-hosts). Shard planning
        must wait for the first worker heartbeats instead of
        degenerating to 2 giant shards against an empty farm."""
        clip = tmp_path / "clip.y4m"
        meta = write_clip(clip, n=16)
        snap = make_settings(gop_frames=2, qp=30, heartbeat_throttle_s=0.0,
                             remote_plan_devices=8,
                             remote_no_worker_grace_s=10.0,
                             min_idle_workers=0)
        coord, execu = make_remote_rig(tmp_path, snap, workers=0)
        # metrics-only agents satisfy admission but can't take shards
        for i in range(8):
            coord.registry.heartbeat(f"dev{i}")
        stop = threading.Event()

        def late_farm():
            time.sleep(0.15)
            for i in range(4):
                coord.registry.heartbeat(f"w{i:02d}",
                                         metrics={"worker": True})
                time.sleep(0.3)     # STAGGERED re-heartbeats, like a
                                    # real farm restart — the settle
                                    # window must count the farm whole,
                                    # not plan on worker #1 alone
            for i in range(2):
                board_worker(execu.board, f"w{i:02d}", stop)

        threading.Thread(target=late_farm, daemon=True).start()
        try:
            job = coord.add_job(str(clip), meta)
        finally:
            stop.set()
        job = coord.store.get(job.id)
        assert job.status is Status.DONE, job.failure_reason
        events = [e["message"] for e in coord.activity.fetch(400)]
        # 8 GOPs over the 4 late workers -> auto ~2 shards/worker ->
        # 8 single-GOP shards; the empty-registry degenerate plan
        # would have been "as 2 shards"
        assert any("as 8 shards" in m for m in events), events
        assert job.parts_total == 8


# ---------------------------------------------------------------------------
# HTTP layer
# ---------------------------------------------------------------------------


class TestWorkApi:
    def test_claim_part_status_over_http(self, tmp_path):
        from thinvids_tpu.api.server import ApiServer

        board, coord, _ = make_board(clock=None)
        board.add_job("j0", [make_shard()], max_attempts=3, backoff_s=0.0,
                      quarantine_after=3)
        api = ApiServer(coord, work=board).start()
        try:
            client = WorkerClient(api.url, timeout_s=5.0)
            assert client.claim("w1") is None          # pipeline-role
            desc = client.claim("w2")
            assert desc["id"] == "j0-0000"
            segs = [fake_segment(0, 0, 2), fake_segment(1, 2, 2)]
            assert client.upload_part(desc["id"], "w2", segs)
            done, total, _r, _f, _h = board.job_progress("j0")
            assert done == total == 2
            # /metrics_snapshot carries the farm stats
            with urllib.request.urlopen(
                    api.url + "/metrics_snapshot", timeout=5) as resp:
                out = json.loads(resp.read())
            assert out["work"]["shards"]["done"] == 1
            # failure report path
            board.add_job("j1", [make_shard(sid="j1-0000", job_id="j1")],
                          max_attempts=3, backoff_s=0.0, quarantine_after=3)
            desc = client.claim("w3")
            client.report_failure(desc["id"], "w3", "synthetic")
            _d, _t, retried, _f, _h = board.job_progress("j1")
            assert retried == 2
        finally:
            api.stop()

    def test_work_routes_503_without_backend(self):
        from thinvids_tpu.api.server import ApiServer

        coord = Coordinator(settings_fn=lambda: make_settings())
        api = ApiServer(coord)      # no work board attached
        with pytest.raises(Exception) as ei:
            api.route("POST", "/work/claim", {}, {"host": "w1"})
        assert getattr(ei.value, "status", None) == 503


class TestWorkerResilience:
    """ISSUE 13 satellite: jittered-backoff retries on the worker's
    HTTP surface — the coordinator's restart window (refused
    connections, 5xx) must neither fail shards nor quarantine healthy
    workers, and an integrity-rejected upload must heal by re-sending
    the idempotent part, not by re-encoding."""

    def _rig(self, tmp_path):
        snap = make_settings(gop_frames=2, qp=30,
                             pipeline_worker_count=0,
                             heartbeat_throttle_s=0.0)
        reg = WorkerRegistry()
        reg.heartbeat("w-res", metrics={"worker": True})
        coord = Coordinator(registry=reg, settings_fn=lambda: snap)
        board = ShardBoard(coord, spool_dir=str(tmp_path / "spool"))
        return coord, board

    def _real_shard(self, clip, meta, sid="jres-0000"):
        gops = tuple(GopSpec(index=i, start_frame=2 * i, num_frames=2)
                     for i in range(2))
        return Shard(id=sid, key="0000", job_id="jres",
                     input_path=str(clip), meta=meta, gops=gops,
                     qp=30, gop_frames=2, timeout_s=120.0)

    def test_claim_loop_survives_api_bounce(self, tmp_path):
        from thinvids_tpu.api.server import ApiServer
        from thinvids_tpu.cluster.remote import WorkerDaemon

        clip = tmp_path / "clip.y4m"
        meta = write_clip(clip, n=4)
        coord, board = self._rig(tmp_path)
        api = ApiServer(coord, work=board).start()
        port = api.port
        client = WorkerClient(api.url, timeout_s=5.0, retries=40,
                              backoff_s=0.05)
        daemon = WorkerDaemon(api.url, host="w-res", poll_s=0.05,
                              client=client)
        stop = threading.Event()
        threading.Thread(target=daemon.run_forever, args=(stop,),
                         daemon=True).start()
        try:
            time.sleep(0.3)             # daemon is mid-claim-loop
            api.stop()                  # bounce: restart window begins
            time.sleep(0.5)
            api = ApiServer(coord, host="127.0.0.1", port=port,
                            work=board).start()
            # work posted AFTER the bounce: the retrying claim loop
            # must find it without ever surfacing a shard failure
            board.add_job("jres", [self._real_shard(clip, meta)],
                          max_attempts=3, backoff_s=0.0,
                          quarantine_after=3)
            deadline = time.time() + 60
            while time.time() < deadline:
                done, total, *_rest = board.job_progress("jres")
                if total and done >= total:
                    break
                coord.registry.heartbeat("w-res",
                                         metrics={"worker": True})
                time.sleep(0.1)
            done, total, retried, failed, _h = board.job_progress("jres")
            assert (done, total, failed) == (2, 2, "")
            assert retried == 0
            assert daemon.shards_failed == 0
            assert daemon.shards_done == 1
        finally:
            stop.set()
            api.stop()

    def test_upload_retries_through_integrity_reject(self, tmp_path):
        """An upload corrupted in transit: ingest rejects on digest,
        the lease comes straight back, and the worker's retry of the
        same (idempotent) upload lands — no attempt burned, no
        quarantine accounting, no re-encode."""
        from thinvids_tpu.api.server import ApiServer
        from thinvids_tpu.cluster.remote import WorkerDaemon

        clip = tmp_path / "clip.y4m"
        meta = write_clip(clip, n=4)
        coord, board = self._rig(tmp_path)
        api = ApiServer(coord, work=board).start()
        try:
            board.add_job("jres", [self._real_shard(clip, meta)],
                          max_attempts=3, backoff_s=0.0,
                          quarantine_after=3)
            api.corrupt_parts(1)        # chaos: flip a bit in the next
            client = WorkerClient(      # upload body before unpack
                api.url, timeout_s=5.0, retries=5, backoff_s=0.05)
            daemon = WorkerDaemon(api.url, host="w-res", poll_s=0.05,
                                  client=client)
            assert daemon.step()        # one claim → encode → upload
            done, total, retried, failed, _h = board.job_progress("jres")
            assert (done, total, failed) == (2, 2, "")
            assert retried == 0                      # no attempt burn
            assert daemon.shards_done == 1
            assert daemon.shards_failed == 0
            snap = board.snapshot()
            assert snap["integrity_rejects"] == 1
            w = {x.host: x for x in coord.registry.all()}["w-res"]
            assert w.consecutive_failures == 0
        finally:
            api.stop()

    def test_upload_gives_up_after_retry_budget(self, tmp_path):
        """Every retry rejected (persistent corruption): upload_part
        returns False instead of looping forever."""
        from thinvids_tpu.api.server import ApiServer

        clip = tmp_path / "clip.y4m"
        meta = write_clip(clip, n=4)
        coord, board = self._rig(tmp_path)
        api = ApiServer(coord, work=board).start()
        try:
            board.add_job("jres", [self._real_shard(clip, meta)],
                          max_attempts=5, backoff_s=0.0,
                          quarantine_after=9)
            client = WorkerClient(api.url, timeout_s=5.0, retries=2,
                                  backoff_s=0.01)
            desc = board.claim("w-res")
            api.corrupt_parts(10)       # poison every retry
            segs = encode_shard(desc, read_video_frames(str(clip)))
            assert client.upload_part(desc["id"], "w-res", segs) is False
            assert board.snapshot()["integrity_rejects"] == 3
        finally:
            api.stop()


def read_video_frames(path):
    from thinvids_tpu.ingest.decode import read_video

    return read_video(path)[1]


# ---------------------------------------------------------------------------
# hermetic multi-process farm (the acceptance test)
# ---------------------------------------------------------------------------


def _call(base, path, method="GET", body=None, timeout=10):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(base + path, data=data, method=method)
    if data:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _wait(predicate, deadline_s, interval=0.25, what="condition"):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        out = predicate()
        if out:
            return out
        time.sleep(interval)
    raise TimeoutError(f"timed out waiting for {what}")


def _farm_env(tmp_path):
    return dict(
        os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
        TVT_EXECUTION_BACKEND="remote",
        TVT_MIN_IDLE_WORKERS="0", TVT_PIPELINE_WORKER_COUNT="2",
        TVT_REMOTE_PLAN_DEVICES="8", TVT_REMOTE_SHARD_GOPS="1",
        TVT_METRICS_TTL_S="3", TVT_REMOTE_RETRY_BACKOFF_S="0.2",
        TVT_GOP_FRAMES="2", TVT_QP="30", TVT_SCHEDULER_POLL_S="0.5")


def _spawn_worker(base, name, env):
    return subprocess.Popen(
        [sys.executable, "-m", "thinvids_tpu.cli", "worker",
         "--coordinator", base, "--node-name", name,
         "--interval", "0.3", "--poll", "0.2"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def test_farm_end_to_end_with_worker_kill(tmp_path):
    """Acceptance: coordinator + 2 localhost worker daemons encode a
    clip whose stitched MP4 is BYTE-identical to the single-process
    LocalExecutor output; a second job still completes after one worker
    daemon is SIGKILLed mid-encode."""
    import socket as socket_mod

    clip1 = tmp_path / "clip1.y4m"
    meta1 = write_clip(clip1, n=16)
    clip2 = tmp_path / "clip2.y4m"
    meta2 = write_clip(clip2, n=36)
    # in-process references on the 8-device test mesh (same plan width
    # as TVT_REMOTE_PLAN_DEVICES pins farm-side)
    ref_settings = make_settings(gop_frames=2, qp=30,
                                 heartbeat_throttle_s=0.0)
    want1 = local_reference_bytes(tmp_path / "r1", clip1, meta1,
                                  ref_settings)
    want2 = local_reference_bytes(tmp_path / "r2", clip2, meta2,
                                  ref_settings)

    with socket_mod.socket() as sk:
        sk.bind(("127.0.0.1", 0))
        port = sk.getsockname()[1]
    base = f"http://127.0.0.1:{port}"
    env = _farm_env(tmp_path)
    coord = subprocess.Popen(
        [sys.executable, "-m", "thinvids_tpu.cli", "coordinator",
         "--host", "127.0.0.1", "--port", str(port),
         "--state-dir", str(tmp_path / "state"),
         "--output-dir", str(tmp_path / "library")],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    workers = []
    try:
        _wait(lambda: _try_health(base), 45, what="coordinator API")
        workers = [_spawn_worker(base, f"farm-w{i}", env)
                   for i in range(2)]
        _wait(lambda: len([n for n in _call(base, "/nodes_data")["nodes"]
                           if n["host"].startswith("farm-w")]) == 2,
              30, what="both workers registered")

        # ---- job 1: byte-identity ------------------------------------
        job1 = _call(base, "/add_job", "POST",
                     {"input_path": str(clip1)})
        done1 = _wait(lambda: _job_if_terminal(base, job1["id"]), 180,
                      what="job1 terminal")
        assert done1["status"] == "done", done1
        with open(done1["output_path"], "rb") as fp:
            assert fp.read() == want1

        # ---- job 2: SIGKILL one worker mid-encode --------------------
        job2 = _call(base, "/add_job", "POST",
                     {"input_path": str(clip2)})

        def victim_busy():
            m = _call(base, "/metrics_snapshot")["metrics"]
            return m.get("farm-w0", {}).get("worker_busy") or None

        try:
            _wait(victim_busy, 60, interval=0.1,
                  what="farm-w0 busy on a shard")
        except TimeoutError:
            pass        # job may already be draining; kill regardless
        workers[0].kill()                      # SIGKILL, no goodbye
        workers[0].wait(timeout=10)
        done2 = _wait(lambda: _job_if_terminal(base, job2["id"]), 240,
                      what="job2 terminal after worker kill")
        assert done2["status"] == "done", done2
        with open(done2["output_path"], "rb") as fp:
            assert fp.read() == want2
        # the farm stats made it to the metrics surface
        snap = _call(base, "/metrics_snapshot")
        assert snap.get("work", {}).get("workers"), snap.get("work")
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait(timeout=10)
        coord.send_signal(signal.SIGTERM)
        try:
            coord.wait(timeout=15)
        except subprocess.TimeoutExpired:
            coord.kill()


def test_coordinator_crash_resume_end_to_end(tmp_path):
    """Acceptance (ISSUE 13): the coordinator is SIGKILLed mid-farm-job
    and restarted over the same state dir. The job must land DONE with
    output BYTE-identical to an uninterrupted run, with >= 1 shard
    rehydrated from the durable part spool (the reuse counter) instead
    of re-encoded — and a spool corruption injected while the
    coordinator was down must be rejected at resume, never stitched."""
    import socket as socket_mod

    clip = tmp_path / "clip.y4m"
    meta = write_clip(clip, n=28)       # 14 GOPs → 14 1-GOP shards
    ref_settings = make_settings(gop_frames=2, qp=30,
                                 heartbeat_throttle_s=0.0)
    want = local_reference_bytes(tmp_path / "ref", clip, meta,
                                 ref_settings)

    with socket_mod.socket() as sk:
        sk.bind(("127.0.0.1", 0))
        port = sk.getsockname()[1]
    base = f"http://127.0.0.1:{port}"
    env = dict(_farm_env(tmp_path),
               TVT_REMOTE_HTTP_RETRIES="12",
               TVT_REMOTE_HTTP_BACKOFF_S="0.2")
    state_dir = str(tmp_path / "state")

    def spawn_coordinator():
        return subprocess.Popen(
            [sys.executable, "-m", "thinvids_tpu.cli", "coordinator",
             "--host", "127.0.0.1", "--port", str(port),
             "--state-dir", state_dir,
             "--output-dir", str(tmp_path / "library")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    coord = spawn_coordinator()
    workers = []
    try:
        _wait(lambda: _try_health(base), 45, what="coordinator API")
        workers = [_spawn_worker(base, f"crash-w{i}", env)
                   for i in range(2)]
        _wait(lambda: len([n for n in _call(base, "/nodes_data")["nodes"]
                           if n["host"].startswith("crash-w")]) == 2,
              30, what="both workers registered")
        job = _call(base, "/add_job", "POST", {"input_path": str(clip)})

        def partially_done():
            try:
                done = _call(base, "/work/board")["shards"]["done"]
            except Exception:   # noqa: BLE001 - board not up yet
                return None
            return done if done >= 4 else None

        _wait(partially_done, 120, interval=0.1,
              what="4+ shards spooled before the crash")
        coord.kill()                    # SIGKILL, no journal goodbye
        coord.wait(timeout=10)

        # chaos: one spooled part rots while the coordinator is down
        # (the production chaos helper the bench tier uses)
        from thinvids_tpu.tools.loadgen import corrupt_spooled_part

        spool_dir = os.path.join(state_dir, "part-spool", job["id"])
        assert corrupt_spooled_part(
            os.path.join(state_dir, "part-spool"), job["id"]) is not None

        coord = spawn_coordinator()     # restart over the same state
        _wait(lambda: _try_health(base), 45,
              what="coordinator API after restart")
        done = _wait(lambda: _job_if_terminal(base, job["id"]), 240,
                     what="job terminal after coordinator restart")
        assert done["status"] == "done", done
        with open(done["output_path"], "rb") as fp:
            assert fp.read() == want    # byte-identical despite the
                                        # crash AND the corruption
        snap = _call(base, "/metrics_snapshot")["work"]
        assert snap["resumed"] >= 1, snap       # spool reuse, not a
                                                # full re-encode
        assert snap["integrity_rejects"] >= 1, snap  # the flipped part
                                                # was caught at resume
        # the finished job released its checkpoint + spool
        assert not os.path.exists(spool_dir)
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait(timeout=10)
        if coord.poll() is None:
            coord.send_signal(signal.SIGTERM)
            try:
                coord.wait(timeout=15)
            except subprocess.TimeoutExpired:
                coord.kill()


def _try_health(base):
    try:
        return _call(base, "/health", timeout=3)
    except (urllib.error.URLError, ConnectionError, OSError):
        return None


def _job_if_terminal(base, job_id):
    job = _call(base, f"/job_properties/{job_id}")["job"]
    return job if job["status"] in ("done", "failed", "stopped") else None
