"""YUV4MPEG2 (y4m) reader/writer.

The uncompressed frame interchange format for the framework: ingest test
clips, dump reconstructions for quality harnesses. Replaces the reference's
reliance on ffmpeg for raw frame access (/root/reference/worker/tasks.py:190).
Supports C420 (jpeg/mpeg2/paldv tagged), C422, C444 and mono, 8-bit.
"""

from __future__ import annotations

import io
import os
from typing import BinaryIO, Iterator

import numpy as np

from ..core.types import ChromaFormat, Frame, VideoMeta

_COLORSPACE_TO_CHROMA = {
    "C420": ChromaFormat.YUV420,
    "C420jpeg": ChromaFormat.YUV420,
    "C420mpeg2": ChromaFormat.YUV420,
    "C420paldv": ChromaFormat.YUV420,
    "C422": ChromaFormat.YUV422,
    "C444": ChromaFormat.YUV444,
    "Cmono": ChromaFormat.YUV400,
}

_CHROMA_TO_COLORSPACE = {
    ChromaFormat.YUV420: "C420jpeg",
    ChromaFormat.YUV422: "C422",
    ChromaFormat.YUV444: "C444",
    ChromaFormat.YUV400: "Cmono",
}


class Y4MReader:
    """Streaming y4m reader; iterate to get :class:`Frame` objects."""

    def __init__(self, fp: BinaryIO) -> None:
        self._fp = fp
        header = self._read_line()
        if not header.startswith("YUV4MPEG2"):
            raise ValueError("not a YUV4MPEG2 stream")
        self.width = 0
        self.height = 0
        self.fps_num, self.fps_den = 30, 1
        self.chroma = ChromaFormat.YUV420
        self.interlace = "p"
        for token in header.split()[1:]:
            tag, rest = token[0], token[1:]
            if tag == "W":
                self.width = int(rest)
            elif tag == "H":
                self.height = int(rest)
            elif tag == "F":
                num, den = rest.split(":")
                self.fps_num, self.fps_den = int(num), int(den)
            elif tag == "I":
                self.interlace = rest
            elif tag == "C":
                try:
                    self.chroma = _COLORSPACE_TO_CHROMA[token]
                except KeyError:
                    raise ValueError(f"unsupported colorspace {token!r}") from None
        if self.width <= 0 or self.height <= 0:
            raise ValueError("y4m header missing W/H")
        if self.interlace not in ("p", "?"):
            raise ValueError("interlaced y4m is not supported")

    def _read_line(self) -> str:
        raw = bytearray()
        while True:
            b = self._fp.read(1)
            if not b:
                raise EOFError("truncated y4m header")
            if b == b"\n":
                return raw.decode("ascii")
            raw += b
            if len(raw) > 512:
                raise ValueError("y4m header line too long")

    @property
    def meta(self) -> VideoMeta:
        return VideoMeta(
            width=self.width,
            height=self.height,
            fps_num=self.fps_num,
            fps_den=self.fps_den,
            chroma=self.chroma,
            codec="rawvideo",
        )

    def _plane_shapes(self) -> list[tuple[int, int]]:
        shapes = [(self.height, self.width)]
        if self.chroma.has_chroma:
            hdiv, vdiv = self.chroma.subsampling
            ch = (self.height + vdiv - 1) // vdiv
            cw = (self.width + hdiv - 1) // hdiv
            shapes += [(ch, cw), (ch, cw)]
        return shapes

    def __iter__(self) -> Iterator[Frame]:
        idx = 0
        while True:
            try:
                line = self._read_line()
            except EOFError:
                return
            if not line.startswith("FRAME"):
                raise ValueError(f"expected FRAME marker, got {line!r}")
            planes = []
            for h, w in self._plane_shapes():
                data = self._fp.read(h * w)
                if len(data) != h * w:
                    raise EOFError("truncated y4m frame payload")
                planes.append(np.frombuffer(data, np.uint8).reshape(h, w))
            y = planes[0]
            u, v = (planes[1], planes[2]) if len(planes) == 3 else (None, None)
            yield Frame(y, u, v, pts=idx)
            idx += 1


class Y4MWriter:
    """Streaming y4m writer."""

    def __init__(self, fp: BinaryIO, meta: VideoMeta) -> None:
        self._fp = fp
        self._meta = meta
        colorspace = _CHROMA_TO_COLORSPACE[meta.chroma]
        fp.write(
            f"YUV4MPEG2 W{meta.width} H{meta.height} "
            f"F{meta.fps_num}:{meta.fps_den} Ip A1:1 {colorspace}\n".encode()
        )

    def write(self, frame: Frame) -> None:
        if (frame.height, frame.width) != (self._meta.height, self._meta.width):
            raise ValueError("frame size does not match stream header")
        self._fp.write(b"FRAME\n")
        self._fp.write(np.ascontiguousarray(frame.y).tobytes())
        if frame.u is not None:
            self._fp.write(np.ascontiguousarray(frame.u).tobytes())
            self._fp.write(np.ascontiguousarray(frame.v).tobytes())


class Y4MRangeReader:
    """O(1) frame-range access to a .y4m file on disk.

    8-bit y4m frames are fixed-size records (a bare ``FRAME\\n`` marker
    + a constant plane payload), so frame ``i`` lives at a computable
    byte offset — the property the streaming ingest pipeline
    (ingest/decode.py) uses to hand a remote worker ONLY its shard's
    frame range and to restart iteration per encode pass without
    re-reading the prefix. Frame-header parameters (``FRAME Ixyz``)
    would break the arithmetic; they are detected and rejected on read
    (probe_video already assumes their absence, ingest/probe.py).
    """

    _MARKER = b"FRAME\n"

    def __init__(self, path: str | os.PathLike) -> None:
        self.path = os.fspath(path)
        self._size = os.path.getsize(self.path)
        with open(self.path, "rb") as fp:
            header = Y4MReader(fp)
            self._data_start = fp.tell()
        self._header = header               # header facts; its fp is closed
        self._shapes = header._plane_shapes()
        payload = sum(h * w for h, w in self._shapes)
        self._record = len(self._MARKER) + payload
        self.num_frames = max(0, (self._size - self._data_start)
                              // self._record)
        # Fail at OPEN time for parameterized frame markers: the
        # fixed-record arithmetic (shared with probe_video) is wrong
        # for them, and surfacing that here beats a mid-encode
        # ValueError after partial work. Mixed files that go bad later
        # are still caught by the per-frame marker check in
        # read_range.
        if self.num_frames > 0:
            with open(self.path, "rb") as fp:
                fp.seek(self._data_start)
                first = fp.read(len(self._MARKER))
            if first != self._MARKER:
                raise ValueError(
                    f"{self.path}: first frame marker {first!r} is not "
                    f"a bare FRAME record — parameterized y4m frame "
                    f"headers are unsupported by the streaming reader "
                    f"(probe_video makes the same assumption)")

    @property
    def meta(self) -> VideoMeta:
        h = self._header
        return VideoMeta(
            width=h.width, height=h.height,
            fps_num=h.fps_num, fps_den=h.fps_den,
            num_frames=self.num_frames, chroma=h.chroma,
            codec="rawvideo",
            duration_s=self.num_frames / h.meta.fps if h.meta.fps else 0.0,
            size_bytes=self._size,
        )

    def read_range(self, start: int, stop: int) -> Iterator[Frame]:
        """Yield frames [start, stop) straight from their byte offsets.
        Each call opens its own file handle, so concurrent iterations
        (an encode pass overlapping an analysis pass) never share a
        cursor."""
        start = max(0, start)
        stop = min(self.num_frames, stop)
        if stop <= start:
            return
        with open(self.path, "rb") as fp:
            fp.seek(self._data_start + start * self._record)
            for idx in range(start, stop):
                marker = fp.read(len(self._MARKER))
                if marker != self._MARKER:
                    raise ValueError(
                        f"{self.path}: frame {idx} marker {marker!r} is "
                        f"not a bare FRAME record (parameterized y4m "
                        f"frame headers are unsupported for range reads)")
                planes = []
                for h, w in self._shapes:
                    data = fp.read(h * w)
                    if len(data) != h * w:
                        raise EOFError("truncated y4m frame payload")
                    planes.append(np.frombuffer(data, np.uint8).reshape(h, w))
                y = planes[0]
                u, v = ((planes[1], planes[2]) if len(planes) == 3
                        else (None, None))
                yield Frame(y, u, v, pts=idx)


def read_y4m(path: str | os.PathLike) -> tuple[VideoMeta, list[Frame]]:
    with open(path, "rb") as fp:
        reader = Y4MReader(fp)
        frames = list(reader)
    meta = reader.meta
    return (
        VideoMeta(
            width=meta.width,
            height=meta.height,
            fps_num=meta.fps_num,
            fps_den=meta.fps_den,
            num_frames=len(frames),
            chroma=meta.chroma,
            codec="rawvideo",
            duration_s=len(frames) / meta.fps if meta.fps else 0.0,
            size_bytes=os.path.getsize(path),
        ),
        frames,
    )


def write_y4m(path: str | os.PathLike, meta: VideoMeta, frames: list[Frame]) -> None:
    with open(path, "wb") as fp:
        writer = Y4MWriter(fp, meta)
        for frame in frames:
            writer.write(frame)


def frames_to_bytes(meta: VideoMeta, frames: list[Frame]) -> bytes:
    buf = io.BytesIO()
    writer = Y4MWriter(buf, meta)
    for frame in frames:
        writer.write(frame)
    return buf.getvalue()
