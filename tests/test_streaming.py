"""Streaming ingest pipeline tests (the ingest→stage→device tentpole):

- FrameSource range access: y4m O(1) frame-range seek, mp4 GOP-range
  decode, lazy slicing windows — all with the frames-decoded counter
  proving the work is O(range), not O(clip).
- Streamed-vs-materialized parity: the production streaming path
  (open_video + background staging) emits a bitstream byte-identical
  to the materialized list path, for y4m and mp4 inputs.
- Bounded residency: a multi-wave encode never holds more than one
  wave of decoded frames in the staging cursor.
- Remote shard-range: a worker daemon's claim decodes only its
  shard's [f0, f0+n) frame range.
- Guard: the executors and the worker daemon must never regress to
  the list-materializing read_video prologue.
"""


import numpy as np
import pytest

from thinvids_tpu.core.types import Frame, GopSpec, VideoMeta, concat_segments
from thinvids_tpu.ingest.decode import open_video, read_video
from thinvids_tpu.io.y4m import write_y4m
from thinvids_tpu.tools import oracle


def grad_frames(n, w=64, h=48):
    yy, xx = np.mgrid[0:h, 0:w]
    return [Frame(
        y=((xx * 2 + yy + 7 * i) % 256).astype(np.uint8),
        u=np.full((h // 2, w // 2), 108, np.uint8),
        v=np.full((h // 2, w // 2), 148, np.uint8),
    ) for i in range(n)]


def write_clip(path, n=32, w=64, h=48):
    meta = VideoMeta(width=w, height=h, fps_num=30, fps_den=1, num_frames=n)
    write_y4m(str(path), meta, grad_frames(n, w, h))
    return meta


def assert_frames_equal(got, want):
    assert len(got) == len(want)
    for a, b in zip(got, want):
        assert np.array_equal(a.y, b.y)
        assert np.array_equal(a.u, b.u)
        assert np.array_equal(a.v, b.v)


def make_mp4(tmp_path, n=12, gop=4):
    """Encode a tiny clip with our own encoder and mux it — the same
    mp4-in fixture recipe test_transcode uses."""
    from thinvids_tpu.io.mp4 import mux_mp4
    from thinvids_tpu.parallel.dispatch import encode_clip_sharded

    meta = VideoMeta(width=64, height=48, fps_num=30, fps_den=1,
                     num_frames=n)
    stream = encode_clip_sharded(grad_frames(n), meta, qp=27,
                                 gop_frames=gop)
    p = tmp_path / "in.mp4"
    p.write_bytes(mux_mp4(stream, meta))
    return p


class TestY4MRangeAccess:
    def test_open_video_meta_matches_materialized(self, tmp_path):
        clip = tmp_path / "clip.y4m"
        write_clip(clip, n=32)
        src = open_video(str(clip))
        meta, frames, audio = read_video(str(clip))
        assert len(src) == 32
        assert src.meta == meta
        assert src.audio is None and audio is None
        assert len(frames) == 32

    def test_read_range_is_o_range_and_bit_exact(self, tmp_path):
        clip = tmp_path / "clip.y4m"
        write_clip(clip, n=32)
        _meta, frames, _ = read_video(str(clip))
        src = open_video(str(clip))
        got = src.read_range(8, 8)
        assert_frames_equal(got, frames[8:16])
        # O(range): only the requested 8 frames were decoded — the
        # fixed-size record arithmetic seeks straight to frame 8
        assert src.frames_decoded == 8
        assert [f.pts for f in got] == list(range(8, 16))

    def test_lazy_window_slicing(self, tmp_path):
        clip = tmp_path / "clip.y4m"
        write_clip(clip, n=32)
        _meta, frames, _ = read_video(str(clip))
        src = open_video(str(clip))
        window = src[8:16]
        assert len(window) == 8
        assert_frames_equal(list(window), frames[8:16])
        nested = window[2:4]            # re-slicing composes offsets
        assert_frames_equal(list(nested), frames[10:12])
        assert np.array_equal(window[3].y, frames[11].y)
        assert np.array_equal(src[-1].y, frames[31].y)
        with pytest.raises(ValueError):
            src[::2]

    def test_restartable_iteration(self, tmp_path):
        """Each iteration opens its own cursor (multi-pass encodes —
        vbr2pass — re-read the source without interference)."""
        clip = tmp_path / "clip.y4m"
        write_clip(clip, n=8)
        src = open_video(str(clip))
        a = [f.y.copy() for f in src.iter_frames()]
        b = [f.y.copy() for f in src.iter_frames()]
        assert len(a) == len(b) == 8
        assert all(np.array_equal(x, y) for x, y in zip(a, b))


@pytest.mark.skipif(not oracle.oracle_available(),
                    reason="libavcodec missing")
class TestMp4RangeAccess:
    def test_range_decode_is_gop_bounded_and_bit_exact(self, tmp_path):
        p = make_mp4(tmp_path, n=12, gop=4)
        _meta, frames, _ = read_video(str(p))
        src = open_video(str(p))
        got = src.read_range(5, 4)      # straddles the GOP-2 boundary
        assert_frames_equal(got, frames[5:9])
        # decode restarts at the sync sample before frame 5 (frame 4)
        # and covers two closed GOPs — bounded by range + lead-in,
        # never the whole clip
        assert src.frames_decoded <= 8 < len(src)

    def test_streamed_encode_bit_identical_mp4(self, tmp_path):
        from thinvids_tpu.parallel.dispatch import GopShardEncoder

        p = make_mp4(tmp_path, n=12, gop=4)
        src = open_video(str(p))
        _meta, frames, _ = read_video(str(p))
        enc_a = GopShardEncoder(src.meta, qp=30, gop_frames=4)
        want = concat_segments(enc_a.encode_waves(enc_a.stage_waves(frames)))
        enc_b = GopShardEncoder(src.meta, qp=30, gop_frames=4)
        got = concat_segments(enc_b.encode(src))
        assert got == want


class TestStreamedEncodeParity:
    def test_streamed_encode_bit_identical_y4m(self, tmp_path):
        """The full streaming path (open_video → background staging →
        wave dispatch) vs the materialized list path: byte-identical
        Annex-B out, byte-identical muxed MP4."""
        from thinvids_tpu.io.mp4 import mux_mp4
        from thinvids_tpu.parallel.dispatch import GopShardEncoder

        clip = tmp_path / "clip.y4m"
        write_clip(clip, n=32)
        src = open_video(str(clip))
        _meta, frames, _ = read_video(str(clip))
        enc_a = GopShardEncoder(src.meta, qp=30, gop_frames=4)
        want = concat_segments(enc_a.encode_waves(enc_a.stage_waves(frames)))
        enc_b = GopShardEncoder(src.meta, qp=30, gop_frames=4)
        got = concat_segments(enc_b.encode(src))
        assert got == want
        assert mux_mp4(got, src.meta) == mux_mp4(want, src.meta)

    def test_staging_error_propagates_from_background_thread(self):
        """A decode failure on the staging thread re-raises at the
        consumer — never a silent hang or truncated output."""
        from thinvids_tpu.parallel.dispatch import background_stage

        def boom():
            yield "first"
            raise RuntimeError("decode exploded")

        feed = background_stage(boom(), decode_ahead=2)
        assert next(feed) == "first"
        with pytest.raises(RuntimeError, match="decode exploded"):
            list(feed)


class TestBoundedResidency:
    def test_peak_resident_frames_is_one_wave(self, tmp_path):
        """A long multi-wave encode holds at most one wave of decoded
        frames in the staging cursor (plus `decode_ahead` staged waves
        as device arrays and `pipeline_window` in flight — none of
        which retain host Frames), and every frame decodes exactly
        once."""
        import jax

        from thinvids_tpu.parallel.dispatch import (GopShardEncoder,
                                                    default_mesh)

        clip = tmp_path / "long.y4m"
        write_clip(clip, n=32)
        src = open_video(str(clip))
        # 1 device x 1 gop/wave x gop 4 -> 8 waves of 4 frames
        enc = GopShardEncoder(src.meta, qp=30,
                              mesh=default_mesh(jax.devices()[:1]),
                              gop_frames=4, gops_per_wave=1,
                              decode_ahead=2, pipeline_window=2)
        segments = enc.encode(src)
        assert len(segments) == 8
        assert src.frames_decoded == 32             # decoded once each
        wave_frames = 4
        assert 0 < enc.staging_stats["peak_resident_frames"] \
            <= wave_frames + 1
        # and the streamed result is still the correct bitstream
        _meta, frames, _ = read_video(str(clip))
        ref = GopShardEncoder(src.meta, qp=30,
                              mesh=default_mesh(jax.devices()[:1]),
                              gop_frames=4, gops_per_wave=1)
        assert concat_segments(segments) == concat_segments(
            ref.encode_waves(ref.stage_waves(frames)))


class TestRemoteShardRange:
    def test_worker_decodes_only_its_shard_range(self, tmp_path):
        """A worker daemon's claim decodes exactly the shard's
        [f0, f0+n) frames — O(shard), not O(clip) — and the part is
        identical to one cut from a whole-clip decode."""
        from thinvids_tpu.cluster.remote import (Shard, WorkerDaemon,
                                                 encode_shard)

        clip = tmp_path / "clip.y4m"
        meta = write_clip(clip, n=32)
        gops = tuple(GopSpec(index=i, start_frame=4 * i, num_frames=4)
                     for i in (2, 3))
        desc = Shard(id="s0", job_id="j0", input_path=str(clip),
                     meta=meta, gops=gops, qp=30, gop_frames=4,
                     timeout_s=60.0).descriptor()

        daemon = WorkerDaemon("http://127.0.0.1:1")
        source = daemon._frames(str(clip))
        segments = encode_shard(desc, source)
        assert source.frames_decoded == 8           # frames [8, 16) only
        assert [s.gop.start_frame for s in segments] == [8, 12]
        # identical to the same descriptor over a materialized clip
        _meta, frames, _ = read_video(str(clip))
        want = encode_shard(desc, frames)
        assert [s.payload for s in segments] == [s.payload for s in want]
        # the cache keeps the OPENED source (no decoded frames)
        assert daemon._frames(str(clip)) is source


class TestNoMaterializedPrologue:
    def test_read_video_ban_is_manifested_and_clean(self, analysis_ctx):
        """The blocking decode prologue must not come back — the
        executors and the worker daemon stream via open_video;
        read_video (list-materializing) is reserved for small-clip
        tools. Migrated from a source grep to the analyzer's
        forbidden-symbol rule (TVT-J002): this asserts the manifest
        still bans it for both modules AND that the pass is clean on
        HEAD (tree-wide enforcement rides `cli.py check` in tier-1)."""
        from thinvids_tpu.analysis import imports

        m, tree = analysis_ctx
        for mod in ("thinvids_tpu.cluster.executor",
                    "thinvids_tpu.cluster.remote"):
            rules = m.forbidden_symbols.get(mod, ())
            assert any(sym == "read_video" for sym, _why in rules), (
                f"manifest no longer bans read_video in {mod}")
        open_ = [f for f in imports.check_forbidden_symbols(tree, m)
                 if f.key not in m.waivers]
        assert not open_, "\n".join(f.format() for f in open_)
