"""shard_map GOP dispatch: one GOP per mesh device per wave.

The reference's dispatch loop enqueued one encode task per segment onto a
Redis-backed queue consumed by worker nodes (/root/reference/worker/
tasks.py:1167-1281); here a wave of GOPs is one SPMD program over the mesh:
frames live HBM-resident per device, the jitted intra compute runs a
sequential `lax.map` over the GOP's frames (the carry will hold reference
frames once P-frames land), and the quantized levels return to host for
entropy packing. Encoded segments concat in index order; bit-identity with
the single-device encode is asserted by tests/test_parallel.py on an
8-device virtual mesh.

Host side, the pipeline is instrumented per stage (StageProfile): every
wave's source decode / staging (stack + H2D upload) / dispatch / device
wait / D2H fetch / sparse unpack / unflatten / CAVLC pack / concat
wall-clock accumulates on the encoder and is exported through bench.py
(`stage_ms`) and the API's /metrics_snapshot. The entropy pack fans out
at SLICE granularity across a per-encoder pool sized by `pack_workers`
(TVT_PACK_WORKERS; default: all cores; threads spawn on demand and
retire with the encoder), decoupled from the in-flight wave window
`pipeline_window` (TVT_PIPELINE_WINDOW).

Ingest is a pipelined stage, not a blocking prologue: `stage_waves`
accepts a streaming FrameSource (ingest.open_video) or a materialized
list and holds only the current wave's decoded frames (a sliding
_FrameCursor window), and :func:`background_stage` runs the whole
decode→stack→upload chain on a staging thread up to `decode_ahead`
waves (TVT_DECODE_AHEAD) ahead of dispatch, overlapping source decode
with device compute.

The device→host boundary itself is compacted and parallelized three
ways (BENCH r04→r05 showed every device-side win dying here):
`compact_transfer` (TVT_COMPACT_TRANSFER, default on) adds a device
stage that packs the two-tier sparse streams into ONE contiguous byte
payload per GOP (jaxcore._compact_stream; format in codecs/h264/
layout.py) so the bulk fetch moves `used` bytes instead of three
budget-padded arrays; collect_wave fetches with one transfer thread
per device shard so the ~0.1–0.2 s tunnel latency overlaps across the
mesh instead of serializing; and `pack_backend=process`
(TVT_PACK_BACKEND) opts into shared-memory pack sidecars (packproc.py)
that run unpack+unflatten+pack outside this process's GIL. Every path
is bit-identical to the original sparse2 transfer (parity-tested), and
the old path stays live as the validated fallback (compact_transfer
off, thread backend, dense wave fallback).

Beside the GOP-wave encoder lives the split-frame mode
(:class:`SfeShardEncoder`, `sfe_bands`/TVT_SFE_BANDS): ONE frame
sharded across the mesh as horizontal MB-row bands — one device per
band, ME halos exchanged over the interconnect (lax.ppermute), each
band entropy-coded as its own slice — with a PER-FRAME dispatch/collect
path (the `sfe` stage) for single-stream glass-to-bitstream latency.
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from collections import deque

from ..core.config import as_bool, get_settings
from ..core.devices import shard_map
from ..core.log import get_logging
# jax-free observability layer: the process-cumulative stage totals
# bridge into the Prometheus registry, and a bound span recorder (the
# executor wires one per traced job) turns every timed stage into a
# span in the job's distributed trace
from ..obs import metrics as obs_metrics
from ..core.types import (BandPlan, ChromaFormat, EncodedSegment, Frame,
                          GopSpec, SegmentPlan, VideoMeta)
from ..codecs.h264 import jaxcore
from ..codecs.h264.encoder import (FrameLevels, _mode_policy,
                                   gop_slice_thunks_planes, pack_slice,
                                   unpack_mode16)
from ..codecs.h264.headers import PPS, SPS
from ..codecs.h264.rdo import RD_OFF, RdConfig, rd_from_settings
# Transfer-layout contract (jax-free module shared with the process
# pack sidecars): per-MB flat sizes + the zero-copy host unflattens.
from ..codecs.h264.layout import _INTRA_FLAT_MB as _INTRA_MB
from ..codecs.h264.layout import (_P_FLAT_MB, unflatten_gop,
                                  unflatten_gop_parts, unflatten_intra,
                                  unflatten_p_planes)
from .planner import plan_bands, plan_fixed_segments, plan_segments

_LOG = get_logging(__name__)


def default_mesh(devices=None) -> Mesh:
    devices = list(jax.devices()) if devices is None else list(devices)
    return Mesh(np.array(devices), ("gop",))


# ---- host-stage wall-clock instrumentation --------------------------------

#: canonical stage keys, in pipeline order (decode = pulling frames
#: from the ingest source; stage = stack + H2D upload — both run on
#: the staging thread when background_stage wraps the generator;
#: scale = dispatching the device-side ABR downscale that derives
#: lower ladder rungs from the staged wave (abr/scale.py);
#: dense_retry = the rare wave-wide dense re-encode + wide fetch when
#: the sparse budgets overflow — split out of "fetch" so the fetch
#: number answers only "what does the COMMON bulk transfer cost";
#: sfe = the split-frame path's per-frame host leg (band sparse unpack
#: + band-slice entropy pack + frame assembly) — the host half of the
#: single-stream glass-to-bitstream latency (SfeShardEncoder))
STAGE_NAMES = ("decode", "stage", "scale", "dispatch", "device_wait",
               "fetch", "dense_retry", "sparse_unpack", "unflatten",
               "pack", "concat", "sfe", "halo")

#: monotonic counters riding in the same snapshot as the stage clocks:
#: dense_fallback_waves (waves that overflowed the sparse budgets and
#: re-encoded dense), h2d_bytes (host→device bytes uploaded while
#: staging waves — the ABR ladder's proof that decode+upload happens
#: ONCE per wave regardless of rung count: lower rungs derive on
#: device, so this must not scale with rungs), d2h_bytes (actual
#: device→host bytes fetched — bench derives d2h_bytes_per_frame from
#: it), fetch_shards (per-shard concurrent fetch transfers issued; 0
#: means every fetch was a single blocking device_get), proc_pack_gops
#: (GOPs handed to the pack_backend=process sidecars instead of the
#: thread pool), sfe_frames (frames that crossed the split-frame
#: per-frame collect path — bands fetched + packed as band slices)
STAGE_COUNTERS = ("dense_fallback_waves", "h2d_bytes", "d2h_bytes",
                  "fetch_shards", "proc_pack_gops", "sfe_frames")


class StageProfile:
    """Thread-safe per-stage wall-clock accumulator for the host half of
    the wave pipeline. Stages overlap across pool threads, so per-stage
    sums can exceed elapsed time — they answer "where do host cycles
    go", not "what is the critical path".

    `mirror` (the process-wide cumulative profile) receives every add
    too, so /metrics_snapshot keeps a job's totals after its encoder is
    garbage-collected; reset() only clears THIS profile (bench resets
    per timed pass without zeroing the process counters)."""

    def __init__(self, mirror: "StageProfile | None" = None,
                 metrics: bool = False) -> None:
        self._lock = threading.Lock()
        self._ms = {k: 0.0 for k in STAGE_NAMES}
        self._counts = {k: 0 for k in STAGE_COUNTERS}
        self._waves = 0
        self._mirror = mirror
        #: bridge into the obs/ metrics registry — set ONLY on the
        #: process-cumulative _TOTALS instance, so every add lands in
        #: the registry exactly once (per-encoder profiles mirror into
        #: _TOTALS, which forwards)
        self._metrics = bool(metrics)
        #: optional span recorder (obs/trace): the executor binds one
        #: per traced job so each timed stage also records a span in
        #: the job's distributed trace. None = zero tracing overhead.
        self._tracer = None

    def set_tracer(self, recorder) -> None:
        """Bind (or clear, with None/an inert recorder) the span sink
        this profile's stage() blocks record into."""
        with self._lock:
            self._tracer = recorder if recorder is not None \
                and getattr(recorder, "enabled", False) else None

    def tracer(self):
        """The bound span recorder, or None (instrumentation sites
        that record spans outside a stage() block read this)."""
        return self._tracer

    def add(self, stage: str, seconds: float) -> None:
        with self._lock:
            self._ms[stage] = self._ms.get(stage, 0.0) + seconds * 1e3
        if self._metrics:
            obs_metrics.STAGE_SECONDS.labels(stage).inc(seconds)
        if self._mirror is not None:
            self._mirror.add(stage, seconds)

    def bump(self, counter: str, n: int = 1) -> None:
        """Increment a monotonic counter (STAGE_COUNTERS) by `n`."""
        with self._lock:
            self._counts[counter] = self._counts.get(counter, 0) + int(n)
        if self._metrics:
            metric = obs_metrics.STAGE_COUNTER_TOTALS.get(counter)
            if metric is not None:
                metric.inc(n)
        if self._mirror is not None:
            self._mirror.bump(counter, n)

    @contextlib.contextmanager
    def stage(self, name: str, **tags):
        tracer = self._tracer
        t0_wall = time.time() if tracer is not None else 0.0
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.add(name, dt)
            if tracer is not None:
                tracer.record(name, t0_wall, dt, **tags)

    def count_wave(self) -> None:
        with self._lock:
            self._waves += 1
        if self._metrics:
            obs_metrics.WAVES_TOTAL.inc()
        if self._mirror is not None:
            self._mirror.count_wave()

    def snapshot(self) -> dict:
        with self._lock:
            out = {k: round(v, 2) for k, v in self._ms.items()}
            out.update(self._counts)
            out["waves"] = self._waves
            return out

    def reset(self) -> None:
        with self._lock:
            for k in self._ms:
                self._ms[k] = 0.0
            for k in self._counts:
                self._counts[k] = 0
            self._waves = 0


#: process-cumulative stage totals (every encoder mirrors into this;
#: the metrics flag bridges each add into the obs/ Prometheus registry)
_TOTALS = StageProfile(metrics=True)


def stage_snapshot() -> dict:
    """Process-cumulative stage_ms across every GopShardEncoder that ran
    here (the /metrics_snapshot exporter — running jobs' waves land as
    they complete, and finished jobs' totals persist)."""
    return _TOTALS.snapshot()


#: process-cumulative SFE per-frame latency samples (ms) — the gaps
#: between consecutive frames' bitstream-ready times across every
#: SfeShardEncoder that ran here. The data frame_done_t always
#: recorded, finally summarized: /metrics_snapshot and the dashboard
#: surface p50/p99 from this ring, and each sample also observes the
#: tvt_sfe_frame_latency_seconds histogram.
_SFE_LAT_MS: deque = deque(maxlen=4096)
#: guards ring iteration vs the collector threads' appends (a deque
#: mutated mid-iteration raises RuntimeError — the snapshot endpoint
#: must not 500 exactly while an SFE job is hot)
_SFE_LAT_LOCK = threading.Lock()


def frame_latency_percentiles() -> dict:
    """{"p50_ms", "p99_ms", "count"} over the recent SFE per-frame
    latency ring; {} when no SFE frame ever completed here."""
    with _SFE_LAT_LOCK:
        samples = sorted(_SFE_LAT_MS)
    pct = obs_metrics.percentiles(samples, {"p50_ms": 0.50,
                                            "p99_ms": 0.99})
    if not pct:
        return {}
    return {k: round(v, 1) for k, v in pct.items()} \
        | {"count": len(samples)}


class _FrameCursor:
    """Sliding decoded-frame window for wave staging.

    Pulls frames on demand from a materialized list or a streaming
    FrameSource (anything exposing ``iter_frames()``), pads them to
    macroblock multiples, and retains only ``[lo, hi)`` — the staging
    loop releases everything below the staged wave's end, so resident
    decoded frames stay bounded by one wave regardless of clip length
    (the paper's never-hold-a-whole-clip invariant)."""

    def __init__(self, frames, profile: StageProfile,
                 require_420: bool = False,
                 stats: dict | None = None) -> None:
        iter_fn = getattr(frames, "iter_frames", None)
        self._it = iter_fn() if iter_fn is not None else iter(frames)
        self._profile = profile
        self._require_420 = require_420
        self._stats = stats if stats is not None else {}
        self._buf: deque = deque()      # padded frames [lo, hi)
        self._lo = 0
        self._hi = 0

    def get(self, i: int) -> Frame:
        """Padded frame at absolute index `i` (must not be released)."""
        if i < self._lo:
            raise IndexError(
                f"frame {i} already released (window starts at "
                f"{self._lo})")
        while self._hi <= i:
            with self._profile.stage("decode"):
                try:
                    f = next(self._it)
                except StopIteration:
                    raise ValueError(
                        f"frame stream ended at {self._hi}, but the "
                        f"wave plan needs frame {i}") from None
            if self._require_420 and f.chroma is not ChromaFormat.YUV420:
                raise ValueError(
                    f"GopShardEncoder supports only 4:2:0 input, got "
                    f"{f.chroma.name}; convert before encoding")
            self._buf.append(f.padded(16))
            self._hi += 1
            if len(self._buf) > self._stats.get("peak_resident_frames", 0):
                self._stats["peak_resident_frames"] = len(self._buf)
        return self._buf[i - self._lo]

    def release_below(self, i: int) -> None:
        while self._lo < i and self._buf:
            self._buf.popleft()
            self._lo += 1


def background_stage(staged_waves, decode_ahead: int = 2):
    """Run a staging generator (stage_waves: source decode + np.stack +
    H2D upload) on its own thread, up to `decode_ahead` staged waves
    ahead of the consumer — ingest becomes a pipelined stage that
    overlaps device compute instead of a blocking prologue on the
    dispatch thread.

    Each queued wave is ALREADY H2D-uploaded: device-side input
    residency is the consumer's in-flight window plus `decode_ahead`
    (+1 blocked in the put) waves of HBM YUV arrays — size the knob
    against HBM headroom, not just source latency.

    Returns a generator yielding the staged tuples in order; close()
    (or exhaustion, or an exception propagating out) stops the staging
    thread and releases its decode window. Exceptions raised while
    staging (bad chroma, truncated source) re-raise at the consumer's
    next pull."""
    import queue as queue_mod

    q: queue_mod.Queue = queue_mod.Queue(max(1, int(decode_ahead)))
    stop = threading.Event()
    done = object()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue_mod.Full:
                continue
        return False

    def feed() -> None:
        try:
            for staged in staged_waves:
                if not _put(staged):
                    return
            _put(done)
        except BaseException as exc:    # noqa: BLE001 - relay to consumer
            _put(exc)
        finally:
            close = getattr(staged_waves, "close", None)
            if close is not None:
                close()

    thread = threading.Thread(target=feed, daemon=True, name="tvt-stage")

    def drain():
        thread.start()
        try:
            while True:
                item = q.get()
                if item is done:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

    return drain()


def _sparse_unpack2_host(nblk: int, nval: int, bitmap, bmask16, vals,
                         L: int) -> np.ndarray:
    """Two-tier sparse unpack: native scatter when a compiler exists,
    jaxcore's numpy reference otherwise (identical output — tested)."""
    from .. import native as native_mod

    if native_mod.available():
        return native_mod.block_sparse_unpack2(nblk, nval, bitmap,
                                               bmask16, vals, L)
    return jaxcore._block_sparse_unpack2(nblk, nval, bitmap, bmask16,
                                         vals, L)


def _flat_levels(y, u, v, qp, mbw, mbh, rd=RD_OFF):
    out = jaxcore._intra_core(y, u, v, qp, mbw=mbw, mbh=mbh, rd=rd)
    ldc, lac, cdc, cac = out[:4]
    parts = [ldc.reshape(-1), lac.reshape(-1), cdc.reshape(-1),
             cac.reshape(-1)]
    if rd.ships_modes:
        parts.append(jaxcore._mode_tail(out[7], out[8], out[9])
                     .astype(jnp.int32))
    return jnp.concatenate(parts)


def _per_gop_sparse(y, u, v, qp, mbw: int, mbh: int, compact: bool = False,
                    rd=RD_OFF):
    """(F, H, W) GOP → (mv int8, dense intra-DC segments, two-tier
    sparse levels for the rest).

    BOTH intra hadamard DC segments — luma DC (nmb * 16) and chroma DC
    (nmb * 8), ~390 KB combined at 1080p — ship DENSE: hadamard DC
    levels are the only ones that exceed int8 at practical QPs (chroma
    DC crosses at QP <~ 20), and the sparse pack has no escape
    side-channel (its full-size scatters were ~60% of the pack's
    device time) — an escape anywhere forces the wave-wide dense
    fallback, so low-QP encodes would otherwise fall permanently into
    the slow path (ADVICE round 5).

    With `compact` the three sparse streams additionally fold into one
    contiguous byte payload on device (jaxcore._compact_stream), so the
    output is (mv8, dense, nblk, nval, n_esc, used, payload) — 7 arrays
    — instead of the 8-array (…, bitmap, bmask16, vals) layout."""
    from ..codecs.h264 import jaxinter

    mv8, flat = jaxinter.encode_gop_planes(y, u, v, qp, mbw=mbw, mbh=mbh,
                                           rd=rd)
    nmb = mbw * mbh
    ndc, nlac, ncdc = nmb * 16, nmb * 240, nmb * 8
    dense_parts = [flat[:ndc], flat[ndc + nlac:ndc + nlac + ncdc]]
    if rd.ships_modes:
        # intra [mode16 | dqp16] tail rides the dense prefix (it is
        # small and mode 0 = V would defeat the sparse pack anyway)
        dense_parts.append(flat[-2 * nmb:])
        rest = jnp.concatenate([flat[ndc:ndc + nlac],
                                flat[ndc + nlac + ncdc:-2 * nmb]])
    else:
        rest = jnp.concatenate([flat[ndc:ndc + nlac],
                                flat[ndc + nlac + ncdc:]])
    dense = jnp.concatenate(dense_parts)
    nblk, nval, n_esc, bitmap, bmask16, vals = \
        jaxcore._block_sparse_pack2(rest)
    if not compact:
        return (mv8, dense, nblk, nval, n_esc, bitmap, bmask16, vals)
    used, payload = jaxcore._compact_stream(nblk, nval, bitmap, bmask16,
                                            vals)
    return (mv8, dense, nblk, nval, n_esc, used, payload)


def _per_gop_dense(y, u, v, qp, mbw: int, mbh: int, dtype, rd=RD_OFF):
    from ..codecs.h264 import jaxinter

    _mv8, flat = jaxinter.encode_gop_planes(y, u, v, qp, mbw=mbw, mbh=mbh,
                                            rd=rd)
    return flat.astype(dtype)


# Zero-copy unflatten views (flat transfer segments → slice arrays) —
# the implementations live in the jax-free layout module so the process
# pack sidecars share them; these aliases keep this module's historical
# names for callers and tests.
_unflatten_gop = unflatten_gop
_unflatten_gop_parts = unflatten_gop_parts


@functools.partial(jax.jit,
                   static_argnames=("mbw", "mbh", "mesh", "compact", "rd"))
def _encode_wave_gop(ys, us, vs, qps, *, mbw: int, mbh: int, mesh: Mesh,
                     compact: bool = False, rd=RD_OFF):
    """ys: (G, F, H, W) uint8 sharded over `gop`, G = devices x k; each
    device sequentially encodes its k GOPs (IDR + P, jaxinter) at its
    per-GOP QP (qps: (G,) int32, the rate-control hook) and sparse-packs
    the plane-layout levels (`compact` folds the sparse streams into
    one byte payload per GOP — see _per_gop_sparse)."""

    def per_dev(y_g, u_g, v_g, qp_g):
        def one(args):
            y, u, v, qp = args
            return _per_gop_sparse(y, u, v, qp, mbw, mbh, compact=compact,
                                   rd=rd)
        return jax.lax.map(one, (y_g, u_g, v_g, qp_g))

    shard = shard_map(
        per_dev, mesh=mesh,
        in_specs=(P("gop"),) * 4,
        out_specs=(P("gop"),) * (7 if compact else 8),
    )
    return shard(ys, us, vs, qps)


@functools.partial(jax.jit,
                   static_argnames=("mbw", "mbh", "compact", "rd"))
def _encode_gop_single(ys, us, vs, qps, *, mbw: int, mbh: int,
                       compact: bool = False, rd=RD_OFF):
    """Single-device wave: the same per-GOP program WITHOUT the
    shard_map wrapper. On one chip shard_map buys nothing and costs a
    lot — measured on TPU v5e: compile 33 s → 810 s and steady-state
    256 ms → 800 ms per 1080p GOP under the manual-axes lowering."""
    def one(args):
        y, u, v, qp = args
        return _per_gop_sparse(y, u, v, qp, mbw, mbh, compact=compact,
                               rd=rd)
    return jax.lax.map(one, (ys, us, vs, qps))


@functools.partial(jax.jit,
                   static_argnames=("mbw", "mbh", "dtype", "rd"))
def _encode_gop_single_dense(ys, us, vs, qps, *, mbw: int, mbh: int, dtype,
                             rd=RD_OFF):
    def one(args):
        y, u, v, qp = args
        return _per_gop_dense(y, u, v, qp, mbw, mbh, dtype, rd=rd)
    return jax.lax.map(one, (ys, us, vs, qps))


@functools.partial(jax.jit,
                   static_argnames=("mbw", "mbh", "mesh", "dtype", "rd"))
def _encode_wave_gop_dense(ys, us, vs, qps, *, mbw: int, mbh: int, mesh: Mesh,
                           dtype, rd=RD_OFF):
    """Dense fallback for the GOP wave: (G, L) levels in `dtype`."""

    def per_dev(y_g, u_g, v_g, qp_g):
        def one(args):
            y, u, v, qp = args
            return _per_gop_dense(y, u, v, qp, mbw, mbh, dtype, rd=rd)
        return jax.lax.map(one, (y_g, u_g, v_g, qp_g))

    shard = shard_map(
        per_dev, mesh=mesh,
        in_specs=(P("gop"),) * 4,
        out_specs=P("gop"),
    )
    return shard(ys, us, vs, qps)


@functools.partial(jax.jit, static_argnames=("mbw", "mbh", "mesh", "rd"))
def _encode_wave(ys, us, vs, qps, *, mbw: int, mbh: int, mesh: Mesh,
                 rd=RD_OFF):
    """All-intra wave. ys: (G, F, H, W) uint8 sharded over `gop`; qps:
    (G,) int32 per-GOP QP — the rate-control hook (this path used to
    take one wave-wide scalar, silently encoding every GOP at base QP
    regardless of `gop_qp` overrides).

    Returns per-frame sparse-packed levels (jaxcore._sparse_pack — ~10x
    fewer device→host bytes than raw int32) with leading (G, F) dims;
    the host checks the nnz/escape counts for the rare dense fallback.
    """

    def per_gop(y_g, u_g, v_g, qp_g):
        # y_g: (1, F, H, W) — this device's GOP(s); qp_g: (1,)
        def one(y_f, u_f, v_f, qp1):
            def per_frame(planes):
                y, u, v = planes
                return jaxcore._sparse_pack(
                    _flat_levels(y, u, v, qp1, mbw, mbh, rd=rd))

            return jax.lax.map(per_frame, (y_f, u_f, v_f))

        return jax.vmap(one)(y_g, u_g, v_g, qp_g)         # each (1, F, ...)

    shard = shard_map(
        per_gop, mesh=mesh,
        in_specs=(P("gop"),) * 4,
        out_specs=(P("gop"),) * 6,
    )
    return shard(ys, us, vs, qps)


@functools.partial(jax.jit,
                   static_argnames=("mbw", "mbh", "mesh", "dtype", "rd"))
def _encode_wave_dense(ys, us, vs, qps, *, mbw: int, mbh: int, mesh: Mesh,
                       dtype, rd=RD_OFF):
    """Dense fallback: (G, F, L) levels in `dtype` (int16 covers the full
    CAVLC level range), at the same per-GOP QPs as the sparse pass."""

    def per_gop(y_g, u_g, v_g, qp_g):
        def one(y_f, u_f, v_f, qp1):
            def per_frame(planes):
                y, u, v = planes
                return _flat_levels(y, u, v, qp1, mbw, mbh, rd=rd)

            return jax.lax.map(per_frame, (y_f, u_f, v_f))

        return jax.vmap(one)(y_g, u_g, v_g, qp_g).astype(dtype)

    shard = shard_map(
        per_gop, mesh=mesh,
        in_specs=(P("gop"),) * 4,
        out_specs=P("gop"),
    )
    return shard(ys, us, vs, qps)


class GopShardEncoder:
    """Encode a clip as closed GOPs fanned across a device mesh."""

    def __init__(self, meta: VideoMeta, qp: int = 27, mesh: Mesh | None = None,
                 gop_frames: int = 32, max_segments: int = 200,
                 inter: bool = True, gops_per_wave: int = 4,
                 pack_workers: int | None = None,
                 pipeline_window: int | None = None,
                 decode_ahead: int | None = None,
                 compact_transfer: bool | None = None,
                 pack_backend: str | None = None,
                 rd: RdConfig | None = None):
        self.meta = meta
        self.qp = qp
        #: inter=True encodes each GOP as IDR + P frames (motion-coded);
        #: False keeps the all-intra path (every frame IDR).
        self.inter = inter
        self.mesh = mesh if mesh is not None else default_mesh()
        self.gop_frames = gop_frames
        self.max_segments = max_segments
        #: GOPs encoded per device per wave (lax.map'd inside one
        #: program) — batches device dispatch + transfer so per-call
        #: host<->device latency amortizes. Inter path only.
        self.gops_per_wave = max(1, int(gops_per_wave))
        self.sps = SPS(width=meta.width, height=meta.height,
                       fps_num=meta.fps_num, fps_den=meta.fps_den)
        self.pps = PPS(init_qp=qp)
        snap = get_settings()
        #: static RD feature set (codecs/h264/rdo.RdConfig): per-MB
        #: intra mode decision, P_Skip bias, in-loop deblocking,
        #: perceptual AQ. None resolves from settings (the
        #: mode_decision/pskip/deblock/aq_strength knobs) so every
        #: settings-built encoder — executor, remote worker, ladder,
        #: live — inherits the job's RD config without new plumbing.
        if rd is None:
            rd = rd_from_settings(snap)
        self.rd = rd
        if self.rd.deblock and not inter:
            raise ValueError(
                "deblock requires the inter (GOP) path: the all-intra "
                "encoder has no recon chain to filter")
        #: slice-granular CAVLC pack threads (0/None in config = all
        #: cores). Decoupled from the wave window: the pack pool sizes
        #: to the HOST (cpu count), the window to device queue depth.
        if pack_workers is None:
            pack_workers = int(snap.get("pack_workers", 0) or 0)
        self.pack_workers = int(pack_workers) or (os.cpu_count() or 2)
        #: in-flight wave window: staged inputs + outputs of this many
        #: waves stay alive at once (device queue x transfer overlap).
        if pipeline_window is None:
            pipeline_window = int(snap.get("pipeline_window", 0) or 0)
        self.pipeline_window = int(pipeline_window) or self.PIPELINE_WINDOW
        #: staged waves decoded + uploaded ahead of dispatch by the
        #: background staging thread (encode() / background_stage).
        #: ADDS to input HBM residency on top of the in-flight window
        #: (each staged-ahead wave is already uploaded).
        if decode_ahead is None:
            decode_ahead = int(snap.get("decode_ahead", 0) or 0)
        self.decode_ahead = int(decode_ahead) or self.DECODE_AHEAD
        #: device-side stream compaction (jaxcore._compact_stream): the
        #: sparse GOP streams fold into one byte payload on device and
        #: the host fetches only the used prefix. Default on; off keeps
        #: the original three-array sparse2 transfer (the validated
        #: fallback — bit-identical either way, parity-tested).
        if compact_transfer is None:
            compact_transfer = as_bool(snap.get("compact_transfer", True),
                                       True)
        self.compact_transfer = bool(compact_transfer)
        #: per-stage host wall-clock (bench `stage_ms`, /metrics_snapshot)
        self.stages = StageProfile(mirror=_TOTALS)
        #: streaming-ingest instrumentation: peak decoded frames the
        #: staging cursor held at once (tests assert the bound)
        self.staging_stats: dict = {"peak_resident_frames": 0}
        #: eager so concurrent collect_wave threads never race a lazy
        #: init; the executor spawns NO threads until first submit
        self._pack_pool = self._new_pack_pool()
        #: bulk-fetch transfer threads: one in-flight transfer per
        #: device shard so the per-fetch link latency (~0.1–0.2 s over
        #: an axon tunnel) overlaps across the mesh instead of
        #: serializing. None on single-device meshes (nothing to
        #: overlap — plain device_get).
        self._fetch_pool = self._new_fetch_pool()
        #: entropy-pack execution backend: "thread" (slice thunks on
        #: the pack pool) or "process" (GOP-granular shared-memory
        #: sidecars, packproc.py — unpack+pack outside this process's
        #: GIL). Process packing rides the compact payload; waves that
        #: fall off it (dense fallback, compact_transfer off, intra
        #: path) pack on threads as before.
        if pack_backend is None:
            pack_backend = str(snap.get("pack_backend", "thread")
                               or "thread")
        self.pack_backend = str(pack_backend)
        #: guards _proc_pool: collect_wave runs on one collector thread
        #: per in-flight wave, and any of them may retire a broken
        #: sidecar pool (_disable_proc_pool) while the others read it —
        #: flagged by `cli.py check` (TVT-T001) and locked since
        self._proc_lock = threading.Lock()
        self._proc_pool = self._new_proc_pool()
        #: one warning per encoder when async D2H prefetch is refused
        #: (a platform where copy_to_host_async silently no-ops must be
        #: visible in the logs, not swallowed)
        self._async_copy_unavailable = False
        #: Optional per-GOP QP overrides (rate control): gop index → qp.
        #: GOPs absent from the map encode at the base `qp`; slice
        #: headers carry the delta vs PPS init_qp.
        self.gop_qp: dict[int, int] = {}
        #: Elastic-replan continuation: when encoding a clip SUFFIX on a
        #: rebuilt mesh, emitted GopSpecs shift by these so indices /
        #: frame ranges (and idr_pic_id) stay globally consistent with
        #: the segments already completed (cluster/executor.py).
        self.gop_index_offset = 0
        self.frame_offset = 0
        #: Externally supplied plan (remote shards, cluster/remote.py):
        #: the EXACT shard-local GOP boundaries to encode, bypassing the
        #: local planner so a worker reproduces the coordinator's global
        #: plan bit-for-bit regardless of its own device count.
        self.plan_override: SegmentPlan | None = None

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size

    def plan(self, num_frames: int) -> SegmentPlan:
        if self.plan_override is not None:
            return self.plan_override
        return plan_segments(num_frames, self.gop_frames, self.num_devices,
                             self.max_segments)

    def stage_waves(self, frames):
        """Host-side staging generator: stack frames into per-wave
        (G, F, H, W) device arrays (HBM-resident input is the design
        invariant — SURVEY.md §0: kernels run over HBM-resident YUV
        planes). Lazily, one wave per iteration, so a long clip never
        pins more than the pipeline window of waves in HBM.

        `frames` may be a materialized list or a streaming FrameSource
        (ingest.open_video); either way only the current wave's decoded
        frames stay resident (_FrameCursor). Wrap the result in
        :func:`background_stage` — or use :meth:`encode` — to run the
        decode + stack + H2D upload on a staging thread ahead of the
        dispatch loop."""
        for wave, full, F, cursor in self._wave_groups(frames,
                                                       require_420=True):
            # prefetch the wave's frames OUTSIDE the "stage" timer so
            # the breakdown keeps decode (source pull) and stage
            # (stack + H2D) disjoint — cursor.get runs its own
            # "decode"-staged fill
            cursor.get(wave[-1].end_frame - 1)
            with self.stages.stage("stage"):
                ys = np.stack([self._gop_plane(cursor, g, F, "y")
                               for g in full])
                us = np.stack([self._gop_plane(cursor, g, F, "u")
                               for g in full])
                vs = np.stack([self._gop_plane(cursor, g, F, "v")
                               for g in full])
                qps = np.asarray([self.gop_qp.get(g.index, self.qp)
                                  for g in full], np.int32)
                self.stages.bump("h2d_bytes", ys.nbytes + us.nbytes
                                 + vs.nbytes + qps.nbytes)
                staged = (wave, jnp.asarray(ys), jnp.asarray(us),
                          jnp.asarray(vs), jnp.asarray(qps))
            yield staged

    def stage_luma_waves(self, frames):
        """Luma-only staging for analysis passes (rate control): chroma
        never leaves the host, halving the upload of a pass that only
        reads Y. Yields (wave, ys)."""
        for wave, full, F, cursor in self._wave_groups(frames):
            cursor.get(wave[-1].end_frame - 1)   # decode outside "stage"
            with self.stages.stage("stage"):
                ys = np.stack([self._gop_plane(cursor, g, F, "y")
                               for g in full])
                self.stages.bump("h2d_bytes", ys.nbytes)
                staged = (wave, jnp.asarray(ys))
            yield staged

    def _wave_groups(self, frames, require_420: bool = False):
        """Shared wave grouping: (wave, device-padded wave, static F,
        frame cursor). Stacks into (G, F, ...) with tail-repeat padding
        to static F; the wave itself pads to a multiple of D gops (the
        pad GOPs are encoded then discarded). The cursor decodes frames
        on demand and each wave's frames are released once the caller
        has staged them into device arrays."""
        plan = self.plan(len(frames))
        cursor = _FrameCursor(frames, self.stages, require_420=require_420,
                              stats=self.staging_stats)
        D = self.num_devices
        per_wave = D * (self.gops_per_wave if self.inter else 1)
        gops = list(plan.gops)
        for wave_start in range(0, len(gops), per_wave):
            wave = gops[wave_start:wave_start + per_wave]
            F = max(g.num_frames for g in wave)
            pad_n = (-len(wave)) % D
            full = wave + [wave[-1]] * pad_n
            yield wave, full, F, cursor
            # the caller staged this wave into device arrays; frames
            # below the next wave's start will never be read again
            cursor.release_below(wave[-1].end_frame)

    def prepare_waves(self, frames) -> tuple[SegmentPlan, list[tuple]]:
        """Eager staging of ALL waves (benchmarks / short clips); for
        long clips prefer encode(), which streams with a bounded window."""
        return self.plan(len(frames)), list(self.stage_waves(frames))

    def encode(self, frames) -> list[EncodedSegment]:
        """Stream-encode: source decode + staging run on a background
        thread up to `decode_ahead` waves ahead (background_stage);
        dispatch/collect pipeline on the calling thread."""
        feed = background_stage(self.stage_waves(frames), self.decode_ahead)
        try:
            return self.encode_waves(feed)
        finally:
            feed.close()

    def dispatch_wave(self, staged: tuple) -> tuple:
        """Enqueue one staged wave's device compute (async); returns an
        opaque pending handle for :meth:`collect_wave`."""
        with self.stages.stage("dispatch"):
            wave, ysd, usd, vsd, qpsd = staged
            ph, pw = ysd.shape[2], ysd.shape[3]
            mbh, mbw = ph // 16, pw // 16
            compact = self.inter and self.compact_transfer
            if self.inter and self.num_devices == 1:
                out = _encode_gop_single(ysd, usd, vsd, qpsd, mbw=mbw,
                                         mbh=mbh, compact=compact,
                                         rd=self.rd)
            elif self.inter:
                out = _encode_wave_gop(ysd, usd, vsd, qpsd, mbw=mbw, mbh=mbh,
                                       mesh=self.mesh, compact=compact,
                                       rd=self.rd)
            else:
                out = _encode_wave(ysd, usd, vsd, qpsd, mbw=mbw, mbh=mbh,
                                   mesh=self.mesh, rd=self.rd)
            if not self._async_copy_unavailable:
                for i, arr in enumerate(out):
                    # Start the device->host copies now, overlapped with
                    # the next wave's compute (the transfer link has high
                    # latency — axon tunnels measure ~0.1-0.2 s per
                    # blocking fetch). The compact payload (index 6) is
                    # NOT prefetched: collect_wave fetches only its used
                    # prefix, and an async copy would drag the whole
                    # budget-padded buffer across the link anyway.
                    if compact and i == 6:
                        continue
                    try:
                        arr.copy_to_host_async()
                    except Exception as exc:   # noqa: BLE001 - visible,
                        # once per encoder: a platform where async D2H
                        # no-ops must show up in the activity log, not
                        # silently serialize every fetch.
                        self._async_copy_unavailable = True
                        _LOG.warning(
                            "copy_to_host_async rejected (%s: %s); "
                            "device→host prefetch disabled for this "
                            "encoder", type(exc).__name__, exc)
                        break
            return (wave, ysd, usd, vsd, qpsd, mbw, mbh, out)

    def _new_pack_pool(self):
        """This encoder's slice-pack pool (threads spawn on demand up
        to pack_workers), or None for inline packing (pack_workers <=
        1). Shut down when the encoder is garbage-collected — a
        long-lived coordinator running many jobs must not accumulate
        parked pack threads."""
        if self.pack_workers <= 1:
            return None
        import concurrent.futures as cf
        import weakref

        pool = cf.ThreadPoolExecutor(self.pack_workers,
                                     thread_name_prefix="tvt-pack")
        weakref.finalize(self, pool.shutdown, False)
        return pool

    def _new_fetch_pool(self):
        """Per-shard D2H transfer threads (collect_wave), or None on a
        single-device mesh. Two slots per device so the next wave's
        shard fetches queue behind the current one's without a new
        round of pool growth."""
        if self.num_devices <= 1:
            return None
        import concurrent.futures as cf
        import weakref

        pool = cf.ThreadPoolExecutor(min(32, 2 * self.num_devices),
                                     thread_name_prefix="tvt-fetch")
        weakref.finalize(self, pool.shutdown, False)
        return pool

    def _new_proc_pool(self):
        """GOP-granular pack sidecar processes (pack_backend=process),
        or None for the threaded backend. Spawn context: children
        import packproc fresh and must never inherit (or initialize) a
        jax backend. Falls back to threads with a warning when the
        platform can't spawn a pool."""
        if self.pack_backend != "process" or not self.inter:
            return None
        import concurrent.futures as cf
        import multiprocessing as mp
        import weakref

        try:
            pool = cf.ProcessPoolExecutor(
                max(1, min(self.pack_workers, 8)),
                mp_context=mp.get_context("spawn"))
        except Exception as exc:    # noqa: BLE001 - degrade, don't die
            _LOG.warning("pack_backend=process unavailable (%s: %s); "
                         "falling back to threaded pack",
                         type(exc).__name__, exc)
            return None
        weakref.finalize(self, pool.shutdown, False)
        return pool

    def _slice_pool(self):
        return self._pack_pool

    #: payload fetch slice quantum cap (bytes): used prefixes round up
    #: to a quantum of max(256, min(this, PB // 8)) so the device-side
    #: slice shapes repeat across waves (each distinct shape
    #: jit-compiles once) instead of recompiling per wave — the
    #: PB // 8 term keeps the rounding proportional at small payloads,
    #: the cap bounds the over-fetch at < 64 KB per GOP at 4K scale.
    PAYLOAD_QUANTUM = 1 << 16

    def _fetch_bulk(self, arrays) -> list[np.ndarray]:
        """Bulk device→host fetch: one transfer per device shard, all
        shards of all arrays in flight at once on the fetch pool, so
        the per-transfer link latency (~0.1–0.2 s over an axon tunnel)
        overlaps across the mesh — an 8-chip wave fetches in ~1 tunnel
        latency instead of 8. Plain blocking device_get on
        single-device meshes (nothing to overlap)."""
        arrays = list(arrays)
        pool = self._fetch_pool
        if pool is None:
            host = jax.device_get(arrays)
            self.stages.bump("d2h_bytes",
                             sum(int(a.nbytes) for a in host))
            return host
        futss = []
        for arr in arrays:
            shards = sorted(arr.addressable_shards,
                            key=lambda s: s.index[0].start or 0)
            self.stages.bump("fetch_shards", len(shards))
            futss.append([pool.submit(np.asarray, s.data)
                          for s in shards])
        host = []
        for futs in futss:
            parts = [f.result() for f in futs]
            a = parts[0] if len(parts) == 1 else np.concatenate(parts)
            self.stages.bump("d2h_bytes", int(a.nbytes))
            host.append(a)
        return host

    def _fetch_payload_rows(self, payload, used) -> list[np.ndarray]:
        """Fetch the wave's compact payloads SLICED to their used
        prefix: each shard moves max(used) bytes per GOP (rounded up to
        PAYLOAD_QUANTUM) instead of the whole budget-padded buffer, one
        transfer thread per device shard. Returns a 1-D uint8 row per
        GOP (row length >= that GOP's used bytes)."""
        used = np.asarray(used)
        G, PB = payload.shape
        q = max(256, min(self.PAYLOAD_QUANTUM, PB // 8))

        def cut(n) -> int:
            return min(PB, -(-max(int(n), 1) // q) * q)

        pool = self._fetch_pool
        if pool is None:
            host = np.asarray(payload[:, :cut(used.max())])
            self.stages.bump("d2h_bytes", int(host.nbytes))
            return list(host)
        shards = sorted(payload.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        self.stages.bump("fetch_shards", len(shards))
        futs = []
        for s in shards:
            a = s.index[0].start or 0
            mu = cut(used[a:a + s.data.shape[0]].max())
            futs.append((a, pool.submit(
                lambda d=s.data, m=mu: np.asarray(d[:, :m]))))
        rows: list = [None] * G
        for a, f in futs:
            part = f.result()
            self.stages.bump("d2h_bytes", int(part.nbytes))
            for i in range(part.shape[0]):
                rows[a + i] = part[i]
        return rows

    @staticmethod
    def _unpack_compact(payload_row: np.ndarray, nblk: int, nval: int,
                        used: int, L: int) -> np.ndarray:
        """Compact payload's used prefix → flat int16 levels (the
        native-or-numpy dispatch lives with the format contract,
        layout.unpack_compact_auto — shared with the pack sidecars)."""
        from ..codecs.h264.layout import unpack_compact_auto

        return unpack_compact_auto(payload_row[:used], nblk, nval, L)

    @staticmethod
    def _release_spool(shm, spools: list) -> None:
        if shm in spools:
            spools.remove(shm)
        shm.close()
        try:
            shm.unlink()
        except FileNotFoundError:       # pragma: no cover
            pass

    def _disable_proc_pool(self, exc: BaseException) -> None:
        """Runtime degrade: a broken sidecar pool (spawn refused, child
        OOM-killed) must not fail the encode — retire the pool and pack
        the rest of the job on threads. Swap-under-lock: several
        collector threads can hit the broken pool in the same wave
        window, and exactly ONE of them must log the retirement."""
        with self._proc_lock:
            pool, self._proc_pool = self._proc_pool, None
        if pool is not None:
            _LOG.warning(
                "pack sidecar pool broke (%s: %s); packing on threads "
                "from here on", type(exc).__name__, exc)

    def _submit_process_pack(self, proc, mv8_g, dc16_g, payload_row,
                             nblk: int, nval: int, used: int,
                             gop: GopSpec, F: int, mbw: int, mbh: int,
                             gop_qp: int, spools: list):
        """Spool one GOP's compact transfer parts ([mv8 | dense DC |
        payload]) into a shared-memory block and submit its
        unpack+unflatten+pack to the sidecar pool (packproc). Returns a
        callable yielding the slice payloads; it releases the spool
        after the result lands (`spools` lets collect_wave release
        blocks whose gather was never reached when a wave fails
        mid-flight). A BROKEN pool degrades instead of failing the
        wave: the same spool bytes pack in-process via packproc."""
        import dataclasses as _dc
        from concurrent.futures.process import BrokenProcessPool
        from multiprocessing import shared_memory

        from . import packproc

        mv = np.ascontiguousarray(mv8_g).view(np.uint8).reshape(-1)
        dn = np.ascontiguousarray(dc16_g).view(np.uint8).reshape(-1)
        pl = np.ascontiguousarray(payload_row[:used])
        total = mv.nbytes + dn.nbytes + pl.nbytes
        shm = shared_memory.SharedMemory(create=True, size=max(1, total))
        spools.append(shm)
        buf = np.frombuffer(shm.buf, np.uint8)
        buf[:mv.nbytes] = mv
        buf[mv.nbytes:mv.nbytes + dn.nbytes] = dn
        buf[mv.nbytes + dn.nbytes:total] = pl
        del buf     # shm.close() refuses while exported views exist
        args = (shm.name, mv.nbytes, dn.nbytes, pl.nbytes, nblk, nval,
                gop.num_frames, F, mbw, mbh, _dc.asdict(self.sps),
                _dc.asdict(self.pps), gop_qp, gop.index,
                _dc.asdict(self.rd))
        try:
            fut = proc.submit(packproc.pack_gop_from_shm, *args)
        except Exception:
            self._release_spool(shm, spools)
            raise
        self.stages.bump("proc_pack_gops")

        def gather() -> list[bytes]:
            try:
                return fut.result()
            except BrokenProcessPool as exc:
                self._disable_proc_pool(exc)
                # the spool holds everything the child would have read
                return packproc.pack_gop_from_shm(*args)
            finally:
                self._release_spool(shm, spools)

        return gather

    def collect_wave(self, pending: tuple) -> list[EncodedSegment]:
        """Fetch one dispatched wave's levels (compact or sparse, with
        the dense fallback) and entropy-pack its GOPs on host, fanning
        the pack across the slice pool — or, with pack_backend=process,
        handing whole GOPs to the shared-memory sidecars."""
        wave, ysd, usd, vsd, qpsd, mbw, mbh, out = pending
        prof = self.stages
        F = ysd.shape[1]
        nmb = mbw * mbh
        ships_modes = self.rd.ships_modes
        tail = 2 * nmb if ships_modes else 0     # [mode16 | dqp16]
        L = (nmb * _INTRA_MB + (F - 1) * nmb * _P_FLAT_MB + tail
             if self.inter else nmb * _INTRA_MB + tail)
        compact = self.inter and self.compact_transfer
        # Barrier on the tiny count outputs first: they complete when
        # the wave's compute does, splitting "waiting on the device"
        # from the bulk D2H fetch in the stage breakdown — and letting
        # a budget overflow skip the bulk sparse fetch entirely.
        with prof.stage("device_wait"):
            if self.inter:
                tiny = jax.device_get(list(out[2:6] if compact
                                           else out[2:5]))
            else:
                tiny = jax.device_get([out[0], out[1]])
        prof.bump("d2h_bytes", sum(int(a.nbytes) for a in tiny))
        flat = None
        used = payload_rows = None
        if self.inter:
            nblk, nval, n_esc = tiny[0], tiny[1], tiny[2]
            # dense prefix = both intra hadamard DC segments (luma +
            # chroma) + the mode/dqp tail when shipped; the sparse
            # remainder skips them (_per_gop_sparse)
            ndc, ncdc = nmb * 16, nmb * 8
            Lr = L - ndc - ncdc - tail
            sparse_ok = jaxcore.block_sparse2_fits(
                nblk.max(), nval.max(), n_esc.max(), Lr)
            if sparse_ok:
                with prof.stage("fetch"):
                    if compact:
                        used = tiny[3]
                        mv8, dc16 = self._fetch_bulk(out[0:2])
                        payload_rows = self._fetch_payload_rows(out[6],
                                                                used)
                    else:
                        mv8, dc16, bitmap, bmask16, vals = \
                            self._fetch_bulk(
                                (out[0], out[1], out[5], out[6], out[7]))
        else:
            nnz, n_esc = tiny
            sparse_ok = jaxcore.sparse_fits(nnz.max(), n_esc.max(), L)
            if sparse_ok:
                with prof.stage("fetch"):
                    bitmap, vals, esc_pos, esc_val = \
                        self._fetch_bulk(out[2:6])
        if not sparse_ok:
            # Rare wave-wide dense retry: re-encode + wide int16 fetch.
            # Its own stage (not "fetch") so the fetch number answers
            # only "what does the common bulk transfer cost", plus a
            # counter so overflow-prone content is visible in metrics.
            prof.bump("dense_fallback_waves")
            with prof.stage("dense_retry"):
                if self.inter and self.num_devices == 1:
                    flat = jax.device_get(_encode_gop_single_dense(
                        ysd, usd, vsd, qpsd, mbw=mbw, mbh=mbh,
                        dtype=jnp.int16, rd=self.rd))
                elif self.inter:
                    flat = jax.device_get(_encode_wave_gop_dense(
                        ysd, usd, vsd, qpsd, mbw=mbw, mbh=mbh,
                        mesh=self.mesh, dtype=jnp.int16, rd=self.rd))
                else:
                    flat = jax.device_get(_encode_wave_dense(
                        ysd, usd, vsd, qpsd, mbw=mbw, mbh=mbh,
                        mesh=self.mesh, dtype=jnp.int16, rd=self.rd))
                prof.bump("d2h_bytes", int(flat.nbytes))
                if self.inter:
                    # the dense program re-emits levels only; MVs still
                    # come from the already-computed sparse outputs
                    (mv8,) = self._fetch_bulk(out[0:1])
        # Header QP must match what the device QUANTIZED with — read it
        # from the staged per-wave array, not the live gop_qp dict (a
        # caller mutating gop_qp between passes must not desync slices
        # already in flight).
        qps_host = np.asarray(qpsd)
        if self.gop_index_offset or self.frame_offset:
            import dataclasses as _dc

            wave = [_dc.replace(g, index=g.index + self.gop_index_offset,
                                start_frame=(g.start_frame
                                             + self.frame_offset))
                    for g in wave]
        # Phase 1: unpack levels and SUBMIT every GOP's pack work — the
        # slice pool packs the whole wave's slices concurrently (or the
        # process sidecars take whole GOPs); phase 2 gathers in GOP
        # order.
        pool = self._slice_pool()
        with self._proc_lock:
            proc = self._proc_pool if (compact and sparse_ok) else None
        #: live shared-memory spools of this wave's process-pack jobs —
        #: released by each gather(), and swept below if the wave dies
        #: before every gather ran (a leaked block outlives the process)
        spools: list = []
        jobs: list[tuple] = []
        for gi, gop in enumerate(wave):
            gop_qp = int(qps_host[gi])
            if self.inter:
                if proc is not None:
                    jobs.append((gop, self._submit_process_pack(
                        proc, mv8[gi], dc16[gi], payload_rows[gi],
                        int(nblk[gi]), int(nval[gi]), int(used[gi]),
                        gop, F, mbw, mbh, gop_qp, spools)))
                    continue
                if sparse_ok:
                    with prof.stage("sparse_unpack"):
                        if compact:
                            rest = self._unpack_compact(
                                payload_rows[gi], int(nblk[gi]),
                                int(nval[gi]), int(used[gi]), Lr)
                        else:
                            rest = _sparse_unpack2_host(
                                int(nblk[gi]), int(nval[gi]), bitmap[gi],
                                bmask16[gi], vals[gi], Lr)
                    with prof.stage("unflatten"):
                        intra, planes = unflatten_gop_parts(
                            dc16[gi], rest, mv8[gi], F, mbw, mbh,
                            ships_modes=ships_modes)
                else:
                    with prof.stage("unflatten"):
                        intra, planes = unflatten_gop(
                            flat[gi], mv8[gi], F, mbw, mbh,
                            ships_modes=ships_modes)
                # gop.num_frames (not F) drops the wave's tail-repeat
                # padding.
                thunks = gop_slice_thunks_planes(
                    intra, planes, gop.num_frames, mbw, mbh, self.sps,
                    self.pps, gop_qp, idr_pic_id=gop.index, rd=self.rd)
            else:
                thunks = []
                for fi in range(gop.num_frames):
                    if sparse_ok:
                        with prof.stage("sparse_unpack"):
                            raw = jaxcore._sparse_unpack(
                                int(nnz[gi, fi]), int(n_esc[gi, fi]),
                                bitmap[gi, fi], vals[gi, fi],
                                esc_pos[gi, fi], esc_val[gi, fi], L)
                    else:
                        raw = flat[gi, fi]
                    thunks.append(functools.partial(
                        self._pack_intra_frame, raw, mbw, mbh, gop, fi,
                        gop_qp))
            if pool is None:
                jobs.append(
                    (gop, lambda ts=thunks: [t() for t in ts]))
            else:
                futs = [pool.submit(t) for t in thunks]
                jobs.append(
                    (gop, lambda fs=futs: [f.result() for f in fs]))
        segments: list[EncodedSegment] = []
        try:
            for gop, gather in jobs:
                with prof.stage("pack"):
                    payload = gather()
                with prof.stage("concat"):
                    seg = EncodedSegment(
                        gop=gop, payload=b"".join(payload),
                        frame_sizes=tuple(len(p) for p in payload))
                segments.append(seg)
        finally:
            for shm in list(spools):    # gathers that never ran
                self._release_spool(shm, spools)
        prof.count_wave()
        return segments

    def _pack_intra_frame(self, raw, mbw: int, mbh: int, gop: GopSpec,
                          fi: int, qp: int) -> bytes:
        """Pack one all-intra frame's IDR slice (+ SPS/PPS at the GOP
        head) from its flat levels — the intra path's slice-pool unit."""
        levels = jaxcore._unpack_levels(raw, mbw, mbh, self.rd)
        nal = pack_slice(levels, mbw, mbh, self.sps, self.pps, qp,
                         idr=True,
                         idr_pic_id=(gop.start_frame + fi) % 65536)
        if fi == 0:
            nal = self.sps.to_nal() + self.pps.to_nal() + nal
        return nal

    #: default in-flight wave window when neither the constructor nor
    #: the `pipeline_window` setting (TVT_PIPELINE_WINDOW) override it.
    PIPELINE_WINDOW = 4

    #: default staged-waves-ahead depth for the background staging
    #: thread when neither the constructor nor the `decode_ahead`
    #: setting (TVT_DECODE_AHEAD) override it.
    DECODE_AHEAD = 2

    def encode_waves(self, waves, window: int | None = None,
                     pack_workers: int | None = None
                     ) -> list[EncodedSegment]:
        """Dispatch staged waves: device compute → async sparse fetch →
        host entropy pack, in wave order.

        Pipelined three ways: up to `window` (default: the
        `pipeline_window` setting) waves are dispatched ahead — device
        queue + async device→host copies overlap the current fetch —
        each wave's fetch+unpack runs on a collector thread per
        in-flight wave, and every slice of every in-flight GOP packs
        on this encoder's `pack_workers` pool (collect_wave), so host
        packing scales with cores instead of with the window.
        """
        import concurrent.futures as cf

        window = window or self.pipeline_window
        if pack_workers is not None and int(pack_workers) != self.pack_workers:
            self.pack_workers = int(pack_workers)
            if self._pack_pool is not None:   # resize: retire the old pool
                self._pack_pool.shutdown(wait=False)
            self._pack_pool = self._new_pack_pool()
        segments: list[EncodedSegment] = []
        waves = iter(waves)
        pending: list[cf.Future] = []

        with cf.ThreadPoolExecutor(window) as pool:
            def dispatch_next():
                try:
                    staged = next(waves)
                except StopIteration:
                    return False
                pending.append(
                    pool.submit(self.collect_wave,
                                self.dispatch_wave(staged)))
                return True

            for _ in range(window):
                if not dispatch_next():
                    break
            while pending:
                segs = pending.pop(0).result()
                dispatch_next()
                segments.extend(segs)
        return segments

    @staticmethod
    def _gop_plane(cursor: _FrameCursor, gop: GopSpec, F: int, plane: str
                   ) -> np.ndarray:
        arrs = [getattr(cursor.get(i), plane)
                for i in range(gop.start_frame, gop.end_frame)]
        while len(arrs) < F:            # tail-repeat to the wave's static F
            arrs.append(arrs[-1])
        return np.stack(arrs)


# ---------------------------------------------------------------------------
# split-frame encoding (SFE): shard ONE frame across the mesh
#
# All parallelism above is GOP-level — ideal for farm throughput,
# useless for the latency of a single stream (a 2160p frame still
# encodes on one chip). SFE instead splits every frame into horizontal
# MB-row bands, one device per band (parallel/planner.plan_bands), and
# steps ONE FRAME per device program: the recon carry chains between
# steps on device, motion estimation reads a halo of reference rows
# from the neighbor bands over the mesh interconnect
# (jaxme.band_halo_exchange → lax.ppermute), and every band
# entropy-codes as its own H.264 slice (first_mb_in_slice = band start)
# so the concat of a frame's band slices is a legal picture with no
# host-side re-mux. Per-frame latency divides by the band count
# instead of amortizing across GOPs — and a frame that doesn't fit one
# device's HBM (8K) fits as bands.
# ---------------------------------------------------------------------------


def _sfe_pack_band(flat):
    """Per-band compact transfer pack: two-tier sparse + byte-payload
    fold with UNIT budget divisors — the buffers are per-frame-band
    sized (small), the fetch moves only the used prefix, and the only
    overflow left is an int8 escape (n_esc > 0 → the GOP reruns dense,
    exactly the wave path's fallback contract)."""
    nblk, nval, n_esc, bitmap, bmask16, vals = \
        jaxcore._block_sparse_pack2(flat, 1, 1)
    used, payload = jaxcore._compact_stream(nblk, nval, bitmap, bmask16,
                                            vals)
    return nblk, nval, n_esc, used, payload


@functools.partial(jax.jit, static_argnames=("mbw", "mbh_band", "mesh",
                                             "rd", "total_mb_rows"))
def _sfe_intra_step(y, u, v, qp, real_rows, *, mbw: int, mbh_band: int,
                    mesh: Mesh | None, rd=RD_OFF, total_mb_rows: int = 0):
    """One IDR frame, banded: y/u/v are full (padded) frame planes
    sharded over rows; each band runs the slice-local intra core and
    compact-packs its level streams. Returns per-band transfer arrays
    (leading dim = bands) + the recon carry, row-sharded on device.
    `mesh=None` = single band, no shard_map wrapper (on one chip the
    manual-axes lowering costs and buys nothing — same rationale as
    _encode_gop_single); outputs keep the leading band dim of 1 so the
    host collect path is band-count agnostic."""
    from ..codecs.h264 import jaxinter

    def per_band(y_b, u_b, v_b, qp_, real_b):
        dense, rest, (ry, ru, rv, pmv) = jaxinter.sfe_intra_band(
            y_b, u_b, v_b, qp_, real_b[0, 0], mbw=mbw, mbh_band=mbh_band,
            rd=rd, total_mb_rows=total_mb_rows,
            axis_name="band" if mesh is not None else None,
            num_bands=mesh.devices.size if mesh is not None else 1)
        nblk, nval, n_esc, used, payload = _sfe_pack_band(rest)
        return (dense[None], nblk[None], nval[None], n_esc[None],
                used[None], payload[None], ry, ru, rv, pmv[None])

    if mesh is None:
        return per_band(y, u, v, qp, real_rows)
    shard = shard_map(
        per_band, mesh=mesh,
        in_specs=(P("band"), P("band"), P("band"), P(), P("band")),
        out_specs=(P("band"),) * 10)
    return shard(y, u, v, qp, real_rows)


@functools.partial(jax.jit, static_argnames=("mbw", "mbh_band", "mesh",
                                             "halo_rows", "num_bands",
                                             "rd", "total_mb_rows"))
def _sfe_p_step(y, u, v, ry, ru, rv, pmv, qp, real_rows, *, mbw: int,
                mbh_band: int, mesh: Mesh | None, halo_rows: int,
                num_bands: int, rd=RD_OFF, total_mb_rows: int = 0):
    """One P frame, banded: the halo exchange + psum'd search centers
    live inside jaxinter.sfe_p_band; this wrapper shards the frame and
    recon carry over rows and compact-packs each band's levels.
    `mesh=None` as in :func:`_sfe_intra_step`."""
    from ..codecs.h264 import jaxinter

    def per_band(y_b, u_b, v_b, ry_b, ru_b, rv_b, pmv_b, qp_, real_b):
        mv8, flat, (ry2, ru2, rv2, med) = jaxinter.sfe_p_band(
            y_b, u_b, v_b, (ry_b, ru_b, rv_b, pmv_b[0]), qp_,
            real_b[0, 0], mbw=mbw, mbh_band=mbh_band,
            halo_rows=halo_rows, num_bands=num_bands,
            axis_name="band" if mesh is not None else None,
            rd=rd, total_mb_rows=total_mb_rows)
        nblk, nval, n_esc, used, payload = _sfe_pack_band(flat)
        return (mv8[None], nblk[None], nval[None], n_esc[None],
                used[None], payload[None], ry2, ru2, rv2, med[None])

    if mesh is None:
        return per_band(y, u, v, ry, ru, rv, pmv, qp, real_rows)
    shard = shard_map(
        per_band, mesh=mesh,
        in_specs=(P("band"),) * 7 + (P(), P("band")),
        out_specs=(P("band"),) * 10)
    return shard(y, u, v, ry, ru, rv, pmv, qp, real_rows)


@functools.partial(jax.jit, static_argnames=("mbw", "mbh_band", "mesh",
                                             "rd", "total_mb_rows"))
def _sfe_intra_step_dense(y, u, v, qp, real_rows, *, mbw: int,
                          mbh_band: int, mesh: Mesh | None, rd=RD_OFF,
                          total_mb_rows: int = 0):
    """Escape fallback: the same intra step emitting the flat int16
    levels uncompressed (layout.unflatten_intra's inverse per band)."""
    from ..codecs.h264 import jaxinter

    def per_band(y_b, u_b, v_b, qp_, real_b):
        flat, (ry, ru, rv, pmv) = jaxinter.sfe_intra_band_dense(
            y_b, u_b, v_b, qp_, real_b[0, 0], mbw=mbw, mbh_band=mbh_band,
            rd=rd, total_mb_rows=total_mb_rows,
            axis_name="band" if mesh is not None else None,
            num_bands=mesh.devices.size if mesh is not None else 1)
        return flat[None], ry, ru, rv, pmv[None]

    if mesh is None:
        return per_band(y, u, v, qp, real_rows)
    shard = shard_map(per_band, mesh=mesh,
                      in_specs=(P("band"),) * 3 + (P(), P("band")),
                      out_specs=(P("band"),) * 5)
    return shard(y, u, v, qp, real_rows)


@functools.partial(jax.jit, static_argnames=("mbw", "mbh_band", "mesh",
                                             "halo_rows", "num_bands",
                                             "rd", "total_mb_rows"))
def _sfe_p_step_dense(y, u, v, ry, ru, rv, pmv, qp, real_rows, *,
                      mbw: int, mbh_band: int, mesh: Mesh | None,
                      halo_rows: int, num_bands: int, rd=RD_OFF,
                      total_mb_rows: int = 0):
    from ..codecs.h264 import jaxinter

    def per_band(y_b, u_b, v_b, ry_b, ru_b, rv_b, pmv_b, qp_, real_b):
        mv8, flat, (ry2, ru2, rv2, med) = jaxinter.sfe_p_band(
            y_b, u_b, v_b, (ry_b, ru_b, rv_b, pmv_b[0]), qp_,
            real_b[0, 0], mbw=mbw, mbh_band=mbh_band,
            halo_rows=halo_rows, num_bands=num_bands,
            axis_name="band" if mesh is not None else None,
            rd=rd, total_mb_rows=total_mb_rows)
        return mv8[None], flat[None], ry2, ru2, rv2, med[None]

    if mesh is None:
        return per_band(y, u, v, ry, ru, rv, pmv, qp, real_rows)
    shard = shard_map(per_band, mesh=mesh,
                      in_specs=(P("band"),) * 7 + (P(), P("band")),
                      out_specs=(P("band"),) * 6)
    return shard(y, u, v, ry, ru, rv, pmv, qp, real_rows)


# ---------------------------------------------------------------------------
# farm-split SFE steps (cross-HOST band slices, parallel/sfefarm.py)
#
# The local steps above run the halo exchange and the probe/median
# psums inside ONE program over the full band mesh. When the band
# layout spans HOSTS, the cross-host halves of those collectives move
# to the host side: neighbor reference rows arrive as injected inputs
# (cluster/halo.py carries them between hosts per frame), the probe
# splits into a per-host partial-cost program + a host-side argmin,
# and the median histogram leaves the device as a per-host partial.
# All three are integer sums, so host-side reduction is bit-identical
# to the device psum — the farm stream equals the local-mesh stream.
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("mesh", "num_bands"))
def _sfe_probe_step(cur_y, ref_y, real_rows, top_y, bot_y, edges, *,
                    mesh: Mesh | None, num_bands: int):
    """Per-host half of the split global-motion probe: each local
    band's partial per-window SAD cost, psum'd over THIS mesh only.
    Returns (num_bands, n*n) int32 — every row identical; the host
    ships row 0 to its peers and argmins the cross-host sum
    (jaxme.probe_center_from_cost). `edges` is the traced (2,) bool
    [edge_top, edge_bot] — an INPUT, not a static, so one compiled
    program serves a band slice at any position in the layout."""
    from ..codecs.h264 import jaxme

    def per_band(cur_b, ref_b, real_b, ty_b, by_b, edges_):
        cost = jaxme.banded_probe_cost(
            cur_b.astype(jnp.int16), ref_b, real_b[0, 0],
            "band" if mesh is not None else None, num_bands,
            top_ext=ty_b, bot_ext=by_b,
            edge_top=edges_[0], edge_bot=edges_[1])
        return cost[None]

    if mesh is None:
        return per_band(cur_y, ref_y, real_rows, top_y, bot_y, edges)
    shard = shard_map(per_band, mesh=mesh,
                      in_specs=(P("band"),) * 5 + (P(),),
                      out_specs=P("band"))
    return shard(cur_y, ref_y, real_rows, top_y, bot_y, edges)


@functools.partial(jax.jit, static_argnames=(
    "mbw", "mbh_band", "mesh", "halo_rows", "num_bands", "rd"))
def _sfe_p_step_farm(y, u, v, ry, ru, rv, pred_mv, probe, ty, by, tu,
                     bu, tv, bv, qp, real_rows, edges, *, mbw: int,
                     mbh_band: int, mesh: Mesh | None, halo_rows: int,
                     num_bands: int, rd=RD_OFF):
    """One P frame of a band SLICE: the search runs on halo-extended
    planes whose slice-edge rows were injected by the host (`ty..bv`,
    band-sharded — only the edge bands' shards are read), the probe
    center and temporal median arrive as replicated host inputs, and
    the per-host histogram partial rides out beside the compact level
    streams. `mesh=None` = single local band, as in the local steps.
    `edges` = traced (2,) bool [edge_top, edge_bot] (an input, not a
    static: a worker re-claiming a DIFFERENT band slice reuses the
    same compiled program)."""
    from ..codecs.h264 import jaxinter

    def per_band(y_b, u_b, v_b, ry_b, ru_b, rv_b, pred_, probe_, ty_b,
                 by_b, tu_b, bu_b, tv_b, bv_b, qp_, real_b, edges_):
        mv8, flat, cnt, n, (ry2, ru2, rv2, _pm) = jaxinter.sfe_p_band(
            y_b, u_b, v_b, (ry_b, ru_b, rv_b, pred_), qp_, real_b[0, 0],
            mbw=mbw, mbh_band=mbh_band, halo_rows=halo_rows,
            num_bands=num_bands,
            axis_name="band" if mesh is not None else None,
            ext=(ty_b, by_b, tu_b, bu_b, tv_b, bv_b),
            edge_top=edges_[0], edge_bot=edges_[1], probe=probe_,
            return_hist=True, rd=rd)
        nblk, nval, n_esc, used, payload = _sfe_pack_band(flat)
        return (mv8[None], nblk[None], nval[None], n_esc[None],
                used[None], payload[None], cnt[None],
                n.reshape(1), ry2, ru2, rv2)

    if mesh is None:
        return per_band(y, u, v, ry, ru, rv, pred_mv, probe, ty, by,
                        tu, bu, tv, bv, qp, real_rows, edges)
    shard = shard_map(
        per_band, mesh=mesh,
        in_specs=(P("band"),) * 6 + (P(), P()) + (P("band"),) * 6
        + (P(), P("band"), P()),
        out_specs=(P("band"),) * 11)
    return shard(y, u, v, ry, ru, rv, pred_mv, probe, ty, by, tu, bu,
                 tv, bv, qp, real_rows, edges)


@functools.partial(jax.jit, static_argnames=(
    "mbw", "mbh_band", "mesh", "halo_rows", "num_bands", "rd"))
def _sfe_p_step_farm_dense(y, u, v, ry, ru, rv, pred_mv, probe, ty, by,
                           tu, bu, tv, bv, qp, real_rows, edges, *,
                           mbw: int, mbh_band: int, mesh: Mesh | None,
                           halo_rows: int, num_bands: int, rd=RD_OFF):
    """Escape fallback for the farm P step: same compute, uncompressed
    int16 levels. The replay is host-local (the cached per-frame
    injected inputs fully determine this slice's bits), so no
    histogram needs to leave the device."""
    from ..codecs.h264 import jaxinter

    def per_band(y_b, u_b, v_b, ry_b, ru_b, rv_b, pred_, probe_, ty_b,
                 by_b, tu_b, bu_b, tv_b, bv_b, qp_, real_b, edges_):
        mv8, flat, _cnt, _n, (ry2, ru2, rv2, _pm) = jaxinter.sfe_p_band(
            y_b, u_b, v_b, (ry_b, ru_b, rv_b, pred_), qp_, real_b[0, 0],
            mbw=mbw, mbh_band=mbh_band, halo_rows=halo_rows,
            num_bands=num_bands,
            axis_name="band" if mesh is not None else None,
            ext=(ty_b, by_b, tu_b, bu_b, tv_b, bv_b),
            edge_top=edges_[0], edge_bot=edges_[1], probe=probe_,
            return_hist=True, rd=rd)
        return mv8[None], flat[None], ry2, ru2, rv2

    if mesh is None:
        return per_band(y, u, v, ry, ru, rv, pred_mv, probe, ty, by,
                        tu, bu, tv, bv, qp, real_rows, edges)
    shard = shard_map(
        per_band, mesh=mesh,
        in_specs=(P("band"),) * 6 + (P(), P()) + (P("band"),) * 6
        + (P(), P("band"), P()),
        out_specs=(P("band"),) * 5)
    return shard(y, u, v, ry, ru, rv, pred_mv, probe, ty, by, tu, bu,
                 tv, bv, qp, real_rows, edges)


class SfeShardEncoder(GopShardEncoder):
    """Split-frame encoding: ONE frame sharded across the mesh as
    horizontal MB-row bands, each entropy-coded as its own H.264 slice.

    The GOP walk is sequential (this is the single-stream latency mode
    — GOP-level parallelism is the parent class); within a GOP, frames
    step one device program at a time with the recon carry resident on
    device, and the collect path is PER FRAME: a frame's band levels
    are fetched and its band slices packed (concurrently on the pack
    pool) as soon as its step completes, while the device runs the
    next frame — `frame_done_t` records each frame's bitstream-ready
    timestamp and the bench derives `sfe_latency_ms_2160p` from it.

    A "wave" for the executor's retry/progress machinery is one GOP
    (closed: an IDR step resets the carry, so a failed GOP re-dispatches
    from its retained staged frames like any wave).

    Output contract: byte-stream-legal multi-slice pictures — the
    concat of a GOP's frames is a closed GOP exactly like the parent's,
    just with `num_bands` slices per picture; downstream (MP4 mux, HLS)
    groups slices into access units by first_mb_in_slice.
    """

    def __init__(self, meta: VideoMeta, qp: int = 27,
                 mesh: Mesh | None = None, gop_frames: int = 32,
                 max_segments: int = 200, bands: int = 0,
                 halo_rows: int | None = None,
                 pack_workers: int | None = None,
                 pipeline_window: int | None = None,
                 decode_ahead: int | None = None,
                 total_bands: int = 0,
                 band_range: tuple[int, int] | None = None,
                 rd: RdConfig | None = None):
        snap = get_settings()
        full_mesh = mesh if mesh is not None else default_mesh()
        devices = list(full_mesh.devices.flat)
        mbh = (meta.height + 15) // 16
        mbw = (meta.width + 15) // 16
        #: pinned GLOBAL band layout. Locally `total_bands=0` sizes it
        #: to this process's devices; on a farm the coordinator pins
        #: `total_bands` for the whole frame and `band_range=(lo, hi)`
        #: assigns this process a contiguous slice of it (the cross-
        #: host SFE shard, parallel/sfefarm.py) — the layout (and so
        #: the slice structure of the bitstream) never depends on any
        #: one host's device count.
        if total_bands:
            self.global_band_plan: BandPlan = plan_bands(
                mbh, mbw, max(1, int(total_bands)))
        else:
            want = int(bands) or len(devices)
            self.global_band_plan = plan_bands(
                mbh, mbw, max(1, min(want, len(devices))))
        lo, hi = band_range if band_range is not None \
            else (0, self.global_band_plan.num_bands)
        lo, hi = int(lo), min(int(hi), self.global_band_plan.num_bands)
        if not 0 <= lo < hi:
            raise ValueError(f"empty band range [{lo}, {hi})")
        if hi - lo > len(devices):
            raise ValueError(
                f"band slice [{lo}, {hi}) needs {hi - lo} devices; "
                f"this host has {len(devices)}")
        #: this process's slice of the layout (band indices, and hence
        #: slice first_mb coordinates, stay GLOBAL)
        self.band_lo, self.band_hi = lo, hi
        self.band_plan: BandPlan = BandPlan(
            bands=self.global_band_plan.bands[lo:hi],
            band_mb_rows=self.global_band_plan.band_mb_rows,
            mb_width=self.global_band_plan.mb_width)
        #: frame 0 of each GOP opens the picture's access unit with
        #: SPS/PPS — only the band slice that owns band 0 emits them
        #: (a farm peer's slices join the SAME access unit downstream)
        self.emit_parameter_sets = lo == 0
        band_mesh = Mesh(np.array(devices[:self.band_plan.num_bands]),
                         ("band",))
        super().__init__(meta, qp=qp, mesh=band_mesh,
                         gop_frames=gop_frames, max_segments=max_segments,
                         inter=True, gops_per_wave=1,
                         pack_workers=pack_workers,
                         pipeline_window=pipeline_window,
                         decode_ahead=decode_ahead,
                         pack_backend="thread", rd=rd)
        if halo_rows is None:
            halo_rows = int(snap.get("sfe_halo_rows", 32) or 32)
        #: reference rows exchanged per side (multiple of 16). >= 23
        #: (SEARCH_RANGE + window + taps) keeps the banded search
        #: bit-identical to full-frame; smaller clamps the vertical
        #: search range (jaxme.halo_clamp) — bounded, not drifting.
        #: Capped at the band height: one ppermute hop reaches one
        #: neighbor, so very thin bands trade vertical range for width.
        self.halo_rows = max(16, (int(halo_rows) // 16) * 16)
        self.halo_rows = min(self.halo_rows,
                             self.band_plan.band_mb_rows * 16)
        #: per-frame bitstream-ready timestamps (time.perf_counter), in
        #: encode order — the bench's latency source. Bounded: a
        #: long-running job appends one entry per frame forever, so
        #: only the most recent window survives (enough for any
        #: latency percentile; bench clears it per timed pass anyway).
        self.frame_done_t: deque = deque(maxlen=4096)
        #: previous frame's bitstream-ready perf_counter — the source
        #: of the per-frame latency gap fed to the process-global
        #: _SFE_LAT_MS ring + the tvt_sfe_frame_latency_seconds
        #: histogram (concurrent collectors append near-order; a
        #: benign race here only drops/shifts one sample)
        self._last_frame_done: float | None = None
        #: test hook: device_get each frame's recon carry into
        #: `recon_frames` (absolute frame index → display-cropped
        #: y/u/v) for conformance parity against an independent decode
        #: — keyed, not appended: pipelined GOPs collect on concurrent
        #: threads in completion order
        self.keep_recon = False
        self.recon_frames: dict[int, tuple] = {}
        # RD feature gates for the banded shape: perceptual AQ would
        # make the per-band activity mean band-local (a different map
        # than the unbanded program) — strip it with a log line rather
        # than encode something byte-different per band count; the
        # in-loop filter needs the cross-band halo exchange, which the
        # cross-host (farm) slices cannot run in one device program.
        if self.rd.aq_q:
            _LOG.warning("perceptual AQ is not supported by split-frame "
                         "encoding; encoding this job with aq off")
            import dataclasses as _dc

            self.rd = _dc.replace(self.rd, aq_q=0)
        if self.rd.deblock and (self.band_lo, self.band_hi) != (
                0, self.global_band_plan.num_bands):
            raise ValueError(
                "deblock is not supported on cross-host band slices; "
                "the remote planner must fall back to GOP shards")
        #: the picture's REAL MB rows (band-grid padding rows beyond it
        #: carry no coded MBs): the deblock masks key off this
        self._total_mb_rows = mbh
        bp = self.band_plan
        self._real_rows = jax.device_put(
            np.asarray([[b.mb_rows * 16] for b in bp.bands], np.int32),
            NamedSharding(self.mesh, P("band")))

    @property
    def num_bands(self) -> int:
        return self.band_plan.num_bands

    def plan(self, num_frames: int) -> SegmentPlan:
        if self.plan_override is not None:
            return self.plan_override
        # fixed grid: GOP boundaries are a pure function of
        # (num_frames, gop_frames, max_segments) — the mesh
        # parallelizes WITHIN frames, so the parent's wave balancing
        # (GOP count rounded to mesh width) would only distort
        # latency-ordered boundaries. max_segments is still honored by
        # growing the GOP length once up front (the parent's cap
        # semantics; long clips must not overshoot segment bookkeeping
        # 8x just because SFE is on).
        gop = max(self.gop_frames,
                  -(-num_frames // max(1, self.max_segments)))
        return plan_fixed_segments(num_frames, gop, self.num_bands)

    # -- staging --------------------------------------------------------

    def _pad_rows(self, plane: np.ndarray, rows: int) -> np.ndarray:
        if plane.shape[0] == rows:
            return np.ascontiguousarray(plane)
        pad = rows - plane.shape[0]
        return np.concatenate([plane, np.repeat(plane[-1:], pad, axis=0)])

    def stage_waves(self, frames):
        """One GOP per staged wave: each frame device_put row-sharded
        over the band mesh (padded to the band grid's height with edge
        replication — the padding rows are computed and discarded). A
        band SLICE (farm mode) pads to the GLOBAL grid height and
        uploads only its own rows — each host decodes the full frame
        but stages O(slice) pixels."""
        plan = self.plan(len(frames))
        cursor = _FrameCursor(frames, self.stages, require_420=True,
                              stats=self.staging_stats)
        rows16 = self.band_plan.band_mb_rows * 16
        Hg = self.global_band_plan.padded_mb_height * 16
        y0, y1 = self.band_lo * rows16, self.band_hi * rows16
        shard = NamedSharding(self.mesh, P("band"))
        for gop in plan.gops:
            cursor.get(gop.end_frame - 1)   # decode outside "stage"
            with self.stages.stage("stage"):
                ys, us, vs = [], [], []
                for i in range(gop.start_frame, gop.end_frame):
                    f = cursor.get(i)
                    ya = self._pad_rows(f.y, Hg)[y0:y1]
                    ua = self._pad_rows(f.u, Hg // 2)[y0 // 2:y1 // 2]
                    va = self._pad_rows(f.v, Hg // 2)[y0 // 2:y1 // 2]
                    self.stages.bump("h2d_bytes", ya.nbytes + ua.nbytes
                                     + va.nbytes)
                    ys.append(jax.device_put(ya, shard))
                    us.append(jax.device_put(ua, shard))
                    vs.append(jax.device_put(va, shard))
                qp = int(self.gop_qp.get(gop.index, self.qp))
            yield (gop, ys, us, vs, qp)
            cursor.release_below(gop.end_frame)

    # -- device steps ---------------------------------------------------

    def encode_waves(self, waves, window: int | None = None,
                     pack_workers: int | None = None):
        # fresh latency baseline per encode pass: the idle gap since a
        # PREVIOUS pass's last frame is not a per-frame latency and
        # must not become the reported p99 (bench reuses one encoder
        # across warmup + timed passes)
        self._last_frame_done = None
        return super().encode_waves(waves, window=window,
                                    pack_workers=pack_workers)

    def _step_mesh(self) -> Mesh | None:
        """None on a single band: the per-band program runs without the
        shard_map wrapper (and without collectives)."""
        return self.mesh if self.band_plan.num_bands > 1 else None

    def _intra_step(self, y, u, v, qp):
        bp = self.band_plan
        return _sfe_intra_step(y, u, v, qp, self._real_rows,
                               mbw=bp.mb_width, mbh_band=bp.band_mb_rows,
                               mesh=self._step_mesh(), rd=self.rd,
                               total_mb_rows=self._total_mb_rows)

    def _p_step(self, y, u, v, carry, qp):
        bp = self.band_plan
        ry, ru, rv, pmv = carry
        return _sfe_p_step(y, u, v, ry, ru, rv, pmv, qp, self._real_rows,
                           mbw=bp.mb_width, mbh_band=bp.band_mb_rows,
                           mesh=self._step_mesh(),
                           halo_rows=self.halo_rows,
                           num_bands=bp.num_bands, rd=self.rd,
                           total_mb_rows=self._total_mb_rows)

    def dispatch_wave(self, staged: tuple) -> tuple:
        """Enqueue one GOP's per-frame steps (all async — jax dispatch
        returns immediately; the device runs them in order as the recon
        carry chains). Returns the per-frame output handles + each
        frame's dispatch timestamp."""
        with self.stages.stage("dispatch"):
            gop, ys, us, vs, qp = staged
            qpj = jnp.asarray(qp, jnp.int32)
            outs: list[tuple] = []
            carries: list[tuple] = []
            carry = None
            for fi in range(gop.num_frames):
                if fi == 0:
                    r = self._intra_step(ys[0], us[0], vs[0], qpj)
                else:
                    r = self._p_step(ys[fi], us[fi], vs[fi], carry, qpj)
                carry = r[6:]
                outs.append(r[:6])
                # retain per-frame carries ONLY for the test hook: each
                # is a full set of band recon planes (~100 MB at 8K),
                # and the step-to-step chain keeps the live one alive
                carries.append(carry if self.keep_recon else None)
                if not self._async_copy_unavailable:
                    try:
                        for arr in r[1:5]:      # tiny counts only: the
                            arr.copy_to_host_async()  # payload fetches a
                    except Exception:           # used-prefix slice
                        self._async_copy_unavailable = True
            return (gop, staged, outs, carries)

    # -- per-frame collect ---------------------------------------------

    def _band_sizes(self, intra: bool) -> tuple[int, int]:
        """(nmb_band, L) of one band's transfer vector."""
        bp = self.band_plan
        nmb = bp.mb_width * bp.band_mb_rows
        L = nmb * (_INTRA_MB - 24) if intra else nmb * _P_FLAT_MB
        return nmb, L

    def _pack_intra_levels(self, intra, bi: int, qp: int,
                           idr_pic_id: int) -> bytes:
        """Shared tail of the sparse and dense-fallback intra band
        packs (which must stay bit-identical): truncate to the band's
        REAL MB rows and emit its IDR band slice. The mode raster —
        shipped per MB when rd.ships_modes, the slice-local
        _mode_policy otherwise — is BAND-relative either way: the
        band's first MB row is its slice's row 0."""
        bp = self.band_plan
        band = bp.bands[bi]
        mbw = bp.mb_width
        n_real = band.mb_rows * mbw
        if len(intra) == 6:
            il_dc, il_ac, ic_dc, ic_ac, mode16, _dqp = intra
            luma_mode, chroma_mode = unpack_mode16(mode16[:n_real])
        else:
            il_dc, il_ac, ic_dc, ic_ac = intra
            luma_mode, chroma_mode = _mode_policy(mbw, band.mb_rows)
        levels = FrameLevels(
            luma_mode=luma_mode, chroma_mode=chroma_mode,
            luma_dc=il_dc[:n_real], luma_ac=il_ac[:n_real],
            chroma_dc=ic_dc[:n_real], chroma_ac=ic_ac[:n_real])
        return pack_slice(levels, mbw, band.mb_rows, self.sps, self.pps,
                          qp, frame_num=0, idr=True,
                          idr_pic_id=idr_pic_id,
                          first_mb=band.start_mb_row * mbw,
                          deblock=self.rd.deblock)

    def _pack_intra_band(self, dense_b, rest, bi: int, qp: int,
                         idr_pic_id: int) -> bytes:
        bp = self.band_plan
        intra = unflatten_gop_parts(dense_b, rest,
                                    np.empty((0, 0, 2), np.int8), 1,
                                    bp.mb_width, bp.band_mb_rows,
                                    ships_modes=self.rd.ships_modes)[0]
        return self._pack_intra_levels(intra, bi, qp, idr_pic_id)

    def _pack_p_band(self, mv8_b, rest, bi: int, qp: int,
                     frame_num: int) -> bytes:
        from ..codecs.h264 import inter as inter_mod

        bp = self.band_plan
        band = bp.bands[bi]
        mbw = bp.mb_width
        mv, lp, udc, vdc, uac, vac = unflatten_p_planes(
            rest, mv8_b, 2, mbw, bp.band_mb_rows)
        rr = band.mb_rows * 16
        n_real = band.mb_rows * mbw
        return inter_mod.pack_p_slice_plane(
            mv[:n_real], lp[0][:rr], udc[0][:n_real], vdc[0][:n_real],
            uac[0][:rr // 2], vac[0][:rr // 2], mbw, band.mb_rows,
            self.sps, self.pps, qp, frame_num=frame_num,
            first_mb=band.start_mb_row * mbw, deblock=self.rd.deblock)

    def _gather_frame(self, thunks: list) -> list[bytes]:
        pool = self._slice_pool()
        if pool is None:
            return [t() for t in thunks]
        return [f.result() for f in [pool.submit(t) for t in thunks]]

    def _note_frame_done(self, frame_index: int) -> None:
        """One SFE frame's bitstream is ready: stamp frame_done_t (the
        bench's latency source), count it, and — when a previous frame
        exists — record the steady-state gap as a latency sample
        (global percentile ring + histogram) and a `sfe_frame` span in
        the job's trace."""
        now = time.perf_counter()
        prev, self._last_frame_done = self._last_frame_done, now
        self.stages.bump("sfe_frames")
        self.frame_done_t.append(now)
        if prev is None or now <= prev:
            return
        gap = now - prev
        with _SFE_LAT_LOCK:
            _SFE_LAT_MS.append(gap * 1e3)
        obs_metrics.SFE_FRAME_SECONDS.observe(gap)
        tracer = self.stages.tracer()
        if tracer is not None:
            tracer.record("sfe_frame", time.time() - gap, gap,
                          frame=frame_index)

    def _keep_recon(self, carry, frame_index: int) -> None:
        ry, ru, rv = jax.device_get(carry[:3])
        h, w = self.meta.height, self.meta.width
        self.recon_frames[frame_index] = (
            np.asarray(ry)[:h, :w].astype(np.uint8),
            np.asarray(ru)[:h // 2, :w // 2].astype(np.uint8),
            np.asarray(rv)[:h // 2, :w // 2].astype(np.uint8))

    def collect_wave(self, pending: tuple) -> list[EncodedSegment]:
        """Per-FRAME collect: barrier on frame fi's tiny counts, fetch
        its band payloads (one transfer per band shard), entropy-pack
        its band slices on the pack pool, and emit the frame's bytes —
        all while the device runs frames fi+1.. of this GOP (and the
        next dispatched GOP). An int8 escape in any band reruns the
        whole GOP through the dense-transfer steps (bit-identical
        levels, wider fetch), the wave path's fallback contract."""
        gop, staged, outs, carries = pending
        prof = self.stages
        bp = self.band_plan
        qp = staged[4]
        if self.gop_index_offset or self.frame_offset:
            import dataclasses as _dc

            gop = _dc.replace(gop, index=gop.index + self.gop_index_offset,
                              start_frame=(gop.start_frame
                                           + self.frame_offset))
        idr_pic_id = gop.index % 65536
        nals: list[bytes] = []
        dense_from = None
        for fi, out in enumerate(outs):
            head, nblk, nval, n_esc, used, payload = out
            with prof.stage("device_wait"):
                tiny = jax.device_get([nblk, nval, n_esc, used])
            prof.bump("d2h_bytes", sum(int(a.nbytes) for a in tiny))
            nblk_h, nval_h, nesc_h, used_h = tiny
            if int(np.asarray(nesc_h).max()) > 0:
                dense_from = fi         # escape: rerun the GOP dense
                break
            _, L = self._band_sizes(intra=(fi == 0))
            with prof.stage("fetch"):
                (head_h,) = self._fetch_bulk([head])
                rows = self._fetch_payload_rows(payload, used_h)
            with prof.stage("sfe"):
                thunks = []
                for bi in range(bp.num_bands):
                    rest = functools.partial(
                        self._unpack_compact, rows[bi], int(nblk_h[bi]),
                        int(nval_h[bi]), int(used_h[bi]), L)
                    if fi == 0:
                        thunks.append(functools.partial(
                            lambda r, b: self._pack_intra_band(
                                head_h[b], r(), b, qp, idr_pic_id),
                            rest, bi))
                    else:
                        thunks.append(functools.partial(
                            lambda r, b, fn: self._pack_p_band(
                                head_h[b], r(), b, qp, fn),
                            rest, bi, fi % 256))
                frame_nal = b"".join(self._gather_frame(thunks))
            if fi == 0 and self.emit_parameter_sets:
                frame_nal = self.sps.to_nal() + self.pps.to_nal() \
                    + frame_nal
            nals.append(frame_nal)
            self._note_frame_done(gop.start_frame + fi)
            if self.keep_recon:
                self._keep_recon(carries[fi], gop.start_frame + fi)
        if dense_from is not None:
            nals = self._collect_dense(gop, staged, nals, dense_from)
        with prof.stage("concat"):
            seg = EncodedSegment(gop=gop, payload=b"".join(nals),
                                 frame_sizes=tuple(len(n) for n in nals))
        prof.count_wave()
        return [seg]

    def _collect_dense(self, gop: GopSpec, staged: tuple,
                       nals: list[bytes], dense_from: int) -> list[bytes]:
        """Escape fallback: rerun the GOP through the dense-transfer
        steps (same compute, uncompressed int16 levels) and pack every
        frame from `dense_from` on. Frames already packed from the
        sparse path are kept — levels are identical either way."""
        prof = self.stages
        bp = self.band_plan
        _, ys, us, vs, qp = staged
        qpj = jnp.asarray(qp, jnp.int32)
        mesh = self._step_mesh()
        idr_pic_id = gop.index % 65536
        prof.bump("dense_fallback_waves")
        with prof.stage("dense_retry"):
            carry = None
            for fi in range(gop.num_frames):
                if fi == 0:
                    r = _sfe_intra_step_dense(
                        ys[0], us[0], vs[0], qpj, self._real_rows,
                        mbw=bp.mb_width, mbh_band=bp.band_mb_rows,
                        mesh=mesh, rd=self.rd,
                        total_mb_rows=self._total_mb_rows)
                    head, flat, carry = None, r[0], r[1:]
                else:
                    r = _sfe_p_step_dense(
                        ys[fi], us[fi], vs[fi], *carry[:3], carry[3],
                        qpj, self._real_rows, mbw=bp.mb_width,
                        mbh_band=bp.band_mb_rows, mesh=mesh,
                        halo_rows=self.halo_rows, num_bands=bp.num_bands,
                        rd=self.rd, total_mb_rows=self._total_mb_rows)
                    head, flat, carry = r[0], r[1], r[2:]
                if fi < dense_from:
                    continue            # already packed from sparse
                if head is None:
                    flat_h = self._fetch_bulk([flat])[0]
                    head_h = None
                else:
                    head_h, flat_h = self._fetch_bulk([head, flat])
                thunks = []
                for bi in range(bp.num_bands):
                    if fi == 0:
                        thunks.append(functools.partial(
                            lambda b, f: self._pack_intra_band_dense(
                                f[b], b, qp, idr_pic_id),
                            bi, flat_h))
                    else:
                        thunks.append(functools.partial(
                            lambda b, m, f, fn: self._pack_p_band(
                                m[b], f[b], b, qp, fn),
                            bi, head_h, flat_h, fi % 256))
                frame_nal = b"".join(self._gather_frame(thunks))
                if fi == 0 and self.emit_parameter_sets:
                    frame_nal = self.sps.to_nal() + self.pps.to_nal() \
                        + frame_nal
                nals.append(frame_nal)
                self._note_frame_done(gop.start_frame + fi)
                if self.keep_recon:
                    self._keep_recon(carry, gop.start_frame + fi)
        return nals

    def _pack_intra_band_dense(self, flat_b, bi: int, qp: int,
                               idr_pic_id: int) -> bytes:
        bp = self.band_plan
        nmb = bp.mb_width * bp.band_mb_rows
        flat_b = np.asarray(flat_b)
        intra = unflatten_intra(flat_b[:nmb * _INTRA_MB], nmb)
        if self.rd.ships_modes:
            t = nmb * _INTRA_MB
            intra = intra + (flat_b[t:t + nmb], flat_b[t + nmb:])
        return self._pack_intra_levels(intra, bi, qp, idr_pic_id)

    def frame_latencies_ms(self) -> list[float]:
        """Per-frame pipeline latency: the gap between consecutive
        frames' bitstream-ready timestamps within the steady state —
        at the live edge each frame exits the (device step → fetch →
        band pack) pipeline one such gap after entering it. The first
        frame of the run (cold: includes dispatch of the whole first
        GOP) is excluded. Sorted first: overlapping collector threads
        (pipeline_window > 1) append near-, not strictly-, in order."""
        ts = sorted(self.frame_done_t)
        return [(b - a) * 1e3 for a, b in zip(ts, ts[1:])]


def make_shard_encoder(meta: VideoMeta, settings, mesh, *,
                       shape: str | None = None, rungs=None,
                       qp: int | None = None, total_bands: int = 0,
                       band_range: tuple[int, int] | None = None,
                       halo_rows: int | None = None, session=None):
    """The ONE plan-driven shard-executor seam: every encode path —
    local executor, remote worker, live pipeline — resolves its
    encoder here, keyed off the unified plan shape
    (parallel/planner.EncodePlan) instead of per-call-site if/else
    ladders.

    shape=None resolves from settings (`sfe_bands > 0` → band shape,
    else GOP waves); `rungs` selects the ladder form (which stages
    once and fans renditions); `band_range`/`total_bands` select the
    cross-host band-slice form (parallel/sfefarm.py) with `session`
    carrying the halo exchange."""
    qp = int(settings.qp) if qp is None else int(qp)
    gop_frames = int(settings.gop_frames)
    max_segments = int(settings.max_segments)
    if rungs:
        from ..abr.ladder import LadderShardEncoder

        return LadderShardEncoder(meta, list(rungs), mesh=mesh,
                                  gop_frames=gop_frames,
                                  max_segments=max_segments)
    if shape is None:
        shape = "band" if int(settings.get("sfe_bands", 0) or 0) > 0 \
            else "gop"
    if shape == "band":
        if halo_rows is None:
            halo_rows = int(settings.get("sfe_halo_rows", 32) or 32)
        if band_range is not None or total_bands:
            from .sfefarm import FarmBandEncoder

            return FarmBandEncoder(
                meta, qp=qp, mesh=mesh, gop_frames=gop_frames,
                max_segments=max_segments, total_bands=total_bands,
                band_range=band_range, halo_rows=halo_rows,
                session=session)
        return SfeShardEncoder(
            meta, qp=qp, mesh=mesh, gop_frames=gop_frames,
            max_segments=max_segments,
            bands=int(settings.get("sfe_bands", 0) or 0),
            halo_rows=halo_rows)
    if shape != "gop":
        raise ValueError(f"unknown shard shape {shape!r}")
    return GopShardEncoder(meta, qp=qp, mesh=mesh,
                           gop_frames=gop_frames,
                           max_segments=max_segments)


def encode_clip_sharded(frames: list[Frame], meta: VideoMeta, qp: int = 27,
                        mesh: Mesh | None = None, gop_frames: int = 32,
                        inter: bool = True) -> bytes:
    """Convenience: plan → shard encode → order-restoring concat."""
    from ..core.types import concat_segments

    enc = GopShardEncoder(meta, qp=qp, mesh=mesh, gop_frames=gop_frames,
                          inter=inter)
    return concat_segments(enc.encode(frames))
