"""Origin response planning + LL-HLS blocking-reload machinery.

`plan_file` turns one request (method, Range, If-None-Match) against
one on-disk resource into a :class:`ServePlan` — status, headers, and
either an in-memory body (hot-cache hit) or a (offset, length) disk
window the HTTP layer streams in chunks. It implements the origin
contract a fronting CDN keys on: strong ETags on everything,
`If-None-Match` → 304, single-range RFC 7233 requests → 206 with
`Content-Range` (multi-range falls back to a full 200, which the RFC
permits), unsatisfiable ranges → 416, and HEAD everywhere so players
and CDNs can probe sizes without downloading.

The LL-HLS half bounds the blocking-reload path: `ReloadGate` caps the
waiters one job may pin (beyond the cap the API answers 503 +
`Retry-After` instead of eating a server thread), and
`PlaylistEdgeWatcher` replaces per-request disk polling with ONE
poller per watched playlist — N waiters on a hot live stream cost one
20 ms file read, not N.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable

from ..obs import metrics as obs_metrics
from .cache import HotSegmentCache, stat_etag


class RangeError(ValueError):
    """Requested range cannot be satisfied (HTTP 416)."""


def parse_range(header: str | None, size: int) -> tuple[int, int] | None:
    """RFC 7233 single byte-range → (offset, length), or None to serve
    the full body (no/foreign/multi range — a multi-range response
    would need multipart/byteranges framing; serving 200 instead is
    spec-legal). Raises :class:`RangeError` when the range is
    syntactically valid but unsatisfiable against `size`."""
    if not header:
        return None
    unit, _, spec = header.partition("=")
    if unit.strip().lower() != "bytes" or "," in spec:
        return None
    start_s, dash, end_s = spec.strip().partition("-")
    if not dash:
        return None
    start_s, end_s = start_s.strip(), end_s.strip()
    try:
        if not start_s:
            # suffix form: last N bytes
            n = int(end_s)
            if n <= 0 or size == 0:
                raise RangeError(header)
            n = min(n, size)
            return size - n, n
        start = int(start_s)
        if start >= size:
            raise RangeError(header)
        end = size - 1 if not end_s else min(int(end_s), size - 1)
        if end < start:
            raise RangeError(header)
        return start, end - start + 1
    except ValueError as exc:
        if isinstance(exc, RangeError):
            raise
        return None


@dataclasses.dataclass
class ServePlan:
    """Resolved response for one file request. `body` set = send those
    bytes (cache hit / empty 304/416); `body` None = stream
    `length` bytes from the file starting at `offset`."""

    status: int
    headers: dict[str, str]
    size: int                       # full representation size
    body: bytes | None = None
    offset: int = 0
    length: int = 0


def _etag_matches(header: str, etag: str) -> bool:
    if header.strip() == "*":
        return True
    # weak-compare per RFC 7232 §3.2: If-None-Match uses weak
    # comparison, so W/ prefixes are stripped on both sides
    candidates = [c.strip() for c in header.split(",")]
    strip = lambda t: t[2:] if t.startswith("W/") else t    # noqa: E731
    return strip(etag) in (strip(c) for c in candidates)


def plan_file(path: str, *, method: str = "GET",
              req_headers=None, headers: dict[str, str] | None = None,
              cache: HotSegmentCache | None = None,
              stats: "OriginStats | None" = None) -> ServePlan:
    """Plan the response for `path`. `headers` are the route's extra
    response headers (Cache-Control); `req_headers` is any mapping with
    .get (the live http.client headers object or a plain dict). Pass
    `cache` only for content-immutable resources (segments / init
    boxes) — playlists must come through with cache=None so every
    request re-reads the rewritten file. Raises OSError when the file
    is unreadable (the API maps that to 404)."""
    req_headers = req_headers or {}
    st = os.stat(path)
    size = st.st_size
    entry = None
    if cache is not None:
        entry = cache.get((path, st.st_mtime_ns, size), path, size)
    etag = entry.etag if entry is not None \
        else stat_etag(st.st_mtime_ns, size)
    out = dict(headers or {})
    out["ETag"] = etag
    out["Accept-Ranges"] = "bytes"
    if stats is not None:
        stats.bump("origin_requests")

    inm = req_headers.get("If-None-Match")
    if inm and _etag_matches(inm, etag):
        if stats is not None:
            stats.bump("origin_304s")
        return ServePlan(status=304, headers=out, size=size, body=b"")

    try:
        rng = parse_range(req_headers.get("Range"), size)
    except RangeError:
        out["Content-Range"] = f"bytes */{size}"
        return ServePlan(status=416, headers=out, size=size, body=b"")

    status, offset, length = 200, 0, size
    if rng is not None:
        offset, length = rng
        status = 206
        out["Content-Range"] = \
            f"bytes {offset}-{offset + length - 1}/{size}"
    if stats is not None and method != "HEAD":
        stats.bump("origin_bytes", length)
    body = entry.data[offset:offset + length] if entry is not None \
        else None
    return ServePlan(status=status, headers=out, size=size, body=body,
                     offset=offset, length=length)


class OriginStats:
    """Monotonic origin counters (stage_ms-style, exported through
    /metrics_snapshot)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts = {"origin_requests": 0, "origin_bytes": 0,
                        "origin_304s": 0, "origin_503s": 0}

    def bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n
        metric = obs_metrics.ORIGIN_COUNTERS.get(key)
        if metric is not None:
            metric.inc(n)

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._counts)


class SessionGauge:
    """Concurrent player sessions per job: a session is any distinct
    (job, session-key) with activity inside the sliding window. The
    key is the client's `X-Tvt-Session` header when it sends one (the
    loadgen does), else its socket address."""

    def __init__(self, window_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.window_s = window_s
        self._clock = clock
        self._lock = threading.Lock()
        self._seen: dict[str, dict[str, float]] = {}

    def record(self, job_id: str, session_key: str) -> None:
        now = self._clock()
        with self._lock:
            sessions = self._seen.setdefault(job_id, {})
            sessions[session_key] = now
            # amortized prune keeps an abandoned job's map bounded
            if len(sessions) % 512 == 0:
                self._prune_locked(now)

    def _prune_locked(self, now: float) -> None:
        horizon = now - self.window_s
        for job_id in list(self._seen):
            sessions = self._seen[job_id]
            for key in [k for k, t in sessions.items() if t < horizon]:
                del sessions[key]
            if not sessions:
                del self._seen[job_id]

    def concurrent(self) -> dict[str, int]:
        with self._lock:
            self._prune_locked(self._clock())
            return {job: len(s) for job, s in self._seen.items()}


class ReloadGate:
    """Per-job cap on concurrent LL-HLS blocking-reload waiters.

    Each blocked reload pins one server thread for up to the hold
    budget; unbounded, a few hundred players on a dead stream exhaust
    the process. `try_enter` refuses past the cap (`limit_fn`, the
    live `origin_max_waiters` setting) and the API answers 503 +
    Retry-After — a spec-legal signal players back off on."""

    def __init__(self, limit_fn: Callable[[], int]) -> None:
        self._limit_fn = limit_fn
        self._lock = threading.Lock()
        self._waiters: dict[str, int] = {}

    def try_enter(self, job_id: str) -> bool:
        limit = max(1, int(self._limit_fn()))
        with self._lock:
            n = self._waiters.get(job_id, 0)
            if n >= limit:
                return False
            self._waiters[job_id] = n + 1
            return True

    def leave(self, job_id: str) -> None:
        with self._lock:
            n = self._waiters.get(job_id, 0) - 1
            if n <= 0:
                self._waiters.pop(job_id, None)
            else:
                self._waiters[job_id] = n

    def waiters(self) -> dict[str, int]:
        with self._lock:
            return dict(self._waiters)

    def total(self) -> int:
        with self._lock:
            return sum(self._waiters.values())


class _Watch:
    """Shared state for one watched playlist path."""

    __slots__ = ("cond", "state", "waiters", "closed")

    def __init__(self) -> None:
        self.cond = threading.Condition()
        self.state: dict | None = None
        self.waiters = 0
        self.closed = False


class PlaylistEdgeWatcher:
    """One disk poller per watched playlist, shared by every waiter.

    The old blocking-reload loop re-opened and re-parsed the playlist
    every 20 ms **per request**; with hundreds of players blocked on
    the same live edge that is hundreds of redundant reads per tick.
    Here the first waiter spawns a poller thread for the path, later
    waiters ride the same parsed state via a condition variable, and
    the poller exits when the last waiter leaves."""

    POLL_S = 0.02

    def __init__(self, parse: Callable[[str], dict] | None = None) -> None:
        if parse is None:
            from ..abr.hls import live_playlist_state as parse
        self._parse = parse
        self._lock = threading.Lock()
        self._watches: dict[str, _Watch] = {}

    @staticmethod
    def satisfied(st: dict | None, want_msn: int,
                  want_part: int | None) -> bool:
        """The RFC 8216bis §6.2.5.2 release condition: the edge reached
        (msn, part), or the stream ended."""
        if st is None:
            return False
        if st["ended"] or want_msn < st["next_msn"]:
            return True
        return (want_part is not None and want_msn == st["next_msn"]
                and want_part < st["next_part"])

    def _read_state(self, path: str) -> dict | None:
        try:
            with open(path, encoding="utf-8") as fp:
                return self._parse(fp.read())
        except (OSError, ValueError):
            return None

    def _enter(self, path: str) -> _Watch:
        with self._lock:
            watch = self._watches.get(path)
            spawn = watch is None
            if spawn:
                watch = self._watches[path] = _Watch()
            watch.waiters += 1
        if spawn:
            threading.Thread(target=self._poll_loop, args=(path, watch),
                             daemon=True, name="tvt-edge-watch").start()
        return watch

    def _leave(self, path: str, watch: _Watch) -> None:
        with self._lock:
            watch.waiters -= 1

    def _poll_done(self, path: str, watch: _Watch) -> bool:
        """Atomically retire the watch when its last waiter left (the
        check and the removal must be one step, or a waiter arriving
        in between would hold a watch nobody polls)."""
        with self._lock:
            if watch.waiters <= 0:
                self._watches.pop(path, None)
                watch.closed = True
                return True
            return False

    def _poll_loop(self, path: str, watch: _Watch) -> None:
        while True:
            st = self._read_state(path)
            with watch.cond:
                watch.state = st
                watch.cond.notify_all()
            if self._poll_done(path, watch):
                return
            time.sleep(self.POLL_S)

    def wait_edge(self, path: str, want_msn: int, want_part: int | None,
                  timeout_s: float) -> bool:
        """Block until the playlist at `path` satisfies (msn, part),
        the stream ends, or `timeout_s` expires. Returns whether the
        release condition was met (timeout → False)."""
        # fast path: already satisfied — no watch, no poller
        if self.satisfied(self._read_state(path), want_msn, want_part):
            return True
        deadline = time.monotonic() + timeout_s
        watch = self._enter(path)
        try:
            with watch.cond:
                while True:
                    if self.satisfied(watch.state, want_msn, want_part):
                        return True
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or watch.closed:
                        return False
                    watch.cond.wait(min(remaining, 0.25))
        finally:
            self._leave(path, watch)


class Origin:
    """The API server's origin bundle: hot-segment cache, request
    counters, per-job session gauges, and the bounded blocking-reload
    machinery — one instance per :class:`~..api.server.ApiServer`,
    reading its knobs live from the coordinator's settings."""

    def __init__(self, settings_fn) -> None:
        self._settings_fn = settings_fn
        self.cache = HotSegmentCache(
            lambda: int(settings_fn().get("origin_cache_bytes", 0) or 0))
        self.stats = OriginStats()
        self.sessions = SessionGauge()
        self.gate = ReloadGate(
            lambda: int(settings_fn().get("origin_max_waiters", 64) or 64))
        self.watcher = PlaylistEdgeWatcher()

    def snapshot(self) -> dict:
        out = self.stats.snapshot()
        out.update(self.cache.snapshot())
        out["blocked_reload_waiters"] = self.gate.total()
        out["sessions"] = self.sessions.concurrent()
        return out
