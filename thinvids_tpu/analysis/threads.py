"""Pass 3 — thread-safety audit.

The farm spans five concurrency domains (staging threads, per-encoder
pack/fetch pools, spawn-context pack sidecars, per-shard worker
daemons, and the lease/packager/HTTP machinery); this pass inventories
the thread entrypoints and flags the shared mutable state they can
race on:

TVT-T001  an instance attribute written WITHOUT a lock from code
          reachable by two distinct thread entrypoints of its class,
          or by one entrypoint that runs concurrently with itself
          (pool-submitted work).
TVT-T002  a blocking call (sleep, subprocess, urlopen, ...) made while
          a lock is held — lock convoys on the claim/heartbeat paths.
TVT-T003  inconsistent lock acquisition order (a cycle in the
          "holding A, acquire B" graph) WITHIN one class. Locks are
          keyed per (module, class); nesting propagates one level
          through same-class ``self.X()`` calls.
TVT-T004  guarded-by violations, two tiers: (a) inferred — a field
          written under two DIFFERENT locks from multi-threaded code
          (empty lockset intersection: each writer believes a
          different lock protects the field, so no lock does); (b)
          declared — the manifest's `guarded_by` names the lock that
          protects a field, and EVERY read/write site outside
          ``__init__`` must hold it (lexically, or via the *_locked
          caller-holds convention).
TVT-T005  CROSS-object lock-order cycles: alias-aware one-level call
          propagation — ``self.board.claim()`` under a held lock
          contributes an edge from the holder's lock to every lock
          `claim` acquires, with `self.board`'s class resolved from
          ``__init__`` construction sites and parameter annotations.
          (PR 7 documented this as beyond lexical analysis; the alias
          map makes the one-level case visible.)

Entrypoint discovery is AST-based: ``threading.Thread(target=f)``
targets, ``pool.submit(f, ...)`` callables (concurrent — many
instances may run at once), plus the manifest's declared entrypoints
for what the AST cannot see (generators handed to a staging thread).
All public methods of a class form ONE additional "api" entrypoint —
external callers are assumed single-threaded unless the manifest says
otherwise, which keeps the pass quiet on driver-style classes.

Honest limits, by design: reads are not flagged (a torn read is real
but drowning the report in read findings would get the pass deleted);
attributes of per-request HTTP handler classes are instance-local and
skipped; lock detection is lexical (``with self._lock:`` blocks and
the ``*_locked`` caller-holds-the-lock naming convention).
"""

from __future__ import annotations

import ast
import dataclasses
import re

from .astutil import (Finding, SourceTree, dotted_name, finding,
                      terminal_name)
from .manifest import Manifest


# ---------------------------------------------------------------------------
# entrypoint discovery
# ---------------------------------------------------------------------------


def _walk_with_class(tree: ast.Module):
    """(enclosing class name | None, node) for every node — nested
    functions keep their class context (a closure handed to a thread
    still runs against that class's `self`)."""

    def rec(node, cls):
        for child in ast.iter_child_nodes(node):
            child_cls = child.name if isinstance(child, ast.ClassDef) \
                else cls
            yield child_cls, child
            yield from rec(child, child_cls)

    yield from rec(tree, None)


def discover_entry_names(tree: SourceTree
                         ) -> tuple[dict[tuple[str, str, str], str],
                                    dict[str, str]]:
    """Thread-target discovery → (qualified, bare) maps to kind
    ("thread" for Thread targets, "concurrent" for executor
    submissions). A ``self.X`` target is QUALIFIED to its lexically
    enclosing (module, class) so `Thread(target=self.run)` in one
    class doesn't brand every `run` method in the package an
    entrypoint (false TVT-T001s on single-threaded classes); targets
    on other receivers fall back to the bare-name map."""
    qualified: dict[tuple[str, str, str], str] = {}
    bare: dict[str, str] = {}

    def record(expr: ast.AST, kind: str, mod: str,
               cls: str | None) -> None:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and cls:
            key = (mod, cls, expr.attr)
            if qualified.get(key) != "concurrent":
                qualified[key] = kind
            return
        name = terminal_name(expr)
        if name and bare.get(name) != "concurrent":
            bare[name] = kind

    for mod in tree.modules():
        for cls, node in _walk_with_class(tree.tree(mod)):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func) or ""
            if callee.split(".")[-1] == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        record(kw.value, "thread", mod, cls)
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "submit" and node.args:
                record(node.args[0], "concurrent", mod, cls)
    return qualified, bare


# ---------------------------------------------------------------------------
# per-class model
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Write:
    attr: str
    method: str
    line: int
    locked: bool
    #: lexically-held lock attrs at the write ((-assumed-) marks the
    #: *_locked caller-holds convention)
    lockset: tuple[str, ...] = ()


@dataclasses.dataclass
class _MethodInfo:
    name: str
    calls: set[str]                  # self.X() targets
    writes: list[_Write]
    #: self.X() calls made while a lock is held: (target, line,
    #: locks held AT the call site)
    locked_calls: list[tuple[str, int, tuple[str, ...]]]
    #: blocking calls anywhere in the body: (display name, line)
    blocking_sites: list[tuple[str, int]]
    #: blocking calls made while a lock is held: (display name, line)
    locked_blocking: list[tuple[str, int]]
    #: lock attrs acquired, with the locks held at acquisition time:
    #: (attr, held-before tuple, line)
    acquisitions: list[tuple[str, tuple[str, ...], int]]
    #: attribute READS of self: (attr, line, lockset, assumed)
    reads: list[tuple[str, int, tuple[str, ...], bool]] = \
        dataclasses.field(default_factory=list)
    #: calls THROUGH an attribute chain: (chain attrs incl. final
    #: method, line, held locks at the call)
    alias_calls: list[tuple[tuple[str, ...], int, tuple[str, ...]]] = \
        dataclasses.field(default_factory=list)
    #: caller-holds-the-lock convention (*_locked name)
    assumed: bool = False


class _MethodVisitor(ast.NodeVisitor):
    """One method's writes / calls / lock usage, tracking the lexical
    ``with``-lock stack (nested function defs inside the method are
    walked too: closures run on the same thread family)."""

    def __init__(self, lock_re: re.Pattern, blocking: tuple[str, ...],
                 assume_locked: bool) -> None:
        self.lock_re = lock_re
        self.blocking = set(blocking)
        self.stack: list[str] = []           # held lock attr names
        self.assume_locked = assume_locked   # *_locked convention
        self.calls: set[str] = set()
        self.writes: list[tuple[str, int, bool, tuple[str, ...]]] = []
        self.locked_calls: list[tuple[str, int,
                                      tuple[str, ...]]] = []
        self.blocking_sites: list[tuple[str, int]] = []
        self.locked_blocking: list[tuple[str, int]] = []
        self.acquisitions: list[tuple[str, tuple[str, ...], int]] = []
        self.reads: list[tuple[str, int, tuple[str, ...], bool]] = []
        self.alias_calls: list[tuple[tuple[str, ...], int,
                                     tuple[str, ...]]] = []
        #: local var → self-attribute chain (`reg = self.co.registry`)
        self._local_alias: dict[str, tuple[str, ...]] = {}

    def _locked(self) -> bool:
        return self.assume_locked or bool(self.stack)

    def _lock_attr(self, expr: ast.AST) -> str | None:
        name = dotted_name(expr)
        if name and self.lock_re.search(name.split(".")[-1]):
            return name.split(".")[-1]
        return None

    def visit_With(self, node: ast.With) -> None:
        acquired: list[str] = []
        for item in node.items:
            expr = item.context_expr
            callee = expr.func if isinstance(expr, ast.Call) else expr
            attr = self._lock_attr(callee)
            if attr is not None:
                self.acquisitions.append(
                    (attr, tuple(self.stack), node.lineno))
                self.stack.append(attr)
                acquired.append(attr)
            else:
                # a non-lock context manager's construction runs under
                # whatever locks earlier items already acquired — e.g.
                # `with self._lock, subprocess.Popen(...) as p:` blocks
                # inside the critical section
                self.visit(expr)
            if item.optional_vars is not None:
                targets = item.optional_vars
                for el in (targets.elts
                           if isinstance(targets, (ast.Tuple, ast.List))
                           else [targets]):
                    self._record_write(el, node.lineno)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.stack.pop()

    def _record_write(self, target: ast.AST, line: int) -> None:
        # self.attr = ... / self.attr[...] = ... / self.attr += ...
        node = target
        if isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            self.writes.append((node.attr, line, self._locked(),
                                tuple(self.stack)))

    def _self_chain(self, node: ast.AST) -> tuple[str, ...] | None:
        """("a", "b") for a pure `self.a.b` attribute chain."""
        name = dotted_name(node)
        if name and name.startswith("self.") and "(" not in name:
            return tuple(name.split(".")[1:])
        return None

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            for el in (tgt.elts if isinstance(tgt, (ast.Tuple, ast.List))
                       else [tgt]):
                self._record_write(el, node.lineno)
        # local aliases of self-attribute chains feed the cross-object
        # lock-order pass (`reg = self.co.registry; reg.lock_stuff()`)
        if len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            chain = self._self_chain(node.value)
            if chain:
                self._local_alias[node.targets[0].id] = chain
            else:
                self._local_alias.pop(node.targets[0].id, None)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_write(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_write(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func)
        term = terminal_name(node.func)
        if name and name.startswith("self.") and name.count(".") == 1:
            self.calls.add(term or "")
            if self._locked():
                self.locked_calls.append((term or "", node.lineno,
                                          tuple(self.stack)))
        elif name and name.startswith("self.") and name.count(".") >= 2:
            self.alias_calls.append(
                (tuple(name.split(".")[1:]), node.lineno,
                 tuple(self.stack)))
        elif name and "." in name and \
                name.split(".")[0] in self._local_alias:
            parts = name.split(".")
            self.alias_calls.append(
                (self._local_alias[parts[0]] + tuple(parts[1:]),
                 node.lineno, tuple(self.stack)))
        if name and (name in self.blocking or term in self.blocking):
            self.blocking_sites.append((name, node.lineno))
            if self._locked():
                self.locked_blocking.append((name, node.lineno))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.ctx, ast.Load) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            self.reads.append((node.attr, node.lineno,
                               tuple(self.stack), self.assume_locked))
        self.generic_visit(node)


def _class_methods(cls: ast.ClassDef):
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _analyze_method(fn, lock_re, blocking) -> _MethodInfo:
    assumed = fn.name.endswith("_locked")
    v = _MethodVisitor(lock_re, blocking, assume_locked=assumed)
    for stmt in fn.body:
        v.visit(stmt)
    return _MethodInfo(
        name=fn.name, calls=v.calls,
        writes=[_Write(a, fn.name, ln, lk, ls)
                for a, ln, lk, ls in v.writes],
        locked_calls=v.locked_calls, blocking_sites=v.blocking_sites,
        locked_blocking=v.locked_blocking, acquisitions=v.acquisitions,
        reads=v.reads, alias_calls=v.alias_calls, assumed=assumed)


def _reachable(methods: dict[str, _MethodInfo], roots: set[str]
               ) -> set[str]:
    seen: set[str] = set()
    frontier = [r for r in roots if r in methods]
    while frontier:
        cur = frontier.pop()
        if cur in seen:
            continue
        seen.add(cur)
        frontier.extend(c for c in methods[cur].calls
                        if c in methods and c not in seen)
    return seen


def _skip_class(cls: ast.ClassDef, manifest: Manifest) -> bool:
    for base in cls.bases:
        name = terminal_name(base)
        if name in manifest.per_request_bases:
            return True
    return False


def _annotation_classes(node: ast.AST) -> list[str]:
    """Candidate class names inside an annotation expression
    (``WorkerRegistry | None``, ``"Coordinator"``, ``Optional[X]``)."""
    names: list[str] = []
    if node is None:
        return names
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            names.append(sub.id)
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            names.extend(p for p in re.split(r"[^\w.]+", sub.value) if p)
    return names


def _build_attr_types(class_info: dict) -> dict:
    """(class key, attr) → class key of objects assigned to
    ``self.attr`` in __init__ — direct construction
    (``self.x = Foo(...)``), annotated parameters (``def __init__(self,
    x: Foo | None)`` + ``self.x = x``), and if-expressions over both.
    Class keys are (mod, name, lineno) so same-named classes stay
    distinct; ambiguous simple names resolve to nothing."""
    index: dict[str, tuple | None] = {}
    for key in class_info:
        cls_name = key[1]
        if cls_name in index:
            index[cls_name] = None          # ambiguous
        else:
            index[cls_name] = key

    def resolve_name(name: str | None):
        if not name:
            return None
        return index.get(name.split(".")[-1])

    out: dict = {}
    for key, info in class_info.items():
        init = info["init"]
        if init is None:
            continue
        params: dict[str, tuple] = {}
        for arg in list(init.args.args) + list(init.args.kwonlyargs):
            for cand in _annotation_classes(arg.annotation):
                hit = resolve_name(cand)
                if hit is not None:
                    params[arg.arg] = hit
                    break

        def resolve_expr(expr):
            if isinstance(expr, ast.Call):
                return resolve_name(dotted_name(expr.func))
            if isinstance(expr, ast.Name):
                return params.get(expr.id)
            if isinstance(expr, ast.IfExp):
                return resolve_expr(expr.body) or resolve_expr(expr.orelse)
            if isinstance(expr, ast.BoolOp):
                for v in expr.values:
                    hit = resolve_expr(v)
                    if hit is not None:
                        return hit
            return None

        for stmt in ast.walk(init):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                tgt = stmt.targets[0]
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    hit = resolve_expr(stmt.value)
                    if hit is not None:
                        out[key + (tgt.attr,)] = hit
    return out


def run(tree: SourceTree, manifest: Manifest) -> list[Finding]:
    lock_re = re.compile(manifest.lock_attr_pattern)
    qualified_entries, bare_entries = discover_entry_names(tree)
    declared: dict[tuple[str, str, str], str] = {}
    for spec, kind in manifest.thread_entrypoints.items():
        mod, _, qual = spec.partition(":")
        cls_name, _, meth = qual.partition(".")
        declared[(mod, cls_name, meth)] = kind
    guarded: dict[tuple[str, str, str], str] = {}
    for spec, lock in manifest.guarded_by.items():
        mod, _, qual = spec.partition(":")
        cls_name, _, attr = qual.partition(".")
        guarded[(mod, cls_name, attr)] = lock

    # -- phase 1: per-class inventory (methods parsed once) ------------
    class_info: dict[tuple[str, str], dict] = {}
    for mod in tree.modules():
        for cls in [n for n in ast.walk(tree.tree(mod))
                    if isinstance(n, ast.ClassDef)]:
            if _skip_class(cls, manifest):
                continue
            methods = {fn.name: _analyze_method(fn, lock_re,
                                                manifest.blocking_calls)
                       for fn in _class_methods(cls)}
            if not methods:
                continue
            init = next((fn for fn in _class_methods(cls)
                         if fn.name == "__init__"), None)
            # keyed by (mod, name, lineno): a second same-named
            # class in one module (nested/factory-local) must not
            # shadow the first out of the audit
            class_info[(mod, cls.name, cls.lineno)] = {
                "node": cls, "methods": methods, "init": init}
    attr_types = _build_attr_types(class_info)

    findings: list[Finding] = []
    lock_edges: dict[tuple[str, str], tuple[str, int]] = {}

    def resolve_chain(ckey, chain):
        """Follow `self.a.b.method()` through the attr-type map;
        returns (tmod, tcls, method_info) or None."""
        cur = ckey
        for attr in chain[:-1]:
            cur = attr_types.get(cur + (attr,))
            if cur is None:
                return None
        target = class_info.get(cur)
        if target is None:
            return None
        info = target["methods"].get(chain[-1])
        if info is None:
            return None
        return cur[0], cur[1], info

    # -- phase 2: per-class findings -----------------------------------
    for ckey, entry_data in class_info.items():
        mod = ckey[0]
        cls = entry_data["node"]
        methods = entry_data["methods"]

        # entrypoints: discovered thread targets + declared ones;
        # everything else public folds into one "api" entry
        entries: dict[str, tuple[set[str], str]] = {}
        for name in methods:
            kind = declared.get((mod, cls.name, name)) or \
                qualified_entries.get((mod, cls.name, name)) or \
                bare_entries.get(name)
            if kind and name != "__init__":
                entries[name] = ({name}, kind)
        api_roots = {name for name in methods
                     if name not in entries and name != "__init__"
                     and (not name.startswith("_")
                          or name == "__call__")}
        if api_roots:
            entries["api"] = (api_roots, "single")

        owns_lock = any(
            lock_re.search(w.attr)
            for info in methods.values() for w in info.writes)
        concurrent_entries = {e for e, (_r, k) in entries.items()
                              if k == "concurrent"}
        multi_threaded = len(entries) > 1 or concurrent_entries

        writes_by_attr: dict[str, list[_Write]] = {}
        for info in methods.values():
            if info.name == "__init__":
                continue
            for w in info.writes:
                writes_by_attr.setdefault(w.attr, []).append(w)

        # -- TVT-T001: unlocked cross-thread writes ----------------
        if multi_threaded:
            reach = {e: _reachable(methods, roots)
                     for e, (roots, _k) in entries.items()}
            for attr, writes in sorted(writes_by_attr.items()):
                unlocked = [w for w in writes if not w.locked]
                if not unlocked:
                    continue
                touched = {e for e in entries
                           for w in writes if w.method in reach[e]}
                racy = len(touched) > 1 or \
                    (touched & concurrent_entries)
                if not racy:
                    continue
                w0 = unlocked[0]
                findings.append(finding(
                    "TVT-T001", mod, w0.line,
                    f"{cls.name}.{attr} written without a lock in "
                    f"{w0.method}() but shared across entrypoints "
                    f"{sorted(touched)}",
                    key_detail=f"{mod}:{cls.name}.{attr}"))

        # -- TVT-T004a: writes guarded by DIFFERENT locks ----------
        if multi_threaded:
            for attr, writes in sorted(writes_by_attr.items()):
                if lock_re.search(attr):
                    continue
                real = [frozenset(w.lockset) for w in writes
                        if w.lockset and not methods[w.method].assumed]
                if len(real) < 2 or len(set(real)) < 2:
                    continue
                if not frozenset.intersection(*real):
                    locks = sorted({", ".join(sorted(s)) for s in real})
                    # anchor on a write that is part of the evidence
                    # (assumed *_locked sites were excluded from it)
                    w0 = min((w for w in writes if w.lockset
                              and not methods[w.method].assumed),
                             key=lambda w: w.line)
                    findings.append(finding(
                        "TVT-T004", mod, w0.line,
                        f"{cls.name}.{attr} is written under "
                        f"DIFFERENT locks ({'; '.join(locks)}) — the "
                        f"lockset intersection is empty, so no single "
                        f"lock protects the field",
                        key_detail=f"{mod}:{cls.name}.{attr}:split"))

        # -- TVT-T004b: declared guarded-by enforcement ------------
        for (gmod, gcls, gattr), lock in sorted(guarded.items()):
            if (gmod, gcls) != (mod, cls.name):
                continue
            seen_sites: set[str] = set()
            for info in methods.values():
                if info.name == "__init__" or info.assumed:
                    continue
                sites = [(w.line, "write", w.lockset)
                         for w in info.writes if w.attr == gattr]
                sites += [(line, "read", lockset)
                          for a, line, lockset, assumed in info.reads
                          if a == gattr and not assumed]
                for line, kindname, lockset in sites:
                    if lock in lockset:
                        continue
                    key = f"{info.name}:{kindname}"
                    if key in seen_sites:
                        continue
                    seen_sites.add(key)
                    findings.append(finding(
                        "TVT-T004", mod, line,
                        f"{cls.name}.{gattr} is declared guarded by "
                        f"`{lock}` but {info.name}() {kindname}s it "
                        f"without holding it (use `with self.{lock}:` "
                        f"or the *_locked convention)",
                        # read and write sites are distinct debts: one
                        # waiver must not silently cover both
                        key_detail=f"{mod}:{cls.name}.{gattr}:"
                                   f"{info.name}:{kindname}"))

        # -- TVT-T002: blocking calls under a lock -----------------
        if owns_lock or multi_threaded:
            for info in methods.values():
                for name, line in info.locked_blocking:
                    findings.append(finding(
                        "TVT-T002", mod, line,
                        f"{cls.name}.{info.name}() calls blocking "
                        f"`{name}` while holding a lock",
                        key_detail=f"{mod}:{cls.name}."
                                   f"{info.name}:{name}"))
                for callee, line, _held in info.locked_calls:
                    target = methods.get(callee)
                    if target and target.blocking_sites:
                        bname, bline = target.blocking_sites[0]
                        findings.append(finding(
                            "TVT-T002", mod, bline,
                            f"{cls.name}.{info.name}() holds a lock "
                            f"across {callee}(), which calls "
                            f"blocking `{bname}`",
                            key_detail=f"{mod}:{cls.name}."
                                       f"{callee}:{bname}"))

        # -- lock-order edges (cycle check runs globally) ----------
        for info in methods.values():
            for attr, held, line in info.acquisitions:
                for h in held:
                    lock_edges.setdefault(
                        (f"{mod}:{cls.name}.{h}",
                         f"{mod}:{cls.name}.{attr}"),
                        (mod, line))
            # one level through same-class calls: holding L at the
            # CALL SITE, call self.X() where X acquires M
            for callee, line, call_held in info.locked_calls:
                target = methods.get(callee)
                if not target:
                    continue
                for attr, _held, aline in target.acquisitions:
                    for h in call_held:
                        lock_edges.setdefault(
                            (f"{mod}:{cls.name}.{h}",
                             f"{mod}:{cls.name}.{attr}"),
                            (mod, aline))

            # cross-OBJECT edges (TVT-T005): `self.a.b.m()` (or via a
            # local alias) while holding a lock → edges from the held
            # locks to every lock `m` acquires on the resolved class.
            # One level of same-class propagation: a locked call to a
            # sibling method carries the locks held AT THAT CALL SITE
            # over the sibling's alias calls (the
            # _worker_eligible_locked shape) — not every lock the
            # caller ever touched, which would fabricate edges that no
            # execution can interleave.
            def _cross_edges(alias_calls, held_hint):
                for chain, _line, held in alias_calls:
                    hold = set(held) or held_hint
                    if not hold:
                        continue
                    resolved = resolve_chain(ckey, chain)
                    if resolved is None:
                        continue
                    tmod, tcls, tinfo = resolved
                    for attr2, _h2, aline2 in tinfo.acquisitions:
                        for h in hold:
                            lock_edges.setdefault(
                                (f"{mod}:{cls.name}.{h}",
                                 f"{tmod}:{tcls}.{attr2}"),
                                (mod, aline2))

            _cross_edges(info.alias_calls, set())
            for callee, _line, call_held in info.locked_calls:
                target = methods.get(callee)
                if target is not None:
                    _cross_edges(target.alias_calls, set(call_held))

    # -- TVT-T003/T005: cycles in the acquisition-order graph ----------
    graph: dict[str, set[str]] = {}
    for (a, b), _site in lock_edges.items():
        if a != b:
            graph.setdefault(a, set()).add(b)
    for cycle in _find_cycles(graph):
        mod = cycle[0].split(":")[0]
        owners = {c.rsplit(".", 1)[0] for c in cycle[:-1]}
        code = "TVT-T005" if len(owners) > 1 else "TVT-T003"
        pretty = " -> ".join(c.split(":", 1)[1] for c in cycle)
        scope = "cross-object " if code == "TVT-T005" else ""
        findings.append(finding(
            code, mod, 0,
            f"inconsistent {scope}lock acquisition order: {pretty}",
            key_detail="->".join(sorted(set(cycle)))))
    return findings


def _find_cycles(graph: dict[str, set[str]]) -> list[list[str]]:
    """Distinct simple cycles (each reported once, rotated to its
    lexicographically-smallest node)."""
    cycles: dict[tuple[str, ...], list[str]] = {}

    def dfs(node: str, path: list[str], on_path: set[str]) -> None:
        for nxt in graph.get(node, ()):
            if nxt in on_path:
                i = path.index(nxt)
                cyc = path[i:] + [nxt]
                body = cyc[:-1]
                k = body.index(min(body))
                canon = tuple(body[k:] + body[:k])
                cycles.setdefault(canon, cyc)
            elif nxt not in path:
                dfs(nxt, path + [nxt], on_path | {nxt})

    for start in sorted(graph):
        dfs(start, [start], {start})
    return list(cycles.values())
