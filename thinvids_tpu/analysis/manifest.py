"""The machine-checked architecture manifest.

This file IS the codebase's correctness contract: which modules must
stay importable without jax, where host-device synchronization is
allowed to live, which thread entrypoints exist beyond what the AST
can discover, which lock guards which field, the control-plane state
machines as explicit transition tables (audited at every write site
AND model-checked — analysis/statemachine.py), the jit/retrace
discipline (where the jit surface lives, which helpers pin shapes,
which hot loops must never block on a transfer), which process-level
env knobs are registered, and the (short) waiver list for findings
that are understood and accepted.

It replaces the per-file grep guards that used to live inside
tests/test_compact.py (device_get allowlist), tests/test_streaming.py
(read_video ban), tests/test_abr.py and tests/test_live.py (jax-free
imports): those tests now assert against THIS manifest, and
``cli.py check`` enforces it over the whole tree in tier-1.

Editing rules:

- adding a module to `JAX_FREE` is free; removing one is an
  architecture change and will fail the subsystem's own tests
  (tests/test_abr.py, tests/test_live.py, ...) until they agree;
- every waiver needs a one-line reason and should name a stable
  finding key (no line numbers) — stale waivers are reported by the
  checker so the list cannot silently rot.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping


@dataclasses.dataclass(frozen=True)
class StateMachine:
    """One declared control-plane state machine.

    `attr` names the instance attribute whose enum writes the TVT-M001
    audit checks inside `scope` (every ``x.<attr> = <Enum>.<MEMBER>``
    write site must carry a local guard proving its source states, and
    every implied source→target edge must be in `transitions`). An
    empty `attr` declares a machine that is model-checked only (the
    QoS gate keeps its state implicitly)."""

    name: str
    enum: str                       # enum simple name ("ShardState")
    attr: str                       # audited instance attribute, "" = none
    scope: tuple[str, ...]          # module prefixes the audit scans
    states: tuple[str, ...]
    initial: tuple[str, ...]        # legal construction-time states
    transitions: tuple[tuple[str, str], ...]
    #: boolean predicate properties on the enum → the states they admit
    #: (``shard.state.is_open`` narrows to PENDING|ASSIGNED)
    predicates: Mapping[str, tuple[str, ...]] = dataclasses.field(
        default_factory=dict)


#: the ShardBoard lease machine (cluster/remote.py). PENDING→DONE is
#: real: a late part from an expired-then-requeued lease is accepted
#: while the shard is open (first result wins, deterministic encode).
#: DONE and FAILED absorb. The TVT-M002 explorer must exercise EXACTLY
#: these edges — a stale table fails the check in either direction.
SHARD_MACHINE = StateMachine(
    name="shard",
    enum="ShardState",
    attr="state",
    scope=("thinvids_tpu.cluster",),
    states=("PENDING", "ASSIGNED", "DONE", "FAILED"),
    initial=("PENDING",),
    transitions=(
        ("PENDING", "ASSIGNED"),    # claim (lease)
        ("ASSIGNED", "DONE"),       # submit_part
        ("PENDING", "DONE"),        # late part after requeue (open wins)
        ("ASSIGNED", "PENDING"),    # failure/expiry requeue, preemption
        ("ASSIGNED", "FAILED"),     # attempt budget exhausted
        # band-group restart (farm SFE, ISSUE 14): band shards encode
        # in LOCKSTEP, so when one falls back to PENDING its DONE
        # siblings requeue too — their spooled parts are RETRACTED
        # first (drop_done), so first-result-wins and resume-reuse
        # stay intact (the re-encode deterministically re-submits the
        # same bytes)
        ("DONE", "PENDING"),
    ),
    predicates={"is_open": ("PENDING", "ASSIGNED")},
)

#: the Job status machine (cluster/jobs.py + coordinator.py). READY is
#: the registration state; WAITING↔ the queue; STARTING/RUNNING/
#: STAMPING are the active set; STOPPED/FAILED/DONE re-queue through
#: queue_job or wipe through restart_job; REJECTED absorbs (re-running
#: an admission-rejected job must go back through policy, so neither
#: queue nor restart may resurrect it).
JOB_MACHINE = StateMachine(
    name="job",
    enum="Status",
    attr="status",
    scope=("thinvids_tpu.cluster",),
    states=("READY", "WAITING", "STARTING", "RUNNING", "STAMPING",
            "STOPPED", "FAILED", "REJECTED", "DONE"),
    initial=("READY",),
    transitions=(
        ("READY", "REJECTED"),      # admission policy at registration
        # queue_job: (re-)queue from any non-active, non-rejected state
        ("READY", "WAITING"), ("WAITING", "WAITING"),
        ("STOPPED", "WAITING"), ("FAILED", "WAITING"),
        ("DONE", "WAITING"),
        ("WAITING", "STARTING"),    # scheduler reserve (run token mint)
        # mark_running is idempotent within a run
        ("STARTING", "RUNNING"), ("RUNNING", "RUNNING"),
        # completion / failure only from the active set
        ("STARTING", "DONE"), ("RUNNING", "DONE"), ("STAMPING", "DONE"),
        ("STARTING", "FAILED"), ("RUNNING", "FAILED"),
        ("STAMPING", "FAILED"),
        # operator stop: non-terminal states only (terminal absorbs)
        ("READY", "STOPPED"), ("WAITING", "STOPPED"),
        ("STARTING", "STOPPED"), ("RUNNING", "STOPPED"),
        ("STAMPING", "STOPPED"),
        # the api stamp flow (api/server.py _h_stamp_job — OUTSIDE the
        # cluster/ audit scope, declared here so the table stays the
        # whole protocol's spec): any non-active, non-rejected job may
        # enter STAMPING and is restored to its prior status after
        ("READY", "STAMPING"), ("WAITING", "STAMPING"),
        ("STOPPED", "STAMPING"), ("FAILED", "STAMPING"),
        ("DONE", "STAMPING"),
        ("STAMPING", "READY"), ("STAMPING", "WAITING"),
        ("STAMPING", "STOPPED"),
        # restart wipe: everything except REJECTED
        ("READY", "READY"), ("WAITING", "READY"), ("STARTING", "READY"),
        ("RUNNING", "READY"), ("STAMPING", "READY"),
        ("STOPPED", "READY"), ("FAILED", "READY"), ("DONE", "READY"),
    ),
    predicates={
        "is_active": ("STARTING", "RUNNING", "STAMPING"),
        "is_terminal": ("STOPPED", "FAILED", "REJECTED", "DONE"),
    },
)

#: the elastic-farm worker lifecycle (farm/lifecycle.py, driven by
#: farm/controller.py): ACTIVE workers claim; DRAINING workers finish
#: in-flight shards but stop claiming; SUSPENDED workers are powered
#: down; WAKING workers have a wake in flight. Every `lifecycle` write
#: site in farm/ is audited (TVT-M001), and the TVT-M002 explorer's
#: `drain` scenario drives this machine against the shard board:
#: no shard is ever leased to a DRAINING/SUSPENDED worker, and a
#: suspend never fires while the worker still holds a lease.
#: WAKING is a legal construction-time state: a freshly PROVISIONED
#: host's first record is born with its wake already in flight.
WORKER_MACHINE = StateMachine(
    name="worker",
    enum="WorkerState",
    attr="lifecycle",
    scope=("thinvids_tpu.farm",),
    states=("ACTIVE", "DRAINING", "SUSPENDED", "WAKING"),
    initial=("ACTIVE", "WAKING"),
    transitions=(
        ("ACTIVE", "DRAINING"),      # scale-down / crashed-host drain
        ("DRAINING", "ACTIVE"),      # demand returned: cancel the drain
        ("DRAINING", "SUSPENDED"),   # lease set empty: suspend fired
        ("SUSPENDED", "WAKING"),     # scale-up: wake fired
        ("WAKING", "ACTIVE"),        # first heartbeat / first claim
        ("WAKING", "SUSPENDED"),     # wake never landed: retry later
        ("SUSPENDED", "ACTIVE"),     # operator-started host rejoined
    ),
)

#: the QoS batch gate (cluster/qos.py): OPEN admits batch claims,
#: PREEMPTING withholds them. No AST-audited attribute (the controller
#: keeps the state as an Event + breached set); the TVT-M002 board
#: model drives breach/recover and validates against this table.
QOS_GATE_MACHINE = StateMachine(
    name="qos-gate",
    enum="",
    attr="",
    scope=(),
    states=("OPEN", "PREEMPTING"),
    initial=("OPEN",),
    transitions=(
        ("OPEN", "PREEMPTING"),     # live part deadline breach
        ("PREEMPTING", "OPEN"),     # recovery / live job terminal
    ),
)


@dataclasses.dataclass(frozen=True)
class Manifest:
    """Declarative inputs to the analysis passes. Defaults are
    the thinvids_tpu contract; tests build custom instances around
    fixture packages."""

    package: str = "thinvids_tpu"

    # -- pass 1: jax confinement (TVT-J001) ---------------------------
    #: modules (or package prefixes) whose TRANSITIVE module-scope
    #: import closure must never reach `jax_roots`. These run on
    #: jax-free worker/sidecar/control-plane processes where
    #: initializing a device backend is wrong or fatal.
    jax_free: tuple[str, ...] = (
        "thinvids_tpu.abr.hls",
        "thinvids_tpu.abr.ladder",
        "thinvids_tpu.live.packager",
        "thinvids_tpu.parallel.packproc",
        "thinvids_tpu.codecs.h264.layout",
        "thinvids_tpu.io",              # whole package
        "thinvids_tpu.ingest.tail",
        # the origin serving layer and its load harness run on the
        # coordinator's API threads / a client box — never on a mesh
        "thinvids_tpu.origin",          # whole package
        "thinvids_tpu.tools.loadgen",
        "thinvids_tpu.cluster.qos",
        # the durable part spool + board checkpoint runs on coordinator
        # control-plane threads (API handlers, the drain loop) — never
        # on a mesh
        "thinvids_tpu.cluster.partstore",
        # the cross-host halo relay/transport (farm SFE) runs on
        # coordinator API threads and worker control flow; the device
        # math it feeds lives in parallel/sfefarm
        "thinvids_tpu.cluster.halo",
        # the observability layer (metrics registry, trace store,
        # flight recorder) runs on coordinator/worker control-plane
        # threads and inside jax-free sidecars
        "thinvids_tpu.obs",             # whole package
        # the elastic farm (capacity controller, lifecycle, provider
        # seam, tenancy) is pure control plane: it spawns and kills
        # worker PROCESSES but never touches a device itself
        "thinvids_tpu.farm",            # whole package
        # self-hosting: the analyzer itself runs inside tier-1 as a
        # fast jax-free subprocess
        "thinvids_tpu.analysis",
        "thinvids_tpu.tools.check",
    )
    #: forbidden external import roots for `jax_free` modules
    jax_roots: tuple[str, ...] = ("jax",)

    # -- pass 1b: forbidden symbols (TVT-J002) ------------------------
    #: module → (symbol, reason): referencing the symbol ANYWHERE in
    #: the module (import, call, attribute) is a finding. The
    #: read_video rule keeps the blocking whole-clip decode prologue
    #: out of the streaming executors (PR 3's invariant, formerly a
    #: grep in tests/test_streaming.py).
    forbidden_symbols: Mapping[str, tuple[tuple[str, str], ...]] = \
        dataclasses.field(default_factory=lambda: {
            "thinvids_tpu.cluster.executor": (
                ("read_video", "executors stream via ingest.open_video; "
                 "read_video materializes the whole clip"),),
            "thinvids_tpu.cluster.remote": (
                ("read_video", "workers range-decode their shard via "
                 "open_video's lazy slices"),),
        })

    # -- pass 2: host-sync confinement (TVT-S001/S002) ----------------
    #: modules (or prefixes) allowed to call the blocking sync APIs:
    #: the wave dispatcher owns the device→host boundary (tiny count
    #: barriers + dense retry), tools/ is offline utilities, and the
    #: two codec entries are single-frame/single-GOP reference paths
    #: (encode_intra_jax, encoder.encode_gop) that never sit on the
    #: wave hot path. (Formerly tests/test_compact.py's ALLOWED set.)
    sync_allowlist: tuple[str, ...] = (
        "thinvids_tpu.parallel.dispatch",
        # the farm-SFE band executor owns the same device→host
        # boundary as dispatch: per-frame tiny-count barriers, halo
        # edge-row fetches, and the probe/histogram partial reads that
        # MUST leave the device between lockstep exchanges
        "thinvids_tpu.parallel.sfefarm",
        "thinvids_tpu.codecs.h264.jaxcore",
        "thinvids_tpu.codecs.h264.encoder",
        "thinvids_tpu.tools",
    )
    #: attribute names whose CALL is a blocking device sync
    sync_calls: tuple[str, ...] = ("device_get", "block_until_ready")

    # -- pass 3: thread-safety audit (TVT-T001/T002/T003) -------------
    #: entrypoints the AST cannot discover (generators handed to a
    #: staging thread, loops driven by an external daemon), declared as
    #: "module:Class.method" → kind ("thread" = one extra thread,
    #: "concurrent" = many instances may run at once).
    thread_entrypoints: Mapping[str, str] = dataclasses.field(
        default_factory=lambda: {
            # stage_waves generators execute ON the tvt-stage thread
            # (background_stage wraps them); the dispatch loop runs on
            # the caller thread concurrently.
            "thinvids_tpu.parallel.dispatch:GopShardEncoder.stage_waves":
                "thread",
            "thinvids_tpu.parallel.dispatch:"
            "GopShardEncoder.stage_luma_waves": "thread",
            # the SFE encoder's per-GOP staging generator runs on the
            # same tvt-stage thread via background_stage
            "thinvids_tpu.parallel.dispatch:SfeShardEncoder.stage_waves":
                "thread",
        })
    #: classes instantiated per request/connection — their `self` is
    #: never shared across threads, so attribute writes are local
    per_request_bases: tuple[str, ...] = (
        "BaseHTTPRequestHandler", "StreamRequestHandler",
        "BaseRequestHandler",
    )
    #: attribute-name pattern that marks a `with self.<attr>:` block as
    #: lock-protected
    lock_attr_pattern: str = r"lock|cond|mutex"
    #: calls considered blocking when made while a lock is held
    blocking_calls: tuple[str, ...] = (
        "time.sleep", "sleep", "urlopen", "subprocess.run",
        "subprocess.check_call", "subprocess.check_output",
        "subprocess.Popen",
    )

    # -- pass 3b: guarded-by inference (TVT-T004) ---------------------
    #: "module:Class.attr" → lock attribute: the field is part of the
    #: class's lock-protected state, so EVERY read/write site outside
    #: __init__ must hold that lock (lexical `with self.<lock>:` or the
    #: *_locked caller-holds convention). Beyond this declared set the
    #: pass still infers: a field written under two DIFFERENT locks
    #: (empty lockset intersection) from multi-threaded code is a
    #: finding without any declaration.
    guarded_by: Mapping[str, str] = dataclasses.field(
        default_factory=lambda: {
            "thinvids_tpu.cluster.remote:ShardBoard._jobs": "_lock",
            "thinvids_tpu.cluster.remote:ShardBoard._order": "_lock",
            "thinvids_tpu.cluster.remote:ShardBoard._parts": "_lock",
            # claim-affinity scoring map: read+written inside claim's
            # locked section only
            "thinvids_tpu.cluster.remote:ShardBoard._affinity": "_lock",
            # halo relay rendezvous store: API handler threads post,
            # long-polls park on the same condition's lock
            "thinvids_tpu.cluster.halo:HaloRelay._jobs": "_cond",
            "thinvids_tpu.cluster.jobs:JobStore._jobs": "_lock",
            "thinvids_tpu.cluster.partstore:PartStore._journals": "_lock",
            "thinvids_tpu.cluster.partstore:PartStore._spool_bytes":
                "_lock",
            "thinvids_tpu.cluster.coordinator:WorkerRegistry._workers":
                "_lock",
            "thinvids_tpu.cluster.coordinator:Coordinator._active_ids":
                "_sched_lock",
            "thinvids_tpu.cluster.qos:QosController._breached": "_lock",
            "thinvids_tpu.farm.controller:CapacityController._recs":
                "_lock",
        })

    # -- pass 5: protocol state machines (TVT-M001/M002) --------------
    #: declared control-plane machines: transition tables the AST audit
    #: checks write sites against, and the bounded explorer validates
    #: the board model against (see analysis/statemachine.py).
    state_machines: tuple[StateMachine, ...] = (
        SHARD_MACHINE, JOB_MACHINE, QOS_GATE_MACHINE, WORKER_MACHINE)

    # -- pass 6: jit/retrace discipline (TVT-X001/X002) ---------------
    #: modules allowed to DEFINE `jax.jit` entry points — the repo's
    #: whole jit surface lives here, so a stray jit elsewhere (which
    #: would grow its own retrace cache outside the pinned-shape
    #: regime) is a finding.
    jit_modules: tuple[str, ...] = (
        "thinvids_tpu.parallel.dispatch",
        "thinvids_tpu.parallel.rc",
        "thinvids_tpu.abr.scale",
        "thinvids_tpu.codecs.h264.jaxcore",
        "thinvids_tpu.codecs.h264.jaxme",
        "thinvids_tpu.codecs.h264.jaxinter",
    )
    #: helper names whose RESULT is a pinned/quantized shape bound: a
    #: data-dependent slice bound (anything derived from `.max()` /
    #: `.item()` on runtime data) inside a jit module must route
    #: through one of these, or every wave recompiles (the PR 4
    #: quantized-slice rule; `cut` is the used-prefix quantizer in
    #: GopShardEncoder._fetch_payload_rows).
    shape_quantizers: tuple[str, ...] = ("cut",)
    #: wave/frame hot-loop functions ("module:Qual.name"): code that
    #: runs once per dispatched wave or per SFE frame step. Blocking
    #: transfers (`device_put`, `device_get`, `block_until_ready`,
    #: `.item()`) are banned here — staging (stage_waves) and collect
    #: (collect_wave, _fetch_*) are the allowlisted transfer sites and
    #: are deliberately NOT in this set.
    hot_loops: tuple[str, ...] = (
        "thinvids_tpu.parallel.dispatch:GopShardEncoder.dispatch_wave",
        "thinvids_tpu.parallel.dispatch:GopShardEncoder.encode_waves",
        "thinvids_tpu.parallel.dispatch:SfeShardEncoder.dispatch_wave",
        "thinvids_tpu.parallel.dispatch:SfeShardEncoder.encode_waves",
        "thinvids_tpu.parallel.dispatch:SfeShardEncoder._intra_step",
        "thinvids_tpu.parallel.dispatch:SfeShardEncoder._p_step",
    )

    # -- pass 4: config discipline (TVT-C001/C002/C003) ---------------
    #: process-level env knobs that are NOT live settings (read once at
    #: process start, no clamp tier) — registered here so the TVT_*
    #: namespace stays inventoried.
    process_env: Mapping[str, str] = dataclasses.field(
        default_factory=lambda: {
            "TVT_API_PORT": "coordinator HTTP port (cli.py)",
            "TVT_STATE_DIR": "durable journal directory (cli.py)",
            "TVT_WATCH_DIR": "watch-folder ingest root (cli.py)",
            "TVT_OUTPUT_DIR": "encode output root (cli.py)",
            "TVT_COORDINATOR_URL": "agent/worker coordinator URL (cli.py)",
            "TVT_LOG_LEVEL": "root log level (core/log.py)",
            "TVT_LOG_FORMAT": "log line format: json = one structured "
                              "object per line with trace/job ids "
                              "(core/log.py)",
            "TVT_NATIVE_SANITIZE": "asan|ubsan native build mode "
                                   "(native/__init__.py)",
        })
    #: foreign platform envs the package may read/write without being
    #: TVT_-namespaced (jax/XLA knobs, sanitizer runtimes, linkers)
    foreign_env_prefixes: tuple[str, ...] = (
        "XLA_", "JAX_", "LD_", "ASAN_", "UBSAN_", "PYTHON", "PATH",
        "HOME", "TMPDIR",
    )
    #: files whose settings-key mentions do NOT count as readers
    #: (the config module itself defines the keys)
    config_module: str = "thinvids_tpu.core.config"

    # -- waivers ------------------------------------------------------
    #: finding key → one-line reason. Keys are the stable `Finding.key`
    #: (code:detail, no line numbers). Keep this SHORT: a waiver is a
    #: debt record, not an off switch. `cli.py check` reports stale
    #: waivers (matching no current finding) so the list cannot rot.
    waivers: Mapping[str, str] = dataclasses.field(
        default_factory=lambda: dict(_WAIVERS))


#: the repo's current waiver list (kept module-level so tests can
#: assert on its size without building a Manifest)
_WAIVERS: dict[str, str] = {
    # core/log.py reads LOG_LEVEL as a fallback after TVT_LOG_LEVEL:
    # reference-compat (the reference's common.py used LOG_LEVEL) and
    # existing deployments keep working.
    "TVT-C002:LOG_LEVEL": "legacy fallback env for TVT_LOG_LEVEL "
                          "(reference compat)",
}


def default_manifest() -> Manifest:
    return Manifest()
