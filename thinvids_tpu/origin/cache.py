"""In-memory hot-segment cache: bounded bytes, LRU, single-flight fill.

The origin's working set is tiny and hot — the live edge's last few
parts and whatever VOD segments the fronting CDN is currently missing —
so a byte-bounded LRU over whole segment bodies removes the disk from
the common path entirely. The cache is **immutable-aware by contract**:
callers only put content-immutable resources through it (fMP4 segments
and init boxes, which always get a NEW uri when content changes;
playlists rewrite in place every part and must never come through
here). Keys carry the file's identity (path, mtime_ns, size) so a
rewritten tree — a restarted live job re-encoding under the same
names — can never serve stale bytes: changed identity is a different
key, and the old entry ages out of the LRU.

Fills are single-flight: when a fresh live part lands and a thundering
herd of players asks for it at once, exactly one request reads the
disk; the rest wait on its fill event and serve from memory.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Callable

from ..obs import metrics as obs_metrics


def strong_etag(data: bytes) -> str:
    """Strong ETag for an in-memory body (content-addressed, so it is
    stable across processes and restarts for identical bytes)."""
    return '"' + hashlib.sha1(data).hexdigest()[:20] + '"'


def stat_etag(mtime_ns: int, size: int) -> str:
    """Strong-in-practice ETag for a streamed-from-disk body, derived
    from the file's identity the way nginx/apache do: any rewrite
    bumps mtime_ns, and our segment outputs commit via atomic rename."""
    return f'"{mtime_ns:x}-{size:x}"'


class CacheEntry:
    """One cached immutable body."""

    __slots__ = ("data", "etag")

    def __init__(self, data: bytes, etag: str) -> None:
        self.data = data
        self.etag = etag


class HotSegmentCache:
    """Byte-bounded LRU of immutable segment bodies.

    `limit_fn` is read per lookup so the `origin_cache_bytes` setting
    stays live-tunable (0 disables caching entirely). Counters are the
    stage_ms-style monotonic tallies /metrics_snapshot exports.
    """

    def __init__(self, limit_fn: Callable[[], int]) -> None:
        self._limit_fn = limit_fn
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, CacheEntry]" = OrderedDict()
        self._filling: dict[tuple, threading.Event] = {}
        self._bytes = 0
        # counters (read via snapshot(); guarded by _lock)
        self._hits = 0
        self._fills = 0
        self._coalesced = 0
        self._evictions = 0

    @staticmethod
    def _read_file(path: str) -> bytes:
        """Disk read seam — tests count calls to prove single-flight."""
        with open(path, "rb") as fp:
            return fp.read()

    def get(self, key: tuple, path: str, size: int) -> CacheEntry | None:
        """Body + ETag for the immutable file at `path`, filled from
        disk at most once per key no matter how many threads ask.
        Returns None when caching is off or the file alone exceeds the
        whole budget (the caller streams from disk instead). Raises
        OSError if the fill's disk read fails."""
        limit = max(0, int(self._limit_fn()))
        if limit <= 0 or size > limit:
            # live-tuned down (or off): release anything the old,
            # larger budget admitted — eviction otherwise only runs on
            # the fill path, which a limit of 0 never reaches
            if self._entries:
                evicted = 0
                with self._lock:
                    while self._bytes > limit and self._entries:
                        _, old = self._entries.popitem(last=False)
                        self._bytes -= len(old.data)
                        self._evictions += 1
                        evicted += 1
                if evicted:
                    obs_metrics.ORIGIN_COUNTERS[
                        "origin_evictions"].inc(evicted)
            return None
        while True:
            with self._lock:
                ent = self._entries.get(key)
                if ent is not None:
                    self._entries.move_to_end(key)
                    self._hits += 1
                    obs_metrics.ORIGIN_COUNTERS["origin_hits"].inc()
                    return ent
                ev = self._filling.get(key)
                if ev is None:
                    ev = threading.Event()
                    self._filling[key] = ev
                    filling = True
                else:
                    self._coalesced += 1
                    obs_metrics.ORIGIN_COUNTERS[
                        "origin_coalesced_fills"].inc()
                    filling = False
            if not filling:
                # herd member: wait for the filler, then re-check (the
                # loop also covers a failed fill — the event is set and
                # the key vacated, so one waiter becomes the new filler)
                ev.wait(5.0)
                continue
            try:
                data = self._read_file(path)
            except OSError:
                with self._lock:
                    self._filling.pop(key, None)
                ev.set()
                raise
            ent = CacheEntry(data, strong_etag(data))
            evicted = 0
            with self._lock:
                self._filling.pop(key, None)
                self._fills += 1
                if len(data) <= limit:
                    self._entries[key] = ent
                    self._bytes += len(data)
                    while self._bytes > limit and self._entries:
                        _, old = self._entries.popitem(last=False)
                        self._bytes -= len(old.data)
                        self._evictions += 1
                        evicted += 1
            obs_metrics.ORIGIN_COUNTERS["origin_fills"].inc()
            if evicted:
                obs_metrics.ORIGIN_COUNTERS[
                    "origin_evictions"].inc(evicted)
            ev.set()
            return ent

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "origin_cache_bytes_used": self._bytes,
                "origin_cache_entries": len(self._entries),
                "origin_hits": self._hits,
                "origin_fills": self._fills,
                "origin_coalesced_fills": self._coalesced,
                "origin_evictions": self._evictions,
            }
