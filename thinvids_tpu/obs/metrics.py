"""Typed metrics registry with Prometheus text exposition.

The system's instrumentation used to be a grab-bag of hand-rolled
snapshot dicts (`StageProfile.snapshot`, `OriginStats`, the QoS
controller's counters) merged by `/metrics_snapshot`; this module is
the registry they all write through now. Three metric kinds, all
thread-safe and label-aware:

- **Counter** — monotonic totals (``tvt_*_total``); `inc(n)` only.
- **Gauge** — settable point-in-time values; `set(v)` / `inc(n)`.
- **Histogram** — fixed-bucket latency distributions with cumulative
  bucket counts, `_sum` and `_count` — the piece the old snapshot
  model could not express (the NVENC longitudinal study's lesson,
  PAPERS.md arXiv:2605.01187: report distributions and trade-off
  curves, not single points).

``REGISTRY.render()`` emits Prometheus text exposition format 0.0.4
(`# HELP` / `# TYPE` headers, escaped label values, cumulative
``le``-labelled buckets ending at ``+Inf``), served by the API's
``GET /metrics``; tests parse it back with a strict reader.

Metric families are declared once at module scope so the exposition
surface is complete (HELP/TYPE present) even before the first event:
a Prometheus scrape of a fresh coordinator sees the whole schema.

jax-free by contract: imported by control-plane modules (origin/, qos,
the API server) that must never initialize a device backend.
"""

from __future__ import annotations

import math
import threading
from typing import Iterable, Iterator, Mapping


def _escape_label(value: str) -> str:
    """Label-value escaping per the exposition format: backslash,
    double-quote and newline."""
    return (str(value).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _escape_help(text: str) -> str:
    return str(text).replace("\\", r"\\").replace("\n", r"\n")


def _fmt(value: float) -> str:
    """Sample-value rendering: integral floats print as integers (the
    common counter case), +Inf per the format, else repr-precision."""
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


class _Child:
    """One labelled series of a counter/gauge."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def get(self) -> float:
        with self._lock:
            return self._value


class _HistChild:
    """One labelled series of a histogram: fixed upper bounds,
    cumulative counts at render time."""

    __slots__ = ("_lock", "_buckets", "_counts", "_sum", "_count")

    def __init__(self, buckets: tuple[float, ...]) -> None:
        self._lock = threading.Lock()
        self._buckets = buckets
        self._counts = [0] * len(buckets)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._sum += value
            self._count += 1
            # per-bucket tallies; snapshot() cumulates at render time
            for i, ub in enumerate(self._buckets):
                if value <= ub:
                    self._counts[i] += 1
                    break

    def snapshot(self) -> tuple[list[int], float, int]:
        """(cumulative per-bucket counts, sum, count)."""
        with self._lock:
            cum, running = [], 0
            for c in self._counts:
                running += c
                cum.append(running)
            return cum, self._sum, self._count


#: default latency buckets (seconds) — sub-5 ms through 10 s covers
#: everything from a hot-cache segment serve to a struggling live part
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                   2.5, 5.0, 10.0)


class Metric:
    """One metric family (a name + kind + label schema) holding its
    labelled children. Unlabelled metrics proxy inc/set/observe to an
    implicit single child."""

    def __init__(self, name: str, help: str, kind: str,
                 labelnames: tuple[str, ...] = (),
                 buckets: tuple[float, ...] | None = None) -> None:
        self.name = name
        self.help = help
        self.kind = kind                     # counter | gauge | histogram
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(sorted(buckets)) if buckets else \
            (DEFAULT_BUCKETS if kind == "histogram" else ())
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], _Child | _HistChild] = {}
        if not self.labelnames:
            self._children[()] = self._new_child()

    def _new_child(self):
        if self.kind == "histogram":
            return _HistChild(self.buckets)
        return _Child()

    def labels(self, *values, **kw):
        """Child for one label combination; positional values follow
        `labelnames` order, keywords match by name."""
        if kw:
            if values:
                raise ValueError("pass labels positionally OR by name")
            values = tuple(kw[name] for name in self.labelnames)
        key = tuple(str(v) for v in values)
        if len(key) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, "
                f"got {key}")
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._new_child()
            return child

    def _default(self):
        if self.labelnames:
            raise ValueError(f"{self.name} is labelled "
                             f"{self.labelnames}; use .labels(...)")
        return self._children[()]

    # unlabelled conveniences
    def inc(self, n: float = 1.0) -> None:
        self._default().inc(n)

    def set(self, value: float) -> None:
        self._default().set(value)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def get(self, *values, **kw) -> float:
        if self.labelnames:
            return self.labels(*values, **kw).get()
        return self._default().get()

    def clear(self) -> None:
        """Drop every labelled child (scrape-time gauges rebuild their
        current children each scrape so stale series don't linger)."""
        with self._lock:
            self._children.clear()
            if not self.labelnames:
                self._children[()] = self._new_child()

    def _label_str(self, key: tuple[str, ...],
                   extra: tuple[tuple[str, str], ...] = ()) -> str:
        pairs = [(n, v) for n, v in zip(self.labelnames, key)]
        pairs.extend(extra)
        if not pairs:
            return ""
        inner = ",".join(f'{n}="{_escape_label(v)}"' for n, v in pairs)
        return "{" + inner + "}"

    def render(self) -> Iterator[str]:
        yield f"# HELP {self.name} {_escape_help(self.help)}"
        yield f"# TYPE {self.name} {self.kind}"
        with self._lock:
            items = sorted(self._children.items())
        for key, child in items:
            if self.kind == "histogram":
                cum, total, count = child.snapshot()
                for ub, c in zip(self.buckets, cum):
                    labels = self._label_str(key, (("le", _fmt(ub)),))
                    yield f"{self.name}_bucket{labels} {c}"
                labels = self._label_str(key, (("le", "+Inf"),))
                yield f"{self.name}_bucket{labels} {count}"
                yield (f"{self.name}_sum{self._label_str(key)} "
                       f"{_fmt(total)}")
                yield (f"{self.name}_count{self._label_str(key)} "
                       f"{count}")
            else:
                yield (f"{self.name}{self._label_str(key)} "
                       f"{_fmt(child.get())}")


class MetricsRegistry:
    """Name-keyed metric index; creation is idempotent (a second
    declaration with the same schema returns the existing family;
    a conflicting one raises — two subsystems silently sharing a name
    with different meanings is exactly the grab-bag this replaces)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, Metric] = {}

    def _declare(self, name: str, help: str, kind: str,
                 labels: Iterable[str] = (),
                 buckets: tuple[float, ...] | None = None) -> Metric:
        labels = tuple(labels)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if existing.kind != kind or existing.labelnames != labels:
                    raise ValueError(
                        f"metric {name} already declared as "
                        f"{existing.kind}{existing.labelnames}; "
                        f"refusing {kind}{labels}")
                return existing
            metric = Metric(name, help, kind, labels, buckets)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labels: Iterable[str] = ()) -> Metric:
        return self._declare(name, help, "counter", labels)

    def gauge(self, name: str, help: str = "",
              labels: Iterable[str] = ()) -> Metric:
        return self._declare(name, help, "gauge", labels)

    def histogram(self, name: str, help: str = "",
                  buckets: tuple[float, ...] | None = None,
                  labels: Iterable[str] = ()) -> Metric:
        return self._declare(name, help, "histogram", labels, buckets)

    def get(self, name: str) -> Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def render(self) -> str:
        """Full Prometheus text exposition (format 0.0.4)."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines: list[str] = []
        for metric in metrics:
            lines.extend(metric.render())
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Zero every family (drop labelled children) — tests only."""
        with self._lock:
            metrics = list(self._metrics.values())
        for metric in metrics:
            metric.clear()


#: the process-wide registry every subsystem writes through
REGISTRY = MetricsRegistry()


# ---------------------------------------------------------------------------
# the repo's metric families, declared once so /metrics always exposes
# the full schema (HELP/TYPE) even before the first event
# ---------------------------------------------------------------------------

# -- host wave pipeline (parallel/dispatch.StageProfile bridges its
#    process-cumulative totals here) -----------------------------------
STAGE_SECONDS = REGISTRY.counter(
    "tvt_stage_seconds_total",
    "host wall-clock per wave-pipeline stage (decode/stage/dispatch/"
    "device_wait/fetch/pack/... — parallel/dispatch.STAGE_NAMES)",
    labels=("stage",))
WAVES_TOTAL = REGISTRY.counter(
    "tvt_waves_total", "waves collected by the wave pipeline")
STAGE_COUNTER_TOTALS = {
    "dense_fallback_waves": REGISTRY.counter(
        "tvt_dense_fallback_waves_total",
        "waves that overflowed the sparse budgets and re-encoded dense"),
    "h2d_bytes": REGISTRY.counter(
        "tvt_h2d_bytes_total", "host-to-device bytes staged"),
    "d2h_bytes": REGISTRY.counter(
        "tvt_d2h_bytes_total", "device-to-host bytes fetched"),
    "fetch_shards": REGISTRY.counter(
        "tvt_fetch_shards_total",
        "per-shard concurrent D2H transfers issued"),
    "proc_pack_gops": REGISTRY.counter(
        "tvt_proc_pack_gops_total",
        "GOPs handed to the process pack sidecars"),
    "sfe_frames": REGISTRY.counter(
        "tvt_sfe_frames_total",
        "frames through the split-frame per-frame collect path"),
}

# -- origin serving (origin/serve.OriginStats + origin/cache) ----------
ORIGIN_COUNTERS = {
    "origin_requests": REGISTRY.counter(
        "tvt_origin_requests_total", "origin file requests planned"),
    "origin_bytes": REGISTRY.counter(
        "tvt_origin_bytes_total", "origin body bytes served"),
    "origin_304s": REGISTRY.counter(
        "tvt_origin_304s_total", "conditional requests answered 304"),
    "origin_503s": REGISTRY.counter(
        "tvt_origin_503s_total",
        "blocking reloads refused over the waiter cap"),
    "origin_hits": REGISTRY.counter(
        "tvt_origin_cache_hits_total", "hot-segment cache hits"),
    "origin_fills": REGISTRY.counter(
        "tvt_origin_cache_fills_total", "hot-segment cache disk fills"),
    "origin_coalesced_fills": REGISTRY.counter(
        "tvt_origin_cache_coalesced_total",
        "requests that rode another thread's single-flight fill"),
    "origin_evictions": REGISTRY.counter(
        "tvt_origin_cache_evictions_total", "LRU evictions"),
}
ORIGIN_SERVE_SECONDS = REGISTRY.histogram(
    "tvt_origin_serve_seconds",
    "wall-clock of one /hls request, plan through last body byte",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0, 2.5, 5.0))
SESSIONS = REGISTRY.gauge(
    "tvt_origin_sessions",
    "concurrent player sessions per job (sliding window)",
    labels=("job",))

# -- QoS (cluster/qos.QosController) -----------------------------------
QOS_BREACHES = REGISTRY.counter(
    "tvt_qos_breaches_total", "live part deadline breach episodes")
QOS_RECOVERIES = REGISTRY.counter(
    "tvt_qos_recoveries_total", "live jobs recovered from a breach")
QOS_PREEMPTED_SHARDS = REGISTRY.counter(
    "tvt_qos_preempted_shards_total",
    "ASSIGNED batch shards requeued by deadline preemption")
QOS_PREEMPTING = REGISTRY.gauge(
    "tvt_qos_preempting",
    "1 while batch work is gated for a breached live job")
LIVE_PART_SECONDS = REGISTRY.histogram(
    "tvt_live_part_latency_seconds",
    "live batch frames-available to parts-fetchable latency",
    buckets=DEFAULT_BUCKETS + (30.0, 60.0))

# -- shard board (cluster/remote.ShardBoard) ---------------------------
SHARD_STATES = REGISTRY.gauge(
    "tvt_shard_board_shards",
    "shards on the remote work board by lease state",
    labels=("state",))
SHARD_CLAIM_SECONDS = REGISTRY.histogram(
    "tvt_shard_claim_to_part_seconds",
    "worker claim to accepted part per shard",
    buckets=(0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
             300.0, 600.0))

# -- farm SFE halo relay (cluster/halo.py) -----------------------------
HALO_RELAY_BLOBS = REGISTRY.gauge(
    "tvt_halo_relay_blobs",
    "band-shard halo blobs buffered on the coordinator relay")
HALO_RELAY_BYTES = REGISTRY.gauge(
    "tvt_halo_relay_bytes",
    "bytes of band-shard halo blobs buffered on the coordinator relay")

# -- durable part spool + crash resume (cluster/partstore.py) -----------
PART_SPOOL_BYTES = REGISTRY.gauge(
    "tvt_part_spool_bytes",
    "bytes of encoded shard parts currently spooled on the "
    "coordinator's disk (DONE shards hold refs, not payload)")
PART_INTEGRITY_FAILURES = REGISTRY.counter(
    "tvt_part_integrity_failures_total",
    "part payloads rejected on a digest mismatch (transfer/storage "
    "corruption — requeued with no attempt burned)")
RESUME_SHARDS_REUSED = REGISTRY.counter(
    "tvt_crash_resume_shards_reused_total",
    "shards rehydrated DONE from the verified spool after a "
    "coordinator restart (work NOT re-encoded)")

# -- split-frame encoding ----------------------------------------------
SFE_FRAME_SECONDS = REGISTRY.histogram(
    "tvt_sfe_frame_latency_seconds",
    "steady-state gap between consecutive SFE frames' "
    "bitstream-ready times")

# -- job control plane / multi-tenant farm ------------------------------
JOBS_BY_STATUS = REGISTRY.gauge(
    "tvt_jobs", "registered jobs by tenant and status",
    labels=("tenant", "status"))
TENANT_ACTIVE_SHARDS = REGISTRY.gauge(
    "tvt_tenant_active_shards",
    "shards currently ASSIGNED on the remote work board, per tenant",
    labels=("tenant",))
FARM_WORKERS = REGISTRY.gauge(
    "tvt_farm_workers",
    "elastic-farm worker hosts by lifecycle state "
    "(farm/controller.py)",
    labels=("lifecycle",))
FARM_WORKER_SECONDS = REGISTRY.counter(
    "tvt_farm_active_worker_seconds_total",
    "cumulative non-SUSPENDED worker-seconds the farm consumed — the "
    "energy-proportionality figure vs. always-on")


def percentiles(sorted_values: list[float],
                points: Mapping[str, float]) -> dict[str, float]:
    """Nearest-rank percentiles over pre-sorted data (the snapshot
    helpers' shared math); empty input yields an empty dict."""
    if not sorted_values:
        return {}
    n = len(sorted_values)
    return {name: sorted_values[min(n - 1, int(q * (n - 1)))]
            for name, q in points.items()}
