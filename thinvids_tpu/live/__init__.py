"""Live LL-HLS subsystem: encode while the source arrives, serve
viewers during ingest.

The batch ladder path (abr/) only produces output at job COMPLETION;
this package decouples output availability from job completion — the
low-latency-live model of JND-aware live-streaming encoding (PAPERS.md
arXiv:2401.15343) applied to the reference's watch-folder-as-ingest
design (SURVEY §2.4). `ingest/tail.py` follows a growing source
GOP-by-GOP, the executor's `_run_live` path feeds completed GOPs
through the existing ladder encoders wave-by-wave, and
:class:`LiveLadderPackager` here writes + announces each segment the
moment the GOP clears every rung: rolling live/EVENT playlists (no
EXT-X-ENDLIST until the stream closes), EXT-X-PART partial segments
with preload hints, and a sliding DVR window (EXT-X-MEDIA-SEQUENCE
advance + on-disk GC). The headline metric is glass-to-playlist
latency (`live_latency_s` in BENCH), not fps.
"""

from .packager import LiveLadderPackager

__all__ = ["LiveLadderPackager"]
