"""JAX/TPU inter-frame (P) encode compute: motion search, motion
compensation, residual transform/quant, closed-loop reconstruction.

Replaces the inter coding half of the reference's ffmpeg encode op point
(/root/reference/worker/tasks.py:1558-1586). TPU-shaped design:

- Motion estimation is FULL-SEARCH over a fixed ±SR integer-pel grid —
  one whole-frame |cur - shifted_ref| + per-MB reduction per candidate,
  iterated with `lax.map` (fixed trip count, static shapes; the classic
  data-dependent diamond/TSS searches are the wrong shape for SPMD —
  SURVEY.md §7.3 #2).
- MVs only affect *bitstream* prediction (mvd), not compute, so every MB
  of a P frame is encoded in parallel given the previous reconstruction;
  frames chain through a `lax.scan` carry holding the recon planes.
- Luma MC is integer-pel (a gather); chroma rides the same MV at 1/8-pel
  resolution via the spec's bilinear formula (fracs ∈ {0, 4}).
- Reconstruction clamps reference reads at the padded frame edge, which
  is exactly the spec's unrestricted-MV edge padding.

The sequential P-slice entropy pack (skip runs, mvp/mvd, CBP) stays on
host: codecs/h264/inter.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .jaxcore import (
    _QPC,
    _ZSCAN,
    _chroma_mb_batch,
    _dequant,
    _fwd4,
    _intra_core,
    _inv4,
    _quant,
    _varying_zero,
    _zigzag,
)

SEARCH_RANGE = 16          # integer-pel, each direction
_MV_LAMBDA = 6             # SAD bias per |mv| unit — favors short vectors


def _mb_blocks(x, n, b):
    """(n, 16, 16) → (n, 16, 4, 4) in raster 4x4 order (for b=4)."""
    return x.reshape(n, b, 4, b, 4).transpose(0, 1, 3, 2, 4).reshape(
        n, b * b, 4, 4)


def _mb_unblocks(x, n, b):
    return x.reshape(n, b, b, 4, 4).transpose(0, 1, 3, 2, 4).reshape(
        n, b * 4, b * 4)


def _motion_search(cur, ref_pad, mbw: int, mbh: int, sr: int):
    """Dense full-search integer ME over the ±sr shift grid: one
    whole-frame |cur - shifted_ref| + per-MB reduction per candidate,
    iterated with `lax.map` (fixed trip count, static shapes — the
    classic data-dependent diamond/TSS walks are the wrong shape for
    SPMD, SURVEY.md §7.3 #2). Subsampled candidate grids are NOT used:
    on grainy content only exact alignment scores low, so a stride-2 or
    half-res pyramid stage misses the sharp minimum entirely (measured).

    cur: (H, W) int32; ref_pad: (H+2sr, W+2sr) int32 edge-padded.
    Returns mv (mbh, mbw, 2) int32 as (dy, dx) in [-sr, sr].
    """
    H, W = cur.shape
    S = 2 * sr + 1

    def cost_for(shift):
        dy = shift // S
        dx = shift % S
        win = jax.lax.dynamic_slice(ref_pad, (dy, dx), (H, W))
        ad = jnp.abs(cur - win)
        sad = ad.reshape(mbh, 16, mbw, 16).sum(axis=(1, 3))
        mv_cost = _MV_LAMBDA * (jnp.abs(dy - sr) + jnp.abs(dx - sr))
        return sad + mv_cost

    costs = jax.lax.map(cost_for, jnp.arange(S * S), batch_size=S)
    best = jnp.argmin(costs, axis=0).astype(jnp.int32)   # (mbh, mbw)
    return jnp.stack([best // S - sr, best % S - sr], axis=-1)


_REFINE = 2                # refinement radius around each MV predictor


def _motion_search_pred(cur, ref_pad, pred_mv, mbw: int, mbh: int, sr: int):
    """Predictor-guided ME (the EPZS idea, SPMD-shaped): evaluate the
    temporal predictor (this MB's vector in the previous frame) and the
    zero vector, each refined over a ±_REFINE window — ~40x less work
    than the dense grid. Falls back gracefully: the zero candidate plus
    refinement bounds the damage when motion changes abruptly, and the
    first P frame of a GOP uses the dense search (no predictor yet).

    All candidates are static-shape gathers; per-MB best by unrolled
    min-tree. Returns mv (mbh, mbw, 2) int32 in [-sr, sr].
    """
    r = _REFINE
    cur_mb = cur.reshape(mbh, 16, mbw, 16).transpose(0, 2, 1, 3)
    idx = jnp.arange(16 + 2 * r)
    my = jnp.arange(mbh)
    mx = jnp.arange(mbw)

    best_cost = None
    best_mv = None
    for cand in (jnp.clip(pred_mv, -(sr - r), sr - r),
                 jnp.zeros_like(pred_mv)):
        rows = (my[:, None] * 16 + sr - r)[:, :, None, None] \
            + cand[..., 0][..., None, None] + idx[None, None, :, None]
        cols = (mx[None, :] * 16 + sr - r)[:, :, None, None] \
            + cand[..., 1][..., None, None] + idx[None, None, None, :]
        window = ref_pad[rows, cols]             # (mbh, mbw, 16+2r, 16+2r)
        for dy in range(2 * r + 1):
            for dx in range(2 * r + 1):
                w = window[:, :, dy:dy + 16, dx:dx + 16]
                sad = jnp.abs(cur_mb - w).sum(axis=(2, 3))
                off = jnp.stack([
                    jnp.broadcast_to(jnp.int32(dy - r), sad.shape),
                    jnp.broadcast_to(jnp.int32(dx - r), sad.shape)],
                    axis=-1)
                total = cand + off
                cost = sad + _MV_LAMBDA * jnp.abs(total).sum(-1)
                if best_cost is None:
                    best_cost, best_mv = cost, total
                else:
                    take = cost < best_cost
                    best_cost = jnp.where(take, cost, best_cost)
                    best_mv = jnp.where(take[..., None], total, best_mv)
    return best_mv


def _mc_luma(ref_pad, mv, mbw: int, mbh: int, sr: int):
    """Integer-pel luma MC: (mbh*mbw, 16, 16) predicted blocks."""
    r = jnp.arange(16)
    my = jnp.arange(mbh)
    mx = jnp.arange(mbw)
    rows = (my[:, None] * 16 + sr)[:, :, None, None] \
        + mv[..., 0][..., None, None] + r[None, None, :, None]
    cols = (mx[None, :] * 16 + sr)[:, :, None, None] \
        + mv[..., 1][..., None, None] + r[None, None, None, :]
    pred = ref_pad[rows, cols]                       # (mbh, mbw, 16, 16)
    return pred.reshape(mbh * mbw, 16, 16)


def _mc_chroma(ref_pad, mv, mbw: int, mbh: int, sr: int):
    """Chroma MC at 1/8-pel: bilinear per §8.4.2.2.2, fracs ∈ {0,4}.

    ref_pad: (H/2 + 2*(sr//2+1), W/2 + ...) edge-padded chroma plane with
    pad `cpad = sr // 2 + 1` (integer part of the largest chroma MV plus
    one for the +1 bilinear tap).
    """
    cpad = sr // 2 + 1
    ci = mv >> 1                                     # integer chroma offset
    frac = (mv & 1) * 4                              # 0 or 4 (x8 units)
    r = jnp.arange(8)
    my = jnp.arange(mbh)
    mx = jnp.arange(mbw)
    rows = (my[:, None] * 8 + cpad)[:, :, None, None] \
        + ci[..., 0][..., None, None] + r[None, None, :, None]
    cols = (mx[None, :] * 8 + cpad)[:, :, None, None] \
        + ci[..., 1][..., None, None] + r[None, None, None, :]
    a = ref_pad[rows, cols]
    b = ref_pad[rows, cols + 1]
    c = ref_pad[rows + 1, cols]
    d = ref_pad[rows + 1, cols + 1]
    xf = frac[..., 1][..., None, None]
    yf = frac[..., 0][..., None, None]
    pred = ((8 - xf) * (8 - yf) * a + xf * (8 - yf) * b
            + (8 - xf) * yf * c + xf * yf * d + 32) >> 6
    return pred.reshape(mbh * mbw, 8, 8)


def _luma_inter_mb_batch(src, pred, qp):
    """Inter luma residual: 16 standalone 4x4 transforms (no DC split).

    src/pred: (n, 16, 16) int32 → (levels (n, 16, 16) z-scan blocks of
    16 zig-zag coeffs, recon (n, 16, 16)).
    """
    n = src.shape[0]
    resid = src - pred
    blocks = _mb_blocks(resid, n, 4)                 # raster 4x4 order
    w = _fwd4(blocks)
    z = _quant(w, qp, skip_dc=False)
    levels = _zigzag(z)[:, _ZSCAN]                   # (n, 16, 16) z-scan
    d = _dequant(z, qp)
    r = (_inv4(d) + 32) >> 6
    rec = jnp.clip(_mb_unblocks(r, n, 4) + pred, 0, 255)
    return levels, rec


def _pad_ref(plane, pad):
    return jnp.pad(plane, pad, mode="edge")


def _encode_p_core(cy, cu, cv, ry, ru, rv, qp, qpc, pred_mv=None,
                   use_pred=None, *, mbw: int, mbh: int,
                   sr: int = SEARCH_RANGE):
    """One P frame given previous recon (ry, ru, rv). All MBs parallel.

    `pred_mv`/`use_pred`: optional temporal MV predictor field — when
    `use_pred` is true the cheap predictor-guided search runs instead of
    the dense grid (the GOP scan passes the previous frame's vectors).

    Returns (mv (nmb,2), luma_levels (nmb,16,16), chroma_dc (nmb,2,4),
    chroma_ac (nmb,2,4,15), recon_y, recon_u, recon_v, mv_grid).
    """
    n = mbw * mbh
    cy = cy.astype(jnp.int32)
    cu = cu.astype(jnp.int32)
    cv = cv.astype(jnp.int32)

    ref_y = _pad_ref(ry, sr)
    if pred_mv is None:
        mv = _motion_search(cy, ref_y, mbw, mbh, sr)     # (mbh, mbw, 2)
    else:
        mv = jax.lax.cond(
            use_pred,
            lambda: _motion_search_pred(cy, ref_y, pred_mv, mbw, mbh, sr),
            lambda: _motion_search(cy, ref_y, mbw, mbh, sr))

    pred_y = _mc_luma(ref_y, mv, mbw, mbh, sr)
    cpad = sr // 2 + 1
    pred_u = _mc_chroma(_pad_ref(ru, cpad), mv, mbw, mbh, sr)
    pred_v = _mc_chroma(_pad_ref(rv, cpad), mv, mbw, mbh, sr)

    src_y = cy.reshape(mbh, 16, mbw, 16).transpose(0, 2, 1, 3).reshape(
        n, 16, 16)
    src_u = cu.reshape(mbh, 8, mbw, 8).transpose(0, 2, 1, 3).reshape(n, 8, 8)
    src_v = cv.reshape(mbh, 8, mbw, 8).transpose(0, 2, 1, 3).reshape(n, 8, 8)

    luma_levels, yrec = _luma_inter_mb_batch(src_y, pred_y, qp)
    udc, uac, urec = _chroma_mb_batch(src_u, pred_u, qpc)
    vdc, vac, vrec = _chroma_mb_batch(src_v, pred_v, qpc)
    chroma_dc = jnp.stack([udc, vdc], axis=1)
    chroma_ac = jnp.stack([uac, vac], axis=1)

    recon_y = yrec.reshape(mbh, mbw, 16, 16).transpose(0, 2, 1, 3).reshape(
        16 * mbh, 16 * mbw)
    recon_u = urec.reshape(mbh, mbw, 8, 8).transpose(0, 2, 1, 3).reshape(
        8 * mbh, 8 * mbw)
    recon_v = vrec.reshape(mbh, mbw, 8, 8).transpose(0, 2, 1, 3).reshape(
        8 * mbh, 8 * mbw)
    return (mv.reshape(n, 2), luma_levels, chroma_dc, chroma_ac,
            recon_y, recon_u, recon_v, mv)


@functools.partial(jax.jit, static_argnames=("mbw", "mbh", "emit_recon"))
def encode_gop_jit(ys, us, vs, qp, *, mbw: int, mbh: int,
                   emit_recon: bool = False):
    """Closed-GOP compute: frame 0 intra, frames 1..F-1 inter (P).

    ys: (F, H, W) uint8. Returns the intra frame's level arrays plus the
    P frames' (mv, luma16, chroma_dc, chroma_ac) stacked over F-1; with
    `emit_recon` also the per-frame reconstructed planes (tests/metrics —
    costs F x frame HBM, off by default).
    """
    qp = qp.astype(jnp.int32)
    qpc = _QPC[jnp.clip(qp, 0, 51)]
    (il_dc, il_ac, ic_dc, ic_ac, ry, ru, rv) = _intra_core(
        ys[0], us[0], vs[0], qp, mbw=mbw, mbh=mbh)

    def p_step(carry, xs):
        ry, ru, rv, prev_mv, has_pred = carry
        cy, cu, cv = xs
        (mv, l16, cdc, cac, ry2, ru2, rv2, mv_grid) = _encode_p_core(
            cy, cu, cv, ry, ru, rv, qp, qpc, prev_mv, has_pred,
            mbw=mbw, mbh=mbh)
        outs = (mv, l16, cdc, cac)
        if emit_recon:
            outs = outs + (ry2, ru2, rv2)
        return (ry2, ru2, rv2, mv_grid, jnp.bool_(True) | has_pred), outs

    # Inits derived from data (not constants) so the scan carries keep
    # the mesh-varying axes under shard_map — see jaxcore._varying_zero.
    zero = _varying_zero(ry)
    zero_mv = jnp.zeros((mbh, mbw, 2), jnp.int32) + zero
    _, pouts = jax.lax.scan(
        p_step, (ry, ru, rv, zero_mv, zero.astype(jnp.bool_)),
        (ys[1:], us[1:], vs[1:]))
    intra = (il_dc, il_ac, ic_dc, ic_ac)
    if emit_recon:
        mv, l16, cdc, cac, pry, pru, prv = pouts
        recon_y = jnp.concatenate([ry[None], pry])
        recon_u = jnp.concatenate([ru[None], pru])
        recon_v = jnp.concatenate([rv[None], prv])
        return intra, (mv, l16, cdc, cac), (recon_y, recon_u, recon_v)
    mv, l16, cdc, cac = pouts
    return intra, (mv, l16, cdc, cac)
