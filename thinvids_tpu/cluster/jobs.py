"""Typed job records + thread-safe store.

The reference kept each job as a ~60-field Redis hash (`job:<uuid>`,
/root/reference/manager/app.py:2367-2370) indexed by a `jobs:all` set
(/root/reference/common.py:231-274); this is the typed in-process
equivalent with the same lifecycle fields: status, per-stage progress,
run-token fence, heartbeat triple, and failure attribution.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from typing import Any, Callable, Iterator, Mapping

from ..core.status import Status
from ..core.types import VideoMeta


def new_run_token() -> str:
    """Fencing token minted per dispatch; stale executors no-op when
    their token no longer matches (the reference's pipeline_run_token,
    /root/reference/worker/tasks.py:396-424)."""
    return uuid.uuid4().hex


@dataclasses.dataclass
class Job:
    """One transcode job. Mutate only through JobStore.update()."""

    id: str
    input_path: str
    meta: VideoMeta | None = None
    status: Status = Status.READY
    # settings overlay (core.config.JOB_SETTING_KEYS subset)
    settings: dict[str, Any] = dataclasses.field(default_factory=dict)
    # admission decision
    processing_mode: str = "split"       # split | direct
    reject_reason: str = ""
    # scheduling / fencing
    run_token: str = ""
    queued_at: float = 0.0
    started_at: float = 0.0
    finished_at: float = 0.0
    created_at: float = dataclasses.field(default_factory=time.time)
    # progress (percent 0-100, parts = GOP segments)
    segment_progress: float = 0.0
    encode_progress: float = 0.0
    combine_progress: float = 0.0
    parts_total: int = 0
    parts_done: int = 0
    # heartbeat (throttled writes; watchdog liveness source)
    heartbeat_at: float = 0.0
    heartbeat_stage: str = ""
    heartbeat_host: str = ""
    heartbeat_note: str = ""
    # failure attribution
    failure_stage: str = ""
    failure_host: str = ""
    failure_reason: str = ""
    # result
    output_path: str = ""
    output_bytes: int = 0
    elapsed_s: float = 0.0

    @property
    def done_ratio(self) -> float:
        if self.parts_total <= 0:
            return 0.0
        return self.parts_done / self.parts_total

    def to_dict(self) -> dict[str, Any]:
        """JSON-clean view (enums → names) for the API/store layers."""
        d = dataclasses.asdict(self)
        d["status"] = self.status.value
        if self.meta is not None:
            meta = dataclasses.asdict(self.meta)
            meta["chroma"] = self.meta.chroma.name
            d["meta"] = meta
        return d


class JobStore:
    """Thread-safe in-process job index.

    The update() path takes the store lock and hands the caller the live
    record — the analog of the reference's HSET read-modify-write under
    its scheduler lock. Snapshots returned by get()/list() are copies.
    """

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._jobs: dict[str, Job] = {}

    def create(self, input_path: str, meta: VideoMeta | None = None,
               settings: Mapping[str, Any] | None = None,
               job_id: str | None = None) -> Job:
        job = Job(id=job_id or uuid.uuid4().hex, input_path=input_path,
                  meta=meta, settings=dict(settings or {}))
        with self._lock:
            if job.id in self._jobs:
                raise ValueError(f"duplicate job id {job.id}")
            self._jobs[job.id] = job
        return self.get(job.id)

    def get(self, job_id: str) -> Job:
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                raise KeyError(f"no such job {job_id}")
            return dataclasses.replace(job)

    def try_get(self, job_id: str) -> Job | None:
        try:
            return self.get(job_id)
        except KeyError:
            return None

    def update(self, job_id: str, fn: Callable[[Job], None]) -> Job:
        """Apply `fn` to the live record under the store lock; returns a
        snapshot of the result."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                raise KeyError(f"no such job {job_id}")
            fn(job)
            return dataclasses.replace(job)

    def delete(self, job_id: str) -> bool:
        with self._lock:
            return self._jobs.pop(job_id, None) is not None

    def list(self, status: Status | None = None) -> list[Job]:
        with self._lock:
            jobs = [dataclasses.replace(j) for j in self._jobs.values()]
        if status is not None:
            jobs = [j for j in jobs if j.status is status]
        return sorted(jobs, key=lambda j: j.created_at)

    def __iter__(self) -> Iterator[Job]:
        return iter(self.list())

    def __len__(self) -> int:
        with self._lock:
            return len(self._jobs)

    def all_idle(self) -> bool:
        """True iff no job is WAITING or active (the reference's
        all_jobs_are_idle, /root/reference/common.py:231-274)."""
        with self._lock:
            return not any(
                j.status is Status.WAITING or j.status.is_active
                for j in self._jobs.values())
