"""Codec implementations — the framework's "native compute" layer.

The reference delegated all codec work to external ffmpeg processes
(/root/reference/worker/tasks.py:1354-1737). Here the encoder IS the
framework: integer transforms, intra prediction, quantization and entropy
coding implemented from the H.264 spec, with the blockwise math running as
JAX/Pallas programs on TPU and the sequential entropy pack on host.
"""
