"""Tail ingest: follow a GROWING media source for live encoding.

The reference's watch folder was batch-only — a file had to stop
changing before the watcher would submit it. A live origin inverts
that: the source is an append-only stream (a y4m file a capture
process is still writing, or a local socket spooled into one), and
the encoder follows the live edge GOP-by-GOP while the writer is
still appending (ROADMAP "Live ABR pipeline"; SURVEY §2.4
watch-folder-as-ingest, generalized to a file that never "settles").

:class:`TailFrameSource` wraps the same fixed-record y4m arithmetic
as :class:`io.y4m.Y4MRangeReader` — 8-bit y4m frames are constant-size
records, so the number of COMPLETE frames on disk is a pure function
of the file size, and a mid-frame partial append simply doesn't count
yet (floor division; the torn tail record becomes visible on a later
poll once the writer finishes it). End-of-stream is declared by a
stall timeout: when the file stops growing for `stall_timeout_s`
seconds (or the writer drops a ``<path>.eos`` marker for an explicit,
latency-free close), the stream ends CLEANLY — the live pipeline
finalizes its playlists instead of failing the job.

:func:`spool_stream` adapts any byte stream (a local socket's
makefile, a pipe) into the growing-file form, so socket ingest rides
the exact same tail path the file case uses.

jax-free by contract: tailing runs on executor threads and in tests
that never load a device backend.
"""

from __future__ import annotations

import os
import time
from typing import BinaryIO, Iterator

import numpy as np

from ..core.types import Frame, VideoMeta
from ..io.y4m import Y4MReader
from .decode import DecodeError, FrameSource

#: filename convention marking a watch-folder drop as a live stream
#: (`clip.live.y4m` → job_type "live"; mirrors the `.ladder` suffix)
LIVE_STEM_SUFFIX = ".live"

#: sidecar marker a writer may create to close the stream explicitly
#: (zero added latency vs waiting out the stall timeout)
EOS_SUFFIX = ".eos"


def is_live_name(path: str) -> bool:
    """True when the filename opts into live ingest (stem ends with
    ``.live``, e.g. ``game7.live.y4m`` — same stem-suffix contract as
    ``.ladder``, so derived names don't inherit it)."""
    stem = os.path.splitext(os.path.basename(path))[0].lower()
    return stem.endswith(LIVE_STEM_SUFFIX)


class TailFrameSource(FrameSource):
    """Follow a growing 8-bit y4m file frame-by-frame.

    `len()` / iteration cover the frames COMPLETE on disk right now;
    the live-specific surface is :meth:`wait_frames` (block until the
    file has grown past a frame count, a poll + EOF-retry loop) and
    :attr:`ended` (the stall timeout or `.eos` marker fired — no more
    frames will ever appear). `read_range` re-stats the file per call,
    so a reader thread and the appending writer never share a cursor.
    """

    def __init__(self, path: str | os.PathLike,
                 stall_timeout_s: float = 10.0,
                 poll_s: float = 0.05) -> None:
        super().__init__()
        self.path = os.fspath(path)
        self.stall_timeout_s = max(0.1, float(stall_timeout_s))
        self.poll_s = max(0.005, float(poll_s))
        self.audio = None
        self.ended = False
        self._wait_header()

    # -- header ---------------------------------------------------------

    def _wait_header(self) -> None:
        """Poll until the stream header is parseable — the writer may
        have created the file but not finished the header line yet. A
        header that never arrives within the stall budget is a
        DecodeError, not a hang."""
        deadline = time.monotonic() + self.stall_timeout_s
        last_err: Exception | None = None
        while True:
            try:
                with open(self.path, "rb") as fp:
                    header = Y4MReader(fp)
                    self._data_start = fp.tell()
                break
            except (FileNotFoundError, EOFError, ValueError) as exc:
                last_err = exc
                if time.monotonic() >= deadline:
                    raise DecodeError(
                        f"no parseable y4m header in {self.path} after "
                        f"{self.stall_timeout_s:.1f}s: {last_err}"
                    ) from last_err
                time.sleep(self.poll_s)
        self._header = header
        self._shapes = header._plane_shapes()
        self._marker = b"FRAME\n"
        payload = sum(h * w for h, w in self._shapes)
        self._record = len(self._marker) + payload

    @property
    def meta(self) -> VideoMeta:
        h = self._header
        n = self.available()
        return VideoMeta(
            width=h.width, height=h.height,
            fps_num=h.fps_num, fps_den=h.fps_den,
            num_frames=n, chroma=h.chroma, codec="rawvideo",
            duration_s=n / h.meta.fps if h.meta.fps else 0.0,
            size_bytes=self._size(),
        )

    # -- growth tracking -------------------------------------------------

    def _size(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def available(self) -> int:
        """COMPLETE frames on disk right now. A torn tail record (the
        writer is mid-frame) is excluded by the floor division and
        becomes visible on a later call."""
        return max(0, (self._size() - self._data_start) // self._record)

    def _eos_marked(self) -> bool:
        return os.path.exists(self.path + EOS_SUFFIX)

    def wait_frames(self, count: int, stop_check=None) -> int:
        """Block until at least `count` complete frames exist, the
        writer closes the stream (`.eos` marker), or the file stops
        growing for `stall_timeout_s` (clean end-of-stream). Returns
        the frames available at return; after `ended` is True the
        count is final. `stop_check()` (optional) is polled each tick
        so a fenced/stopped job aborts the wait in ~`poll_s` instead
        of riding out the stall budget."""
        last_size = self._size()
        stall_deadline = time.monotonic() + self.stall_timeout_s
        while True:
            n = self.available()
            if n >= count:
                return n
            if self._eos_marked():
                self.ended = True
                return self.available()
            if stop_check is not None and stop_check():
                return n
            size = self._size()
            if size != last_size:
                last_size = size
                stall_deadline = time.monotonic() + self.stall_timeout_s
            elif time.monotonic() >= stall_deadline:
                self.ended = True
                return self.available()
            time.sleep(self.poll_s)

    # -- FrameSource surface ---------------------------------------------

    def __len__(self) -> int:
        return self.available()

    def iter_frames(self, start: int = 0,
                    stop: int | None = None) -> Iterator[Frame]:
        """Yield COMPLETE frames [start, stop) from their byte offsets
        (the Y4MRangeReader arithmetic, re-statted per call so the
        range never reads past the writer's last full record)."""
        n = self.available()
        stop = n if stop is None else min(stop, n)
        start = max(0, start)
        if stop <= start:
            return
        with open(self.path, "rb") as fp:
            fp.seek(self._data_start + start * self._record)
            for idx in range(start, stop):
                marker = fp.read(len(self._marker))
                if marker != self._marker:
                    raise ValueError(
                        f"{self.path}: frame {idx} marker {marker!r} is "
                        f"not a bare FRAME record (parameterized y4m "
                        f"frame headers are unsupported for tailing)")
                planes = []
                for h, w in self._shapes:
                    data = fp.read(h * w)
                    if len(data) != h * w:
                        raise EOFError("truncated y4m frame payload")
                    planes.append(
                        np.frombuffer(data, np.uint8).reshape(h, w))
                y = planes[0]
                u, v = ((planes[1], planes[2]) if len(planes) == 3
                        else (None, None))
                self.frames_decoded += 1
                yield Frame(y, u, v, pts=idx)


def spool_stream(stream: BinaryIO, path: str | os.PathLike,
                 chunk_bytes: int = 1 << 16,
                 mark_eos: bool = True) -> int:
    """Copy a byte stream (local socket makefile, pipe, stdin) into an
    append-only file so socket ingest reuses the growing-file tail
    path. Blocks until the stream EOFs; drops the ``.eos`` marker on
    completion so the tailer ends without waiting out the stall
    budget. Returns bytes spooled."""
    path = os.fspath(path)
    total = 0
    with open(path, "ab") as out:
        while True:
            chunk = stream.read(chunk_bytes)
            if not chunk:
                break
            out.write(chunk)
            out.flush()
            total += len(chunk)
    if mark_eos:
        with open(path + EOS_SUFFIX, "wb"):
            pass
    return total
