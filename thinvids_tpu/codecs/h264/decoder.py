"""H.264 baseline decoder (subset matching the encoder's profile).

Independent implementation of the decode direction — parses Annex-B
streams (SPS/PPS, IDR + non-IDR slices, CAVLC, I16x16 and P_L0_16x16,
multi-slice pictures) and reconstructs frames. Used by tests as the
in-repo conformance check of encoder output (alongside the libavcodec
ctypes oracle — which this container may not have) and by the
stamp/seam verification tooling to decode without external binaries.

Scope grows with the encoder: one reference frame (the previous
decoded picture), whole-MB partitions, half-pel MVs (quarter-pel mvd),
deblocking disabled, and pictures split into any number of slices —
the split-frame-encoding path emits one slice per MB-row band, and
this decoder applies the same §7.4.3 cross-slice neighbor
unavailability the encoder's band packers assume.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..h264 import cavlc
from ...core.types import ChromaFormat, Frame, VideoMeta
from ...io.bits import BitReader, split_annexb
from .headers import (
    NAL_PPS,
    NAL_SLICE_IDR,
    NAL_SLICE_NON_IDR,
    NAL_SPS,
    PPS,
    SLICE_TYPE_I,
    SLICE_TYPE_P,
    SPS,
    SliceHeader,
)
from .inter import _CODE_TO_CBP_INTER, _median3
from .intra import (
    CHROMA_BLOCK_ORDER,
    LUMA_BLOCK_ORDER,
    predict_chroma8,
    predict_luma16,
    reconstruct_chroma8,
    reconstruct_luma16,
)
from .transform import chroma_qp, dequant_4x4, inverse_4x4, inverse_zigzag

#: luma interpolation pad: |mv| <= 16 pel plus the 6-tap reach (3)
_MC_PAD = 24
_MC_PAD_C = 12


@dataclasses.dataclass
class DecodedStream:
    meta: VideoMeta
    frames: list[Frame]


class _Picture:
    """One picture being assembled from its (possibly many) slices."""

    def __init__(self, sps: SPS) -> None:
        self.mbw, self.mbh = sps.mb_width, sps.mb_height
        mbw, mbh = self.mbw, self.mbh
        self.y = np.zeros((16 * mbh, 16 * mbw), np.uint8)
        self.u = np.zeros((8 * mbh, 8 * mbw), np.uint8)
        self.v = np.zeros((8 * mbh, 8 * mbw), np.uint8)
        # CAVLC nC neighbor state (total_coeff per 4x4 block), shared
        # across the picture's slices; cross-slice neighbors are never
        # CONSULTED (availability checks below), matching §7.4.3.
        self.luma_counts = np.zeros((4 * mbh, 4 * mbw), np.int32)
        self.chroma_counts = np.zeros((2, 2 * mbh, 2 * mbw), np.int32)
        self.mv = np.zeros((mbh, mbw, 2), np.int32)     # (dy, dx) half-pel
        self.decoded = 0                                # MBs decoded so far
        # in-loop deblocking state: the effective QP_Y of every MB (the
        # running slice QP after mb_qp_delta; uncoded MBs keep the
        # running value — §8.7's QP for skipped MBs), the picture's
        # coding type, and whether ANY slice enabled the filter (all
        # slices of a picture carry the same idc in our streams).
        self.qp_mb = np.zeros((mbh, mbw), np.int32)
        self.intra = True
        self.deblock = False


def _tap6(x: np.ndarray, axis: int) -> np.ndarray:
    """6-tap §8.4.2.2.1 filter along `axis` with the same roll
    convention as jaxme._tap6_lane (roll(x, k) moves element l to
    l + k): out[l] = x[l-2] -5x[l-1] +20x[l] +20x[l+1] -5x[l+2] +x[l+3].
    Wrapped edge rows/lanes stay inside the MC pad and are never read."""
    r = lambda k: np.roll(x, k, axis=axis)
    return r(2) - 5 * r(1) + 20 * x + 20 * r(-1) - 5 * r(-2) + r(-3)


def _halfpel_planes_np(ref_y: np.ndarray):
    """(R, B, H, J) int32 planes over an edge-padded reference — the
    numpy mirror of jaxme._halfpel_planes (identical rounding)."""
    r32 = np.pad(ref_y.astype(np.int32), _MC_PAD, mode="edge")
    hb1 = _tap6(r32, axis=1)
    b = np.clip((hb1 + 16) >> 5, 0, 255)
    h = np.clip((_tap6(r32, axis=0) + 16) >> 5, 0, 255)
    j = np.clip((_tap6(hb1, axis=0) + 512) >> 10, 0, 255)
    return (r32, b, h, j)


class _RefFrame:
    """Previous decoded picture + lazily-built interpolation planes."""

    def __init__(self, pic: _Picture) -> None:
        self.y, self.u, self.v = pic.y, pic.u, pic.v
        self._planes = None
        self._cu = None
        self._cv = None

    def luma_pred(self, my: int, mx: int, mv) -> np.ndarray:
        if self._planes is None:
            self._planes = _halfpel_planes_np(self.y)
        dy, dx = int(mv[0]), int(mv[1])
        plane = self._planes[(dy & 1) * 2 + (dx & 1)]
        r0 = _MC_PAD + 16 * my + (dy >> 1)
        c0 = _MC_PAD + 16 * mx + (dx >> 1)
        return plane[r0:r0 + 16, c0:c0 + 16]

    def chroma_pred(self, my: int, mx: int, mv):
        """(pred_u, pred_v) via the §8.4.2.2.2 eighth-pel bilinear."""
        if self._cu is None:
            self._cu = np.pad(self.u.astype(np.int32), _MC_PAD_C,
                              mode="edge")
            self._cv = np.pad(self.v.astype(np.int32), _MC_PAD_C,
                              mode="edge")
        dy, dx = int(mv[0]), int(mv[1])
        oy, ox = dy >> 2, dx >> 2
        ey, ex = (dy & 3) * 2, (dx & 3) * 2
        r0 = _MC_PAD_C + 8 * my + oy
        c0 = _MC_PAD_C + 8 * mx + ox

        def bil(C):
            a = C[r0:r0 + 8, c0:c0 + 8]
            b = C[r0:r0 + 8, c0 + 1:c0 + 9]
            c = C[r0 + 1:r0 + 9, c0:c0 + 8]
            d = C[r0 + 1:r0 + 9, c0 + 1:c0 + 9]
            return ((8 - ex) * (8 - ey) * a + ex * (8 - ey) * b
                    + (8 - ex) * ey * c + ex * ey * d + 32) >> 6

        return bil(self._cu), bil(self._cv)


def _mvp_and_skip(pic: _Picture, my: int, mx: int, slice_first: int):
    """(mvp, skip_mv) for MB (my, mx) — §8.4.1.3 median prediction with
    the C→D fallback and §8.4.1.1 P_Skip inference, neighbors limited
    to the CURRENT slice (the decoder-side mirror of inter.predict_mvs,
    which the band packers apply in band-local coordinates)."""
    mbw = pic.mbw
    mi = my * mbw + mx
    zero = np.zeros(2, np.int32)
    avail_a = mx > 0 and mi - 1 >= slice_first
    avail_b = my > 0 and mi - mbw >= slice_first
    mva = pic.mv[my, mx - 1] if avail_a else zero
    mvb = pic.mv[my - 1, mx] if avail_b else zero
    if my > 0 and mx + 1 < mbw and mi - mbw + 1 >= slice_first:
        avail_c, mvc = True, pic.mv[my - 1, mx + 1]
    elif my > 0 and mx > 0 and mi - mbw - 1 >= slice_first:
        avail_c, mvc = True, pic.mv[my - 1, mx - 1]
    else:
        avail_c, mvc = False, zero
    n_avail = int(avail_a) + int(avail_b) + int(avail_c)
    if not avail_b and not avail_c and avail_a:
        p = mva
    elif n_avail == 1:
        p = mva if avail_a else (mvb if avail_b else mvc)
    else:
        p = np.array([_median3(int(mva[0]), int(mvb[0]), int(mvc[0])),
                      _median3(int(mva[1]), int(mvb[1]), int(mvc[1]))],
                     np.int32)
    if (not avail_a or not avail_b
            or (mva[0] == 0 and mva[1] == 0)
            or (mvb[0] == 0 and mvb[1] == 0)):
        skip = zero
    else:
        skip = p
    return np.asarray(p, np.int32), np.asarray(skip, np.int32)


def _decode_islice(br: BitReader, pic: _Picture,
                   header: SliceHeader) -> None:
    """Decode one I slice (any first_mb) into the picture state."""
    mbw, mbh = pic.mbw, pic.mbh
    nmb = mbw * mbh
    first = header.first_mb
    qp = header.qp
    y, u, v = pic.y, pic.u, pic.v
    luma_counts, chroma_counts = pic.luma_counts, pic.chroma_counts

    mi = first
    while mi < nmb and br.more_rbsp_data():
        my, mx = divmod(mi, mbw)
        mb_type = br.ue()
        if not 1 <= mb_type <= 24:
            raise ValueError(f"unsupported I mb_type {mb_type}")
        luma_mode = (mb_type - 1) % 4
        cbp_chroma = ((mb_type - 1) // 4) % 3
        cbp_luma = 15 if (mb_type - 1) >= 12 else 0
        chroma_mode = br.ue()
        qp += br.se()                       # mb_qp_delta
        pic.qp_mb[my, mx] = qp
        qpc = chroma_qp(qp)

        # in-slice neighbor availability (§7.4.3): an MB in another
        # slice is unavailable to prediction AND to nC derivation
        a_ok = mx > 0 and mi - 1 >= first
        b_ok = my > 0 and mi - mbw >= first
        d_ok = my > 0 and mx > 0 and mi - mbw - 1 >= first

        by0, bx0 = 4 * my, 4 * mx
        na = int(luma_counts[by0, bx0 - 1]) if a_ok else None
        nb = int(luma_counts[by0 - 1, bx0]) if b_ok else None
        luma_dc = np.array(
            cavlc.decode_residual(br, cavlc.luma_nc(na, nb), 16), np.int32)

        luma_ac = np.zeros((16, 15), np.int32)
        for bi, (bx, by) in enumerate(LUMA_BLOCK_ORDER):
            gy, gx = by0 + by, bx0 + bx
            if cbp_luma:
                na = (int(luma_counts[gy, gx - 1])
                      if gx > bx0 or a_ok else None) if gx > 0 else None
                nb = (int(luma_counts[gy - 1, gx])
                      if gy > by0 or b_ok else None) if gy > 0 else None
                coeffs = cavlc.decode_residual(br, cavlc.luma_nc(na, nb), 15)
                luma_ac[bi] = coeffs
                luma_counts[gy, gx] = sum(1 for c in coeffs if c)
            else:
                luma_counts[gy, gx] = 0

        chroma_dc = np.zeros((2, 4), np.int32)
        if cbp_chroma > 0:
            for ci in range(2):
                chroma_dc[ci] = cavlc.decode_residual(br, -1, 4)
        chroma_ac = np.zeros((2, 4, 15), np.int32)
        cy0, cx0 = 2 * my, 2 * mx
        for ci in range(2):
            for bi, (bx, by) in enumerate(CHROMA_BLOCK_ORDER):
                gy, gx = cy0 + by, cx0 + bx
                if cbp_chroma == 2:
                    na = (int(chroma_counts[ci, gy, gx - 1])
                          if gx > cx0 or a_ok else None) if gx > 0 else None
                    nb = (int(chroma_counts[ci, gy - 1, gx])
                          if gy > cy0 or b_ok else None) if gy > 0 else None
                    coeffs = cavlc.decode_residual(
                        br, cavlc.luma_nc(na, nb), 15)
                    chroma_ac[ci, bi] = coeffs
                    chroma_counts[ci, gy, gx] = sum(1 for c in coeffs if c)
                else:
                    chroma_counts[ci, gy, gx] = 0

        # Reconstruct.
        top = y[16 * my - 1, 16 * mx:16 * mx + 16] if b_ok else None
        left = y[16 * my:16 * my + 16, 16 * mx - 1] if a_ok else None
        tl = int(y[16 * my - 1, 16 * mx - 1]) if d_ok else None
        pred = predict_luma16(luma_mode, top, left, tl)
        y[16 * my:16 * my + 16, 16 * mx:16 * mx + 16] = reconstruct_luma16(
            pred, luma_dc, luma_ac, qp)
        for ci, plane in enumerate((u, v)):
            ctop = plane[8 * my - 1, 8 * mx:8 * mx + 8] if b_ok else None
            cleft = plane[8 * my:8 * my + 8, 8 * mx - 1] if a_ok else None
            ctl = int(plane[8 * my - 1, 8 * mx - 1]) if d_ok else None
            cpred = predict_chroma8(chroma_mode, ctop, cleft, ctl)
            plane[8 * my:8 * my + 8, 8 * mx:8 * mx + 8] = reconstruct_chroma8(
                cpred, chroma_dc[ci], chroma_ac[ci], qpc)
        pic.decoded += 1
        mi += 1


def _recon_p_mb(pic: _Picture, ref: _RefFrame, my: int, mx: int, mv,
                luma16, chroma_dc, chroma_ac, qp: int) -> None:
    pred = ref.luma_pred(my, mx, mv)
    out = np.empty((16, 16), np.int32)
    for bi, (bx, by) in enumerate(LUMA_BLOCK_ORDER):
        z = inverse_zigzag(np.asarray(luma16[bi], np.int32))
        d = dequant_4x4(z, qp)                 # inter: no luma DC split
        r = (inverse_4x4(d) + 32) >> 6
        p = pred[4 * by:4 * by + 4, 4 * bx:4 * bx + 4]
        out[4 * by:4 * by + 4, 4 * bx:4 * bx + 4] = p + r
    pic.y[16 * my:16 * my + 16, 16 * mx:16 * mx + 16] = \
        np.clip(out, 0, 255).astype(np.uint8)
    qpc = chroma_qp(qp)
    pu, pv = ref.chroma_pred(my, mx, mv)
    for ci, (plane, cpred) in enumerate(((pic.u, pu), (pic.v, pv))):
        plane[8 * my:8 * my + 8, 8 * mx:8 * mx + 8] = reconstruct_chroma8(
            cpred, chroma_dc[ci], chroma_ac[ci], qpc)


def _decode_pslice(br: BitReader, pic: _Picture, header: SliceHeader,
                   ref: _RefFrame) -> None:
    """Decode one P slice (any first_mb): skip runs, P_L0_16x16 MBs."""
    mbw, mbh = pic.mbw, pic.mbh
    nmb = mbw * mbh
    first = header.first_mb
    qp = header.qp
    zero16 = np.zeros((16, 16), np.int32)
    zero_cdc = np.zeros((2, 4), np.int32)
    zero_cac = np.zeros((2, 4, 15), np.int32)

    mi = first
    while mi < nmb and br.more_rbsp_data():
        run = br.ue()                          # mb_skip_run
        for _ in range(run):
            if mi >= nmb:
                raise ValueError("mb_skip_run past end of picture")
            my, mx = divmod(mi, mbw)
            _, skip_mv = _mvp_and_skip(pic, my, mx, first)
            pic.mv[my, mx] = skip_mv
            pic.qp_mb[my, mx] = qp          # skip: running QP (§8.7)
            _recon_p_mb(pic, ref, my, mx, skip_mv, zero16, zero_cdc,
                        zero_cac, qp)
            pic.decoded += 1
            mi += 1
        if mi >= nmb or not br.more_rbsp_data():
            break                              # trailing skip run
        my, mx = divmod(mi, mbw)
        mb_type = br.ue()
        if mb_type != 0:
            raise ValueError(f"unsupported P mb_type {mb_type}")
        mvd_x = br.se()                        # quarter-pel, x first
        mvd_y = br.se()
        if (mvd_x | mvd_y) & 1:
            raise ValueError("quarter-pel mvd not supported (half-pel "
                             "encoder)")
        mvp, _ = _mvp_and_skip(pic, my, mx, first)
        mv = np.array([mvp[0] + mvd_y // 2, mvp[1] + mvd_x // 2], np.int32)
        pic.mv[my, mx] = mv
        cbp = _CODE_TO_CBP_INTER[br.ue()]
        cbp_luma, cbp_chroma = cbp & 15, cbp >> 4
        if cbp:
            qp += br.se()                      # mb_qp_delta
        pic.qp_mb[my, mx] = qp

        a_ok = mx > 0 and mi - 1 >= first
        b_ok = my > 0 and mi - mbw >= first
        by0, bx0 = 4 * my, 4 * mx
        luma16 = np.zeros((16, 16), np.int32)
        for bi, (bx, by) in enumerate(LUMA_BLOCK_ORDER):
            gy, gx = by0 + by, bx0 + bx
            if cbp_luma & (1 << (bi // 4)):
                na = (int(pic.luma_counts[gy, gx - 1])
                      if gx > bx0 or a_ok else None) if gx > 0 else None
                nb = (int(pic.luma_counts[gy - 1, gx])
                      if gy > by0 or b_ok else None) if gy > 0 else None
                coeffs = cavlc.decode_residual(br, cavlc.luma_nc(na, nb), 16)
                luma16[bi] = coeffs
                pic.luma_counts[gy, gx] = sum(1 for c in coeffs if c)
            else:
                pic.luma_counts[gy, gx] = 0

        chroma_dc = np.zeros((2, 4), np.int32)
        if cbp_chroma > 0:
            for ci in range(2):
                chroma_dc[ci] = cavlc.decode_residual(br, -1, 4)
        chroma_ac = np.zeros((2, 4, 15), np.int32)
        cy0, cx0 = 2 * my, 2 * mx
        for ci in range(2):
            for bi, (bx, by) in enumerate(CHROMA_BLOCK_ORDER):
                gy, gx = cy0 + by, cx0 + bx
                if cbp_chroma == 2:
                    na = (int(pic.chroma_counts[ci, gy, gx - 1])
                          if gx > cx0 or a_ok else None) if gx > 0 else None
                    nb = (int(pic.chroma_counts[ci, gy - 1, gx])
                          if gy > cy0 or b_ok else None) if gy > 0 else None
                    coeffs = cavlc.decode_residual(
                        br, cavlc.luma_nc(na, nb), 15)
                    chroma_ac[ci, bi] = coeffs
                    pic.chroma_counts[ci, gy, gx] = sum(
                        1 for c in coeffs if c)
                else:
                    pic.chroma_counts[ci, gy, gx] = 0

        _recon_p_mb(pic, ref, my, mx, mv, luma16, chroma_dc, chroma_ac, qp)
        pic.decoded += 1
        mi += 1


def decode_annexb(stream: bytes) -> DecodedStream:
    """Decode an Annex-B byte stream produced by this package's encoder."""
    sps: SPS | None = None
    pps: PPS | None = None
    frames: list[Frame] = []
    pic: _Picture | None = None
    ref: _RefFrame | None = None

    def finish_picture() -> None:
        nonlocal pic, ref
        if pic is None:
            return
        if pic.decoded != pic.mbw * pic.mbh:
            raise ValueError(
                f"picture ended with {pic.decoded} of "
                f"{pic.mbw * pic.mbh} MBs decoded (missing slice?)")
        if pic.deblock:
            # §8.7 in-loop filter over the whole decoded picture
            # (shifted-plane schedule, codecs/h264/deblock.py): the
            # filtered planes are both the output frame and the next
            # P picture's reference — exactly the encoder's recon
            # carry. Intra prediction inside the picture already ran
            # on unfiltered samples, as the spec requires.
            from .deblock import deblock_frame

            nz4 = None if pic.intra else (pic.luma_counts > 0)
            pic.y, pic.u, pic.v = deblock_frame(
                pic.y, pic.u, pic.v, pic.qp_mb, intra=pic.intra,
                nz4=nz4, mv=None if pic.intra else pic.mv)
        w, h = sps.width, sps.height
        frames.append(Frame(
            pic.y[:h, :w], pic.u[:h // 2, :w // 2],
            pic.v[:h // 2, :w // 2], pts=len(frames)))
        ref = _RefFrame(pic)                  # next P picture's reference
        pic = None

    for nal_ref_idc, nal_type, rbsp in split_annexb(stream):
        if nal_type == NAL_SPS:
            sps = SPS.parse_rbsp(rbsp)
        elif nal_type == NAL_PPS:
            pps = PPS.parse_rbsp(rbsp)
        elif nal_type in (NAL_SLICE_IDR, NAL_SLICE_NON_IDR):
            if sps is None or pps is None:
                raise ValueError("slice before parameter sets")
            br = BitReader(rbsp)
            header = SliceHeader.parse(br, sps, pps, nal_type, nal_ref_idc)
            if header.slice_type not in (SLICE_TYPE_I, SLICE_TYPE_P):
                raise ValueError(
                    f"unsupported slice type {header.slice_type}")
            if header.deblock_idc == 2:
                raise ValueError(
                    "disable_deblocking_filter_idc == 2 (slice-local "
                    "filtering) not supported; this codec emits 0 or 1")
            if header.first_mb == 0:
                finish_picture()              # new access unit
                pic = _Picture(sps)
            elif pic is None:
                raise ValueError("slice with first_mb != 0 opens a picture")
            pic.intra = header.slice_type == SLICE_TYPE_I
            pic.deblock = pic.deblock or header.deblock_idc == 0
            if header.slice_type == SLICE_TYPE_I:
                _decode_islice(br, pic, header)
            else:
                if ref is None:
                    raise ValueError("P slice without a reference frame")
                _decode_pslice(br, pic, header, ref)
    finish_picture()
    if sps is None:
        raise ValueError("no SPS in stream")
    meta = VideoMeta(width=sps.width, height=sps.height,
                     fps_num=sps.fps_num, fps_den=sps.fps_den,
                     num_frames=len(frames), chroma=ChromaFormat.YUV420,
                     codec="h264", size_bytes=len(stream))
    return DecodedStream(meta=meta, frames=frames)
