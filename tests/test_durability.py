"""Durable state: journal-backed JobStore + file-backed ActivityLog.

The reference survives manager restarts because Redis + the filesystem
are the source of truth (SURVEY.md §5.4); these tests assert the same
contract for the journal: a new coordinator over the same state dir
sees every job, requeues orphaned in-flight work, and keeps activity
history.
"""

import json
import os

from thinvids_tpu.cluster.coordinator import Coordinator
from thinvids_tpu.cluster.jobs import Job, JobStore
from thinvids_tpu.core.events import ActivityLog
from thinvids_tpu.core.status import Status
from thinvids_tpu.core.types import ChromaFormat, VideoMeta


def _meta():
    return VideoMeta(width=64, height=48, num_frames=10, codec="rawvideo",
                     duration_s=0.33, size_bytes=999)


class TestJobJson:
    def test_roundtrip(self):
        job = Job(id="j1", input_path="/x.y4m", meta=_meta(),
                  status=Status.RUNNING, settings={"qp": 30},
                  parts_total=4, parts_done=2, failure_reason="")
        back = Job.from_dict(json.loads(json.dumps(job.to_dict())))
        assert back == job
        assert back.meta.chroma is ChromaFormat.YUV420

    def test_unknown_fields_dropped(self):
        d = Job(id="j2", input_path="/y.y4m").to_dict()
        d["some_future_field"] = 1
        d["meta"] = None
        assert Job.from_dict(d).id == "j2"

    def test_corrupt_status_becomes_failed_not_schedulable(self):
        d = Job(id="j3", input_path="/z.y4m", status=Status.DONE).to_dict()
        d["status"] = "garbage"
        back = Job.from_dict(d)
        assert back.status is Status.FAILED
        assert "corrupt" in back.failure_reason


class TestJobStoreJournal:
    def test_restart_recovers_jobs(self, tmp_path):
        path = str(tmp_path / "jobs.jsonl")
        store = JobStore(path)
        a = store.create("/a.y4m", meta=_meta())
        store.create("/b.y4m")
        store.update(a.id, lambda j: setattr(j, "status", Status.DONE))
        store.close()

        store2 = JobStore(path)
        assert len(store2) == 2
        assert store2.get(a.id).status is Status.DONE
        assert store2.get(a.id).meta == _meta()

    def test_delete_survives_restart(self, tmp_path):
        path = str(tmp_path / "jobs.jsonl")
        store = JobStore(path)
        a = store.create("/a.y4m")
        b = store.create("/b.y4m")
        store.delete(a.id)
        store.close()
        store2 = JobStore(path)
        assert store2.try_get(a.id) is None
        assert store2.get(b.id).input_path == "/b.y4m"

    def test_torn_tail_line_ignored(self, tmp_path):
        path = str(tmp_path / "jobs.jsonl")
        store = JobStore(path)
        store.create("/a.y4m")
        store.close()
        with open(path, "a", encoding="utf-8") as fh:
            fh.write('{"op": "put", "job": {"id": "tr')   # crash mid-write
        store2 = JobStore(path)
        assert len(store2) == 1

    def test_sigkill_truncation_at_every_byte_offset(self, tmp_path):
        """ISSUE 13 satellite: a coordinator SIGKILLed mid-append can
        leave ANY byte prefix of the final record. For every
        truncation point, replay must recover the intact prefix,
        physically truncate the torn tail (an unterminated tail would
        weld the next append onto it and lose BOTH records), and keep
        accepting appends that then survive another restart."""
        ref_path = str(tmp_path / "ref.jsonl")
        ref = JobStore(ref_path)
        ref.create("/a.y4m", job_id="job-a")
        last = ref.create("/b.y4m", job_id="job-b")
        ref.close()
        with open(ref_path, "rb") as fh:
            data = fh.read()
        # byte offset where the final record begins
        body = data.rstrip(b"\n")
        last_start = body.rfind(b"\n") + 1
        for cut in range(last_start, len(data)):
            path = str(tmp_path / f"cut{cut}.jsonl")
            with open(path, "wb") as fh:
                fh.write(data[:cut])      # SIGKILL at byte `cut`
            store = JobStore(path)
            assert store.try_get("job-a") is not None, cut
            # the torn record either vanished (prefix cut) or — when
            # the cut only lost the newline — replayed whole
            survivors = {j.id for j in store.list()}
            assert survivors in ({"job-a"}, {"job-a", "job-b"}), cut
            # appends after recovery round-trip through a restart
            store.create("/c.y4m", job_id="job-c")
            store.close()
            store2 = JobStore(path)
            assert store2.try_get("job-c") is not None, cut
            assert store2.try_get("job-a") is not None, cut
            store2.close()
        assert last.id == "job-b"

    def test_compaction_bounds_journal(self, tmp_path):
        path = str(tmp_path / "jobs.jsonl")
        store = JobStore(path)
        job = store.create("/a.y4m")
        for i in range(1200):
            store.update(job.id, lambda j: setattr(j, "parts_done", i))
        with open(path, encoding="utf-8") as fh:
            assert sum(1 for _ in fh) < 1200
        store.close()
        assert JobStore(path).get(job.id).parts_done == 1199

    def test_second_store_on_same_journal_rejected(self, tmp_path):
        path = str(tmp_path / "jobs.jsonl")
        store = JobStore(path)
        store.create("/a.y4m")
        import pytest as _pytest
        with _pytest.raises(RuntimeError, match="owned"):
            JobStore(path)
        store.close()
        JobStore(path).close()     # released -> ok


class TestActivityPersistence:
    def test_events_survive_restart(self, tmp_path):
        path = str(tmp_path / "activity.jsonl")
        log = ActivityLog(path=path)
        log.emit("start", "hello", job_id="j1")
        log.emit("encode", "part done", job_id="j1", part=3)
        log.close()
        log2 = ActivityLog(path=path)
        events = log2.fetch()
        assert [e["message"] for e in events] == ["part done", "hello"]
        assert log2.fetch_job("j1")
        # appends keep working after replay
        log2.emit("finish", "done", job_id="j1")
        log2.close()
        log3 = ActivityLog(path=path)
        assert log3.fetch()[0]["message"] == "done"
        log3.close()

    def test_cap_truncates_file(self, tmp_path):
        path = str(tmp_path / "activity.jsonl")
        log = ActivityLog(cap=10, path=path)
        for i in range(50):
            log.emit("info", f"e{i}")
        log.close()
        log2 = ActivityLog(cap=10, path=path)
        assert len(log2.fetch(100)) == 10
        with open(path, encoding="utf-8") as fh:
            assert sum(1 for _ in fh) == 10

    def test_runtime_rotation_bounds_file(self, tmp_path):
        path = str(tmp_path / "activity.jsonl")
        log = ActivityLog(cap=10, path=path)
        for i in range(200):                 # >> 4x cap
            log.emit("info", f"e{i}")
        with open(path, encoding="utf-8") as fh:
            assert sum(1 for _ in fh) < 40
        assert log.fetch(5)[0]["message"] == "e199"


class TestCoordinatorRecovery:
    def test_orphaned_running_job_requeued(self, tmp_path):
        state = str(tmp_path / "state")
        co = Coordinator(state_dir=state)
        job = co.store.create("/a.y4m", meta=_meta())
        co.store.update(job.id, lambda j: (
            setattr(j, "status", Status.RUNNING),
            setattr(j, "run_token", "tok")))
        # simulate crash: release handles, new coordinator on same dir
        co.close()
        co2 = Coordinator(state_dir=state)
        assert co2.store.get(job.id).status is Status.RUNNING
        requeued = co2.recover_jobs()
        assert requeued == [job.id]
        j = co2.store.get(job.id)
        assert j.status is Status.WAITING
        assert j.run_token == ""
        assert any("restart" in line.lower() or "requeued" in line.lower()
                   for line in co2.activity.fetch_job(job.id))

    def test_recovery_keeps_progress_for_resume(self, tmp_path):
        """With resume_enabled (the default) the crash requeue keeps
        parts_done/parts_total visible — the resumed run rehydrates
        from the part spool and re-reports from there, so recovery
        must not flap the dashboard to zero."""
        state = str(tmp_path / "state")
        co = Coordinator(state_dir=state)
        job = co.store.create("/a.y4m", meta=_meta())
        co.store.update(job.id, lambda j: (
            setattr(j, "status", Status.RUNNING),
            setattr(j, "run_token", "tok"),
            setattr(j, "parts_total", 8),
            setattr(j, "parts_done", 5)))
        co.close()
        co2 = Coordinator(state_dir=state)
        assert co2.recover_jobs() == [job.id]
        j = co2.store.get(job.id)
        assert j.status is Status.WAITING and j.run_token == ""
        assert (j.parts_done, j.parts_total) == (5, 8)
        assert any("crash-resume" in line
                   for line in co2.activity.fetch_job(job.id))
        co2.close()

    def test_done_jobs_left_alone(self, tmp_path):
        state = str(tmp_path / "state")
        co = Coordinator(state_dir=state)
        job = co.store.create("/a.y4m")
        co.store.update(job.id, lambda j: setattr(j, "status", Status.DONE))
        co.close()
        co2 = Coordinator(state_dir=state)
        assert co2.recover_jobs() == []
        assert co2.store.get(job.id).status is Status.DONE
        assert os.path.exists(os.path.join(state, "jobs.jsonl"))
