"""Observability layer (thinvids_tpu/obs/): metrics registry +
Prometheus exposition, distributed tracing, flight recorder.

Covers the ISSUE 10 acceptance surface:

- ``GET /metrics`` serves VALID Prometheus text exposition (asserted
  by the strict parser below) covering stage, origin, QoS and
  shard-board metrics;
- ``GET /trace/<job>`` exports valid Chrome trace-event JSON whose
  spans nest correctly for a local e2e job, and — for a 2-worker
  remote e2e job over the real HTTP /work protocol — yields ONE trace
  whose coordinator and worker spans share the job's trace id
  (X-Tvt-Trace propagation);
- the flight recorder dumps ``<job>.trace.json`` on an injected shard
  failure (worker quarantine) and on job failure;
- tracing enabled changes no output bytes and its overhead is bounded.
"""

import json
import os
import re
import threading
import time

import numpy as np
import pytest

from thinvids_tpu.cluster import Coordinator, WorkerRegistry
from thinvids_tpu.cluster.executor import LocalExecutor
from thinvids_tpu.core.config import (DEFAULT_SETTINGS, Settings,
                                      reset_live_settings,
                                      update_live_settings)
from thinvids_tpu.core.status import Status
from thinvids_tpu.core.types import VideoMeta
from thinvids_tpu.io.y4m import write_y4m
from thinvids_tpu.obs import flight, trace
from thinvids_tpu.obs.metrics import MetricsRegistry, REGISTRY

import bench


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def make_settings(**over):
    return Settings(values=dict(DEFAULT_SETTINGS, **over))


def clip_frames(w=64, h=48, n=8):
    return bench.make_frames(n, w, h)


def write_clip(path, w=64, h=48, n=8):
    meta = VideoMeta(width=w, height=h, fps_num=30, fps_den=1,
                     num_frames=n)
    write_y4m(str(path), meta, clip_frames(w, h, n))
    return meta


_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (\S+)$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(value: str) -> str:
    return (value.replace(r"\"", '"').replace(r"\n", "\n")
            .replace("\\\\", "\\"))


def _value(text: str) -> float:
    if text == "+Inf":
        return float("inf")
    return float(text)


def parse_prometheus(text):
    """Strict text-exposition parser: every sample line must belong to
    a family announced by # HELP + # TYPE, labels must parse, values
    must be numbers. Returns {family: {"type", "help", "samples":
    [(name, {label: value}, float)]}}."""
    families = {}
    owner = {}
    for line in text.rstrip("\n").split("\n"):
        assert line.strip() == line and line, f"bad line {line!r}"
        if line.startswith("# HELP "):
            _h, name, help_text = line[2:].split(" ", 2)
            families[name] = {"help": help_text, "type": None,
                              "samples": []}
            owner[name] = name
        elif line.startswith("# TYPE "):
            _t, name, kind = line[2:].split(" ", 2)
            assert name in families, f"TYPE before HELP for {name}"
            families[name]["type"] = kind
            if kind == "histogram":
                for suffix in ("_bucket", "_sum", "_count"):
                    owner[name + suffix] = name
        elif line.startswith("#"):
            continue
        else:
            m = _SAMPLE_RE.match(line)
            assert m, f"unparseable sample line {line!r}"
            name, raw_labels, raw_value = m.groups()
            fam = owner.get(name)
            assert fam is not None, f"sample {name} for unknown family"
            labels = {}
            if raw_labels:
                consumed = 0
                for lm in _LABEL_RE.finditer(raw_labels):
                    labels[lm.group(1)] = _unescape(lm.group(2))
                    consumed = lm.end()
                rest = raw_labels[consumed:].strip(", ")
                assert not rest, f"unparsed labels {rest!r} in {line!r}"
            families[fam]["samples"].append(
                (name, labels, _value(raw_value)))
    for name, fam in families.items():
        assert fam["type"] in ("counter", "gauge", "histogram"), name
    return families


def local_rig(tmp_path, snap, workers=8, **executor_kw):
    reg = WorkerRegistry()
    for i in range(workers):
        reg.heartbeat(f"w{i:02d}")
    coord = Coordinator(registry=reg, settings_fn=lambda: snap)
    execu = LocalExecutor(coord, output_dir=str(tmp_path / "lib"),
                          sync=True, **executor_kw)
    coord._launcher = execu.launch
    return coord, execu


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


class TestMetricsRegistry:
    def test_counter_gauge_histogram_render_and_parse(self):
        reg = MetricsRegistry()
        c = reg.counter("t_requests_total", "requests", labels=("route",))
        c.labels("hls").inc()
        c.labels("hls").inc(2)
        g = reg.gauge("t_sessions", "sessions")
        g.set(7)
        h = reg.histogram("t_latency_seconds", "latency",
                          buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        fams = parse_prometheus(reg.render())
        assert fams["t_requests_total"]["type"] == "counter"
        assert ("t_requests_total", {"route": "hls"}, 3.0) \
            in fams["t_requests_total"]["samples"]
        assert ("t_sessions", {}, 7.0) in fams["t_sessions"]["samples"]
        assert fams["t_latency_seconds"]["type"] == "histogram"

    def test_histogram_buckets_monotone_and_inf_equals_count(self):
        reg = MetricsRegistry()
        h = reg.histogram("t_h_seconds", "h", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.005, 0.05, 0.5, 2.0, 9.0):
            h.observe(v)
        fams = parse_prometheus(reg.render())
        samples = fams["t_h_seconds"]["samples"]
        buckets = [(labels["le"], v) for name, labels, v in samples
                   if name.endswith("_bucket")]
        counts = [v for _le, v in buckets]
        assert counts == sorted(counts), "bucket counts must be cumulative"
        count = next(v for name, _l, v in samples
                     if name.endswith("_count"))
        total = next(v for name, _l, v in samples
                     if name.endswith("_sum"))
        assert buckets[-1][0] == "+Inf" and buckets[-1][1] == count == 6
        assert total == pytest.approx(11.56)

    def test_label_escaping_roundtrips(self):
        reg = MetricsRegistry()
        g = reg.gauge("t_esc", "esc", labels=("path",))
        nasty = 'a"b\\c\nd'
        g.labels(nasty).set(1)
        fams = parse_prometheus(reg.render())
        (_name, labels, value), = fams["t_esc"]["samples"]
        assert labels["path"] == nasty and value == 1.0

    def test_conflicting_redeclaration_raises(self):
        reg = MetricsRegistry()
        reg.counter("t_x_total", "x")
        assert reg.counter("t_x_total", "x") is reg.get("t_x_total")
        with pytest.raises(ValueError):
            reg.gauge("t_x_total", "x")
        with pytest.raises(ValueError):
            reg.counter("t_x_total", "x", labels=("a",))


# ---------------------------------------------------------------------------
# trace store
# ---------------------------------------------------------------------------


class TestTraceStore:
    def test_ring_bound_honors_trace_ring_spans(self):
        update_live_settings({"trace_ring_spans": 256})
        try:
            store = trace.TraceStore()
            store.start("jring")
            for i in range(300):
                store.record_span("jring", "s", t0=float(i), dur_s=0.01)
            snap = store.snapshot("jring")
            assert len(snap["spans"]) == 256
            # oldest evicted, newest kept
            assert snap["spans"][-1]["t0"] == 299.0
        finally:
            reset_live_settings()

    def test_trace_sample_zero_records_nothing(self):
        update_live_settings({"trace_sample": 0.0})
        try:
            store = trace.TraceStore()
            assert store.start("joff") == ""
            rec = store.recorder("joff")
            assert not rec.enabled
            with rec.span("anything"):
                pass
            assert store.snapshot("joff")["spans"] == []
        finally:
            reset_live_settings()

    def test_ingest_drops_stale_trace_id(self):
        store = trace.TraceStore()
        tid = store.start("jr")
        wire = [{"name": "w", "t0": 1.0, "dur_s": 0.5,
                 "tags": {"k": 1}}]
        assert store.ingest("jr", "not-the-trace", wire) == 0
        assert store.ingest("jr", tid, wire, host="w00") == 1
        span = store.snapshot("jr")["spans"][0]
        assert span["host"] == "w00" and span["tags"] == {"k": 1}

    def test_export_chrome_shape(self):
        store = trace.TraceStore()
        tid = store.start("jx")
        rec = store.recorder("jx", host="h1")
        with rec.span("outer", wave=0):
            with rec.span("inner"):
                pass
        doc = store.export_chrome("jx")
        events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in events} == {"outer", "inner"}
        for e in events:
            assert isinstance(e["ts"], int) and e["dur"] >= 1
            assert e["args"]["trace_id"] == tid
        metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        assert any(e["name"] == "process_name"
                   and e["args"]["name"] == "h1" for e in metas)
        assert doc["otherData"]["trace_id"] == tid

    def test_eviction_is_lru_by_activity_not_start_order(self):
        """A long-running job that keeps recording must survive 64+
        later dispatches; the idle completed jobs age out instead."""
        store = trace.TraceStore()
        store.start("long-runner")
        for i in range(trace.MAX_JOBS - 1):
            store.start(f"short-{i}")
            # the long job records between other dispatches (activity)
            store.record_span("long-runner", "wave", t0=float(i),
                              dur_s=0.1)
        store.start("one-more")        # evicts the LRU entry
        assert store.snapshot("long-runner") is not None
        assert store.snapshot("short-0") is None

    def test_restart_gets_fresh_trace_and_drops_straggler_spans(self):
        store = trace.TraceStore()
        old = store.start("j2")
        new = store.start("j2")
        assert old != new
        assert store.ingest(
            "j2", old, [{"name": "stale", "t0": 1.0, "dur_s": 1.0}]) == 0
        assert store.trace_id("j2") == new

    def test_bind_exposes_ids_to_current_thread(self):
        assert trace.current_ids() is None
        with trace.bind("jobX", "traceY"):
            assert trace.current_ids() == ("jobX", "traceY")
        assert trace.current_ids() is None


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------


class TestKnobs:
    def test_clamps(self):
        try:
            applied = update_live_settings({
                "trace_ring_spans": 1, "trace_sample": 7.5,
                "metrics_enabled": "0", "flight_record": "no"})
            assert applied["trace_ring_spans"] == 256
            assert applied["trace_sample"] == 1.0
            assert applied["metrics_enabled"] is False
            assert applied["flight_record"] is False
            applied = update_live_settings({"trace_ring_spans": 10 ** 9})
            assert applied["trace_ring_spans"] == 65536
        finally:
            reset_live_settings()

    def test_metrics_endpoint_gated_by_metrics_enabled(self):
        from thinvids_tpu.api.server import ApiError, ApiServer

        coord = Coordinator(
            settings_fn=lambda: make_settings(metrics_enabled=False))
        api = ApiServer(coord)
        with pytest.raises(ApiError) as ei:
            api.route("GET", "/metrics", {}, {})
        assert ei.value.status == 404


# ---------------------------------------------------------------------------
# JSON log mode
# ---------------------------------------------------------------------------


class TestJsonLogs:
    def _record(self, msg="hello"):
        import logging

        return logging.LogRecord("thinvids_tpu.test", logging.INFO,
                                 __file__, 1, msg, None, None)

    def test_json_formatter_emits_one_object_with_trace_ids(self):
        from thinvids_tpu.core.log import JsonFormatter

        fmt = JsonFormatter("hostA")
        doc = json.loads(fmt.format(self._record()))
        assert doc["msg"] == "hello" and doc["host"] == "hostA"
        assert doc["level"] == "INFO" and "job_id" not in doc
        with trace.bind("jobJ", "traceT"):
            doc = json.loads(fmt.format(self._record("in job")))
        assert doc["job_id"] == "jobJ" and doc["trace_id"] == "traceT"

    def test_env_selects_json_formatter(self, monkeypatch):
        from thinvids_tpu.core.log import JsonFormatter, _make_formatter

        monkeypatch.setenv("TVT_LOG_FORMAT", "json")
        assert isinstance(_make_formatter("h"), JsonFormatter)
        monkeypatch.delenv("TVT_LOG_FORMAT")
        assert not isinstance(_make_formatter("h"), JsonFormatter)


# ---------------------------------------------------------------------------
# local e2e: trace + metrics through the production pipeline
# ---------------------------------------------------------------------------


def _assert_spans_nest(doc):
    """Chrome events on one (pid, tid) must nest by containment (a
    child never straddles its parent's end) — 1 ms tolerance for the
    independent float→µs truncations of start and duration."""
    by_thread = {}
    for e in doc["traceEvents"]:
        if e["ph"] != "X":
            continue
        by_thread.setdefault((e["pid"], e["tid"]), []).append(e)
    tol = 1000
    for events in by_thread.values():
        events.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []
        for e in events:
            while stack and e["ts"] >= stack[-1]["ts"] \
                    + stack[-1]["dur"] - tol:
                stack.pop()
            if stack:
                parent = stack[-1]
                assert e["ts"] + e["dur"] <= parent["ts"] \
                    + parent["dur"] + tol, \
                    (f"span {e['name']} straddles "
                     f"{parent['name']}'s end")
            stack.append(e)


class TestLocalE2E:
    def test_local_job_yields_one_nested_trace_and_metrics(self, tmp_path):
        from thinvids_tpu.api.server import ApiServer

        clip = tmp_path / "clip.y4m"
        meta = write_clip(clip, n=8)
        snap = make_settings(gop_frames=2, qp=30,
                             heartbeat_throttle_s=0.0)
        coord, _execu = local_rig(tmp_path, snap)
        job = coord.add_job(str(clip), meta)
        job = coord.store.get(job.id)
        assert job.status is Status.DONE, job.failure_reason

        api = ApiServer(coord)
        status, doc = api.route("GET", f"/trace/{job.id}", {}, {})
        assert status == 200
        json.dumps(doc)                       # valid JSON document
        events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert events, "local job recorded no spans"
        names = {e["name"] for e in events}
        # the pipeline stages + per-wave spans all landed
        for want in ("decode", "stage", "dispatch", "device_wait",
                     "fetch", "pack", "concat", "wave_collect",
                     "wave_dispatch"):
            assert want in names, f"missing span {want}"
        # ONE trace id across every span
        assert {e["args"]["trace_id"] for e in events} \
            == {doc["otherData"]["trace_id"]}
        _assert_spans_nest(doc)

        # /metrics: valid exposition covering stage, origin, QoS and
        # shard-board families (the parser is strict)
        status, text = api.route("GET", "/metrics", {}, {})
        assert status == 200
        fams = parse_prometheus(text.body.decode("utf-8"))
        stage = fams["tvt_stage_seconds_total"]
        assert stage["type"] == "counter"
        stages_seen = {labels["stage"]
                       for _n, labels, v in stage["samples"] if v > 0}
        assert {"dispatch", "device_wait", "pack"} <= stages_seen
        assert fams["tvt_origin_requests_total"]["type"] == "counter"
        assert fams["tvt_qos_breaches_total"]["type"] == "counter"
        assert fams["tvt_qos_preempting"]["type"] == "gauge"
        board = fams["tvt_shard_board_shards"]
        assert {labels["state"] for _n, labels, _v
                in board["samples"]} >= {"pending", "assigned", "done"}
        jobs = {labels["status"]: v
                for _n, labels, v in fams["tvt_jobs"]["samples"]}
        assert jobs["done"] >= 1
        hist = fams["tvt_sfe_frame_latency_seconds"]
        assert hist["type"] == "histogram"

    def test_unsampled_job_returns_404_trace(self, tmp_path):
        from thinvids_tpu.api.server import ApiError, ApiServer

        clip = tmp_path / "clip.y4m"
        meta = write_clip(clip, n=4)
        update_live_settings({"trace_sample": 0.0})
        try:
            snap = make_settings(gop_frames=2, qp=30,
                                 heartbeat_throttle_s=0.0)
            coord, _execu = local_rig(tmp_path, snap)
            job = coord.add_job(str(clip), meta)
            job = coord.store.get(job.id)
            assert job.status is Status.DONE, job.failure_reason
            api = ApiServer(coord)
            with pytest.raises(ApiError) as ei:
                api.route("GET", f"/trace/{job.id}", {}, {})
            assert ei.value.status == 404
        finally:
            reset_live_settings()


# ---------------------------------------------------------------------------
# remote e2e: 2 workers over the real HTTP /work protocol
# ---------------------------------------------------------------------------


class TestRemoteTrace:
    def test_two_worker_farm_job_yields_one_coherent_trace(self, tmp_path):
        from thinvids_tpu.api.server import ApiServer
        from thinvids_tpu.cluster.remote import RemoteExecutor, WorkerDaemon

        clip = tmp_path / "clip.y4m"
        meta = write_clip(clip, n=16)
        snap = make_settings(gop_frames=2, qp=30,
                             heartbeat_throttle_s=0.0,
                             remote_plan_devices=8, remote_shard_gops=2,
                             remote_no_worker_grace_s=30.0,
                             min_idle_workers=0)
        reg = WorkerRegistry()
        hosts = ("tw00", "tw01")
        for host in hosts:
            reg.heartbeat(host, metrics={"worker": True})
        coord = Coordinator(registry=reg, settings_fn=lambda: snap)
        execu = RemoteExecutor(coord, output_dir=str(tmp_path / "lib"),
                               sync=True, poll_s=0.02)
        coord._launcher = execu.launch
        api = ApiServer(coord, work=execu.board).start()
        stop = threading.Event()
        daemons = [WorkerDaemon(api.url, host=host, poll_s=0.02)
                   for host in hosts]
        threads = [threading.Thread(target=d.run_forever, args=(stop,),
                                    daemon=True) for d in daemons]
        for t in threads:
            t.start()
        try:
            job = coord.add_job(str(clip), meta)
            job = coord.store.get(job.id)
            assert job.status is Status.DONE, job.failure_reason
            # worker span uploads are best-effort async after the last
            # part lands — wait for both hosts' spans to arrive
            deadline = time.time() + 20
            while time.time() < deadline:
                snap_t = trace.TRACE.snapshot(job.id)
                span_hosts = {s["host"] for s in snap_t["spans"]}
                if set(hosts) <= span_hosts:
                    break
                time.sleep(0.05)
            status, doc = api.route("GET", f"/trace/{job.id}", {}, {})
        finally:
            stop.set()
            for t in threads:
                t.join(5)
            api.stop()
        events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        trace_id = doc["otherData"]["trace_id"]
        # ONE trace id on every span, coordinator and workers alike
        assert {e["args"]["trace_id"] for e in events} == {trace_id}
        names = {e["name"] for e in events}
        assert "shard" in names, "coordinator-side shard spans missing"
        assert "worker_shard" in names and "upload_part" in names, \
            "worker-side spans missing"
        # worker stage clocks (encode internals) rode along too
        assert "pack" in names and "device_wait" in names
        pid_names = {e["args"]["name"]
                     for e in doc["traceEvents"]
                     if e["ph"] == "M" and e["name"] == "process_name"}
        assert set(hosts) <= pid_names


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_artifact_on_injected_shard_failure_quarantine(self, tmp_path):
        """Two injected consecutive shard failures quarantine the
        worker — the job's flight record must land as
        <job>.trace.json with the shard errors inside."""
        from thinvids_tpu.cluster.remote import Shard, ShardBoard
        from thinvids_tpu.core.types import GopSpec

        snap = make_settings(pipeline_worker_count=0, min_idle_workers=0)
        reg = WorkerRegistry()
        reg.heartbeat("bad-worker", metrics={"worker": True})
        coord = Coordinator(registry=reg, settings_fn=lambda: snap)
        flight.configure(str(tmp_path))
        board = ShardBoard(coord)
        trace.TRACE.start("jobq")
        meta = VideoMeta(width=64, height=48, fps_num=30, fps_den=1,
                         num_frames=4)
        shard = Shard(id="jobq-0000", job_id="jobq", input_path="x.y4m",
                      meta=meta,
                      gops=(GopSpec(index=0, start_frame=0,
                                    num_frames=2),),
                      qp=30, gop_frames=2, timeout_s=100.0)
        board.add_job("jobq", [shard], max_attempts=5, backoff_s=0.0,
                      quarantine_after=2)
        for _ in range(2):
            desc = board.claim("bad-worker")
            assert desc is not None
            board.report_failure(desc["id"], "bad-worker", "injected")
        assert coord.registry.all()[0].disabled
        path = tmp_path / "jobq.trace.json"
        assert path.exists(), "flight record not written on quarantine"
        doc = json.loads(path.read_text())
        other = doc["otherData"]
        assert "quarantined" in other["reason"]
        assert any("injected" in e["message"] for e in other["errors"])
        assert "settings" in other and "traceEvents" in doc

    def test_artifact_on_job_failure_with_settings_and_errors(
            self, tmp_path):
        clip = tmp_path / "clip.y4m"
        meta = write_clip(clip, n=4)
        snap = make_settings(gop_frames=2, qp=30,
                             heartbeat_throttle_s=0.0)

        def broken_factory(_meta, _settings, _mesh):
            raise RuntimeError("injected encoder failure")

        coord, _execu = local_rig(tmp_path, snap,
                                  encoder_factory=broken_factory)
        job = coord.add_job(str(clip), meta)
        job = coord.store.get(job.id)
        assert job.status is Status.FAILED
        path = tmp_path / "lib" / f"{job.id}.trace.json"
        assert path.exists(), "flight record not written on job failure"
        doc = json.loads(path.read_text())
        other = doc["otherData"]
        assert "injected encoder failure" in other["reason"]
        assert any("injected encoder failure" in e["message"]
                   for e in other["errors"])
        assert other["settings"]["gop_frames"] == 2

    def test_unsampled_job_still_dumps_errors_and_settings(self, tmp_path):
        """flight_record is an independent gate from trace_sample: a
        sampled-out job's postmortem still dumps (error ring +
        settings, empty traceEvents)."""
        flight.configure(str(tmp_path))
        update_live_settings({"trace_sample": 0.0})
        try:
            assert trace.TRACE.start("junsamp") == ""
            trace.TRACE.record_error("junsamp", "it broke")
            path = flight.record("junsamp", reason="failure",
                                 settings=make_settings(qp=33))
        finally:
            reset_live_settings()
        assert path and os.path.exists(path)
        doc = json.loads(open(path).read())
        assert [e for e in doc["traceEvents"] if e.get("ph") == "X"] \
            == []
        assert any("it broke" in e["message"]
                   for e in doc["otherData"]["errors"])
        assert doc["otherData"]["settings"]["qp"] == 33

    def test_flight_record_gate_off_writes_nothing(self, tmp_path):
        flight.configure(str(tmp_path))
        trace.TRACE.start("jgate")
        update_live_settings({"flight_record": False})
        try:
            assert flight.record("jgate", reason="x") is None
        finally:
            reset_live_settings()
        assert not (tmp_path / "jgate.trace.json").exists()


# ---------------------------------------------------------------------------
# parity + overhead
# ---------------------------------------------------------------------------


class TestTracingParity:
    def test_tracing_changes_no_output_bytes(self):
        from thinvids_tpu.core.types import concat_segments
        from thinvids_tpu.parallel.dispatch import GopShardEncoder

        frames = clip_frames(n=8)
        meta = VideoMeta(width=64, height=48, fps_num=30, fps_den=1,
                         num_frames=8)
        enc = GopShardEncoder(meta, qp=30, gop_frames=2)
        baseline = concat_segments(enc.encode(frames))
        trace.TRACE.start("parity-job")
        enc.stages.set_tracer(trace.TRACE.recorder("parity-job"))
        try:
            traced = concat_segments(enc.encode(frames))
        finally:
            enc.stages.set_tracer(None)
        assert traced == baseline
        spans = trace.TRACE.snapshot("parity-job")["spans"]
        assert spans, "tracer was bound but recorded nothing"
        trace.TRACE.drop("parity-job")

    def test_overhead_guard(self):
        """Loose CI-safe bound — the honest <3% gate is the BENCH's
        trace_overhead_pct on the driver's 1080p run; this guard
        catches only a catastrophic regression (spans on the per-MB
        path instead of the per-stage path, a lock convoy, ...)."""
        r = bench._run_trace_overhead(64, 48, nframes=8, qp=27,
                                      gop_frames=2, runs=3)
        assert r["sampled"] is True
        assert r["overhead_pct"] < 50.0, r


# ---------------------------------------------------------------------------
# snapshot percentiles (satellite: frame_latencies_ms p50/p99)
# ---------------------------------------------------------------------------


class TestSfeLatencyPercentiles:
    def test_metrics_snapshot_carries_sfe_percentiles(self, tmp_path):
        from thinvids_tpu.api.server import ApiServer
        from thinvids_tpu.core.types import concat_segments
        from thinvids_tpu.parallel.dispatch import SfeShardEncoder

        meta = VideoMeta(width=64, height=96, fps_num=30, fps_den=1,
                         num_frames=6)
        enc = SfeShardEncoder(meta, qp=30, gop_frames=3, bands=2)
        concat_segments(enc.encode(clip_frames(64, 96, 6)))
        assert len(enc.frame_latencies_ms()) >= 4
        coord = Coordinator(settings_fn=lambda: make_settings())
        api = ApiServer(coord)
        _status, out = api.route("GET", "/metrics_snapshot", {}, {})
        pct = out["sfe_latency_ms"]
        assert pct["count"] >= 4
        assert pct["p99_ms"] >= pct["p50_ms"] > 0
