"""JAX/TPU inter-frame (P) encode compute: motion search, motion
compensation, residual transform/quant, closed-loop reconstruction.

Replaces the inter coding half of the reference's ffmpeg encode op point
(/root/reference/worker/tasks.py:1558-1586). TPU-shaped design:

- Motion estimation + compensation are ONE Pallas kernel pass per frame
  (codecs/h264/jaxme.py): MXU-matmul SAD over static candidate windows
  around dynamically re-anchored centers, half-pel 6-tap interpolation,
  and a running per-MB best-(cost, mv, pred) select — the kernel emits
  the final prediction planes, so MC never runs as a separate pass.
  MVs are HALF-PEL units throughout.
- Residual DCT/quant/dequant/IDCT run in PLANE layout: 4x4 butterflies
  as strided slices along H then W of the full frame — no (n, 16, 4, 4)
  relayout in the hot loop, int16 storage.
- Frames chain through a `lax.scan` carry holding the recon planes and
  the previous frame's median MV (the EPZS temporal predictor collapsed
  to its frame mode, as one search center).

The sequential P-slice entropy pack (skip runs, mvp/mvd, CBP) stays on
host: codecs/h264/inter.py.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from .jaxcore import (
    _MF,
    _QPC,
    _V,
    _ZZ,
    _ZSCAN,
    _intra_core,
    _mode_tail,
    _varying_zero,
)
from . import jaxdeblock, jaxme, rdo
from .rdo import RD_OFF

SEARCH_RANGE = jaxme.SEARCH_RANGE      # integer-pel, each direction


# ---------------------------------------------------------------------------
# plane-layout 4x4 transforms (bit-exact ports of jaxcore._fwd4/_inv4,
# applied to whole (H, W) planes via length-4 strided butterflies)
# ---------------------------------------------------------------------------

def _fwd4_axis0(x):
    """Forward core transform along H (rows of each 4x4 block)."""
    H, W = x.shape
    v = x.reshape(H // 4, 4, W)
    a, b, c, d = v[:, 0], v[:, 1], v[:, 2], v[:, 3]
    s0, s3 = a + d, a - d
    s1, s2 = b + c, b - c
    return jnp.stack(
        [s0 + s1, 2 * s3 + s2, s0 - s1, s3 - 2 * s2], axis=1
    ).reshape(H, W)


def _fwd4_axis1(x):
    """Forward core transform along W (columns of each 4x4 block)."""
    H, W = x.shape
    v = x.reshape(H, W // 4, 4)
    a, b, c, d = v[..., 0], v[..., 1], v[..., 2], v[..., 3]
    s0, s3 = a + d, a - d
    s1, s2 = b + c, b - c
    return jnp.stack(
        [s0 + s1, 2 * s3 + s2, s0 - s1, s3 - 2 * s2], axis=-1
    ).reshape(H, W)


def _fwd4_plane(x):
    """W = CF @ x @ CF^T per 4x4 block, plane layout (H then W — same
    order as jaxcore._fwd4's einsum)."""
    return _fwd4_axis1(_fwd4_axis0(x))


def _inv4_axis1(d):
    H, W = d.shape
    v = d.reshape(H, W // 4, 4)
    d0, d1, d2, d3 = v[..., 0], v[..., 1], v[..., 2], v[..., 3]
    e0, e1 = d0 + d2, d0 - d2
    e2, e3 = (d1 >> 1) - d3, d1 + (d3 >> 1)
    return jnp.stack([e0 + e3, e1 + e2, e1 - e2, e0 - e3],
                     axis=-1).reshape(H, W)


def _inv4_axis0(f):
    H, W = f.shape
    v = f.reshape(H // 4, 4, W)
    g0, g1, g2, g3 = v[:, 0], v[:, 1], v[:, 2], v[:, 3]
    h0, h1 = g0 + g2, g0 - g2
    h2, h3 = (g1 >> 1) - g3, g1 + (g3 >> 1)
    return jnp.stack([h0 + h3, h1 + h2, h1 - h2, h0 - h3],
                     axis=1).reshape(H, W)


def _inv4_plane(d):
    """Inverse core transform, plane layout (W then H — exactly
    jaxcore._inv4's stage order, which matters for the >>1 rounding)."""
    return _inv4_axis0(_inv4_axis1(d))


def _tile_plane(tbl, H, W):
    """Tile a (4, 4) per-coefficient table over an (H, W) plane."""
    return jnp.tile(tbl, (H // 4, W // 4))


def _quant_plane(w, mf_plane, qp):
    """Quantize an INTER coefficient plane with the f = (1 << qbits) / 6
    rounding bias (over-rounding inter residuals inflates levels and
    bitrate; the intra paths in jaxcore keep the standard 1/3)."""
    qbits = 15 + qp // 6
    f = (1 << qbits) // 6
    z = (jnp.abs(w) * mf_plane + f) >> qbits
    return jnp.where(w < 0, -z, z)


def _dequant_plane(z, v_plane, qp):
    return (z * v_plane) << (qp // 6)


# ---------------------------------------------------------------------------
# P-frame residual coding in plane layout
# (motion search + compensation live in jaxme.me_search)
# ---------------------------------------------------------------------------

def _dc_mask(H, W):
    m = np.ones((4, 4), np.int16)
    m[0, 0] = 0
    return jnp.asarray(np.tile(m, (H // 4, W // 4)))


def _luma_plane_to_blocks(z, mbw: int, mbh: int):
    """(H, W) coeff plane → (nmb, 16, 16) z-scan blocks of zigzag
    coeffs (the packer's layout)."""
    x = z.reshape(mbh, 4, 4, mbw, 4, 4).transpose(0, 3, 1, 4, 2, 5)
    x = x.reshape(mbh * mbw, 16, 16)
    return x[:, _ZSCAN][..., _ZZ]


def _chroma_plane_to_blocks(z, mbw: int, mbh: int):
    """(H/2, W/2) coeff plane → (nmb, 4, 16) raster blocks of zigzag
    coeffs."""
    x = z.reshape(mbh, 2, 4, mbw, 2, 4).transpose(0, 3, 1, 4, 2, 5)
    x = x.reshape(mbh * mbw, 4, 16)
    return x[..., _ZZ]


def _dc_pos_expand(dcr_grid, h, wd_):
    """Place a (h/4, wd_/4) grid at the (0, 0) position of every 4x4
    block of an (h, wd_) zero plane — an outer-product broadcast, not a
    scatter (the .at[::4, ::4].set lowering measured ~2 ms/frame)."""
    m4 = jnp.zeros((4, 4), dcr_grid.dtype).at[0, 0].set(1)
    out = dcr_grid[:, None, :, None] * m4[None, :, None, :]
    return out.reshape(h, wd_)


def _encode_p_plane(cy, cu, cv, ry, ru, rv, pred_mv, qp, qpc, *, mbw: int,
                    mbh: int, blocked: bool = True, rd=RD_OFF):
    """One P frame given previous recon planes (int16). `pred_mv` is the
    previous frame's median MV in half-pel units (a search center).

    `blocked=True` returns level arrays in the host packer's blocked
    layout (the conformance/host path). `blocked=False` skips the
    device-side relayout entirely and returns raw coefficient PLANES —
    the sharded transfer path's format; the relayout then happens on
    host inside the pack pool (measured: the blocked transposes +
    zigzag gathers cost ~0.5 s per 1080p GOP on a v5e chip, twice the
    rest of the GOP's compute).
    """
    n = mbw * mbh
    cy16 = cy.astype(jnp.int16)
    cu16 = cu.astype(jnp.int16)
    cv16 = cv.astype(jnp.int16)

    mv, pred_y, pred_u, pred_v, med_mv = jaxme.me_search(
        cy16, ry, ru, rv, pred_mv, qp.astype(jnp.int32))

    (luma_levels, chroma_dc, chroma_ac, recon_y, recon_u, recon_v,
     nz4) = _residual_p(cy16, cu16, cv16, pred_y, pred_u, pred_v, qp,
                        qpc, mbw=mbw, mbh=mbh, blocked=blocked, rd=rd)
    if rd.deblock:
        qp_map = jnp.broadcast_to(qp.astype(jnp.int32), (mbh, mbw))
        recon_y, recon_u, recon_v = jaxdeblock.deblock_frame_jax(
            recon_y, recon_u, recon_v, qp_map, intra=False, nz4=nz4,
            mv=mv)
    return (mv.reshape(n, 2), luma_levels, chroma_dc, chroma_ac,
            recon_y, recon_u, recon_v, med_mv)


def _residual_p(cy16, cu16, cv16, pred_y, pred_u, pred_v, qp, qpc, *,
                mbw: int, mbh: int, blocked: bool = True, rd=RD_OFF):
    """Residual transform/quant/recon for one P frame given its
    prediction planes — the motion-search-free half of
    :func:`_encode_p_plane`, split out so the banded (SFE) path can
    pair it with `jaxme.me_search_banded`. Per-MB local math only: no
    cross-MB (or cross-band) dependencies.

    With ``rd.pskip`` an MB whose quantized residual is negligible
    (sum |level| <= rdo.PSKIP_SUM across all planes, every |level| <=
    1) drops the residual entirely: its recon becomes pure prediction
    — exactly what a decoder reconstructs for a P_Skip MB — and the
    entropy packer's §8.4.1.1 inference turns it into a skip run
    whenever its MV matches the skip predictor.

    Also returns nz4, the (4·mbh, 4·mbw) any-nonzero map of the FINAL
    luma levels (the deblocking filter's bS=2 input)."""
    H, W = cy16.shape
    n = mbw * mbh
    qp32 = qp.astype(jnp.int32)
    mf_y = _tile_plane(_MF[qp32 % 6], H, W)
    v_y = _tile_plane(_V[qp32 % 6], H, W)
    mf_c = _tile_plane(_MF[qpc % 6], H // 2, W // 2)
    v_c = _tile_plane(_V[qpc % 6], H // 2, W // 2)

    # --- quantize: luma plane + both chroma planes -------------------
    resid = (cy16 - pred_y).astype(jnp.int32)
    w = _fwd4_plane(resid)
    z = _quant_plane(w, mf_y, qp32)

    def chroma_quant(cplane16, pred):
        h, wd_ = cplane16.shape
        resid = (cplane16 - pred).astype(jnp.int32)
        wch = _fwd4_plane(resid)
        dc = wch[::4, ::4]                               # (2*mbh, 2*mbw)
        g = dc.reshape(mbh, 2, mbw, 2)
        a, b = g[:, 0, :, 0], g[:, 0, :, 1]
        c, dd = g[:, 1, :, 0], g[:, 1, :, 1]
        wd2 = jnp.stack([a + b + c + dd, a - b + c - dd,
                         a + b - c - dd, a - b - c + dd], axis=-1)
        # chroma DC quant (jaxcore._chroma_dc_quant with the inter
        # rounding bias)
        qbits = 15 + qpc // 6
        f = (1 << qbits) // 6
        mf00 = _MF[qpc % 6, 0, 0]
        zdc = (jnp.abs(wd2) * mf00 + 2 * f) >> (qbits + 1)
        zdc = jnp.where(wd2 < 0, -zdc, zdc)              # (mbh, mbw, 4)
        # AC quant with DC positions zeroed
        zac = _quant_plane(wch, mf_c, qpc) * _dc_mask(h, wd_)
        return zdc, zac

    u_zdc, u_zac = chroma_quant(cu16, pred_u)
    v_zdc, v_zac = chroma_quant(cv16, pred_v)

    if rd.pskip:
        # P_Skip bias: per-MB level mass across every plane
        zb = z.reshape(mbh, 16, mbw, 16)
        az = jnp.abs(zb)
        def cmass(zac):
            c = jnp.abs(zac.reshape(mbh, 8, mbw, 8))
            return c.sum(axis=(1, 3)), c.max(axis=(1, 3))
        us, umx = cmass(u_zac)
        vs, vmx = cmass(v_zac)
        mb_sum = (az.sum(axis=(1, 3)) + us + vs
                  + jnp.abs(u_zdc).sum(axis=-1) + jnp.abs(v_zdc).sum(-1))
        mb_max = jnp.maximum(
            jnp.maximum(az.max(axis=(1, 3)), jnp.maximum(umx, vmx)),
            jnp.maximum(jnp.abs(u_zdc).max(-1), jnp.abs(v_zdc).max(-1)))
        drop = (mb_sum <= rdo.PSKIP_SUM) & (mb_max <= 1)   # (mbh, mbw)
        keep_y = ~jnp.repeat(jnp.repeat(drop, 16, 0), 16, 1)
        keep_c = ~jnp.repeat(jnp.repeat(drop, 8, 0), 8, 1)
        z = jnp.where(keep_y.reshape(H, W), z, 0)
        u_zac = jnp.where(keep_c, u_zac, 0)
        v_zac = jnp.where(keep_c, v_zac, 0)
        u_zdc = jnp.where(drop[..., None], 0, u_zdc)
        v_zdc = jnp.where(drop[..., None], 0, v_zdc)

    nz4 = jaxdeblock.nz4_from_luma_plane(z, mbh, mbw)

    # --- reconstruct from the (possibly zeroed) levels ---------------
    d = _dequant_plane(z, v_y, qp32)
    recon_y = jnp.clip((_inv4_plane(d) + 32 >> 6) + pred_y, 0, 255
                       ).astype(jnp.int16)
    if blocked:
        luma_levels = _luma_plane_to_blocks(z.astype(jnp.int16), mbw, mbh
                                            ).astype(jnp.int32)
    else:
        luma_levels = z.astype(jnp.int16)               # (H, W) coeff plane

    def chroma_recon(pred, zdc, zac):
        h, wd_ = pred.shape
        # recon: dequant AC, reinsert dequantized DC, inverse
        dac = _dequant_plane(zac, v_c, qpc)
        z00, z01 = zdc[..., 0], zdc[..., 1]
        z10, z11 = zdc[..., 2], zdc[..., 3]
        f00 = z00 + z01 + z10 + z11
        f01 = z00 - z01 + z10 - z11
        f10 = z00 + z01 - z10 - z11
        f11 = z00 - z01 - z10 + z11
        ls = _V[qpc % 6, 0, 0] * 16
        fdc = jnp.stack([jnp.stack([f00, f01], -1),
                         jnp.stack([f10, f11], -1)], -2)  # (mbh,mbw,2,2)
        dcr = ((fdc * ls) << (qpc // 6)) >> 5
        dcr_grid = dcr.transpose(0, 2, 1, 3).reshape(2 * mbh, 2 * mbw)
        # zac zeroes every DC position, so dequantized DC re-enters as
        # an add of an expanded grid — no scatter.
        dfull = dac + _dc_pos_expand(dcr_grid, h, wd_)
        rec = jnp.clip((_inv4_plane(dfull) + 32 >> 6) + pred, 0, 255
                       ).astype(jnp.int16)
        if blocked:
            ac = _chroma_plane_to_blocks(zac.astype(jnp.int16), mbw, mbh
                                         )[..., 1:].astype(jnp.int32)
        else:
            ac = zac.astype(jnp.int16)                  # (H/2, W/2) plane
        dc_lev = zdc.reshape(n, 4)
        return dc_lev, ac, rec

    udc, uac, recon_u = chroma_recon(pred_u, u_zdc, u_zac)
    vdc, vac, recon_v = chroma_recon(pred_v, v_zdc, v_zac)
    if blocked:
        chroma_dc = jnp.stack([udc, vdc], axis=1)        # (n, 2, 4)
        chroma_ac = jnp.stack([uac, vac], axis=1)        # (n, 2, 4, 15)
    else:
        chroma_dc = jnp.stack([udc, vdc]).astype(jnp.int16)  # (2, n, 4)
        chroma_ac = jnp.stack([uac, vac])                # (2, H/2, W/2)

    return (luma_levels, chroma_dc, chroma_ac, recon_y, recon_u, recon_v,
            nz4)


def _intra_frame_outputs(y, u, v, qp, *, mbw: int, mbh: int, rd):
    """Shared IDR half of the GOP programs: intra core + (optionally)
    deblocked recon carry + the pack-facing intra tuple (4 blocked
    arrays, or 6 with the per-MB [mode16 | dqp16] side channel when
    rd.ships_modes)."""
    out = _intra_core(y, u, v, qp, mbw=mbw, mbh=mbh, rd=rd)
    il_dc, il_ac, ic_dc, ic_ac, ry, ru, rv = out[:7]
    luma_mode, chroma_mode, qp_delta = out[7:]
    ry = ry.astype(jnp.int16)
    ru = ru.astype(jnp.int16)
    rv = rv.astype(jnp.int16)
    if rd.deblock:
        qp_map = (qp.astype(jnp.int32) + qp_delta).reshape(mbh, mbw)
        ry, ru, rv = jaxdeblock.deblock_frame_jax(
            ry, ru, rv, qp_map, intra=True)
    if rd.ships_modes:
        tail = _mode_tail(luma_mode, chroma_mode, qp_delta)
        intra = (il_dc, il_ac, ic_dc, ic_ac,
                 tail[:mbw * mbh], tail[mbw * mbh:])
    else:
        intra = (il_dc, il_ac, ic_dc, ic_ac)
    return intra, (ry, ru, rv)


@functools.partial(jax.jit,
                   static_argnames=("mbw", "mbh", "emit_recon", "rd"))
def encode_gop_jit(ys, us, vs, qp, *, mbw: int, mbh: int,
                   emit_recon: bool = False, rd=RD_OFF):
    """Closed-GOP compute: frame 0 intra, frames 1..F-1 inter (P).

    ys: (F, H, W) uint8. Returns the intra frame's level arrays (plus
    the mode/dqp side channel when rd.ships_modes) and the P frames'
    (mv, luma16, chroma_dc, chroma_ac) stacked over F-1; with
    `emit_recon` also the per-frame reconstructed planes (tests/metrics
    — costs F x frame HBM, off by default). With rd.deblock the recon
    chained between frames (and emitted) is the §8.7-filtered plane —
    exactly what a conformant decoder holds.
    """
    qp = qp.astype(jnp.int32)
    qpc = _QPC[jnp.clip(qp, 0, 51)]
    intra, (ry, ru, rv) = _intra_frame_outputs(
        ys[0], us[0], vs[0], qp, mbw=mbw, mbh=mbh, rd=rd)

    def p_step(carry, xs):
        ry, ru, rv, pred_mv = carry
        cy, cu, cv = xs
        (mv, l16, cdc, cac, ry2, ru2, rv2, med_mv) = _encode_p_plane(
            cy, cu, cv, ry, ru, rv, pred_mv, qp, qpc, mbw=mbw, mbh=mbh,
            rd=rd)
        outs = (mv, l16, cdc, cac)
        if emit_recon:
            outs = outs + (ry2, ru2, rv2)
        return (ry2, ru2, rv2, med_mv), outs

    # Inits derived from data (not constants) so the scan carries keep
    # the mesh-varying axes under shard_map — see jaxcore._varying_zero.
    zero = _varying_zero(ry)
    zero_mv = jnp.zeros(2, jnp.int32) + zero
    _, pouts = jax.lax.scan(
        p_step, (ry, ru, rv, zero_mv), (ys[1:], us[1:], vs[1:]))
    if emit_recon:
        mv, l16, cdc, cac, pry, pru, prv = pouts
        recon_y = jnp.concatenate([ry[None], pry]).astype(jnp.int32)
        recon_u = jnp.concatenate([ru[None], pru]).astype(jnp.int32)
        recon_v = jnp.concatenate([rv[None], prv]).astype(jnp.int32)
        return intra, (mv, l16, cdc, cac), (recon_y, recon_u, recon_v)
    mv, l16, cdc, cac = pouts
    return intra, (mv, l16, cdc, cac)


# Per-MB flat sizes for the plane-layout GOP transfer: the P part of the
# flat vector is (F-1) * nmb * _P_FLAT_MB int16 values laid out
# struct-of-arrays: all luma coeff planes, then u DC, v DC (hadamard
# domain), then u AC, v AC coeff planes (DC positions zeroed). The
# values live in the jax-free layout module (the host inverses and the
# process pack sidecars read them without dragging jax in); re-exported
# here next to the encode that emits the layout.
from .layout import _INTRA_FLAT_MB, _P_FLAT_MB  # noqa: E402


def encode_gop_planes(ys, us, vs, qp, *, mbw: int, mbh: int, rd=RD_OFF):
    """Closed-GOP compute emitting PLANE-layout levels for the sharded
    transfer path: returns (mv (F-1, nmb, 2) int8, flat int16).

    flat layout (all reshape(-1), no relayout on device):
      [ intra il_dc | il_ac | ic_dc | ic_ac          (nmb * 384)
      | luma coeff planes   (F-1, H, W)
      | u DC (F-1, nmb, 4) | v DC (F-1, nmb, 4)
      | u AC plane (F-1, H/2, W/2) | v AC plane (F-1, H/2, W/2)
      | intra mode16 (nmb) | intra dqp16 (nmb)   — rd.ships_modes only ]

    The host inverse is parallel/dispatch._unflatten_gop.
    """
    # The int8 MV transfer rides on search candidates being bounded by
    # construction: centers clamp to ±(SEARCH_RANGE - window) pel and
    # offsets add ≤ the window, so |mv| ≤ 2 * SEARCH_RANGE half-pel
    # units per frame (each P frame references its immediate
    # predecessor — MVs never accumulate).
    if 2 * SEARCH_RANGE > 127:
        raise ValueError("SEARCH_RANGE exceeds the int8 MV transfer")
    qp = qp.astype(jnp.int32)
    qpc = _QPC[jnp.clip(qp, 0, 51)]
    intra, (ry, ru, rv) = _intra_frame_outputs(
        ys[0], us[0], vs[0], qp, mbw=mbw, mbh=mbh, rd=rd)

    def p_step(carry, xs):
        ry, ru, rv, pred_mv = carry
        cy, cu, cv = xs
        (mv, lp, cdc, cac, ry2, ru2, rv2, med_mv) = _encode_p_plane(
            cy, cu, cv, ry, ru, rv, pred_mv, qp, qpc, mbw=mbw, mbh=mbh,
            blocked=False, rd=rd)
        return (ry2, ru2, rv2, med_mv), (mv.astype(jnp.int8), lp, cdc, cac)

    zero = _varying_zero(ry)
    zero_mv = jnp.zeros(2, jnp.int32) + zero
    _, (mv8, lps, cdcs, cacs) = jax.lax.scan(
        p_step, (ry, ru, rv, zero_mv), (ys[1:], us[1:], vs[1:]))
    # cdcs: (F-1, 2, n, 4) int16; cacs: (F-1, 2, H/2, W/2) int16
    parts = [
        intra[0].reshape(-1).astype(jnp.int16),
        intra[1].reshape(-1).astype(jnp.int16),
        intra[2].reshape(-1).astype(jnp.int16),
        intra[3].reshape(-1).astype(jnp.int16),
        lps.reshape(-1),
        cdcs[:, 0].reshape(-1), cdcs[:, 1].reshape(-1),
        cacs[:, 0].reshape(-1), cacs[:, 1].reshape(-1),
    ]
    if rd.ships_modes:
        parts.extend([intra[4], intra[5]])
    return mv8, jnp.concatenate(parts)


# ---------------------------------------------------------------------------
# split-frame encoding (SFE): per-band, per-FRAME step cores
#
# The GOP paths above amortize dispatch by batching a whole GOP per
# program; the SFE path instead steps ONE frame at a time so the
# per-frame glass-to-bitstream latency is a single device step + band
# fetch + band-slice pack (parallel/dispatch.SfeShardEncoder). Each
# core runs on one band's (Hb, W) shard under shard_map; the recon
# carry chains between steps ON DEVICE.
# ---------------------------------------------------------------------------


def _deblock_band(ry, ru, rv, qp, *, intra: bool, nz4, mv, mbw: int,
                  mbh_band: int, total_mb_rows: int, axis_name,
                  num_bands: int):
    """Deblock one band's recon with a ONE-MB-ROW cross-band halo.

    The §8.7 filter's vertical passes are row-local, and its horizontal
    passes read/write at most 4 rows across an MB edge — so exchanging
    16 raw recon rows (plus the neighbor MB row's bS metadata: nz map
    and MVs; QP is flat in SFE) and running the full shifted-plane
    schedule on the extended planes reproduces the FULL-FRAME filter
    exactly: halo rows V-filter to the same values the neighbor band
    computes for its own rows, the boundary H edge is computed
    identically on both sides, and the per-band slices back out
    byte-identical to the unbanded program (tested across band
    counts). Frame edges / the last band's padding rows are masked via
    the global (mb_row0, total_mb_rows) coordinates, with mb_row0
    traced (lax.axis_index) so one program serves every band."""
    banded = axis_name is not None and num_bands > 1
    exch = functools.partial(jaxme.band_halo_exchange,
                             axis_name=axis_name, num_bands=num_bands)
    ry_e = exch(ry, 16)
    ru_e = exch(ru, 8)
    rv_e = exch(rv, 8)
    idx = jax.lax.axis_index(axis_name) if banded \
        else jnp.int32(0) + _varying_zero(ry)
    mb_row0 = idx * mbh_band - 1          # extended plane: 1 MB row above
    qp_map = jnp.broadcast_to(qp.astype(jnp.int32),
                              (mbh_band + 2, mbw))
    nz_e = mv_e = None
    if not intra:
        nz_e = exch(nz4.astype(jnp.int16), 4) != 0
        mv_e = exch(mv.reshape(mbh_band, 2 * mbw), 1) \
            .reshape(mbh_band + 2, mbw, 2)
    y2, u2, v2 = jaxdeblock.deblock_frame_jax(
        ry_e, ru_e, rv_e, qp_map, intra=intra, nz4=nz_e, mv=mv_e,
        mb_row0=mb_row0, total_mb_rows=total_mb_rows)
    return (y2[16:16 + 16 * mbh_band], u2[8:8 + 8 * mbh_band],
            v2[8:8 + 8 * mbh_band])


def _fixup_band_recon(plane, real_rows, scale: int = 1):
    """Maintain the SFE recon invariant on a band plane: rows at/past
    this band's real content (the last band's MB padding) are the
    edge-replication of the last REAL row. The full-frame search pads
    its reference with edge replication below the frame; without this
    fixup the padding rows would instead hold the recon of replicated
    SOURCE rows — close, but not the bits the full-frame program (or a
    conformant decoder's edge clamp) sees."""
    H = plane.shape[0]
    real = jnp.maximum(real_rows // scale, 1)
    rows = jnp.arange(H)
    return jnp.take(plane, jnp.minimum(rows, real - 1), axis=0)


def _sfe_intra_common(y, u, v, qp, real_rows, *, mbw: int,
                      mbh_band: int, rd, total_mb_rows: int,
                      axis_name, num_bands: int):
    """Shared intra-band compute: slice-local core + recon fixup +
    (with rd.deblock) the cross-band-halo in-loop filter on the carry.
    Returns (core outputs, (ry, ru, rv, zero_mv))."""
    out = _intra_core(y, u, v, qp, mbw=mbw, mbh=mbh_band, rd=rd)
    ry = _fixup_band_recon(out[4].astype(jnp.int16), real_rows)
    ru = _fixup_band_recon(out[5].astype(jnp.int16), real_rows, 2)
    rv = _fixup_band_recon(out[6].astype(jnp.int16), real_rows, 2)
    if rd.deblock:
        # SFE runs AQ-free (enforced at encoder construction), so the
        # band qp map is flat and no qp metadata crosses bands.
        ry, ru, rv = _deblock_band(
            ry, ru, rv, qp, intra=True, nz4=None, mv=None, mbw=mbw,
            mbh_band=mbh_band, total_mb_rows=total_mb_rows,
            axis_name=axis_name, num_bands=num_bands)
        ry = _fixup_band_recon(ry, real_rows)
        ru = _fixup_band_recon(ru, real_rows, 2)
        rv = _fixup_band_recon(rv, real_rows, 2)
    zero_mv = jnp.zeros(2, jnp.int32) + _varying_zero(ry)
    return out, (ry, ru, rv, zero_mv)


def sfe_intra_band(y, u, v, qp, real_rows, *, mbw: int, mbh_band: int,
                   rd=RD_OFF, total_mb_rows: int = 0, axis_name=None,
                   num_bands: int = 1):
    """One band's IDR step: slice-local intra prediction — the band's
    first MB row predicts like a frame's row 0 because the MBs above
    live in ANOTHER slice and are unavailable to intra prediction
    (§8.3: exactly what a conformant decoder reconstructs), so no
    cross-band exchange is needed on intra frames (the in-loop filter,
    when enabled, is the one cross-band consumer — _deblock_band).

    Returns (dense, rest, (ry, ru, rv, pred_mv)): dense is the
    hadamard-DC prefix [il_dc | ic_dc] shipped uncompressed (the only
    levels that exceed int8 at practical QPs — same rationale as
    dispatch._per_gop_sparse) plus, when rd.ships_modes, the per-MB
    [mode16 | dqp16] side channel; rest is [il_ac | ic_ac] for the
    sparse transfer, and the carry holds the fixed-up recon + a zero
    median MV (each GOP's temporal predictor restarts at its IDR)."""
    qp = qp.astype(jnp.int32)
    out, carry = _sfe_intra_common(
        y, u, v, qp, real_rows, mbw=mbw, mbh_band=mbh_band, rd=rd,
        total_mb_rows=total_mb_rows, axis_name=axis_name,
        num_bands=num_bands)
    il_dc, il_ac, ic_dc, ic_ac = out[:4]
    dense_parts = [il_dc.reshape(-1).astype(jnp.int16),
                   ic_dc.reshape(-1).astype(jnp.int16)]
    if rd.ships_modes:
        dense_parts.append(_mode_tail(out[7], out[8], out[9]))
    dense = jnp.concatenate(dense_parts)
    rest = jnp.concatenate([il_ac.reshape(-1).astype(jnp.int16),
                            ic_ac.reshape(-1).astype(jnp.int16)])
    return dense, rest, carry


def sfe_intra_band_dense(y, u, v, qp, real_rows, *, mbw: int,
                         mbh_band: int, rd=RD_OFF,
                         total_mb_rows: int = 0, axis_name=None,
                         num_bands: int = 1):
    """Dense-transfer variant of :func:`sfe_intra_band`: one flat int16
    vector in the standard intra layout (layout.unflatten_intra's
    inverse, mode/dqp tail appended when rd.ships_modes) — the escape
    fallback path."""
    qp = qp.astype(jnp.int32)
    out, carry = _sfe_intra_common(
        y, u, v, qp, real_rows, mbw=mbw, mbh_band=mbh_band, rd=rd,
        total_mb_rows=total_mb_rows, axis_name=axis_name,
        num_bands=num_bands)
    il_dc, il_ac, ic_dc, ic_ac = out[:4]
    parts = [
        il_dc.reshape(-1).astype(jnp.int16),
        il_ac.reshape(-1).astype(jnp.int16),
        ic_dc.reshape(-1).astype(jnp.int16),
        ic_ac.reshape(-1).astype(jnp.int16)]
    if rd.ships_modes:
        parts.append(_mode_tail(out[7], out[8], out[9]))
    return jnp.concatenate(parts), carry


def sfe_p_band(y, u, v, carry, qp, real_rows, *, mbw: int, mbh_band: int,
               halo_rows: int, num_bands: int, axis_name, ext=None,
               edge_top: bool = True, edge_bot: bool = True, probe=None,
               return_hist: bool = False, rd=RD_OFF,
               total_mb_rows: int = 0):
    """One band's P step: banded motion search (halo exchange + psum'd
    global centers/median, jaxme.me_search_banded) + the shared
    residual core, emitting PLANE-layout levels for the per-frame
    sparse transfer.

    Farm mode (parallel/sfefarm.py): `ext`/`edge_top`/`edge_bot`
    inject the cross-HOST neighbor reference rows, `probe` the
    host-resolved global probe center, and `return_hist=True` returns
    the per-host histogram partial instead of the on-device median
    (the host finishes it across peers and feeds it back as the next
    frame's `pred_mv`).

    Returns (mv8 (nmb, 2) int8, flat int16 [luma plane | u dc | v dc |
    u ac | v ac] — a single-frame slice of encode_gop_planes' P layout,
    so layout.unflatten_p_planes(flat, mv8, 2, ...) is the host
    inverse), plus the chained (ry, ru, rv, med_mv) carry; with
    `return_hist` the tail is (cnt, n, (ry, ru, rv, pred_mv))."""
    if 2 * SEARCH_RANGE > 127:
        raise ValueError("SEARCH_RANGE exceeds the int8 MV transfer")
    if rd.deblock and (ext is not None or probe is not None
                       or return_hist):
        # Farm band slices exchange halos over the host relay once per
        # frame; the in-loop filter would need a second (post-recon)
        # relay round. The remote planner falls back to GOP-range
        # shards for deblock-enabled jobs instead.
        raise ValueError("deblock is not supported on cross-host band "
                         "slices; use GOP sharding for this job")
    ry, ru, rv, pred_mv = carry
    qp32 = qp.astype(jnp.int32)
    qpc = _QPC[jnp.clip(qp32, 0, 51)]
    cy16 = y.astype(jnp.int16)
    cu16 = u.astype(jnp.int16)
    cv16 = v.astype(jnp.int16)
    out = jaxme.me_search_banded(
        cy16, ry, ru, rv, pred_mv, qp32, halo_rows=halo_rows,
        num_bands=num_bands, axis_name=axis_name, real_rows=real_rows,
        ext=ext, edge_top=edge_top, edge_bot=edge_bot, probe=probe,
        return_hist=return_hist)
    if return_hist:
        mv, py, pu, pv, cnt, n = out
    else:
        mv, py, pu, pv, med = out
    (lp, cdc, cac, ry2, ru2, rv2, nz4) = _residual_p(
        cy16, cu16, cv16, py, pu, pv, qp32, qpc, mbw=mbw, mbh=mbh_band,
        blocked=False, rd=rd)
    ry2 = _fixup_band_recon(ry2, real_rows)
    ru2 = _fixup_band_recon(ru2, real_rows, 2)
    rv2 = _fixup_band_recon(rv2, real_rows, 2)
    if rd.deblock:
        ry2, ru2, rv2 = _deblock_band(
            ry2, ru2, rv2, qp32, intra=False, nz4=nz4, mv=mv,
            mbw=mbw, mbh_band=mbh_band, total_mb_rows=total_mb_rows,
            axis_name=axis_name, num_bands=num_bands)
        ry2 = _fixup_band_recon(ry2, real_rows)
        ru2 = _fixup_band_recon(ru2, real_rows, 2)
        rv2 = _fixup_band_recon(rv2, real_rows, 2)
    flat = jnp.concatenate([
        lp.reshape(-1),
        cdc[0].reshape(-1), cdc[1].reshape(-1),
        cac[0].reshape(-1), cac[1].reshape(-1)])
    mv8 = mv.reshape(-1, 2).astype(jnp.int8)
    if return_hist:
        # the host owns the median in farm mode: carry the INPUT pred
        # (ignored — the next step receives the cross-host median as a
        # fresh input) so the carry shape matches the local chain's
        return mv8, flat, cnt, n, (ry2, ru2, rv2, pred_mv)
    return mv8, flat, (ry2, ru2, rv2, med)
