"""H.264 baseline intra encoder.

Architecture (TPU-first): the per-frame COMPUTE (prediction, forward
transform, quantization, closed-loop reconstruction) is separable from the
sequential entropy PACK. The compute path here has a numpy reference
implementation (`encode_frame_arrays`) and a jitted JAX implementation
(jaxcore.py) that must match it bit-exactly; the packer (`pack_slice`)
turns level arrays into a conformant CAVLC slice on the host.

Replaces the reference's ffmpeg encode op point
(/root/reference/worker/tasks.py:1558-1586) with an in-framework codec.

Mode policy (keeps macroblock rows data-parallel for the TPU scan):
- MB (0,0): DC prediction (no neighbors);
- row 0, col > 0: horizontal (left-only dependency);
- rows >= 1: vertical (depends only on the reconstructed row above).
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

from ...core.types import Frame, VideoMeta
from ...io.bits import BitWriter, annexb_nal
from . import cavlc
from .headers import (
    NAL_SLICE_IDR,
    PPS,
    SLICE_TYPE_I,
    SPS,
    SliceHeader,
)
from .intra import (
    CHROMA_BLOCK_ORDER,
    CHROMA_DC,
    CHROMA_H,
    CHROMA_V,
    LUMA_BLOCK_ORDER,
    LUMA_DC,
    LUMA_H,
    LUMA_V,
    predict_chroma8,
    predict_luma16,
    reconstruct_chroma8,
    reconstruct_luma16,
)
from .transform import (
    chroma_dc_forward,
    chroma_dc_quant,
    chroma_qp,
    forward_4x4,
    luma_dc_forward,
    luma_dc_quant,
    quant_4x4,
    zigzag,
)


@dataclasses.dataclass
class FrameLevels:
    """Quantized level arrays for one frame, MB raster order (nmb = mbw*mbh).

    This is the compute→pack interface; the JAX path produces the same
    structure. All zig-zag ordered as the packer expects. Level arrays
    may be int32 or int16 (CAVLC levels fit int16 at every legal QP;
    the transfer paths hand the packer int16 views and the native layer
    packs them without a widening copy).
    """

    luma_mode: np.ndarray    # (nmb,) int32
    chroma_mode: np.ndarray  # (nmb,) int32
    luma_dc: np.ndarray      # (nmb, 16)
    luma_ac: np.ndarray      # (nmb, 16, 15), z-scan block order
    chroma_dc: np.ndarray    # (nmb, 2, 4), raster DC order (Cb, Cr)
    chroma_ac: np.ndarray    # (nmb, 2, 4, 15)


def _mode_policy(mbw: int, mbh: int) -> tuple[np.ndarray, np.ndarray]:
    luma = np.full((mbh, mbw), LUMA_V, np.int32)
    luma[0, :] = LUMA_H
    luma[0, 0] = LUMA_DC
    chroma = np.full((mbh, mbw), CHROMA_V, np.int32)
    chroma[0, :] = CHROMA_H
    chroma[0, 0] = CHROMA_DC
    return luma.reshape(-1), chroma.reshape(-1)


def encode_frame_arrays(y: np.ndarray, u: np.ndarray, v: np.ndarray, qp: int
                        ) -> tuple[FrameLevels, tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Numpy reference of the intra compute path.

    Inputs are padded planes (y: multiple of 16, chroma: half). Returns the
    level arrays and the reconstructed planes (the decoder's exact output).
    """
    mbh, mbw = y.shape[0] // 16, y.shape[1] // 16
    nmb = mbh * mbw
    qpc = chroma_qp(qp)
    luma_mode, chroma_mode = _mode_policy(mbw, mbh)

    recon_y = np.zeros_like(y)
    recon_u = np.zeros_like(u)
    recon_v = np.zeros_like(v)
    levels = FrameLevels(
        luma_mode=luma_mode,
        chroma_mode=chroma_mode,
        luma_dc=np.zeros((nmb, 16), np.int32),
        luma_ac=np.zeros((nmb, 16, 15), np.int32),
        chroma_dc=np.zeros((nmb, 2, 4), np.int32),
        chroma_ac=np.zeros((nmb, 2, 4, 15), np.int32),
    )

    for my in range(mbh):
        for mx in range(mbw):
            mi = my * mbw + mx
            # --- luma ---
            src = y[16 * my:16 * my + 16, 16 * mx:16 * mx + 16]
            top = recon_y[16 * my - 1, 16 * mx:16 * mx + 16] if my > 0 else None
            left = recon_y[16 * my:16 * my + 16, 16 * mx - 1] if mx > 0 else None
            tl = int(recon_y[16 * my - 1, 16 * mx - 1]) if (my > 0 and mx > 0) else None
            pred = predict_luma16(int(luma_mode[mi]), top, left, tl)
            resid = src.astype(np.int32) - pred.astype(np.int32)
            blocks = np.stack([
                resid[4 * by:4 * by + 4, 4 * bx:4 * bx + 4]
                for bx, by in LUMA_BLOCK_ORDER
            ])                                             # (16,4,4) z-scan
            w = forward_4x4(blocks)
            # DC path: spatial (4,4) grid of per-block DCs, zig-zag coded.
            dc_spatial = np.zeros((4, 4), np.int32)
            for bi, (bx, by) in enumerate(LUMA_BLOCK_ORDER):
                dc_spatial[by, bx] = w[bi, 0, 0]
            wd = luma_dc_forward(dc_spatial)
            levels.luma_dc[mi] = zigzag(luma_dc_quant(wd, qp))
            z = quant_4x4(w, qp, intra=True, skip_dc=True)
            levels.luma_ac[mi] = zigzag(z)[:, 1:]
            recon_y[16 * my:16 * my + 16, 16 * mx:16 * mx + 16] = (
                reconstruct_luma16(pred, levels.luma_dc[mi], levels.luma_ac[mi], qp)
            )
            # --- chroma ---
            for ci, (plane, recon) in enumerate(((u, recon_u), (v, recon_v))):
                csrc = plane[8 * my:8 * my + 8, 8 * mx:8 * mx + 8]
                ctop = recon[8 * my - 1, 8 * mx:8 * mx + 8] if my > 0 else None
                cleft = recon[8 * my:8 * my + 8, 8 * mx - 1] if mx > 0 else None
                ctl = int(recon[8 * my - 1, 8 * mx - 1]) if (my > 0 and mx > 0) else None
                cpred = predict_chroma8(int(chroma_mode[mi]), ctop, cleft, ctl)
                cres = csrc.astype(np.int32) - cpred.astype(np.int32)
                cblocks = np.stack([
                    cres[4 * by:4 * by + 4, 4 * bx:4 * bx + 4]
                    for bx, by in CHROMA_BLOCK_ORDER
                ])                                         # (4,4,4)
                cw = forward_4x4(cblocks)
                cdc = np.array([[cw[0, 0, 0], cw[1, 0, 0]],
                                [cw[2, 0, 0], cw[3, 0, 0]]], np.int32)
                wd2 = chroma_dc_forward(cdc)
                levels.chroma_dc[mi, ci] = chroma_dc_quant(wd2, qpc).reshape(-1)
                cz = quant_4x4(cw, qpc, intra=True, skip_dc=True)
                levels.chroma_ac[mi, ci] = zigzag(cz)[:, 1:]
                recon[8 * my:8 * my + 8, 8 * mx:8 * mx + 8] = reconstruct_chroma8(
                    cpred, levels.chroma_dc[mi, ci], levels.chroma_ac[mi, ci], qpc
                )
    return levels, (recon_y, recon_u, recon_v)


def mb_cbp(levels: FrameLevels, mi: int) -> tuple[int, int]:
    """(cbp_luma in {0,15}, cbp_chroma in {0,1,2}) for MB `mi`."""
    cbp_luma = 15 if np.any(levels.luma_ac[mi]) else 0
    if np.any(levels.chroma_ac[mi]):
        cbp_chroma = 2
    elif np.any(levels.chroma_dc[mi]):
        cbp_chroma = 1
    else:
        cbp_chroma = 0
    return cbp_luma, cbp_chroma


def pack_slice(levels: FrameLevels, mbw: int, mbh: int, sps: SPS, pps: PPS,
               qp: int, frame_num: int = 0, idr: bool = True,
               idr_pic_id: int = 0, native: bool | None = None,
               first_mb: int = 0) -> bytes:
    """Entropy-pack one I slice into an Annex-B NAL unit.

    `levels`/`mbw`/`mbh` describe the SLICE's macroblocks; with a
    nonzero `first_mb` (split-frame encoding: one horizontal MB-row
    band per slice) the slice covers MB raster addresses
    [first_mb, first_mb + mbw*mbh) of a larger picture, and the CAVLC
    nC / intra-prediction neighbor logic below — which treats the
    band's first row as having no MBs above — is exactly the §7.4.3
    cross-slice unavailability a decoder applies.

    `native=None` auto-selects the C++ packer when buildable; False forces
    the pure-Python reference path (both produce identical bits — tested).
    """
    bw = BitWriter()
    header = SliceHeader(
        slice_type=SLICE_TYPE_I, frame_num=frame_num, idr=idr, qp=qp,
        idr_pic_id=idr_pic_id, first_mb=first_mb,
    )
    header.write(bw, sps, pps)

    if native is not False:
        from ... import native as native_mod

        if native_mod.available():
            hdr_bytes, hdr_bits = bw.getvalue_unaligned()
            ebsp = native_mod.pack_islice(
                hdr_bytes, hdr_bits, levels.luma_mode, levels.chroma_mode,
                levels.luma_dc, levels.luma_ac, levels.chroma_dc,
                levels.chroma_ac, mbw, mbh)
            start = b"\x00\x00\x00\x01"
            nal_header = bytes([(3 << 5) | (NAL_SLICE_IDR if idr else 1)])
            return start + nal_header + ebsp
        if native:
            raise RuntimeError("native packer requested but unavailable")

    # nC neighbor maps: total_coeff per 4x4 luma / chroma block.
    luma_counts = np.zeros((4 * mbh, 4 * mbw), np.int32)
    chroma_counts = np.zeros((2, 2 * mbh, 2 * mbw), np.int32)

    for my in range(mbh):
        for mx in range(mbw):
            mi = my * mbw + mx
            cbp_luma, cbp_chroma = mb_cbp(levels, mi)
            mb_type = 1 + int(levels.luma_mode[mi]) + 4 * cbp_chroma \
                + 12 * (1 if cbp_luma else 0)
            bw.ue(mb_type)
            bw.ue(int(levels.chroma_mode[mi]))   # intra_chroma_pred_mode
            bw.se(0)                             # mb_qp_delta

            # Luma DC: nC from blkIdx 0 neighbors.
            by0, bx0 = 4 * my, 4 * mx
            na = int(luma_counts[by0, bx0 - 1]) if bx0 > 0 else None
            nb = int(luma_counts[by0 - 1, bx0]) if by0 > 0 else None
            cavlc.encode_residual(bw, levels.luma_dc[mi].tolist(),
                                  cavlc.luma_nc(na, nb))

            # Luma AC in z-scan block order.
            for bi, (bx, by) in enumerate(LUMA_BLOCK_ORDER):
                gy, gx = by0 + by, bx0 + bx
                if cbp_luma:
                    na = int(luma_counts[gy, gx - 1]) if gx > 0 else None
                    nb = int(luma_counts[gy - 1, gx]) if gy > 0 else None
                    tc = cavlc.encode_residual(
                        bw, levels.luma_ac[mi, bi].tolist(), cavlc.luma_nc(na, nb))
                    luma_counts[gy, gx] = tc
                else:
                    luma_counts[gy, gx] = 0

            # Chroma DC (both planes) then AC.
            if cbp_chroma > 0:
                for ci in range(2):
                    cavlc.encode_residual(
                        bw, levels.chroma_dc[mi, ci].tolist(), -1)
            cy0, cx0 = 2 * my, 2 * mx
            for ci in range(2):
                for bi, (bx, by) in enumerate(CHROMA_BLOCK_ORDER):
                    gy, gx = cy0 + by, cx0 + bx
                    if cbp_chroma == 2:
                        na = int(chroma_counts[ci, gy, gx - 1]) if gx > 0 else None
                        nb = int(chroma_counts[ci, gy - 1, gx]) if gy > 0 else None
                        tc = cavlc.encode_residual(
                            bw, levels.chroma_ac[mi, ci, bi].tolist(),
                            cavlc.luma_nc(na, nb))
                        chroma_counts[ci, gy, gx] = tc
                    else:
                        chroma_counts[ci, gy, gx] = 0

    bw.rbsp_trailing_bits()
    return annexb_nal(3, NAL_SLICE_IDR if idr else 1, bw.getvalue())


class H264Encoder:
    """Stateful per-job encoder: sequence headers + frame encode.

    v1 scope: intra-only (every frame IDR), 4:2:0, fixed qp, CAVLC.

    The jitted JAX compute path is the default engine (TPU-first); pass
    `use_jax=False` for the numpy reference implementation.
    """

    def __init__(self, meta: VideoMeta, qp: int = 27, use_jax: bool = True):
        self.meta = meta
        self.qp = qp
        self.use_jax = use_jax
        self.sps = SPS(width=meta.width, height=meta.height,
                       fps_num=meta.fps_num, fps_den=meta.fps_den)
        self.pps = PPS(init_qp=qp)
        self._jax_fn = None

    def _compute(self, y: np.ndarray, u: np.ndarray, v: np.ndarray) -> FrameLevels:
        if self.use_jax:
            from . import jaxcore

            if self._jax_fn is None:
                self._jax_fn = jaxcore.build_intra_encoder(
                    y.shape, self.qp)
            return self._jax_fn(y, u, v)
        levels, _ = encode_frame_arrays(y, u, v, self.qp)
        return levels

    def encode_frame(self, frame: Frame, frame_num: int = 0,
                     idr_pic_id: int = 0, with_headers: bool = True) -> bytes:
        from ...core.types import ChromaFormat

        if frame.chroma is not ChromaFormat.YUV420:
            # The MB geometry below hard-assumes 4:2:0 (8x8 chroma per MB);
            # feeding 4:2:2/4:4:4 would silently mis-encode.
            raise ValueError(
                f"H264Encoder supports only 4:2:0 input, got "
                f"{frame.chroma.name}; convert before encoding")
        padded = frame.padded(16)
        levels = self._compute(padded.y, padded.u, padded.v)
        mbh, mbw = padded.y.shape[0] // 16, padded.y.shape[1] // 16
        slice_nal = pack_slice(levels, mbw, mbh, self.sps, self.pps, self.qp,
                               frame_num=0, idr=True,
                               idr_pic_id=idr_pic_id % 65536)
        if with_headers:
            return self.sps.to_nal() + self.pps.to_nal() + slice_nal
        return slice_nal


def encode_frames(frames: list[Frame], meta: VideoMeta, qp: int = 27,
                  use_jax: bool = True) -> bytes:
    """Encode a closed sequence of frames to one Annex-B byte stream
    (all-intra: every frame IDR)."""
    enc = H264Encoder(meta, qp=qp, use_jax=use_jax)
    out = []
    for i, frame in enumerate(frames):
        out.append(enc.encode_frame(frame, idr_pic_id=i,
                                    with_headers=(i == 0)))
    return b"".join(out)


def encode_gop(frames: list[Frame], meta: VideoMeta, qp: int = 27,
               idr_pic_id: int = 0, with_headers: bool = True,
               return_recon: bool = False):
    """Encode a closed GOP: frame 0 IDR, frames 1..F-1 inter-coded (P).

    The whole GOP's compute (intra frame + motion search / compensation /
    transform chained through a `lax.scan` recon carry) is ONE jitted XLA
    program (jaxinter.encode_gop_jit); this host half packs the I-slice
    and P-slices. Replaces the reference's inter-coded ffmpeg op point
    (/root/reference/worker/tasks.py:1558-1586).
    """
    import jax
    import jax.numpy as jnp

    from ...core.types import ChromaFormat
    from . import jaxinter

    if not frames:
        raise ValueError("empty GOP")
    bad = next((f for f in frames
                if f.chroma is not ChromaFormat.YUV420), None)
    if bad is not None:
        raise ValueError(
            f"encode_gop supports only 4:2:0 input, got {bad.chroma.name}")
    padded = [f.padded(16) for f in frames]
    ph, pw = padded[0].y.shape
    mbh, mbw = ph // 16, pw // 16
    ys = jnp.asarray(np.stack([p.y for p in padded]))
    us = jnp.asarray(np.stack([p.u for p in padded]))
    vs = jnp.asarray(np.stack([p.v for p in padded]))

    out = jaxinter.encode_gop_jit(ys, us, vs, jnp.asarray(qp),
                                  mbw=mbw, mbh=mbh,
                                  emit_recon=return_recon)
    if return_recon:
        (intra, pouts, recons) = jax.device_get(out)
    else:
        (intra, pouts) = jax.device_get(out)
    il_dc, il_ac, ic_dc, ic_ac = intra
    mv, l16, cdc, cac = pouts

    sps = SPS(width=meta.width, height=meta.height,
              fps_num=meta.fps_num, fps_den=meta.fps_den)
    pps = PPS(init_qp=qp)
    nals = pack_gop_slices(intra, pouts, len(frames), mbw, mbh, sps, pps,
                           qp, idr_pic_id, with_headers=with_headers)
    stream = b"".join(nals)
    if return_recon:
        return stream, recons
    return stream


def _gop_slice_thunks(intra, pack_p, num_frames: int, mbw: int, mbh: int,
                      sps: SPS, pps: PPS, qp: int, idr_pic_id: int,
                      with_headers: bool) -> list:
    """Per-slice pack closures for one GOP (IDR thunk first, then one
    per P frame). A GOP's slices are independent bit-strings until the
    final concat, so callers may run the thunks on a thread pool (the
    native packer releases the GIL for the C call); running them in
    order serially yields the same bytes. Every GOP-pack entry point
    funnels through here so the bit-identity contract between paths
    cannot drift in the IDR/header logic."""
    il_dc, il_ac, ic_dc, ic_ac = intra
    luma_mode, chroma_mode = _mode_policy(mbw, mbh)
    intra_levels = FrameLevels(
        luma_mode=luma_mode, chroma_mode=chroma_mode,
        luma_dc=il_dc, luma_ac=il_ac, chroma_dc=ic_dc, chroma_ac=ic_ac)
    head = sps.to_nal() + pps.to_nal() if with_headers else b""

    def pack_idr():
        return head + pack_slice(intra_levels, mbw, mbh, sps, pps, qp,
                                 frame_num=0, idr=True,
                                 idr_pic_id=idr_pic_id % 65536)

    thunks = [pack_idr]
    for i in range(num_frames - 1):
        thunks.append(functools.partial(pack_p, i, (i + 1) % 256))
    return thunks


def run_slice_thunks(thunks: list, pool=None) -> list[bytes]:
    """Evaluate slice-pack thunks in slice order; with `pool` (any
    Executor) the packs run concurrently, without it serially — the
    resulting bytes are identical either way."""
    if pool is None or len(thunks) <= 1:
        return [t() for t in thunks]
    return [f.result() for f in [pool.submit(t) for t in thunks]]


def _pack_gop_common(intra, pack_p, num_frames: int, mbw: int, mbh: int,
                     sps: SPS, pps: PPS, qp: int, idr_pic_id: int,
                     with_headers: bool, pool=None) -> list[bytes]:
    """Shared host half of GOP entropy packing: IDR slice from blocked
    intra levels + one P slice per remaining frame via `pack_p(i,
    frame_num)`, optionally fanned across `pool` at slice granularity."""
    return run_slice_thunks(
        _gop_slice_thunks(intra, pack_p, num_frames, mbw, mbh, sps, pps,
                          qp, idr_pic_id, with_headers), pool)


def gop_slice_thunks_planes(intra, planes, num_frames: int, mbw: int,
                            mbh: int, sps: SPS, pps: PPS, qp: int,
                            idr_pic_id: int,
                            with_headers: bool = True) -> list:
    """Per-slice pack thunks for one PLANE-layout GOP (see
    pack_gop_slices_planes for the array contract). dispatch.collect_wave
    submits these so slices from ALL of a wave's GOPs pack concurrently
    on the pack pool instead of GOP-by-GOP."""
    from . import inter as inter_mod

    mv8, lp, udc, vdc, uac, vac = planes
    return _gop_slice_thunks(
        intra,
        lambda i, fn: inter_mod.pack_p_slice_plane(
            mv8[i], lp[i], udc[i], vdc[i], uac[i], vac[i], mbw, mbh,
            sps, pps, qp, frame_num=fn),
        num_frames, mbw, mbh, sps, pps, qp, idr_pic_id, with_headers)


def pack_gop_slices_planes(intra, planes, num_frames: int, mbw: int,
                           mbh: int, sps: SPS, pps: PPS, qp: int,
                           idr_pic_id: int, with_headers: bool = True,
                           pool=None) -> list[bytes]:
    """Entropy-pack one GOP whose P frames arrive as PLANE-layout level
    arrays (the sharded transfer format, jaxinter.encode_gop_planes):
    planes = (mv8 (F-1,nmb,2) int8, luma planes (F-1,H,W) int16,
    u_dc/v_dc (F-1,nmb,4) int16, u_ac/v_ac (F-1,H/2,W/2) int16).
    The intra frame stays blocked (jaxcore._intra_core emits blocked).
    Bit-identical to pack_gop_slices on the equivalent blocked arrays."""
    return run_slice_thunks(
        gop_slice_thunks_planes(intra, planes, num_frames, mbw, mbh, sps,
                                pps, qp, idr_pic_id, with_headers), pool)


def pack_gop_slices(intra, pouts, num_frames: int, mbw: int, mbh: int,
                    sps: SPS, pps: PPS, qp: int, idr_pic_id: int,
                    with_headers: bool = True, pool=None) -> list[bytes]:
    """Entropy-pack one GOP's slices from BLOCKED device level arrays
    (the single-device encode_gop path).

    intra: (luma_dc, luma_ac, chroma_dc, chroma_ac); pouts: the P
    frames' (mv, luma16, chroma_dc, chroma_ac), leading dim >= num
    frames - 1 (extra tail-padding entries are ignored).
    """
    from . import inter as inter_mod

    mv, l16, cdc, cac = pouts
    return _pack_gop_common(
        intra,
        lambda i, fn: inter_mod.pack_p_slice(
            mv[i], l16[i], cdc[i], cac[i], mbw, mbh, sps, pps, qp,
            frame_num=fn),
        num_frames, mbw, mbh, sps, pps, qp, idr_pic_id, with_headers,
        pool=pool)
