"""Bit-level IO: MSB-first bit packing, Exp-Golomb codes, NAL framing.

These are the primitives under every H.26x bitstream the codecs emit. The
reference never wrote a bit itself (ffmpeg did); here the bit layer is
first-class and unit-tested against known codewords.

Performance note: the writer batches bits through a Python-int accumulator
and flushes whole bytes. The hot entropy pack runs through the optional C++
packer (``thinvids_tpu.native``) when built; this module is the always-on
fallback and the semantic reference.
"""

from __future__ import annotations


class BitWriter:
    """MSB-first bit writer with Exp-Golomb helpers (H.264 §9.1)."""

    def __init__(self) -> None:
        self._buf = bytearray()
        self._acc = 0       # pending bits, MSB-first in the low `_nbits`
        self._nbits = 0

    def write(self, value: int, nbits: int) -> None:
        """Append `nbits` bits of `value` (unsigned, MSB first)."""
        if nbits < 0 or (nbits == 0 and value):
            raise ValueError("bad bit count")
        if value < 0 or (nbits < 64 and value >> nbits):
            raise ValueError(f"value {value} does not fit in {nbits} bits")
        self._acc = (self._acc << nbits) | value
        self._nbits += nbits
        while self._nbits >= 8:
            self._nbits -= 8
            self._buf.append((self._acc >> self._nbits) & 0xFF)
        self._acc &= (1 << self._nbits) - 1

    def write_bit(self, bit: int) -> None:
        self.write(1 if bit else 0, 1)

    def ue(self, value: int) -> None:
        """Unsigned Exp-Golomb: codeNum → [zeros prefix] 1 [info]."""
        if value < 0:
            raise ValueError("ue() requires non-negative value")
        code = value + 1
        nbits = code.bit_length()
        self.write(0, nbits - 1)
        self.write(code, nbits)

    def se(self, value: int) -> None:
        """Signed Exp-Golomb (H.264 §9.1.1): v>0 → 2v-1, v<=0 → -2v."""
        self.ue(2 * value - 1 if value > 0 else -2 * value)

    def byte_align(self, fill_bit: int = 0) -> None:
        if self._nbits % 8:
            pad = 8 - (self._nbits % 8)
            self.write((1 << pad) - 1 if fill_bit else 0, pad)

    def rbsp_trailing_bits(self) -> None:
        """rbsp_stop_one_bit + zero alignment (H.264 §7.3.2.11)."""
        self.write_bit(1)
        self.byte_align(0)

    @property
    def bit_length(self) -> int:
        return len(self._buf) * 8 + self._nbits

    def getvalue(self) -> bytes:
        if self._nbits:
            raise ValueError(
                f"{self._nbits} unflushed bits; call byte_align() or "
                "rbsp_trailing_bits() first"
            )
        return bytes(self._buf)

    def getvalue_unaligned(self) -> tuple[bytes, int]:
        """(zero-padded bytes, true bit length) — for splicing into another
        bit writer (e.g. the native packer continues after the header)."""
        total_bits = self.bit_length
        if self._nbits:
            pad = 8 - self._nbits
            data = bytes(self._buf) + bytes([(self._acc << pad) & 0xFF])
        else:
            data = bytes(self._buf)
        return data, total_bits


class BitReader:
    """MSB-first bit reader matching :class:`BitWriter`."""

    def __init__(self, data: bytes) -> None:
        self._data = data
        self._pos = 0  # bit position

    @property
    def bits_left(self) -> int:
        return len(self._data) * 8 - self._pos

    @property
    def bit_position(self) -> int:
        return self._pos

    def read(self, nbits: int) -> int:
        if nbits > self.bits_left:
            raise EOFError("bitstream exhausted")
        value = 0
        pos = self._pos
        for _ in range(nbits):
            byte = self._data[pos >> 3]
            value = (value << 1) | ((byte >> (7 - (pos & 7))) & 1)
            pos += 1
        self._pos = pos
        return value

    def read_bit(self) -> int:
        return self.read(1)

    def peek(self, nbits: int) -> int:
        """Read without consuming; short reads at EOF are zero-padded."""
        pos = self._pos
        avail = min(nbits, self.bits_left)
        value = self.read(avail)
        self._pos = pos
        return value << (nbits - avail)

    def ue(self) -> int:
        zeros = 0
        while self.read_bit() == 0:
            zeros += 1
            if zeros > 63:
                raise ValueError("corrupt exp-golomb code")
        return (1 << zeros) - 1 + (self.read(zeros) if zeros else 0)

    def se(self) -> int:
        code = self.ue()
        mag = (code + 1) >> 1
        return mag if code & 1 else -mag

    def byte_align(self) -> None:
        self._pos = (self._pos + 7) & ~7

    def more_rbsp_data(self) -> bool:
        """True if payload bits remain before the rbsp trailing pattern."""
        if self.bits_left <= 0:
            return False
        # Trailing = stop bit '1' followed only by zeros to stream end.
        tail = self._pos
        data, pos = self._data, len(self._data) * 8
        while pos > tail:
            pos -= 1
            if (data[pos >> 3] >> (7 - (pos & 7))) & 1:
                return pos != tail
        return False  # degenerate: all zeros


def rbsp_to_ebsp(rbsp: bytes) -> bytes:
    """Insert emulation-prevention 0x03 bytes (H.264 §7.4.1.1).

    Any 00 00 followed by a byte <= 03 gets 03 interposed so the start-code
    prefix 00 00 01 can never appear inside a NAL payload.
    """
    out = bytearray()
    zeros = 0
    for b in rbsp:
        if zeros >= 2 and b <= 3:
            out.append(3)
            zeros = 0
        out.append(b)
        zeros = zeros + 1 if b == 0 else 0
    return bytes(out)


def ebsp_to_rbsp(ebsp: bytes) -> bytes:
    """Strip emulation-prevention 0x03 bytes."""
    out = bytearray()
    zeros = 0
    i = 0
    n = len(ebsp)
    while i < n:
        b = ebsp[i]
        if zeros >= 2 and b == 3 and i + 1 < n and ebsp[i + 1] <= 3:
            zeros = 0
            i += 1
            continue
        out.append(b)
        zeros = zeros + 1 if b == 0 else 0
        i += 1
    return bytes(out)


def slice_first_mb(nal: bytes) -> int:
    """first_mb_in_slice of a raw VCL NAL (header byte + EBSP payload)
    — the slice header's leading ue(v). Only a short prefix is
    unescaped: enough bits for any legal MB address. Used to group a
    picture's slices into ONE access unit (a multi-slice picture's
    later slices have first_mb != 0 and must ride with the slice that
    opened the picture — split-frame encoding emits one slice per
    MB-row band)."""
    return BitReader(ebsp_to_rbsp(nal[1:12])).ue()


def annexb_nal(nal_ref_idc: int, nal_unit_type: int, rbsp: bytes,
               long_start_code: bool = True) -> bytes:
    """Wrap an RBSP payload as one Annex-B NAL unit.

    forbidden_zero_bit(0) | nal_ref_idc(2) | nal_unit_type(5), then the
    emulation-prevented payload, preceded by a start code.
    """
    if not 0 <= nal_ref_idc <= 3 or not 0 <= nal_unit_type <= 31:
        raise ValueError("bad NAL header fields")
    header = bytes([(nal_ref_idc << 5) | nal_unit_type])
    start = b"\x00\x00\x00\x01" if long_start_code else b"\x00\x00\x01"
    return start + header + rbsp_to_ebsp(rbsp)


def split_annexb(stream: bytes) -> list[tuple[int, int, bytes]]:
    """Split an Annex-B stream into (nal_ref_idc, nal_unit_type, rbsp) units."""
    units: list[tuple[int, int, bytes]] = []
    i = 0
    n = len(stream)
    starts: list[int] = []
    while i + 2 < n:
        if stream[i] == 0 and stream[i + 1] == 0 and stream[i + 2] == 1:
            starts.append(i + 3)
            i += 3
        else:
            i += 1
    for idx, s in enumerate(starts):
        end = n if idx + 1 == len(starts) else starts[idx + 1]
        # back off the next start code (and its optional leading zero byte)
        if idx + 1 < len(starts):
            end -= 3
            while end > s and stream[end - 1] == 0:
                end -= 1
        payload = stream[s:end]
        if not payload:
            continue
        header = payload[0]
        units.append(((header >> 5) & 3, header & 31, ebsp_to_rbsp(payload[1:])))
    return units
