"""Origin serving subsystem (jax-free).

The HLS routes stopped being "read a file per request" here: an
in-memory hot-segment cache with strong ETags (`cache.py`), RFC 7233
range / HEAD / conditional-GET planning plus the bounded LL-HLS
blocking-reload machinery (`serve.py`), and per-job concurrent-session
gauges — the pieces a CDN-fronted origin needs to survive concurrent
viewers while the farm keeps encoding. Everything here runs on the
coordinator's API threads: no jax, no device state.
"""

from .cache import HotSegmentCache
from .serve import Origin, ServePlan, plan_file

__all__ = ["HotSegmentCache", "Origin", "ServePlan", "plan_file"]
