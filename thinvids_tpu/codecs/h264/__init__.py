"""H.264/AVC baseline-profile intra codec (CAVLC, I16x16).

Built from ITU-T H.264 (Rec. 08/2021) semantics:
- 4x4 integer core transform + 4x4/2x2 Hadamard DC transforms (§8.5)
- I16x16 luma and 8x8 chroma intra prediction (§8.3)
- CAVLC residual coding (§9.2) with the Table 9-5/9-7/9-8/9-10 VLCs
- Annex-B byte streams: SPS/PPS/IDR slices, deblocking disabled via
  slice header so reconstruction is filter-free and bit-exactly testable.

The encode hot path (prediction, transform, quant, reconstruction) runs as
a jitted JAX program scanning macroblock rows; entropy packing is host-side.
An independent decoder (decoder.py) plus a ctypes libavcodec oracle give
two-sided conformance coverage.
"""

__all__ = ["H264Encoder", "encode_frames", "SPS", "PPS"]


def __getattr__(name):  # lazy: keep table/transform imports light
    if name in __all__:
        from . import encoder, headers

        return {
            "H264Encoder": encoder.H264Encoder,
            "encode_frames": encoder.encode_frames,
            "SPS": headers.SPS,
            "PPS": headers.PPS,
        }[name]
    raise AttributeError(name)
