"""Durable shard-part spool + per-job board checkpoint.

The reference survives manager restarts because *everything* lives in
Redis — "the job hash IS the job's checkpoint" (SURVEY §5.4) — and the
encoded part payloads live on the stitcher's disk, not in a process
heap. Until this module, the repro journaled only the Job records:
every completed shard's encoded bytes sat solely in coordinator RAM
(``shard.segments``), so a coordinator crash threw away hours of farm
work and ``recover_jobs`` could only restart from scratch.

Two durable pieces, both jax-free (this module runs on coordinator
control-plane threads only):

- **Part spool** — ``spool()`` + ``commit()`` stream one accepted
  part's payload to ``<root>/<job>/<key>.part`` (the `pack_parts` wire
  framing, digests included) via temp file + fsync + atomic rename, so
  a crash can never leave a torn part that later verifies. The board
  then holds a :class:`PartRef` (path + per-segment sha256 + size)
  instead of the bytes — DONE shards stop pinning payload in RAM.

- **Board checkpoint** — a per-job append journal
  (``<root>/<job>.board.jsonl``) with the same flock / append /
  compact discipline as ``JobStore``: one ``plan`` record (the full
  deterministic shard plan + a plan signature over the inputs that
  change encoded bytes) followed by one ``done`` record per accepted
  part. ``load_job`` replays it; ``begin_job`` re-anchors it — keeping
  the done map when the signature still matches (crash-resume) and
  resetting it when it doesn't (settings/input changed: stale parts
  must never rehydrate).

Integrity is end-to-end: refs carry the digests recorded at ACCEPT
time (the sidecar manifest), and ``read_part`` re-hashes the spooled
payloads against them before any byte reaches the stitcher — a flipped
bit on disk surfaces as :class:`PartIntegrityError`, never as corrupt
output. The same digests ride the ``/work`` wire framing so transfer
corruption is rejected at ingest (cluster/remote.py `unpack_parts`).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import threading
import time
from typing import Any, Callable, Iterable, Mapping

from ..obs import metrics as obs_metrics


class PartIntegrityError(ValueError):
    """A part's payload no longer matches its recorded sha256 — a
    transfer or storage fault, never a worker fault (rejections must
    not burn shard attempts or quarantine accounting)."""


def segment_sha256(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()


@dataclasses.dataclass(frozen=True)
class PartRef:
    """Durable reference to one spooled part: what the board holds
    instead of the encoded bytes."""

    job_id: str
    key: str                      # run-stable shard plan key
    path: str
    digests: tuple[str, ...]      # per-segment payload sha256
    nbytes: int

    def to_dict(self) -> dict[str, Any]:
        return {"key": self.key, "path": self.path,
                "digests": list(self.digests), "nbytes": self.nbytes}


@dataclasses.dataclass
class JobCheckpoint:
    """Replayed view of one job's board journal."""

    plan: dict[str, Any]          # the deterministic shard plan record
    done: dict[str, PartRef]      # plan key → accepted part


class PartStore:
    """Thread-safe spool + checkpoint store rooted at one directory.

    Exclusive-owned via flock on a sidecar lock file (the JobStore
    discipline): two coordinators spooling into the same root would
    both "durably" record divergent state. The lock releases on
    process death, so a SIGKILLed coordinator's successor opens the
    same root cleanly.
    """

    def __init__(self, root: str,
                 clock: Callable[[], float] = time.time) -> None:
        self.root = root
        self._clock = clock
        self._lock = threading.Lock()
        #: job_id → open append handle for the job's board journal
        self._journals: dict[str, Any] = {}
        self._spool_bytes = 0
        self._closed = False
        os.makedirs(root, exist_ok=True)
        self._acquire_lockfile()
        # restart: the gauge must reflect what already sits on disk
        with self._lock:
            self._spool_bytes = self._scan_spool_bytes()
            self._set_gauge_locked()

    # -- ownership -----------------------------------------------------

    def _acquire_lockfile(self) -> None:
        import fcntl

        self._lockfile = open(os.path.join(self.root, ".lock"), "w")
        try:
            fcntl.flock(self._lockfile, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            self._lockfile.close()
            self._lockfile = None
            raise RuntimeError(
                f"part spool {self.root} is owned by another store "
                "(close() it first)")

    def close(self) -> None:
        """Release journal handles + the ownership flock. Spooled
        parts and journals stay on disk — they ARE the checkpoint a
        successor store resumes from."""
        import fcntl

        with self._lock:
            self._closed = True
            for fh in self._journals.values():
                fh.close()
            self._journals.clear()
            if self._lockfile is not None:
                fcntl.flock(self._lockfile, fcntl.LOCK_UN)
                self._lockfile.close()
                self._lockfile = None

    # -- paths ---------------------------------------------------------

    def _journal_path(self, job_id: str) -> str:
        return os.path.join(self.root, f"{job_id}.board.jsonl")

    def _spool_dir(self, job_id: str) -> str:
        return os.path.join(self.root, job_id)

    def _scan_spool_bytes(self) -> int:
        total = 0
        try:
            with os.scandir(self.root) as it:
                dirs = [e.path for e in it if e.is_dir()]
        except OSError:
            return 0
        for d in dirs:
            try:
                with os.scandir(d) as it:
                    total += sum(e.stat().st_size for e in it
                                 if e.name.endswith(".part"))
            except OSError:
                continue
        return total

    def _set_gauge_locked(self) -> None:
        obs_metrics.PART_SPOOL_BYTES.set(self._spool_bytes)

    def spool_bytes(self) -> int:
        with self._lock:
            return self._spool_bytes

    # -- journal (flock/append/compact, per job) -----------------------

    def _append_locked(self, job_id: str, rec: Mapping[str, Any]) -> None:
        if self._closed:
            raise RuntimeError(
                "PartStore is closed; a write now would journal "
                "without the ownership lock")
        fh = self._journals.get(job_id)
        if fh is None:
            fh = self._journals[job_id] = open(
                self._journal_path(job_id), "a", encoding="utf-8")
        fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
        fh.flush()
        os.fsync(fh.fileno())

    def _rewrite_locked(self, job_id: str, plan: Mapping[str, Any],
                        done: Iterable[PartRef]) -> None:
        """Compact: one plan line + the retained done lines, committed
        by atomic rename (a crash mid-compact keeps the old journal)."""
        fh = self._journals.pop(job_id, None)
        if fh is not None:
            fh.close()
        path = self._journal_path(job_id)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as out:
            out.write(json.dumps({"op": "plan", "plan": dict(plan)},
                                 separators=(",", ":")) + "\n")
            for ref in done:
                out.write(json.dumps({"op": "done", **ref.to_dict()},
                                     separators=(",", ":")) + "\n")
            out.flush()
            os.fsync(out.fileno())
        os.replace(tmp, path)

    def load_job(self, job_id: str) -> JobCheckpoint | None:
        """Replay one job's board journal: the latest plan record plus
        the done map recorded under it. Torn tails (a coordinator
        killed mid-append) replay as the intact prefix — one bad line
        never discards the checkpoint. None when no journal exists or
        no plan record survives."""
        path = self._journal_path(job_id)
        if not os.path.exists(path):
            return None
        plan: dict[str, Any] | None = None
        done: dict[str, PartRef] = {}
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue          # torn/rotted line: skip, keep prefix
                if rec.get("op") == "plan":
                    plan = rec.get("plan") or {}
                    done = {}         # a plan line re-anchors the job
                elif rec.get("op") == "drop":
                    done.pop(str(rec.get("key")), None)
                elif rec.get("op") == "done" and plan is not None:
                    try:
                        done[str(rec["key"])] = PartRef(
                            job_id=job_id, key=str(rec["key"]),
                            path=str(rec["path"]),
                            digests=tuple(str(d)
                                          for d in rec["digests"]),
                            nbytes=int(rec["nbytes"]))
                    except (KeyError, TypeError, ValueError):
                        continue      # malformed record: worth nothing
        if plan is None:
            return None
        return JobCheckpoint(plan=plan, done=done)

    def begin_job(self, job_id: str,
                  plan: Mapping[str, Any]) -> dict[str, PartRef]:
        """(Re-)anchor a job's checkpoint at `plan`. When the existing
        journal's plan signature matches ``plan["sig"]``, the done
        records for keys still in the plan are RETAINED and returned —
        the crash-resume path rehydrates from them (after verifying
        the spooled bytes). Any other case (no journal, signature
        drift, keys that left the plan) resets: stale parts encoded
        under different settings must never rehydrate, so their spool
        files are dropped with the records."""
        ck = self.load_job(job_id)
        keys = {str(s["key"]) for s in plan.get("shards", ())}
        retained: dict[str, PartRef] = {}
        dropped: list[PartRef] = []
        if ck is not None and ck.plan.get("sig") == plan.get("sig"):
            for key, ref in ck.done.items():
                if key in keys and os.path.exists(ref.path):
                    retained[key] = ref
                else:
                    dropped.append(ref)
        elif ck is not None:
            dropped.extend(ck.done.values())
        with self._lock:
            self._rewrite_locked(job_id, plan, retained.values())
            for ref in dropped:
                self._unlink_part_locked(ref.path)
            # sweep spool files no retained record names (orphans from
            # a crash between rename and journal append, or a stale
            # plan's leftovers)
            keep = {os.path.realpath(r.path) for r in retained.values()}
            sdir = self._spool_dir(job_id)
            try:
                with os.scandir(sdir) as it:
                    orphans = [e.path for e in it
                               if e.name.endswith(".part")
                               and os.path.realpath(e.path) not in keep]
            except OSError:
                orphans = []
            for p in orphans:
                self._unlink_part_locked(p)
            self._set_gauge_locked()
        return retained

    # -- spool ---------------------------------------------------------

    @staticmethod
    def _frame_digests(data: bytes, segments) -> tuple[str, ...]:
        """Per-segment digests lifted from the `pack_parts` header —
        already computed by the sender and (on the ingest path)
        already VERIFIED by unpack_parts, so spooling never re-hashes
        the payloads. Records without a digest (pre-digest workers)
        hash their payload here as the fallback."""
        hlen = int.from_bytes(data[:4], "big")
        header = json.loads(data[4:4 + hlen])
        out = []
        for rec, seg in zip(header["segments"], segments):
            d = rec.get("sha256")
            out.append(str(d) if d else segment_sha256(seg.payload))
        return tuple(out)

    def spool(self, job_id: str, key: str, segments,
              data: bytes | None = None) -> tuple[PartRef, str]:
        """Stream one part to a job-scoped temp file (the `pack_parts`
        framing, digests embedded), fsync'd. `data` — when the caller
        already holds the exact wire bytes (the /work ingest path) —
        is spooled verbatim instead of re-serializing the segments.
        Returns the (ref, temp path); the caller either
        :meth:`commit`\\ s it under its own acceptance lock or
        :meth:`discard`\\ s it. The final path is keyed by the
        run-STABLE plan key, so a resumed run finds the part
        regardless of the run token."""
        if data is None:
            from .remote import pack_parts

            data = pack_parts(segments)
        digests = self._frame_digests(data, segments)
        sdir = self._spool_dir(job_id)
        os.makedirs(sdir, exist_ok=True)
        path = os.path.join(sdir, f"{key}.part")
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
        with open(tmp, "wb") as fp:
            fp.write(data)
            fp.flush()
            os.fsync(fp.fileno())
        return PartRef(job_id=job_id, key=key, path=path,
                       digests=digests, nbytes=len(data)), tmp

    def commit(self, ref: PartRef, tmp: str) -> None:
        """Atomically publish a spooled temp into place and journal the
        done record. Rename-before-journal: a crash between the two
        leaves an orphan part file (reaped by the next begin_job), a
        journal record can never point at missing bytes."""
        with self._lock:
            had = 0
            try:
                had = os.stat(ref.path).st_size
            except OSError:
                pass
            os.replace(tmp, ref.path)
            self._spool_bytes += ref.nbytes - had
            self._append_locked(ref.job_id, {"op": "done",
                                             **ref.to_dict()})
            self._set_gauge_locked()

    def discard(self, tmp: str) -> None:
        """Drop an uncommitted spool temp (the board refused the part:
        duplicate after DONE, superseded entry)."""
        try:
            os.unlink(tmp)
        except OSError:
            pass

    def _unlink_part_locked(self, path: str) -> None:
        try:
            size = os.stat(path).st_size
            os.unlink(path)
            self._spool_bytes -= size
        except OSError:
            pass

    # -- read-back + verification --------------------------------------

    def read_part(self, ref: PartRef, verify: bool = True):
        """Load one spooled part back into EncodedSegments. With
        `verify` (the default), every payload is re-hashed against the
        digests recorded at accept time — the stitcher's last gate, so
        a bit that flipped on disk raises :class:`PartIntegrityError`
        instead of landing in the output tree."""
        from .remote import unpack_parts

        try:
            with open(ref.path, "rb") as fp:
                data = fp.read()
        except OSError as exc:
            raise PartIntegrityError(
                f"spooled part {ref.path} unreadable: {exc}")
        try:
            # the wire framing's own digest check runs here too when
            # verifying (defense in depth: header rot raises, not
            # mis-parses)
            segments = unpack_parts(data, verify=verify)
        except PartIntegrityError:
            raise
        except ValueError as exc:
            raise PartIntegrityError(
                f"spooled part {ref.path} is torn: {exc}")
        if verify:
            got = tuple(segment_sha256(s.payload) for s in segments)
            if got != ref.digests:
                raise PartIntegrityError(
                    f"spooled part {ref.path} does not match its "
                    f"recorded digests (storage corruption)")
        return segments

    def verify_part(self, ref: PartRef) -> bool:
        """True iff the spooled part still matches its manifest — the
        resume path's gate before rehydrating a shard as DONE."""
        try:
            self.read_part(ref, verify=True)
            return True
        except PartIntegrityError:
            return False

    def drop_done(self, job_id: str, key: str, ref: PartRef) -> None:
        """Forget one done record (resume verification failed): unlink
        the corrupt part and journal the retraction so a second
        restart does not trust it either."""
        with self._lock:
            self._unlink_part_locked(ref.path)
            self._append_locked(job_id, {"op": "drop", "key": key})
            self._set_gauge_locked()

    def clear_job(self, job_id: str) -> None:
        """Drop a finished job's journal + spool tree (the output is
        committed; the checkpoint has nothing left to protect)."""
        with self._lock:
            fh = self._journals.pop(job_id, None)
            if fh is not None:
                fh.close()
            try:
                os.unlink(self._journal_path(job_id))
            except OSError:
                pass
            sdir = self._spool_dir(job_id)
            freed = 0
            try:
                with os.scandir(sdir) as it:
                    freed = sum(e.stat().st_size for e in it
                                if e.name.endswith(".part"))
            except OSError:
                pass
            shutil.rmtree(sdir, ignore_errors=True)
            self._spool_bytes -= freed
            self._set_gauge_locked()
