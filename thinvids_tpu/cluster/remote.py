"""Remote worker execution backend: encode shards over HTTP.

The capability VERDICT C10/A8 called out as missing: registered remote
agents could heartbeat but "never receive work". This module is the
paper's farm made real — a job's GOP ranges are sharded across worker
daemons on other hosts, each worker encodes its shard on its own device
mesh and streams the encoded part back, and the coordinator
concat-stitches the parts through the same stamp/seam-safe path the
local executor uses (closed GOPs + idr_pic_id offsets keep the stitched
bitstream bit-identical to a single-process encode).

Control flow is PULL-based, like the reference's Huey consumers popping
a Redis queue (/root/reference/worker/tasks.py:1167-1281): workers POST
``/work/claim`` on the coordinator API, encode, then stream the part to
``/work/part/<shard>``; a failed shard is reported on ``/work/status``.
Pull keeps the coordinator passive — no reverse connections into NATed
workers — and makes worker death purely a lease problem.

Robustness is lease-based:

- every ASSIGNED shard carries a deadline; `requeue_expired` returns it
  to PENDING (with exponential backoff) when the lease runs out or the
  worker's registry heartbeat goes stale (SIGKILL mid-shard);
- a worker accumulating `remote_worker_max_failures` CONSECUTIVE
  failures is quarantined via `WorkerRegistry.set_disabled`, exactly
  like the operator's /nodes/disable;
- a shard burning `part_failure_max_retries` attempts fails the job
  with host attribution;
- no live eligible worker for `remote_no_worker_grace_s` while shards
  are open fails the job instead of hanging.

`assign_roles`' pipeline/encode split governs placement: encode-role
workers always claim; pipeline-role workers are held back for the
pipeline stages unless the farm has no encode-role workers at all (a
two-node farm must not deadlock).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import socket
import struct
import threading
import time
from typing import TYPE_CHECKING, Any, Callable, Iterable, Mapping

from ..core.status import ShardState, Status
from ..core.types import (ChromaFormat, EncodedSegment, GopSpec, SegmentPlan,
                          VideoMeta)
from ..obs import flight as obs_flight
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from .executor import HaltedError, LocalExecutor
from .jobs import Job
from .partstore import PartIntegrityError, PartRef, PartStore

if TYPE_CHECKING:
    from .coordinator import Coordinator

# ---------------------------------------------------------------------------
# wire helpers
# ---------------------------------------------------------------------------


def meta_to_dict(meta: VideoMeta) -> dict[str, Any]:
    d = dataclasses.asdict(meta)
    d["chroma"] = meta.chroma.name
    return d


def meta_from_dict(d: Mapping[str, Any]) -> VideoMeta:
    data = dict(d)
    data["chroma"] = ChromaFormat[data.get("chroma", "YUV420")]
    known = {f.name for f in dataclasses.fields(VideoMeta)}
    return VideoMeta(**{k: v for k, v in data.items() if k in known})


def pack_parts(segments: Iterable[EncodedSegment]) -> bytes:
    """Binary part framing: 4-byte BE header length + JSON segment
    directory + concatenated Annex-B payloads. The payload bytes ship
    raw (no base64 inflation) — the part stream IS the scarce resource
    on a farm's uplink, the reason the reference PUT raw chunks at its
    stitcher (/root/reference/worker/tasks.py:1667-1674). Every
    segment record carries its payload's sha256 so a flipped bit on
    the wire (or later on the spool disk) is rejected at unpack, never
    stitched silently."""
    from .partstore import segment_sha256

    segments = list(segments)
    header = json.dumps({
        "segments": [{
            "index": s.gop.index,
            "start_frame": s.gop.start_frame,
            "num_frames": s.gop.num_frames,
            "idr": s.gop.idr,
            "frame_sizes": list(s.frame_sizes),
            "size": len(s.payload),
            "sha256": segment_sha256(s.payload),
        } for s in segments],
    }, separators=(",", ":")).encode()
    return b"".join([struct.pack(">I", len(header)), header]
                    + [s.payload for s in segments])


def unpack_parts(data: bytes, verify: bool = True) -> list[EncodedSegment]:
    """Inverse of :func:`pack_parts`; raises ValueError on torn frames
    (a truncated upload must not stitch silently) and — with `verify`,
    the default — PartIntegrityError when a payload's sha256 no longer
    matches its header record (pre-digest frames verify trivially;
    `part_integrity=False` turns the digest check off)."""
    from .partstore import PartIntegrityError, segment_sha256

    if len(data) < 4:
        raise ValueError("part frame too short")
    hlen = struct.unpack(">I", data[:4])[0]
    if 4 + hlen > len(data):
        raise ValueError("part header exceeds frame")
    header = json.loads(data[4:4 + hlen])
    segments: list[EncodedSegment] = []
    off = 4 + hlen
    for rec in header["segments"]:
        size = int(rec["size"])
        payload = data[off:off + size]
        if len(payload) != size:
            raise ValueError("part payload truncated")
        off += size
        want = rec.get("sha256")
        if verify and want and segment_sha256(payload) != str(want):
            raise PartIntegrityError(
                f"segment {rec.get('index')} payload does not match "
                f"its sha256 (corrupt in transfer or storage)")
        segments.append(EncodedSegment(
            gop=GopSpec(index=int(rec["index"]),
                        start_frame=int(rec["start_frame"]),
                        num_frames=int(rec["num_frames"]),
                        idr=bool(rec.get("idr", True))),
            payload=payload,
            frame_sizes=tuple(int(x) for x in rec["frame_sizes"])))
    if off != len(data):
        raise ValueError("trailing bytes after last part payload")
    return segments


# ---------------------------------------------------------------------------
# coordinator side: shards + board
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Shard:
    """One leased unit of a job's encode — either a contiguous GOP
    *range* (the classic farm shape: whole GOPs on one worker) or a
    frame *band* (farm SFE: a contiguous slice of the job's global
    band layout, every GOP, with per-frame halo exchange against the
    sibling band shards — parallel/sfefarm.py). One worker holds the
    lease at a time (the analog of a reference 'part' task on the
    encode queue)."""

    id: str
    job_id: str
    input_path: str
    meta: VideoMeta                 # SOURCE meta (what the worker decodes)
    gops: tuple[GopSpec, ...]       # GLOBAL indices / frame ranges
    qp: int
    gop_frames: int
    timeout_s: float
    #: shard shape: "gop" (GOP range — today's wire form, absent on
    #: the wire for rolling-upgrade compat) or "band" (frame-band
    #: slice). Workers that don't recognize a shape reject it as
    #: UNSUPPORTED: the board requeues with NO attempt burned and
    #: stops offering the shard to that host.
    shape: str = "gop"
    #: band shape only: this shard's [band_start, band_start +
    #: band_count) slice of the job's `total_bands`-band layout, plus
    #: the pinned halo depth every sibling agrees on
    band_start: int = 0
    band_count: int = 0
    total_bands: int = 0
    halo_rows: int = 0
    #: hosts that rejected this shard's shape (old workers): the claim
    #: never offers it to them again, so an unsupported rejection
    #: cannot ping-pong
    no_hosts: tuple[str, ...] = ()
    # ABR ladder (abr/ladder.py): which rendition this shard encodes;
    # empty = plain single-rendition shard. Scaled rungs carry their
    # target dims — the worker derives them on ITS device mesh from the
    # source-resolution frames it decodes anyway.
    rung: str = ""
    rung_width: int = 0
    rung_height: int = 0
    # QoS class rank (cluster/qos.py: live=0 > ladder=1 > batch=2):
    # claims hand out the best class first, and batch-rank shards are
    # requeued/eligibility-gated while a live job is over deadline
    priority: int = 2
    # tenant namespace (farm/tenancy.py): within a priority class the
    # claim picks the most-underserved tenant first (weighted fair
    # share over currently-ASSIGNED shards), so one tenant's backlog
    # cannot monopolize the farm
    tenant: str = "default"
    # distributed-trace context (obs/trace): the job's trace id rides
    # the claim descriptor to the worker, which echoes it back in the
    # X-Tvt-Trace header on its /work uploads — a farm job's worker
    # spans land in the SAME coordinator-side trace. "" = unsampled.
    trace_id: str = ""
    # run-STABLE plan key ("<rung->NNNN"): the durable checkpoint and
    # spool are keyed by this, not by the run-scoped id, so a resumed
    # run's fresh token still finds the crashed run's accepted parts
    # (cluster/partstore.py)
    key: str = ""
    state: ShardState = ShardState.PENDING
    attempt: int = 0                # completed (failed) attempts so far
    not_before: float = 0.0         # backoff gate for re-claims
    assigned_host: str = ""
    assigned_at: float = 0.0
    deadline_at: float = 0.0
    finished_host: str = ""
    elapsed_s: float = 0.0
    fail_reason: str = ""
    #: rehydrated DONE from the verified spool on crash-resume (never
    #: re-encoded this run)
    resumed: bool = False
    #: lifetime digest rejections against this shard: transient flips
    #: requeue free, but past ShardBoard.INTEGRITY_FREE_REJECTS the
    #: rejection escalates into the normal failure path so a
    #: deterministic corruption source cannot livelock the job
    rejects: int = 0
    #: durable part reference once DONE (partstore.PartRef fields):
    #: the payload itself lives on the spool disk, not in this record
    part_path: str = ""
    part_digests: tuple[str, ...] = ()
    part_bytes: int = 0
    #: transient: populated from the spool by take_shards for the
    #: stitcher; empty while the shard sits DONE on the board
    segments: list[EncodedSegment] = dataclasses.field(default_factory=list)

    @property
    def start_frame(self) -> int:
        return self.gops[0].start_frame

    @property
    def num_frames(self) -> int:
        return self.gops[-1].end_frame - self.gops[0].start_frame

    def descriptor(self) -> dict[str, Any]:
        """Wire form handed to a claiming worker. GOP indices and frame
        ranges are SHARD-LOCAL; the worker re-bases via the encoder's
        gop_index_offset / frame_offset so emitted segments (and their
        idr_pic_id) are globally consistent — the same continuation
        mechanism the elastic replan uses (cluster/executor.py)."""
        g0, f0 = self.gops[0].index, self.gops[0].start_frame
        desc = {
            "id": self.id,
            "job_id": self.job_id,
            "input_path": self.input_path,
            "meta": meta_to_dict(self.meta),
            "start_frame": f0,
            "num_frames": self.num_frames,
            "gop_index_offset": g0,
            "gops": [[g.index - g0, g.start_frame - f0, g.num_frames]
                     for g in self.gops],
            "qp": self.qp,
            "gop_frames": self.gop_frames,
            "attempt": self.attempt,
            "timeout_s": self.timeout_s,
        }
        if self.rung:
            desc["rung"] = {"name": self.rung, "width": self.rung_width,
                            "height": self.rung_height}
        if self.shape != "gop":
            # explicit shape tag ONLY for new shapes: a GOP-range
            # shard's wire form is unchanged, so a rolling upgrade
            # keeps old workers serving GOP shards while band shards
            # flow to new ones (unknown shape → unsupported-requeue)
            desc["shape"] = self.shape
        if self.shape == "band":
            desc["band"] = {
                "start": self.band_start, "count": self.band_count,
                "total": self.total_bands, "halo_rows": self.halo_rows,
                # groups + halo generation are board state (the full
                # sibling partition and the current exchange epoch):
                # ShardBoard.claim fills them in at grant time
            }
        if self.trace_id:
            desc["trace"] = {"trace_id": self.trace_id,
                             "job_id": self.job_id}
        return desc


@dataclasses.dataclass
class _JobEntry:
    shards: dict[str, Shard]
    max_attempts: int
    backoff_s: float
    quarantine_after: int
    #: run token of the executor run that installed this entry: a
    #: superseded run's cleanup must not cancel its successor's shards
    owner_token: str = ""
    failed_reason: str = ""
    failed_host: str = ""
    retried_parts: int = 0
    #: halo-exchange generation for band shards (cluster/halo.py):
    #: bumped whenever a band shard leaves its lease abnormally — the
    #: sibling group restarts together (the exchange is lockstep) and
    #: stale workers' halo traffic answers `stale`
    halo_gen: int = 1


class ShardBoard:
    """Thread-safe work queue the coordinator API exposes to workers.

    One board serves every job the RemoteExecutor runs; claims hand out
    the oldest eligible PENDING shard across jobs (FIFO keeps the drain
    scheduler's admission assumptions intact)."""

    def __init__(self, coordinator: "Coordinator",
                 clock: Callable[[], float] = time.time,
                 spool_dir: str | None = None) -> None:
        self.coordinator = coordinator
        self._clock = clock
        self._lock = threading.Lock()
        self._jobs: dict[str, _JobEntry] = {}
        self._order: list[str] = []     # shard ids in dispatch order
        #: ring of recent shard completions for the dashboard
        self._recent: list[dict[str, Any]] = []
        #: lifetime QoS preemptions (ASSIGNED batch shards requeued)
        self._preempted = 0
        #: lifetime digest rejections (transfer/storage corruption —
        #: requeued with NO attempt burned) and crash-resume reuses
        self._integrity_rejects = 0
        self._resumed = 0
        #: durable part spool + board checkpoint (partstore.PartStore),
        #: created lazily so claim-only boards never touch disk. The
        #: RemoteExecutor passes a STABLE dir (part_spool_dir setting,
        #: else under its output dir) so a restarted coordinator finds
        #: the crashed run's parts; unanchored boards (unit tests)
        #: spool into a private temp dir.
        self._spool_dir = spool_dir
        self._parts: PartStore | None = None
        #: cross-host halo rendezvous for band shards (cluster/halo.py;
        #: served at /work/halo). Generation-fenced by the entries'
        #: halo_gen.
        from .halo import HaloRelay

        self.halo = HaloRelay()
        #: claim affinity: host → {input_path: last claimed END frame}
        #: — the claim prefers shards whose source range continues what
        #: the worker's source cache already covers (a neighboring
        #: range re-claims decode the prefix otherwise). Bounded per
        #: host; purely a scoring hint, no protocol change.
        self._affinity: dict[str, dict[str, int]] = {}

    @property
    def parts(self) -> PartStore:
        with self._lock:
            if self._parts is None:
                root = self._spool_dir
                if not root:
                    root = str(self.coordinator._settings_fn().get(
                        "part_spool_dir", "") or "")
                if not root:
                    import tempfile

                    root = tempfile.mkdtemp(prefix="tvt-part-spool-")
                self._parts = PartStore(root, clock=self._clock)
            return self._parts

    # -- job lifecycle (RemoteExecutor) --------------------------------

    def add_job(self, job_id: str, shards: list[Shard], max_attempts: int,
                backoff_s: float, quarantine_after: int,
                token: str = "") -> None:
        with self._lock:
            stale = self._jobs.pop(job_id, None)
            if stale is not None:
                # restart raced the old run's cleanup: the new entry
                # supersedes it outright
                self._order = [sid for sid in self._order
                               if sid not in stale.shards]
            entry = _JobEntry(
                shards={s.id: s for s in shards},
                max_attempts=max_attempts, backoff_s=backoff_s,
                quarantine_after=quarantine_after, owner_token=token,
                # the halo generation CONTINUES across a superseding
                # re-add: the stale entry's in-flight workers carry its
                # gen and must see `stale`, not adopt the new group
                halo_gen=(stale.halo_gen + 1 if stale is not None
                          else 1))
            self._jobs[job_id] = entry
            self._order.extend(s.id for s in shards)
            banded = any(s.shape == "band" for s in shards)
            gen = entry.halo_gen
        if banded:
            # seed the halo relay: only SEEDED jobs may rendezvous
            # (posts/waits against an unknown job answer `stale`
            # instead of resurrecting a cleared entry — halo.py)
            self.halo.set_gen(job_id, gen)

    def rehydrate_done(self, shard: Shard, ref: PartRef) -> None:
        """Crash-resume: mark one freshly planned shard DONE from a
        VERIFIED spooled part (cluster/partstore.py) before the plan
        posts to the board — the work is NOT re-encoded and the new
        run's board entry starts with the crashed run's progress. The
        PENDING guard makes the edge locally provable (PENDING→DONE is
        the declared late-part edge: a durable part IS a part that
        arrived before any lease)."""
        with self._lock:
            if shard.state is not ShardState.PENDING:
                return
            shard.state = ShardState.DONE
            shard.segments = []
            shard.part_path = ref.path
            shard.part_digests = ref.digests
            shard.part_bytes = ref.nbytes
            shard.finished_host = "resume"
            shard.resumed = True
            self._resumed += 1
        obs_metrics.RESUME_SHARDS_REUSED.inc()

    def note_spool_corruption(self, job_id: str, key: str,
                              reason: str) -> None:
        """Resume verification found a spooled part that no longer
        matches its manifest: counted like an ingest digest rejection
        (the shard simply re-encodes — no attempt burned, the record
        is retracted by the caller)."""
        with self._lock:
            self._integrity_rejects += 1
        obs_metrics.PART_INTEGRITY_FAILURES.inc()
        self.coordinator.activity.emit(
            "integrity",
            f"spooled part {key} failed its resume digest check; "
            f"shard will re-encode: {reason}", job_id=job_id)

    def cancel_job(self, job_id: str, token: str | None = None) -> None:
        """Drop a job's board state. With `token` set, only the entry
        that run installed is removed — a halted run waking after a
        restart must not cancel the new run's shards (the board analog
        of the coordinator's run-token fence)."""
        with self._lock:
            entry = self._jobs.get(job_id)
            if entry is None:
                return
            if token is not None and entry.owner_token != token:
                return
            del self._jobs[job_id]
            self._order = [sid for sid in self._order
                           if sid not in entry.shards]
        self.halo.clear_job(job_id)

    def job_progress(self, job_id: str) -> tuple[int, int, int, str, str]:
        """(gops_done, gops_total, parts_retried, failed_reason,
        failed_host) for one job."""
        with self._lock:
            entry = self._jobs.get(job_id)
            if entry is None:
                return 0, 0, 0, "cancelled", ""
            done = sum(len(s.gops) for s in entry.shards.values()
                       if s.state is ShardState.DONE)
            total = sum(len(s.gops) for s in entry.shards.values())
            return (done, total, entry.retried_parts, entry.failed_reason,
                    entry.failed_host)

    def take_shards(self, job_id: str,
                    token: str | None = None) -> list[Shard]:
        """Collect a fully-DONE job's shard records (segments + rung
        tags) and drop its board state. Token-fenced like cancel_job: a
        stale run must not pop the entry a restarted run installed.
        Raises HaltedError when fenced out, RuntimeError if any shard
        is not DONE (caller raced).

        Segments load back from the durable spool here — OUTSIDE the
        board lock — and every payload re-verifies against the digests
        recorded at accept time (`part_integrity`): a bit that flipped
        on the spool disk fails the collect (the job fails with
        attribution and its checkpoint survives for a verified resume)
        instead of reaching the stitcher."""
        with self._lock:
            entry = self._jobs.get(job_id)
            if entry is None or (token is not None
                                 and entry.owner_token != token):
                raise HaltedError(
                    f"job {job_id} board entry superseded before "
                    f"collection")
            del self._jobs[job_id]
            self._order = [sid for sid in self._order
                           if sid not in entry.shards]
            for shard in entry.shards.values():
                if shard.state is not ShardState.DONE:
                    raise RuntimeError(
                        f"collected shard {shard.id} in state "
                        f"{shard.state.value}")
            shards = list(entry.shards.values())
        self.halo.clear_job(job_id)
        verify = bool(self.coordinator._settings_fn().get(
            "part_integrity", True))
        parts = self.parts
        for shard in shards:
            if shard.segments or not shard.part_path:
                continue            # legacy/in-memory record
            ref = PartRef(job_id=job_id, key=shard.key or shard.id,
                          path=shard.part_path,
                          digests=shard.part_digests,
                          nbytes=shard.part_bytes)
            try:
                shard.segments = parts.read_part(ref, verify=verify)
            except PartIntegrityError as exc:
                with self._lock:
                    # keep the snapshot counter in step with the
                    # Prometheus total: the dashboard/bench read both
                    self._integrity_rejects += 1
                obs_metrics.PART_INTEGRITY_FAILURES.inc()
                raise RuntimeError(
                    f"shard {shard.id}: spooled part failed its "
                    f"pre-stitch digest check ({exc}); refusing to "
                    f"stitch corrupt bytes") from exc
        return shards

    def take_segments(self, job_id: str,
                      token: str | None = None) -> list[EncodedSegment]:
        """Flattened-segment view of :meth:`take_shards` (the
        single-rendition path)."""
        return [seg for shard in self.take_shards(job_id, token=token)
                for seg in shard.segments]

    # -- worker-facing API (via api/server.py /work/* routes) ----------

    def _worker_eligible_locked(self, host: str, now: float) -> bool:
        """Placement gate: quarantined AND stale workers never claim
        (liveness is re-checked HERE, under the lock, from the
        registry's current state — a worker whose heartbeat TTL lapsed
        used to be able to win a shard in a race against
        ``requeue_expired``'s pre-lock active-set snapshot, which then
        immediately swept the fresh lease and burned an attempt); the
        elastic-farm lifecycle gate refuses DRAINING/SUSPENDED workers
        outright (farm/controller.py — the model-checked invariant);
        then the pipeline/encode role split governs who encodes — an
        encode-role worker always claims, a pipeline-role worker is
        held in reserve for the pipeline stages and claims only
        OVERFLOW: when no live encode-role host is a claim-capable
        worker, or when more shards are pending than live encode
        workers can start on (reserving it would just idle the farm).
        Daemons self-identify with ``worker: true`` in their heartbeat
        metrics; metrics-only agents and the coordinator's device
        pseudo-hosts can hold the encode role but can't take work, and
        must not starve the farm."""
        reg = self.coordinator.registry
        snap = self.coordinator._settings_fn()
        ttl = float(snap.metrics_ttl_s)
        reg.assign_roles(int(snap.pipeline_worker_count))
        workers = {w.host: w for w in reg.all()}
        me = workers.get(host)
        if me is None or me.disabled or now - me.last_seen > ttl:
            return False
        farm = getattr(self.coordinator, "farm", None)
        if farm is not None and not farm.claim_allowed(host):
            return False
        if me.role == "encode":
            return True
        active = reg.active(ttl, now=now)
        encode_workers = sum(1 for w in active
                             if w.role == "encode" and w.metrics.get("worker"))
        if encode_workers == 0:
            return True
        pending = sum(
            1 for entry in self._jobs.values()
            for s in entry.shards.values()
            if s.state is ShardState.PENDING and now >= s.not_before)
        return pending > encode_workers

    def _batch_gated_locked(self) -> bool:
        """True while the QoS controller has batch work preempted for
        a live job over its part deadline (cluster/qos.py)."""
        from .qos import QosController

        qos: QosController | None = getattr(self.coordinator, "qos", None)
        return qos is not None and not qos.batch_allowed()

    def claim(self, host: str) -> dict[str, Any] | None:
        """Lease the best eligible PENDING shard to `host` — highest
        QoS class first (live > ladder > batch), most-underserved
        tenant within a class (weighted fair share over the tenants'
        currently-ASSIGNED shards, farm/tenancy.py), oldest within
        that; batch-rank shards are withheld entirely while a live
        job is over its deadline. None when no work (or the host may
        not take any). A GRANTED claim doubles as a liveness
        heartbeat — a worker that demonstrably encoded its way here is
        alive — but an idle poll does not: a worker whose agent
        heartbeat lapsed cannot win work merely by asking (the
        eligibility gate re-checks the TTL under the lock)."""
        from ..farm.tenancy import fair_usage, parse_tenant_shares
        from .qos import BATCH_RANK

        host = (host or "").strip()
        if not host:
            return None
        now = self._clock()
        granted: dict[str, Any] | None = None
        with self._lock:
            if not self._worker_eligible_locked(host, now):
                return None
            batch_gated = self._batch_gated_locked()
            shares = parse_tenant_shares(
                self.coordinator._settings_fn().get("tenant_shares", ""))
            usage: dict[str, float] = {}
            for entry in self._jobs.values():
                for s in entry.shards.values():
                    if s.state is ShardState.ASSIGNED:
                        usage[s.tenant] = usage.get(s.tenant, 0.0) + 1.0
            seen = self._affinity.get(host, {})
            host_devices = 1
            for wk in self.coordinator.registry.all():
                if wk.host == host:
                    host_devices = max(1, int((wk.metrics or {}).get(
                        "worker_devices", 1) or 1))
                    break
            best: Shard | None = None
            best_key: tuple[int, float, int, int] | None = None
            for pos, sid in enumerate(self._order):
                shard = self._find_locked(sid)
                if (shard is None or shard.state is not ShardState.PENDING
                        or now < shard.not_before):
                    continue
                if batch_gated and shard.priority >= BATCH_RANK:
                    continue
                if host in shard.no_hosts:
                    continue        # this host rejected the shape
                if shard.shape == "band" \
                        and shard.band_count > host_devices:
                    # a band slice never fits a smaller mesh than it
                    # was planned for: granting would fail the encode,
                    # burn an attempt AND restart the lockstep group —
                    # an under-provisioned late joiner must simply
                    # never see the shard
                    continue
                # affinity score (0 best): the worker's source cache
                # already covers this input and the shard CONTINUES
                # its last range (the cached demux state decodes
                # forward, no prefix re-walk) > same input (open
                # source reused) > cold open. Strictly below priority
                # and tenant fairness — a hint, never a policy.
                if shard.input_path in seen:
                    affinity = 0 if seen[shard.input_path] \
                        == shard.start_frame else 1
                else:
                    affinity = 2
                key = (shard.priority,
                       fair_usage(shares, usage, shard.tenant),
                       affinity, pos)
                if best_key is None or key < best_key:
                    best, best_key = shard, key
            if best is not None and best.state is ShardState.PENDING:
                # the re-assert is free under the lock and makes the
                # lease edge locally provable: only PENDING→ASSIGNED
                # exists (TVT-M001 audits this site against the
                # declared shard table)
                best.state = ShardState.ASSIGNED
                best.assigned_host = host
                best.assigned_at = now
                best.deadline_at = now + best.timeout_s
                granted = best.descriptor()
                if best.shape == "band":
                    entry = self._jobs[best.job_id]
                    granted["band"]["gen"] = entry.halo_gen
                    granted["band"]["groups"] = sorted(
                        [s.band_start, s.band_start + s.band_count]
                        for s in entry.shards.values()
                        if s.shape == "band")
                # affinity record: remember where this host's source
                # cursor for the input will END (bounded per host)
                rec = self._affinity.setdefault(host, {})
                rec[best.input_path] = best.gops[-1].end_frame
                while len(rec) > 4:
                    rec.pop(next(iter(rec)))
                # grant-heartbeat INSIDE the lock: the lease and the
                # liveness refresh commit atomically w.r.t. the sweep
                # (which reads the registry under this same lock), so
                # a fresh lease can never look orphaned
                self.coordinator.registry.heartbeat(host, now=now)
        return granted

    def submit_part(self, shard_id: str, host: str,
                    segments: list[EncodedSegment],
                    raw: bytes | None = None) -> bool:
        """Accept one encoded part. First result wins: a part from a
        worker whose lease already expired is still accepted while the
        shard is open (the encode is deterministic, so any completed
        attempt is THE answer); a duplicate after DONE is dropped.

        The payload is streamed to the durable part spool (temp +
        fsync + atomic rename, digests journaled — partstore.py)
        BEFORE the shard flips DONE, and the board keeps only the
        PartRef: a DONE shard pins no payload in coordinator RAM, and
        a coordinator crash after this call resumes the shard from
        disk instead of re-encoding it."""
        now = self._clock()
        with self._lock:
            shard = self._find_locked(shard_id)
            if shard is None or not shard.state.is_open:
                return False
            want = sorted(g.index for g in shard.gops)
            got = sorted(s.gop.index for s in segments)
            if want != got:
                raise ValueError(
                    f"part for shard {shard_id} covers GOPs {got}, "
                    f"expected {want}")
            job_id, key = shard.job_id, shard.key or shard.id
        # spool AND commit (rename + journal fsync) OUTSIDE the board
        # lock — disk syncs must not stall concurrent claims/sweeps.
        # Committing before the accept re-check is safe: a done record
        # the board then refuses is harmless — a same-key duplicate
        # carries identical bytes (deterministic encode, gop-validated
        # above), and an orphan from a cancelled entry is reaped by
        # the next begin_job; on a FAILED shard the record even lets a
        # later resume rehydrate the finished work.
        parts = self.parts
        ref, tmp = parts.spool(job_id, key, segments,
                               data=bytes(raw) if raw is not None
                               else None)
        parts.commit(ref, tmp)
        with self._lock:
            shard = self._find_locked(shard_id)
            if shard is None or not shard.state.is_open \
                    or shard.job_id != job_id:
                return False
            shard.state = ShardState.DONE
            shard.segments = []           # the spool holds the bytes
            shard.part_path = ref.path
            shard.part_digests = ref.digests
            shard.part_bytes = ref.nbytes
            shard.finished_host = host
            shard.elapsed_s = now - shard.assigned_at if shard.assigned_at \
                else 0.0
            self._recent.append({
                "shard": shard_id, "job_id": shard.job_id, "host": host,
                "gops": len(shard.gops), "elapsed_s": round(shard.elapsed_s, 3),
                "bytes": ref.nbytes,
                "attempt": shard.attempt + 1, "ts": now,
            })
            del self._recent[:-50]
            job_id, elapsed = shard.job_id, shard.elapsed_s
            assigned_at, gops = shard.assigned_at, len(shard.gops)
        # coordinator-side shard span (lease → accepted part): the
        # farm-level skeleton of the job's trace, which the worker's
        # own uploaded spans then fill in. Board clocks are epoch
        # (time.time) in production, matching the span timebase.
        obs_metrics.SHARD_CLAIM_SECONDS.observe(max(0.0, elapsed))
        obs_trace.TRACE.record_span(
            job_id, "shard", t0=assigned_at or now, dur_s=elapsed,
            host=host, tags={"shard": shard_id, "gops": gops})
        self.coordinator.registry.record_shard_result(host, ok=True)
        return True

    #: digest rejections one shard absorbs for free (requeue, no
    #: attempt burned) before escalating into the normal failure path:
    #: a deterministically corrupting link would otherwise
    #: claim/encode/reject hot-loop forever — the lease never expires
    #: (each cycle is fast) and the job heartbeat never stalls, so
    #: nothing else bounds it
    INTEGRITY_FREE_REJECTS = 4

    def reject_part(self, shard_id: str, host: str, reason: str) -> None:
        """Digest-mismatch rejection at ingest: a TRANSFER fault, not a
        worker fault — the lease (when this host still holds it) is
        handed straight back with NO attempt burned, no backoff and no
        quarantine accounting (the same semantics as QoS preemption),
        and the event counts in `tvt_part_integrity_failures_total`.
        The worker retries the idempotent upload; a re-encode by
        whoever claims next is the fallback. A shard rejected more
        than INTEGRITY_FREE_REJECTS times is no longer a transient
        flip: it escalates through report_failure (attempt burned,
        backoff, quarantine accounting) so the job eventually FAILS
        with attribution instead of livelocking."""
        requeued = False
        escalate = False
        band_job = ""
        with self._lock:
            self._integrity_rejects += 1
            shard = self._find_locked(shard_id)
            if shard is not None and shard.state is ShardState.ASSIGNED \
                    and shard.assigned_host == host:
                shard.rejects += 1
                if shard.rejects > self.INTEGRITY_FREE_REJECTS:
                    escalate = True     # leave ASSIGNED: the failure
                                        # path below owns the requeue
                else:
                    shard.state = ShardState.PENDING
                    shard.assigned_host = ""
                    shard.not_before = 0.0
                    requeued = True
                    if shard.shape == "band":
                        band_job = shard.job_id
        obs_metrics.PART_INTEGRITY_FAILURES.inc()
        self.coordinator.activity.emit(
            "integrity",
            f"part for shard {shard_id} from {host or 'unknown'} "
            f"rejected on digest mismatch"
            + (" (lease requeued, no attempt burned)" if requeued
               else "") + f": {reason}",
            host=host)
        if band_job:
            self._restart_band_group(band_job)
        if escalate:
            self.report_failure(
                shard_id, host,
                f"persistent part corruption: digest rejected "
                f"{self.INTEGRITY_FREE_REJECTS + 1}+ times: {reason}")

    def report_unsupported(self, shard_id: str, host: str,
                           reason: str) -> None:
        """A worker rejected the shard's SHAPE (an old daemon that
        predates frame-band shards): a capability gap, not a fault —
        the lease goes straight back with NO attempt burned, no
        backoff and no quarantine accounting, and the shard stops
        being offered to that host (`no_hosts`) so the rejection
        cannot ping-pong between the same pair forever."""
        requeued = False
        with self._lock:
            shard = self._find_locked(shard_id)
            if shard is not None and shard.state is ShardState.ASSIGNED \
                    and shard.assigned_host == host:
                shard.state = ShardState.PENDING
                shard.assigned_host = ""
                shard.not_before = 0.0
                if host not in shard.no_hosts:
                    shard.no_hosts = shard.no_hosts + (host,)
                job_id = shard.job_id
                requeued = True
        self.coordinator.activity.emit(
            "shard-requeue",
            f"shard {shard_id} shape rejected by {host or 'unknown'} "
            f"(worker too old?): requeued with no attempt burned: "
            f"{reason}", host=host)
        if requeued:
            self._restart_band_group(job_id)

    def _restart_band_group(self, job_id: str) -> None:
        """Band shards exchange halo rows in LOCKSTEP: when one of a
        job's band shards falls back to PENDING (failure, expiry,
        integrity reject, preemption, unsupported shape), its siblings
        are blocked on exchanges that will never complete — requeue
        them too. ASSIGNED siblings requeue with preemption semantics
        (NO attempt burned, their late parts still land); DONE
        siblings requeue with their spooled part RETRACTED (a finished
        slice is useless without live peers to feed the re-encoder's
        halo — the model-checked DONE→PENDING edge; the re-encode
        deterministically re-submits identical bytes). The halo
        generation bumps so in-flight workers of the old epoch see
        `stale` and abandon cleanly (cluster/halo.py). A FAILED band
        shard only bumps the generation: the job is failing, and
        retracting its siblings' finished parts would just cost the
        next resume."""
        bumped = 0
        requeued: list[tuple[str, str, str]] = []
        retract: list[PartRef] = []
        with self._lock:
            entry = self._jobs.get(job_id)
            if entry is None:
                return
            band = [s for s in entry.shards.values()
                    if s.shape == "band"]
            if not band:
                return
            restart = any(s.state is ShardState.PENDING for s in band)
            if not restart:
                if any(s.state is ShardState.FAILED for s in band):
                    entry.halo_gen += 1
                    bumped = entry.halo_gen
                else:
                    return
            else:
                for shard in band:
                    if shard.state not in (ShardState.ASSIGNED,
                                           ShardState.DONE):
                        continue
                    was = shard.state
                    if was is ShardState.DONE and shard.part_path:
                        retract.append(PartRef(
                            job_id=job_id, key=shard.key or shard.id,
                            path=shard.part_path,
                            digests=shard.part_digests,
                            nbytes=shard.part_bytes))
                    shard.state = ShardState.PENDING
                    host = shard.assigned_host or shard.finished_host
                    shard.assigned_host = ""
                    shard.not_before = 0.0
                    shard.segments = []
                    shard.part_path = ""
                    shard.part_digests = ()
                    shard.part_bytes = 0
                    shard.finished_host = ""
                    shard.resumed = False
                    requeued.append((shard.id, host, was.value))
                    if was is ShardState.ASSIGNED:
                        self._preempted += 1
                entry.halo_gen += 1
                bumped = entry.halo_gen
        self.halo.set_gen(job_id, bumped)
        if retract:
            # spool hygiene OUTSIDE the board lock (journal fsync):
            # best-effort — an undropped record is re-verified (and
            # dropped all-or-nothing) by any later resume anyway
            parts = self.parts
            for ref in retract:
                try:
                    parts.drop_done(job_id, ref.key, ref)
                except Exception:   # noqa: BLE001 - hygiene only
                    pass
        for sid, host, was in requeued:
            self.coordinator.activity.emit(
                "shard-requeue",
                f"band shard {sid} ({was}) requeued off "
                f"{host or 'unknown'}: sibling band restarted the "
                f"halo group (gen {bumped})",
                job_id=job_id, host=host)

    def report_failure(self, shard_id: str, host: str, error: str) -> None:
        """Worker-reported failure OR lease expiry: requeue with backoff
        until the attempt budget burns out, then fail the job; count the
        failure against the worker and quarantine a repeat offender.
        A failed BAND shard additionally restarts its sibling band
        group (lockstep halo exchange — see _restart_band_group)."""
        now = self._clock()
        co = self.coordinator
        with self._lock:
            shard = self._find_locked(shard_id)
            if shard is None or shard.state is not ShardState.ASSIGNED:
                return
            if shard.assigned_host != host:
                # stale report: the lease already moved on (sweep requeued
                # it and another worker holds it now) — an evicted
                # worker's failure must not burn the current holder's
                # attempt, let alone the job's budget
                return
            entry = self._jobs[shard.job_id]
            shard.attempt += 1
            shard.assigned_host = ""
            entry.retried_parts += len(shard.gops)
            if shard.attempt > entry.max_attempts:
                shard.state = ShardState.FAILED
                shard.fail_reason = (
                    f"shard {shard.id} failed after {shard.attempt} "
                    f"attempts (last on {host or 'unknown'}): {error}")
                entry.failed_reason = entry.failed_reason or shard.fail_reason
                entry.failed_host = entry.failed_host or host
            else:
                shard.state = ShardState.PENDING
                shard.not_before = now + entry.backoff_s \
                    * (2 ** (shard.attempt - 1))
            job_id = shard.job_id
            shard_tenant = shard.tenant
            shard_is_band = shard.shape == "band"
            quarantine_after = entry.quarantine_after
            # capture under the lock: a concurrent claim can flip the
            # shard back to ASSIGNED before the emit below runs, which
            # must not relabel a routine requeue as an ERROR
            event_kind = ("shard-requeue"
                          if shard.state is ShardState.PENDING else "error")
            attempt_no = shard.attempt
        co.activity.emit(
            event_kind,
            f"shard {shard_id} attempt {attempt_no} on "
            f"{host or 'unknown'} failed: {error}",
            job_id=job_id, host=host)
        obs_trace.TRACE.record_error(
            job_id, f"shard {shard_id} attempt {attempt_no} on "
                    f"{host or 'unknown'}: {error}")
        if shard_is_band:
            self._restart_band_group(job_id)
        if host:
            streak = co.registry.record_shard_result(host, ok=False)
            if streak >= quarantine_after:
                co.registry.set_disabled(
                    host, True,
                    reason=f"quarantined: {streak} consecutive shard "
                           f"failures")
                co.activity.emit(
                    "quarantine",
                    f"worker {host} quarantined after {streak} "
                    f"consecutive shard failures", host=host)
                # postmortem artifact for the job the quarantine hit:
                # its spans, the shard failures above, settings
                obs_flight.record(
                    job_id,
                    reason=f"worker {host} quarantined after {streak} "
                           f"consecutive shard failures",
                    settings=self.coordinator._settings_fn(),
                    tenant=shard_tenant)

    def requeue_expired(self) -> list[str]:
        """Lease sweep: requeue ASSIGNED shards whose deadline passed or
        whose worker's heartbeat went stale (killed mid-shard). Returns
        the requeued/failed shard ids. The active set is computed
        UNDER the board lock so a lease granted concurrently (claims
        heartbeat on grant before releasing their `now`) can never be
        judged against a staler snapshot than the one that granted
        it."""
        now = self._clock()
        snap = self.coordinator._settings_fn()
        expired: list[tuple[str, str, str]] = []
        with self._lock:
            active = {w.host for w in self.coordinator.registry.active(
                float(snap.metrics_ttl_s), now=now)}
            for entry in self._jobs.values():
                for shard in entry.shards.values():
                    if shard.state is not ShardState.ASSIGNED:
                        continue
                    if now > shard.deadline_at:
                        expired.append((shard.id, shard.assigned_host,
                                        f"lease expired after "
                                        f"{shard.timeout_s:.0f}s"))
                    elif shard.assigned_host not in active:
                        expired.append((shard.id, shard.assigned_host,
                                        "worker heartbeat lost"))
        for sid, host, why in expired:
            self.report_failure(sid, host, why)
        return [sid for sid, _h, _w in expired]

    def _preempt_where(self, keep_assigned) -> list[tuple[str, str]]:
        """Shared preemption body: requeue every ASSIGNED shard for
        which `keep_assigned(shard)` is False. NOT a failure — no
        attempt is burned, no backoff, no quarantine accounting; the
        evicted worker's late part is still accepted while the shard
        is open (first result wins, deterministic encode), so no work
        is wasted either. Counted in the snapshot's `preempted`
        figure. Returns the (shard id, evicted host) pairs."""
        requeued: list[tuple[str, str]] = []
        band_jobs: set[str] = set()
        with self._lock:
            for entry in self._jobs.values():
                for shard in entry.shards.values():
                    if shard.state is not ShardState.ASSIGNED \
                            or keep_assigned(shard):
                        continue
                    shard.state = ShardState.PENDING
                    host = shard.assigned_host
                    shard.assigned_host = ""
                    shard.not_before = 0.0
                    requeued.append((shard.id, host))
                    self._preempted += 1
                    if shard.shape == "band":
                        band_jobs.add(shard.job_id)
        for jid in band_jobs:
            # a preempted band shard strands its lockstep siblings:
            # restart the group (and stale the halo epoch) together
            self._restart_band_group(jid)
        return requeued

    def preempt_batch(self) -> int:
        """QoS preemption (cluster/qos.py): requeue every ASSIGNED
        batch-rank shard so its worker frees up for the struggling
        live edge. Returns how many shards were requeued."""
        from .qos import BATCH_RANK

        requeued = self._preempt_where(
            lambda s: s.priority < BATCH_RANK)
        for sid, host in requeued:
            self.coordinator.activity.emit(
                "qos-preempt",
                f"batch shard {sid} requeued off {host or 'unknown'} "
                f"(live deadline breach)", host=host)
        return len(requeued)

    def preempt_host(self, host: str) -> int:
        """Requeue every shard ASSIGNED to `host` — the elastic farm's
        drain-grace escape hatch (farm/controller.py): a DRAINING
        worker stuck past `drain_grace_s` has its leases handed back
        with the same preemption semantics as the QoS path (shared
        body above). Returns how many leases were requeued."""
        requeued = self._preempt_where(
            lambda s: s.assigned_host != host)
        for sid, _h in requeued:
            self.coordinator.activity.emit(
                "farm", f"shard {sid} requeued off draining worker "
                f"{host}", host=host)
        return len(requeued)

    # alias the controller calls by intent (drain-grace requeue)
    requeue_host = preempt_host

    def host_leases(self, host: str) -> int:
        """ASSIGNED shards currently leased to `host` — the drain
        controller's single-host is-it-empty-yet re-check."""
        with self._lock:
            return sum(
                1 for entry in self._jobs.values()
                for s in entry.shards.values()
                if s.state is ShardState.ASSIGNED
                and s.assigned_host == host)

    def host_lease_counts(self) -> dict[str, int]:
        """ASSIGNED shards per host in ONE locked pass — the capacity
        controller's per-tick observation (per-host host_leases calls
        would take the board lock once per worker)."""
        out: dict[str, int] = {}
        with self._lock:
            for entry in self._jobs.values():
                for s in entry.shards.values():
                    if s.state is ShardState.ASSIGNED:
                        out[s.assigned_host] = \
                            out.get(s.assigned_host, 0) + 1
        return out

    def queue_depth(self, now: float | None = None) -> dict[int, int]:
        """Claimable PENDING shards by QoS rank — the capacity
        controller's demand input (backoff-gated shards excluded: they
        are not claimable THIS instant, and counting them would make
        the farm chase retries)."""
        now = self._clock() if now is None else now
        depth: dict[int, int] = {}
        with self._lock:
            for entry in self._jobs.values():
                for s in entry.shards.values():
                    if s.state is ShardState.PENDING \
                            and now >= s.not_before:
                        depth[s.priority] = depth.get(s.priority, 0) + 1
        return depth

    def tenant_assigned(self) -> dict[str, int]:
        """Currently-ASSIGNED shards per tenant — the
        `tvt_tenant_active_shards` gauge's scrape-time source."""
        out: dict[str, int] = {}
        with self._lock:
            for entry in self._jobs.values():
                for s in entry.shards.values():
                    if s.state is ShardState.ASSIGNED:
                        out[s.tenant] = out.get(s.tenant, 0) + 1
        return out

    def _find_locked(self, shard_id: str) -> Shard | None:
        for entry in self._jobs.values():
            shard = entry.shards.get(shard_id)
            if shard is not None:
                return shard
        return None

    # -- observability -------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """Per-shard timing + queue depth for /metrics_snapshot and the
        dashboard's farm panel."""
        with self._lock:
            counts = {s.value: 0 for s in ShardState}
            per_job: dict[str, dict[str, int]] = {}
            tenants: dict[str, dict[str, int]] = {}
            for job_id, entry in self._jobs.items():
                jc = per_job.setdefault(job_id, dict.fromkeys(
                    (s.value for s in ShardState), 0))
                for shard in entry.shards.values():
                    counts[shard.state.value] += 1
                    jc[shard.state.value] += 1
                    tc = tenants.setdefault(shard.tenant, dict.fromkeys(
                        (s.value for s in ShardState), 0))
                    tc[shard.state.value] += 1
            recent = list(self._recent)
            preempted = self._preempted
            integrity_rejects = self._integrity_rejects
            resumed = self._resumed
            spool = self._parts
        workers = {}
        for w in self.coordinator.registry.all():
            if w.shards_done or w.shards_failed:
                workers[w.host] = {
                    "shards_done": w.shards_done,
                    "shards_failed": w.shards_failed,
                    "quarantined": w.disabled,
                }
        # walk recents newest-first so each worker gets its latest timing
        for rec in reversed(recent):
            stats = workers.setdefault(rec["host"], {
                "shards_done": 0, "shards_failed": 0, "quarantined": False})
            stats.setdefault("last_shard_s", rec["elapsed_s"])
        return {"shards": counts, "jobs": per_job, "workers": workers,
                "tenants": tenants, "recent": recent[-20:],
                "preempted": preempted,
                # durable-spool health (partstore.py): crash-resume
                # reuses, digest rejections, bytes spooled on disk
                "resumed": resumed,
                "integrity_rejects": integrity_rejects,
                "spool_bytes": spool.spool_bytes()
                if spool is not None else 0,
                # halo relay occupancy (cluster/halo.py): band-shard
                # rendezvous blobs buffered on the coordinator
                "halo": self.halo.snapshot()}


class RemoteExecutor(LocalExecutor):
    """Coordinator-side launcher that farms encode shards out to worker
    daemons instead of the local mesh. Shares LocalExecutor's whole
    probe → stitch → mux → complete scaffolding; only the encode stage
    (`_encode_job`) differs. vbr2pass jobs still encode locally — the
    two-pass QP solver needs global complexity stats on one mesh — and
    so do jobs the admission policy marked ``processing_mode="direct"``
    (whole-file mode: VC-1-style codecs, oversize files under
    ``large_file_behavior="direct"``), which would defeat the split.

    The shared run() only OPENS the source (streaming ingest,
    ingest.open_video): the farm path reads the frame count and the
    audio track for the mux without ever decoding the clip on the
    coordinator."""

    #: wait-loop tick (real time; lease math runs on the injected
    #: clock). The protocol's timescales are seconds — shard leases,
    #: backoff, worker claim polls — so sub-second is already prompt;
    #: tests inject a faster tick.
    POLL_S = 0.25

    def __init__(self, coordinator, output_dir: str,
                 host: str = "coordinator", sync: bool = False,
                 poll_s: float | None = None,
                 clock: Callable[[], float] = time.time,
                 spool_dir: str | None = None) -> None:
        super().__init__(coordinator, output_dir, mesh=None, host=host,
                         sync=sync)
        self._clock = clock
        self.poll_s = poll_s if poll_s is not None else self.POLL_S
        # durable part spool + board checkpoint root: the explicit
        # arg, else the part_spool_dir setting, else a STABLE path
        # under the output dir — a restarted coordinator must find the
        # crashed run's parts, so a tempdir would defeat resume
        if spool_dir is None:
            snap = coordinator._settings_fn()
            spool_dir = str(snap.get("part_spool_dir", "") or "") \
                or os.path.join(output_dir, ".part-spool")
        self.board = ShardBoard(coordinator, clock=clock,
                                spool_dir=spool_dir)
        # live deadline breach → requeue this board's ASSIGNED batch
        # shards (cluster/qos.py fires the hook outside its lock)
        qos = getattr(coordinator, "qos", None)
        if qos is not None:
            qos.on_preempt(self.board.preempt_batch)

    def run(self, job: Job) -> None:
        super().run(job)
        # release the durable checkpoint once the job's output is
        # COMMITTED (and only then — a crash between collect and the
        # mp4 commit must still resume from the spool). Best-effort:
        # spool hygiene never fails a finished job.
        try:
            done = self.coordinator.store.try_get(job.id)
            if done is not None and done.status is Status.DONE:
                self.board.parts.clear_job(job.id)
        except Exception:       # noqa: BLE001 - cleanup only
            pass

    # -- shard planning ------------------------------------------------

    def _live_workers(self):
        """Active CLAIM-CAPABLE workers (daemons flag themselves with
        ``worker: true`` in heartbeat metrics). The registry also holds
        the coordinator's own agent, its device pseudo-hosts, and
        metrics-only agents — none of which can take a shard, and
        counting them would both inflate the shard plan and keep the
        all-workers-dead fail-fast from ever firing."""
        snap = self.coordinator._settings_fn()
        reg = self.coordinator.registry
        reg.assign_roles(int(snap.pipeline_worker_count))
        active = reg.active(float(snap.metrics_ttl_s), now=self._clock())
        return [w for w in active if w.metrics.get("worker")]

    def _plan_remote(self, num_frames: int, settings) -> SegmentPlan:
        from ..parallel.planner import plan_segments

        workers = self._live_workers()
        plan_devices = int(settings.get("remote_plan_devices", 0)) \
            or max(1, len(workers))
        return plan_segments(num_frames, int(settings.gop_frames),
                             plan_devices, int(settings.max_segments))

    def _shards_for(self, job: Job, meta, plan: SegmentPlan, settings,
                    qp: int, rung=None, token: str = "") -> list[Shard]:
        """Cut one GOP plan into leased shards. With `rung` set
        (abr.ladder.Rung) the shards are tagged for that rendition —
        same GOP ranges as every other rung, so the rendition set stays
        boundary-aligned no matter which workers encode which rungs.

        Shard ids are RUN-SCOPED (the run token rides in the id): a
        restarted job plans fresh shards under a new token, so a part
        still in flight from the superseded run resolves to NO shard
        and is dropped instead of landing in the new run's entry — the
        old run may have encoded under different job settings (QP,
        gop_frames), so a same-id part would be silently wrong bytes.
        The TVT-M002 board model checks exactly this (`cross-run-part`
        invariant; the `shared_ids` mutation reproduces the hole)."""
        from .qos import job_rank

        workers = self._live_workers()
        per_shard = int(settings.get("remote_shard_gops", 0))
        if per_shard <= 0:
            # auto: ~2 shards per worker so a straggler can rebalance
            per_shard = max(1, -(-plan.num_gops
                                 // max(1, 2 * max(1, len(workers)))))
        shards = []
        base_timeout = float(settings.remote_shard_timeout_s)
        tag = f"{rung.name}-" if rung is not None else ""
        priority = job_rank(
            getattr(job, "job_type", "transcode"),
            str(settings.get("job_priority", "auto") or "auto"))
        trace_id = obs_trace.TRACE.trace_id(job.id)
        run = f"{token[:6]}-" if token else ""
        for i in range(0, plan.num_gops, per_shard):
            gops = plan.gops[i:i + per_shard]
            # the plan key is run-STABLE (no token): the durable
            # checkpoint and spool key on it so a resumed run's fresh
            # token still resolves the crashed run's accepted parts
            key = f"{tag}{gops[0].index:04d}"
            shards.append(Shard(
                id=f"{job.id[:12]}-{run}{key}", key=key,
                job_id=job.id, input_path=job.input_path, meta=meta,
                gops=tuple(gops), qp=int(qp),
                gop_frames=int(settings.gop_frames),
                # lease scales with shard size: a 100-GOP shard must
                # not be failure-counted on a single-GOP budget (dead
                # workers are swept by heartbeat TTL long before any
                # lease anyway — the lease only guards live-but-stuck)
                timeout_s=base_timeout * len(gops),
                rung=rung.name if rung is not None else "",
                rung_width=rung.width if rung is not None else 0,
                rung_height=rung.height if rung is not None else 0,
                priority=priority, trace_id=trace_id,
                tenant=getattr(job, "tenant", "default") or "default"))
        return shards

    def _build_shards(self, job: Job, meta, num_frames: int,
                      settings, token: str = ""
                      ) -> tuple[SegmentPlan, list[Shard]]:
        if self._band_shape(job, settings):
            return self._build_band_shards(job, meta, num_frames,
                                           settings, token=token)
        plan = self._plan_remote(num_frames, settings)
        return plan, self._shards_for(job, meta, plan, settings,
                                      qp=int(settings.qp), token=token)

    @staticmethod
    def _band_shape(job: Job, settings) -> bool:
        """Plan frame-band shards (farm SFE) instead of GOP ranges?
        `sfe_bands > 0` opts the job into split-frame encoding and
        `sfe_farm` (default on) lets the remote backend spread the
        bands across hosts; ladder/live jobs keep their existing shard
        shapes (rung x range / local edge). A deblock-enabled job
        keeps GOP-range shards: the in-loop filter's cross-band halo
        is a device collective, which a cross-host band slice cannot
        run (the SFE steps refuse it), while whole GOPs deblock
        entirely worker-locally."""
        from ..core.config import as_bool

        return (int(settings.get("sfe_bands", 0) or 0) > 0
                and bool(settings.get("sfe_farm", True))
                and not as_bool(settings.get("deblock", False), False)
                and getattr(job, "job_type", "transcode") == "transcode")

    def _build_band_shards(self, job: Job, meta, num_frames: int,
                           settings, token: str = ""
                           ) -> tuple[SegmentPlan, list[Shard]]:
        """Plan one frame-band shard per worker: a contiguous slice of
        the job's global band layout covering EVERY GOP, encoded in
        lockstep with the sibling slices (halo over the /work relay).
        The band count CLAMPS to workers x min(worker devices): a
        shard must never carry more bands than its host's mesh — a
        mid-job dense fallback on the slowest worker would silently
        serialize the whole group, so the plan refuses up front (WARN)
        instead."""
        from ..parallel.planner import plan_encode

        workers = self._live_workers()
        nworkers = max(1, len(workers))
        dev_counts = [max(1, int(w.metrics.get("worker_devices", 1)
                                 or 1)) for w in workers] or [1]
        min_dev = min(dev_counts)
        mbh = (meta.height + 15) // 16
        requested = int(settings.get("sfe_bands", 0) or 0) \
            or nworkers * min_dev
        cap = nworkers * min_dev
        if requested > cap:
            self.coordinator.activity.emit(
                "shard",
                f"WARN: sfe_bands={requested} clamped to {cap} "
                f"({nworkers} workers x {min_dev} devices on the "
                f"slowest): a band shard must fit its host's mesh",
                job_id=job.id, host=self.host)
            requested = cap
        eplan = plan_encode(
            num_frames, settings, num_devices=nworkers, shape="band",
            total_bands=min(requested, mbh), group_count=nworkers,
            mb_height=mbh)
        return eplan.segments, self._band_shards_for(
            job, meta, eplan, settings, token=token)

    def _band_shards_for(self, job: Job, meta, eplan, settings,
                         token: str = "") -> list[Shard]:
        from .qos import job_rank

        seg = eplan.segments
        priority = job_rank(
            getattr(job, "job_type", "transcode"),
            str(settings.get("job_priority", "auto") or "auto"))
        trace_id = obs_trace.TRACE.trace_id(job.id)
        run = f"{token[:6]}-" if token else ""
        base_timeout = float(settings.remote_shard_timeout_s)
        shards = []
        for lo, hi in eplan.band_groups:
            key = f"band{lo:03d}"
            shards.append(Shard(
                id=f"{job.id[:12]}-{run}{key}", key=key,
                job_id=job.id, input_path=job.input_path, meta=meta,
                gops=tuple(seg.gops), qp=int(settings.qp),
                gop_frames=int(seg.frames_per_gop),
                timeout_s=base_timeout * len(seg.gops),
                shape="band", band_start=int(lo),
                band_count=int(hi - lo),
                total_bands=int(eplan.total_bands),
                halo_rows=int(eplan.halo_rows),
                priority=priority, trace_id=trace_id,
                tenant=getattr(job, "tenant", "default") or "default"))
        return shards

    # -- durable checkpoint / crash-resume (cluster/partstore.py) ------

    @staticmethod
    def _plan_signature(job: Job, settings, rungs=None) -> str:
        """Fingerprint of everything that changes a shard's ENCODED
        BYTES: the input file's identity plus the settings the encode
        reads. A resumed run whose signature matches may reuse spooled
        parts verbatim; any drift (operator changed qp, file replaced)
        resets the checkpoint instead of rehydrating stale bytes."""
        from ..ingest.watcher import file_signature

        try:
            fsig = file_signature(job.input_path)
        except OSError:
            fsig = "unreadable"
        fields = [job.input_path, fsig,
                  getattr(job, "job_type", "transcode"),
                  str(int(settings.qp)), str(int(settings.gop_frames))]
        if rungs:
            fields.extend(f"{r.name}:{r.width}x{r.height}@{r.qp}"
                          for r in rungs)
        # band-shape knobs join the signature ONLY when SFE is on, so
        # every pre-existing GOP-shaped checkpoint keeps its signature
        # (a band-layout change MUST reset the checkpoint: the spooled
        # parts' slice structure would no longer match the plan)
        sfe_bands = int(settings.get("sfe_bands", 0) or 0)
        if sfe_bands > 0:
            fields.extend(["band", str(sfe_bands),
                           str(int(settings.get("sfe_halo_rows", 32)
                                   or 32))])
        return hashlib.sha256("|".join(fields).encode()).hexdigest()[:16]

    @staticmethod
    def _plan_record(sig: str, plan: SegmentPlan,
                     shards: list[Shard]) -> dict[str, Any]:
        """JSON-able form of one deterministic shard plan — what the
        board checkpoint journals so a restarted coordinator re-plans
        from the RECORD, not from whatever worker count happens to be
        live at recovery time."""
        def gop_rows(gops):
            return [[g.index, g.start_frame, g.num_frames, bool(g.idr)]
                    for g in gops]

        return {
            "sig": sig,
            "gop_frames": int(plan.frames_per_gop),
            "num_devices": int(plan.num_devices),
            "plan_gops": gop_rows(plan.gops),
            "shards": [{
                "key": s.key, "qp": int(s.qp),
                "gops": gop_rows(s.gops),
                "timeout_s": float(s.timeout_s),
                "rung": s.rung, "rung_width": int(s.rung_width),
                "rung_height": int(s.rung_height),
                # band shape (absent/"gop" on classic shards so old
                # checkpoints replay unchanged)
                "shape": s.shape,
                "band_start": int(s.band_start),
                "band_count": int(s.band_count),
                "total_bands": int(s.total_bands),
                "halo_rows": int(s.halo_rows),
            } for s in shards],
        }

    def _shards_from_record(self, job: Job, meta, rec: Mapping[str, Any],
                            settings, token: str
                            ) -> tuple[SegmentPlan, list[Shard]]:
        """Rebuild the checkpointed plan under the NEW run token: same
        plan keys (so done records resolve), fresh run-scoped ids (so
        the crashed run's in-flight parts still drop — the cross-run
        fence survives resume)."""
        from .qos import job_rank

        def gops_of(rows):
            return tuple(GopSpec(index=int(i), start_frame=int(s),
                                 num_frames=int(n), idr=bool(idr))
                         for i, s, n, idr in rows)

        gop_frames = int(rec.get("gop_frames", settings.gop_frames))
        plan = SegmentPlan(gops=gops_of(rec["plan_gops"]),
                           num_devices=int(rec.get("num_devices", 1)),
                           frames_per_gop=gop_frames)
        priority = job_rank(
            getattr(job, "job_type", "transcode"),
            str(settings.get("job_priority", "auto") or "auto"))
        trace_id = obs_trace.TRACE.trace_id(job.id)
        run = f"{token[:6]}-" if token else ""
        shards = []
        for srec in rec["shards"]:
            key = str(srec["key"])
            shards.append(Shard(
                id=f"{job.id[:12]}-{run}{key}", key=key,
                job_id=job.id, input_path=job.input_path, meta=meta,
                gops=gops_of(srec["gops"]), qp=int(srec["qp"]),
                gop_frames=gop_frames,
                timeout_s=float(srec["timeout_s"]),
                rung=str(srec.get("rung", "")),
                rung_width=int(srec.get("rung_width", 0)),
                rung_height=int(srec.get("rung_height", 0)),
                shape=str(srec.get("shape", "gop") or "gop"),
                band_start=int(srec.get("band_start", 0)),
                band_count=int(srec.get("band_count", 0)),
                total_bands=int(srec.get("total_bands", 0)),
                halo_rows=int(srec.get("halo_rows", 0)),
                priority=priority, trace_id=trace_id,
                tenant=getattr(job, "tenant", "default") or "default"))
        return plan, shards

    def _plan_or_resume(self, job: Job, token: str, settings, meta,
                        num_frames: int, rungs=None
                        ) -> tuple[SegmentPlan, list[Shard], int]:
        """The RESUME path `recover_jobs` grew: when a durable board
        checkpoint exists for this job and its plan signature still
        matches, re-plan deterministically FROM the checkpoint, verify
        every recorded part against its digests, rehydrate the
        verified ones as DONE under the fresh run token, and leave
        only the remainder PENDING. Otherwise plan fresh (waiting for
        the farm as usual) and anchor a new checkpoint. Returns
        (plan, shards, reused_count)."""
        co = self.coordinator
        sig = self._plan_signature(job, settings, rungs=rungs)
        parts = self.board.parts
        resume = bool(settings.get("resume_enabled", True))
        rec: Mapping[str, Any] | None = None
        if resume:
            ck = parts.load_job(job.id)
            if ck is not None and ck.plan.get("sig") == sig \
                    and ck.plan.get("shards"):
                rec = ck.plan
        if rec is not None:
            plan, shards = self._shards_from_record(job, meta, rec,
                                                    settings, token)
        else:
            self._await_first_workers(job, token, settings)
            if rungs is None:
                plan, shards = self._build_shards(job, meta, num_frames,
                                                  settings, token=token)
            else:
                plan = self._plan_remote(num_frames, settings)
                shards = []
                for rung in rungs:
                    shards.extend(self._shards_for(
                        job, meta, plan, settings, qp=rung.qp,
                        rung=rung, token=token))
            rec = self._plan_record(sig, plan, shards)
        refs = parts.begin_job(job.id, rec)
        reused = 0
        if resume and shards and shards[0].shape == "band":
            # band groups resume ALL-OR-NOTHING: a partially-resumed
            # group would strand the re-encoding shard waiting on halo
            # exchanges its DONE siblings will never send. Either every
            # band shard's part verifies (whole job rehydrates — no
            # encode at all) or none does (whole group re-encodes).
            verified = {s.key: refs[s.key] for s in shards
                        if refs.get(s.key) is not None
                        and parts.verify_part(refs[s.key])}
            if len(verified) == len(shards):
                for shard in shards:
                    self.board.rehydrate_done(shard, verified[shard.key])
                    reused += 1
            else:
                for shard in shards:
                    ref = refs.get(shard.key)
                    if ref is not None:
                        parts.drop_done(job.id, shard.key, ref)
                if verified:
                    co.activity.emit(
                        "resume",
                        f"band group resume is all-or-nothing: "
                        f"{len(verified)}/{len(shards)} parts verified "
                        f"— dropping them, the group re-encodes in "
                        f"lockstep", job_id=job.id, host=self.host)
        elif resume:
            for shard in shards:
                ref = refs.get(shard.key)
                if ref is None:
                    continue
                if parts.verify_part(ref):
                    self.board.rehydrate_done(shard, ref)
                    reused += 1
                else:
                    # bit rot / torn spool: retract the record and let
                    # the shard re-encode — a transfer/storage fault,
                    # no attempt burned
                    self.board.note_spool_corruption(
                        job.id, shard.key, "digest mismatch on the "
                        "spooled part")
                    parts.drop_done(job.id, shard.key, ref)
        if reused:
            co.activity.emit(
                "resume",
                f"crash-resume: {reused}/{len(shards)} shards "
                f"rehydrated DONE from the verified part spool",
                job_id=job.id, host=self.host)
        return plan, shards, reused

    # -- encode stage override -----------------------------------------

    #: after the FIRST worker of a cold farm heartbeats, keep waiting
    #: until the live-worker count has been stable this long before
    #: planning — staggered daemon restarts re-heartbeat over a few
    #: seconds (default agent interval is 1 s), and planning on worker
    #: #1 alone would still produce the degenerate 2-giant-shard plan.
    SETTLE_S = 2.0

    def _await_first_workers(self, job: Job, token: str, settings) -> None:
        """Defer shard planning on a COLD farm until claim-capable
        workers have heartbeated, bounded by
        `remote_no_worker_grace_s`. A coordinator restart recovers jobs
        as soon as the API is up (cli.py), usually BEFORE any worker
        re-heartbeats — and planning against an empty registry
        degenerates to 2 giant shards on a full farm (the round-2
        ROADMAP open item). A warm farm (workers already live) plans
        immediately with zero added latency; a cold one waits for the
        first heartbeat and then for the worker count to settle
        (SETTLE_S), so a staggered farm restart is counted whole. On
        grace expiry planning proceeds anyway; the encode loop's
        no-live-worker failsafe still fails the job if the farm stays
        dark."""
        if self._live_workers():
            return                      # warm farm: plan now
        co = self.coordinator
        grace = float(settings.remote_no_worker_grace_s)
        settle = min(self.SETTLE_S, grace / 4.0)
        t0 = self._clock()
        seen = 0
        last_change = t0

        def tick(note: str) -> None:
            if not co.token_is_current(job.id, token):
                raise HaltedError("stale run token")
            co.heartbeat_job(job.id, token, "segment", host=self.host,
                             note=note)
            time.sleep(self.poll_s)

        while self._clock() - t0 < grace:
            n = len(self._live_workers())
            if n != seen:
                seen = n
                last_change = self._clock()
            elif n > 0 and self._clock() - last_change >= settle:
                return                  # farm width stable: plan
            tick("waiting for first worker heartbeat" if n == 0 else
                 f"waiting for the farm to settle ({n} workers)")

    def _encode_job(self, job: Job, token: str, frames, settings, meta,
                    stage: list) -> list:
        co = self.coordinator
        target_kbps = float(settings.get("target_bitrate_kbps", 0.0))
        if str(settings.rc_mode) == "vbr2pass" and target_kbps > 0:
            co.activity.emit(
                "encode", "vbr2pass encodes on the coordinator mesh "
                "(global QP solve)", job_id=job.id, host=self.host)
            return super()._encode_job(job, token, frames, settings,
                                       meta, stage)
        if str(getattr(job, "processing_mode", "split") or "split") \
                == "direct":
            co.activity.emit(
                "encode", "direct mode: whole-clip encode on the "
                "coordinator mesh (admission policy bypasses the farm "
                "split)", job_id=job.id, host=self.host)
            return super()._encode_job(job, token, frames, settings,
                                       meta, stage)

        stage[0] = "segment"
        plan, shards, reused = self._plan_or_resume(
            job, token, settings, meta, len(frames))
        banded = bool(shards) and shards[0].shape == "band"
        parts_total = plan.num_gops * (len(shards) if banded else 1)
        co.update_progress(job.id, token, parts_total=parts_total,
                           segment_progress=100.0)
        if banded:
            note = (f"{plan.num_gops} GOPs x {len(shards)} band "
                    f"slices (farm SFE, {shards[0].total_bands} bands)")
            act = note
        else:
            note = f"{plan.num_gops} GOPs in {len(shards)} shards"
            act = f"{plan.num_gops} GOPs as {len(shards)} shards"
        co.heartbeat_job(job.id, token, stage[0], host=self.host,
                         note=note)
        co.activity.emit(
            "shard", f"dispatching {act} to the worker farm"
            + (f" ({reused} resumed from the spool)" if reused else ""),
            job_id=job.id, host=self.host)

        stage[0] = "encode"
        done_shards = self._drain_board(job, token, settings, shards)
        if banded:
            segments = stitch_band_shards(done_shards)
        else:
            segments = [seg for shard in done_shards
                        for seg in shard.segments]
        segments.sort(key=lambda s: s.gop.index)
        return segments

    def _drain_board(self, job: Job, token: str, settings,
                     shards: list[Shard]) -> list[Shard]:
        """Post the shards and babysit the farm until every one is
        DONE: lease sweeps, progress writes (only on change — the store
        is journal-backed), the all-workers-dead failsafe, and
        token-fenced cleanup. Returns the completed shard records."""
        self.board.add_job(
            job.id, shards,
            max_attempts=int(settings.part_failure_max_retries),
            backoff_s=float(settings.remote_retry_backoff_s),
            quarantine_after=int(settings.remote_worker_max_failures),
            token=token)
        try:
            return self._wait_board(job, token, settings)
        finally:
            self.board.cancel_job(job.id, token=token)

    def _wait_board(self, job: Job, token: str, settings,
                    report_progress: bool = True) -> list[Shard]:
        """Babysit the posted board entry to completion (the shared
        tail of _drain_board and the live catch-up fan-out, which owns
        its board entry's lifecycle — and its progress counters)."""
        co = self.coordinator
        grace = float(settings.remote_no_worker_grace_s)
        workerless_since: float | None = None
        last_progress = (-1, -1)
        while True:
            if not co.token_is_current(job.id, token):
                raise HaltedError("stale run token")
            self.board.requeue_expired()
            done, total, retried, failed, failed_host = \
                self.board.job_progress(job.id)
            if report_progress and (done, retried) != last_progress:
                last_progress = (done, retried)
                co.update_progress(
                    job.id, token, parts_done=done,
                    parts_retried=retried,
                    encode_progress=100.0 * done / max(1, total))
            if failed:
                raise RuntimeError(failed)
            if done >= total:
                return self.board.take_shards(job.id, token=token)
            live = self._live_workers()
            if live:
                workerless_since = None
            else:
                now = self._clock()
                if workerless_since is None:
                    workerless_since = now
                elif now - workerless_since > grace:
                    raise RuntimeError(
                        f"no live encode workers for {grace:.0f}s; "
                        f"{total - done} GOPs stranded")
            co.heartbeat_job(
                job.id, token, "encode", host=self.host,
                note=f"{done}/{total} GOPs on {len(live)} workers")
            time.sleep(self.poll_s)

    # -- live catch-up fan-out -----------------------------------------

    #: minimum whole backlog GOPs (beyond the live-edge GOP kept
    #: local) before a live batch fans across the farm: a one-GOP
    #: round-trip would put worker latency inside the glass-to-
    #: playlist path for nothing
    LIVE_FARM_MIN_GOPS = 2

    def _live_backlog_cap(self, job, settings, enc) -> int:
        """Catch-up batches may span the whole farm's width, not just
        the local mesh: the farm absorbs the backlog while the edge
        GOP encodes locally. When the fan-out cannot engage (knob off,
        direct-mode job), the LOCAL wave bound stays in force — an
        inflated batch would otherwise serialize whole farm-widths of
        GOPs through the local mesh before the packager sees a part."""
        base = enc.num_devices * enc.gops_per_wave
        if not bool(settings.get("live_farm_catchup", True))                 or str(getattr(job, "processing_mode", "split")
                       or "split") == "direct":
            return base
        return base * max(1, len(self._live_workers()))

    def _live_encode_batch(self, job: Job, token: str, settings, enc,
                           rungs, tail, frames_done: int,
                           gops_done: int, count: int, gop_n: int,
                           sfe_live: bool):
        """Fan a live job's catch-up GOPs across the farm while the
        NEWEST GOP (the live edge) encodes on the coordinator mesh —
        the farm eats the backlog concurrently with the edge, so one
        host's throughput no longer bounds how fast a live stream
        recovers. Small batches (the steady live edge) stay entirely
        local: a worker round-trip inside the glass-to-playlist path
        would only add latency."""
        from ..abr.ladder import LadderGopBundle

        workers = self._live_workers()
        farm_gops = count // gop_n - 1      # newest GOP stays local
        if (not bool(settings.get("live_farm_catchup", True))
                or not workers
                or farm_gops < self.LIVE_FARM_MIN_GOPS
                or str(getattr(job, "processing_mode", "split")
                       or "split") == "direct"):
            return super()._live_encode_batch(
                job, token, settings, enc, rungs, tail, frames_done,
                gops_done, count, gop_n, sfe_live)
        co = self.coordinator
        farm_frames = farm_gops * gop_n
        plan = SegmentPlan(
            gops=tuple(GopSpec(index=gops_done + i,
                               start_frame=frames_done + i * gop_n,
                               num_frames=gop_n)
                       for i in range(farm_gops)),
            num_devices=max(1, len(workers)), frames_per_gop=gop_n)
        shards: list[Shard] = []
        for rung in rungs:
            shards.extend(self._shards_for(job, tail.meta, plan,
                                           settings, qp=rung.qp,
                                           rung=rung, token=token))
        co.activity.emit(
            "shard", f"live catch-up: farming {farm_gops} backlog "
            f"GOPs x {len(rungs)} rungs across {len(workers)} workers "
            f"while the edge encodes locally",
            job_id=job.id, host=self.host)
        self.board.add_job(
            job.id, shards,
            max_attempts=int(settings.part_failure_max_retries),
            backoff_s=float(settings.remote_retry_backoff_s),
            quarantine_after=int(settings.remote_worker_max_failures),
            token=token)
        try:
            # edge GOP (+ any EOS partial tail) locally, farm in flight
            local = super()._live_encode_batch(
                job, token, settings, enc, rungs, tail,
                frames_done + farm_frames, gops_done + farm_gops,
                count - farm_frames, gop_n, sfe_live)
            try:
                done_shards = self._wait_board(job, token, settings,
                                               report_progress=False)
            except HaltedError:
                raise
            except RuntimeError as exc:
                # the farm died under the catch-up batch (shard budget
                # burned, all workers dark): a live stream must not
                # fail for it — nothing was consumed yet, so encode
                # the span locally (deterministic: identical bytes)
                co.activity.emit(
                    "shard", f"live catch-up farm failed ({exc}); "
                    f"re-encoding the {farm_gops}-GOP span locally",
                    job_id=job.id, host=self.host)
                self.board.cancel_job(job.id, token=token)
                return super()._live_encode_batch(
                    job, token, settings, enc, rungs, tail,
                    frames_done, gops_done, farm_frames, gop_n,
                    sfe_live) + local
        finally:
            self.board.cancel_job(job.id, token=token)
        by_gop: dict[int, dict] = {}
        gop_of: dict[int, GopSpec] = {}
        for shard in done_shards:
            for seg in shard.segments:
                name = shard.rung or rungs[0].name
                by_gop.setdefault(seg.gop.index, {})[name] = seg
                gop_of[seg.gop.index] = seg.gop
        farm_bundles = [
            LadderGopBundle(gop=gop_of[i], renditions=by_gop[i])
            for i in sorted(by_gop)]
        for b in farm_bundles:
            missing = [r.name for r in rungs
                       if r.name not in b.renditions]
            if missing:
                raise RuntimeError(
                    f"live catch-up GOP {b.gop.index} missing rungs "
                    f"{missing}")
        return farm_bundles + local

    def _encode_ladder(self, job: Job, token: str, frames, settings,
                       meta, stage: list):
        """Ladder jobs on the farm: rungs × GOP-range shards fan across
        the workers (every rung shares ONE GOP plan, so segments align
        no matter which host encodes which rung) and the coordinator
        only groups the streamed-back parts per rung for packaging.
        Direct-mode jobs still encode whole on the coordinator mesh."""
        from ..abr.ladder import plan_ladder

        co = self.coordinator
        if str(getattr(job, "processing_mode", "split") or "split") \
                == "direct":
            co.activity.emit(
                "encode", "direct mode: whole-ladder encode on the "
                "coordinator mesh", job_id=job.id, host=self.host)
            return super()._encode_ladder(job, token, frames, settings,
                                          meta, stage)

        stage[0] = "segment"
        rungs = plan_ladder(meta, settings)
        plan, shards, reused = self._plan_or_resume(
            job, token, settings, meta, len(frames), rungs=rungs)
        total_parts = plan.num_gops * len(rungs)
        co.update_progress(job.id, token, parts_total=total_parts,
                           segment_progress=100.0)
        co.heartbeat_job(
            job.id, token, stage[0], host=self.host,
            note=f"{plan.num_gops} GOPs x {len(rungs)} rungs in "
                 f"{len(shards)} shards")
        co.activity.emit(
            "shard", f"dispatching {plan.num_gops} GOPs x {len(rungs)} "
            f"rungs as {len(shards)} shards to the worker farm"
            + (f" ({reused} resumed from the spool)" if reused else ""),
            job_id=job.id, host=self.host)

        stage[0] = "encode"
        by_rung: dict[str, list] = {r.name: [] for r in rungs}
        for shard in self._drain_board(job, token, settings, shards):
            by_rung[shard.rung or rungs[0].name].extend(shard.segments)
        for segs in by_rung.values():
            segs.sort(key=lambda s: s.gop.index)
        return rungs, by_rung


def stitch_band_shards(shards: Iterable[Shard]) -> list[EncodedSegment]:
    """Zip a band-sharded job's per-GOP slice streams back into whole
    pictures: for every GOP, frame f's access unit is the concat of
    every band group's frame-f slice bytes in band order (group 0
    carries the SPS/PPS prefix on IDR frames). Byte-identical to what
    a local-mesh SfeShardEncoder with the same global band layout
    emits — the downstream stitch/mux path needs no band awareness."""
    groups = sorted((s for s in shards if s.shape == "band"),
                    key=lambda s: s.band_start)
    if not groups:
        return []
    per = [{seg.gop.index: seg for seg in s.segments} for s in groups]
    indices = sorted(per[0])
    out: list[EncodedSegment] = []
    for gi in indices:
        segs = []
        for p, s in zip(per, groups):
            if gi not in p:
                raise ValueError(
                    f"band shard {s.id} is missing GOP {gi}")
            segs.append(p[gi])
        nframes = {len(s.frame_sizes) for s in segs}
        if len(nframes) != 1:
            raise ValueError(
                f"band shards disagree on GOP {gi}'s frame count: "
                f"{sorted(len(s.frame_sizes) for s in segs)}")
        payload = bytearray()
        sizes = []
        offs = [0] * len(segs)
        for f in range(nframes.pop()):
            total = 0
            for k, seg in enumerate(segs):
                sz = seg.frame_sizes[f]
                payload += seg.payload[offs[k]:offs[k] + sz]
                offs[k] += sz
                total += sz
            sizes.append(total)
        out.append(EncodedSegment(gop=segs[0].gop,
                                  payload=bytes(payload),
                                  frame_sizes=tuple(sizes)))
    return out


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------


class UnsupportedShardShape(RuntimeError):
    """The claim descriptor carries a shard shape this worker does not
    implement (version skew on a rolling upgrade): reported as
    `unsupported` so the board requeues with NO attempt burned and
    stops offering the shard to this host."""


def _encode_band_shard(desc: Mapping[str, Any], frames, mesh=None,
                       tracer=None, halo_transport=None
                       ) -> list[EncodedSegment]:
    """Encode one frame-band shard: this host owns bands
    [start, start+count) of the job's `total`-band layout, steps the
    shard's whole GOP walk in lockstep with the sibling groups, and
    exchanges per-frame halo rows / probe partials / histogram
    partials through `halo_transport` (cluster/halo.py — the
    coordinator-relayed route). Bit-identity contract as
    parallel/sfefarm.py documents."""
    from ..core.config import get_settings
    from ..parallel.dispatch import make_shard_encoder
    from .halo import HaloSession

    meta = meta_from_dict(desc["meta"])
    gops = tuple(GopSpec(index=int(i), start_frame=int(s),
                         num_frames=int(n))
                 for i, s, n in desc["gops"])
    band = desc.get("band") or {}
    lo = int(band.get("start", 0))
    cnt = max(1, int(band.get("count", 1) or 1))
    total = int(band.get("total", 0) or 0) or (lo + cnt)
    groups = [(int(a), int(b))
              for a, b in (band.get("groups") or [[lo, lo + cnt]])]
    session = None
    if len(groups) > 1:
        if halo_transport is None:
            raise ValueError(
                "band shard has sibling groups but no halo transport")
        session = HaloSession(halo_transport, band_lo=lo,
                              band_hi=lo + cnt, groups=groups)
    enc = make_shard_encoder(
        meta, get_settings(), mesh, shape="band",
        qp=int(desc["qp"]), total_bands=total,
        band_range=(lo, lo + cnt),
        halo_rows=int(band.get("halo_rows", 32) or 32),
        session=session)
    if tracer is not None:
        enc.stages.set_tracer(tracer)
    enc.plan_override = SegmentPlan(
        gops=gops, num_devices=enc.num_devices,
        frames_per_gop=int(desc.get("gop_frames", 32)))
    enc.gop_index_offset = int(desc["gop_index_offset"])
    enc.frame_offset = int(desc["start_frame"])
    f0 = int(desc["start_frame"])
    sub = frames[f0:f0 + int(desc["num_frames"])]
    if len(sub) != int(desc["num_frames"]):
        raise ValueError(
            f"{desc['input_path']}: band shard wants frames "
            f"[{f0}, {f0 + int(desc['num_frames'])}) but clip has "
            f"{len(frames)}")
    return enc.encode(sub)


def encode_shard(desc: Mapping[str, Any], frames, mesh=None, tracer=None,
                 halo_transport=None) -> list[EncodedSegment]:
    """Encode one claimed shard on this process's devices. Pure w.r.t.
    the descriptor: the plan override pins the coordinator's exact GOP
    boundaries and the index/frame offsets re-base the emitted segments
    to global coordinates, so the part is bit-identical to what a
    single-process encode of the whole clip would have produced for
    these GOPs.

    `frames` may be a materialized list of the WHOLE clip or a lazy
    FrameSource (ingest.open_video): slicing a source yields a window
    that decodes only this shard's [f0, f0+n) frame range — O(shard)
    decode work and resident memory per claim instead of O(clip).

    The encoder is built from this process's settings snapshot, so a
    worker inherits the full collect path — compact device→host level
    transfer (TVT_COMPACT_TRANSFER), per-shard concurrent fetch, and
    the pack backend (TVT_PACK_BACKEND) — from its own environment;
    output stays bit-identical to the coordinator's plan regardless of
    which transfer/pack path each worker takes (parity-tested).

    `tracer` (an obs/trace span sink — the daemon's SpanBuffer) binds
    to the encoder's stage profile so the worker's decode/dispatch/
    fetch/pack stages become spans in the job's distributed trace."""
    from ..parallel.dispatch import GopShardEncoder

    shape = str(desc.get("shape", "gop") or "gop")
    if shape == "band":
        return _encode_band_shard(desc, frames, mesh=mesh, tracer=tracer,
                                  halo_transport=halo_transport)
    if shape != "gop":
        raise UnsupportedShardShape(
            f"shard shape {shape!r} not implemented by this worker")
    meta = meta_from_dict(desc["meta"])
    gops = tuple(GopSpec(index=int(i), start_frame=int(s),
                         num_frames=int(n))
                 for i, s, n in desc["gops"])
    rung_desc = desc.get("rung")
    rung = None
    if rung_desc and (int(rung_desc["width"]), int(rung_desc["height"])) \
            != (meta.width, meta.height):
        # scaled ladder rung: decode at source resolution, derive the
        # rung on THIS worker's devices (abr/scale.py), encode at the
        # rung's dims — the wire still carries plain segments
        from ..abr.ladder import LadderShardEncoder, Rung

        rung = Rung(name=str(rung_desc.get("name", "rung")),
                    width=int(rung_desc["width"]),
                    height=int(rung_desc["height"]), qp=int(desc["qp"]))
        enc = LadderShardEncoder(meta, [rung], mesh=mesh,
                                 gop_frames=int(desc.get("gop_frames",
                                                         32)))
    else:
        enc = GopShardEncoder(meta, qp=int(desc["qp"]), mesh=mesh,
                              gop_frames=int(desc.get("gop_frames", 32)))
    if tracer is not None:
        enc.stages.set_tracer(tracer)
    enc.plan_override = SegmentPlan(
        gops=gops, num_devices=enc.num_devices,
        frames_per_gop=int(desc.get("gop_frames", 32)))
    enc.gop_index_offset = int(desc["gop_index_offset"])
    enc.frame_offset = int(desc["start_frame"])
    f0 = int(desc["start_frame"])
    sub = frames[f0:f0 + int(desc["num_frames"])]
    if len(sub) != int(desc["num_frames"]):
        raise ValueError(
            f"{desc['input_path']}: shard wants frames "
            f"[{f0}, {f0 + int(desc['num_frames'])}) but clip has "
            f"{len(frames)}")
    if rung is not None:
        return [b.renditions[rung.name] for b in enc.encode(sub)]
    return enc.encode(sub)


class WorkerClient:
    """Minimal stdlib HTTP client for the /work/* routes.

    Every request retries through transient transport failures —
    connection refused, resets, HTTP 5xx — with jittered exponential
    backoff (`remote_http_retries` × `remote_http_backoff_s`): a
    coordinator restart window (a few seconds of refused connections
    while the journal replays) must not fail shards or quarantine
    healthy workers. All three verbs are safe to repeat: claims are
    leases (a lost grant expires into the sweep), part uploads are
    idempotent via their digests (duplicates drop at the board), and
    failure reports are absorbing."""

    def __init__(self, base_url: str, timeout_s: float = 30.0,
                 retries: int | None = None,
                 backoff_s: float | None = None) -> None:
        from ..core.config import get_settings

        self.base = base_url.rstrip("/")
        self.timeout_s = timeout_s
        snap = get_settings()
        self.retries = int(snap.get("remote_http_retries", 4)) \
            if retries is None else max(0, int(retries))
        self.backoff_s = float(snap.get("remote_http_backoff_s", 0.5)) \
            if backoff_s is None else max(0.0, float(backoff_s))

    #: integrity-rejection re-sends per upload, ON TOP of the
    #: transport retries inside each _request: more than a couple of
    #: consecutive digest rejects means the corruption is persistent
    #: and re-encoding (via the requeued lease) is the better path —
    #: a full retries×retries product would defeat the configured
    #: bound on how long one upload can mask a dead coordinator
    INTEGRITY_RESENDS = 2

    def _request(self, path: str, data: bytes, content_type: str,
                 timeout_s: float | None = None,
                 trace_id: str = "") -> dict[str, Any]:
        import urllib.request

        from ..core.retry import call_with_backoff

        headers = {"Content-Type": content_type}
        if trace_id:
            # the remote worker protocol's trace-context header —
            # consumed by POST /work/spans, where the coordinator
            # validates it against the job's LIVE trace and drops
            # stale-run stragglers
            headers["X-Tvt-Trace"] = trace_id

        def send() -> dict[str, Any]:
            req = urllib.request.Request(
                self.base + path, data=data, method="POST",
                headers=headers)
            with urllib.request.urlopen(
                    req, timeout=timeout_s or self.timeout_s) as resp:
                return json.loads(resp.read())

        return call_with_backoff(send, self.retries, self.backoff_s)

    def claim(self, host: str) -> dict[str, Any] | None:
        out = self._request("/work/claim",
                            json.dumps({"host": host}).encode(),
                            "application/json")
        return out.get("shard")

    def upload_part(self, shard_id: str, host: str,
                    segments: list[EncodedSegment]) -> bool:
        from ..core.retry import sleep_backoff

        data = pack_parts(segments)
        for attempt in range(self.INTEGRITY_RESENDS + 1):
            out = self._request(
                f"/work/part/{shard_id}?host={host}", data,
                "application/octet-stream",
                # parts can be large; scale the budget, floor at the
                # default
                timeout_s=max(self.timeout_s, 120.0))
            # digest rejection at ingest ({"retry": true}): the bytes
            # corrupted in TRANSIT, the lease came straight back with
            # no attempt burned — re-send the (idempotent) upload
            # instead of re-encoding the shard
            if out.get("ok") or not out.get("retry"):
                return bool(out.get("ok"))
            if attempt < self.INTEGRITY_RESENDS:
                sleep_backoff(attempt, self.backoff_s)
        return False

    def upload_spans(self, job_id: str, trace_id: str, host: str,
                     spans: list[dict[str, Any]]) -> int:
        """Ship a shard's collected spans to the coordinator's trace
        ring (POST /work/spans, trace id in X-Tvt-Trace). Returns how
        many the coordinator recorded."""
        out = self._request(
            "/work/spans", json.dumps({
                "job_id": job_id, "host": host, "spans": spans,
            }).encode(), "application/json", trace_id=trace_id)
        return int(out.get("recorded", 0))

    def report_failure(self, shard_id: str, host: str, error: str,
                       unsupported: bool = False) -> None:
        self._request("/work/status", json.dumps({
            "shard_id": shard_id, "host": host, "ok": False,
            "unsupported": bool(unsupported),
            "error": error[:500]}).encode(), "application/json")


class WorkerDaemon:
    """Claim → range-decode → encode → stream-back loop.

    One daemon per worker host (`python -m thinvids_tpu.cli worker`).
    The source cache holds the last `CACHE_CLIPS` OPENED inputs keyed
    by path+signature (header/demux state, compressed samples for mp4 —
    never decoded frames), and each claimed shard decodes only its own
    [f0, f0+n) frame range through the lazy slice — O(shard) decode
    work and memory per claim instead of decoding the whole clip to
    cut out one range (the farm analog of the reference worker's local
    scratch copy of its segment range)."""

    CACHE_CLIPS = 2

    def __init__(self, coordinator_url: str, host: str | None = None,
                 poll_s: float | None = None, mesh=None,
                 client: WorkerClient | None = None) -> None:
        from ..core.config import get_settings

        self.host = host or socket.gethostname()
        self.client = client or WorkerClient(coordinator_url)
        # floor regardless of source: the env tier is coerced but not
        # clamped, and a non-positive poll busy-spins /work/claim
        self.poll_s = max(0.05, poll_s if poll_s is not None else
                          float(get_settings().remote_claim_poll_s))
        self.mesh = mesh
        self.busy = False
        self.shards_done = 0
        self.shards_failed = 0
        self._device_count: int | None = None
        #: input_path → (signature, opened FrameSource — no decoded
        #: frames cached; shards range-decode on demand)
        self._cache: dict[str, tuple[str, Any]] = {}

    # -- metrics seam (NodeAgent extra_metrics) ------------------------

    def metrics(self) -> dict[str, Any]:
        if self._device_count is None:
            # lazy, once: the heartbeat advertises this host's device
            # mesh width so the coordinator's band planner can clamp a
            # shard's band count to the SLOWEST worker's devices (a
            # worker is a jax process by definition — initializing the
            # backend here only front-loads what the first claim does)
            try:
                if self.mesh is not None:
                    self._device_count = int(self.mesh.devices.size)
                else:
                    import jax

                    self._device_count = len(jax.devices())
            except Exception:   # noqa: BLE001 - degraded heartbeat
                self._device_count = 1
        return {"worker": True, "worker_busy": self.busy,
                "worker_devices": self._device_count,
                "worker_shards_done": self.shards_done,
                "worker_shards_failed": self.shards_failed}

    # -- source cache --------------------------------------------------

    def _frames(self, input_path: str):
        """Open (header parse / demux — NOT decode) the clip, cached by
        path+signature. The shard slice taken in step() is a lazy
        window over this source, so each claim decodes only its own
        [f0, f0+n) frame range."""
        from ..ingest.decode import open_video
        from ..ingest.watcher import file_signature

        sig = file_signature(input_path)
        hit = self._cache.get(input_path)
        if hit is not None and hit[0] == sig:
            return hit[1]
        # source only: the shard encode never touches meta (the shard
        # descriptor carries it) or audio (the coordinator muxes it)
        source = open_video(input_path)
        self._cache[input_path] = (sig, source)
        while len(self._cache) > self.CACHE_CLIPS:
            self._cache.pop(next(iter(self._cache)))
        return source

    # -- loop ----------------------------------------------------------

    def step(self) -> bool:
        """One claim attempt. Returns True when a shard was processed
        (successfully or not), False when the board had nothing.

        When the claim descriptor carries a trace context, the shard's
        worker-side spans (source open, encode incl. the encoder's
        stage clocks, part upload) collect in a local SpanBuffer and
        ship to the coordinator's trace ring afterwards — best-effort,
        never part of the shard's success or failure."""
        from .halo import HaloClient, HaloStaleError

        shard = self.client.claim(self.host)
        if not shard:
            return False
        trace = shard.get("trace") or {}
        buf = obs_trace.SpanBuffer(
            str(trace.get("trace_id", "")), str(trace.get("job_id", "")),
            host=self.host) if trace.get("trace_id") else None
        # inert recorder when untraced: span() is a no-op context, so
        # the work loop below stays unconditional
        sink = buf if buf is not None else obs_trace.NULL_RECORDER
        halo_transport = None
        if str(shard.get("shape", "gop") or "gop") == "band":
            band = shard.get("band") or {}
            halo_transport = HaloClient(
                self.client.base, str(shard.get("job_id", "")),
                int(band.get("gen", 1) or 1))
        self.busy = True
        try:
            with sink.span("worker_shard", shard=shard["id"],
                           attempt=shard.get("attempt", 0)):
                with sink.span("open_source"):
                    frames = self._frames(shard["input_path"])
                segments = encode_shard(shard, frames, mesh=self.mesh,
                                        tracer=buf,
                                        halo_transport=halo_transport)
                # the board may refuse the part (lease moved on, job
                # gone): only an ACCEPTED part counts toward the gauge
                with sink.span("upload_part"):
                    accepted = self.client.upload_part(
                        shard["id"], self.host, segments)
            if accepted:
                self.shards_done += 1
        except HaloStaleError:
            # the band group restarted under a newer halo generation:
            # the board already took this lease back (sibling requeue),
            # so abandon silently — not a failure, nothing to report
            pass
        except UnsupportedShardShape as exc:
            try:
                self.client.report_failure(
                    shard["id"], self.host, str(exc), unsupported=True)
            except Exception:       # noqa: BLE001 - coordinator gone;
                pass                # the lease sweep requeues the shard
        except Exception as exc:    # noqa: BLE001 - report, keep serving
            self.shards_failed += 1
            try:
                self.client.report_failure(
                    shard["id"], self.host,
                    f"{type(exc).__name__}: {exc}")
            except Exception:       # noqa: BLE001 - coordinator gone;
                pass                # the lease sweep requeues the shard
        finally:
            self.busy = False
            if buf is not None:
                try:
                    self.client.upload_spans(
                        buf.job_id, buf.trace_id, self.host, buf.drain())
                except Exception:   # noqa: BLE001 - tracing is never
                    pass            # allowed to fail the work loop
        return True

    def run_forever(self, stop: threading.Event | None = None) -> None:
        from ..core.log import get_logging

        log = get_logging("thinvids_tpu.worker")
        stop = stop or threading.Event()
        claim_failures = 0
        while not stop.is_set():
            try:
                worked = self.step()
                claim_failures = 0
            except Exception as exc:  # noqa: BLE001 - claim failed
                worked = False        # (coordinator restarting): back off
                claim_failures += 1
                # throttled: surface a misconfigured coordinator (e.g.
                # local backend → /work 503) instead of idling silently
                if claim_failures in (1, 10) or claim_failures % 100 == 0:
                    log.warning(
                        "claim against %s failing (x%d): %s",
                        self.client.base, claim_failures, exc)
            if not worked:
                stop.wait(self.poll_s)
