"""Cross-host halo exchange for farm split-frame encoding (SFE).

PR 9's SFE shards ONE frame across a local device mesh; the band
shards' halo rows, global-motion probe and temporal-median histogram
travel over the mesh interconnect (ppermute/psum). This module carries
the same three flows BETWEEN HOSTS when the band layout spans the farm
(cluster/remote.py band shards, parallel/sfefarm.py):

- per-frame neighbor reference rows (the pixel halo each band slice
  needs for its motion search);
- per-frame probe partial costs and histogram partials (tiny integer
  vectors whose cross-host sums are bit-identical to the device psum).

Transport is a coordinator-RELAYED rendezvous, not worker-to-worker
sockets: band workers already hold a connection to the coordinator API
(NAT-safe, no farm-internal reachability requirement), so blobs POST to
``/work/halo`` and peers long-poll the same route. Every blob rides the
PR 13 digest framing (length-prefixed JSON directory + raw payload with
per-array sha256) and every request retries through transient transport
failures with the shared jittered backoff (core/retry.py).

Staleness is generation-fenced: whenever a band shard of a job leaves
its lease abnormally, the ShardBoard restarts the WHOLE band group
(siblings requeue with no attempt burned — the exchange is lockstep, a
lost peer strands everyone) and bumps the job's halo generation. Posts
and fetches carrying an older generation answer ``stale`` and the
worker abandons the shard silently (its lease was already requeued).
All halo payloads are DETERMINISTIC (same inputs → same bytes), so a
duplicate post from a retried attempt is harmless by construction.

jax-free: runs on coordinator API threads and inside worker control
flow; the device math lives in parallel/sfefarm.py.
"""

from __future__ import annotations

import hashlib
import json
import struct
import threading
import time
from typing import Any, Callable, Mapping

import numpy as np


class HaloStaleError(RuntimeError):
    """The job's halo generation moved on (a band peer was requeued and
    the group restarted): abandon this shard attempt silently — the
    board already took the lease back."""


class HaloTimeoutError(RuntimeError):
    """A peer's blob never arrived within `halo_timeout_s` (peer died
    or is partitioned): fail the shard so the lease machinery requeues
    the whole band group."""


# ---------------------------------------------------------------------------
# blob framing (the PR 13 digest framing, generalized to named arrays)
# ---------------------------------------------------------------------------


def pack_arrays(arrays: Mapping[str, np.ndarray]) -> bytes:
    """Named-array blob: 4-byte BE header length + JSON directory +
    concatenated C-order buffers; each array record carries its
    payload's sha256 so a flipped bit on the wire is rejected at
    unpack, never fed into a motion search."""
    names = sorted(arrays)
    bufs = [np.ascontiguousarray(arrays[k]).tobytes() for k in names]
    header = json.dumps({"arrays": [{
        "name": k,
        "dtype": str(np.asarray(arrays[k]).dtype),
        "shape": list(np.asarray(arrays[k]).shape),
        "size": len(buf),
        "sha256": hashlib.sha256(buf).hexdigest(),
    } for k, buf in zip(names, bufs)]}, separators=(",", ":")).encode()
    return b"".join([struct.pack(">I", len(header)), header] + bufs)


def unpack_arrays(data: bytes) -> dict[str, np.ndarray]:
    """Inverse of :func:`pack_arrays`; raises ValueError on torn or
    digest-mismatched frames."""
    if len(data) < 4:
        raise ValueError("halo blob too short")
    hlen = struct.unpack(">I", data[:4])[0]
    if 4 + hlen > len(data):
        raise ValueError("halo blob header exceeds frame")
    header = json.loads(data[4:4 + hlen])
    out: dict[str, np.ndarray] = {}
    off = 4 + hlen
    for rec in header["arrays"]:
        size = int(rec["size"])
        buf = data[off:off + size]
        if len(buf) != size:
            raise ValueError("halo blob payload truncated")
        off += size
        if hashlib.sha256(buf).hexdigest() != str(rec["sha256"]):
            raise ValueError(
                f"halo array {rec['name']} does not match its sha256")
        out[str(rec["name"])] = np.frombuffer(
            buf, dtype=np.dtype(str(rec["dtype"]))).reshape(
                [int(x) for x in rec["shape"]]).copy()
    if off != len(data):
        raise ValueError("trailing bytes after halo blob")
    return out


# ---------------------------------------------------------------------------
# coordinator-side relay
# ---------------------------------------------------------------------------


class HaloRelay:
    """Generation-fenced rendezvous buffer the coordinator API exposes
    at /work/halo. Blobs key on (seq, band, kind) where `seq` is the
    GLOBAL frame index — monotonic across the job, so a bounded ring
    per (band, kind) stream suffices: lockstep peers never trail by
    more than a frame, and a restarted group runs under a fresh
    generation (which clears the store outright)."""

    #: retained frames per (band, kind) stream — peers are lockstep
    #: (skew ≤ 1 frame); the margin absorbs scheduling jitter only
    RING = 8

    def __init__(self) -> None:
        self._cond = threading.Condition()
        #: job id → {"gen", "blobs" {(seq, band, kind): bytes},
        #:           "hi" {(band, kind): max seq}, "bytes"}
        self._jobs: dict[str, dict[str, Any]] = {}

    def _entry_locked(self, job_id: str) -> dict[str, Any]:
        ent = self._jobs.get(job_id)
        if ent is None:
            ent = {"gen": 0, "blobs": {}, "hi": {}, "bytes": 0}
            self._jobs[job_id] = ent
        return ent

    def set_gen(self, job_id: str, gen: int) -> None:
        """Adopt a new halo generation for the job (ShardBoard band-
        group restart): all buffered blobs drop and every parked
        long-poll wakes to answer `stale`."""
        with self._cond:
            ent = self._entry_locked(job_id)
            if gen > ent["gen"]:
                ent["gen"] = gen
                ent["blobs"].clear()
                ent["hi"].clear()
                ent["bytes"] = 0
                self._cond.notify_all()

    def clear_job(self, job_id: str) -> None:
        with self._cond:
            self._jobs.pop(job_id, None)
            self._cond.notify_all()

    def post(self, job_id: str, gen: int, seq: int, band: int,
             kind: str, data: bytes) -> bool:
        """Store one blob. Returns False when `gen` is stale (the
        poster's band group restarted under a newer generation) or the
        job is unknown — the board seeds every band job's entry at
        add_job and clears it at collect/cancel, so a straggler's post
        after the job closed must answer `stale`, never resurrect a
        dead entry (the coordinator would leak its blobs forever)."""
        with self._cond:
            ent = self._jobs.get(job_id)
            if ent is None or gen < ent["gen"]:
                return False
            if gen > ent["gen"]:
                # first post of a fresh generation adopts it (the board
                # set it at requeue time; this covers claim-before-sync)
                ent["gen"] = gen
                ent["blobs"].clear()
                ent["hi"].clear()
                ent["bytes"] = 0
            key = (int(seq), int(band), str(kind))
            prior = ent["blobs"].get(key)
            if prior is None:
                ent["bytes"] += len(data)
                ent["blobs"][key] = bytes(data)
            stream = (key[1], key[2])
            hi = max(int(seq), ent["hi"].get(stream, -1))
            ent["hi"][stream] = hi
            for k in [k for k in ent["blobs"]
                      if (k[1], k[2]) == stream and k[0] < hi - self.RING]:
                ent["bytes"] -= len(ent["blobs"].pop(k))
            self._cond.notify_all()
        return True

    def wait(self, job_id: str, gen: int, seq: int, band: int,
             kind: str, timeout_s: float) -> bytes | None:
        """Blocking fetch: the parked long-poll behind GET /work/halo.
        Returns the blob, None on timeout (caller re-polls), or raises
        HaloStaleError when the generation moved on."""
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        key = (int(seq), int(band), str(kind))
        with self._cond:
            while True:
                ent = self._jobs.get(job_id)
                if ent is None:
                    raise HaloStaleError(
                        f"job {job_id} has no live halo entry "
                        f"(collected, cancelled, or never banded)")
                if gen < ent["gen"]:
                    raise HaloStaleError(
                        f"halo generation {gen} superseded by "
                        f"{ent['gen']}")
                blob = ent["blobs"].get(key)
                if blob is not None:
                    return blob
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                self._cond.wait(min(left, 1.0))

    def snapshot(self) -> dict[str, Any]:
        with self._cond:
            return {
                "jobs": len(self._jobs),
                "blobs": sum(len(e["blobs"]) for e in self._jobs.values()),
                "bytes": sum(e["bytes"] for e in self._jobs.values()),
            }


# ---------------------------------------------------------------------------
# worker-side transports
# ---------------------------------------------------------------------------


class HaloClient:
    """Worker-side /work/halo transport: digest-framed blobs over the
    coordinator relay, with the shared jittered-backoff retry policy
    (core/retry.py) under every request and a generous bounded wait
    for peers (`halo_timeout_s` — peers legitimately lag by a device
    step plus scheduling jitter, not more)."""

    def __init__(self, base_url: str, job_id: str, gen: int,
                 timeout_s: float | None = None,
                 retries: int | None = None,
                 backoff_s: float | None = None) -> None:
        from ..core.config import get_settings

        snap = get_settings()
        self.base = base_url.rstrip("/")
        self.job_id = job_id
        self.gen = int(gen)
        self.timeout_s = float(snap.get("halo_timeout_s", 60.0)) \
            if timeout_s is None else max(0.1, float(timeout_s))
        self.retries = int(snap.get("remote_http_retries", 4)) \
            if retries is None else max(0, int(retries))
        self.backoff_s = float(snap.get("remote_http_backoff_s", 0.5)) \
            if backoff_s is None else max(0.0, float(backoff_s))

    def _url(self, seq: int, band: int, kind: str,
             wait: float | None = None) -> str:
        q = (f"job={self.job_id}&gen={self.gen}&seq={int(seq)}"
             f"&band={int(band)}&kind={kind}")
        if wait is not None:
            q += f"&wait={wait:.1f}"
        return f"{self.base}/work/halo?{q}"

    def _request(self, url: str, data: bytes | None,
                 timeout_s: float) -> tuple[bytes, str]:
        import urllib.request

        from ..core.retry import call_with_backoff

        def send() -> tuple[bytes, str]:
            req = urllib.request.Request(
                url, data=data, method="POST" if data is not None
                else "GET",
                headers={"Content-Type": "application/octet-stream"}
                if data is not None else {})
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                return resp.read(), str(
                    resp.headers.get("Content-Type") or "")

        return call_with_backoff(send, self.retries, self.backoff_s)

    def post_blob(self, seq: int, band: int, kind: str,
                  data: bytes) -> None:
        body, ctype = self._request(self._url(seq, band, kind), data,
                                    timeout_s=30.0)
        out = json.loads(body) if "json" in ctype else {}
        if out.get("stale"):
            raise HaloStaleError(
                f"halo post {seq}/{band}/{kind} rejected: generation "
                f"{self.gen} superseded")

    def fetch_blob(self, seq: int, band: int, kind: str) -> bytes:
        deadline = time.monotonic() + self.timeout_s
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                raise HaloTimeoutError(
                    f"halo blob {seq}/{band}/{kind} not published "
                    f"within {self.timeout_s:.0f}s (peer dead or "
                    f"partitioned)")
            wait = min(2.0, max(0.1, left))
            body, ctype = self._request(
                self._url(seq, band, kind, wait=wait), None,
                timeout_s=wait + 30.0)
            if "octet-stream" in ctype:
                return body
            out = json.loads(body)
            if out.get("stale"):
                raise HaloStaleError(
                    f"halo fetch {seq}/{band}/{kind}: generation "
                    f"{self.gen} superseded")
            # pending: the server-side park expired; re-poll


class LocalHaloHub:
    """In-process transport over a HaloRelay instance — the unit-test /
    single-process form of the same protocol (every code path but the
    HTTP hop)."""

    def __init__(self, relay: HaloRelay, job_id: str, gen: int,
                 timeout_s: float = 30.0) -> None:
        self.relay = relay
        self.job_id = job_id
        self.gen = int(gen)
        self.timeout_s = float(timeout_s)

    def post_blob(self, seq: int, band: int, kind: str,
                  data: bytes) -> None:
        if not self.relay.post(self.job_id, self.gen, seq, band, kind,
                               data):
            raise HaloStaleError(
                f"halo post {seq}/{band}/{kind}: generation {self.gen} "
                f"superseded")

    def fetch_blob(self, seq: int, band: int, kind: str) -> bytes:
        deadline = time.monotonic() + self.timeout_s
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                raise HaloTimeoutError(
                    f"halo blob {seq}/{band}/{kind} not published "
                    f"within {self.timeout_s:.0f}s")
            blob = self.relay.wait(self.job_id, self.gen, seq, band,
                                   kind, min(left, 2.0))
            if blob is not None:
                return blob


# ---------------------------------------------------------------------------
# per-shard session (what the farm encoder talks to)
# ---------------------------------------------------------------------------


class HaloSession:
    """One band shard's view of the exchange: publishes this slice's
    edge rows / histogram partials and gathers the peers', keyed by
    the GLOBAL frame index. Pure numpy — the device math (and the
    host-side argmin/median tails) live in parallel/sfefarm.py."""

    def __init__(self, transport, *, band_lo: int, band_hi: int,
                 groups, on_wait: Callable[[float], None] | None = None
                 ) -> None:
        self.t = transport
        self.lo = int(band_lo)
        self.hi = int(band_hi)
        self.groups = [(int(lo), int(hi)) for lo, hi in groups]
        self.total = max((hi for _lo, hi in self.groups),
                         default=self.hi)
        self.peers = [g for g in self.groups if g != (self.lo, self.hi)]
        #: optional wall-clock sink (the encoder's "halo" stage timer)
        self.on_wait = on_wait

    def _fetch(self, seq: int, band: int, kind: str
               ) -> dict[str, np.ndarray]:
        t0 = time.perf_counter()
        try:
            return unpack_arrays(self.t.fetch_blob(seq, band, kind))
        finally:
            if self.on_wait is not None:
                self.on_wait(time.perf_counter() - t0)

    def _fetch_many(self, reqs: list[tuple[int, int, str]]
                    ) -> list[dict[str, np.ndarray]]:
        """Independent long-polls fan out concurrently (one
        short-lived thread per extra request): a multi-group farm must
        not pay (groups - 1) SERIAL relay round-trips per frame for
        payloads that don't depend on each other."""
        if len(reqs) <= 1:
            return [self._fetch(*r) for r in reqs]
        out: list = [None] * len(reqs)
        errs: list[BaseException] = []

        def get(k: int, r: tuple[int, int, str]) -> None:
            try:
                out[k] = self._fetch(*r)
            except BaseException as exc:    # noqa: BLE001 - re-raised
                errs.append(exc)

        threads = [threading.Thread(target=get, args=(k, r),
                                    daemon=True,
                                    name="tvt-halo-fetch")
                   for k, r in enumerate(reqs[1:], 1)]
        for t in threads:
            t.start()
        get(0, reqs[0])
        for t in threads:
            t.join()
        if errs:
            raise errs[0]
        return out

    # -- round A: recon edges + histogram partials ---------------------

    def publish_state(self, seq: int,
                      top: Mapping[str, np.ndarray] | None = None,
                      bot: Mapping[str, np.ndarray] | None = None,
                      hist: Mapping[str, np.ndarray] | None = None
                      ) -> None:
        """After frame `seq`'s step: ship this slice's boundary recon
        rows to the adjacent groups and (for P frames) its histogram
        partial to every peer."""
        if self.lo > 0 and top is not None:
            self.t.post_blob(seq, self.lo, "top", pack_arrays(top))
        if self.hi < self.total and bot is not None:
            self.t.post_blob(seq, self.hi - 1, "bot", pack_arrays(bot))
        if hist is not None and self.peers:
            self.t.post_blob(seq, self.lo, "hist", pack_arrays(hist))

    def gather_edges(self, seq: int) -> tuple[
            dict[str, np.ndarray] | None, dict[str, np.ndarray] | None]:
        """Neighbor recon rows of frame `seq` (the reference for frame
        seq+1's search): (top_ext, bot_ext), None at true frame
        edges."""
        reqs = []
        if self.lo > 0:
            reqs.append((seq, self.lo - 1, "bot"))
        if self.hi < self.total:
            reqs.append((seq, self.hi, "top"))
        got = dict(zip([r[2] for r in reqs], self._fetch_many(reqs)))
        return got.get("bot"), got.get("top")

    def gather_hists(self, seq: int) -> list[dict[str, np.ndarray]]:
        """Every peer's histogram partial for frame `seq`."""
        return self._fetch_many([(seq, lo, "hist")
                                 for lo, _hi in self.peers])

    # -- round B: probe partial reduction ------------------------------

    def sum_probe(self, seq: int, cost: np.ndarray) -> np.ndarray:
        """Cross-host sum of the probe's per-window partial costs for
        frame `seq`. int32 like the device psum (order-independent,
        and wrapping semantics match XLA's exactly, so the argmin can
        never diverge from the full-mesh program's)."""
        total = np.asarray(cost, np.int32)
        if self.peers:
            self.t.post_blob(seq, self.lo, "probe",
                             pack_arrays({"cost": np.asarray(cost)}))
            for peer in self._fetch_many([(seq, lo, "probe")
                                          for lo, _hi in self.peers]):
                total = (total + np.asarray(peer["cost"],
                                            np.int32)).astype(np.int32)
        return total
