"""MP4 muxer tests: structural parse + external decodability via cv2
(opencv bundles ffmpeg — the de-facto container conformance oracle)."""

import struct

import numpy as np
import pytest

from thinvids_tpu.codecs.h264.encoder import encode_gop
from thinvids_tpu.core.types import Frame, VideoMeta
from thinvids_tpu.io.mp4 import annexb_to_samples, mux_mp4, split_annexb


def clip(w=64, h=48, n=6):
    yy, xx = np.mgrid[0:h, 0:w]
    return [Frame(
        y=((xx + yy * 2 + 5 * i) % 256).astype(np.uint8),
        u=np.full((h // 2, w // 2), 110, np.uint8),
        v=np.full((h // 2, w // 2), 140, np.uint8),
    ) for i in range(n)]


def toplevel_boxes(data):
    boxes = []
    i = 0
    while i < len(data):
        size = struct.unpack(">I", data[i:i + 4])[0]
        boxes.append(data[i + 4:i + 8].decode())
        i += size
    return boxes


class TestMux:
    def test_annexb_split_and_samples(self):
        meta = VideoMeta(width=64, height=48, fps_num=30, fps_den=1)
        stream = encode_gop(clip(), meta, qp=30)
        sps, pps, samples, keys = annexb_to_samples(stream)
        assert sps[0] & 0x1F == 7 and pps[0] & 0x1F == 8
        assert len(samples) == 6
        assert keys == [True] + [False] * 5     # IDR + 5 P
        nals = split_annexb(stream)
        assert len(nals) == 8                   # SPS PPS IDR 5xP

    def test_faststart_layout_and_structure(self):
        meta = VideoMeta(width=64, height=48, fps_num=30, fps_den=1)
        stream = encode_gop(clip(), meta, qp=30)
        mp4 = mux_mp4(stream, meta)
        assert toplevel_boxes(mp4) == ["ftyp", "moov", "mdat"]
        # chunk offset points at the first sample inside mdat
        # box: [size][`stco`][ver/flags][count][offset0]
        stco_at = mp4.find(b"stco")
        off = struct.unpack(">I", mp4[stco_at + 12:stco_at + 16])[0]
        first_len = struct.unpack(">I", mp4[off:off + 4])[0]
        assert mp4[off + 4] & 0x1F == 5         # IDR NAL right there
        assert first_len > 0

    def test_cv2_decodes_mp4(self, tmp_path):
        import cv2

        w, h, n = 64, 48, 8
        meta = VideoMeta(width=w, height=h, fps_num=25, fps_den=1)
        stream = encode_gop(clip(w, h, n), meta, qp=28)
        path = str(tmp_path / "out.mp4")
        open(path, "wb").write(mux_mp4(stream, meta))
        cap = cv2.VideoCapture(path)
        assert int(cap.get(cv2.CAP_PROP_FRAME_COUNT)) == n
        assert int(cap.get(cv2.CAP_PROP_FRAME_WIDTH)) == w
        assert int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT)) == h
        assert abs(cap.get(cv2.CAP_PROP_FPS) - 25.0) < 0.01
        count = 0
        while True:
            ok, img = cap.read()
            if not ok:
                break
            assert img.shape[:2] == (h, w)
            count += 1
        assert count == n

    def test_cropped_dims_in_container(self, tmp_path):
        import cv2

        w, h = 70, 50
        meta = VideoMeta(width=w, height=h, fps_num=30, fps_den=1)
        stream = encode_gop(clip(w, h, 4), meta, qp=30)
        path = str(tmp_path / "crop.mp4")
        open(path, "wb").write(mux_mp4(stream, meta))
        cap = cv2.VideoCapture(path)
        ok, img = cap.read()
        assert ok and img.shape[:2] == (h, w)

    def test_no_parameter_sets_raises(self):
        with pytest.raises(ValueError, match="SPS/PPS"):
            mux_mp4(b"\x00\x00\x01\x65\x88", VideoMeta(width=16, height=16))

    def test_tkhd_spec_layout(self):
        # ISO 14496-12 §8.3.2 version-0 tkhd is exactly 92 bytes; the
        # matrix and width/height must land on spec offsets (positional
        # parsers like the ffmpeg mov demuxer read them by offset).
        w, h = 64, 48
        meta = VideoMeta(width=w, height=h, fps_num=30, fps_den=1)
        mp4 = mux_mp4(encode_gop(clip(w, h, 4), meta, qp=30), meta)
        at = mp4.find(b"tkhd") - 4
        size = struct.unpack(">I", mp4[at:at + 4])[0]
        assert size == 92
        box = mp4[at:at + size]
        # matrix at offset 40 within the box body (8 header + 4 verflags
        # + 20 ids/duration + 16 reserved/layer/volume)
        matrix = struct.unpack(">9I", box[48:84])
        assert matrix == (0x10000, 0, 0, 0, 0x10000, 0, 0, 0, 0x40000000)
        tw, th = struct.unpack(">II", box[84:92])
        assert (tw >> 16, th >> 16) == (w, h)

    def test_mdat_over_limit_raises(self, monkeypatch):
        # The 4 GiB 32-bit box-size ceiling must fail loudly, not emit a
        # corrupt file; exercised by lowering the guard threshold.
        import thinvids_tpu.io.mp4 as mp4mod

        meta = VideoMeta(width=64, height=48, fps_num=30, fps_den=1)
        stream = encode_gop(clip(64, 48, 4), meta, qp=30)
        monkeypatch.setattr(mp4mod, "_MAX_MDAT", 50)
        with pytest.raises(ValueError, match="32-bit"):
            mp4mod.mux_mp4(stream, meta)


class TestProbeSizeZero:
    """ISO BMFF size==0 ("box extends to end of file") handling in the
    streaming moov probe (probe_mp4_header)."""

    def _boxes(self, mp4):
        out, i = {}, 0
        while i < len(mp4):
            size = struct.unpack(">I", mp4[i:i + 4])[0]
            out[mp4[i + 4:i + 8]] = mp4[i:i + size]
            i += size
        return out

    def test_probe_size_zero_moov_at_eof(self, tmp_path):
        from thinvids_tpu.io.mp4 import probe_mp4_header

        meta = VideoMeta(width=64, height=48, fps_num=30, fps_den=1)
        mp4 = mux_mp4(encode_gop(clip(), meta, qp=30), meta)
        ref_path = tmp_path / "ref.mp4"
        ref_path.write_bytes(mp4)
        boxes = self._boxes(mp4)
        # moov moved last with size 0 (extends to EOF)
        moov0 = struct.pack(">I", 0) + boxes[b"moov"][4:]
        p = tmp_path / "eof_moov.mp4"
        p.write_bytes(boxes[b"ftyp"] + boxes[b"mdat"] + moov0)
        assert probe_mp4_header(str(p)) == probe_mp4_header(str(ref_path))

    def test_probe_size_zero_non_moov_stops_at_eof(self, tmp_path):
        # Regression: a size==0 non-moov box seeked 0 bytes, so the next
        # iteration re-parsed the box's own PAYLOAD as top-level headers
        # — here that payload embeds a fake moov the probe used to find.
        from thinvids_tpu.io.mp4 import probe_mp4_header

        ftyp = struct.pack(">I", 16) + b"ftyp" + b"isom" \
            + struct.pack(">I", 0)
        fake_moov = struct.pack(">I", 16) + b"moov" + b"\0" * 8
        free0 = struct.pack(">I", 0) + b"free" + fake_moov
        p = tmp_path / "free0.mp4"
        p.write_bytes(ftyp + free0)
        with pytest.raises(ValueError, match="no moov"):
            probe_mp4_header(str(p))
