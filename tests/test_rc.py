"""Rate control: psum complexity exchange + per-GOP QP + 2-pass VBR.

BASELINE config 4's shape: per-GOP rate-control stats exchanged with
`jax.lax.psum` over the gop mesh axis, per-GOP QPs solved against a
bitrate target, slice headers carrying the deltas. Decisions must be
identical sharded vs single-device, and the 2-pass output must land
within ±10% of the target on synthetic content.
"""

import numpy as np
import pytest

import jax

from thinvids_tpu.core.types import Frame, VideoMeta, concat_segments
from thinvids_tpu.parallel import rc
from thinvids_tpu.parallel.dispatch import GopShardEncoder
from jax.sharding import Mesh


def _clip(n=32, w=128, h=64, seed=0):
    """Half flat / half busy content so complexity shares differ."""
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:h, 0:w]
    frames = []
    for i in range(n):
        if i < n // 2:
            y = np.full((h, w), 120, np.uint8)       # flat, cheap GOPs
        else:
            y = ((xx * 3 + yy + 5 * i) % 256).astype(np.uint8)
            y = np.clip(y + rng.integers(-20, 21, (h, w)), 0,
                        255).astype(np.uint8)        # busy GOPs
        frames.append(Frame(
            y=y, u=np.full((h // 2, w // 2), 110, np.uint8),
            v=np.full((h // 2, w // 2), 140, np.uint8)))
    meta = VideoMeta(width=w, height=h, fps_num=30, fps_den=1,
                     num_frames=n)
    return frames, meta


class TestComplexityShares:
    def test_sharded_matches_single_device(self):
        frames, meta = _clip()
        enc8 = GopShardEncoder(meta, qp=27, gop_frames=4)
        assert enc8.num_devices == 8
        single = Mesh(np.array(jax.devices()[:1]), ("gop",))
        enc1 = GopShardEncoder(meta, qp=27, mesh=single, gop_frames=4,
                               gops_per_wave=8)
        s8 = rc.analyze_complexity(enc8, frames)
        s1 = rc.analyze_complexity(enc1, frames)
        assert len(s8) == 8
        np.testing.assert_allclose(s8, s1, rtol=1e-5)
        assert abs(s8.sum() - 1.0) < 1e-6
        # busy half must carry most of the complexity
        assert s8[4:].sum() > 0.9

    def test_qp_decisions_identical_sharded_vs_single(self):
        frames, meta = _clip()
        single = Mesh(np.array(jax.devices()[:1]), ("gop",))
        encs = [GopShardEncoder(meta, qp=27, gop_frames=4),
                GopShardEncoder(meta, qp=27, mesh=single, gop_frames=4,
                                gops_per_wave=8)]
        qps = []
        for enc in encs:
            shares = rc.analyze_complexity(enc, frames)
            segs = enc.encode_waves(enc.stage_waves(frames))
            nbytes = np.asarray([len(s.payload) for s in segs], np.float64)
            qps.append(rc.solve_gop_qps(27, nbytes, shares, 100_000.0))
        np.testing.assert_array_equal(qps[0], qps[1])


class TestPerGopQp:
    def test_per_gop_qp_stream_decodes_and_obeys_qp(self):
        from thinvids_tpu.tools import oracle

        frames, meta = _clip()
        enc = GopShardEncoder(meta, qp=27, gop_frames=4)
        n_gops = enc.plan(len(frames)).num_gops
        enc.gop_qp = {i: (20 if i % 2 == 0 else 36) for i in range(n_gops)}
        segs = enc.encode_waves(enc.stage_waves(frames))
        stream = concat_segments(segs)
        # lower-QP GOPs must spend more bits than same-content higher-QP
        # ones: compare the two busy-half pairs
        busy = sorted(segs[4:], key=lambda s: s.gop.index)
        low = [s for s in busy if enc.gop_qp[s.gop.index] == 20]
        high = [s for s in busy if enc.gop_qp[s.gop.index] == 36]
        assert min(len(p.payload) for p in low) > \
            max(len(p.payload) for p in high)
        if oracle.oracle_available():
            assert len(oracle.decode_h264(stream)) == len(frames)

    def test_base_qp_unchanged_bit_identity(self):
        # gop_qp empty -> byte-identical to the pre-rate-control path
        frames, meta = _clip(n=8)
        enc = GopShardEncoder(meta, qp=27, gop_frames=4)
        a = concat_segments(enc.encode_waves(enc.stage_waves(frames)))
        from thinvids_tpu.parallel.dispatch import encode_clip_sharded
        b = encode_clip_sharded(frames, meta, qp=27, gop_frames=4)
        assert a == b


class TestVbr2Pass:
    @pytest.mark.parametrize("target_kbps", [200.0, 600.0])
    def test_hits_bitrate_within_10pct(self, target_kbps):
        frames, meta = _clip()
        segs, stats = rc.encode_vbr2pass(frames, meta, target_kbps,
                                         base_qp=27, gop_frames=4)
        assert len(segs) == 8
        err = abs(stats["pass2_bits"] - stats["target_bits"]) \
            / stats["target_bits"]
        assert err < 0.10, stats
        # busy GOPs must get lower (or equal) QP than flat ones
        qps = stats["gop_qps"]
        assert min(qps[4:]) <= min(qps[:4])

    def test_unreachable_target_saturates_at_qp_floor(self):
        # this clip cannot produce 5 Mbps even at QP_MIN: the solver
        # must stop at the floor instead of spinning through passes
        frames, meta = _clip()
        segs, stats = rc.encode_vbr2pass(frames, meta, 5000.0,
                                         base_qp=27, gop_frames=4)
        assert all(q == rc.QP_MIN for q in stats["gop_qps"])
        assert stats["passes"] <= 4
        assert stats["pass2_bits"] < stats["target_bits"]


class TestJndMaskedShares:
    def test_zero_strength_is_identity(self):
        import numpy as np

        from thinvids_tpu.parallel.rc import jnd_masked_shares

        s = np.asarray([0.5, 0.3, 0.2])
        np.testing.assert_array_equal(jnd_masked_shares(s, 0.0), s)

    def test_masking_flattens_toward_uniform(self):
        """Busy GOPs mask their own distortion: their share of the bit
        budget shrinks relative to raw complexity, flat GOPs gain —
        but the ORDER is preserved and the result stays a
        distribution."""
        import numpy as np

        from thinvids_tpu.parallel.rc import jnd_masked_shares

        s = np.asarray([0.7, 0.2, 0.1])
        m = jnd_masked_shares(s, 1.0)
        assert abs(m.sum() - 1.0) < 1e-12
        assert m[0] < s[0] and m[2] > s[2]
        assert m[0] > m[1] > m[2]

    def test_vbr2pass_accepts_aq_strength(self):
        import inspect

        from thinvids_tpu.parallel import rc

        assert "aq_strength" in inspect.signature(
            rc.encode_vbr2pass).parameters
