"""Control-plane tests: store, policy, scheduler gates, watchdog, fencing.

All time-dependent behavior runs on a fake clock — the testability the
reference never had (SURVEY.md §4: its retry/watchdog complexity existed
precisely because it was untestable off-cluster).
"""

import numpy as np
import pytest

from thinvids_tpu.cluster import (
    Coordinator,
    JobStore,
    WorkerRegistry,
    evaluate_job_policy,
)
from thinvids_tpu.core.config import (
    DEFAULT_SETTINGS,
    Settings,
    overlay_job_settings,
)
from thinvids_tpu.core.status import Status
from thinvids_tpu.core.types import VideoMeta


class FakeClock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def make_settings(**over):
    values = dict(DEFAULT_SETTINGS)
    values.update(over)
    return Settings(values=values)


def make_coord(clock=None, launcher=None, workers=8, pipeline=8, **over):
    clock = clock or FakeClock()
    snap = make_settings(**over)
    reg = WorkerRegistry(clock=clock)
    for i in range(workers):
        reg.heartbeat(f"w{i:02d}", now=clock())
    coord = Coordinator(registry=reg, launcher=launcher, clock=clock,
                        settings_fn=lambda: snap)
    return coord, clock


def meta(codec="h264", size=1 << 20):
    return VideoMeta(width=64, height=48, num_frames=8, codec=codec,
                     size_bytes=size)


class TestPolicy:
    def test_av1_toggle(self):
        s_off = make_settings(reject_av1=False)
        s_on = make_settings(reject_av1=True)
        assert evaluate_job_policy(meta(codec="av1"), s_off).accepted
        d = evaluate_job_policy(meta(codec="av1"), s_on)
        assert not d.accepted and "av1" in d.reason

    def test_large_file_behaviors(self):
        big = meta(size=16 << 30)
        assert not evaluate_job_policy(
            big, make_settings(large_file_behavior="reject")).accepted
        assert evaluate_job_policy(
            big, make_settings(large_file_behavior="direct")
        ).processing_mode == "direct"
        d = evaluate_job_policy(
            big, make_settings(large_file_behavior="nfs"))
        assert d.processing_mode == "split" and d.scratch_mode == "nfs"

    def test_vc1_forced_direct(self):
        assert evaluate_job_policy(
            meta(codec="vc1"), make_settings()).processing_mode == "direct"


class TestJobStore:
    def test_crud_and_all_idle(self):
        store = JobStore()
        job = store.create("/in/a.y4m", meta=meta())
        assert store.all_idle()
        store.update(job.id, lambda j: setattr(j, "status", Status.WAITING))
        assert not store.all_idle()
        assert len(store.list(Status.WAITING)) == 1
        assert store.delete(job.id)
        assert not store.delete(job.id)
        with pytest.raises(KeyError):
            store.get(job.id)

    def test_snapshots_are_copies(self):
        store = JobStore()
        job = store.create("/in/a.y4m")
        snap = store.get(job.id)
        snap.status = Status.FAILED          # mutating the copy
        assert store.get(job.id).status is Status.READY


class TestDispatch:
    def test_auto_start_launches(self):
        launched = []
        coord, _ = make_coord(launcher=launched.append)
        job = coord.add_job("/in/a.y4m", meta())
        assert job.status is Status.STARTING
        assert [j.id for j in launched] == [job.id]
        assert launched[0].run_token

    def test_rejected_job_not_queued(self):
        coord, _ = make_coord(reject_av1=True)
        job = coord.add_job("/in/bad.av1", meta(codec="av1"))
        assert job.status is Status.REJECTED
        assert coord.store.all_idle()

    def test_capacity_gate_blocks_second_job(self):
        launched = []
        coord, _ = make_coord(launcher=launched.append)
        a = coord.add_job("/in/a.y4m", meta())
        b = coord.add_job("/in/b.y4m", meta())
        assert coord.store.get(a.id).status is Status.STARTING
        assert coord.store.get(b.id).status is Status.WAITING
        assert len(launched) == 1

    def test_drain_gate_admits_second_job(self):
        launched = []
        coord, _ = make_coord(launcher=launched.append)
        a = coord.add_job("/in/a.y4m", meta())
        b = coord.add_job("/in/b.y4m", meta())
        tok = coord.store.get(a.id).run_token
        # a becomes RUNNING, fully segmented, 75% drained -> shareable
        coord.mark_running(a.id, tok)
        coord.update_progress(a.id, tok, segment_progress=100.0,
                              parts_total=8, parts_done=6)
        coord.dispatch_next_waiting_job()
        assert coord.store.get(b.id).status is Status.STARTING
        assert len(launched) == 2

    def test_drain_below_ratio_blocks(self):
        coord, _ = make_coord()
        a = coord.add_job("/in/a.y4m", meta())
        b = coord.add_job("/in/b.y4m", meta())
        tok = coord.store.get(a.id).run_token
        coord.mark_running(a.id, tok)
        coord.update_progress(a.id, tok, segment_progress=100.0,
                              parts_total=8, parts_done=5)   # 62.5% < 75%
        coord.dispatch_next_waiting_job()
        assert coord.store.get(b.id).status is Status.WAITING

    def test_no_workers_no_dispatch(self):
        coord, _ = make_coord(workers=0)
        job = coord.add_job("/in/a.y4m", meta())
        assert coord.store.get(job.id).status is Status.WAITING

    def test_min_idle_workers_gate(self):
        # 3 workers satisfy the slot check (3 >= 0 used + 2) but not the
        # min-idle estimate (3 < 4), so nothing dispatches.
        coord, _ = make_coord(workers=3, min_idle_workers=4)
        a = coord.add_job("/in/a.y4m", meta())
        assert coord.store.get(a.id).status is Status.WAITING

    def test_device_count_weights_slot_capacity(self):
        """One node reporting N devices carries 1+N scheduler slots —
        the honest replacement for the phantom `{host}-devN` pseudo-
        nodes the CLI used to heartbeat (VERDICT Weak #7)."""
        launched = []
        clock = FakeClock()
        snap = make_settings(min_idle_workers=4)
        reg = WorkerRegistry(clock=clock)
        reg.heartbeat("tpu-host", metrics={"devices": 8}, now=clock())
        coord = Coordinator(registry=reg, launcher=launched.append,
                            clock=clock, settings_fn=lambda: snap)
        job = coord.add_job("/in/a.y4m", meta())
        # 9 slots: pipeline gate (>= 2) and idle gate (9 - 2 >= 4) pass
        assert coord.store.get(job.id).status is Status.STARTING
        assert launched

    def test_single_deviceless_node_blocks_dispatch(self):
        # without a device count the lone node is 1 slot < the 2 a
        # segmenting job needs — no phantom inflation to hide behind
        coord, _ = make_coord(workers=1, min_idle_workers=0)
        job = coord.add_job("/in/a.y4m", meta())
        assert coord.store.get(job.id).status is Status.WAITING

    def test_stale_worker_heartbeats_expire(self):
        launched = []
        coord, clock = make_coord(launcher=launched.append)
        clock.advance(60.0)          # all worker heartbeats now stale
        job = coord.add_job("/in/a.y4m", meta())
        assert coord.store.get(job.id).status is Status.WAITING
        # a fresh heartbeat revives capacity
        for i in range(8):
            coord.registry.heartbeat(f"w{i:02d}")
        coord.dispatch_next_waiting_job()
        assert coord.store.get(job.id).status is Status.STARTING

    def test_oldest_waiting_dispatched_first(self):
        launched = []
        coord, clock = make_coord(launcher=launched.append, workers=0)
        a = coord.add_job("/in/a.y4m", meta())
        clock.advance(1)
        b = coord.add_job("/in/b.y4m", meta())
        for i in range(8):
            coord.registry.heartbeat(f"w{i:02d}")
        coord.dispatch_next_waiting_job()
        assert launched and launched[0].id == a.id
        assert coord.store.get(b.id).status is Status.WAITING


class TestFencing:
    def test_stale_token_ignored(self):
        coord, _ = make_coord()
        a = coord.add_job("/in/a.y4m", meta())
        old = coord.store.get(a.id).run_token
        coord.restart_job(a.id)              # mints a new token
        new = coord.store.get(a.id).run_token
        assert old != new
        assert not coord.update_progress(a.id, old, parts_done=3)
        assert not coord.heartbeat_job(a.id, old, "encode")
        assert not coord.complete_job(a.id, old, "/out/x.264", 1)
        assert coord.update_progress(a.id, new, parts_total=4, parts_done=3)

    def test_stop_revokes_token(self):
        coord, _ = make_coord()
        a = coord.add_job("/in/a.y4m", meta())
        tok = coord.store.get(a.id).run_token
        coord.stop_job(a.id)
        assert not coord.token_is_current(a.id, tok)

    def test_progress_monotonic(self):
        coord, _ = make_coord()
        a = coord.add_job("/in/a.y4m", meta())
        tok = coord.store.get(a.id).run_token
        coord.update_progress(a.id, tok, encode_progress=50.0)
        coord.update_progress(a.id, tok, encode_progress=30.0)  # regress
        assert coord.store.get(a.id).encode_progress == 50.0


class TestWatchdog:
    def test_stalled_starting_job_fails(self):
        coord, clock = make_coord()
        a = coord.add_job("/in/a.y4m", meta())
        clock.advance(301.0)                 # budget 300s for STARTING
        failed = coord.check_stalled_jobs()
        assert [j.id for j in failed] == [a.id]
        job = coord.store.get(a.id)
        assert job.status is Status.FAILED
        assert "no heartbeat" in job.failure_reason
        assert job.run_token == ""           # revoked

    def test_heartbeat_defers_watchdog(self):
        coord, clock = make_coord()
        a = coord.add_job("/in/a.y4m", meta())
        tok = coord.store.get(a.id).run_token
        clock.advance(250.0)
        coord.heartbeat_job(a.id, tok, "segment", host="exec0")
        clock.advance(250.0)                 # 500s total, 250s since beat
        assert coord.check_stalled_jobs() == []

    def test_running_budget_longer(self):
        coord, clock = make_coord()
        a = coord.add_job("/in/a.y4m", meta())
        tok = coord.store.get(a.id).run_token
        coord.mark_running(a.id, tok)
        coord.heartbeat_job(a.id, tok, "encode")
        clock.advance(400.0)                 # > STARTING 300, < RUNNING 900
        assert coord.check_stalled_jobs() == []
        clock.advance(600.0)
        assert [j.id for j in coord.check_stalled_jobs()] == [a.id]

    def test_watchdog_failure_redispatches_next(self):
        launched = []
        coord, clock = make_coord(launcher=launched.append)
        a = coord.add_job("/in/a.y4m", meta())
        b = coord.add_job("/in/b.y4m", meta())
        clock.advance(301.0)
        coord.registry  # workers stale too — revive them:
        for i in range(8):
            coord.registry.heartbeat(f"w{i:02d}")
        coord.check_stalled_jobs()
        assert coord.store.get(a.id).status is Status.FAILED
        assert coord.store.get(b.id).status is Status.STARTING
        assert [j.id for j in launched] == [a.id, b.id]


class TestLifecycle:
    def test_complete_flow(self):
        coord, _ = make_coord()
        a = coord.add_job("/in/a.y4m", meta())
        tok = coord.store.get(a.id).run_token
        coord.mark_running(a.id, tok)
        coord.update_progress(a.id, tok, segment_progress=100.0,
                              parts_total=4, parts_done=4,
                              encode_progress=100.0)
        assert coord.complete_job(a.id, tok, "/lib/a.mp4", 12345)
        job = coord.store.get(a.id)
        assert job.status is Status.DONE
        assert job.output_path == "/lib/a.mp4"
        assert coord.store.all_idle()

    def test_executor_fail_attribution(self):
        coord, _ = make_coord()
        a = coord.add_job("/in/a.y4m", meta())
        tok = coord.store.get(a.id).run_token
        coord.fail_job(a.id, tok, stage="encode", host="exec1",
                       reason="part 3 failed 5 times")
        job = coord.store.get(a.id)
        assert job.status is Status.FAILED
        assert job.failure_stage == "encode"
        assert job.failure_host == "exec1"

    def test_restart_after_failure(self):
        coord, _ = make_coord()
        a = coord.add_job("/in/a.y4m", meta())
        tok = coord.store.get(a.id).run_token
        coord.fail_job(a.id, tok, "encode", "exec1", "boom")
        job = coord.restart_job(a.id)
        assert job.status is Status.STARTING     # re-dispatched
        assert job.failure_reason == ""
        assert job.run_token and job.run_token != tok

    def test_activity_log_wired(self):
        coord, _ = make_coord()
        a = coord.add_job("/in/a.y4m", meta())
        events = coord.activity.fetch()
        assert any(e["stage"] == "dispatch" for e in events)
        lines = coord.activity.fetch_job(a.id)
        assert lines


class TestRegistry:
    def test_role_assignment_natural_sort(self):
        reg = WorkerRegistry(clock=lambda: 0.0)
        for h in ("w10", "w2", "w1"):
            reg.heartbeat(h, now=0.0)
        roles = reg.assign_roles(2)
        assert roles == {"w1": "pipeline", "w2": "pipeline",
                         "w10": "encode"}

    def test_disabled_workers_excluded(self):
        clock = FakeClock()
        reg = WorkerRegistry(clock=clock)
        reg.heartbeat("a")
        reg.heartbeat("b")
        reg.set_disabled("a", True, reason="flaky")
        assert [w.host for w in reg.active(15.0)] == ["b"]
        assert reg.assign_roles(2) == {"b": "pipeline"}

    def test_job_settings_overlay(self):
        coord, _ = make_coord()
        a = coord.add_job("/in/a.y4m", meta(), settings={"qp": 40,
                                                        "bogus": 1})
        snap = coord.job_settings(coord.store.get(a.id))
        assert snap.qp == 40
        assert "bogus" not in snap.values


class TestProtocolGuards:
    """Regression tests for the TVT-M001 status-machine guards: every
    Status write site in the coordinator now proves its source states
    locally, so the races/holes below stay fixed (see the declared job
    table in analysis/manifest.py)."""

    def test_stop_on_terminal_job_is_a_noop(self):
        coord, _ = make_coord()
        a = coord.add_job("/in/a.y4m", meta())
        tok = coord.store.get(a.id).run_token
        coord.mark_running(a.id, tok)
        assert coord.complete_job(a.id, tok, "/lib/a.mp4", 7)
        stopped = coord.stop_job(a.id)
        # terminal absorbs: the result must survive an operator stop
        assert stopped.status is Status.DONE
        assert stopped.output_path == "/lib/a.mp4"

    def test_stale_watchdog_verdict_cannot_fail_done_job(self):
        # the watchdog reads the active set as a snapshot; simulate
        # the job completing between that read and the fail write
        coord, _ = make_coord()
        a = coord.add_job("/in/a.y4m", meta())
        tok = coord.store.get(a.id).run_token
        coord.mark_running(a.id, tok)
        coord.complete_job(a.id, tok, "/lib/a.mp4", 7)
        coord._fail(a.id, "encode", "w00", "no heartbeat (stale)")
        job = coord.store.get(a.id)
        assert job.status is Status.DONE
        assert job.failure_reason == ""
        assert job.output_path == "/lib/a.mp4"

    def test_rejected_job_cannot_be_requeued_or_restarted(self):
        coord, _ = make_coord(reject_av1=True)
        a = coord.add_job("/in/clip.mkv", meta(codec="av1"))
        assert coord.store.get(a.id).status is Status.REJECTED
        with pytest.raises(ValueError):
            coord.queue_job(a.id)
        with pytest.raises(ValueError):
            coord.restart_job(a.id)
        assert coord.store.get(a.id).status is Status.REJECTED

    def test_operator_stop_wins_reserve_race(self, monkeypatch):
        import dataclasses as _dc

        coord, _ = make_coord(auto_start_jobs=False)
        a = coord.add_job("/in/a.y4m", meta())
        coord.queue_job(a.id)
        stale = [_dc.replace(j)
                 for j in coord.store.list(Status.WAITING)]
        coord.stop_job(a.id)
        real_list = coord.store.list

        def stale_list(status=None):
            if status is Status.WAITING:
                return [_dc.replace(j) for j in stale]
            return real_list(status)

        monkeypatch.setattr(coord.store, "list", stale_list)
        # the scheduler sees the pre-stop WAITING snapshot; the
        # reserve guard must notice the job left WAITING and bail
        assert coord.dispatch_next_waiting_job() is None
        assert coord.store.get(a.id).status is Status.STOPPED

    def test_reserve_race_falls_through_to_next_candidate(self,
                                                          monkeypatch):
        import dataclasses as _dc

        launched = []
        coord, clock = make_coord(auto_start_jobs=False,
                                  launcher=launched.append)
        a = coord.add_job("/in/a.y4m", meta())
        clock.advance(1)
        b = coord.add_job("/in/b.y4m", meta())
        coord.queue_job(a.id)
        coord.queue_job(b.id)
        stale = [_dc.replace(j)
                 for j in coord.store.list(Status.WAITING)]
        coord.stop_job(a.id)               # a raced out of WAITING
        real_list = coord.store.list

        def stale_list(status=None):
            if status is Status.WAITING:
                return [_dc.replace(j) for j in stale]
            return real_list(status)

        monkeypatch.setattr(coord.store, "list", stale_list)
        job = coord.dispatch_next_waiting_job()
        # one stopped candidate must not strand the rest of the queue
        assert job is not None and job.id == b.id
        assert coord.store.get(b.id).status is Status.STARTING
        assert coord.store.get(a.id).status is Status.STOPPED

    def test_straggler_mark_running_after_done_is_ignored(self):
        coord, _ = make_coord()
        a = coord.add_job("/in/a.y4m", meta())
        tok = coord.store.get(a.id).run_token
        coord.mark_running(a.id, tok)
        coord.complete_job(a.id, tok, "/lib/a.mp4", 7)
        coord.mark_running(a.id, tok)      # straggler executor thread
        assert coord.store.get(a.id).status is Status.DONE

    def test_second_complete_is_rejected(self):
        coord, _ = make_coord()
        a = coord.add_job("/in/a.y4m", meta())
        tok = coord.store.get(a.id).run_token
        coord.mark_running(a.id, tok)
        assert coord.complete_job(a.id, tok, "/lib/a.mp4", 7)
        assert not coord.complete_job(a.id, tok, "/lib/other.mp4", 9)
        job = coord.store.get(a.id)
        assert job.output_path == "/lib/a.mp4"
        assert job.output_bytes == 7
