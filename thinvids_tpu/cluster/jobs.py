"""Typed job records + thread-safe store.

The reference kept each job as a ~60-field Redis hash (`job:<uuid>`,
/root/reference/manager/app.py:2367-2370) indexed by a `jobs:all` set
(/root/reference/common.py:231-274); this is the typed in-process
equivalent with the same lifecycle fields: status, per-stage progress,
run-token fence, heartbeat triple, and failure attribution.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import uuid
from typing import Any, Callable, Iterator, Mapping

from ..core.status import Status
from ..core.types import ChromaFormat, VideoMeta


def new_run_token() -> str:
    """Fencing token minted per dispatch; stale executors no-op when
    their token no longer matches (the reference's pipeline_run_token,
    /root/reference/worker/tasks.py:396-424)."""
    return uuid.uuid4().hex


@dataclasses.dataclass
class Job:
    """One transcode job. Mutate only through JobStore.update()."""

    id: str
    input_path: str
    meta: VideoMeta | None = None
    status: Status = Status.READY
    # what the job produces: "transcode" = single-rendition MP4,
    # "ladder" = the ABR rendition set packaged as HLS (abr/),
    # "live" = LL-HLS ladder tailed from a GROWING source — output
    # (the served playlist tree) becomes available DURING the run,
    # not at completion (live/)
    job_type: str = "transcode"
    # settings overlay (core.config.JOB_SETTING_KEYS subset)
    settings: dict[str, Any] = dataclasses.field(default_factory=dict)
    # tenant namespace (farm/tenancy.py): resolved at registration
    # from the per-job `tenant` setting, the `<tenant>__name` filename
    # prefix, or the cluster default — the fair-share admission key
    # and the per-tenant metrics label
    tenant: str = "default"
    # admission decision (policy.py): the remote backend encodes
    # "direct" jobs whole on the coordinator mesh instead of farming
    # split shards (cluster/remote.py)
    processing_mode: str = "split"       # split | direct
    reject_reason: str = ""
    # scheduling / fencing
    run_token: str = ""
    queued_at: float = 0.0
    started_at: float = 0.0
    finished_at: float = 0.0
    created_at: float = dataclasses.field(default_factory=time.time)
    # progress (percent 0-100, parts = GOP segments)
    segment_progress: float = 0.0
    encode_progress: float = 0.0
    combine_progress: float = 0.0
    parts_total: int = 0
    parts_done: int = 0
    # parts re-dispatched after a worker failure/timeout (remote
    # backend) or wave retry — the farm-health signal the dashboard
    # surfaces next to parts_done
    parts_retried: int = 0
    # heartbeat (throttled writes; watchdog liveness source)
    heartbeat_at: float = 0.0
    heartbeat_stage: str = ""
    heartbeat_host: str = ""
    heartbeat_note: str = ""
    # failure attribution
    failure_stage: str = ""
    failure_host: str = ""
    failure_reason: str = ""
    # result
    output_path: str = ""
    output_bytes: int = 0
    elapsed_s: float = 0.0

    @property
    def done_ratio(self) -> float:
        if self.parts_total <= 0:
            return 0.0
        return self.parts_done / self.parts_total

    def to_dict(self) -> dict[str, Any]:
        """JSON-clean view (enums → names) for the API/store layers."""
        d = dataclasses.asdict(self)
        d["status"] = self.status.value
        if self.meta is not None:
            meta = dataclasses.asdict(self.meta)
            meta["chroma"] = self.meta.chroma.name
            d["meta"] = meta
        return d

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "Job":
        """Inverse of to_dict (the journal restore path). Unknown keys
        are dropped so old journals survive field additions."""
        data = dict(d)
        raw_status = data.get("status")
        try:
            data["status"] = Status.parse(raw_status)
        except ValueError:
            # A corrupted persisted status must never silently become
            # schedulable again (core/status.py contract) — surface it
            # as a failed job with attribution instead.
            data["status"] = Status.FAILED
            data.setdefault("failure_stage", "restore")
            data["failure_reason"] = (
                f"corrupt persisted status {raw_status!r}")
        meta = data.get("meta")
        if meta is not None:
            meta = dict(meta)
            meta["chroma"] = ChromaFormat[meta.get("chroma", "YUV420")]
            known_m = {f.name for f in dataclasses.fields(VideoMeta)}
            data["meta"] = VideoMeta(
                **{k: v for k, v in meta.items() if k in known_m})
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in known})


class JobStore:
    """Thread-safe job index, optionally journal-backed.

    The update() path takes the store lock and hands the caller the live
    record — the analog of the reference's HSET read-modify-write under
    its scheduler lock. Snapshots returned by get()/list() are copies.

    With `path` set, every mutation appends a JSON line
    (``{"op": "put"|"del", ...}``) to the journal, and construction
    replays it — the durable-state role Redis played for the reference
    (SURVEY.md §5.4: the job hash IS the job's checkpoint). The journal
    is compacted to one line per live job on open and whenever it grows
    past ~10x the live set.
    """

    def __init__(self, path: str | None = None) -> None:
        self._lock = threading.RLock()
        self._jobs: dict[str, Job] = {}
        self._path = path
        self._journal: Any = None
        self._lockfile: Any = None
        self._journal_lines = 0
        self._closed = False
        if path:
            self._acquire_lockfile()
            try:
                # construction is single-threaded, but `_jobs` is
                # lock-guarded state (TVT-T004): hold the lock so the
                # replay/compact sites follow the same discipline as
                # every other access
                with self._lock:
                    self._replay_locked()
                    self._compact_locked()
            except BaseException:
                self.close()           # don't leak the flock on failure
                raise

    def _acquire_lockfile(self) -> None:
        """Exclusive-own the journal via flock on a sidecar lock file
        (never replaced, so compaction can't orphan the lock). A second
        store over the same path would otherwise os.replace the journal
        out from under the first one's append handle — both would then
        'durably' write divergent state."""
        import fcntl

        self._lockfile = open(self._path + ".lock", "w")
        try:
            fcntl.flock(self._lockfile, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            self._lockfile.close()
            self._lockfile = None
            raise RuntimeError(
                f"job journal {self._path} is owned by another store "
                "(close() it first)")

    def close(self) -> None:
        """Release the journal handle and ownership lock. Further
        mutations raise — a closed store must never silently reopen the
        journal without the lock."""
        with self._lock:
            self._closed = True
            if self._journal is not None:
                self._journal.close()
                self._journal = None
            if self._lockfile is not None:
                import fcntl

                fcntl.flock(self._lockfile, fcntl.LOCK_UN)
                self._lockfile.close()
                self._lockfile = None

    # -- journal -------------------------------------------------------

    def _replay_locked(self) -> None:
        """Replay the journal into `_jobs`. A coordinator SIGKILLed
        mid-append leaves a torn final line (any byte prefix of the
        record): the intact prefix replays and the torn tail is
        physically TRUNCATED — appending after a torn, newline-less
        tail would weld the next record onto it and lose BOTH. Bad
        lines in the middle of the file (bit rot) are skipped, never
        truncated: the records after them are still good."""
        if not os.path.exists(self._path):
            return
        with open(self._path, "rb") as fh:
            data = fh.read()
        pos = 0
        good_end = 0                  # byte offset after the last
        while pos < len(data):        # cleanly replayed line
            nl = data.find(b"\n", pos)
            end = len(data) if nl < 0 else nl + 1
            line = data[pos:end].strip()
            pos = end
            if not line:
                good_end = end
                continue
            try:
                rec = json.loads(line)
                if rec.get("op") == "put":
                    job = Job.from_dict(rec["job"])
                    self._jobs[job.id] = job
                elif rec.get("op") == "del":
                    self._jobs.pop(rec.get("id"), None)
            except Exception:         # noqa: BLE001 - skip the one bad
                continue              # record (torn write / bit rot),
                                      # never abort the whole replay
            # an unterminated final line that still parses is a record
            # whose newline alone was lost — accept it, but leave
            # good_end behind it so the rewrite below re-terminates
            if nl >= 0:
                good_end = end
        if good_end < len(data):
            # torn tail (or a parsed-but-unterminated last record):
            # truncate to the last clean boundary; the compaction that
            # follows construction rewrites live state anyway
            with open(self._path, "r+b") as fh:
                fh.truncate(good_end)

    def _compact_locked(self) -> None:
        """Rewrite the journal as one put per live job (atomic rename)."""
        if self._journal is not None:
            self._journal.close()
            self._journal = None
        tmp = self._path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            for job in self._jobs.values():
                fh.write(json.dumps({"op": "put", "job": job.to_dict()})
                         + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self._path)
        self._journal = open(self._path, "a", encoding="utf-8")
        self._journal_lines = len(self._jobs)

    def _log_locked(self, rec: dict[str, Any]) -> None:
        if not self._path:
            return
        if self._closed:
            raise RuntimeError(
                "JobStore is closed; mutation after close() would write "
                "the journal without the ownership lock")
        if self._journal is None:
            self._journal = open(self._path, "a", encoding="utf-8")
        self._journal.write(json.dumps(rec) + "\n")
        self._journal.flush()
        self._journal_lines += 1
        if self._journal_lines > max(1000, 10 * len(self._jobs)):
            self._compact_locked()

    def _log_put_locked(self, job: Job) -> None:
        self._log_locked({"op": "put", "job": job.to_dict()})

    def create(self, input_path: str, meta: VideoMeta | None = None,
               settings: Mapping[str, Any] | None = None,
               job_id: str | None = None,
               job_type: str = "transcode",
               tenant: str = "default") -> Job:
        job = Job(id=job_id or uuid.uuid4().hex, input_path=input_path,
                  meta=meta, settings=dict(settings or {}),
                  job_type=job_type, tenant=tenant)
        with self._lock:
            if job.id in self._jobs:
                raise ValueError(f"duplicate job id {job.id}")
            self._jobs[job.id] = job
            self._log_put_locked(job)
        return self.get(job.id)

    def get(self, job_id: str) -> Job:
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                raise KeyError(f"no such job {job_id}")
            return dataclasses.replace(job)

    def try_get(self, job_id: str) -> Job | None:
        try:
            return self.get(job_id)
        except KeyError:
            return None

    def update(self, job_id: str, fn: Callable[[Job], None]) -> Job:
        """Apply `fn` to the live record under the store lock; returns a
        snapshot of the result."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                raise KeyError(f"no such job {job_id}")
            fn(job)
            self._log_put_locked(job)
            return dataclasses.replace(job)

    def delete(self, job_id: str) -> bool:
        with self._lock:
            gone = self._jobs.pop(job_id, None) is not None
            if gone:
                self._log_locked({"op": "del", "id": job_id})
            return gone

    def list(self, status: Status | None = None) -> list[Job]:
        with self._lock:
            jobs = [dataclasses.replace(j) for j in self._jobs.values()]
        if status is not None:
            jobs = [j for j in jobs if j.status is status]
        return sorted(jobs, key=lambda j: j.created_at)

    def __iter__(self) -> Iterator[Job]:
        return iter(self.list())

    def __len__(self) -> int:
        with self._lock:
            return len(self._jobs)

    def all_idle(self) -> bool:
        """True iff no job is WAITING or active (the reference's
        all_jobs_are_idle, /root/reference/common.py:231-274)."""
        with self._lock:
            return not any(
                j.status is Status.WAITING or j.status.is_active
                for j in self._jobs.values())
