"""Repo-native static analysis: machine-checked architecture invariants.

Four passes over the package's ASTs, driven by the declarative
manifest (analysis/manifest.py) and runnable in <5 s without jax:

1. imports     — jax confinement (TVT-J001) + forbidden symbols
                 (TVT-J002): declared jax-free modules never reach
                 `jax` through any module-scope import chain.
2. syncs       — host-sync confinement (TVT-S001/S002): blocking
                 device_get / block_until_ready / implicit
                 np.asarray-on-device syncs stay inside the dispatch
                 boundary.
3. threads     — thread-safety audit (TVT-T001/T002/T003): unlocked
                 cross-entrypoint writes, blocking calls under locks,
                 lock-order inversions.
4. configcheck — config discipline (TVT-C001/C002/C003): no dead
                 settings keys, a registered TVT_* env namespace, no
                 raw settings subscripts around the clamp tier.

Run via ``python -m thinvids_tpu.cli check`` (tools/check.py); tier-1
shells out to it (tests/test_analysis.py), replacing the per-file grep
guards that used to live in four separate test files.

jax-free by contract — and self-hosted: this package is in its own
manifest's `jax_free` list, so the analyzer analyzes itself.
"""

from __future__ import annotations

from .astutil import Finding, SourceTree
from .manifest import Manifest, default_manifest


def run_all(tree: SourceTree, manifest: Manifest,
            defaults: dict | None = None) -> list[Finding]:
    """Every pass over one source tree; findings in pass order
    (waivers NOT applied — see apply_waivers)."""
    from . import configcheck, imports, syncs, threads

    findings: list[Finding] = []
    findings += imports.run(tree, manifest)
    findings += syncs.run(tree, manifest)
    findings += threads.run(tree, manifest)
    findings += configcheck.run(tree, manifest, defaults)
    return findings


def apply_waivers(findings: list[Finding], manifest: Manifest
                  ) -> tuple[list[Finding], list[Finding], list[str]]:
    """(open findings, waived findings, stale waiver keys)."""
    waived = [f for f in findings if f.key in manifest.waivers]
    open_ = [f for f in findings if f.key not in manifest.waivers]
    hit = {f.key for f in waived}
    stale = sorted(k for k in manifest.waivers if k not in hit)
    return open_, waived, stale


__all__ = ["Finding", "SourceTree", "Manifest", "default_manifest",
           "run_all", "apply_waivers"]
