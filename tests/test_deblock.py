"""In-loop deblocking filter (§8.7, shifted-plane schedule).

Pins: the threshold tables, the numpy↔JAX backend parity (one
implementation, two ops shims — deblock.py / jaxdeblock.py), the
band-split consistency the SFE halo exchange relies on, filter
behavior on known edges, and the libavcodec oracle parity BOUND of the
shifted-plane approximation (skipped when the oracle is absent).
"""

import numpy as np
import pytest

from thinvids_tpu.codecs.h264.deblock import (ALPHA_TABLE, BETA_TABLE,
                                              TC0_TABLE, deblock_frame)


def _rand_frame(mbh, mbw, seed=0, smooth=False):
    rng = np.random.default_rng(seed)
    if smooth:
        base = rng.integers(90, 120, (4 * mbh, 4 * mbw))
        y = np.repeat(np.repeat(base, 4, 0), 4, 1).astype(np.uint8)
    else:
        y = rng.integers(0, 256, (16 * mbh, 16 * mbw), np.uint8)
    u = y[::2, ::2].copy()
    v = 255 - u
    return y, u, v


class TestTables:
    def test_shapes_and_anchors(self):
        assert ALPHA_TABLE.shape == (52,)
        assert BETA_TABLE.shape == (52,)
        assert TC0_TABLE.shape == (3, 52)
        # spec anchor points (Table 8-16 / 8-17)
        assert ALPHA_TABLE[26] == 15 and ALPHA_TABLE[51] == 255
        assert BETA_TABLE[26] == 6 and BETA_TABLE[51] == 18
        assert ALPHA_TABLE[15] == 0 and BETA_TABLE[15] == 0
        assert TC0_TABLE[2, 51] == 25 and TC0_TABLE[0, 51] == 13
        assert (TC0_TABLE[:, :17] == 0).all()
        # monotone non-decreasing in qp, and bS3 >= bS2 >= bS1
        for t in (ALPHA_TABLE, BETA_TABLE, *TC0_TABLE):
            assert (np.diff(t) >= 0).all()
        assert (TC0_TABLE[2] >= TC0_TABLE[1]).all()
        assert (TC0_TABLE[1] >= TC0_TABLE[0]).all()


class TestNumpyJaxParity:
    @pytest.mark.parametrize("seed", [0, 1])
    @pytest.mark.parametrize("intra", [True, False])
    def test_random_fields(self, seed, intra):
        from thinvids_tpu.codecs.h264.jaxdeblock import deblock_frame_jax

        mbh, mbw = 5, 7
        y, u, v = _rand_frame(mbh, mbw, seed, smooth=(seed == 1))
        rng = np.random.default_rng(seed + 100)
        qp = rng.integers(16, 48, (mbh, mbw))
        kw = {}
        if not intra:
            kw = dict(nz4=rng.random((4 * mbh, 4 * mbw)) < 0.4,
                      mv=rng.integers(-12, 13, (mbh, mbw, 2)))
        a = deblock_frame(y, u, v, qp, intra=intra, **kw)
        b = deblock_frame_jax(y, u, v, qp, intra=intra, **kw)
        for pa, pb in zip(a, b):
            np.testing.assert_array_equal(pa, np.asarray(pb))

    def test_filters_blocky_content(self):
        mbh, mbw = 3, 3
        y, u, v = _rand_frame(mbh, mbw, 1, smooth=True)
        qp = np.full((mbh, mbw), 30)
        y2, u2, v2 = deblock_frame(y, u, v, qp, intra=True)
        assert (y2 != y).sum() > y.size // 4     # blocking edges filtered
        assert (u2 != u).any()

    def test_low_qp_disables_filter(self):
        # indexA < 16 -> alpha/beta 0 -> nothing may change
        mbh, mbw = 2, 2
        y, u, v = _rand_frame(mbh, mbw, 2, smooth=True)
        qp = np.full((mbh, mbw), 10)
        y2, u2, v2 = deblock_frame(y, u, v, qp, intra=True)
        np.testing.assert_array_equal(y2, y)
        np.testing.assert_array_equal(u2, u)


class TestBandSplit:
    def test_band_slices_reproduce_full_frame(self):
        """A band slice with a one-MB-row halo plus its neighbor's bS
        metadata computes exactly the full-frame filter for its own
        rows — the invariant the SFE cross-band exchange rides on."""
        mbh, mbw = 6, 4
        y, u, v = _rand_frame(mbh, mbw, 3, smooth=True)
        rng = np.random.default_rng(7)
        qp = rng.integers(20, 40, (mbh, mbw))
        nz = rng.random((4 * mbh, 4 * mbw)) < 0.5
        mv = rng.integers(-6, 7, (mbh, mbw, 2))
        full = deblock_frame(y, u, v, qp, intra=False, nz4=nz, mv=mv)

        def band(lo_mb, hi_mb):
            lo, hi = max(0, lo_mb - 1), min(mbh, hi_mb + 1)
            out = deblock_frame(
                y[16 * lo:16 * hi], u[8 * lo:8 * hi], v[8 * lo:8 * hi],
                qp[lo:hi], intra=False, nz4=nz[4 * lo:4 * hi],
                mv=mv[lo:hi], mb_row0=lo, total_mb_rows=mbh)
            s = lo_mb - lo
            return tuple(p[k * s:k * s + k * (hi_mb - lo_mb)]
                         for p, k in zip(out, (16, 8, 8)))

        splits = [(0, 2), (2, 5), (5, 6)]
        for pi in range(3):
            got = np.concatenate([band(a, b)[pi] for a, b in splits])
            np.testing.assert_array_equal(got, full[pi])

    def test_padding_rows_not_filtered_across(self):
        """Horizontal edges at/below total_mb_rows (band-grid padding)
        do not exist in the picture and must not modify real rows."""
        mbh, mbw = 3, 2
        y, u, v = _rand_frame(mbh, mbw, 4, smooth=True)
        qp = np.full((mbh, mbw), 32)
        full = deblock_frame(y[:32], u[:16], v[:16], qp[:2], intra=True)
        padded = deblock_frame(y, u, v, qp, intra=True,
                               mb_row0=0, total_mb_rows=2)
        np.testing.assert_array_equal(padded[0][:32], full[0])
        np.testing.assert_array_equal(padded[1][:16], full[1])


class TestOracleParity:
    def test_shifted_plane_bound_vs_libavcodec(self):
        """The shifted-plane schedule deviates from the spec's per-MB
        sample ordering only where adjacent edges both trigger; this
        pins the measured bound against libavcodec's spec-exact
        decode: per-frame max |diff| <= 4 and mean PSNR vs the oracle
        >= 48 dB over a deblocked GOP."""
        from thinvids_tpu.tools import oracle

        if not oracle.oracle_available():
            pytest.skip("libavcodec oracle not available")
        from bench import make_frames
        from thinvids_tpu.codecs.h264.encoder import encode_gop
        from thinvids_tpu.codecs.h264.rdo import RdConfig
        from thinvids_tpu.core.types import VideoMeta
        from thinvids_tpu.tools.metrics import psnr

        w, h, n = 192, 160, 5
        frames = make_frames(n, w, h)
        meta = VideoMeta(width=w, height=h, fps_num=30, fps_den=1,
                         num_frames=n)
        stream, recons = encode_gop(frames, meta, qp=30,
                                    return_recon=True,
                                    rd=RdConfig(deblock=True))
        decoded = oracle.decode_h264(stream)
        ry = np.asarray(recons[0])
        psnrs = []
        for i, (oy, _ou, _ov) in enumerate(decoded):
            diff = np.abs(oy.astype(np.int32)
                          - ry[i][:h, :w].astype(np.int32))
            assert diff.max() <= 4, f"frame {i}: max diff {diff.max()}"
            psnrs.append(psnr(oy, ry[i][:h, :w]))
        assert np.mean(psnrs) >= 48.0
