"""Logging setup.

Port of the reference's idempotent shared logger
(/root/reference/common.py:100-161): one root configuration, format with
hostname + pid, ``TVT_LOG_LEVEL`` env override (legacy ``LOG_LEVEL``
still honored), noisy third-party loggers quieted.

``TVT_LOG_FORMAT=json`` switches every line to one structured JSON
object (ts/level/logger/host/pid/msg, plus the active job and trace id
when the emitting thread runs inside a traced job — obs/trace.bind),
so farm logs can be machine-joined against ``GET /trace/<job>``
exports instead of regex-scraped.
"""

from __future__ import annotations

import logging
import os
import socket

_CONFIGURED = False
_FORMAT = (
    "%(asctime)s %(levelname)s {host} %(name)s [%(process)d] TVT %(message)s"
)

_QUIET = ("urllib3", "watchdog", "jax._src", "absl")


class JsonFormatter(logging.Formatter):
    """One JSON object per line, stamped with the thread's ambient
    (job_id, trace_id) when obs/trace.bind is active — the join key
    between farm logs and the job's distributed trace."""

    def __init__(self, host: str) -> None:
        super().__init__()
        self._host = host

    def format(self, record: logging.LogRecord) -> str:
        import json

        doc = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "host": self._host,
            "pid": record.process,
            "thread": record.threadName,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            doc["exc"] = self.formatException(record.exc_info)
        try:
            # lazy: core/log must stay importable before (and without)
            # the obs package — e.g. from config-less tooling
            from ..obs.trace import current_ids

            ids = current_ids()
        except Exception:   # noqa: BLE001 - never fail a log line
            ids = None
        if ids is not None:
            doc["job_id"], doc["trace_id"] = ids
        return json.dumps(doc, default=str)


def _make_formatter(host: str) -> logging.Formatter:
    """The formatter TVT_LOG_FORMAT selects: "json" = structured
    one-object-per-line, anything else = the human text format."""
    if os.environ.get("TVT_LOG_FORMAT", "").strip().lower() == "json":
        return JsonFormatter(host)
    return logging.Formatter(_FORMAT.format(host=host))


def get_logging(name: str = "thinvids_tpu") -> logging.Logger:
    global _CONFIGURED
    if not _CONFIGURED:
        # TVT_LOG_LEVEL is the registered knob (analysis/manifest.py);
        # bare LOG_LEVEL survives as a reference-compat fallback
        # (waived in the manifest)
        level_name = os.environ.get(
            "TVT_LOG_LEVEL", os.environ.get("LOG_LEVEL", "INFO")).upper()
        level = getattr(logging, level_name, logging.INFO)
        handler = logging.StreamHandler()
        handler.setFormatter(_make_formatter(socket.gethostname()))
        root = logging.getLogger()
        root.setLevel(level)
        # Idempotent: only attach our handler if a TVT handler is absent.
        if not any(getattr(h, "_tvt", False) for h in root.handlers):
            handler._tvt = True  # type: ignore[attr-defined]
            root.addHandler(handler)
        for quiet in _QUIET:
            logging.getLogger(quiet).setLevel(logging.WARNING)
        _CONFIGURED = True
    return logging.getLogger(name)
