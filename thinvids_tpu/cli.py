"""Process entrypoints: coordinator and agent daemons.

`python -m thinvids_tpu.cli coordinator` is the manager-host process —
the union of the reference's gunicorn app + watcher daemon +
housekeeping unit (/root/reference/ansible_manager.yml:264-349):
durable coordinator, executor, HTTP API + dashboard, watch-folder
ingest, orphan recovery, scheduler kicks.

`python -m thinvids_tpu.cli agent` is the worker-host daemon — the
reference's thinman-agent (/root/reference/agent/agent.py): 1 Hz
host + accelerator metrics heartbeats to the coordinator API.
"""

from __future__ import annotations

import argparse
import os
import signal
import threading


def run_coordinator(args: argparse.Namespace) -> None:
    from .api import ApiServer
    from .cluster.agent import NodeAgent, coordinator_submitter
    from .cluster.coordinator import Coordinator
    from .cluster.executor import LocalExecutor
    from .core.log import get_logging
    from .ingest import FileLedger, WatchIngester, coordinator_submitter \
        as ingest_submitter

    log = get_logging("thinvids_tpu.coordinator")
    state_dir = args.state_dir or os.environ.get("TVT_STATE_DIR")
    co = Coordinator(state_dir=state_dir)
    execu = LocalExecutor(co, args.output_dir, sync=False)
    co._launcher = execu.launch
    requeued = co.recover_jobs()
    if requeued:
        log.info("requeued %d orphaned jobs after restart", len(requeued))
    # scheduler poll + watchdog (the reference's daemon threads,
    # app.py:1474-1516) — without these a WAITING job whose dispatch
    # gate failed once would sit queued forever
    co.start_background()

    roots = {name: path for name, path in
             (("watch", args.watch_dir), ("library", args.output_dir))
             if path}
    api = ApiServer(co, host=args.host, port=args.port,
                    browse_roots=roots).start()
    log.info("api + dashboard on %s", api.url)

    # Local agent: the coordinator host reports its own health, and its
    # accelerator devices register as encode slots — on a TPU host the
    # devices are the "workers" the scheduler gates on (the reference
    # gated on live thin-client nodes, app.py:1088-1133).
    host_submit = coordinator_submitter(co)

    def submit(host: str, metrics) -> None:
        host_submit(host, metrics)
        for i in range(int(metrics.get("devices", 0) or 0)):
            co.registry.heartbeat(f"{host}-dev{i}")

    agent = NodeAgent(submit, idle_probe=co.store.all_idle).start()

    stop = threading.Event()
    watcher_thread = None
    if args.watch_dir:
        ledger = FileLedger(os.path.join(
            state_dir or args.output_dir, "processed.log"))
        ingester = WatchIngester(args.watch_dir, ledger,
                                 submit=ingest_submitter(co))
        adopted = ingester.bootstrap_if_first_run()
        if adopted:
            log.info("first run: adopted %d existing files", adopted)

        def watch_loop() -> None:
            while not stop.wait(args.scan_interval):
                try:
                    for rel in ingester.scan_once():
                        log.info("ingested %s", rel)
                except Exception as exc:     # noqa: BLE001 - keep watching
                    log.warning("watch scan failed: %s", exc)

        watcher_thread = threading.Thread(target=watch_loop, daemon=True,
                                          name="tvt-watcher")
        watcher_thread.start()
        log.info("watching %s", args.watch_dir)

    def shutdown(*_sig) -> None:
        stop.set()
        co.stop_background()
        agent.stop()
        api.stop()
        # let in-flight encodes finish before the journal closes — a
        # SIGTERM mid-job must not behave like a crash
        execu.join(timeout=30)
        co.close()

    signal.signal(signal.SIGTERM, shutdown)
    try:
        signal.pause()
    except (KeyboardInterrupt, AttributeError):
        pass
    finally:
        shutdown()


def run_agent(args: argparse.Namespace) -> None:
    from .cluster.agent import NodeAgent, http_submitter
    from .core.log import get_logging

    log = get_logging("thinvids_tpu.agent")
    agent = NodeAgent(http_submitter(args.coordinator), host=args.node_name,
                      interval_s=args.interval)
    log.info("heartbeating to %s every %.1fs", args.coordinator,
             args.interval)
    agent.start()
    try:
        signal.pause()
    except (KeyboardInterrupt, AttributeError):
        pass
    finally:
        agent.stop()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="thinvids_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("coordinator", help="manager: API, scheduler, "
                                           "executor, ingest")
    c.add_argument("--host", default="0.0.0.0")
    c.add_argument("--port", type=int,
                   default=int(os.environ.get("TVT_API_PORT", "5005")))
    c.add_argument("--state-dir",
                   default=os.environ.get("TVT_STATE_DIR"))
    c.add_argument("--watch-dir",
                   default=os.environ.get("TVT_WATCH_DIR"))
    c.add_argument("--output-dir",
                   default=os.environ.get("TVT_OUTPUT_DIR", "./library"))
    c.add_argument("--scan-interval", type=float, default=60.0)
    c.set_defaults(fn=run_coordinator)

    a = sub.add_parser("agent", help="worker: metrics heartbeats")
    a.add_argument("--coordinator",
                   default=os.environ.get("TVT_COORDINATOR_URL",
                                          "http://127.0.0.1:5005"))
    a.add_argument("--node-name", default=None)
    a.add_argument("--interval", type=float, default=1.0)
    a.set_defaults(fn=run_agent)
    return p


def main(argv: list[str] | None = None) -> None:
    args = build_parser().parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
