"""H.264 integer transforms and quantization (spec §8.5, numpy reference).

This module is the semantic ground truth for the codec's math; the JAX/
Pallas path (jaxcore.py) must match it bit-exactly (tested). All functions
operate on int32 numpy arrays and follow the spec's integer arithmetic, so
encoder reconstruction equals what a conformant decoder produces.

Shapes: 4x4 blocks are the unit. Batched variants accept (..., 4, 4).
"""

from __future__ import annotations

import numpy as np

# Forward core transform matrix Cf (§8.5, encoder side per JM):
CF = np.array(
    [[1, 1, 1, 1], [2, 1, -1, -2], [1, -1, -1, 1], [1, -2, 2, -1]], np.int32
)
# 4x4 Hadamard (luma DC), and 2x2 Hadamard (chroma DC).
H4 = np.array(
    [[1, 1, 1, 1], [1, 1, -1, -1], [1, -1, -1, 1], [1, -1, 1, -1]], np.int32
)
H2 = np.array([[1, 1], [1, -1]], np.int32)

# Quant multiplier MF (Table derived from §8.5.9 normAdjust) by qp%6 and
# coefficient position class: class0 = (0,0),(0,2),(2,0),(2,2);
# class1 = remaining; class2 = (1,1),(1,3),(3,1),(3,3).
_MF_CLASSES = np.array(
    [
        [13107, 8066, 5243],
        [11916, 7490, 4660],
        [10082, 6554, 4194],
        [9362, 5825, 3647],
        [8192, 5243, 3355],
        [7282, 4559, 2893],
    ],
    np.int32,
)
# Dequant scale V (normAdjust4x4): same class layout.
_V_CLASSES = np.array(
    [
        [10, 13, 16],
        [11, 14, 18],
        [13, 16, 20],
        [14, 18, 23],
        [16, 20, 25],
        [18, 23, 29],
    ],
    np.int32,
)

_POS_CLASS = np.array(
    [[0, 1, 0, 1], [1, 2, 1, 2], [0, 1, 0, 1], [1, 2, 1, 2]], np.int32
)

# MF[qp%6] and V[qp%6] as full 4x4 matrices.
MF_TABLE = _MF_CLASSES[:, _POS_CLASS]          # (6, 4, 4)
V_TABLE = _V_CLASSES[:, _POS_CLASS]            # (6, 4, 4)

# Chroma qp mapping (§8.5.8 Table 8-15) for qPi in 0..51.
CHROMA_QP_TABLE = np.array(
    list(range(30))
    + [29, 30, 31, 32, 32, 33, 34, 34, 35, 35, 36, 36, 37, 37, 37, 38, 38, 38, 39, 39, 39, 39],
    np.int32,
)

# Zig-zag scan order for 4x4 blocks (§8.5.5, frame coding).
ZIGZAG_4x4 = np.array(
    [0, 1, 4, 8, 5, 2, 3, 6, 9, 12, 13, 10, 7, 11, 14, 15], np.int32
)


def chroma_qp(qp: int, offset: int = 0) -> int:
    return int(CHROMA_QP_TABLE[min(51, max(0, qp + offset))])


def forward_4x4(block: np.ndarray) -> np.ndarray:
    """Core forward transform W = Cf X CfT over (..., 4, 4) residuals."""
    x = block.astype(np.int32)
    return np.einsum("ij,...jk,lk->...il", CF, x, CF).astype(np.int32)


def inverse_4x4(coeffs: np.ndarray) -> np.ndarray:
    """Spec §8.5.12.2 inverse core transform (without the final shift).

    Input: dequantized coefficients d (..., 4, 4). Output: r' such that
    residual = (r' + 32) >> 6.
    """
    d = coeffs.astype(np.int32)
    # Horizontal (rows): e/f per spec
    d0, d1, d2, d3 = d[..., 0], d[..., 1], d[..., 2], d[..., 3]
    e0 = d0 + d2
    e1 = d0 - d2
    e2 = (d1 >> 1) - d3
    e3 = d1 + (d3 >> 1)
    f = np.stack([e0 + e3, e1 + e2, e1 - e2, e0 - e3], axis=-1)
    # Vertical (columns)
    g0, g1, g2, g3 = f[..., 0, :], f[..., 1, :], f[..., 2, :], f[..., 3, :]
    h0 = g0 + g2
    h1 = g0 - g2
    h2 = (g1 >> 1) - g3
    h3 = g1 + (g3 >> 1)
    return np.stack([h0 + h3, h1 + h2, h1 - h2, h0 - h3], axis=-2).astype(np.int32)


def quant_4x4(coeffs: np.ndarray, qp: int, intra: bool = True,
              skip_dc: bool = False) -> np.ndarray:
    """Scalar quantization |Z| = (|W|*MF + f) >> qbits with sign restore."""
    qbits = 15 + qp // 6
    f = ((1 << qbits) // 3) if intra else ((1 << qbits) // 6)
    mf = MF_TABLE[qp % 6]
    w = coeffs.astype(np.int64)
    z = ((np.abs(w) * mf + f) >> qbits).astype(np.int32)
    z = np.where(coeffs < 0, -z, z)
    if skip_dc:
        z = z.copy()
        z[..., 0, 0] = 0
    return z


def dequant_4x4(levels: np.ndarray, qp: int, skip_dc: bool = False) -> np.ndarray:
    """AC/full dequant d = z * V << (qp//6) (bit-exact vs spec §8.5.12.1)."""
    v = V_TABLE[qp % 6]
    d = (levels.astype(np.int32) * v) << (qp // 6)
    if skip_dc:
        d = d.copy()
        d[..., 0, 0] = 0
    return d


def luma_dc_forward(dc: np.ndarray) -> np.ndarray:
    """4x4 Hadamard of the 16 I16x16 luma DC coefficients, /2 (encoder)."""
    x = dc.astype(np.int32)
    return (np.einsum("ij,...jk,lk->...il", H4, x, H4) // 2).astype(np.int32)


def luma_dc_quant(wd: np.ndarray, qp: int) -> np.ndarray:
    qbits = 15 + qp // 6
    f = (1 << qbits) // 3
    mf00 = int(MF_TABLE[qp % 6][0, 0])
    z = ((np.abs(wd.astype(np.int64)) * mf00 + 2 * f) >> (qbits + 1)).astype(np.int32)
    return np.where(wd < 0, -z, z)


def luma_dc_dequant(z: np.ndarray, qp: int) -> np.ndarray:
    """Spec §8.5.10: inverse Hadamard then DC-specific scaling."""
    f = np.einsum("ij,...jk,lk->...il", H4, z.astype(np.int32), H4)
    ls = int(V_TABLE[qp % 6][0, 0]) * 16  # LevelScale4x4(qp%6, 0, 0)
    if qp >= 36:
        return (f * ls) << (qp // 6 - 6)
    shift = 6 - qp // 6
    return (f * ls + (1 << (shift - 1))) >> shift


def chroma_dc_forward(dc: np.ndarray) -> np.ndarray:
    """2x2 Hadamard of chroma DC (..., 2, 2)."""
    return np.einsum(
        "ij,...jk,lk->...il", H2, dc.astype(np.int32), H2
    ).astype(np.int32)


def chroma_dc_quant(wd: np.ndarray, qp: int, intra: bool = True) -> np.ndarray:
    qbits = 15 + qp // 6
    f = ((1 << qbits) // 3) if intra else ((1 << qbits) // 6)
    mf00 = int(MF_TABLE[qp % 6][0, 0])
    z = ((np.abs(wd.astype(np.int64)) * mf00 + 2 * f) >> (qbits + 1)).astype(np.int32)
    return np.where(wd < 0, -z, z)


def chroma_dc_dequant(z: np.ndarray, qp: int) -> np.ndarray:
    """Spec §8.5.11: inverse 2x2 Hadamard, then ((f*LS) << (qp/6)) >> 5."""
    f = np.einsum("ij,...jk,lk->...il", H2, z.astype(np.int32), H2)
    ls = int(V_TABLE[qp % 6][0, 0]) * 16
    return ((f * ls) << (qp // 6)) >> 5


def reconstruct_4x4(pred: np.ndarray, dequant: np.ndarray) -> np.ndarray:
    """pred + inverse-transformed residual, rounded and clipped to uint8."""
    r = inverse_4x4(dequant)
    return np.clip(pred.astype(np.int32) + ((r + 32) >> 6), 0, 255).astype(np.uint8)


def zigzag(block: np.ndarray) -> np.ndarray:
    """4x4 block (..., 4, 4) → (..., 16) in zig-zag order."""
    flat = block.reshape(*block.shape[:-2], 16)
    return flat[..., ZIGZAG_4x4]


def inverse_zigzag(seq: np.ndarray) -> np.ndarray:
    """(..., 16) zig-zag sequence → (..., 4, 4) block."""
    out = np.empty_like(seq)
    out[..., ZIGZAG_4x4] = seq
    return out.reshape(*seq.shape[:-1], 4, 4)


def blocks_from_plane(plane: np.ndarray, size: int = 4) -> np.ndarray:
    """(H, W) → (H//size, W//size, size, size) tiling."""
    h, w = plane.shape
    return plane.reshape(h // size, size, w // size, size).swapaxes(1, 2)


def plane_from_blocks(blocks: np.ndarray) -> np.ndarray:
    """(bh, bw, size, size) → (H, W)."""
    bh, bw, s, _ = blocks.shape
    return blocks.swapaxes(1, 2).reshape(bh * s, bw * s)
