"""Core video data types.

TPU-first framing: frames are numpy/JAX arrays of YUV planes padded to
block-aligned shapes so every downstream kernel sees static, tile-friendly
shapes. Descriptor dataclasses (GopSpec/SegmentPlan) are the typed analog of
the reference's ~60-field Redis job hash (/root/reference/manager/app.py:2367)
and its parts planning (/root/reference/worker/tasks.py:597-609).
"""

from __future__ import annotations

import dataclasses
import enum
import math
from typing import Sequence

import numpy as np


class ChromaFormat(enum.Enum):
    YUV400 = 0
    YUV420 = 1
    YUV422 = 2
    YUV444 = 3

    @property
    def has_chroma(self) -> bool:
        return self is not ChromaFormat.YUV400

    @property
    def subsampling(self) -> tuple[int, int]:
        """(horizontal, vertical) chroma divisors.

        YUV400 reports (1, 1) so generic ``dim // divisor`` callers never
        divide by zero; gate on :attr:`has_chroma` before touching chroma.
        """
        return {
            ChromaFormat.YUV400: (1, 1),
            ChromaFormat.YUV420: (2, 2),
            ChromaFormat.YUV422: (2, 1),
            ChromaFormat.YUV444: (1, 1),
        }[self]


class FrameType(enum.IntEnum):
    I = 0
    P = 1
    B = 2


@dataclasses.dataclass(frozen=True)
class VideoMeta:
    """Probe result for a source video (analog of the reference's ffprobe
    surface, /root/reference/manager/app.py:2120-2220)."""

    width: int
    height: int
    fps_num: int = 30
    fps_den: int = 1
    num_frames: int = 0
    chroma: ChromaFormat = ChromaFormat.YUV420
    bit_depth: int = 8
    codec: str = "raw"
    duration_s: float = 0.0
    size_bytes: int = 0

    @property
    def fps(self) -> float:
        return self.fps_num / max(1, self.fps_den)

    @property
    def mb_width(self) -> int:
        return (self.width + 15) // 16

    @property
    def mb_height(self) -> int:
        return (self.height + 15) // 16


def pad_to_multiple(plane: np.ndarray, mult: int, fill: str = "edge") -> np.ndarray:
    """Pad a 2-D plane up to a multiple of `mult` in both dims.

    Edge replication matches encoder convention (padding never introduces
    artificial gradients at the picture boundary).
    """
    h, w = plane.shape
    ph = (mult - h % mult) % mult
    pw = (mult - w % mult) % mult
    if ph == 0 and pw == 0:
        return plane
    return np.pad(plane, ((0, ph), (0, pw)), mode=fill)


def pad_to_shape(plane: np.ndarray, h: int, w: int, fill: str = "edge") -> np.ndarray:
    """Pad a 2-D plane up to an exact (h, w) target with edge replication."""
    ch, cw = plane.shape
    if ch > h or cw > w:
        raise ValueError(f"plane {plane.shape} larger than target {(h, w)}")
    if (ch, cw) == (h, w):
        return plane
    return np.pad(plane, ((0, h - ch), (0, w - cw)), mode=fill)


@dataclasses.dataclass
class Frame:
    """One video frame as planar YUV arrays (uint8, full range of the
    8-bit studio swing is preserved; no normalization).

    Planes are stored UNpadded; kernels pad on ingest so the stored frame
    remains the ground truth for quality metrics.
    """

    y: np.ndarray
    u: np.ndarray | None = None
    v: np.ndarray | None = None
    pts: int = 0
    frame_type: FrameType = FrameType.I

    @property
    def width(self) -> int:
        return int(self.y.shape[1])

    @property
    def height(self) -> int:
        return int(self.y.shape[0])

    def _chroma_divisors(self) -> tuple[int, int]:
        """(horizontal, vertical) divisors inferred from u-plane shape via
        per-axis ceil-division ratios (robust to odd source dimensions).

        Each chroma axis must be exactly ceil(luma/2) or exactly luma —
        anything else is a malformed plane, not a subsampling format."""
        ch, cw = self.u.shape
        if cw == (self.width + 1) // 2:
            hdiv = 2
        elif cw == self.width:
            hdiv = 1
        else:
            raise ValueError(
                f"chroma width {cw} matches neither {self.width} (4:4:4) "
                f"nor {(self.width + 1) // 2} (4:2:x) for luma width "
                f"{self.width}")
        if ch == (self.height + 1) // 2:
            vdiv = 2
        elif ch == self.height:
            vdiv = 1
        else:
            raise ValueError(
                f"chroma height {ch} matches neither {self.height} nor "
                f"{(self.height + 1) // 2} for luma height {self.height}")
        if (hdiv, vdiv) == (1, 2):
            raise ValueError("4:4:0 chroma layout is not supported")
        return hdiv, vdiv

    @property
    def chroma(self) -> ChromaFormat:
        if self.u is None:
            return ChromaFormat.YUV400
        return {
            (2, 2): ChromaFormat.YUV420,
            (2, 1): ChromaFormat.YUV422,
            (1, 1): ChromaFormat.YUV444,
        }[self._chroma_divisors()]

    def padded(self, mult: int = 16) -> "Frame":
        """Pad planes so luma is a multiple of ``mult`` in both dims and each
        chroma plane is exactly padded_luma_dim // divisor per axis (the
        invariant every block kernel assumes)."""
        y = pad_to_multiple(self.y, mult)
        u = self.u
        v = self.v
        if (u is None) != (v is None):
            raise ValueError("frame must have both u and v planes, or neither")
        if u is not None:
            ph, pw = y.shape
            hdiv, vdiv = self._chroma_divisors()
            u = pad_to_shape(u, ph // vdiv, pw // hdiv)
            v = pad_to_shape(v, ph // vdiv, pw // hdiv)
        return Frame(y, u, v, self.pts, self.frame_type)


@dataclasses.dataclass(frozen=True)
class GopSpec:
    """A closed GOP: the unit of parallel work (the analog of a
    reference 'part', /root/reference/worker/tasks.py:977-1052)."""

    index: int            # GOP index within the job (concat order)
    start_frame: int      # first frame (inclusive) in source order
    num_frames: int       # frames in this GOP
    idr: bool = True      # closed GOP: leading frame is an IDR

    @property
    def end_frame(self) -> int:
        return self.start_frame + self.num_frames


@dataclasses.dataclass(frozen=True)
class SegmentPlan:
    """Full sharding plan for a job: GOP boundaries + device layout.

    Mirrors the reference parts-planner semantics: target work size per
    shard, rounded up to a multiple of the usable worker (device) count so
    waves fill the farm (/root/reference/worker/tasks.py:597-609,1019-1031).
    """

    gops: tuple[GopSpec, ...]
    num_devices: int
    frames_per_gop: int

    @property
    def num_gops(self) -> int:
        return len(self.gops)

    @property
    def waves(self) -> int:
        return math.ceil(self.num_gops / max(1, self.num_devices))


@dataclasses.dataclass(frozen=True)
class BandSpec:
    """One horizontal MB-row band of a frame — the split-frame-encoding
    (SFE) unit of intra-frame parallel work. Each band is entropy-coded
    as its own H.264 slice (`first_mb_in_slice = start_mb_row * mbw`),
    so the concat of a frame's band slices is a legal picture."""

    index: int            # band index, top to bottom (slice order)
    start_mb_row: int     # first REAL MB row of this band
    mb_rows: int          # REAL MB rows entropy-coded from this band

    @property
    def end_mb_row(self) -> int:
        return self.start_mb_row + self.mb_rows


@dataclasses.dataclass(frozen=True)
class BandPlan:
    """Pinned per-job SFE band layout: every band owns `band_mb_rows`
    padded MB rows on its device (equal shard shapes for shard_map);
    only the last band's tail may be padding (encoded then discarded —
    never entropy-coded). Boundaries are a pure function of the frame's
    MB height and the band count, so the slice layout of a job never
    depends on arrival timing or mesh shape drift."""

    bands: tuple[BandSpec, ...]
    band_mb_rows: int     # padded MB rows per band (device shard height)
    mb_width: int

    @property
    def num_bands(self) -> int:
        return len(self.bands)

    @property
    def padded_mb_height(self) -> int:
        return self.num_bands * self.band_mb_rows


@dataclasses.dataclass
class EncodedSegment:
    """One encoded GOP's bitstream + bookkeeping (the analog of an encoded
    part PUT to the stitcher, /root/reference/worker/tasks.py:1667-1674)."""

    gop: GopSpec
    payload: bytes                    # Annex-B access units, concat-safe
    frame_sizes: tuple[int, ...] = ()
    distortion_sse: float = 0.0
    elapsed_s: float = 0.0

    @property
    def size_bytes(self) -> int:
        return len(self.payload)


def concat_segments(segments: Sequence[EncodedSegment]) -> bytes:
    """Order-restoring concat (the stitcher's frontier-ordered concat,
    /root/reference/worker/tasks.py:2047-2069). Segments must be closed
    GOPs starting with IDR + parameter sets so the join is seamless."""
    ordered = sorted(segments, key=lambda s: s.gop.index)
    expect = 0
    for seg in ordered:
        if seg.gop.index < expect:
            raise ValueError(f"duplicate segment index {seg.gop.index}")
        if seg.gop.index > expect:
            raise ValueError(f"missing segment index {expect}")
        expect += 1
    return b"".join(s.payload for s in ordered)
